#!/usr/bin/env python
"""Segment-level fuzz for the datagram-stream transport parser.

The dstream segment path takes UNTRUSTED UDP: any host can lob bytes at
the socket (the reference's QUIC slot has quinn's hardened parser here;
`serf/Cargo.toml:24-56`).  This target drives `_on_datagram` — the full
demux/decrypt/header/connection state machine — with:

- pure garbage datagrams (random bytes, random lengths),
- structure-aware mutations of VALID segments (bit flips, truncations,
  kind/seq corruption, replayed ciphertexts),
- valid-handshake interleavings (SYN floods, data-before-SYN, FIN storms),

and asserts the transport's contracts: no exception ever escapes the
datagram callback, the connection table and accept queue stay bounded,
and an established stream keeps working afterwards.

Run standalone: ``python fuzz/fuzz_dstream.py --seconds 30``; CI runs a
short slice via tests/test_fuzz_harness.py.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import random
import struct
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from serf_tpu.host.dstream import (  # noqa: E402
    MAX_ACCEPT_BACKLOG,
    MAX_PEER_CONNS,
    DatagramStreamTransport,
    K_ACK,
    K_DATA,
    K_FIN,
    K_RST,
    K_SYN,
    K_SYN_ACK,
    T_PACKET,
    T_SEGMENT,
    _HDR,
)
from serf_tpu.host.keyring import SecretKeyring  # noqa: E402

KINDS = (K_SYN, K_SYN_ACK, K_DATA, K_ACK, K_FIN, K_RST, 0, 7, 255)


def _valid_segment(t: DatagramStreamTransport, rng: random.Random) -> bytes:
    cid = rng.getrandbits(64).to_bytes(8, "big")
    kind = rng.choice(KINDS)
    seq = rng.choice((0, 1, rng.getrandbits(16), 2**32 - 1))
    payload = os.urandom(rng.randrange(0, 64))
    return t._encode_segment(cid, kind, seq, payload)


def _mutate(raw: bytes, rng: random.Random) -> bytes:
    b = bytearray(raw)
    op = rng.random()
    if op < 0.35 and b:                       # bit flip(s)
        for _ in range(rng.randrange(1, 4)):
            i = rng.randrange(len(b))
            b[i] ^= 1 << rng.randrange(8)
        return bytes(b)
    if op < 0.6:                              # truncate
        return bytes(b[:rng.randrange(0, len(b) + 1)])
    if op < 0.8 and b:                        # splice garbage tail
        return bytes(b[:rng.randrange(len(b))]) + os.urandom(
            rng.randrange(0, 32))
    return bytes(b) + os.urandom(rng.randrange(0, 16))  # extend


async def _fuzz(seed: int, seconds: float, cases_cap) -> dict:
    rng = random.Random(seed)
    keyring = SecretKeyring(bytes(range(16)))
    stats = {"cases": 0, "violations": 0, "examples": []}

    rings = (None, keyring)
    for ring_idx, ring in enumerate(rings):
        t = await DatagramStreamTransport.bind(("127.0.0.1", 0), keyring=ring)
        peer = await DatagramStreamTransport.bind(("127.0.0.1", 0),
                                                  keyring=ring)
        # one real stream that must survive the storm
        dial = asyncio.ensure_future(peer.dial(t.local_addr))
        _, srv = await asyncio.wait_for(t.accept(), 5)
        cli = await dial

        # each ring gets half the time budget and an even (ceil-split)
        # share of the case budget; the cap must actually terminate the
        # ring (not just the inner batch) so a cases-driven CI run is
        # deterministic in size and sums to exactly cases_cap
        if cases_cap:
            remaining = max(0, cases_cap - stats["cases"])
            share = -(-remaining // (len(rings) - ring_idx))
            ring_cap = stats["cases"] + share
        else:
            ring_cap = None
        deadline = time.monotonic() + seconds / 2
        src = ("127.0.0.1", 54321)
        while time.monotonic() < deadline:
            if ring_cap is not None and stats["cases"] >= ring_cap:
                break
            for _ in range(200):
                if ring_cap is not None and stats["cases"] >= ring_cap:
                    break
                stats["cases"] += 1
                roll = rng.random()
                if roll < 0.3:
                    wire = os.urandom(rng.randrange(0, 200))
                elif roll < 0.4:
                    wire = bytes([rng.choice((T_PACKET, T_SEGMENT, 2, 9))]) \
                        + os.urandom(rng.randrange(0, 100))
                elif roll < 0.8:
                    wire = _mutate(_valid_segment(t, rng), rng)
                else:
                    wire = _valid_segment(t, rng)     # replay-style valid
                try:
                    t._on_datagram(wire, (src[0], src[1] + rng.randrange(4)))
                except Exception as e:  # noqa: BLE001 - the contract
                    stats["violations"] += 1
                    if len(stats["examples"]) < 5:
                        stats["examples"].append(
                            f"{type(e).__name__}: {e} <- {wire[:40].hex()}")
                # drain accepts so the queue-bound check below is about
                # the transport's own cap, not this loop never accepting
                while not t._accepts.empty() and \
                        t._accepts.qsize() > MAX_ACCEPT_BACKLOG // 2:
                    t._accepts.get_nowait()
            await asyncio.sleep(0)
            # bounded-state contracts
            assert len(t._conns) <= MAX_ACCEPT_BACKLOG + 4 * MAX_PEER_CONNS, \
                f"conn table grew to {len(t._conns)}"

        # the pre-existing stream still works after the storm
        await cli.send_frame(b"post-storm ping")
        got = await srv.recv_frame(timeout=10)
        assert got == b"post-storm ping", "established stream corrupted"
        await cli.close()
        await t.shutdown()
        await peer.shutdown()
    return stats


def run(seed: int = 0, seconds: float = 10.0, cases=None) -> dict:
    return asyncio.run(_fuzz(seed, seconds, cases))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seconds", type=float, default=30.0)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    stats = run(seed=args.seed, seconds=args.seconds)
    print(f"dstream fuzz: {stats['cases']} cases, "
          f"{stats['violations']} violations")
    for ex in stats["examples"]:
        print("  ", ex)
    return 1 if stats["violations"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
