#!/usr/bin/env python
"""Standing fuzz harness over the wire codec — the analog of the reference's
libfuzzer target (fuzz/fuzz_targets/messages.rs:12-16, fuzzy::Message).

Three loops, seeded and time-/case-boxed:

1. **round-trip**: arbitrary messages of every envelope type (incl. RELAY
   nesting and swim COMPOUND wrapping) must satisfy
   ``decode(encode(m)) == m``.
2. **mutation**: truncations / bit-flips / splices of valid encodings must
   either decode to *something* or raise ``DecodeError`` — never any other
   exception (the fail-closed contract).
3. **garbage**: raw random buffers, same contract; also fed through the
   swim-packet decoder and the native C++ field scanner (differential vs
   the pure-Python scanner when the native lib is available).

Run standalone (CI artifact)::

    python fuzz/fuzz_messages.py --seconds 60 --seed 0
    python fuzz/fuzz_messages.py --cases 1000000

Prints one JSON summary line; exit code 0 iff no contract violations.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from serf_tpu import codec
from serf_tpu.host import messages as sm
from serf_tpu.types.filters import IdFilter, TagFilter
from serf_tpu.types.member import Member, MemberStatus, Node
from serf_tpu.types.messages import (
    ConflictResponseMessage,
    JoinMessage,
    KeyRequestMessage,
    KeyResponseMessage,
    LeaveMessage,
    PushPullMessage,
    QueryFlag,
    QueryMessage,
    QueryResponseMessage,
    RelayMessage,
    UserEventMessage,
    UserEvents,
    decode_message,
    encode_message,
    encode_relay_message,
)
from serf_tpu.types.tags import Tags


def _arb_str(rng: random.Random, max_len: int = 24) -> str:
    n = rng.randrange(max_len)
    return "".join(chr(rng.choice((rng.randrange(32, 127),
                                   rng.randrange(0x80, 0x2FF))))
                   for _ in range(n))


def _arb_bytes(rng: random.Random, max_len: int = 64) -> bytes:
    return rng.randbytes(rng.randrange(max_len))


def _arb_node(rng: random.Random) -> Node:
    addr = rng.choice([
        None,
        rng.randrange(1 << 16),
        (_arb_str(rng, 12).replace(":", "_"), rng.randrange(1 << 16)),
        _arb_str(rng, 12).replace(":", "_") or "x",
    ])
    return Node(_arb_str(rng), addr)


def _arb_ltime(rng: random.Random) -> int:
    return rng.choice([0, 1, rng.randrange(1 << 16), rng.randrange(1 << 63)])


def _arb_member(rng: random.Random) -> Member:
    tags = Tags({_arb_str(rng, 8): _arb_str(rng, 8)
                 for _ in range(rng.randrange(3))})
    return Member(_arb_node(rng), tags,
                  MemberStatus(rng.randrange(5)))


def _arb_filter(rng: random.Random):
    if rng.random() < 0.5:
        return IdFilter(tuple(_arb_str(rng) for _ in range(rng.randrange(4))))
    # keep expr a literal so construction cannot fail
    return TagFilter(_arb_str(rng, 8), "literal" + _arb_str(rng, 4)
                     .replace("\\", "").replace("[", "").replace("(", "")
                     .replace("*", "").replace("+", "").replace("?", "")
                     .replace("{", "").replace("|", "").replace(")", "")
                     .replace("]", "").replace("^", "").replace("$", ""))


def _arb_user_events(rng: random.Random) -> UserEvents:
    return UserEvents(_arb_ltime(rng), tuple(
        UserEventMessage(_arb_ltime(rng), _arb_str(rng), _arb_bytes(rng),
                         rng.random() < 0.5)
        for _ in range(rng.randrange(3))))


def arbitrary_message(rng: random.Random, depth: int = 0):
    """The fuzzy::Message analog: any envelope type, relay-nested up to 3."""
    kinds = ["join", "leave", "user", "pushpull", "query", "query_resp",
             "conflict", "key_req", "key_resp"]
    if depth < 3:
        kinds.append("relay")
    k = rng.choice(kinds)
    if k == "join":
        return JoinMessage(_arb_ltime(rng), _arb_str(rng))
    if k == "leave":
        return LeaveMessage(_arb_ltime(rng), _arb_str(rng),
                            rng.random() < 0.5)
    if k == "user":
        return UserEventMessage(_arb_ltime(rng), _arb_str(rng),
                                _arb_bytes(rng), rng.random() < 0.5)
    if k == "pushpull":
        return PushPullMessage(
            _arb_ltime(rng),
            {_arb_str(rng): _arb_ltime(rng) for _ in range(rng.randrange(4))},
            tuple(_arb_str(rng) for _ in range(rng.randrange(3))),
            _arb_ltime(rng),
            tuple(_arb_user_events(rng) for _ in range(rng.randrange(3))),
            _arb_ltime(rng))
    if k == "query":
        return QueryMessage(
            _arb_ltime(rng), rng.randrange(1 << 32), _arb_node(rng),
            tuple(_arb_filter(rng) for _ in range(rng.randrange(3))),
            QueryFlag(rng.randrange(4)), rng.randrange(6),
            rng.randrange(1 << 40), _arb_str(rng), _arb_bytes(rng))
    if k == "query_resp":
        return QueryResponseMessage(_arb_ltime(rng), rng.randrange(1 << 32),
                                    _arb_node(rng), QueryFlag(rng.randrange(4)),
                                    _arb_bytes(rng))
    if k == "conflict":
        return ConflictResponseMessage(_arb_member(rng))
    if k == "key_req":
        return KeyRequestMessage(_arb_bytes(rng, 33))
    if k == "key_resp":
        return KeyResponseMessage(rng.random() < 0.5, _arb_str(rng),
                                  tuple(_arb_bytes(rng, 33)
                                        for _ in range(rng.randrange(3))),
                                  _arb_bytes(rng, 33))
    # relay: nest an encoded inner message
    inner = arbitrary_message(rng, depth + 1)
    return RelayMessage(_arb_node(rng), encode_message(inner)
                        if not isinstance(inner, RelayMessage)
                        else encode_relay_message(inner.node, inner.payload))


def encode_any(msg) -> bytes:
    if isinstance(msg, RelayMessage):
        return encode_relay_message(msg.node, msg.payload)
    return encode_message(msg)


def _mutate(rng: random.Random, raw: bytes) -> bytes:
    choice = rng.random()
    b = bytearray(raw)
    if choice < 0.35 and b:                       # truncate
        return bytes(b[:rng.randrange(len(b))])
    if choice < 0.7 and b:                        # bit flips
        for _ in range(rng.randrange(1, 4)):
            i = rng.randrange(len(b))
            b[i] ^= 1 << rng.randrange(8)
        return bytes(b)
    if choice < 0.9 and b:                        # splice random chunk
        i = rng.randrange(len(b))
        return bytes(b[:i]) + rng.randbytes(rng.randrange(8)) + bytes(b[i:])
    return rng.randbytes(rng.randrange(96))       # replace wholesale


def _python_scan(buf: bytes):
    """Independent pure-Python field scan (the differential oracle — kept
    deliberately separate from the dispatching ``codec.iter_fields``)."""
    out = []
    pos, end = 0, len(buf)
    while pos < end:
        key, pos = codec.decode_varint(buf, pos)
        field, wt = codec.split_tag(key)
        if wt == codec.WT_VARINT:
            value, pos = codec.decode_varint(buf, pos)
        elif wt == codec.WT_FIXED64:
            if pos + 8 > end:
                raise codec.DecodeError("truncated fixed64")
            value, pos = buf[pos:pos + 8], pos + 8
        elif wt == codec.WT_LENGTH_DELIMITED:
            ln, pos = codec.decode_varint(buf, pos)
            if pos + ln > end:
                raise codec.DecodeError("truncated length-delimited field")
            value, pos = buf[pos:pos + ln], pos + ln
        elif wt == codec.WT_FIXED32:
            if pos + 4 > end:
                raise codec.DecodeError("truncated fixed32")
            value, pos = buf[pos:pos + 4], pos + 4
        else:
            raise codec.DecodeError(f"unknown wire type {wt}")
        out.append((field, wt, bytes(value) if isinstance(value, (bytes, bytearray)) else value, pos))
    return out


def _native_scanner():
    try:
        from serf_tpu.codec import _native
        if _native.load() is not None:
            return _native
    except Exception:  # noqa: BLE001 - native lib strictly optional here
        pass
    return None


def run(seed: int, seconds: float | None, cases: int | None) -> dict:
    rng = random.Random(seed)
    native = _native_scanner()
    stats = {"round_trips": 0, "mutations": 0, "garbage": 0,
             "decode_errors": 0, "violations": 0, "native_diffs": 0}
    deadline = time.monotonic() + seconds if seconds else None
    examples = []

    def check_decode(buf: bytes, where: str) -> None:
        try:
            decode_message(buf)
        except codec.DecodeError:
            stats["decode_errors"] += 1
        except Exception as e:  # noqa: BLE001 - the contract under test
            stats["violations"] += 1
            if len(examples) < 5:
                examples.append({"where": where, "err": repr(e),
                                 "buf": buf[:64].hex()})
        # swim packet layer (COMPOUND/USER framing shares the contract)
        try:
            sm.decode_swim(buf)
        except codec.DecodeError:
            pass
        except Exception as e:  # noqa: BLE001
            stats["violations"] += 1
            if len(examples) < 5:
                examples.append({"where": where + "/swim", "err": repr(e),
                                 "buf": buf[:64].hex()})
        if native is not None:
            body = buf[1:]
            scanned = native.scan_fields(body, 0, len(body))
            try:
                py = _python_scan(body)
            except codec.DecodeError:
                py = None
            if scanned is not None:
                got = (None if scanned == -1 else
                       [(f, w, bytes(v) if isinstance(v, (bytes, bytearray, memoryview)) else v, p)
                        for f, w, v, p in scanned])
                if got != py:
                    stats["native_diffs"] += 1
                    if len(examples) < 5:
                        examples.append({"where": where + "/native",
                                         "buf": body[:64].hex()})

    try:
        from serf_tpu.codec import _native
        lz4 = _native.lz4_fns()
        snappy = _native.snappy_fns()
    except Exception:  # noqa: BLE001 - native strictly optional
        lz4 = None
        snappy = None

    def check_lz4(buf: bytes) -> None:
        """The native LZ4 decoder parses untrusted packets: it must reject
        or produce exactly the requested size — never crash or over-read."""
        if lz4 is None:
            return
        comp, decomp = lz4
        try:
            decomp(buf, 64)   # wrapper raises unless exactly 64 decoded
        except ValueError:
            stats["decode_errors"] += 1
        except Exception as e:  # noqa: BLE001 - contract under test
            stats["violations"] += 1
            if len(examples) < 5:
                examples.append({"where": "lz4", "err": repr(e),
                                 "buf": buf[:64].hex()})
        # round-trip on the same buffer as plaintext
        try:
            enc = comp(buf)
            if decomp(enc, len(buf)) != buf:
                raise AssertionError("lz4 round-trip mismatch")
        except Exception as e:  # noqa: BLE001 - contract under test
            stats["violations"] += 1
            if len(examples) < 5:
                examples.append({"where": "lz4-roundtrip", "err": repr(e),
                                 "buf": buf[:64].hex()})

    def check_snappy(buf: bytes) -> None:
        """Same contract as check_lz4 for the native snappy decoder."""
        if snappy is None:
            return
        comp, decomp = snappy
        try:
            decomp(buf, 64)
        except ValueError:
            stats["decode_errors"] += 1
        except Exception as e:  # noqa: BLE001 - contract under test
            stats["violations"] += 1
            if len(examples) < 5:
                examples.append({"where": "snappy", "err": repr(e),
                                 "buf": buf[:64].hex()})
        try:
            enc = comp(buf)
            if decomp(enc, len(buf)) != buf:
                raise AssertionError("snappy round-trip mismatch")
        except Exception as e:  # noqa: BLE001 - contract under test
            stats["violations"] += 1
            if len(examples) < 5:
                examples.append({"where": "snappy-roundtrip", "err": repr(e),
                                 "buf": buf[:64].hex()})

    i = 0
    while True:
        if deadline is not None and time.monotonic() >= deadline:
            break
        if cases is not None and i >= cases:
            break
        i += 1
        msg = arbitrary_message(rng)
        raw = encode_any(msg)
        check_lz4(_mutate(rng, raw))
        check_snappy(_mutate(rng, raw))
        back = decode_message(raw)
        if back != msg:
            stats["violations"] += 1
            if len(examples) < 5:
                examples.append({"where": "round-trip",
                                 "msg": repr(msg)[:200],
                                 "back": repr(back)[:200]})
        stats["round_trips"] += 1

        # wrap through the swim USER framing + COMPOUND, like real packets
        pkt = sm.encode_compound([sm.encode_swim(sm.UserMsg(raw))])
        out = sm.decode_swim(pkt)
        if not (len(out) == 1 and out[0].payload == raw):
            stats["violations"] += 1

        for _ in range(4):
            check_decode(_mutate(rng, raw), "mutation")
            stats["mutations"] += 1
        check_decode(rng.randbytes(rng.randrange(96)), "garbage")
        stats["garbage"] += 1

    stats["cases"] = i
    stats["seed"] = seed
    stats["examples"] = examples
    stats["ok"] = stats["violations"] == 0 and stats["native_diffs"] == 0
    return stats


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seconds", type=float, default=None)
    ap.add_argument("--cases", type=int, default=None)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    if args.seconds is None and args.cases is None:
        args.seconds = 30.0
    stats = run(args.seed, args.seconds, args.cases)
    print(json.dumps(stats))
    return 0 if stats["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
