"""Regression tests for the round-1 advisor findings (ADVICE.md).

1. A crafted deeply-nested COMPOUND datagram must raise DecodeError, not
   RecursionError (remote one-packet DoS on the receive loop).
2. Stale-incarnation leave messages must be ignored (no re-mark of a
   rejoined/refuted node as LEFT).
3. Snapshotter.leave() must stop recording/compaction and clear the alive
   set, so a restart does not auto-rejoin a deliberately-left cluster.
4. MetricsSink.observe must keep bounded state, not append raw samples
   forever.
"""

import asyncio

import pytest

from serf_tpu import codec
from serf_tpu.host import messages as sm
from serf_tpu.host.memberlist import Memberlist, NodeState
from serf_tpu.host.messages import SwimState
from serf_tpu.host.transport import LoopbackNetwork
from serf_tpu.options import MemberlistOptions
from serf_tpu.types.member import Node


# ---------------------------------------------------------------------------
# 1. COMPOUND nesting bomb
# ---------------------------------------------------------------------------

def _nested_compound(depth: int, leaf: bytes) -> bytes:
    pkt = leaf
    for _ in range(depth):
        pkt = sm.encode_compound([pkt])
    return pkt


def test_compound_bomb_raises_decode_error_not_recursion():
    leaf = sm.encode_swim(sm.Ping(1, Node("a", "x"), "b"))
    # ~4k nesting levels fits in an ~8-16KB datagram and previously blew the
    # Python recursion limit, escaping the DecodeError contract.
    bomb = _nested_compound(5000, leaf)
    with pytest.raises(codec.DecodeError):
        sm.decode_swim(bomb)


def test_compound_moderate_nesting_decodes_in_order():
    p1 = sm.encode_swim(sm.Ping(1, Node("a", "x"), "b"))
    p2 = sm.encode_swim(sm.Ping(2, Node("c", "y"), "d"))
    p3 = sm.encode_swim(sm.Ping(3, Node("e", "z"), "f"))
    pkt = sm.encode_compound([p1, sm.encode_compound([p2, p3])])
    out = sm.decode_swim(pkt)
    assert [m.seq for m in out] == [1, 2, 3]


def test_compound_deep_but_legit_nesting_ok():
    leaf = sm.encode_swim(sm.Ping(7, Node("a", "x"), "b"))
    pkt = _nested_compound(64, leaf)
    out = sm.decode_swim(pkt)
    assert len(out) == 1 and out[0].seq == 7


# ---------------------------------------------------------------------------
# 2. stale-incarnation leave
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_stale_leave_does_not_remark_refuted_node():
    net = LoopbackNetwork()
    ml = Memberlist(net.bind("addr-0"), MemberlistOptions.local(), "node-0")
    await ml.start()
    try:
        ml._nodes["node-1"] = NodeState(Node("node-1", "addr-1"),
                                        incarnation=5, state=SwimState.ALIVE)
        # an old leave (incarnation 3) still circulating in gossip
        ml._handle_dead(sm.Dead(3, "node-1", "node-1"))
        assert ml._nodes["node-1"].state == SwimState.ALIVE
        # a current leave is honored
        ml._handle_dead(sm.Dead(5, "node-1", "node-1"))
        assert ml._nodes["node-1"].state == SwimState.LEFT
    finally:
        await ml.shutdown()


@pytest.mark.asyncio
async def test_stale_dead_from_third_party_still_ignored():
    net = LoopbackNetwork()
    ml = Memberlist(net.bind("addr-0"), MemberlistOptions.local(), "node-0")
    await ml.start()
    try:
        ml._nodes["node-1"] = NodeState(Node("node-1", "addr-1"),
                                        incarnation=5, state=SwimState.ALIVE)
        ml._handle_dead(sm.Dead(4, "node-1", "node-2"))
        assert ml._nodes["node-1"].state == SwimState.ALIVE
    finally:
        await ml.shutdown()


# ---------------------------------------------------------------------------
# 3. snapshot leave vs compaction
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_snapshot_leave_survives_compaction(tmp_path):
    from serf_tpu.host.events import MemberEvent, MemberEventType
    from serf_tpu.host.snapshot import (R_LEAVE, Snapshotter,
                                        open_and_replay_snapshot)
    from serf_tpu.types.member import Member

    path = str(tmp_path / "snap.db")
    snap = Snapshotter(path, open_and_replay_snapshot(path),
                       min_compact_size=64)
    members = [Member(Node(f"node-{i}", f"addr-{i}")) for i in range(8)]
    snap.observe(MemberEvent(MemberEventType.JOIN, tuple(members)))
    await snap.leave()
    # post-leave observations and compactions must be suppressed
    snap.observe(MemberEvent(MemberEventType.JOIN,
                             (Member(Node("late", "addr-x")),)))
    snap._maybe_compact()  # would previously rewrite the log w/o the leave
    await snap.shutdown()

    replay = open_and_replay_snapshot(path, rejoin_after_leave=False)
    assert replay.left_before
    assert replay.alive_nodes == []


@pytest.mark.asyncio
async def test_snapshot_leave_keeps_alive_set_when_rejoin_after_leave(tmp_path):
    from serf_tpu.host.events import MemberEvent, MemberEventType
    from serf_tpu.host.snapshot import Snapshotter, open_and_replay_snapshot
    from serf_tpu.types.member import Member

    path = str(tmp_path / "snap.db")
    snap = Snapshotter(path, open_and_replay_snapshot(path),
                       rejoin_after_leave=True)
    snap.observe(MemberEvent(MemberEventType.JOIN,
                             (Member(Node("peer", "addr-1")),)))
    await snap.leave()
    assert "peer" in snap._alive  # kept for rejoin
    await snap.shutdown()
    replay = open_and_replay_snapshot(path, rejoin_after_leave=True)
    assert replay.left_before
    assert [n.id for n in replay.alive_nodes] == ["peer"]


# ---------------------------------------------------------------------------
# 4. bounded metrics
# ---------------------------------------------------------------------------

def test_metrics_histograms_are_bounded():
    from serf_tpu.utils.metrics import HISTOGRAM_RING_SIZE, MetricsSink

    sink = MetricsSink()
    n = HISTOGRAM_RING_SIZE * 4
    for i in range(n):
        sink.observe("pkt.size", float(i))
    summ = sink.histogram_summary("pkt.size")
    assert summ.count == n
    assert summ.min == 0.0 and summ.max == float(n - 1)
    assert summ.mean == pytest.approx((n - 1) / 2)
    recent = sink.histogram("pkt.size")
    assert len(recent) == HISTOGRAM_RING_SIZE
    # ring holds the most recent samples, oldest first
    assert recent[0] == float(n - HISTOGRAM_RING_SIZE)
    assert recent[-1] == float(n - 1)


def test_compound_with_empty_part_raises_decode_error():
    pkt = sm.encode_compound([b""])
    with pytest.raises(codec.DecodeError):
        sm.decode_swim(pkt)


# ---------------------------------------------------------------------------
# round-2 ADVICE: pick_bounded must trace for any max_events
# ---------------------------------------------------------------------------

def test_pick_bounded_max_events_above_group_count_traces():
    """ADVICE r2 (low): the grouped path called top_k(col_max, max_events)
    with a _PICK_GROUPS-element array — max_events > _PICK_GROUPS failed at
    trace time.  The k is now clamped and the tail padded inactive."""
    import jax
    import jax.numpy as jnp
    from serf_tpu.models.dissemination import (
        _PICK_FLAT_MAX, _PICK_GROUPS, pick_bounded)

    n = 2 * _PICK_FLAT_MAX          # forces the grouped path
    jax.eval_shape(                  # trace only; no large CPU compute
        lambda c, k: pick_bounded(c, _PICK_GROUPS + 64, k),
        jax.ShapeDtypeStruct((n,), jnp.bool_),
        jax.random.PRNGKey(0))


def test_pick_bounded_max_events_above_n_flat():
    """Flat path: max_events > n must clamp top_k's k and still pick every
    candidate."""
    import jax
    import jax.numpy as jnp
    from serf_tpu.models.dissemination import pick_bounded

    candidates = jnp.asarray([True, False, True, False])
    chosen, subjects, active = pick_bounded(
        candidates, 8, jax.random.PRNGKey(3))
    assert bool(jnp.all(chosen == candidates))
    assert int(jnp.sum(active)) == 2
