"""Device-plane tests: dissemination, failure detection, partition/heal,
Vivaldi parity vs the host oracle, and multi-device sharding parity.

These run on the virtual 8-device CPU mesh (conftest) — the backend-generic
test translation of the reference's runtime-generic suites (SURVEY.md §4):
the host plane is the oracle, the device plane must agree.
"""

import functools
import math
import random

import jax
import jax.numpy as jnp
import pytest

from serf_tpu.models.antientropy import (
    knowledge_agreement,
    make_partition,
    push_pull_round,
)
from serf_tpu.models.dissemination import (
    GossipConfig,
    K_ALIVE,
    K_DEAD,
    K_SUSPECT,
    K_USER_EVENT,
    coverage,
    fully_disseminated,
    inject_fact,
    make_state,
    pack_bits,
    round_step,
    run_rounds,
    unpack_bits,
)
from serf_tpu.models.failure import (
    FailureConfig,
    believed_dead,
    detection_complete,
    probe_round,
    rotation_offset,
    run_swim,
    swim_round,
)
from serf_tpu.models.swim import ClusterConfig, cluster_round, make_cluster, run_cluster
from serf_tpu.models.vivaldi import (
    VivaldiConfig,
    ground_truth_rtt,
    make_vivaldi,
    mean_relative_error,
    vivaldi_update,
)
from serf_tpu.parallel.mesh import make_mesh, shard_state, state_shardings


def test_pack_unpack_round_trip():
    key = jax.random.key(0)
    mask = jax.random.bernoulli(key, 0.3, (17, 64))
    assert bool(jnp.all(unpack_bits(pack_bits(mask), 64) == mask))


def test_single_fact_disseminates_log_n():
    cfg = GossipConfig(n=1024, k_facts=32)
    s = inject_fact(make_state(cfg), cfg, 0, K_USER_EVENT, 0, 1, 0)
    run = jax.jit(functools.partial(run_rounds, cfg=cfg),
                  static_argnames=("num_rounds",))
    # epidemic spread: O(log N) rounds; 30 rounds is generous for N=1024
    s = run(s, key=jax.random.key(1), num_rounds=30)
    assert float(coverage(s, cfg)[0]) == 1.0
    assert bool(fully_disseminated(s, cfg)[0])


def test_transmit_budget_retires_facts():
    cfg = GossipConfig(n=64, k_facts=32)
    s = inject_fact(make_state(cfg), cfg, 0, K_USER_EVENT, 0, 1, 0)
    run = jax.jit(functools.partial(run_rounds, cfg=cfg),
                  static_argnames=("num_rounds",))
    s = run(s, key=jax.random.key(1), num_rounds=200)
    # after convergence + budget exhaustion nothing is being sent
    from serf_tpu.models.dissemination import budgets_of
    assert int(jnp.sum(budgets_of(s, cfg))) == 0
    assert float(coverage(s, cfg)[0]) == 1.0


def test_dead_nodes_learn_nothing():
    cfg = GossipConfig(n=128, k_facts=32)
    s = make_state(cfg)
    s = s._replace(alive=s.alive.at[7].set(False))
    s = inject_fact(s, cfg, 0, K_USER_EVENT, 0, 1, 0)
    s = run_rounds(s, cfg, jax.random.key(1), 40)
    known = unpack_bits(s.known, cfg.k_facts)
    assert not bool(known[7, 0])
    assert float(coverage(s, cfg)[0]) == 1.0  # alive nodes all converged


def test_fact_ring_overwrites_oldest():
    cfg = GossipConfig(n=32, k_facts=32)
    s = make_state(cfg)
    for i in range(cfg.k_facts + 3):
        s = inject_fact(s, cfg, i, K_USER_EVENT, 0, i + 1, 0)
    # slots 0..2 were overwritten by subjects 32..34
    assert int(s.facts.subject[0]) == 32
    assert int(s.facts.subject[3]) == 3


def test_failure_detection_and_dissemination():
    cfg = GossipConfig(n=256, k_facts=64)
    fcfg = FailureConfig(suspicion_rounds=8, max_new_facts=4)
    s = make_state(cfg)
    dead = jnp.array([3, 77, 200])
    s = s._replace(alive=s.alive.at[dead].set(False))
    step = jax.jit(functools.partial(swim_round, cfg=cfg, fcfg=fcfg))
    key = jax.random.key(5)
    done = None
    for r in range(150):
        key, k2 = jax.random.split(key)
        s = step(s, key=k2)
        if bool(detection_complete(s, cfg, fcfg)):
            done = r + 1
            break
    assert done is not None, "deaths never fully detected"
    # no false positives
    bd = believed_dead(s, cfg, fcfg)
    assert int(jnp.sum(bd & s.alive)) == 0


def test_no_false_deaths_under_packet_loss():
    """Lifeguard property: refutation keeps healthy nodes alive even with
    30% ack loss (the reference's suspicion/refute machinery)."""
    cfg = GossipConfig(n=128, k_facts=64)
    fcfg = FailureConfig(suspicion_rounds=10, max_new_facts=4,
                         probe_drop_rate=0.3)
    s = make_state(cfg)
    run = jax.jit(functools.partial(run_swim, cfg=cfg, fcfg=fcfg),
                  static_argnames=("num_rounds",))
    s = run(s, key=jax.random.key(9), num_rounds=80)
    bd = believed_dead(s, cfg, fcfg)
    assert int(jnp.sum(bd)) == 0
    assert int(jnp.sum(s.incarnation > 1)) > 0  # refutations happened


def test_partition_blocks_and_heal_merges():
    """Baseline config #4: push/pull anti-entropy under partition + heal."""
    cfg = GossipConfig(n=256, k_facts=32)
    s = make_state(cfg)
    group = make_partition(cfg.n, 0.5)
    # one fact born on each side
    s = inject_fact(s, cfg, 0, K_USER_EVENT, 0, 1, 0)        # group 0 origin
    s = inject_fact(s, cfg, 1, K_USER_EVENT, 0, 2, cfg.n - 1)  # group 1 origin
    key = jax.random.key(3)
    step = jax.jit(functools.partial(round_step, cfg=cfg))
    for _ in range(40):
        key, k2 = jax.random.split(key)
        s = step(s, key=k2, group=group)
    known = unpack_bits(s.known, cfg.k_facts)
    half = cfg.n // 2
    # each fact fully covers its own side, zero leakage across
    assert bool(jnp.all(known[:half, 0])) and not bool(jnp.any(known[half:, 0]))
    assert bool(jnp.all(known[half:, 1])) and not bool(jnp.any(known[:half, 1]))
    # heal: anti-entropy re-energizes budgets; cluster fully merges
    healed = jnp.zeros((cfg.n,), jnp.int32)
    pp = jax.jit(functools.partial(push_pull_round, cfg=cfg))
    merged_at = None
    for r in range(60):
        key, k2, k3 = jax.random.split(key, 3)
        s = pp(s, key=k2, group=healed)
        s = step(s, key=k3, group=healed)
        if float(knowledge_agreement(s, cfg)) == 1.0:
            merged_at = r + 1
            break
    assert merged_at is not None, "two-cluster merge never completed"


def test_vivaldi_device_matches_host_oracle():
    """State parity: the vectorized vivaldi update must reproduce the host
    CoordinateClient (latency_filter_size=1) step-for-step."""
    from serf_tpu.host.coordinate import Coordinate, CoordinateClient, CoordinateOptions

    n, steps = 4, 25
    vcfg = VivaldiConfig()
    dev = make_vivaldi(n, vcfg)
    hosts = [
        CoordinateClient(CoordinateOptions(latency_filter_size=1),
                         rng=random.Random(i))
        for i in range(n)
    ]
    # start from distinct positions: coincident points trigger *random*
    # separation vectors (different RNGs host vs device would chaotically
    # diverge); distinct starts make the whole math path deterministic
    rng = random.Random(0)
    init = [[(i + 1) * 1e-3 * (d + 1) for d in range(vcfg.dimensionality)]
            for i in range(n)]
    dev = dev._replace(vec=jnp.array(init, jnp.float32))
    for i, h in enumerate(hosts):
        h.set_coordinate(Coordinate(portion=tuple(init[i]),
                                    error=vcfg.error_max, adjustment=0.0,
                                    height=vcfg.height_min))
    key = jax.random.key(0)
    for step in range(steps):
        # never self-peer: measuring rtt to yourself is coincident-coords
        # territory (random separation vectors, untestable determinism)
        peers = jnp.array([(i + 1 + rng.randrange(n - 1)) % n
                           for i in range(n)])
        rtts = jnp.array([0.01 + 0.02 * rng.random() for _ in range(n)],
                         jnp.float32)
        # host side: same peers/rtts, coordinates exchanged before updates
        # (both sides read the pre-round peer state)
        coords = [h.get_coordinate() for h in hosts]
        for i in range(n):
            hosts[i].update(f"n{int(peers[i])}", coords[int(peers[i])],
                            float(rtts[i]))
        key, k2 = jax.random.split(key)
        dev = vivaldi_update(dev, vcfg, peers, rtts, k2)
        for i in range(n):
            hc = hosts[i].get_coordinate()
            assert math.isclose(float(dev.error[i]), hc.error,
                                rel_tol=1e-3, abs_tol=1e-5), \
                f"error diverged at step {step} node {i}"
            assert math.isclose(float(dev.adjustment[i]), hc.adjustment,
                                rel_tol=1e-3, abs_tol=1e-6), \
                f"adjustment diverged at step {step} node {i}"
            for d in range(vcfg.dimensionality):
                assert math.isclose(float(dev.vec[i, d]), hc.portion[d],
                                    rel_tol=1e-3, abs_tol=1e-6), \
                    f"vec[{d}] diverged at step {step} node {i}"
            assert math.isclose(float(dev.height[i]), hc.height,
                                rel_tol=1e-3, abs_tol=1e-7), \
                f"height diverged at step {step} node {i}"


def test_vivaldi_estimates_improve():
    n = 512
    vcfg = VivaldiConfig()
    key = jax.random.key(0)
    positions = jax.random.uniform(key, (n, 3), jnp.float32) * 0.05
    dev = make_vivaldi(n, vcfg)
    step = jax.jit(functools.partial(vivaldi_update, cfg=vcfg))
    err0 = float(mean_relative_error(dev, vcfg, positions, jax.random.key(1)))
    for r in range(150):
        key, k1, k2 = jax.random.split(key, 3)
        peers = jax.random.randint(k1, (n,), 0, n)
        rtt = ground_truth_rtt(positions, jnp.arange(n), peers)
        dev = step(dev, peer=peers, rtt=rtt, key=k2)
    err1 = float(mean_relative_error(dev, vcfg, positions, jax.random.key(2)))
    assert err1 < err0 * 0.5, f"estimation error did not improve: {err0} -> {err1}"


def test_cluster_round_composes():
    cfg = ClusterConfig(gossip=GossipConfig(n=512, k_facts=32),
                        push_pull_every=8)
    key = jax.random.key(0)
    state = make_cluster(cfg, key)
    state = state._replace(
        gossip=inject_fact(state.gossip, cfg.gossip, 2, K_USER_EVENT, 0, 1, 0))
    run = jax.jit(functools.partial(run_cluster, cfg=cfg),
                  static_argnames=("num_rounds",))
    out = run(state, key=jax.random.key(1), num_rounds=25)
    assert float(coverage(out.gossip, cfg.gossip)[0]) == 1.0
    assert int(out.gossip.round) == 25


def test_sustained_load_keeps_gate_open_and_disseminates():
    """``run_cluster_sustained`` (the bench headline workload): continuous
    event injection keeps the quiescent gate open, the fact ring fills and
    recycles, and a fact that lived out its ring lifetime reached every
    alive node before its slot recycled — i.e. the sustained config does
    full dissemination work every round AND that work completes."""
    from serf_tpu.models.swim import run_cluster_sustained

    # k_facts=64: each fact lives 32 rounds, above the 16-round transmit
    # limit at n=1024 (the ADVICE-r5 lifetime headroom sustained_round
    # now enforces at trace time)
    cfg = ClusterConfig(gossip=GossipConfig(n=1024, k_facts=64,
                                            peer_sampling="rotation"),
                        probe_every=5)
    state = make_cluster(cfg, jax.random.key(0))
    run = jax.jit(functools.partial(run_cluster_sustained, cfg=cfg),
                  static_argnames=("num_rounds", "events_per_round"))
    out = run(state, key=jax.random.key(1), num_rounds=100,
              events_per_round=2)
    g = out.gossip
    assert int(g.round) == 100
    assert int(g.next_slot) == 200, "injection did not run every round"
    assert bool(jnp.all(g.facts.valid)), "ring did not fill"
    # the quiescent gate never closed: the last injection was this round
    assert int(g.round) - int(g.last_learn) < cfg.gossip.transmit_limit
    cov = coverage(g, cfg.gossip)
    k = cfg.gossip.k_facts
    oldest = [(int(g.next_slot) + i) % k for i in range(4)]
    newest = (int(g.next_slot) - 1) % k
    # oldest surviving facts (injected k/rate = 32 > transmit_limit
    # rounds ago) fully disseminated; the fact injected THIS round has not
    for s in oldest:
        assert float(cov[s]) == 1.0, f"old fact {s} never fully spread"
    assert float(cov[newest]) < 1.0, "a fresh fact cannot be everywhere"


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
@pytest.mark.parametrize("n,rounds", [
    # the 1024-node/30-round GSPMD-lowered parity run was ~18s of tier-1
    # wall clock — promoted to @slow (ISSUE 11 budget reclaim); the
    # smaller variant keeps the pure-GSPMD run_cluster parity bar in
    # tier-1 (the authored-exchange parity crosses live in
    # test_sharded_round/test_ring)
    pytest.param(1024, 30, marks=pytest.mark.slow),
    (256, 16),
])
def test_sharded_parity_8_devices(n, rounds):
    """The same simulation sharded over 8 devices must be bit-identical to
    the single-device run (the north-star 'state parity' bar)."""
    cfg = ClusterConfig(gossip=GossipConfig(n=n, k_facts=32),
                        push_pull_every=10)
    key = jax.random.key(0)
    state = make_cluster(cfg, key)
    state = state._replace(
        gossip=inject_fact(state.gossip, cfg.gossip, 3, K_USER_EVENT, 0, 5, 0))
    mesh = make_mesh(8)
    sharded = shard_state(state, mesh)
    out_sh = state_shardings(state, mesh)
    run8 = jax.jit(functools.partial(run_cluster, cfg=cfg),
                   static_argnames=("num_rounds",), out_shardings=out_sh)
    run1 = jax.jit(functools.partial(run_cluster, cfg=cfg),
                   static_argnames=("num_rounds",))
    s8 = run8(sharded, key=jax.random.key(2), num_rounds=rounds)
    s1 = run1(state, key=jax.random.key(2), num_rounds=rounds)
    assert bool(jnp.all(s1.gossip.known == s8.gossip.known))
    assert bool(jnp.all(s1.gossip.stamp == s8.gossip.stamp))
    assert bool(jnp.allclose(s1.vivaldi.vec, s8.vivaldi.vec, atol=1e-6))


def test_graft_entry_contract_fast():
    """entry()'s contract without paying the 16k-node compile: abstract
    tracing (eval_shape) type-checks the whole round and the output
    pytree — the full compile + multichip dryrun runs under -m slow."""
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.eval_shape(fn, *args)
    assert out.gossip.round.shape == ()
    assert out.gossip.known.shape[0] == args[0].gossip.known.shape[0]


@pytest.mark.slow
def test_graft_entry_smoke():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert int(out.gossip.round) == 1
    g.dryrun_multichip(len(jax.devices()))

def test_failure_detection_when_node_zero_dies():
    """Regression: subject 0's suspicion must get a real (alive) detector as
    origin — an unmasked scatter once handed it dead node 0 itself, wedging
    detection forever."""
    cfg = GossipConfig(n=512, k_facts=64)
    fcfg = FailureConfig(suspicion_rounds=8, max_new_facts=4)
    s = make_state(cfg)
    s = s._replace(alive=s.alive.at[0].set(False))  # node 0 dies
    step = jax.jit(functools.partial(swim_round, cfg=cfg, fcfg=fcfg))
    key = jax.random.key(11)
    for r in range(120):
        key, k2 = jax.random.split(key)
        s = step(s, key=k2)
        if bool(detection_complete(s, cfg, fcfg)):
            break
    else:
        raise AssertionError("death of node 0 never fully detected")


def test_checkpoint_resume_bit_exact():
    """Device checkpoint/resume: a resumed run with the same keys must be
    bit-identical to an unbroken run (SURVEY.md §7 stage 9)."""
    import tempfile, os
    from serf_tpu.models import checkpoint

    cfg = ClusterConfig(gossip=GossipConfig(n=256, k_facts=32),
                        push_pull_every=8)
    state = make_cluster(cfg, jax.random.key(0))
    state = state._replace(
        gossip=inject_fact(state.gossip, cfg.gossip, 1, K_USER_EVENT, 0, 1, 0))
    step = jax.jit(functools.partial(cluster_round, cfg=cfg))
    keys = jax.random.split(jax.random.key(9), 20)

    # unbroken run
    a = state
    for k in keys:
        a = step(a, key=k)

    # run 10, checkpoint, restore, run 10 more
    b = state
    for k in keys[:10]:
        b = step(b, key=k)
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "ck.npz")
        checkpoint.save(p, b)
        template = make_cluster(cfg, jax.random.key(0))
        template = template._replace(
            gossip=inject_fact(template.gossip, cfg.gossip, 1, K_USER_EVENT, 0, 1, 0))
        b = checkpoint.restore(p, template)
    for k in keys[10:]:
        b = step(b, key=k)

    for la, lb in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        assert bool(jnp.all(la == lb))


def test_checkpoint_shape_mismatch_rejected():
    import tempfile, os
    from serf_tpu.models import checkpoint

    cfg_a = ClusterConfig(gossip=GossipConfig(n=128, k_facts=32))
    cfg_b = ClusterConfig(gossip=GossipConfig(n=256, k_facts=32))
    sa = make_cluster(cfg_a, jax.random.key(0))
    sb = make_cluster(cfg_b, jax.random.key(0))
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "ck.npz")
        checkpoint.save(p, sa)
        with pytest.raises(ValueError):
            checkpoint.restore(p, sb)


def test_composed_views_none_stays_none():
    """A death notice about a never-joined member carries no serf status
    (review finding)."""
    from serf_tpu.models.membership import (composed_views, V_ALIVE, V_FAILED,
                                            V_LEFT, V_LEAVING, V_NONE)
    from serf_tpu.models.dissemination import K_JOIN, K_LEAVE

    cfg = GossipConfig(n=64, k_facts=32)
    s = make_state(cfg)
    s = inject_fact(s, cfg, 0, K_JOIN, 0, 5, 0)    # subject 0 joined
    s = inject_fact(s, cfg, 1, K_LEAVE, 0, 6, 0)   # subject 1 leaving
    # subject 2: no intent at all
    s = run_rounds(s, cfg, jax.random.key(0), 25)
    subjects = jnp.arange(3, dtype=jnp.int32)
    swim_dead = jnp.ones((cfg.n, 3), bool)  # everyone believes all 3 dead
    v = composed_views(s, cfg, subjects, swim_dead)
    assert int(v[0, 0]) == V_FAILED    # alive -> failed
    assert int(v[0, 1]) == V_LEFT      # leaving -> left
    assert int(v[0, 2]) == V_NONE      # never seen -> stays none


def test_failure_config_rejects_oversized_suspicion_window():
    """Derived q-ages are pinned at AGE_PIN_Q quarter-ticks; windows
    beyond AGE_PIN_Q * STAMP_UNIT rounds are unrepresentable."""
    from serf_tpu.models.dissemination import AGE_PIN_Q, STAMP_UNIT
    bound = AGE_PIN_Q * STAMP_UNIT
    with pytest.raises(ValueError):
        FailureConfig(suspicion_rounds=bound + 1)
    FailureConfig(suspicion_rounds=bound)  # boundary ok


def test_hybrid_multihost_mesh_runs():
    """DCN x ICI hybrid sharding: one step over the (1, n_devices) mesh on
    this single host; multi-host is the same contract over processes."""
    import numpy as np
    from jax.sharding import Mesh
    from serf_tpu.parallel import multihost

    n_dev = len(jax.devices())
    devices = np.array(jax.devices()).reshape(1, n_dev)
    mesh = Mesh(devices, (multihost.DCN_AXIS, multihost.ICI_AXIS))
    cfg = ClusterConfig(gossip=GossipConfig(n=128 * n_dev, k_facts=32))
    state = make_cluster(cfg, jax.random.key(0))
    state = state._replace(
        gossip=inject_fact(state.gossip, cfg.gossip, 1, K_USER_EVENT, 0, 1, 0))
    sharded = multihost.shard_cluster_hybrid(state, mesh)
    out = jax.jit(functools.partial(cluster_round, cfg=cfg))(
        sharded, key=jax.random.key(1))
    assert int(out.gossip.round) == 1


def test_10k_node_dissemination_config():
    """Baseline config #2 at true scale: a user event over a 10k-node
    cluster reaches full coverage within the epidemic bound."""
    cfg = GossipConfig(n=10_000, k_facts=32)
    s = inject_fact(make_state(cfg), cfg, 0, K_USER_EVENT, 0, 1, 0)
    run = jax.jit(functools.partial(run_rounds, cfg=cfg),
                  static_argnames=("num_rounds",))
    s = run(s, key=jax.random.key(0), num_rounds=30)
    assert float(coverage(s, cfg)[0]) == 1.0


def test_inject_facts_batch_matches_sequential_inject():
    """The one-scatter batched injection must be state-identical to the
    sequential inject_fact loop it replaced (round-1 verdict, weak #7)."""
    from serf_tpu.models.dissemination import (FactTable, GossipState,
                                                inject_facts_batch)

    cfg = GossipConfig(n=64, k_facts=32, fanout=2)
    rng = random.Random(7)

    for trial in range(20):
        state = make_state(cfg)
        # pre-populate a few slots so retirement/clearing is exercised
        for s in range(rng.randrange(0, 5)):
            state = inject_fact(state, cfg, subject=rng.randrange(cfg.n),
                                kind=K_USER_EVENT, incarnation=1,
                                ltime=s, origin=rng.randrange(cfg.n))
        state = state._replace(round=jnp.asarray(rng.randrange(50), jnp.int32))

        m = 8
        n_real = rng.randrange(0, m + 1)
        subjects = [rng.randrange(cfg.n) for _ in range(m)]
        origins = [rng.randrange(cfg.n) for _ in range(m)]
        incs = [rng.randrange(1, 5) for _ in range(m)]
        active = [i < n_real for i in range(m)]

        seq = state
        for i in range(m):
            if active[i]:
                seq = inject_fact(seq, cfg, subject=subjects[i], kind=K_SUSPECT,
                                  incarnation=incs[i],
                                  ltime=int(state.round), origin=origins[i])

        batch = inject_facts_batch(
            state, cfg,
            subjects=jnp.asarray(subjects, jnp.int32),
            kind=K_SUSPECT,
            incarnations=jnp.asarray(incs, jnp.uint32),
            ltimes=jnp.full((m,), int(state.round), jnp.uint32),
            origins=jnp.asarray(origins, jnp.int32),
            active=jnp.asarray(active),
        )

        for name in GossipState._fields:
            a, b = getattr(seq, name), getattr(batch, name)
            if name == "facts":
                for fn in FactTable._fields:
                    assert jnp.array_equal(getattr(a, fn), getattr(b, fn)), \
                        f"trial {trial}: facts.{fn} mismatch"
            else:
                assert jnp.array_equal(a, b), f"trial {trial}: {name} mismatch"


def test_inject_facts_batch_jaxpr_has_no_per_candidate_state_copies():
    """The batched injection must not materialize per-candidate copies of the
    N-major planes: the jaxpr should contain O(1) select_n ops over the
    age plane, not O(max_new)."""
    from serf_tpu.models.dissemination import inject_facts_batch

    cfg = GossipConfig(n=256, k_facts=64)
    state = make_state(cfg)
    m = 8

    def f(state):
        return inject_facts_batch(
            state, cfg,
            subjects=jnp.arange(m, dtype=jnp.int32),
            kind=K_SUSPECT,
            incarnations=jnp.ones((m,), jnp.uint32),
            ltimes=jnp.zeros((m,), jnp.uint32),
            origins=jnp.arange(m, dtype=jnp.int32),
            active=jnp.ones((m,), bool),
        )

    jaxpr = jax.make_jaxpr(f)(state)
    text = str(jaxpr)
    # count full-plane selects — jaxpr renders them as e.g.
    # "c:u8[256,64] = select_n ...".  With the stamp plane, injection needs
    # NO full-plane select at all (retirement is the known-bit clear; the
    # stamp write is a bounded scatter); a couple of incidental word-plane
    # ops are fine; one-per-candidate (8+) is the regression this guards.
    import re
    full_plane = re.findall(r"\[256,64\] = select_n|\[256,2\] = select_n", text)
    assert len(full_plane) <= 4, \
        f"expected <=4 full-plane select_n ops, found {len(full_plane)}"


def test_indirect_probes_suppress_false_suspicion():
    """SWIM indirect probing: with k=3 helpers, 20% path loss almost never
    suspects a healthy node (needs all 4 paths down: 0.2^4 = 0.16%), while
    k=0 suspects constantly."""
    from serf_tpu.models.failure import probe_round

    cfg = GossipConfig(n=512, k_facts=64)
    s = make_state(cfg)  # everyone alive: any suspicion is false
    key = jax.random.key(21)

    def count_suspects(fcfg, rounds=30):
        st, k = s, key
        total = 0
        step = jax.jit(functools.partial(probe_round, cfg=cfg, fcfg=fcfg))
        for _ in range(rounds):
            k, k2 = jax.random.split(k)
            st2 = step(st, key=k2)
            total += int(st2.next_slot - st.next_slot)
            st = st2
        return total

    with_ind = count_suspects(FailureConfig(probe_drop_rate=0.2,
                                            indirect_probes=3))
    without = count_suspects(FailureConfig(probe_drop_rate=0.2,
                                           indirect_probes=0))
    # k=0 control saturates the 8/round injection cap (~240 over 30 rounds);
    # k=3 expectation is n·p^4 ≈ 0.8/round ≈ 25 — allow 2.5x slack
    assert without >= 200, f"k=0 control too quiet: {without}"
    assert with_ind <= 62, (with_ind, without)


def test_indirect_probes_do_not_mask_real_deaths():
    """A dead target never acks on any path: detection latency is unchanged
    by indirect probing."""
    cfg = GossipConfig(n=256, k_facts=64)
    fcfg = FailureConfig(suspicion_rounds=8, max_new_facts=4,
                         probe_drop_rate=0.2, indirect_probes=3)
    s = make_state(cfg)._replace(
        alive=jnp.ones((256,), bool).at[42].set(False))
    step = jax.jit(functools.partial(swim_round, cfg=cfg, fcfg=fcfg))
    key = jax.random.key(22)
    for r in range(120):
        key, k2 = jax.random.split(key)
        s = step(s, key=k2)
        if bool(detection_complete(s, cfg, fcfg)):
            break
    assert bool(detection_complete(s, cfg, fcfg))


def test_declare_round_attributes_declarer_per_subject():
    """Each dead declaration's origin must be a knower whose suspicion of
    THAT subject expired, not one global declarer (round-1 verdict weak #9)."""
    from serf_tpu.models.failure import declare_round

    cfg = GossipConfig(n=64, k_facts=32)
    fcfg = FailureConfig(suspicion_rounds=4, max_new_facts=4)
    s = make_state(cfg)
    # two suspicions about different subjects, known at different knowers
    s = inject_fact(s, cfg, subject=10, kind=K_SUSPECT, incarnation=1,
                    ltime=1, origin=20)
    s = inject_fact(s, cfg, subject=11, kind=K_SUSPECT, incarnation=1,
                    ltime=1, origin=30)
    # age both past the suspicion window at their origins only:
    # back-date the learn stamps so the derived q-ages are 3 quarters
    # (= 12 rounds, past suspicion_rounds=4).  Slots 0 and 1 share a
    # packed byte, so edit through the nibble view.
    from serf_tpu.models.dissemination import (
        pack_stamp_nibbles,
        round_q,
        stamp_nibbles,
    )
    aged = (round_q(s.round) - jnp.uint8(3)) & jnp.uint8(0xF)
    nib = stamp_nibbles(s.stamp, cfg.k_facts, cfg.pack_stamp)
    nib = nib.at[20, 0].set(aged).at[30, 1].set(aged)
    s = s._replace(stamp=pack_stamp_nibbles(nib, cfg.pack_stamp),
                   alive=s.alive.at[10].set(False).at[11].set(False))
    out = declare_round(s, cfg, fcfg, jax.random.key(0))
    dead_slots = jnp.nonzero((out.facts.kind == K_DEAD) & out.facts.valid)[0]
    origin_of = {}
    known = unpack_bits(out.known, cfg.k_facts)
    for sl in dead_slots:
        sl = int(sl)
        subject = int(out.facts.subject[sl])
        knowers = jnp.nonzero(known[:, sl])[0]
        assert len(knowers) == 1
        origin_of[subject] = int(knowers[0])
    assert origin_of == {10: 20, 11: 30}


@pytest.mark.parametrize("n", [
    # the 1024-node cross was ~23s of tier-1 wall clock — promoted to
    # @slow (ISSUE 11 budget reclaim); the 256-node variant keeps the
    # query+churn+linger sharded parity cross pinned every run
    pytest.param(1024, marks=pytest.mark.slow),
    256,
])
def test_sharded_query_churn_parity_8_devices(n):
    """Query gather + churn composed with the flagship round — including
    the leave-linger countdown carry the production step ships — sharded
    over 8 devices, must be bit-identical to the single-device run."""
    from serf_tpu.models.churn import (ChurnConfig, churn_round,
                                       linger_init, linger_step)
    from serf_tpu.models.query import (QueryConfig, launch_query,
                                       make_queries, no_filter_mask,
                                       query_round)

    cfg = ClusterConfig(gossip=GossipConfig(n=n, k_facts=32),
                        push_pull_every=10)
    ccfg = ChurnConfig(fail_rate=1e-3, leave_rate=1e-3, rejoin_rate=0.05,
                       max_events=4)
    qcfg = QueryConfig(q_slots=2, relay_factor=2)
    state = make_cluster(cfg, jax.random.key(0))
    g, qs, _ = launch_query(state.gossip, make_queries(cfg.gossip, qcfg),
                            cfg.gossip, qcfg, origin=0,
                            eligible=no_filter_mask(cfg.n))
    state = state._replace(gossip=g)

    def steps(st, qs, key, num_rounds):
        def body(carry, subkey):
            st, qs, cd = carry
            k_c, k_r, k_q = jax.random.split(subkey, 3)
            g, new_leavers = churn_round(st.gossip, cfg.gossip, ccfg, k_c)
            st = st._replace(gossip=g)
            st = cluster_round(st, cfg, k_r)
            qs = query_round(st.gossip, qs, cfg.gossip, qcfg, k_q)
            cd, go_down = linger_step(cd, new_leavers,
                                      ccfg.leave_linger_rounds,
                                      alive=st.gossip.alive)
            g2 = st.gossip
            st = st._replace(gossip=g2._replace(alive=g2.alive & ~go_down))
            return (st, qs, cd), ()
        (st, qs, _cd), _ = jax.lax.scan(body, (st, qs, linger_init(cfg.n)),
                                        jax.random.split(key, num_rounds))
        return st, qs

    mesh = make_mesh(8)
    out_sh = (state_shardings(state, mesh), state_shardings(
        make_queries(cfg.gossip, qcfg), mesh))
    run8 = jax.jit(steps, static_argnames=("num_rounds",),
                   out_shardings=out_sh)
    run1 = jax.jit(steps, static_argnames=("num_rounds",))
    s8, q8 = run8(shard_state(state, mesh), shard_state(qs, mesh),
                  jax.random.key(2), num_rounds=25)
    s1, q1 = run1(state, qs, jax.random.key(2), num_rounds=25)
    assert bool(jnp.all(s1.gossip.known == s8.gossip.known))
    assert bool(jnp.all(s1.gossip.alive == s8.gossip.alive))
    assert bool(jnp.all(q1.responded == q8.responded))
    assert bool(jnp.all(q1.resp_value == q8.resp_value))


def test_round_robin_probe_schedule_detects_deterministically():
    """Round-robin probing (memberlist's shuffled probe-list analog): every
    node is probed exactly once per round, so a death is under suspicion
    within the first round and the detection deadline is deterministic."""
    cfg = GossipConfig(n=512, k_facts=64)
    fcfg = FailureConfig(suspicion_rounds=8, max_new_facts=4,
                         probe_schedule="round_robin")
    s = make_state(cfg)._replace(
        alive=jnp.ones((512,), bool).at[99].set(False))
    # exactly one suspicion fact after a single probe round, every time
    out = probe_round(s, cfg, fcfg, jax.random.key(0))
    assert int(out.next_slot) == 1
    assert int(out.facts.subject[0]) == 99

    # full detection inside the deterministic budget
    step = jax.jit(functools.partial(swim_round, cfg=cfg, fcfg=fcfg))
    key = jax.random.key(1)
    budget = 1 + fcfg.suspicion_rounds + 1 + 30  # probe+age+declare+gossip
    for _ in range(budget):
        key, k2 = jax.random.split(key)
        s = step(s, key=k2)
    assert bool(detection_complete(s, cfg, fcfg))


def test_round_robin_offsets_cover_all_peers():
    """The rotation offsets visit (nearly) all distances over n rounds —
    no node pair goes unprobed indefinitely."""
    n = 64
    offsets = {int(rotation_offset(r, n)) for r in range(n * 4)}
    assert min(offsets) >= 1 and max(offsets) <= n - 1
    assert len(offsets) >= (n - 1) * 3 // 4  # wide coverage of distances


def test_probe_schedule_validation():
    with pytest.raises(ValueError):
        FailureConfig(probe_schedule="nope")


def test_checkpoint_resume_mid_query_bit_exact():
    """Checkpoint the composed (cluster, queries) state mid-gather and
    resume: the continuation must be bit-identical to the unbroken run."""
    import tempfile

    from serf_tpu.models import checkpoint
    from serf_tpu.models.query import (QueryConfig, launch_query,
                                       make_queries, no_filter_mask,
                                       query_round)

    cfg = ClusterConfig(gossip=GossipConfig(n=256, k_facts=32),
                        push_pull_every=8)
    qcfg = QueryConfig(q_slots=2, relay_factor=1)
    state = make_cluster(cfg, jax.random.key(0))
    g, qs, qi = launch_query(state.gossip, make_queries(cfg.gossip, qcfg),
                             cfg.gossip, qcfg, origin=0,
                             eligible=no_filter_mask(cfg.n))
    state = state._replace(gossip=g)

    # one jitted composed step, shared by the unbroken and resumed runs
    # (eager per-round dispatch made this the slowest test in the suite
    # for no extra coverage; the SAME compiled step on both sides is the
    # stronger bit-exactness statement anyway)
    @jax.jit
    def step(st, qs, k1, k2):
        st = cluster_round(st, cfg, k1)
        return st, query_round(st.gossip, qs, cfg.gossip, qcfg, k2)

    def advance(st, qs, key, rounds):
        for _ in range(rounds):
            key, k1, k2 = jax.random.split(key, 3)
            st, qs = step(st, qs, k1, k2)
        return st, qs

    # run 5 rounds, checkpoint mid-query, run 5 more
    st_a, qs_a = advance(state, qs, jax.random.key(7), 5)
    with tempfile.TemporaryDirectory() as d:
        checkpoint.save(f"{d}/mid.npz", (st_a, qs_a))
        st_a, qs_a = advance(st_a, qs_a, jax.random.key(8), 5)

        # restore and continue with the same keys
        st_b, qs_b = checkpoint.restore(
            f"{d}/mid.npz", (make_cluster(cfg, jax.random.key(0)),
                             make_queries(cfg.gossip, qcfg)))
    st_b, qs_b = advance(st_b, qs_b, jax.random.key(8), 5)

    assert bool(jnp.all(st_a.gossip.known == st_b.gossip.known))
    assert bool(jnp.all(qs_a.responded == qs_b.responded))
    assert bool(jnp.all(qs_a.resp_value == qs_b.resp_value))
    assert int(qs_a.next_q) == int(qs_b.next_q)


@pytest.mark.slow  # scale variant; vivaldi co-training is tier-1 at 512
def test_vivaldi_cotrained_with_gossip_at_100k():
    """Baseline config #5 accuracy at scale: Vivaldi co-trained inside the
    full flagship round (gossip + failure detection + anti-entropy sharing
    the peer samples) at 100k nodes must substantially reduce the RTT
    estimation error.  (Throughput at 1M is the TPU bench's job; this pins
    the accuracy claim beyond n=256 — round-1 verdict, weak #5.)"""
    n = 100_000
    cfg = ClusterConfig(gossip=GossipConfig(n=n, k_facts=64),
                        push_pull_every=16)
    state = make_cluster(cfg, jax.random.key(0))
    err0 = float(mean_relative_error(state.vivaldi, cfg.vivaldi,
                                     state.positions, jax.random.key(1)))
    run = jax.jit(functools.partial(run_cluster, cfg=cfg),
                  static_argnames=("num_rounds",))
    state = run(state, key=jax.random.key(2), num_rounds=200)
    err1 = float(mean_relative_error(state.vivaldi, cfg.vivaldi,
                                     state.positions, jax.random.key(3)))
    assert err1 < err0 * 0.5, f"error did not halve at 100k: {err0} -> {err1}"


# -- bounded selection (pick_bounded) ----------------------------------------

def test_pick_bounded_flat_small_n():
    from serf_tpu.models.dissemination import pick_bounded

    n = 512
    cand = jnp.zeros((n,), bool).at[jnp.asarray([7, 100, 511])].set(True)
    chosen, subjects, active = pick_bounded(cand, 8, jax.random.key(0))
    assert int(active.sum()) == 3
    assert sorted(int(s) for s, a in zip(subjects, active) if a) == [7, 100, 511]
    # prefix-active contract (inject_facts_batch requirement)
    na = int(active.sum())
    assert bool(jnp.all(active[:na])) and not bool(jnp.any(active[na:]))


def test_pick_bounded_grouped_large_n_exact_when_sparse():
    """The two-level strided path (n > _PICK_FLAT_MAX) finds candidates that
    all live in distinct strided groups — including a contiguous id run,
    which by construction spreads across groups."""
    from serf_tpu.models.dissemination import _PICK_FLAT_MAX, pick_bounded

    n = _PICK_FLAT_MAX + 1337          # forces the grouped path
    ids = [0, 1, 2, 3, n - 1]          # contiguous run + the last (padded row)
    cand = jnp.zeros((n,), bool).at[jnp.asarray(ids)].set(True)
    chosen, subjects, active = pick_bounded(cand, 8, jax.random.key(1))
    assert int(active.sum()) == len(ids)
    assert sorted(int(s) for s, a in zip(subjects, active) if a) == ids
    na = int(active.sum())
    assert bool(jnp.all(active[:na])) and not bool(jnp.any(active[na:]))
    assert int(chosen.sum()) == len(ids)
    assert all(bool(chosen[i]) for i in ids)


def test_pick_bounded_grouped_bounded_and_valid_under_collisions():
    """Candidates colliding modulo the group count can defer extras to later
    rounds (documented bias) but picks stay valid, distinct, and bounded."""
    from serf_tpu.models.dissemination import (
        _PICK_FLAT_MAX,
        _PICK_GROUPS,
        pick_bounded,
    )

    n = _PICK_FLAT_MAX * 2
    g = _PICK_GROUPS
    # 6 candidates in ONE strided group, 2 in another
    ids = [5, 5 + g, 5 + 2 * g, 5 + 3 * g, 5 + 4 * g, 5 + 5 * g, 9, 9 + g]
    cand = jnp.zeros((n,), bool).at[jnp.asarray(ids)].set(True)
    chosen, subjects, active = pick_bounded(cand, 4, jax.random.key(2))
    picked = [int(s) for s, a in zip(subjects, active) if a]
    assert 2 <= len(picked) <= 4          # ≥ one per distinct group, ≤ bound
    assert len(set(picked)) == len(picked)
    assert all(p in ids for p in picked)
    # group-5's winner and group-9's winner must both be present
    assert any(p % g == 5 for p in picked)
    assert any(p % g == 9 for p in picked)


def test_pick_bounded_grouped_none_and_all():
    from serf_tpu.models.dissemination import _PICK_FLAT_MAX, pick_bounded

    n = _PICK_FLAT_MAX + 1
    none = jnp.zeros((n,), bool)
    chosen, subjects, active = pick_bounded(none, 8, jax.random.key(3))
    assert not bool(jnp.any(active)) and not bool(jnp.any(chosen))
    every = jnp.ones((n,), bool)
    chosen, subjects, active = pick_bounded(every, 8, jax.random.key(4))
    assert int(active.sum()) == 8
    assert len({int(s) for s in subjects}) == 8


# ---------------------------------------------------------------------------
# stamp-plane wraparound (the 4-bit quarter-round representation)
# ---------------------------------------------------------------------------

def test_stamp_wrap_never_resends_old_facts():
    """The mod-16 quarter stamp wraps every 64 rounds; without the
    clamp (riding the learn passes, standalone via last_clamp when
    quiet), a fully disseminated fact's derived age would wrap back
    under transmit_limit and the whole cluster would re-send it.  The
    clamp must keep budgets at zero forever."""
    from serf_tpu.models.dissemination import budgets_of

    cfg = GossipConfig(n=64, k_facts=32)
    s = inject_fact(make_state(cfg), cfg, 0, K_USER_EVENT, 0, 1, 0)
    run = jax.jit(functools.partial(run_rounds, cfg=cfg),
                  static_argnames=("num_rounds",))
    s = run(s, key=jax.random.key(0), num_rounds=40)
    assert float(coverage(s, cfg)[0]) == 1.0
    assert int(jnp.sum(budgets_of(s, cfg))) == 0
    # cross the wrap (and several clamp periods): budgets must stay zero
    for stop in (230, 258, 266, 300, 520):
        extra = stop - int(s.round)
        s = run(s, key=jax.random.key(stop), num_rounds=extra)
        assert int(jnp.sum(budgets_of(s, cfg))) == 0, f"resend at {stop}"


def test_stamp_wrap_age_of_view():
    """age_of: derived ages track quarters-since-learn, 255 where
    unknown, and stay pinned (>= thresholds) across the wrap."""
    from serf_tpu.models.dissemination import (
        AGE_PIN_Q,
        CLAMP_EVERY,
        STAMP_UNIT,
        age_of,
    )

    cfg = GossipConfig(n=64, k_facts=32)
    s = inject_fact(make_state(cfg), cfg, 5, K_USER_EVENT, 0, 1, origin=5)
    ages = age_of(s, cfg)
    assert int(ages[5, 0]) == 0          # origin learned now
    assert int(ages[6, 0]) == 255        # everyone else unknown
    run = jax.jit(functools.partial(run_rounds, cfg=cfg),
                  static_argnames=("num_rounds",))
    s2 = run(s, key=jax.random.key(1), num_rounds=7)
    assert int(age_of(s2, cfg)[5, 0]) == 7 // STAMP_UNIT
    # far past the wrap the origin's age reads pinned-high, never young
    s3 = run(s2, key=jax.random.key(2), num_rounds=600)
    a = int(age_of(s3, cfg)[5, 0])
    assert AGE_PIN_Q <= a <= AGE_PIN_Q + CLAMP_EVERY // STAMP_UNIT
    assert a >= cfg.transmit_limit_q


def test_pick_bounded_adversarial_drain():
    """VERDICT r3 #10: adversarial candidate sets must still drain near the
    ideal ⌈|C|/max_events⌉ rate.  The per-round layout alternation
    (strided groups vs contiguous blocks, keyed off the PRNG) guarantees
    no FIXED set is degenerate every round: a set colliding mod G is
    spaced ≥ G apart so contiguous blocks split it perfectly, and a
    contiguous run spreads across strided groups.  Expected drain ≈ 2x
    ideal (the degenerate layout contributes ~1 pick/round, the good one
    up to max_events)."""
    from serf_tpu.models.dissemination import (
        _PICK_FLAT_MAX,
        _PICK_GROUPS,
        pick_bounded,
    )

    n = _PICK_FLAT_MAX * 2            # grouped path; rows = n/G = 32
    g = _PICK_GROUPS
    max_events = 8

    def drain(ids, key, cap):
        cand = jnp.zeros((n,), bool).at[jnp.asarray(ids)].set(True)
        pick = jax.jit(functools.partial(pick_bounded, max_events=max_events))
        rounds = 0
        while bool(cand.any()):
            rounds += 1
            assert rounds <= cap, \
                f"{len(ids)} candidates not drained in {cap} rounds"
            key, k = jax.random.split(key)
            chosen, subjects, active = pick(cand, key=k)
            picked = int(active.sum())
            assert picked >= 1, "a non-empty candidate set yielded no pick"
            assert picked <= max_events
            # picks are real candidates and distinct
            assert bool(jnp.all(cand[subjects[:picked]]))
            cand = cand & ~chosen
        return rounds

    rows = n // g                     # 32: a strided group holds `rows` ids
    c = rows                          # the LARGEST possible one-group set
    ideal = -(-c // max_events)       # 4
    cap = 3 * ideal + 8               # 20: ~2x ideal + coin-flip variance
    # all candidates ≡ 5 (mod G): ONE strided group, but `rows` distinct
    # contiguous blocks — the block-layout rounds drain it at full rate
    strided_degenerate = [5 + k * g for k in range(c)]
    r1 = drain(strided_degenerate, jax.random.key(11), cap)
    # contiguous run 0..31: ONE contiguous block, but spreads over `rows`
    # distinct strided groups
    block_degenerate = list(range(c))
    r2 = drain(block_degenerate, jax.random.key(12), cap)
    # neither can beat the ideal rate; both stay within the alternation bound
    assert r1 >= ideal and r2 >= ideal, (r1, r2)


def test_vivaldi_latency_filter_rejects_spikes():
    """The optional per-node median latency filter (VivaldiConfig.
    latency_filter_size=3, the reference's per-peer filter re-shaped to
    O(N) state): under heavy-tailed RTT noise (10% of samples spiked
    10x — the TCP-retransmit outliers the reference filter exists for),
    filtered estimation must beat unfiltered.  Both runs see the SAME
    noisy sample stream."""
    n = 512
    key = jax.random.key(0)
    positions = jax.random.uniform(key, (n, 3), jnp.float32) * 0.05

    def run(fsize, rounds=200):
        vcfg = VivaldiConfig(latency_filter_size=fsize)
        dev = make_vivaldi(n, vcfg)
        step = jax.jit(functools.partial(vivaldi_update, cfg=vcfg))
        k = jax.random.key(7)
        for _ in range(rounds):
            k, k1, k2, k3 = jax.random.split(k, 4)
            peers = jax.random.randint(k1, (n,), 0, n)
            rtt = ground_truth_rtt(positions, jnp.arange(n), peers)
            spike = jax.random.bernoulli(k3, 0.10, (n,))
            rtt = jnp.where(spike, rtt * 10.0, rtt)
            dev = step(dev, peer=peers, rtt=rtt, key=k2)
        return float(mean_relative_error(dev, vcfg, positions,
                                         jax.random.key(9)))

    err_raw = run(1)
    err_filtered = run(3)
    assert err_filtered < err_raw, \
        (f"median filter did not help under spike noise: "
         f"filtered {err_filtered:.3f} vs raw {err_raw:.3f}")


def test_failure_gates_requiesce_after_detection():
    """The refute/declare skip-gates must switch OFF again once the
    detection cycle completes — retired-but-valid ring facts (declared
    deaths, refuted suspicions) may NOT keep the N×K phases hot, or the
    steady-state round (what the bench's timed scans measure) pays the
    active-round cost forever."""
    from serf_tpu.models.failure import (accusations_pending,
                                         live_suspicions)

    cfg = GossipConfig(n=512, k_facts=64)
    fcfg = FailureConfig(suspicion_rounds=8, max_new_facts=4,
                         probe_drop_rate=0.05)
    s = make_state(cfg)
    dead = jnp.array([3, 77, 200])
    s = s._replace(alive=s.alive.at[dead].set(False))
    run = jax.jit(functools.partial(run_swim, cfg=cfg, fcfg=fcfg),
                  static_argnames=("num_rounds",))
    s = run(s, key=jax.random.key(5), num_rounds=120)
    assert bool(detection_complete(s, cfg, fcfg))
    # the ring still holds the history (valid suspect/dead facts) ...
    assert int(jnp.sum((s.facts.kind == K_DEAD) & s.facts.valid)) >= 3
    assert int(jnp.sum((s.facts.kind == K_SUSPECT) & s.facts.valid)) >= 3
    # ... but nothing can still act: both gates read quiescent
    assert not bool(jnp.any(accusations_pending(s))), \
        "refute gate stayed hot after detection completed"
    assert not bool(jnp.any(live_suspicions(s))), \
        "declare gate stayed hot after detection completed"


def test_quiet_round_gate_fixed_point_and_reopen():
    """The round_step quiet gate (last_learn): once nothing has been
    learned for transmit_limit rounds, the gossip exchange is a bit-exact
    identity (known/stamp are a fixed point); a NEW injection re-opens
    the gate and the fresh fact still fully disseminates."""
    cfg = GossipConfig(n=256, k_facts=32)
    s = inject_fact(make_state(cfg), cfg, 0, K_USER_EVENT, 0, 1, 0)
    run = jax.jit(functools.partial(run_rounds, cfg=cfg),
                  static_argnames=("num_rounds",))
    # converge + exhaust every budget, then some quiet rounds
    s = run(s, key=jax.random.key(1), num_rounds=120)
    assert float(coverage(s, cfg)[0]) == 1.0
    assert int(s.round) - int(s.last_learn) >= cfg.transmit_limit, \
        "cluster did not go quiet"
    # fixed point: further rounds change NOTHING but the round counter
    s2 = run(s, key=jax.random.key(2), num_rounds=40)
    assert bool(jnp.all(s2.known == s.known))
    assert int(s2.last_learn) == int(s.last_learn)
    # stamps may only change via the clamp re-pin; derived q-ages must
    # still read >= the transmit window for every known fact
    from serf_tpu.models.dissemination import age_of
    ages = age_of(s2, cfg)
    known = unpack_bits(s2.known, cfg.k_facts)
    assert int(jnp.min(jnp.where(known, ages, jnp.uint8(255)))) \
        >= cfg.transmit_limit_q
    # re-open: a new fact injected into the quiet cluster disseminates
    s3 = inject_fact(s2, cfg, 9, K_USER_EVENT, 0, 2, origin=9)
    assert int(s3.last_learn) == int(s3.round)
    s3 = run(s3, key=jax.random.key(3), num_rounds=40)
    assert float(coverage(s3, cfg)[1]) == 1.0, \
        "fresh fact did not disseminate after the quiet gate re-opened"


def test_probe_cadence_detects_and_converges():
    """probe_every=5 (the reference LAN profile's gossip:probe cadence
    mapping): detection still completes — suspicion windows are measured
    in gossip rounds, probes just fire less often — and vivaldi still
    converges on the sparser ack stream."""
    from serf_tpu.models.vivaldi import mean_relative_error

    cfg = ClusterConfig(gossip=GossipConfig(n=512, k_facts=64),
                        failure=FailureConfig(suspicion_rounds=8,
                                              max_new_facts=8),
                        probe_every=5, push_pull_every=16)
    state = make_cluster(cfg, jax.random.key(0))
    g = state.gossip
    dead = jnp.array([3, 200, 400])
    g = g._replace(alive=g.alive.at[dead].set(False))
    state = state._replace(gossip=g)
    run = jax.jit(functools.partial(run_cluster, cfg=cfg),
                  static_argnames=("num_rounds",))
    e0 = float(mean_relative_error(state.vivaldi, cfg.vivaldi,
                                   state.positions, jax.random.key(5)))
    state = run(state, key=jax.random.key(1), num_rounds=250)
    assert bool(detection_complete(state.gossip, cfg.gossip, cfg.failure))
    bd = believed_dead(state.gossip, cfg.gossip, cfg.failure)
    assert int(jnp.sum(bd & state.gossip.alive)) == 0
    e1 = float(mean_relative_error(state.vivaldi, cfg.vivaldi,
                                   state.positions, jax.random.key(6)))
    assert e1 < e0 * 0.7, (e0, e1)
