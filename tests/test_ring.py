"""Ring-pipelined gossip exchange (parallel/ring.py): bit-parity with the
all-gather round on the virtual 8-device mesh, partition masking, and
convergence."""

import functools

import jax
import jax.numpy as jnp
import pytest

from serf_tpu.models.antientropy import make_partition
from serf_tpu.models.dissemination import (
    GossipConfig,
    K_USER_EVENT,
    coverage,
    inject_fact,
    make_state,
    round_step,
    unpack_bits,
)
from serf_tpu.parallel.mesh import make_mesh, shard_state, state_shardings
from serf_tpu.parallel.ring import round_step_ring


def _seeded(cfg, n_facts=4):
    s = make_state(cfg)
    for i in range(n_facts):
        s = inject_fact(s, cfg, subject=(i * 97) % cfg.n, kind=K_USER_EVENT,
                        incarnation=0, ltime=i + 1,
                        origin=(i * 193) % cfg.n)
    return s


def test_ring_round_bit_identical_to_all_gather():
    cfg = GossipConfig(n=512, k_facts=32, fanout=3)
    mesh = make_mesh(8)
    base = _seeded(cfg)
    ring = jax.jit(functools.partial(round_step_ring, cfg=cfg, mesh=mesh))
    ref = jax.jit(functools.partial(round_step, cfg=cfg))
    a, b = shard_state(base, mesh), base
    key = jax.random.key(0)
    for _ in range(15):
        key, k2 = jax.random.split(key)
        a = ring(a, key=k2)
        b = ref(b, key=k2)
    for name in ("known", "stamp", "round"):
        assert bool(jnp.all(getattr(a, name) == getattr(b, name))), name


def test_ring_round_respects_partition():
    cfg = GossipConfig(n=256, k_facts=32, fanout=3)
    mesh = make_mesh(8)
    group = make_partition(cfg.n, 0.5)
    s = make_state(cfg)
    s = inject_fact(s, cfg, 0, K_USER_EVENT, 0, 1, 0)             # side 0
    s = inject_fact(s, cfg, 1, K_USER_EVENT, 0, 2, cfg.n - 1)     # side 1
    ring = jax.jit(functools.partial(round_step_ring, cfg=cfg, mesh=mesh))
    ref = jax.jit(functools.partial(round_step, cfg=cfg))
    a, b = shard_state(s, mesh), s
    key = jax.random.key(1)
    for _ in range(30):
        key, k2 = jax.random.split(key)
        a = ring(a, key=k2, group=group)
        b = ref(b, key=k2, group=group)
    assert bool(jnp.all(a.known == b.known))
    known = unpack_bits(a.known, cfg.k_facts)
    half = cfg.n // 2
    assert bool(jnp.all(known[:half, 0])) and not bool(jnp.any(known[half:, 0]))
    assert bool(jnp.all(known[half:, 1])) and not bool(jnp.any(known[:half, 1]))


def test_ring_round_converges_standalone():
    cfg = GossipConfig(n=1024, k_facts=32, fanout=3)
    mesh = make_mesh(8)
    s = shard_state(inject_fact(make_state(cfg), cfg, 0, K_USER_EVENT,
                                0, 1, 0), mesh)
    ring = jax.jit(functools.partial(round_step_ring, cfg=cfg, mesh=mesh))
    key = jax.random.key(2)
    for _ in range(30):
        key, k2 = jax.random.split(key)
        s = ring(s, key=k2)
    assert float(coverage(s, cfg)[0]) == 1.0


def test_ring_round_rejects_indivisible_n():
    cfg = GossipConfig(n=100, k_facts=32)
    mesh = make_mesh(8)
    with pytest.raises(ValueError):
        round_step_ring(make_state(cfg), cfg, jax.random.key(0), mesh)
