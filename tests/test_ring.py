"""The flagship sharded exchange (parallel/ring.py): bit-parity with the
unsharded round on the virtual 8-device mesh for BOTH explicit ICI
schedules, partition masking, loss masking, convergence, and the
N-not-divisible-by-P fallback.  (Rotation sampling — the production
flagship — is covered at cluster level in tests/test_sharded_round.py;
this file pins the iid mode, where the exchange is a data-dependent
gather.)"""

import functools

import jax
import jax.numpy as jnp
import pytest

from serf_tpu.models.antientropy import make_partition
from serf_tpu.models.dissemination import (
    GossipConfig,
    K_USER_EVENT,
    coverage,
    inject_fact,
    make_state,
    round_step,
    unpack_bits,
)
from serf_tpu.parallel.mesh import shard_state
from serf_tpu.parallel.ring import sharded_round_step


def _seeded(cfg, n_facts=4):
    s = make_state(cfg)
    for i in range(n_facts):
        s = inject_fact(s, cfg, subject=(i * 97) % cfg.n, kind=K_USER_EVENT,
                        incarnation=0, ltime=i + 1,
                        origin=(i * 193) % cfg.n)
    return s


def _mixed_round_kwargs(i, group):
    """The per-round mask mix every parity variant drives: plain,
    partitioned, lossy — cycling so one trajectory covers all three."""
    if i % 3 == 1:
        return dict(group=group)
    if i % 3 == 2:
        return dict(drop_rate=jnp.float32(0.25))
    return {}


def _parity_cfg(sampling):
    return GossipConfig(n=512, k_facts=32, fanout=3,
                        peer_sampling=sampling)


def _ref_trajectory(sampling):
    """Unsharded reference, ONE compile per sampling mode (memoized —
    both schedules and the P=1 variant compare against it)."""
    cache = _ref_trajectory.__dict__.setdefault("cache", {})
    if sampling not in cache:
        cfg = _parity_cfg(sampling)
        ref = jax.jit(functools.partial(round_step, cfg=cfg))
        b = _seeded(cfg)
        group = make_partition(cfg.n, 0.5)
        key = jax.random.key(0)
        for i in range(12):
            key, k2 = jax.random.split(key)
            b = ref(b, key=k2, **_mixed_round_kwargs(i, group))
        cache[sampling] = b
    return cache[sampling]


@pytest.mark.parametrize("sampling,schedule,n_devices", [
    ("iid", "ring", 8),
    ("iid", "allgather", 8),
    ("rotation", "ring", 8),
    ("rotation", "allgather", 8),
    ("rotation", "ring", 1),          # P=1: degenerate shard, no collective
])
def test_sharded_round_bit_identical(vmesh8, sampling, schedule,
                                     n_devices):
    """Every (sampling mode × explicit schedule) leg produces the same
    state as the unsharded round — same RNG stream, same merge —
    including under partition and loss masks (mixed in across the
    rounds) and on the degenerate 1-device mesh."""
    from serf_tpu.parallel.mesh import make_mesh

    mesh = vmesh8 if n_devices == 8 else make_mesh(1)
    cfg = _parity_cfg(sampling)
    group = make_partition(cfg.n, 0.5)
    sh = jax.jit(functools.partial(sharded_round_step, cfg=cfg,
                                   mesh=mesh, schedule=schedule))
    a = shard_state(_seeded(cfg), mesh)
    key = jax.random.key(0)
    for i in range(12):
        key, k2 = jax.random.split(key)
        a = sh(a, key=k2, **_mixed_round_kwargs(i, group))
    b = _ref_trajectory(sampling)
    for name in ("known", "stamp", "round", "sendable", "sendable_round",
                 "last_learn", "last_clamp"):
        assert bool(jnp.all(getattr(a, name) == getattr(b, name))), name


def test_sharded_round_respects_partition(vmesh8):
    cfg = GossipConfig(n=256, k_facts=32, fanout=3, peer_sampling="iid")
    group = make_partition(cfg.n, 0.5)
    s = make_state(cfg)
    s = inject_fact(s, cfg, 0, K_USER_EVENT, 0, 1, 0)             # side 0
    s = inject_fact(s, cfg, 1, K_USER_EVENT, 0, 2, cfg.n - 1)     # side 1
    sh = jax.jit(functools.partial(sharded_round_step, cfg=cfg,
                                   mesh=vmesh8, schedule="ring"))
    a = shard_state(s, vmesh8)
    key = jax.random.key(1)
    for _ in range(30):
        key, k2 = jax.random.split(key)
        a = sh(a, key=k2, group=group)
    known = unpack_bits(a.known, cfg.k_facts)
    half = cfg.n // 2
    assert bool(jnp.all(known[:half, 0])) and not bool(jnp.any(known[half:, 0]))
    assert bool(jnp.all(known[half:, 1])) and not bool(jnp.any(known[:half, 1]))


def test_sharded_round_converges_standalone(vmesh8):
    cfg = GossipConfig(n=1024, k_facts=32, fanout=3, peer_sampling="iid")
    s = shard_state(inject_fact(make_state(cfg), cfg, 0, K_USER_EVENT,
                                0, 1, 0), vmesh8)
    sh = jax.jit(functools.partial(sharded_round_step, cfg=cfg,
                                   mesh=vmesh8, schedule="ring"))
    key = jax.random.key(2)
    for _ in range(30):
        key, k2 = jax.random.split(key)
        s = sh(s, key=k2)
    assert float(coverage(s, cfg)[0]) == 1.0


def test_indivisible_n_falls_back_bit_exact(vmesh8):
    """n % P != 0 must not crash OR change results: the exchange falls
    back to the GSPMD-lowered unsharded leg (recorded as a
    ``shard-fallback`` flight event) and stays bit-identical."""
    from serf_tpu import obs

    cfg = GossipConfig(n=100, k_facts=32, fanout=3, peer_sampling="iid")
    base = _seeded(cfg)
    sh = jax.jit(functools.partial(sharded_round_step, cfg=cfg,
                                   mesh=vmesh8, schedule="ring"))
    ref = jax.jit(functools.partial(round_step, cfg=cfg))
    a, b = base, base
    key = jax.random.key(3)
    for _ in range(8):
        key, k2 = jax.random.split(key)
        a, b = sh(a, key=k2), ref(b, key=k2)
    assert bool(jnp.all(a.known == b.known))
    assert bool(jnp.all(a.stamp == b.stamp))
    assert obs.flight_dump(kind="shard-fallback"), \
        "fallback must be recorded, not silent"


def test_unknown_schedule_rejected(vmesh8):
    cfg = GossipConfig(n=256, k_facts=32)
    with pytest.raises(ValueError, match="schedule"):
        sharded_round_step(make_state(cfg), cfg, jax.random.key(0),
                           vmesh8, schedule="butterfly")
