"""Poisson churn at scale: baseline config #3 (100k nodes) plus unit
semantics for the churn process itself.

The scale test drives 100_000 nodes through the full flagship round
(`cluster_round`: gossip + failure detection + anti-entropy + Vivaldi)
under a Poisson leave/fail/rejoin process with packet loss, then asserts
the reference failure-detector contract: every down node is detected
within the suspicion-window bound, and **no node that stayed up is ever
believed dead** (no false deaths) at realistic drop rates.
"""

import functools

import jax
import jax.numpy as jnp
import pytest

from serf_tpu.models.churn import ChurnConfig, churn_round, run_cluster_churn
from serf_tpu.models.dissemination import (
    GossipConfig,
    K_ALIVE,
    K_LEAVE,
    make_state,
)
from serf_tpu.models.failure import FailureConfig, believed_dead, detection_complete
from serf_tpu.models.swim import ClusterConfig, make_cluster, run_cluster


def test_churn_round_semantics_small():
    cfg = GossipConfig(n=64, k_facts=32)
    ccfg = ChurnConfig(fail_rate=0.2, leave_rate=0.2, rejoin_rate=0.5,
                       max_events=4)
    state = make_state(cfg)._replace(
        alive=jnp.ones((64,), bool).at[0:8].set(False))
    out, pending = churn_round(state, cfg, ccfg, jax.random.key(0))

    # caps respected: ≤4 immediate fails among previously-alive, ≤4 pending
    # leavers (still alive until after their announcement round), ≤4 rejoins
    newly_down = state.alive & ~out.alive
    newly_up = ~state.alive & out.alive
    assert int(jnp.sum(newly_down)) <= 4
    assert int(jnp.sum(pending)) <= 4
    assert int(jnp.sum(newly_up)) <= 4
    # leavers are still alive (they announce before going dark) and are
    # disjoint from the crashed
    assert not bool(jnp.any(pending & ~out.alive))
    # rejoiners bumped their incarnation
    assert bool(jnp.all(jnp.where(newly_up, out.incarnation == 2, True)))
    # leave facts announced exactly for the pending leavers
    leave_subjects = set(
        int(s) for s, k, v in zip(out.facts.subject, out.facts.kind,
                                  out.facts.valid)
        if bool(v) and int(k) == K_LEAVE)
    assert leave_subjects == set(int(i) for i in jnp.nonzero(pending)[0])
    # alive facts announced for every rejoiner
    alive_subjects = set(
        int(s) for s, k, v in zip(out.facts.subject, out.facts.kind,
                                  out.facts.valid)
        if bool(v) and int(k) == K_ALIVE)
    up_ids = set(int(i) for i in jnp.nonzero(newly_up)[0])
    assert alive_subjects == up_ids


def test_churn_rates_zero_is_identity():
    cfg = GossipConfig(n=32, k_facts=32)
    state = make_state(cfg)
    out, pending = churn_round(state, cfg, ChurnConfig(), jax.random.key(1))
    assert bool(jnp.all(out.alive == state.alive))
    assert bool(jnp.all(out.known == state.known))
    assert int(out.next_slot) == int(state.next_slot)
    assert int(jnp.sum(pending)) == 0


def test_leave_announcement_disseminates_before_leaver_goes_dark():
    """A graceful leaver's K_LEAVE fact must actually spread: run churn with
    only leaves and verify the announcement reaches the cluster even though
    the leaver goes dark after its linger window (the device analog of the
    reference's leave broadcast drain) expires."""
    from serf_tpu.models.dissemination import coverage
    from serf_tpu.models.swim import ClusterConfig, make_cluster

    cfg = ClusterConfig(gossip=GossipConfig(n=256, k_facts=32, fanout=3),
                        with_failure=False, with_vivaldi=False)
    ccfg = ChurnConfig(leave_rate=0.01, max_events=2)
    state = make_cluster(cfg, jax.random.key(0))
    state, trace = run_cluster_churn(state, cfg, ccfg, jax.random.key(1), 8)
    downs = int(jnp.sum(trace.ever_down))
    assert downs > 0, "no leaves sampled; pick a different seed"
    # let the announcements disseminate among survivors
    state = run_cluster(state, cfg, jax.random.key(2), 30)
    g = state.gossip
    leave_slots = jnp.nonzero((g.facts.kind == K_LEAVE) & g.facts.valid)[0]
    assert len(leave_slots) > 0
    cov = coverage(g, cfg.gossip)
    for sl in leave_slots:
        assert float(cov[int(sl)]) == 1.0, \
            f"leave fact in slot {int(sl)} did not disseminate"


@pytest.mark.slow  # scale variant; churn semantics are tier-1 at small n
def test_poisson_churn_100k_detection_and_no_false_deaths():
    """Baseline config #3 at its stated scale (run once per session: ~1 min
    CPU).  30 churned rounds then a settle window; the detector must catch
    every down node and never kill a node that stayed up."""
    n = 100_000
    cfg = ClusterConfig(
        # k_facts=256: the fact ring must hold a live suspect/dead fact for
        # every churned subject simultaneously — the reference sizes its
        # dedup buffers at event_buffer_size=512 for the same reason
        gossip=GossipConfig(n=n, k_facts=256, fanout=3),
        failure=FailureConfig(suspicion_rounds=12, max_new_facts=8,
                              probe_drop_rate=0.02),
        push_pull_every=16,
        with_vivaldi=False,   # vivaldi has its own scale test; keep this lean
    )
    ccfg = ChurnConfig(fail_rate=1e-5, leave_rate=1e-5, rejoin_rate=0.02,
                       max_events=8)
    key = jax.random.key(42)
    state = make_cluster(cfg, key)

    churn = jax.jit(functools.partial(run_cluster_churn, cfg=cfg, ccfg=ccfg,
                                      num_rounds=30),
                    static_argnames=())
    state, trace = run_cluster_churn(state, cfg, ccfg,
                                     jax.random.key(7), 30)
    # Poisson process actually fired (expected ~2/round/kind at these rates)
    downs = int(jnp.sum(trace.ever_down))
    assert downs > 10, f"churn too quiet: {downs} down events"

    # settle: no churn; bounded-suspicion coverage sweeps + suspicion window
    # + declaration sweeps + full-dissemination slack
    settle = cfg.failure.suspicion_rounds * 2 + 80
    state = run_cluster(state, cfg, jax.random.key(8), settle)

    assert bool(detection_complete(state.gossip, cfg.gossip, cfg.failure)), \
        "down nodes not fully detected within the settle window"
    believed = believed_dead(state.gossip, cfg.gossip, cfg.failure)
    false_deaths = believed & trace.always_up
    assert int(jnp.sum(false_deaths)) == 0, \
        f"{int(jnp.sum(false_deaths))} false deaths among always-up nodes"


def test_leave_linger_countdown_semantics():
    """linger_step: a leaver stays up exactly leave_linger_rounds rounds
    after announcing, re-announcing re-arms, and idle nodes never fire."""
    from serf_tpu.models.churn import linger_init, linger_step

    n = 4
    cd = linger_init(n)
    none = jnp.zeros((n,), bool)
    leaver = none.at[1].set(True)

    cd, down = linger_step(cd, leaver, 3)      # announce: cd 3 -> 2
    assert not bool(down.any())
    cd, down = linger_step(cd, none, 3)        # 2 -> 1
    assert not bool(down.any())
    cd, down = linger_step(cd, leaver, 3)      # re-announce re-arms: 3 -> 2
    assert not bool(down.any())
    cd, down = linger_step(cd, none, 3)        # 2 -> 1
    cd, down = linger_step(cd, none, 3)        # 1 -> 0: goes down NOW
    assert bool(down[1]) and int(down.sum()) == 1
    cd, down = linger_step(cd, none, 3)        # stays down, no re-fire
    assert not bool(down.any())

    # a node that DIES mid-linger has its countdown cleared: a later
    # rejoin must not be forced straight back down by the stale timer
    cd = linger_init(n)
    cd, down = linger_step(cd, leaver, 3)                  # announce
    alive = jnp.ones((n,), bool).at[1].set(False)          # crashes now
    cd, down = linger_step(cd, none, 3, alive=alive)       # cleared
    assert not bool(down.any()) and int(cd[1]) == 0
    alive = alive.at[1].set(True)                          # rejoins
    for _ in range(4):
        cd, down = linger_step(cd, none, 3, alive=alive)
        assert not bool(down.any()), "stale linger killed a rejoiner"

    # linger_rounds values past the u8 range clamp instead of wrapping
    cd = linger_init(n)
    cd, down = linger_step(cd, leaver, 256)
    assert int(cd[1]) == 254                               # armed at 255
