"""ISSUE 7 acceptance: the fused pallas round family
(``ops.fused_select_cached`` / ``ops.fused_merge``) is BIT-EXACT with
the phased XLA reference on EVERY GossipState leaf — sendable cache,
stamp clamp timing, tombstone, coverage, and believed_dead included —
for both stamp flavors, single-device and sharded (vmesh8, where the
kernels run under shard_map per chip).  Plus the loud VMEM/shape
fallback contract (flight event + ``serf.pallas.fused_fallback``
counter) and the fused dispatch timers riding the shared obs split.

Interpret mode on CPU; the compiled-parity gate for real TPU is
``tools/tpu_proof.py`` (the pallas stage runs whatever family the
config dispatches, which is now the fused one by default)."""

import dataclasses
import functools

import jax
import jax.numpy as jnp
import pytest

from serf_tpu.models.dissemination import (
    GossipConfig,
    K_DEAD,
    K_USER_EVENT,
    coverage,
    inject_fact,
    inject_facts_batch,
    make_state,
    round_step,
)
from serf_tpu.ops import round_kernels


def _rand_state(cfg, key):
    k2, k3, k4 = jax.random.split(key, 3)
    s = make_state(cfg)
    known = jax.random.bits(k2, (cfg.n, cfg.words), jnp.uint32)
    stamp = jax.random.randint(k3, (cfg.n, cfg.stamp_cols), 0, 256
                               ).astype(jnp.uint8)
    if not cfg.pack_stamp:
        stamp = stamp & 0xF
    alive = jax.random.bernoulli(k4, 0.9, (cfg.n,))
    return s._replace(known=known, stamp=stamp, alive=alive,
                      round=jnp.asarray(7, jnp.int32))


def _fused(cfg):
    return dataclasses.replace(cfg, use_pallas=True, fused_kernels=True)


def _assert_states_equal(a, b, context=""):
    for (path, la), lb in zip(jax.tree_util.tree_leaves_with_path(a),
                              jax.tree_util.tree_leaves(b)):
        assert bool(jnp.all(la == lb)), (
            f"leaf {jax.tree_util.keystr(path)} diverged {context}")


def _drive_pair(cfg, n_rounds=4, mesh=None, seed=1):
    """Run fused vs phased-XLA rounds in lockstep (same keys), with
    injections between rounds (cache-mirror + retirement paths) and a
    batch containing a DEAD fact so the tombstone fold and
    believed_dead plumbing are exercised; assert every leaf after every
    round."""
    fast = _fused(cfg)
    s0 = _rand_state(cfg, jax.random.key(seed))
    s0 = inject_fact(s0, cfg, 3, K_USER_EVENT, 0, 9, 3)
    if mesh is None:
        step_a = jax.jit(functools.partial(round_step, cfg=cfg))
        step_b = jax.jit(functools.partial(round_step, cfg=fast))
    else:
        from serf_tpu.parallel.ring import sharded_round_step
        step_a = jax.jit(functools.partial(sharded_round_step, cfg=cfg,
                                           mesh=mesh))
        step_b = jax.jit(functools.partial(sharded_round_step, cfg=fast,
                                           mesh=mesh))
    a, b = s0, s0
    n = cfg.n
    for r in range(n_rounds):
        key = jax.random.key(100 + r)
        a = step_a(a, key=key)
        b = step_b(b, key=key)
        _assert_states_equal(a, b, f"after round {r}")
        kind = K_DEAD if r == 1 else K_USER_EVENT
        subs = jnp.asarray([(r * 7 + 1) % n, (r * 11 + 2) % n], jnp.int32)
        args = dict(kind=kind, incarnations=jnp.ones((2,), jnp.uint32),
                    ltimes=jnp.asarray([30 + 2 * r, 31 + 2 * r],
                                       jnp.uint32),
                    origins=subs, active=jnp.ones((2,), bool))
        a = inject_facts_batch(a, cfg, subs, **args)
        b = inject_facts_batch(b, fast, subs, **args)
    _assert_states_equal(a, b, "at end of drive")
    # protocol outcomes, not just raw planes
    assert bool(jnp.all(coverage(a, cfg) == coverage(b, cfg)))
    return a, b


@pytest.mark.parametrize("packed", [True, False])
def test_fused_round_bit_exact_single_device(packed):
    cfg = GossipConfig(n=512, k_facts=64, pack_stamp=packed)
    _drive_pair(cfg)


def test_fused_round_bit_exact_cache_off():
    cfg = GossipConfig(n=512, k_facts=64, use_sendable_cache=False)
    _drive_pair(cfg)


def test_fused_round_bit_exact_under_chaos_masks():
    """Partition groups + per-round loss flow through the exchange leg
    around the fused kernels — the chaos plane composes with the fused
    round unchanged, bit-exactly."""
    cfg = GossipConfig(n=512, k_facts=64)
    fast = _fused(cfg)
    s0 = inject_fact(_rand_state(cfg, jax.random.key(3)), cfg, 3,
                     K_USER_EVENT, 0, 9, 3)
    group = (jnp.arange(512) % 2).astype(jnp.int32)
    step_a = jax.jit(functools.partial(round_step, cfg=cfg,
                                       drop_rate=0.25))
    step_b = jax.jit(functools.partial(round_step, cfg=fast,
                                       drop_rate=0.25))
    a, b = s0, s0
    for r in range(3):
        key = jax.random.key(40 + r)
        a = step_a(a, key=key, group=group)
        b = step_b(b, key=key, group=group)
        _assert_states_equal(a, b, f"under chaos masks, round {r}")


def test_fused_round_bit_exact_sharded_vmesh8(vmesh8):
    """The fused kernels under shard_map (8 virtual devices) against the
    single-path XLA reference — the PR-6 sharded round could not run
    pallas at all; this pins that the re-enabled path changed nothing."""
    cfg = GossipConfig(n=2048, k_facts=64)
    _drive_pair(cfg, n_rounds=3, mesh=vmesh8)


@pytest.mark.slow
@pytest.mark.parametrize("packed", [True, False])
def test_fused_round_bit_exact_sharded_flavors(vmesh8, packed):
    """Heavy cross-product (flavors x sharded x longer drive)."""
    cfg = GossipConfig(n=2048, k_facts=64, pack_stamp=packed)
    _drive_pair(cfg, n_rounds=6, mesh=vmesh8, seed=5)


def test_fused_cluster_round_views_and_believed_dead():
    """Full flagship cluster rounds (probe/refute/declare/push-pull on
    top) under sustained load with real deaths: final ClusterState and
    the derived membership outcomes (believed_dead) must match between
    the fused and XLA paths."""
    from serf_tpu.models.failure import believed_dead
    from serf_tpu.models.swim import (
        ClusterConfig,
        FailureConfig,
        make_cluster,
        run_cluster_sustained,
    )

    def mk(gossip):
        return ClusterConfig(
            gossip=gossip,
            failure=FailureConfig(suspicion_rounds=4, max_new_facts=8),
            push_pull_every=8, probe_every=2)

    g = GossipConfig(n=512, k_facts=64, peer_sampling="rotation")
    cfg_a, cfg_b = mk(g), mk(_fused(g))
    st = make_cluster(cfg_a, jax.random.key(0))
    gos = st.gossip._replace(
        alive=st.gossip.alive.at[jnp.asarray([5, 99])].set(False))
    st = st._replace(gossip=gos)
    out = []
    for cfg in (cfg_a, cfg_b):
        run = jax.jit(functools.partial(run_cluster_sustained, cfg=cfg,
                                        events_per_round=2),
                      static_argnames=("num_rounds",))
        out.append(run(st, key=jax.random.key(7), num_rounds=16))
    _assert_states_equal(out[0], out[1], "after 16 sustained cluster rounds")
    bd_a = believed_dead(out[0].gossip, cfg_a.gossip, cfg_a.failure)
    bd_b = believed_dead(out[1].gossip, cfg_b.gossip, cfg_b.failure)
    assert bool(jnp.all(bd_a == bd_b))


def test_fused_ok_gate_shape_and_vmem():
    ok, reason = round_kernels.fused_ok(1_000_000, 64, 32)
    assert ok and reason == ""
    ok, reason = round_kernels.fused_ok(1000, 64, 32)
    assert not ok and "node block" in reason
    ok, reason = round_kernels.fused_ok(512, 48, 24)
    assert not ok and "multiple of 32" in reason
    # big-K: the working set exceeds the VMEM budget at EVERY block size
    # -> loud fallback instead of a Mosaic OOM (ISSUE 7 satellite)
    big_k = 1 << 18
    ok, reason = round_kernels.fused_ok(512, big_k, big_k // 2)
    assert not ok and "VMEM" in reason
    assert round_kernels.fused_vmem_bytes(
        32, big_k, big_k // 2) > round_kernels.VMEM_BUDGET_BYTES


def test_fused_fallback_counter_and_flight_reason():
    """A gate rejection must leave BOTH breadcrumbs: the pallas-fallback
    flight event carrying the reason, and the
    serf.pallas.fused_fallback counter (labeled by op)."""
    from serf_tpu import obs
    from serf_tpu.utils import metrics

    rec = obs.FlightRecorder(capacity=64)
    old = obs.global_recorder()
    obs.set_global_recorder(rec)
    sink = metrics.MetricsSink()
    old_sink = metrics.global_sink()
    metrics.set_global_sink(sink)
    try:
        cfg = GossipConfig(n=100, k_facts=32, use_pallas=True)
        s = inject_fact(make_state(cfg), cfg, 0, K_USER_EVENT, 0, 1, 0)
        s = jax.jit(functools.partial(round_step, cfg=cfg))(
            s, key=jax.random.key(0))
        assert int(s.round) == 1
        events = rec.dump(kind="pallas-fallback")
        assert events and "node block" in events[0]["reason"]
        assert sink.counter("serf.pallas.fused_fallback",
                            {"op": "round_step"}) >= 1
    finally:
        obs.set_global_recorder(old)
        metrics.set_global_sink(old_sink)


def test_fused_dispatch_timers_ride_obs_split():
    """Satellite: the fused kernels time under the shared obs dispatch
    registry (compile-vs-steady split) — no second jax.device_get, just
    the host wall clock the other device ops already use."""
    from serf_tpu.obs.device import dispatch_summary, reset_dispatch_registry

    reset_dispatch_registry()
    n, k = 64, 64
    cfg = GossipConfig(n=n, k_facts=k)
    known = jnp.zeros((n, cfg.words), jnp.uint32)
    stamp = jnp.zeros((n, cfg.stamp_cols), jnp.uint8)
    alive = jnp.ones((n, 1), jnp.uint8)
    round_kernels.fused_select_cached(known, known, alive, k_facts=k,
                                      stamp_cols=cfg.stamp_cols)
    round_kernels.fused_merge(known, known, alive, stamp, 1,
                              limit_q=cfg.transmit_limit_q, packed=True,
                              k_facts=k, with_cache=True)
    summary = dispatch_summary()
    assert summary["ops.fused_select"]["calls"] == 1
    assert summary["ops.fused_merge"]["calls"] == 1
