"""Lamport clock semantics (reference serf-core/src/types/clock.rs:175-191)."""

import threading

from serf_tpu.types.clock import LamportClock


def test_basic():
    c = LamportClock()
    assert c.time() == 0
    assert c.increment() == 1  # returns post-increment value (clock.rs fetch_add+1)
    assert c.time() == 1
    c.witness(41)
    assert c.time() == 42
    c.witness(41)  # stale witness: no-op
    assert c.time() == 42
    c.witness(30)
    assert c.time() == 42


def test_witness_equal_bumps():
    c = LamportClock(10)
    c.witness(10)
    assert c.time() == 11


def test_concurrent_increments():
    c = LamportClock()
    N, T = 1000, 8
    seen = [set() for _ in range(T)]

    def worker(i):
        for _ in range(N):
            seen[i].add(c.increment())

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    all_seen = set().union(*seen)
    assert len(all_seen) == N * T  # every increment returned a unique value
    assert c.time() == N * T
