"""Always-on watchdog + black box (ISSUE 17 tentpole, acceptance-
pinned): the per-round device invariant row obeys the house invariant —
OFF (default) the sustained scan is jaxpr-identical to the plain path
(the row is Python-gated out of existence, pinned by a poisoned
``invariant_row``), ON it changes no ``GossipState`` leaf and adds ZERO
per-run host transfers (device_get-count pinned) — and the verdict
names the **first violating round straight from scan output**, no
post-hoc judging.  The host ``Watchdog`` breaches LIVE (first breaching
tick named mid-run), triggers bounded black-box dumps on every node
(rotated, schema-valid, renderable), and the ``_serf_blackbox``
internal query folds the cluster's bundle inventory like
``_serf_stats``.

Budget discipline: one tiny config (n=64, K=32), 10-round scans,
module-scoped run pair; the stamp-flavor × mesh cross is ``@slow``.
"""

import importlib.util
import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serf_tpu.control.device import ControlConfig
from serf_tpu.models.dissemination import (
    GossipConfig,
    K_USER_EVENT,
    inject_fact,
)
from serf_tpu.models.failure import FailureConfig
from serf_tpu.models.swim import (
    ClusterConfig,
    make_cluster,
    run_cluster_sustained,
)
from serf_tpu.obs import flight
from serf_tpu.obs.blackbox import (
    BlackBox,
    BlackboxPartial,
    load_bundle,
    validate_bundle,
)
from serf_tpu.obs.timeseries import SeriesStore
from serf_tpu.obs.watchdog import (
    INVARIANT_FIELDS,
    INVARIANT_MERGE,
    Watchdog,
    WatchdogConfig,
    arm_shed_ratio_watch,
    emit_device_watchdog,
    format_invariants,
    summarize_invariants,
)
from serf_tpu.parallel.mesh import shard_state

REPO = Path(__file__).resolve().parent.parent
N, K, ROUNDS = 64, 32, 10
IDX = {f: i for i, f in enumerate(INVARIANT_FIELDS)}
FLAGS = INVARIANT_FIELDS[:-1]                      # all but viol_mask


def _cfg(pack=True, schedule="ring"):
    return ClusterConfig(
        gossip=GossipConfig(n=N, k_facts=K, peer_sampling="rotation",
                            pack_stamp=pack),
        failure=FailureConfig(suspicion_rounds=8, max_new_facts=8,
                              probe_schedule="round_robin"),
        control=ControlConfig(enabled=False),
        push_pull_every=8, probe_every=2, exchange_schedule=schedule)


def _seeded(cfg):
    st = make_cluster(cfg, jax.random.key(0))
    g = inject_fact(st.gossip, cfg.gossip, subject=3, kind=K_USER_EVENT,
                    incarnation=0, ltime=5, origin=0)
    return st._replace(gossip=g)


def _run(cfg, judged, mesh=None):
    run = jax.jit(lambda s, k: run_cluster_sustained(
        s, cfg, k, ROUNDS, 2, mesh=mesh, collect_invariants=judged))
    st = _seeded(cfg)
    if mesh is not None:
        st = shard_state(st, mesh)
    out = run(st, jax.random.key(3))
    if judged:
        final, irows = out
        return final, jax.device_get(irows)
    return out, None


def _assert_leaves_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        assert (np.asarray(jax.device_get(x))
                == np.asarray(jax.device_get(y))).all()


@pytest.fixture(scope="module")
def inv_pair():
    """One off/on run pair, shared by the device-plane pins."""
    cfg = _cfg()
    f_off, _ = _run(cfg, judged=False)
    f_on, irows = _run(cfg, judged=True)
    return cfg, f_off, f_on, irows


# ---------------------------------------------------------------------------
# house invariant: judge off = plain path (jaxpr + Python gate),
# judge on = same state, zero extra transfers
# ---------------------------------------------------------------------------


def test_off_path_is_python_gated(monkeypatch):
    """THE off-is-free pin, both ways: with the flag off the jaxpr is
    byte-identical to the plain call AND ``invariant_row`` is never even
    called (poisoned here) — with it on, the poison trips at trace
    time.  The row cannot cost the untraced path anything."""
    from serf_tpu.models import swim as swim_mod

    cfg = _cfg()
    st = _seeded(cfg)
    plain = str(jax.make_jaxpr(lambda s, k: run_cluster_sustained(
        s, cfg, k, ROUNDS, 2))(st, jax.random.key(3)))
    off = str(jax.make_jaxpr(lambda s, k: run_cluster_sustained(
        s, cfg, k, ROUNDS, 2, collect_invariants=False))(
            st, jax.random.key(3)))
    assert off == plain

    def _poison(*a, **k):
        raise AssertionError("invariant_row reached with the flag off")
    monkeypatch.setattr(swim_mod, "invariant_row", _poison)
    jax.make_jaxpr(lambda s, k: run_cluster_sustained(
        s, cfg, k, ROUNDS, 2))(st, jax.random.key(3))     # fine
    with pytest.raises(AssertionError, match="flag off"):
        jax.make_jaxpr(lambda s, k: run_cluster_sustained(
            s, cfg, k, ROUNDS, 2, collect_invariants=True))(
                st, jax.random.key(3))


def test_judge_on_is_state_bit_exact(inv_pair):
    """Judging on changes no GossipState leaf: the invariant rows are
    extra scan OUTPUTS, never a state perturbation — and a fault-free
    run judges green every round (viol_mask all-zero)."""
    _, f_off, f_on, irows = inv_pair
    _assert_leaves_equal(f_off, f_on)
    assert irows.shape == (ROUNDS, len(INVARIANT_FIELDS))
    assert (irows[:, : len(FLAGS)] == 1.0).all()
    assert (irows[:, IDX["viol_mask"]] == 0.0).all()


@pytest.mark.parametrize("pack", [False])
def test_judge_on_is_state_bit_exact_unpacked(pack):
    """Same pin for the other stamp flavor (packed rode the module
    fixture)."""
    cfg = _cfg(pack=pack)
    f_off, _ = _run(cfg, judged=False)
    f_on, irows = _run(cfg, judged=True)
    _assert_leaves_equal(f_off, f_on)
    assert irows.shape == (ROUNDS, len(INVARIANT_FIELDS))


def test_judge_on_bit_exact_vmesh8(inv_pair, vmesh8):
    """Sharded flagship: state bit-exact AND the sharded rows equal the
    unsharded ones bit-for-bit — every predicate folds from replicated
    operands (the all-``replicated`` INVARIANT_MERGE contract), so the
    mesh cannot change a single bit."""
    cfg, _, _, ref_rows = inv_pair
    f_off, _ = _run(cfg, judged=False, mesh=vmesh8)
    f_on, irows = _run(cfg, judged=True, mesh=vmesh8)
    _assert_leaves_equal(f_off, f_on)
    assert (irows == ref_rows).all()


@pytest.mark.slow
@pytest.mark.parametrize("pack", [True, False])
@pytest.mark.parametrize("schedule", ["ring", "allgather"])
def test_judge_bit_exact_heavy_cross(vmesh8, pack, schedule):
    """Redundant heavy parametrization: both stamp flavors × both ICI
    schedules on the virtual mesh (each axis already covered above)."""
    cfg = _cfg(pack=pack, schedule=schedule)
    f_off, _ = _run(cfg, judged=False, mesh=vmesh8)
    f_on, rows = _run(cfg, judged=True, mesh=vmesh8)
    _assert_leaves_equal(f_off, f_on)
    _, ref = _run(cfg, judged=True)
    assert (rows == ref).all()


def _count_device_gets(monkeypatch, **kwargs):
    from serf_tpu.faults.device import run_device_plan
    from serf_tpu.faults.plan import named_plan

    real = jax.device_get
    calls = []
    monkeypatch.setattr(jax, "device_get",
                        lambda *a, **k: calls.append(1) or real(*a, **k))
    result = run_device_plan(named_plan("partition-heal-loss"), _cfg(),
                             **kwargs)
    monkeypatch.setattr(jax, "device_get", real)
    return len(calls), result


def test_judging_adds_zero_transfers(monkeypatch):
    """THE acceptance pin: a chaos run judging every round performs
    exactly as many jax.device_get calls as the telemetry-only run —
    the invariant rows ride the existing end-of-run transfer.  The
    legal-fault run judges green on every predicate, live."""
    n_tele, _ = _count_device_gets(monkeypatch, collect_telemetry=True)
    n_both, r = _count_device_gets(monkeypatch, collect_telemetry=True,
                                   collect_invariants=True)
    assert n_both == n_tele, (
        f"judged run did {n_both} device_gets vs {n_tele} without")
    assert r.watchdog is not None and r.watchdog["ok"]
    assert r.watchdog["first_violation"] is None
    assert set(r.watchdog["fields"]) == set(FLAGS)
    assert np.asarray(r.watchdog["rows"]).shape[1] == len(INVARIANT_FIELDS)


# ---------------------------------------------------------------------------
# first-violation naming: straight from scan rows, no post-hoc judging
# ---------------------------------------------------------------------------


def _rows_with(violations):
    """Green rows with {round_index: [field, ...]} violations stamped
    in (exactly the scan's stacked-output shape)."""
    rows = np.ones((8, len(INVARIANT_FIELDS)), np.float32)
    rows[:, IDX["viol_mask"]] = 0.0
    for i, fields in violations.items():
        for f in fields:
            rows[i, IDX[f]] = 0.0
            rows[i, IDX["viol_mask"]] += float(1 << IDX[f])
    return rows


def test_summary_names_first_violating_round():
    rows = _rows_with({3: ["no_false_dead"], 5: ["ltime_ok"],
                       6: ["no_false_dead"]})
    s = summarize_invariants(rows)
    assert not s["ok"] and s["rounds"] == 8
    assert s["first_violation"] == {"round": 4,
                                    "fields": ["no_false_dead"]}
    assert s["per_field"]["no_false_dead"] == {
        "first_violation_round": 4, "violations": 2}
    assert s["per_field"]["ltime_ok"] == {
        "first_violation_round": 6, "violations": 1}
    assert s["per_field"]["overflow_ok"]["first_violation_round"] is None
    assert s["violations"] == 3
    # absolute rounds: row i of a chunk starting at base describes the
    # state AFTER round base+i+1 (the telemetry stamp convention)
    assert summarize_invariants(rows, base_round=10)[
        "first_violation"]["round"] == 14
    # ties: two fields first violated on the same round are both named
    tie = summarize_invariants(
        _rows_with({2: ["overflow_ok", "coverage_monotone"]}))
    assert tie["first_violation"]["round"] == 3
    assert set(tie["first_violation"]["fields"]) == {
        "overflow_ok", "coverage_monotone"}
    green = summarize_invariants(_rows_with({}))
    assert green["ok"] and green["first_violation"] is None


def test_device_breach_lands_flight_event_and_report():
    """A breaching summary emits the ``watchdog-breach`` flight event
    naming the first violating round, and formats as one FAIL block."""
    rec = flight.global_recorder()
    since = rec.last_seq
    s = summarize_invariants(_rows_with({4: ["overflow_ok"]}),
                             base_round=20)
    emit_device_watchdog(s)
    ev = [e for e in rec.dump(kind="watchdog-breach", since_seq=since)]
    assert len(ev) == 1
    assert ev[0]["plane"] == "device" and ev[0]["round"] == 25
    assert ev[0]["invariants"] == ["overflow_ok"]
    text = format_invariants(s)
    assert "BREACHED" in text and "first violated at round 25" in text
    assert "FAIL" in text and "ltime_ok" in text


def test_merge_contract_is_replicated_everywhere():
    """The serflint ``invariant-field-drift`` contract, asserted at the
    source: every row field reduces, and only via ``replicated``."""
    assert set(INVARIANT_MERGE) == set(INVARIANT_FIELDS)
    assert set(INVARIANT_MERGE.values()) == {"replicated"}


# ---------------------------------------------------------------------------
# host plane: the continuous watchdog
# ---------------------------------------------------------------------------


def _flag_box(tmp_path, node="u0", **wd_kw):
    rec = flight.FlightRecorder()
    wd = Watchdog(cfg=WatchdogConfig(**wd_kw), recorder=rec)
    box = BlackBox(str(tmp_path), node=node, recorder=rec)
    wd.add_blackbox(box)
    tripped = {"on": False}
    wd.arm("trip", lambda: (not tripped["on"], "tripped flag"))
    return wd, box, rec, tripped


def test_live_breach_names_first_tick_and_dumps(tmp_path):
    """THE host acceptance pin (unit flavor): the verdict is produced
    AT the breaching tick — ``first_breach`` names it live, the flight
    event and the bundle exist before the run is over."""
    since = flight.global_recorder().last_seq
    wd, box, rec, tripped = _flag_box(tmp_path, dump_every_ticks=1)
    assert wd.tick().ok and wd.tick().ok
    tripped["on"] = True
    v = wd.tick()
    assert not v.ok and v.tick == 3 and v.breaches == ["trip"]
    assert wd.first_breach is v and wd.breaches == 1
    ev = flight.global_recorder().dump(kind="watchdog-breach",
                                       since_seq=since)
    assert ev and ev[-1]["tick"] == 3 and ev[-1]["plane"] == "host"
    paths = box.bundle_paths()
    assert len(paths) == 1
    b = load_bundle(paths[0])
    assert validate_bundle(b) == []
    assert b["meta"]["reason"] == "breach"
    assert b["watchdog"]["state"]["first_breach"]["tick"] == 3
    # verdict history (the timeline lane's feed) carries the live tick
    st = wd.state()
    assert st["ok"] is False
    assert [h["tick"] for h in st["history"] if not h["ok"]] == [3]


def test_dump_debounce_and_disjoint_flight_tails(tmp_path):
    """Dumps are debounced to one per ``dump_every_ticks``; consecutive
    dumps carry DISJOINT flight tails (the watchdog-owned cursor)."""
    wd, box, rec, tripped = _flag_box(tmp_path, dump_every_ticks=3)
    tripped["on"] = True
    rec.record("queue-overflow", queue="a")
    wd.tick()                                 # breach -> dump 1
    wd.tick()
    wd.tick()                                 # debounced
    assert len(box.bundle_paths()) == 1
    rec.record("queue-overflow", queue="b")
    wd.tick()                                 # 3 ticks later -> dump 2
    paths = box.bundle_paths()
    assert len(paths) == 2
    first, second = (load_bundle(p)["flight"] for p in paths)
    seqs_a = {e["seq"] for e in first["events"]}
    seqs_b = {e["seq"] for e in second["events"]}
    assert seqs_a and seqs_b and not (seqs_a & seqs_b)
    assert any(e["queue"] == "b" for e in second["events"])


def test_rotation_is_bounded(tmp_path):
    """max_bundles evicts oldest-first; the retained set never grows."""
    rec = flight.FlightRecorder()
    box = BlackBox(str(tmp_path), node="rot", max_bundles=2,
                   recorder=rec)
    for i in range(5):
        box.dump(reason=f"r{i}")
    paths = box.bundle_paths()
    assert len(paths) == 2 and box.rotated == 3
    assert [load_bundle(p)["meta"]["seq"] for p in paths] == [4, 5]


def test_broken_predicate_is_a_breach(tmp_path):
    """A predicate that raises is itself a breach (a broken verifier
    must never read as green)."""
    wd, _, _, _ = _flag_box(tmp_path)

    def boom():
        raise RuntimeError("sensor gone")
    wd.arm("sensor", boom)
    v = wd.tick()
    assert not v.ok and "sensor" in v.breaches
    assert "predicate raised" in v.detail


def test_shed_ratio_burn_breaches_only_when_sustained():
    """The shed-ratio SLO watch breaches on BOTH burn windows only —
    a healthy run stays green, a sustained >objective shed ratio names
    the first breaching tick."""
    store = SeriesStore()
    rec = flight.FlightRecorder()
    wd = Watchdog(store=store, recorder=rec)
    arm_shed_ratio_watch(wd, store)
    t = 0.0
    for _ in range(10):                       # healthy: 20% shed
        store.append("serf.overload.ingress_shed", t, 2, kind="delta")
        store.append("serf.overload.ingress_admitted", t, 8,
                     kind="delta")
        t += 1.0
        assert wd.tick().ok
    for _ in range(40):                       # storm: ~99.8% shed
        store.append("serf.overload.ingress_shed", t, 500, kind="delta")
        store.append("serf.overload.ingress_admitted", t, 1,
                     kind="delta")
        t += 1.0
        wd.tick()
    assert wd.first_breach is not None
    assert wd.first_breach.breaches == ["slo:shed-ratio"]
    assert "sustained burn" in wd.first_breach.detail


async def test_task_failure_hook_is_a_breach(tmp_path):
    """A process-fatal task exception through the ``spawn_logged`` seam
    is a breach: verdict + undebounced dump."""
    import asyncio

    from serf_tpu.utils.tasks import spawn_logged

    wd, box, _, _ = _flag_box(tmp_path, dump_every_ticks=8)
    wd.install_task_hook()
    try:
        async def die():
            raise RuntimeError("fatal")
        t = spawn_logged(die(), "doomed-task")
        await asyncio.wait([t])
        await asyncio.sleep(0)                # let done-callbacks run
        assert wd.breaches == 1
        assert wd.first_breach.breaches == ["task-exception"]
        assert "doomed-task" in wd.first_breach.detail
        paths = box.bundle_paths()
        assert len(paths) == 1
        assert load_bundle(paths[0])["meta"]["reason"] == "task-exception"
    finally:
        wd.uninstall_task_hook()


# ---------------------------------------------------------------------------
# cluster forensics: _serf_blackbox (the _serf_stats contract)
# ---------------------------------------------------------------------------


def _blackbox_tool():
    spec = importlib.util.spec_from_file_location(
        "blackbox_tool", REPO / "tools" / "blackbox.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_blackbox_partials_merge_like_stats():
    """Partials over disjoint responder sets fold to the union —
    associative, commutative, relay-safe (the ``StatsPartial``
    contract verbatim)."""
    a = BlackboxPartial.of({"n0": {"id": "n0", "n": 1}})
    b = BlackboxPartial.of({"n1": {"id": "n1", "n": 2}})
    c = BlackboxPartial.of({"n2": {"id": "n2", "n": 0}})
    left = a.merge(b).merge(c)
    right = a.merge(b.merge(c))
    assert left.nodes == right.nodes == b.merge(a).merge(c).nodes
    snap = left.finish("n0", 3)
    assert snap.complete and snap.bundles == 3


async def test_cluster_blackbox_covers_every_node(tmp_path):
    """Scatter ``_serf_blackbox`` across a live loopback cluster: every
    node answers with its bundle inventory, the fold is complete, and
    each latest bundle is schema-valid and renderable."""
    import asyncio

    from serf_tpu.host import LoopbackNetwork, Serf
    from serf_tpu.host.query import QueryParam
    from serf_tpu.options import Options

    net = LoopbackNetwork()
    nodes = [await Serf.create(net.bind(f"addr-{i}"), Options.local(),
                               f"node-{i}") for i in range(3)]
    try:
        for s in nodes[1:]:
            await s.join("addr-0")
        deadline = asyncio.get_running_loop().time() + 10.0
        while asyncio.get_running_loop().time() < deadline and \
                not all(len(s.members()) == 3 for s in nodes):
            await asyncio.sleep(0.02)
        tool = _blackbox_tool()
        for s in nodes:
            s.blackbox = BlackBox(str(tmp_path), node=s.local_id,
                                  recorder=flight.FlightRecorder())
            s.blackbox.dump(reason="test-sweep")
        snap = await nodes[0].cluster_blackbox(QueryParam(timeout=3.0))
        assert set(snap.nodes) == {"node-0", "node-1", "node-2"}
        assert snap.complete and snap.bundles == 3
        for nid, inv in snap.nodes.items():
            assert inv["n"] == 1 and inv["latest"]["seq"] == 1
            assert inv["latest"]["reason"] == "test-sweep"
            bundle = load_bundle(inv["latest"]["path"])
            assert validate_bundle(bundle) == []
            assert nid in tool.render_bundle(bundle)
        # round-trips through JSON (the obstop --json contract)
        assert json.loads(json.dumps(snap.to_dict()))["responders"] == 3
    finally:
        for s in nodes:
            await s.shutdown()


# ---------------------------------------------------------------------------
# THE acceptance scenario: a live mid-run breach on the host plane
# ---------------------------------------------------------------------------


async def test_host_plan_live_breach_dumps_every_node(tmp_path):
    """A storm a tight admission config MUST shed >objective: the
    always-on watchdog breaches the shed-ratio burn LIVE (first
    breaching tick named by a verdict produced mid-run, not by any
    post-hoc judge), and the triggered black boxes leave a schema-valid,
    renderable bundle for EVERY node."""
    from serf_tpu.faults.host import run_host_plan
    from serf_tpu.faults.plan import FaultPhase, FaultPlan
    from serf_tpu.options import Options

    plan = FaultPlan(
        name="watchdog-shed", n=3, seed=11,
        phases=(
            FaultPhase(name="warm", duration_s=0.3),
            FaultPhase(name="storm1", duration_s=1.2, event_rate=1200.0),
            FaultPhase(name="storm2", duration_s=1.2, event_rate=1200.0),
            FaultPhase(name="storm3", duration_s=1.2, event_rate=1200.0),
        ),
        settle_s=6.0,
    )
    opts = Options.local(
        user_event_rate=1.0, user_event_burst=1,
        query_rate=1.0, query_burst=1,
        event_queue_bytes=64 * 1024, query_queue_bytes=64 * 1024)
    since = flight.global_recorder().last_seq
    result = await run_host_plan(plan, tmp_dir=str(tmp_path), opts=opts)
    wd = result.watchdog
    assert wd is not None and wd["ok"] is False
    fb = wd["first_breach"]
    assert fb is not None and fb["tick"] >= 1 and fb["breaches"]
    breached = {b for v in wd["history"] for b in v["breaches"]}
    assert "slo:shed-ratio" in breached
    # the verdict was produced AT its tick: the first_breach precedes
    # (or is) every breaching verdict in the live-accumulated history
    # (state() keeps the last 16), and the flight ring carries the
    # breach event stamped with that same tick
    bad_ticks = [v["tick"] for v in wd["history"] if not v["ok"]]
    assert bad_ticks and fb["tick"] <= min(bad_ticks)
    ev = flight.global_recorder().dump(kind="watchdog-breach",
                                       since_seq=since)
    assert any(e.get("plane") == "host" and e.get("tick") in bad_ticks
               for e in ev), "no live breach event survived in the ring"
    # forensics on EVERY node: one+ bundle each, schema-valid, renderable
    tool = _blackbox_tool()
    by_node = {}
    for p in sorted((Path(str(tmp_path)) / "blackbox").glob("*.json")):
        b = load_bundle(str(p))
        assert validate_bundle(b) == []
        by_node.setdefault(b["meta"]["node"], []).append(b)
    assert set(by_node) == {"n0", "n1", "n2"}, sorted(by_node)
    for node, bundles in by_node.items():
        latest = bundles[-1]
        assert latest["meta"]["reason"] in ("breach", "task-exception")
        assert latest["watchdog"]["state"]["first_breach"] is not None
        text = tool.render_bundle(latest)
        assert node in text and "black box" in text
    assert wd["bundles"], "watchdog state must list the bundle paths"
