"""Keyring: install/use/remove semantics, encryption round-trip, persistence."""

import pytest

pytest.importorskip(
    "cryptography", reason="cryptography not installed in this image")

from serf_tpu.host.keyring import KeyringError, SecretKeyring  # noqa: E402

K1, K2, K3 = bytes(range(16)), bytes(range(16, 48)), bytes(range(8, 32))


def test_encrypt_decrypt_round_trip():
    ring = SecretKeyring(K1)
    ct = ring.encrypt(b"gossip", b"aad")
    assert ring.decrypt(ct, b"aad") == b"gossip"
    with pytest.raises(KeyringError):
        ring.decrypt(ct, b"wrong-aad")
    with pytest.raises(KeyringError):
        ring.decrypt(b"\x01" + b"0" * 30)


def test_rotation_any_installed_key_decrypts():
    ring = SecretKeyring(K1)
    ct_old = ring.encrypt(b"old")
    ring.install(K2)
    ring.use_key(K2)
    assert ring.decrypt(ct_old) == b"old"          # old-key traffic still readable
    ct_new = ring.encrypt(b"new")
    peer = SecretKeyring(K1, [K2])
    assert peer.decrypt(ct_new) == b"new"          # peer mid-rotation reads new traffic
    with pytest.raises(KeyringError):
        ring.remove(K2)                            # cannot remove primary
    ring.remove(K1)
    with pytest.raises(KeyringError):
        ring.decrypt(ct_old)                       # removed key no longer decrypts


def test_save_load_preserves_rotated_primary(tmp_path):
    ring = SecretKeyring(K1)
    ring.install(K2)
    ring.use_key(K2)
    p = str(tmp_path / "keyring.json")
    ring.save(p)
    import os
    assert oct(os.stat(p).st_mode & 0o777) == "0o600"
    loaded = SecretKeyring.load(p)
    assert loaded.primary_key() == K2              # rotation survives persistence
    assert set(loaded.keys()) == {K1, K2}


def test_bad_key_sizes_rejected():
    with pytest.raises(KeyringError):
        SecretKeyring(b"short")
    ring = SecretKeyring(K1)
    with pytest.raises(KeyringError):
        ring.install(b"also-bad")
    with pytest.raises(KeyringError):
        ring.use_key(K3)  # not installed
