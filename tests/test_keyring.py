"""Keyring: install/use/remove semantics, encryption round-trip,
decrypt robustness (wrong key, truncated/malformed frames, torn files),
fallback ordering, and persistence.  Runs on whichever AEAD backend the
image has (AES-GCM via the ``cryptography`` wheel, else the stdlib
HMAC-SHA256-CTR fallback) — no importorskip: encrypted transport must
work on wheel-less images too."""

import json

import pytest

from serf_tpu.host.keyring import (
    CRYPTO_BACKEND,
    ENCRYPTION_FRAME_SCHEMA,
    KeyringError,
    SecretKeyring,
    key_digest,
)
from serf_tpu.utils import metrics

K1, K2, K3 = bytes(range(16)), bytes(range(16, 48)), bytes(range(8, 32))


def _counter(name: str) -> float:
    return sum(v for (n, _l), v in metrics.global_sink().counters.items()
               if n == name)


def test_backend_is_named():
    assert CRYPTO_BACKEND in ("aes-gcm", "hmac-sha256-ctr")


def test_encrypt_decrypt_round_trip():
    ring = SecretKeyring(K1)
    ct = ring.encrypt(b"gossip", b"aad")
    assert ring.decrypt(ct, b"aad") == b"gossip"
    with pytest.raises(KeyringError):
        ring.decrypt(ct, b"wrong-aad")
    with pytest.raises(KeyringError):
        ring.decrypt(b"\x01" + b"0" * 30)


def test_rotation_any_installed_key_decrypts():
    ring = SecretKeyring(K1)
    ct_old = ring.encrypt(b"old")
    ring.install(K2)
    ring.use_key(K2)
    assert ring.decrypt(ct_old) == b"old"          # old-key traffic still readable
    ct_new = ring.encrypt(b"new")
    peer = SecretKeyring(K1, [K2])
    assert peer.decrypt(ct_new) == b"new"          # peer mid-rotation reads new traffic
    with pytest.raises(KeyringError):
        ring.remove(K2)                            # cannot remove primary
    ring.remove(K1)
    with pytest.raises(KeyringError):
        ring.decrypt(ct_old)                       # removed key no longer decrypts


def test_wrong_key_frame_fails_closed_and_counts():
    ours = SecretKeyring(K1)
    theirs = SecretKeyring(K2)
    frame = theirs.encrypt(b"not ours")
    before = _counter("serf.keyring.decrypt_fail")
    with pytest.raises(KeyringError):
        ours.decrypt(frame)
    assert _counter("serf.keyring.decrypt_fail") == before + 1


def test_truncated_and_malformed_ciphertext():
    ring = SecretKeyring(K1)
    frame = ring.encrypt(b"payload of reasonable length")
    # shorter than version+nonce+tag: malformed, not an index error
    for cut in (0, 1, 12, 28):
        with pytest.raises(KeyringError):
            ring.decrypt(frame[:cut])
    # full-length but wrong version byte
    with pytest.raises(KeyringError):
        ring.decrypt(b"\x7f" + frame[1:])
    # truncated ciphertext (tag present but ct shortened): auth fails
    with pytest.raises(KeyringError):
        ring.decrypt(frame[:1 + 12] + frame[1 + 12 + 4:])
    # single flipped bit anywhere in the body: auth fails
    tampered = bytearray(frame)
    tampered[len(frame) // 2] ^= 0x40
    with pytest.raises(KeyringError):
        ring.decrypt(bytes(tampered))


def test_fallback_order_primary_then_secondaries():
    # sender still on the OLD key; receiver already rotated primary to
    # K2 but keeps K1 installed — decrypt must fall back and count it
    sender = SecretKeyring(K1)
    receiver = SecretKeyring(K1, [K2])
    receiver.use_key(K2)
    frame = sender.encrypt(b"late packet")
    fb = _counter("serf.keyring.decrypt_fallback")
    assert receiver.decrypt(frame) == b"late packet"
    assert _counter("serf.keyring.decrypt_fallback") == fb + 1
    # primary-path decrypt does NOT count a fallback
    fb = _counter("serf.keyring.decrypt_fallback")
    assert receiver.decrypt(receiver.encrypt(b"hot")) == b"hot"
    assert _counter("serf.keyring.decrypt_fallback") == fb


def test_torn_keyring_file_fails_closed(tmp_path):
    good = tmp_path / "good.keyring"
    SecretKeyring(K1, [K2]).save(str(good))
    blob = good.read_text()
    # torn tail (crash mid-write of a non-atomic writer)
    torn = tmp_path / "torn.keyring"
    torn.write_text(blob[: len(blob) // 2])
    with pytest.raises(KeyringError):
        SecretKeyring.load(str(torn))
    # valid JSON, invalid base64
    bad64 = tmp_path / "bad64.keyring"
    bad64.write_text(json.dumps(["!!!not-base-64!!!"]))
    with pytest.raises(KeyringError):
        SecretKeyring.load(str(bad64))
    # empty list
    empty = tmp_path / "empty.keyring"
    empty.write_text("[]")
    with pytest.raises(KeyringError):
        SecretKeyring.load(str(empty))


def test_save_load_preserves_rotated_primary(tmp_path):
    ring = SecretKeyring(K1)
    ring.install(K2)
    ring.use_key(K2)
    p = str(tmp_path / "keyring.json")
    ring.save(p)
    import os
    assert oct(os.stat(p).st_mode & 0o777) == "0o600"
    loaded = SecretKeyring.load(p)
    assert loaded.primary_key() == K2              # rotation survives persistence
    assert set(loaded.keys()) == {K1, K2}


def test_digest_is_non_secret_and_comparable():
    a = SecretKeyring(K1, [K2])
    b = SecretKeyring(K1, [K2])
    assert a.digest() == b.digest()
    d = a.digest()
    assert d["primary"] == key_digest(K1)
    assert sorted(d["keys"]) == sorted([key_digest(K1), key_digest(K2)])
    # digests are 12-hex identities, never key material
    assert all(len(x) == 12 for x in [d["primary"], *d["keys"]])


def test_frame_schema_literal_shape():
    # the serflint-pinned wire surface: keep the declared shape honest
    assert set(ENCRYPTION_FRAME_SCHEMA) == {
        "encrypted-frame", "encrypt-pipeline", "batch-encryption"}
    assert ENCRYPTION_FRAME_SCHEMA["encrypt-pipeline"][-1] == "encrypt"


def test_bad_key_sizes_rejected():
    with pytest.raises(KeyringError):
        SecretKeyring(b"short")
    ring = SecretKeyring(K1)
    with pytest.raises(KeyringError):
        ring.install(b"also-bad")
    with pytest.raises(KeyringError):
        ring.use_key(K3)  # not installed
