"""Device event streaming + exact push-gossip (MXU) mode."""

import functools

import jax
import jax.numpy as jnp

from serf_tpu.models.dissemination import (
    GossipConfig,
    K_USER_EVENT,
    coverage,
    inject_fact,
    make_state,
    push_round_step,
    round_step,
)
from serf_tpu.models.events import DeviceEventStream, RoundSummary, summarize


def test_push_mode_disseminates_and_respects_budgets():
    cfg = GossipConfig(n=256, k_facts=32)
    s = inject_fact(make_state(cfg), cfg, 0, K_USER_EVENT, 0, 1, 0)
    step = jax.jit(functools.partial(push_round_step, cfg=cfg))
    key = jax.random.key(0)
    for r in range(40):
        key, k2 = jax.random.split(key)
        s = step(s, key=k2)
        if float(coverage(s, cfg)[0]) == 1.0:
            break
    assert float(coverage(s, cfg)[0]) == 1.0
    # budgets exhaust after convergence
    for r in range(cfg.transmit_limit + 2):
        key, k2 = jax.random.split(key)
        s = step(s, key=k2)
    from serf_tpu.models.dissemination import budgets_of
    assert int(jnp.sum(budgets_of(s, cfg))) == 0


def test_push_mode_dead_nodes_dont_send_or_learn():
    cfg = GossipConfig(n=128, k_facts=32)
    s = make_state(cfg)
    s = s._replace(alive=s.alive.at[5].set(False))
    s = inject_fact(s, cfg, 0, K_USER_EVENT, 0, 1, 5)  # origin is dead!
    step = jax.jit(functools.partial(push_round_step, cfg=cfg))
    key = jax.random.key(1)
    for _ in range(30):
        key, k2 = jax.random.split(key)
        s = step(s, key=k2)
    assert float(coverage(s, cfg)[0]) == 0.0  # dead origin spreads nothing


def test_push_and_pull_reach_same_fixpoint():
    """Different exchange directions, same converged knowledge."""
    cfg = GossipConfig(n=256, k_facts=32)
    base = inject_fact(make_state(cfg), cfg, 0, K_USER_EVENT, 0, 1, 0)
    pull_step = jax.jit(functools.partial(round_step, cfg=cfg))
    push_step = jax.jit(functools.partial(push_round_step, cfg=cfg))
    a, b = base, base
    key = jax.random.key(2)
    for _ in range(50):
        key, k1, k2 = jax.random.split(key, 3)
        a = pull_step(a, key=k1)
        b = push_step(b, key=k2)
    assert float(coverage(a, cfg)[0]) == 1.0
    assert float(coverage(b, cfg)[0]) == 1.0
    assert bool(jnp.all(a.known == b.known))


def test_device_event_stream():
    cfg = GossipConfig(n=128, k_facts=32)
    s = make_state(cfg)
    stream = DeviceEventStream(cfg)
    step = jax.jit(functools.partial(round_step, cfg=cfg))
    events = stream.push(jax.device_get(summarize(s, cfg)))
    assert events == []
    s = inject_fact(s, cfg, 7, K_USER_EVENT, 0, 1, 0)
    events = stream.push(jax.device_get(summarize(s, cfg)))
    assert any(e.kind == "fact-born" and e.subject == 7 for e in events)
    key = jax.random.key(3)
    full = []
    for _ in range(40):
        key, k2 = jax.random.split(key)
        s = step(s, key=k2)
        full.extend(e for e in stream.push(jax.device_get(summarize(s, cfg)))
                    if e.kind == "fully-disseminated")
        if full:
            break
    assert full and full[0].subject == 7
    assert full[0].knowers == cfg.n


def test_device_event_stream_emits_retired_on_ring_overwrite():
    cfg = GossipConfig(n=64, k_facts=32)
    s = make_state(cfg)
    stream = DeviceEventStream(cfg)
    s = inject_fact(s, cfg, 7, K_USER_EVENT, 0, 1, 0)
    stream.push(summarize(s, cfg))
    # wrap the ring: k_facts more injections overwrite slot 0
    for i in range(cfg.k_facts):
        s = inject_fact(s, cfg, 100 + i, K_USER_EVENT, 0, 2 + i, 0)
    events = stream.push(summarize(s, cfg))
    assert any(e.kind == "retired" and e.subject == 7 for e in events)
    # the new occupants of the ring are born
    assert sum(e.kind == "fact-born" for e in events) == cfg.k_facts


def test_device_event_stream_single_transfer_per_push():
    """push() must not issue per-slot device syncs: after one device_get the
    diff is pure numpy.  Guard by counting jax.device_get calls."""
    import numpy as np
    from unittest import mock

    cfg = GossipConfig(n=64, k_facts=32)
    s = inject_fact(make_state(cfg), cfg, 3, K_USER_EVENT, 0, 1, 0)
    stream = DeviceEventStream(cfg)
    summary = summarize(s, cfg)
    real = jax.device_get
    calls = []

    def counting(x):
        calls.append(1)
        return real(x)

    with mock.patch.object(jax, "device_get", counting):
        stream.push(summary)
    assert len(calls) == 1
