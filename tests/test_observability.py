"""Observability subsystem tests: trace spans, flight recorder, exporters,
queue gauges, device-plane emitters, and the metrics/README lint.

The scenario test at the bottom is the acceptance pin: one
join -> user event -> query -> leave run must leave the documented host
metric names populated, spans in the trace ring, state transitions in the
flight recorder, and a Prometheus export that round-trips through the
bundled parser.
"""

import asyncio
import logging
import subprocess
import sys
from pathlib import Path

import pytest

from serf_tpu import obs
from serf_tpu.obs.device import (
    dispatch_summary,
    dispatch_timer,
    record_dispatch,
    reset_dispatch_registry,
)
from serf_tpu.obs.export import parse_prometheus_text, prometheus_text
from serf_tpu.obs.flight import FlightRecorder
from serf_tpu.obs.trace import TraceBuffer, current_span, span
from serf_tpu.utils import metrics
from serf_tpu.utils.logging import ROOT_LOGGER, get_logger, setup_logging
from serf_tpu.utils.metrics import HistogramSummary, MetricsSink

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def fresh_obs():
    """Isolate every test: fresh sink, trace ring, flight ring, dispatch
    registry; restore the previous globals afterwards."""
    old_sink = metrics.global_sink()
    old_tracer = obs.global_tracer()
    old_rec = obs.global_recorder()
    metrics.set_global_sink(MetricsSink())
    obs.set_global_tracer(TraceBuffer())
    obs.set_global_recorder(FlightRecorder())
    reset_dispatch_registry()
    yield
    metrics.set_global_sink(old_sink)
    obs.set_global_tracer(old_tracer)
    obs.set_global_recorder(old_rec)
    reset_dispatch_registry()


# -- trace spans -------------------------------------------------------------


def test_span_nesting_and_timing():
    with span("outer", node="a") as outer:
        assert current_span() is outer
        with span("inner") as inner:
            assert inner.parent_id == outer.span_id
            assert inner.depth == outer.depth + 1
        # contextvar restored after the child exits
        assert current_span() is outer
    assert current_span() is None
    assert outer.parent_id == 0 and outer.depth == 0

    dump = obs.trace_dump()
    # children finish (and land in the ring) before their parents
    names = [d["name"] for d in dump]
    assert names == ["inner", "outer"]
    by_name = {d["name"]: d for d in dump}
    assert by_name["inner"]["parent_id"] == by_name["outer"]["span_id"]
    assert by_name["outer"]["attrs"] == {"node": "a"}
    assert by_name["outer"]["duration_ms"] >= by_name["inner"]["duration_ms"]
    assert all(d["duration_ms"] >= 0.0 for d in dump)
    assert all(d["status"] == "ok" for d in dump)


def test_span_error_status_and_histogram_feed():
    with pytest.raises(RuntimeError):
        with span("will-fail"):
            raise RuntimeError("boom")
    (d,) = obs.trace_dump(name="will-fail")
    assert d["status"] == "error"
    # every finished span feeds the aggregate latency histogram
    h = metrics.global_sink().histogram_summary(
        "serf.trace.span-ms", {"span": "will-fail"})
    assert h is not None and h.count == 1


def test_trace_buffer_wraparound_drops_oldest():
    buf = TraceBuffer(capacity=4)
    obs.set_global_tracer(buf)
    for i in range(7):
        with span(f"s{i}"):
            pass
    assert len(buf) == 4
    assert buf.recorded == 7
    assert [d["name"] for d in buf.dump()] == ["s3", "s4", "s5", "s6"]
    assert [d["name"] for d in buf.dump(limit=2)] == ["s5", "s6"]


def test_spans_nest_per_asyncio_task():
    async def child(tag):
        with span(tag) as s:
            await asyncio.sleep(0)
            # sibling tasks must not become each other's parents
            assert current_span() is s
            return s.parent_id

    async def main():
        with span("root") as root:
            pids = await asyncio.gather(child("a"), child("b"))
        return root.span_id, pids

    root_id, pids = asyncio.run(main())
    assert pids == [root_id, root_id]


# -- flight recorder ---------------------------------------------------------


def test_flight_ring_wraparound_and_filters():
    rec = FlightRecorder(capacity=8)
    obs.set_global_recorder(rec)
    for i in range(20):
        obs.record("member-state", node=f"n{i % 2}", status="ALIVE", i=i)
    assert len(rec) == 8
    assert rec.recorded == 20
    assert rec.dropped == 12
    dump = obs.flight_dump()
    assert [e["i"] for e in dump] == list(range(12, 20))   # oldest first
    assert [e["seq"] for e in dump] == list(range(13, 21))
    # filters compose: kind, node, last-N
    assert all(e["kind"] == "member-state" for e in dump)
    n0 = obs.flight_dump(node="n0")
    assert all(e["node"] == "n0" for e in n0) and len(n0) == 4
    assert [e["i"] for e in obs.flight_dump(node="n0", last=2)] == [16, 18]
    assert obs.flight_dump(kind="no-such-kind") == []


def test_flight_since_seq_incremental_poll():
    """The multi-node merge contract (ISSUE 9 satellite): every record
    carries a monotonic seq, ``last_seq`` is the resume cursor, and
    ``dump(since_seq=)`` returns only newer records — so a poller that
    missed ring-evicted overlap still merges streams in (time, seq)
    order without duplicates."""
    rec = FlightRecorder(capacity=8)
    obs.set_global_recorder(rec)
    for i in range(5):
        obs.record("member-state", i=i)
    cursor = rec.last_seq
    assert cursor == 5
    assert obs.flight_dump(since_seq=cursor) == []
    for i in range(5, 12):
        obs.record("member-state", i=i)
    fresh = obs.flight_dump(since_seq=cursor)
    assert [e["seq"] for e in fresh] == list(range(6, 13))
    # even after eviction ate part of the overlap, since_seq never
    # re-delivers already-seen records (seqs 1-4 evicted, 5 retained)
    retained = rec.dump()
    assert retained[0]["seq"] == 5
    assert all(e["seq"] > cursor for e in rec.dump(since_seq=cursor))
    # filters compose with the cursor
    assert rec.dump(kind="member-state", since_seq=10, last=1)[0]["seq"] == 12


# -- metrics sink satellites -------------------------------------------------


def test_histogram_empty_min_max_are_zero_not_inf():
    h = HistogramSummary()
    assert h.min == 0.0 and h.max == 0.0 and h.mean == 0.0
    assert h.percentile(50) == 0.0
    h.observe(3.0)
    h.observe(1.0)
    assert h.min == 1.0 and h.max == 3.0


def test_histogram_percentiles_from_sample_ring():
    h = HistogramSummary(ring_size=128)
    for v in range(1, 101):        # 1..100
        h.observe(float(v))
    assert h.percentile(50) == 50.0
    assert h.percentile(95) == 95.0
    assert h.percentile(99) == 99.0
    assert h.percentile(0) == 1.0
    assert h.percentile(100) == 100.0
    with pytest.raises(ValueError):
        h.percentile(101)


def test_empty_histogram_never_exports_inf():
    sink = metrics.global_sink()
    sink.histograms[("hollow.hist", ())]    # defaultdict: count == 0 entry
    text = prometheus_text()
    assert "Inf" not in text
    parsed = parse_prometheus_text(text)
    assert parsed[("hollow_hist_min", ())] == 0.0
    assert parsed[("hollow_hist_max", ())] == 0.0


# -- exporters ---------------------------------------------------------------


def test_prometheus_text_escaping_label_ordering_roundtrip():
    metrics.incr("serf.member.join", 2,
                 {"dc": 'us-"west"\\1', "az": "line1\nline2"})
    metrics.gauge("serf.queue.event", 5, {"node": "a"})
    for v in (1.0, 2.0, 3.0, 4.0):
        metrics.observe("serf.trace.span-ms", v, {"span": "swim.probe"})
    text = prometheus_text()

    # name sanitization + counter suffix
    assert "serf_member_join_total{" in text
    # label keys render in sorted order (the sink stores sorted label sets)
    line = next(ln for ln in text.splitlines()
                if ln.startswith("serf_member_join_total"))
    assert line.index('az="') < line.index('dc="')
    # escaping: backslash, double-quote, newline
    assert '\\"west\\"' in line and "\\n" in line and "\\\\1" in line

    parsed = parse_prometheus_text(text)   # raises on any malformed line
    labels = (("az", "line1\nline2"), ("dc", 'us-"west"\\1'))
    assert parsed[("serf_member_join_total", labels)] == 2.0
    assert parsed[("serf_queue_event", (("node", "a"),))] == 5.0
    q95 = ("serf_trace_span_ms",
           (("span", "swim.probe"), ("quantile", "0.95")))
    assert parsed[q95] == 4.0
    assert parsed[("serf_trace_span_ms_count",
                   (("span", "swim.probe"),))] == 4.0
    assert parsed[("serf_trace_span_ms_sum",
                   (("span", "swim.probe"),))] == 10.0


def test_parser_rejects_malformed_lines():
    with pytest.raises(ValueError):
        parse_prometheus_text("not a metric line at all }{")


def test_json_snapshot_bundles_all_three_surfaces():
    metrics.incr("serf.events")
    with span("serf.query"):
        pass
    obs.record("probe-failed", node="a", target="b")
    snap = obs.json_snapshot()
    assert snap["metrics"]["counters"]["serf.events"] == 1.0
    assert [s["name"] for s in snap["trace"]] == ["serf.query"]
    assert [e["kind"] for e in snap["flight"]] == ["probe-failed"]
    # histogram summaries carry the ring percentiles
    hist = snap["metrics"]["histograms"]['serf.trace.span-ms{span=serf.query}']
    assert hist["count"] == 1 and hist["p50"] == hist["max"]


# -- logging satellites ------------------------------------------------------


def test_setup_logging_idempotent_under_configured_root():
    parent = logging.getLogger(ROOT_LOGGER)
    before = list(parent.handlers)
    try:
        logging.basicConfig(level="WARNING")   # simulate pytest/app config
        l1 = setup_logging(level="DEBUG")
        l2 = setup_logging(level="INFO")
        assert l1 is l2 is parent
        ours = [h for h in parent.handlers if h not in before]
        assert len(ours) == 1                  # repeated calls: one handler
        assert parent.level == logging.INFO    # level re-applied
        assert setup_logging(env_var="SERF_TPU_NO_SUCH_VAR") is None
    finally:
        for h in [h for h in parent.handlers if h not in before]:
            parent.removeHandler(h)
        parent.setLevel(logging.NOTSET)


def test_get_logger_hangs_off_serf_tpu_tree():
    assert get_logger("memberlist").name == "serf_tpu.memberlist"
    assert get_logger("serf_tpu").name == "serf_tpu"
    assert get_logger("serf_tpu.codec.native").name == "serf_tpu.codec.native"
    assert get_logger("memberlist").parent.name == "serf_tpu"


# -- queue-depth gauges ------------------------------------------------------


def test_named_queue_emits_depth_gauges_and_flight_events():
    from serf_tpu.host.broadcast import Broadcast, TransmitLimitedQueue

    sink = metrics.global_sink()
    q = TransmitLimitedQueue(retransmit_mult=1, node_count_fn=lambda: 1,
                             name="intent")
    q.queue_broadcast(Broadcast(b"x" * 8, name="a"))
    q.queue_broadcast(Broadcast(b"y" * 8, name="b"))
    assert sink.gauge_value("serf.queue.intent") == 2
    # retransmit_mult=1 @ n=1 -> transmit limit 1: one drain retires all
    q.get_broadcasts(overhead=0, limit=1000)
    assert sink.gauge_value("serf.queue.intent") == 0
    retired = obs.flight_dump(kind="broadcast-retired")
    assert {e["subject"] for e in retired} == {"a", "b"}

    for i in range(6):
        q.queue_broadcast(Broadcast(b"z" * 8, name=f"m{i}"))
    q.prune(max_retained=2)
    assert sink.gauge_value("serf.queue.intent") == 2
    (ov,) = obs.flight_dump(kind="queue-overflow")
    assert ov["queue"] == "intent" and ov["dropped"] == 4

    # unnamed queues stay silent (no gauge family pollution)
    q2 = TransmitLimitedQueue(retransmit_mult=1, node_count_fn=lambda: 1)
    q2.queue_broadcast(Broadcast(b"q", name="c"))
    assert sink.gauge_value("serf.queue.None") is None


# -- device-plane dispatch timing --------------------------------------------


def test_dispatch_timer_compile_vs_steady_split():
    assert record_dispatch("op.x", 50.0, signature=(32, 64))[0] == "compile"
    assert record_dispatch("op.x", 1.0, signature=(32, 64))[0] == "steady"
    assert record_dispatch("op.x", 2.0, signature=(32, 64))[0] == "steady"
    # a new signature (shape change) honestly re-labels compile
    assert record_dispatch("op.x", 40.0, signature=(64, 64))[0] == "compile"
    with dispatch_timer("op.y"):
        pass
    summary = dispatch_summary()
    assert summary["op.x"]["compile_ms"] == pytest.approx(90.0)
    assert summary["op.x"]["steady_ms_mean"] == pytest.approx(1.5)
    assert summary["op.x"]["calls"] == 4
    assert summary["op.y"]["calls"] == 1
    sink = metrics.global_sink()
    assert sink.counter("serf.device.dispatch.calls", {"op": "op.x"}) == 4
    h = sink.histogram_summary("serf.device.dispatch-ms",
                               {"op": "op.x", "phase": "steady"})
    assert h.count == 2


def test_pallas_kernel_dispatches_are_timed():
    jnp = pytest.importorskip("jax.numpy")
    from serf_tpu.ops.round_kernels import merge_incoming, select_packets

    n, k, w = 32, 32, 1
    stamp = jnp.zeros((n, k), jnp.uint8)     # unpacked nibble flavor
    known = jnp.ones((n, w), jnp.uint32)
    alive = jnp.ones((n, 1), jnp.uint8)
    packets = select_packets(stamp, known, alive, limit_q=2, round_=0,
                             packed=False, k_facts=k)
    assert packets.shape == (n, w)
    merge_incoming(known, packets, alive, stamp, next_round=1,
                   packed=False, k_facts=k)

    summary = dispatch_summary()
    assert summary["ops.select_packets"]["calls"] == 1
    assert summary["ops.merge_incoming"]["calls"] == 1
    sink = metrics.global_sink()
    assert sink.counter("serf.device.dispatch.calls",
                        {"op": "ops.select_packets"}) == 1
    h = sink.histogram_summary(
        "serf.device.dispatch-ms",
        {"op": "ops.select_packets", "phase": "compile"})
    assert h is not None and h.count == 1


# -- device-plane model emitters ---------------------------------------------


def test_cluster_emitters_populate_device_metrics():
    jax = pytest.importorskip("jax")
    from serf_tpu.models.swim import (
        ClusterConfig,
        emit_cluster_metrics,
        make_cluster,
        run_cluster,
    )
    from serf_tpu.models.dissemination import (
        GossipConfig,
        K_USER_EVENT,
        inject_fact,
    )

    cfg = ClusterConfig(gossip=GossipConfig(n=64, k_facts=32),
                        push_pull_every=8)
    state = make_cluster(cfg, jax.random.key(0))
    g = inject_fact(state.gossip, cfg.gossip, subject=1, kind=K_USER_EVENT,
                    incarnation=0, ltime=1, origin=0)
    g = g._replace(alive=g.alive.at[7].set(False))
    state = state._replace(gossip=g)
    state = run_cluster(state, cfg, jax.random.key(1), num_rounds=8)

    vals = emit_cluster_metrics(state, cfg)
    sink = metrics.global_sink()
    # >= 3 device-plane names, asserted through the SINK (not the return)
    assert sink.gauge_value("serf.model.gossip.round") == 8.0
    assert sink.gauge_value("serf.model.gossip.alive") == 63.0
    assert sink.gauge_value("serf.model.gossip.coverage") > 0.0
    assert sink.gauge_value("serf.model.vivaldi.error") is not None
    assert sink.gauge_value("serf.model.swim.live-suspicions") is not None
    assert vals["serf.model.gossip.facts-valid"] >= 1.0
    # the full documented gossip/swim/vivaldi families all emitted
    families = [n for n in vals if n.startswith("serf.model.")]
    assert len(families) >= 10


def test_traffic_model_emitter():
    from serf_tpu.models.accounting import emit_traffic_metrics, round_traffic
    from serf_tpu.models.swim import flagship_config

    report = round_traffic(flagship_config(1024, 64))
    vals = emit_traffic_metrics(report)
    sink = metrics.global_sink()
    assert sink.gauge_value("serf.model.traffic.bytes-per-round") == \
        pytest.approx(report.total_bytes)
    assert sink.gauge_value("serf.model.traffic.ceiling-rps") > 0
    dom = report.dominator()
    assert sink.gauge_value("serf.model.traffic.plane-bytes",
                            {"plane": dom}) > 0
    assert vals["serf.model.traffic.bytes-per-round"] > 0


# -- metrics lint (tier-1 fast test) -----------------------------------------


def test_metrics_lint_readme_in_sync():
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "metrics_lint.py")],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# -- the full-picture scenario -----------------------------------------------


@pytest.mark.asyncio
async def test_join_query_leave_scenario_populates_observability():
    from serf_tpu.host import (
        EventSubscriber,
        LoopbackNetwork,
        QueryParam,
        Serf,
    )
    from serf_tpu.options import Options

    from serf_tpu.host import QueryEvent

    # gossip chatter emits wire spans continuously; a big ring keeps the
    # one-shot serf.query span in view for the assertions at the end
    obs.set_global_tracer(TraceBuffer(capacity=65536))
    net = LoopbackNetwork()
    sub = EventSubscriber()
    bsub = EventSubscriber()
    a = await Serf.create(net.bind("a"), Options.local(), "node-a",
                          subscriber=sub)
    b = await Serf.create(net.bind("b"), Options.local(), "node-b",
                          subscriber=bsub)
    c = await Serf.create(net.bind("c"), Options.local(), "node-c")
    try:
        await b.join("a")
        await c.join("a")

        async def converged():
            end = asyncio.get_running_loop().time() + 7.0
            while asyncio.get_running_loop().time() < end:
                if all(len(s.members()) == 3 for s in (a, b, c)):
                    return True
                await asyncio.sleep(0.02)
            return False

        assert await converged()
        await b.user_event("deploy", b"v2")

        async def responder():
            while True:
                ev = await bsub.next()
                if isinstance(ev, QueryEvent) and ev.name == "status":
                    await ev.respond(b"pong")
                    return

        task = asyncio.create_task(responder())
        resp = await a.query("status", b"ping", QueryParam(timeout=2.0))
        got = [r async for r in resp.responses()]
        task.cancel()
        assert got and got[0].payload == b"pong"
        await c.leave()

        st = a.stats()
        counters = st.metrics["counters"]
        # member lifecycle counters
        assert counters["serf.member.join"] >= 2.0
        assert counters.get("serf.queries", 0.0) >= 1.0
        assert counters.get("serf.query.responses", 0.0) >= 1.0
        # gossip byte histograms + queue gauges (docstring-promised names)
        hists = st.metrics["histograms"]
        assert any(h.startswith("serf.messages.sent") for h in hists)
        assert any(h.startswith("serf.query.rtt-ms") for h in hists)
        gauges = st.metrics["gauges"]
        for qname in ("serf.queue.intent", "serf.queue.event",
                      "serf.queue.query"):
            assert qname in gauges, (qname, sorted(gauges))

        # trace ring saw the hot paths
        span_names = {s["name"] for s in st.trace}
        assert "serf.broadcast.drain" in span_names
        assert "serf.query" in span_names
        assert "wire.encode" in span_names and "wire.decode" in span_names

        # flight recorder reconstructs the membership story
        transitions = [e for e in st.flight if e["kind"] == "member-state"]
        assert any(e["member"] == "node-b" and e["status"] == "ALIVE"
                   for e in transitions)
        swim_moves = [e for e in st.flight if e["kind"] == "swim-state"]
        assert any(e["member"] == "node-c" for e in swim_moves)

        # Prometheus export round-trips and carries the counters
        parsed = parse_prometheus_text(prometheus_text())
        assert parsed[("serf_member_join_total", ())] >= 2.0
        assert ("serf_queue_event", ()) in parsed
    finally:
        for s in (a, b, c):
            await s.shutdown()
