"""Host-plane MPMC pipeline + batched codec (host throughput rebuild).

Acceptance pins:

- the ORDERING CONTRACT: per-dependency-key FIFO is preserved under
  parallel application with randomized worker interleaving, and the
  lossless-subscriber guarantee stays intact (no drops, no contract
  violations) while cross-key events reorder freely;
- the run-to-completion inline fast path applies idle-chain events
  synchronously (zero queue-wait) and never reorders a key;
- entries carry their own enqueue timestamps (the age gauges can no
  longer skew — there is no parallel side-deque);
- the BATCH envelope + frame codec round-trips, fails closed on
  truncation, and the gossip drain actually packs it;
- the bounded decode memo returns the identical immutable message for
  repeated bytes and evicts FIFO;
- per-tenant fairness buckets isolate name classes on the admission
  plane.

A heavier randomized soak runs under ``-m slow``.
"""

import asyncio
import random

import pytest

from serf_tpu import codec
from serf_tpu.host.events import (
    EventSubscriber,
    MemberEvent,
    MemberEventType,
    UserEvent,
)
from serf_tpu.host.pipeline import (
    EventPipeline,
    dependency_key,
    name_class,
)
from serf_tpu.types.member import Member, Node
from serf_tpu.types.messages import (
    BatchMessage,
    JoinMessage,
    UserEventMessage,
    decode_message,
    decode_message_batch,
    decode_message_cached,
    encode_message,
    encode_message_batch,
)

pytestmark = pytest.mark.asyncio


def _spawn(coro, name):
    t = asyncio.create_task(coro, name=name)
    return t


def _member_event(node_id: str) -> MemberEvent:
    return MemberEvent(MemberEventType.JOIN,
                       (Member(Node(node_id)),))


# ---------------------------------------------------------------------------
# dependency keys / name classes
# ---------------------------------------------------------------------------


async def test_name_class_strips_one_numeric_tail():
    assert name_class("storm-17") == "storm"
    assert name_class("deploy") == "deploy"
    assert name_class("svc.web.42") == "svc.web"
    assert name_class("shard:9") == "shard"
    assert name_class("v2-rollout") == "v2-rollout"   # tail not numeric
    assert name_class("") == ""


async def test_dependency_key_rules():
    assert dependency_key(_member_event("n1")) == ("member", "n1")
    assert dependency_key(_member_event("n2")) == ("member", "n2")
    assert dependency_key(UserEvent(1, "storm-3", b"")) == ("user", "storm")
    assert dependency_key(object()) == ("misc", "")


# ---------------------------------------------------------------------------
# ordering contract: per-key FIFO under parallel application
# ---------------------------------------------------------------------------


async def _drive_interleaved(n_events: int, n_keys: int, seed: int,
                             workers: int = 4):
    """Offer ``n_events`` across ``n_keys`` tenants into a pipeline
    whose delivery awaits random sleeps — maximal worker interleaving —
    and return the delivered sequence."""
    rng = random.Random(seed)
    delivered = []
    done = asyncio.Event()

    async def deliver(ev):
        # random awaits force arbitrary interleaving between workers
        if rng.random() < 0.5:
            await asyncio.sleep(rng.random() * 0.002)
        delivered.append(ev)
        if len(delivered) == n_events:
            done.set()

    p = EventPipeline(spawn=_spawn, deliver=deliver, workers=workers)
    offered = []
    for i in range(n_events):
        k = rng.randrange(n_keys)
        ev = UserEvent(i, f"tenant{k}-{i}", b"")
        offered.append(ev)
        p.offer(ev)
        if rng.random() < 0.2:
            await asyncio.sleep(0)
    await asyncio.wait_for(done.wait(), 10.0)
    await p.aclose()
    return offered, delivered


async def test_per_key_fifo_preserved_under_randomized_interleave():
    offered, delivered = await _drive_interleaved(
        n_events=200, n_keys=8, seed=1234)
    assert len(delivered) == len(offered)          # nothing lost
    # per-key FIFO: each tenant's events arrive in offer order ...
    for k in range(8):
        want = [e.ltime for e in offered
                if name_class(e.name) == f"tenant{k}"]
        got = [e.ltime for e in delivered
               if name_class(e.name) == f"tenant{k}"]
        assert got == want, f"tenant{k} reordered"
    # ... while cross-key order DID interleave (the parallelism is real;
    # seeds are fixed, so this is deterministic)
    assert [e.ltime for e in delivered] != [e.ltime for e in offered]


async def test_lossless_subscriber_guarantee_under_parallel_application():
    """Parallel appliers pushing one lossless subscriber: every event
    arrives exactly once (no drop-oldest, no contract violation), with
    per-key order intact, even while the reader lags."""
    sub = EventSubscriber(maxsize=4, lossless=True)

    async def deliver(ev):
        await sub.push(ev)

    p = EventPipeline(spawn=_spawn, deliver=deliver, workers=4)
    n = 100
    for i in range(n):
        p.offer(UserEvent(i, f"t{i % 5}-{i}", b""))
    got = []
    while len(got) < n:
        got.append(await asyncio.wait_for(sub.next(), 5.0))
        await asyncio.sleep(0.001)                 # lagging reader
    assert sub.dropped == 0 and sub.lossless_violations == 0
    for k in range(5):
        seq = [e.ltime for e in got if name_class(e.name) == f"t{k}"]
        assert seq == sorted(seq)
    await p.aclose()


async def test_inline_fast_path_applies_synchronously():
    """Sync delivery + idle chain = run-to-completion at offer():
    applied before offer returns, zero pipeline depth, no task wake."""
    out = []
    p = EventPipeline(spawn=_spawn, deliver_sync=out.append, workers=2)
    ev = UserEvent(1, "ping-1", b"")
    p.offer(ev)
    assert out == [ev]                   # applied inline, synchronously
    assert p.depth() == 0 and p.inflight() == 0
    assert p.applied == 1
    await p.aclose()


async def test_monitor_gauges_cover_the_pr15_observability_gap():
    """The rebuilt seam's monitor-tick gauges (ISSUE 15 satellite):
    per-worker occupancy, the inline-vs-queued delivery split, the
    ready-ring depth, and the per-dependency-key chain length p50/max
    — emitted by ``gauge()``, never per event."""
    from serf_tpu.utils import metrics

    prev = metrics.global_sink()
    sink = metrics.MetricsSink()
    metrics.set_global_sink(sink)
    try:
        gate = asyncio.Event()

        async def deliver(ev):
            await gate.wait()

        p = EventPipeline(spawn=_spawn, deliver=deliver, workers=2,
                          node="t")
        # two hot keys with uneven chains + one worker-held entry each
        for i in range(5):
            p.offer(UserEvent(i, "storm-1", b""))
        p.offer(UserEvent(9, "deploy-1", b""))
        await asyncio.sleep(0.05)        # both workers block in deliver
        p.gauge()

        def g(name):
            return sink.gauges[(name, (("node", "t"),))]

        from serf_tpu.utils.metrics import percentile_of

        assert g("serf.pipeline.occupancy") == 1.0   # 2 of 2 workers busy
        assert g("serf.pipeline.chain-max") == 4.0   # storm minus in-service
        # chains at this instant: storm=4 queued, deploy=0 (in service)
        assert g("serf.pipeline.chain-p50") == percentile_of([0, 4], 50)
        assert g("serf.pipeline.ready-depth") == 0.0  # both keys in service
        gate.set()
        await asyncio.sleep(0.05)
        p.gauge()
        # all six applied through the queued path: inline share is 0
        assert p.applied == 6 and p.inline_applied == 0
        assert g("serf.pipeline.inline-share") == 0.0
        assert g("serf.pipeline.occupancy") == 0.0
        await p.aclose()

        # the sync-delivery pipeline takes the inline fast path -> 1.0
        p2 = EventPipeline(spawn=_spawn, deliver_sync=lambda ev: None,
                           workers=2, node="t2")
        p2.offer(UserEvent(1, "ping-1", b""))
        p2.gauge()
        assert p2.inline_applied == 1
        assert sink.gauges[("serf.pipeline.inline-share",
                            (("node", "t2"),))] == 1.0
        await p2.aclose()
    finally:
        metrics.set_global_sink(prev)


async def test_entries_carry_their_own_timestamps():
    """oldest_age reads the queued entries themselves; a wedged lossless
    delivery grows it, a drain zeroes it (no side-deque to skew)."""
    gate = asyncio.Event()

    async def deliver(ev):
        await gate.wait()

    p = EventPipeline(spawn=_spawn, deliver=deliver, workers=1)
    for i in range(3):
        p.offer(UserEvent(i, f"w-{i}", b""))
    await asyncio.sleep(0.05)           # worker picks one, blocks
    assert p.inflight() == 1
    assert p.depth() == 2
    assert p.oldest_age() > 0.02
    assert p.oldest_service_age() > 0.02
    gate.set()
    await asyncio.sleep(0.05)
    assert p.depth() == 0 and p.inflight() == 0
    assert p.oldest_age() == 0.0 and p.oldest_service_age() == 0.0
    await p.aclose()


async def test_member_events_serialize_per_member_not_globally():
    order = []

    async def deliver(ev):
        await asyncio.sleep(0.001)
        order.append(ev)

    p = EventPipeline(spawn=_spawn, deliver=deliver, workers=4)
    for i in range(10):
        p.offer(_member_event(f"n{i % 2}"))
    while len(order) < 10:
        await asyncio.sleep(0.01)
    for nid in ("n0", "n1"):
        seq = [e for e in order if e.members[0].node.id == nid]
        assert len(seq) == 5            # all delivered, per-member FIFO
    await p.aclose()


# ---------------------------------------------------------------------------
# batched codec
# ---------------------------------------------------------------------------


async def test_batch_envelope_roundtrip_and_fail_closed():
    raws = [encode_message(JoinMessage(7, "a")),
            encode_message(UserEventMessage(9, "deploy-1", b"x")),
            encode_message(UserEventMessage(10, "deploy-2", b"yy"))]
    batch = encode_message_batch(raws)
    assert decode_message_batch(batch) == raws
    # decode_message dispatches it as a BatchMessage too
    msg = decode_message(batch)
    assert isinstance(msg, BatchMessage) and list(msg.parts) == raws
    # framing overhead is 1-2 bytes/part + the envelope byte
    assert len(batch) <= 1 + sum(len(r) + 2 for r in raws)
    # truncation fails closed
    with pytest.raises(codec.DecodeError):
        decode_message_batch(batch[:-1])
    with pytest.raises(codec.DecodeError):
        decode_message_batch(b"")


async def test_decode_cache_returns_identical_immutable_message():
    from serf_tpu.types import messages as m

    raw = encode_message(UserEventMessage(42, "cache-1", b"p"))
    a = decode_message_cached(raw)
    b = decode_message_cached(raw)
    assert a is b                        # one decode served both
    assert a == decode_message(raw)      # and it is the right decode
    # PUSH_PULL (mutable dict field) is never cached
    from serf_tpu.types.messages import PushPullMessage
    pp_raw = encode_message(PushPullMessage(1, {"n": 2}))
    assert decode_message_cached(pp_raw) is not decode_message_cached(pp_raw)
    # bounded: FIFO eviction keeps the memo at its cap
    old_max = m._DECODE_CACHE_MAX
    m._DECODE_CACHE_MAX = 4
    try:
        m._decode_cache.clear()
        raws = [encode_message(UserEventMessage(i, f"e-{i}", b""))
                for i in range(8)]
        for r in raws:
            decode_message_cached(r)
        assert len(m._decode_cache) <= 4
        assert bytes(raws[-1]) in m._decode_cache      # newest retained
    finally:
        m._DECODE_CACHE_MAX = old_max
        m._decode_cache.clear()


async def test_gossip_drain_packs_batches_and_disseminates():
    """Two-node cluster: queued user-event broadcasts ride ONE BATCH
    envelope per gossip packet, and the peer still sees every event."""
    from serf_tpu.host import LoopbackNetwork, Serf
    from serf_tpu.options import Options
    from serf_tpu.utils import metrics

    def _ctr(name):
        sink = metrics.global_sink()
        return sum(v for (n, _l), v in sink.counters.items() if n == name)

    net = LoopbackNetwork()
    sub = EventSubscriber()
    a = await Serf.create(net.bind("a"), Options.local(), "ba")
    b = await Serf.create(net.bind("b"), Options.local(), "bb",
                          subscriber=sub)
    base = _ctr("serf.codec.batch")
    try:
        await b.join("a")
        for i in range(6):
            await a.user_event(f"batchy-{i}", b"", coalesce=False)
        deadline = asyncio.get_running_loop().time() + 5.0
        seen = set()
        while len(seen) < 6 and \
                asyncio.get_running_loop().time() < deadline:
            ev = sub.try_next()
            if ev is None:
                await asyncio.sleep(0.01)
            elif isinstance(ev, UserEvent):
                seen.add(ev.name)
        assert len(seen) == 6            # every event disseminated
        assert _ctr("serf.codec.batch") - base >= 1
        assert _ctr("serf.codec.batch-messages") >= 2
    finally:
        await a.shutdown()
        await b.shutdown()


# ---------------------------------------------------------------------------
# per-tenant fairness (admission plane)
# ---------------------------------------------------------------------------


async def test_coalesce_stage_buffer_is_bounded():
    """A flusher wedged on its output must not let the coalescer buffer
    grow without bound: past MAX_BUFFERED, feed() declines and the
    event takes the direct delivery path (backpressure re-engages)."""
    from serf_tpu.host.events import UserEventCoalescer
    from serf_tpu.host.pipeline import CoalesceStage

    blocked = asyncio.Event()

    async def wedged_out(ev):
        await blocked.wait()                 # the stalled consumer

    stage = CoalesceStage(UserEventCoalescer(), wedged_out,
                          coalesce_period=0.01, quiescent_period=0.01,
                          spawn=_spawn, name="wedge-test",
                          max_buffered=16)
    declined = 0
    for i in range(100):
        ev = UserEvent(i, f"cc-{i}", b"", coalesce=True)
        if not stage.feed(ev):
            declined += 1
        if i % 10 == 0:
            await asyncio.sleep(0.005)       # let the flusher wedge
    # the buffer stayed at its bound; overflow was declined to the
    # caller (which would deliver directly, engaging backpressure).
    # Total wedged memory is <= 2x the bound: the live buffer plus at
    # most ONE in-flight flush batch the single flusher task holds.
    assert stage.coalescer.pending() <= 16 + 1
    assert declined >= 100 - 2 * 16 - 2
    blocked.set()
    await asyncio.sleep(0.05)
    stage._task.cancel()


async def test_aclose_drains_inflight_deliveries():
    """aclose() must not cancel a worker mid-delivery when the intake
    happens to be empty: everything offered before close is applied."""
    delivered = []

    async def deliver(ev):
        await asyncio.sleep(0.02)        # in-flight when aclose arrives
        delivered.append(ev)

    p = EventPipeline(spawn=_spawn, deliver=deliver, workers=2)
    p.offer(UserEvent(1, "a-1", b""))
    p.offer(UserEvent(2, "b-1", b""))
    await asyncio.sleep(0.005)           # both picked up, both awaiting
    await p.aclose()
    assert len(delivered) == 2


async def test_global_rate_shed_refunds_tenant_token():
    """Fairness both ways: a request shed by the GLOBAL bucket must not
    leave the tenant's own budget drained."""
    from serf_tpu.host import LoopbackNetwork, OverloadError, Serf
    from serf_tpu.options import Options

    net = LoopbackNetwork()
    s = await Serf.create(
        net.bind("t9"),
        Options.local(user_event_rate=0.001, user_event_burst=1,
                      tenant_event_rate=0.001, tenant_event_burst=2),
        "t9")
    try:
        await s.user_event("quiet-1", b"")       # takes the 1 global token
        for _ in range(3):
            with pytest.raises(OverloadError) as ei:
                await s.user_event("quiet-2", b"")
            # always the GLOBAL bucket shedding — the tenant token was
            # refunded each time, so "tenant" never becomes the reason
            assert ei.value.reason == "rate"
        bucket = s._admission._tenants[("user_event", "quiet")]
        assert bucket.tokens >= 1.0
    finally:
        await s.shutdown()


async def test_tenant_buckets_isolate_name_classes():
    from serf_tpu.host import LoopbackNetwork, OverloadError, Serf
    from serf_tpu.options import Options

    net = LoopbackNetwork()
    s = await Serf.create(
        net.bind("t0"),
        Options.local(tenant_event_rate=0.001, tenant_event_burst=2),
        "t0")
    try:
        # tenant "noisy": two tokens, then shed with reason `tenant`
        await s.user_event("noisy-1", b"")
        await s.user_event("noisy-2", b"")
        with pytest.raises(OverloadError) as ei:
            await s.user_event("noisy-3", b"")
        assert ei.value.reason == "tenant"
        # a DIFFERENT name class keeps its full budget
        await s.user_event("quiet-1", b"")
        await s.user_event("quiet-2", b"")
    finally:
        await s.shutdown()


# ---------------------------------------------------------------------------
# soak (slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow
async def test_ordering_contract_soak_heavy():
    """5k events × 16 tenants × 8 workers × aggressive random awaits:
    per-key FIFO and zero loss must hold at an order of magnitude more
    interleaving pressure."""
    offered, delivered = await _drive_interleaved(
        n_events=5000, n_keys=16, seed=99, workers=8)
    assert len(delivered) == len(offered)
    for k in range(16):
        want = [e.ltime for e in offered
                if name_class(e.name) == f"tenant{k}"]
        got = [e.ltime for e in delivered
               if name_class(e.name) == f"tenant{k}"]
        assert got == want
