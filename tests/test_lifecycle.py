"""Message lifecycle ledger (ISSUE 12): stage clocks, sampling,
attribution, slow-message flight events, queue-age gauges, the
stage-latency SLO rows, and the host-plane bench bands.

Acceptance pins:

- the ledger attributes >= 90% of sampled end-to-end latency to named
  stages on a real loopback cluster (the wiring-completeness pin, the
  host twin of the roundprof byte-attribution pin);
- 1-in-N sampling costs < 5% of loopback ingest throughput (measurement
  must never become the load — the PR-5 health-gate rule);
- slow-message flight events fire with full stage breakdowns under the
  slow-consumer plan;
- the `apply-stage-p99` / `queue-wait-share` SLO rows judge from the
  run's ledger snapshot (and skip green when nothing was sampled);
- BASELINE.json carries host_plane.* bands and the regression gate
  (the `--strict` exit-4 decision input) flags a violating host run.
"""

import asyncio
import json
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from serf_tpu.host.broadcast import Broadcast, TransmitLimitedQueue  # noqa: E402
from serf_tpu.obs import flight, lifecycle, slo  # noqa: E402
from serf_tpu.utils import metrics  # noqa: E402


@pytest.fixture
def fresh_obs():
    """Fresh global sink + flight recorder + lifecycle ledger."""
    old_sink = metrics.global_sink()
    old_rec = flight.global_recorder()
    metrics.set_global_sink(metrics.MetricsSink())
    flight.set_global_recorder(flight.FlightRecorder())
    old_led = lifecycle.set_global_ledger(lifecycle.LifecycleLedger())
    yield metrics.global_sink(), flight.global_recorder()
    metrics.set_global_sink(old_sink)
    flight.set_global_recorder(old_rec)
    lifecycle.set_global_ledger(old_led)


# ---------------------------------------------------------------------------
# unit: clock + ledger mechanics
# ---------------------------------------------------------------------------


def test_stage_clock_chains_and_accumulates():
    clk = lifecycle.StageClock("UserEventMessage", "local")
    clk.stamp("apply")
    clk.stamp("queue-wait")
    clk.stamp("queue-wait")            # repeated stamps accumulate
    assert set(clk.stages) == {"apply", "queue-wait"}
    assert all(v >= 0.0 for v in clk.stages.values())
    # the chain covers t0..last exactly
    assert sum(clk.stages.values()) == pytest.approx(clk.last - clk.t0,
                                                     abs=1e-6)


def test_sampling_cadence_and_always_on_counters(fresh_obs):
    sink, _rec = fresh_obs
    led = lifecycle.LifecycleLedger(sample_n=3)
    clocks = [led.begin("local", kind="X") for _ in range(9)]
    assert sum(c is not None for c in clocks) == 3
    assert led.seen == 9 and led.sampled == 3
    # always-on counter counts EVERY message, sampled or not
    assert sink.counter("serf.lifecycle.messages",
                        {"origin": "local"}) == 9.0
    assert sink.counter("serf.lifecycle.sampled") == 3.0
    # sample_n=0: counters on, clocks off
    led0 = lifecycle.LifecycleLedger(sample_n=0)
    assert all(led0.begin("local") is None for _ in range(5))
    assert led0.seen == 5 and led0.sampled == 0


def test_remote_clock_backdates_to_packet_timestamp(fresh_obs):
    led = lifecycle.LifecycleLedger(sample_n=1)
    t_recv = time.monotonic()
    time.sleep(0.01)
    led.note_packet(t_recv)
    clk = led.begin("remote")
    assert clk is not None and clk.t0 == t_recv
    # wire+SWIM decode time landed in the transport stage
    assert clk.stages["transport"] >= 0.01


def test_attach_ride_finish_and_slow_event(fresh_obs):
    _sink, rec = fresh_obs

    class Ev:                                    # any attribute-capable event
        pass

    led = lifecycle.LifecycleLedger(sample_n=1, slow_ms=0.0)
    led.begin("local", kind="UserEventMessage")
    ev = Ev()
    led.attach_current(ev)                       # stamps `apply`, rides ev
    led.event_stamp(ev, "queue-wait")
    led.event_finish(ev, "tee")
    assert led.finished == 1 and led.delivered == 1
    # double-finish is a no-op
    led.event_finish(ev, "tee")
    assert led.finished == 1
    # slow_ms=0 -> the message must have fired slow-message with the
    # full per-stage breakdown
    slow = rec.dump(kind="slow-message")
    assert len(slow) == 1
    assert set(slow[0]["stages_ms"]) == {"apply", "queue-wait", "tee"}
    assert slow[0]["message"] == "UserEventMessage"
    snap = led.snapshot()
    assert snap["slow"] == 1 and snap["attributed_frac"] == 1.0
    assert {r["stage"] for r in snap["stages"]} == \
        {"apply", "queue-wait", "tee"}


def test_shed_and_discard_paths(fresh_obs):
    led = lifecycle.LifecycleLedger(sample_n=1, slow_ms=1e9)

    class Ev:
        pass

    led.begin("local")
    led.attach_current(Ev(), shed=True)          # inbox shed: finish now
    assert led.shed == 1 and led.finished == 1
    led.begin("remote")
    led.discard_current()                        # undecodable: no aggregation
    assert led.finished == 1
    # finish_current attributes the handler residue to `apply`
    led.begin("remote", kind="LeaveMessage")
    led.finish_current()
    assert led.finished == 2
    snap = led.snapshot()
    assert lifecycle.format_waterfall(snap)      # renders without raising


def test_queue_oldest_age():
    q = TransmitLimitedQueue(2, lambda: 4, name=None)
    assert q.oldest_age() == 0.0
    q.queue_broadcast(Broadcast(b"a"))
    time.sleep(0.02)
    q.queue_broadcast(Broadcast(b"b"))
    now = time.monotonic()
    assert q.oldest_age(now) >= 0.02
    # the age tracks the OLDEST item, not the newest
    assert q.oldest_age(now) == pytest.approx(
        now - min(b.enqueued_at for b in q._items), abs=1e-6)


# ---------------------------------------------------------------------------
# loopback: attribution self-check + queue-age gauges
# ---------------------------------------------------------------------------


async def _loopback_cluster(n, led, **opt_kw):
    from serf_tpu.host import LoopbackNetwork, Serf
    from serf_tpu.host.events import EventSubscriber
    from serf_tpu.options import Options

    lifecycle.set_global_ledger(led)
    net = LoopbackNetwork()
    nodes = []
    for i in range(n):
        nodes.append(await Serf.create(
            net.bind(f"n{i}"), Options.local(**opt_kw), f"n{i}",
            subscriber=EventSubscriber()))
    for s in nodes[1:]:
        await s.join("n0")
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if all(len(s.members()) == n for s in nodes):
            break
        await asyncio.sleep(0.02)
    return nodes


async def test_attribution_pin_on_loopback_cluster(fresh_obs):
    """THE acceptance pin: >= 90% of sampled end-to-end latency lands in
    named stages on a real cluster (remote gossip + local origins, full
    delivery through the tee)."""
    led = lifecycle.LifecycleLedger(sample_n=1, slow_ms=1e9)
    nodes = await _loopback_cluster(3, led)
    try:
        for k in range(15):
            await nodes[k % 3].user_event(f"ev-{k}", b"x", coalesce=False)
        await asyncio.sleep(0.4)                 # let deliveries complete
        snap = led.snapshot()
        assert snap["finished"] >= 15
        assert snap["delivered"] >= 10           # tee-complete deliveries
        assert snap["attributed_frac"] is not None
        assert snap["attributed_frac"] >= 0.9
        stages = {r["stage"] for r in snap["stages"]}
        # every named stage observed: remote path (transport/decode/
        # dispatch) and delivery path (apply/queue-wait/tee)
        assert stages == set(lifecycle.STAGES)
        assert snap["owner_p50"] in lifecycle.STAGES
        json.dumps(snap)                         # artifact-serializable
    finally:
        for s in nodes:
            await s.shutdown()


async def test_queue_age_gauges_on_monitor_tick(fresh_obs):
    sink, _rec = fresh_obs
    led = lifecycle.LifecycleLedger(sample_n=0)
    nodes = await _loopback_cluster(2, led)
    try:
        await nodes[0].user_event("age-probe", b"x", coalesce=False)
        # Options.local health_interval = 0.25s: wait out one tick
        await asyncio.sleep(0.6)
        names = {n for (n, _l) in sink.gauges
                 if n.startswith("serf.queue.age.")}
        assert names == {f"serf.queue.age.{q}" for q in
                         ("intent", "event", "query", "inbox", "tee")}
        # live queues drain fast: ages are sane, not runaway
        for (n, _l), v in sink.gauges.items():
            if n.startswith("serf.queue.age."):
                assert 0.0 <= v < 60.0
    finally:
        for s in nodes:
            await s.shutdown()


# ---------------------------------------------------------------------------
# overhead: sampling must never become the load
# ---------------------------------------------------------------------------


async def test_sampling_overhead_under_5_percent(fresh_obs):
    """Ingest throughput with 1-in-32 sampling vs clocks-off, driven
    synchronously through the real hot path (notify_message: decode +
    handler + emit).  Measurement discipline for a noisy shared
    container: within each session, small off/on chunks alternate in
    ABBA order (fresh ltime/name blocks per chunk, so every chunk does
    identical accept+emit work and neither config systematically runs
    on a larger engine state); the session verdict is the MEDIAN of
    pairwise chunk ratios (a preempted chunk is an outlier the median
    ignores), and the final verdict takes the best of several sessions.
    The contract: sampling costs <5% throughput."""
    import statistics

    from serf_tpu.host import LoopbackNetwork, Serf
    from serf_tpu.options import Options
    from serf_tpu.types.messages import UserEventMessage, encode_message

    net = LoopbackNetwork()
    chunk, npairs, sessions = 150, 20, 4

    async def session(rep):
        node = await Serf.create(net.bind(f"m{rep}"), Options.local(),
                                 f"m{rep}")
        deliver = node._delegate.notify_message
        led_off = lifecycle.LifecycleLedger(sample_n=0)
        led_on = lifecycle.LifecycleLedger(sample_n=32, slow_ms=1e9)
        base = 1000

        def run_chunk(led):
            nonlocal base
            raws = [encode_message(UserEventMessage(
                base + i, f"ov-{rep}-{base}-{i}", b"p", False))
                for i in range(chunk)]
            base += chunk + 10
            lifecycle.set_global_ledger(led)
            t0 = time.perf_counter()
            for raw in raws:
                deliver(raw)
            return time.perf_counter() - t0

        run_chunk(led_off), run_chunk(led_on)    # warm both paths
        ratios = []
        for p in range(npairs):
            if p % 2:                            # ABBA ordering
                on, off = run_chunk(led_on), run_chunk(led_off)
            else:
                off, on = run_chunk(led_off), run_chunk(led_on)
            ratios.append(on / off)
        await node.shutdown()
        return statistics.median(ratios)

    medians = [await session(r) for r in range(sessions)]
    overhead = min(medians) - 1.0
    assert overhead < 0.05, (
        f"1-in-32 sampling cost {overhead:.1%} of ingest throughput "
        f"(session medians: {[round(m, 3) for m in medians]})")


# ---------------------------------------------------------------------------
# SLO rows + chaos integration
# ---------------------------------------------------------------------------


def test_stage_slo_rows_judge_from_ledger_snapshot(fresh_obs):
    from serf_tpu.faults.plan import named_plan

    plan = named_plan("self-check")

    class R:
        settle_convergence_s = 0.1
        settle_converged = True
        false_dead = 0
        load = None
        lifecycle = {
            "queue_wait_share": 0.3,
            "stages": [
                {"stage": "apply", "count": 40, "mean_ms": 0.1,
                 "p50_ms": 0.05, "p99_ms": 1.5, "share": 0.1},
            ],
        }

    verdicts = {v.slo: v for v in slo.judge_host_run(R(), plan)}
    assert verdicts["apply-stage-p99"].ok
    assert verdicts["apply-stage-p99"].value == pytest.approx(1.5)
    assert verdicts["queue-wait-share"].value == pytest.approx(0.3)

    class Bare:                         # no ledger ran: skipped, green
        settle_convergence_s = 0.1
        settle_converged = True
        false_dead = 0
        load = None

    verdicts = {v.slo: v for v in slo.judge_host_run(Bare(), plan)}
    assert verdicts["apply-stage-p99"].skipped
    assert verdicts["queue-wait-share"].skipped


async def test_slow_consumer_plan_fires_slow_messages(fresh_obs):
    """Acceptance, re-anchored onto the MPMC pipeline: the rebuilt
    delivery path applies drop-oldest subscribers inline, so the
    slow-consumer PLAN no longer produces multi-ms deliveries (that is
    the rebuild's point) — the plan run now pins invariants + the SLO
    rows judging from the scoped ledger, and the slow-message machinery
    is pinned where slowness still genuinely exists: a wedged LOSSLESS
    subscriber backpressuring the pipeline's async path."""
    from serf_tpu.faults.host import run_host_plan
    from serf_tpu.faults.plan import named_plan
    from serf_tpu.host import EventSubscriber, LoopbackNetwork, Serf
    from serf_tpu.options import Options

    result = await run_host_plan(named_plan("slow-consumer"),
                                 lifecycle_slow_ms=2.0)
    assert result.report.ok
    lc = result.lifecycle
    assert lc is not None and lc["sampled"] > 0
    # the run's ledger was scoped: the global ledger is untouched
    assert lifecycle.global_ledger().seen == 0
    # the stage-latency SLO rows judge from the run's snapshot
    verdicts = {v.slo: v
                for v in slo.judge_host_run(result,
                                            named_plan("slow-consumer"))}
    assert not verdicts["apply-stage-p99"].skipped
    assert not verdicts["queue-wait-share"].skipped

    # slow-message flight events still fire, with full breakdowns,
    # where delivery is genuinely slow: a lossless consumer that only
    # drains after a wedge (every sampled message, slow_ms=2)
    led = lifecycle.set_global_ledger(
        lifecycle.LifecycleLedger(sample_n=1, slow_ms=2.0))
    try:
        net = LoopbackNetwork()
        sub = EventSubscriber(maxsize=1, lossless=True)
        s = await Serf.create(net.bind("sl0"), Options.local(), "sl0",
                              subscriber=sub)
        try:
            for i in range(6):
                await s.user_event(f"wedge-{i}", b"", coalesce=False)
            await asyncio.sleep(0.05)        # workers block on the push
            while sub.try_next() is not None:
                await asyncio.sleep(0.01)    # slow drain past slow_ms
        finally:
            await s.shutdown()
        run_led = lifecycle.global_ledger()
        assert run_led.slow > 0
    finally:
        lifecycle.set_global_ledger(led)
    slow = flight.flight_dump(kind="slow-message")
    assert slow, "no slow-message flight events from the wedged reader"
    for e in slow[-3:]:
        assert e["e2e_ms"] > e["threshold_ms"]
        assert e["stages_ms"]                     # full stage breakdown
        assert set(e["stages_ms"]) <= set(lifecycle.STAGES)


# ---------------------------------------------------------------------------
# bench host-plane bands (the regression gate guards the host forever)
# ---------------------------------------------------------------------------


def test_host_plane_bands_committed_and_gate_flags_regression():
    bands = json.loads((REPO / "BASELINE.json").read_text())["bands"]
    cpu = bands["cpu"]
    assert "host_plane.events_per_sec" in cpu
    assert "host_plane.queries_per_sec" in cpu
    assert "host_plane.lifecycle.attributed_frac" in cpu
    # a healthy capture passes...
    good = {"host_plane": {
        "events_per_sec": 150.0, "queries_per_sec": 80.0,
        "lifecycle": {"attributed_frac": 1.0,
                      "e2e": {"p99_ms": 30.0}}}}
    gate = slo.score_bench(good, bands, "cpu")
    assert not [v for v in gate["violations"]
                if v.startswith("host_plane.")]
    # ...a collapsed host plane (or broken stage wiring) trips the gate
    # — the exact condition under which `bench.py --strict` exits 4
    bad = {"host_plane": {
        "events_per_sec": 1.0, "queries_per_sec": 80.0,
        "lifecycle": {"attributed_frac": 0.5,
                      "e2e": {"p99_ms": 30.0}}}}
    gate = slo.score_bench(bad, bands, "cpu")
    assert not gate["ok"]
    assert "host_plane.events_per_sec" in gate["violations"]
    assert "host_plane.lifecycle.attributed_frac" in gate["violations"]


def test_bench_strict_exits_4_on_host_band_violation(monkeypatch):
    """The --strict contract, exercised against the committed bands: a
    violating gate exits 4, a green gate (or non-strict run) exits 0."""
    import bench

    bands = json.loads((REPO / "BASELINE.json").read_text())["bands"]
    bad_gate = slo.score_bench(
        {"host_plane": {"events_per_sec": 1.0}}, bands, "cpu")
    assert not bad_gate["ok"]
    monkeypatch.setenv("SERF_TPU_BENCH_STRICT", "1")
    assert bench.strict_gate_rc(bad_gate) == 4
    assert bench.strict_gate_rc({"ok": True, "violations": []}) == 0
    monkeypatch.delenv("SERF_TPU_BENCH_STRICT")
    assert bench.strict_gate_rc(bad_gate) == 0    # warn-only default
