"""Overload-protection plane (ISSUE 5): bounded everything, priority
shedding, admission control.

Pins:

- ``TransmitLimitedQueue`` byte budgets: most-transmitted-first shedding,
  exact byte bookkeeping through queue/drain/prune/invalidate, and the
  never-shed contract for membership queues;
- ingress admission: token buckets + health floor raise ``OverloadError``
  and the accounting (admitted + shed == offered) closes on the engine's
  own counters;
- responder-side query fast-fail: an overloaded node answers
  ``QueryFlag.OVERLOADED`` instead of timing out silently;
- the single periodic query sweep: no per-query expiry tasks, the
  handler map is TTL-reclaimed and capacity-bounded with
  earliest-deadline eviction;
- bounded event inbox: user events shed at the cap, member events never;
- slow-reader EventChannel under sustained push: memory stays bounded,
  the tee gauge tracks, and the lossless-violation guard fires exactly
  when contracted (heavy soak variants are ``slow``);
- per-peer send pacing at the transport seam.
"""

import asyncio

import pytest

from serf_tpu.host.admission import (
    AdmissionController,
    OverloadError,
    PeerPacer,
    TokenBucket,
)
from serf_tpu.host.broadcast import Broadcast, TransmitLimitedQueue
from serf_tpu.host.events import EventSubscriber, MemberEvent, MemberEventType, UserEvent
from serf_tpu.host.serf import Serf
from serf_tpu.host.transport import LoopbackNetwork
from serf_tpu.options import Options
from serf_tpu.utils import metrics

pytestmark = pytest.mark.asyncio


def _counter(name, **want_labels):
    sink = metrics.global_sink()
    total = 0.0
    for (n, labels), v in sink.counters.items():
        if n != name:
            continue
        ld = dict(labels or ())
        if all(ld.get(k) == v2 for k, v2 in want_labels.items()):
            total += v
    return total


# ---------------------------------------------------------------------------
# token bucket / pacer units
# ---------------------------------------------------------------------------


def test_token_bucket_limits_and_refills():
    now = [0.0]
    b = TokenBucket(rate=10.0, burst=2.0, clock=lambda: now[0])
    assert b.try_take() and b.try_take()       # burst
    assert not b.try_take()                    # empty
    now[0] += 0.1                              # +1 token
    assert b.try_take()
    assert not b.try_take()
    now[0] += 10.0                             # refill clamps at burst
    assert b.try_take() and b.try_take() and not b.try_take()
    # rate <= 0 admits everything
    free = TokenBucket(rate=0.0, burst=1.0)
    assert all(free.try_take() for _ in range(100))


def test_peer_pacer_is_per_destination_and_bounded():
    p = PeerPacer(rate=0.0001, burst=2.0)      # ~never refills in-test
    assert p.admit("a") and p.admit("a")
    assert not p.admit("a")                    # a's bucket empty
    assert p.admit("b")                        # b unaffected
    # the peer map itself is bounded (stalest eviction, no unbounded map)
    from serf_tpu.host import admission
    for i in range(admission.PACER_MAX_PEERS + 10):
        p.admit(f"peer-{i}")
    assert len(p._peers) <= admission.PACER_MAX_PEERS


async def test_memberlist_send_pacing_drops_over_rate():
    from dataclasses import replace

    net = LoopbackNetwork()
    opts = Options.local()
    opts = opts.replace(memberlist=replace(
        opts.memberlist, peer_send_rate=5.0, peer_send_burst=2))
    a = await Serf.create(net.bind("p0"), opts, "p0")
    b = await Serf.create(net.bind("p1"), Options.local(), "p1")
    try:
        await b.join("p0")
        base = _counter("serf.overload.paced_dropped")
        for _ in range(30):
            await a.memberlist.send("p1", b"x")
        assert _counter("serf.overload.paced_dropped") > base
        # the SWIM plane is NEVER paced: with a's user bucket long
        # drained, probes/acks still flow and membership stays intact
        await asyncio.sleep(0.5)    # several probe intervals
        assert a.num_members() == 2 and b.num_members() == 2
        assert all(m.status.name == "ALIVE" for m in a.members())
    finally:
        await a.shutdown()
        await b.shutdown()


# ---------------------------------------------------------------------------
# byte-bounded broadcast queues
# ---------------------------------------------------------------------------


def test_queue_byte_budget_sheds_most_transmitted_first():
    q = TransmitLimitedQueue(4, lambda: 100, name="t-shed",
                             max_bytes=100)
    old = Broadcast(b"x" * 40)
    q.queue_broadcast(old)
    old.transmits = 3                      # well-disseminated
    mid = Broadcast(b"y" * 40)
    q.queue_broadcast(mid)
    mid.transmits = 1
    assert q.bytes() == 80
    fresh = Broadcast(b"z" * 40)
    q.queue_broadcast(fresh)               # 120 > 100: shed
    assert q.bytes() <= 100
    msgs = [b.msg for b in q._items]
    assert fresh.msg in msgs               # freshest survives
    assert old.msg not in msgs             # most-transmitted went first
    assert q.shed == 1 and q.shed_bytes == 40
    assert _counter("serf.overload.queue_shed", queue="t-shed") >= 1


def test_queue_byte_bookkeeping_through_drain_prune_invalidate():
    q = TransmitLimitedQueue(1, lambda: 1, name="t-bytes")
    for i in range(4):
        q.queue_broadcast(Broadcast(b"m" * 10, name=f"s{i}"))
    assert q.bytes() == 40
    q.queue_broadcast(Broadcast(b"mm" * 10, name="s0"))  # invalidates s0
    assert q.bytes() == 30 + 20
    # retransmit limit 1 at n=1: one drain retires what it sends
    q.get_broadcasts(0, 1000)
    assert q.bytes() == 0 and len(q) == 0
    for i in range(4):
        q.queue_broadcast(Broadcast(b"m" * 10))
    q.prune(1)
    assert q.bytes() == 10 and len(q) == 1


def test_membership_queue_never_sheddable():
    with pytest.raises(ValueError):
        TransmitLimitedQueue(4, lambda: 1, max_bytes=10, sheddable=False)
    q = TransmitLimitedQueue(4, lambda: 1, sheddable=False)
    for i in range(100):
        q.queue_broadcast(Broadcast(b"x" * 100))
    assert len(q) == 100                   # no byte budget, nothing shed
    assert q.shed == 0


# ---------------------------------------------------------------------------
# ingress admission
# ---------------------------------------------------------------------------


async def test_user_event_rate_limit_sheds_and_accounts():
    net = LoopbackNetwork()
    opts = Options.local(user_event_rate=5.0, user_event_burst=3)
    s = await Serf.create(net.bind("a0"), opts, "a0")
    base_adm = _counter("serf.overload.ingress_admitted", op="user_event")
    base_shed = _counter("serf.overload.ingress_shed", op="user_event")
    try:
        offered, admitted, shed = 20, 0, 0
        for i in range(offered):
            try:
                await s.user_event(f"e{i}", b"x", coalesce=False)
                admitted += 1
            except OverloadError as e:
                assert e.op == "user_event" and e.reason == "rate"
                shed += 1
        assert admitted >= 3               # the burst got through
        assert shed > 0                    # the rest was shed
        assert admitted + shed == offered
        # the engine's own counters close the same accounting
        adm_d = _counter("serf.overload.ingress_admitted",
                         op="user_event") - base_adm
        shed_d = _counter("serf.overload.ingress_shed",
                          op="user_event") - base_shed
        assert adm_d == admitted and shed_d == shed
    finally:
        await s.shutdown()


async def test_health_floor_sheds_ingress_and_internal_queries_exempt():
    net = LoopbackNetwork()
    opts = Options.local(admission_min_health=100)
    s = await Serf.create(net.bind("h0"), opts, "h0")
    try:
        # healthy node (score 100): admitted
        await s.user_event("ok", b"", coalesce=False)
        # saturate the loop-lag component -> score < 100 -> shed
        s._loop_lag_ewma_ms = 1e6
        s._admission._health_at = -1e9     # invalidate the gate's cache
        with pytest.raises(OverloadError) as ei:
            await s.user_event("no", b"", coalesce=False)
        assert ei.value.reason == "health"
        with pytest.raises(OverloadError):
            await s.query("user-query", b"")
        # internal control queries bypass admission: the stats plane must
        # work EXACTLY when the node is overloaded
        resp = await s.query("_serf_ping", b"")
        assert resp is not None
    finally:
        await s.shutdown()


async def test_responder_fast_fails_overloaded_query():
    net = LoopbackNetwork()
    a = await Serf.create(net.bind("q0"), Options.local(), "q0")
    b = await Serf.create(net.bind("q1"),
                          Options.local(admission_min_health=100), "q1")
    base_ff = _counter("serf.overload.query_fastfail")
    try:
        await b.join("q0")
        # wedge b: health floor trips its responder-side self-awareness
        b._loop_lag_ewma_ms = 1e6
        b._admission._health_at = -1e9
        from serf_tpu.host.query import QueryParam
        resp = await a.query("who-is-there", b"", QueryParam(timeout=0.5))
        await asyncio.sleep(0.3)
        assert "q1" in resp.overloaded_responders
        assert _counter("serf.overload.query_fastfail") > base_ff
        assert _counter("serf.overload.remote_overloaded") >= 1
    finally:
        await a.shutdown()
        await b.shutdown()


# ---------------------------------------------------------------------------
# query handler map: single sweep, bounded capacity
# ---------------------------------------------------------------------------


async def test_query_sweep_replaces_per_query_tasks():
    net = LoopbackNetwork()
    s = await Serf.create(net.bind("s0"), Options.local(), "s0")
    try:
        from serf_tpu.host.query import QueryParam
        for i in range(5):
            await s.query(f"q{i}", b"", QueryParam(timeout=0.05))
        # a query storm is NOT a task storm: no per-query expiry tasks
        names = [t.get_name() for t in asyncio.all_tasks()]
        assert not any("serf-query-expire" in n for n in names)
        assert len(s._query_responses) == 5
        # the single periodic sweep reclaims them after the deadline
        await asyncio.sleep(0.4)           # local sweep interval is 0.1s
        assert len(s._query_responses) == 0
    finally:
        await s.shutdown()


async def test_query_responses_capacity_evicts_earliest_deadline():
    net = LoopbackNetwork()
    s = await Serf.create(net.bind("c0"),
                          Options.local(max_query_responses=3), "c0")
    base = _counter("serf.overload.query_responses_shed")
    try:
        from serf_tpu.host.query import QueryParam
        resps = [await s.query(f"q{i}", b"", QueryParam(timeout=5.0))
                 for i in range(6)]
        assert len(s._query_responses) <= 3
        assert _counter("serf.overload.query_responses_shed") - base >= 3
        # the evicted handlers were CLOSED (explicit, not leaked)
        assert sum(1 for r in resps if r._closed) >= 3
    finally:
        await s.shutdown()


# ---------------------------------------------------------------------------
# bounded event inbox
# ---------------------------------------------------------------------------


async def test_event_inbox_sheds_user_events_never_member_events():
    # a LOSSLESS subscriber keeps delivery on the pipeline's ASYNC path
    # (no run-to-completion inline fast path), so a synchronous burst
    # genuinely fills the bounded intake — the shed semantics under test
    net = LoopbackNetwork()
    sub = EventSubscriber(maxsize=1, lossless=True)
    s = await Serf.create(net.bind("i0"),
                          Options.local(event_inbox_max=8), "i0",
                          subscriber=sub)
    base = _counter("serf.overload.event_shed")
    try:
        # let the pipeline apply the startup self-join event
        while s.pipeline_depth():
            await asyncio.sleep(0.01)
        while sub.try_next() is not None:
            pass
        # synchronous burst: the applier workers get no loop turns, so
        # the intake genuinely fills
        for i in range(50):
            s._emit(UserEvent(i, f"u{i}", b""))
        assert s.pipeline_depth() <= 8
        shed = _counter("serf.overload.event_shed") - base
        assert shed == 50 - 8
        # membership state is NEVER shed, even over the cap
        me = MemberEvent(MemberEventType.JOIN, (s.local_member(),))
        s._emit(me)
        assert s.pipeline_depth() == 9
    finally:
        await s.shutdown()


# ---------------------------------------------------------------------------
# slow-reader EventChannel under sustained push (ISSUE 5 satellite)
# ---------------------------------------------------------------------------


async def _pump_slow_reader(n_events: int, inbox_max: int):
    """Sustained push against a LOSSLESS subscriber that never reads:
    returns (serf, subscriber, shed_delta, violations)."""
    net = LoopbackNetwork()
    sub = EventSubscriber(maxsize=16, lossless=True)
    s = await Serf.create(net.bind("w0"),
                          Options.local(event_inbox_max=inbox_max), "w0",
                          subscriber=sub)
    base = _counter("serf.overload.event_shed")
    for i in range(n_events):
        s._emit(UserEvent(i, f"e{i}", b"payload"))
        if i % 64 == 0:
            await asyncio.sleep(0)         # let the pipeline tee run
    await asyncio.sleep(0.1)
    shed = _counter("serf.overload.event_shed") - base
    return net, s, sub, shed


async def test_slow_lossless_reader_memory_bounded_and_gauge_tracks():
    # the delivery path absorbs subscriber(16) + in-service workers +
    # intake(64) before shedding starts — pump past all of it
    inbox_max = 64
    n = 5000
    net, s, sub, shed = await _pump_slow_reader(n, inbox_max)
    try:
        # memory stays bounded end to end: subscriber queue at its cap,
        # pipeline intake at its, everything else shed AND counted
        assert sub.qsize() <= 16
        assert s.pipeline_depth() <= inbox_max
        assert shed > 0
        assert sub.qsize() + s.pipeline_depth() \
            + s._pipeline.inflight() + shed >= n - 32
        # the tee gauge tracked the backlog (health input)
        s._gauge_queue_ages()
        g = metrics.global_sink().gauge_value(
            "serf.events.tee_depth", {"node": "w0"})
        assert g is not None and g > 0
        assert s.event_tee_fill() > 0
        # the LOSSLESS contract held: shedding happened at the bounded
        # intake (admission), never by drop-oldest on the channel
        assert sub.dropped == 0 and sub.lossless_violations == 0
    finally:
        await s.shutdown()


async def test_lossless_violation_guard_fires_exactly_when_contracted():
    sub = EventSubscriber(maxsize=2, lossless=True)
    await sub.push(UserEvent(1, "a", b""))
    await sub.push(UserEvent(2, "b", b""))
    assert sub.lossless_violations == 0
    # a synchronous producer bypassing the awaiting push IS the contract
    # break — the guard must fire exactly then, loudly
    sub._push(UserEvent(3, "c", b""))
    assert sub.lossless_violations == 1 and sub.dropped == 1
    assert _counter("serf.subscriber.lossless_violation") >= 1


@pytest.mark.slow
async def test_slow_reader_soak_heavy():
    """Heavy soak sibling: 10k events against a wedged lossless reader —
    bounds must hold at an order of magnitude more pressure."""
    inbox_max = 128
    net, s, sub, shed = await _pump_slow_reader(10_000, inbox_max)
    try:
        assert sub.qsize() <= 16
        assert s.pipeline_depth() <= inbox_max
        assert sub.lossless_violations == 0
        # slack: each applier worker holds at most one event in hand
        assert shed >= 10_000 - 16 - inbox_max \
            - s.opts.pipeline_workers - 4
    finally:
        await s.shutdown()
