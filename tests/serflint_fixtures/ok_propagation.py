"""serflint fixture: the clean twin of bad_propagation.py — every row
field has a merge entry with a legal op, every merge entry is a row
field, and the toy README propagation table carries exactly these rows
— must produce zero ``propagation-field-drift`` findings."""

PROPAGATION_FIELDS = ("slots_sent", "cov_min")

PROPAGATION_MERGE = {
    "slots_sent": "sum",
    "cov_min": "replicated",
}
