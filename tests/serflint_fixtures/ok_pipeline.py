"""serflint golden fixture: the clean twin of bad_pipeline.py — events
go through the MPMC hand-off API; no finding may fire."""


class PoliteEngine:
    def __init__(self, pipeline):
        self._pipeline = pipeline

    def emit(self, ev):
        # the one hand-off API: bounded, dependency-keyed, shed-accounted
        if self._pipeline.depth() < 8192:
            self._pipeline.offer(ev)

    def backlog_age(self):
        return self._pipeline.oldest_age()
