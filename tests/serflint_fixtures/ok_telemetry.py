"""serflint fixture: the clean twin of bad_telemetry.py — every row
field has a merge entry with a legal op, every merge entry is a row
field, and the toy README table carries exactly these rows — must
produce zero ``telemetry-field-drift`` findings."""

TELEMETRY_FIELDS = ("alive", "agreement")

TELEMETRY_MERGE = {
    "alive": "sum",
    "agreement": "sum",
}
