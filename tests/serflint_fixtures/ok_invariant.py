"""serflint fixture: the clean twin of bad_invariant.py — every
invariant row field has a merge entry with a legal op (``replicated``
is the only one: invariant flags are judged from replicated operands),
every merge entry is a row field, and the toy README invariant table
carries exactly these rows — must produce zero
``invariant-field-drift`` findings."""

INVARIANT_FIELDS = ("overflow_ok", "viol_mask")

INVARIANT_MERGE = {
    "overflow_ok": "replicated",
    "viol_mask": "replicated",
}
