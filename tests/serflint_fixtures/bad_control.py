"""serflint fixture: control-knob declarations that MUST fire
``control-knob-drift``.

Linted pure-AST as a toy project's ``serf_tpu/control/device.py`` with
``registry.control_knobs = {"fanout", "probe_mult"}``:

- ``rogue_knob`` is a KNOB_FIELDS entry nobody declared
  (``field:rogue_knob``) AND has no law (``lawless:rogue_knob``);
- a DEVICE_LAWS entry actuates ``undeclared_law_knob``
  (``law:undeclared_law_knob``);
- declared ``probe_mult`` appears in no field tuple and no law
  (``undefined:probe_mult`` — exercised by the test via the registry).
"""

KNOB_FIELDS = ("fanout", "rogue_knob")

DEVICE_LAWS = (
    ("some-signal", "fanout", "up"),
    ("some-signal", "undeclared_law_knob", "down"),
)
