"""serflint fixture: the clean twin of bad_jax.py — NO JAX rule may
fire (linted at a serf_tpu/models/ path inside a toy project)."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def select_on_tracer(x):
    # the traced branch, expressed symbolically
    return jnp.where(x > 0, x + 1, x - 1)


@jax.jit
def branch_on_config(x, cfg):
    # cfg params are static by convention — a Python branch is fine
    if cfg.with_failure:
        return x + 1
    return x


@jax.jit
def optional_arg(x, key=None):
    # `is None` dispatch on an optional arg is Python-level and legit
    if key is None:
        return x
    return x + 1


def scan_body_symbolic(carry, x):
    return carry + jnp.minimum(x, 1), x


def drive(xs):
    return jax.lax.scan(scan_body_symbolic, 0, xs)


def emit_round_metrics(state):
    # not round-step code (emit_* batched-pull pattern): host transfer ok
    return {"serf.fixture.gauge": float(np.asarray(state).sum())}


def round_step_on_device(state):
    # the hot path stays on device
    return state * 2


@jax.jit
def jitted_consumer(x, extras):
    return x


def caller(x):
    # hashable static shapes: tuple, not list
    return jitted_consumer(x, (1, 2, 3))
