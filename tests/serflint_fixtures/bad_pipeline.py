"""serflint golden fixture: every pipeline-bypass pattern MUST fire.

Placed (by the test) at serf_tpu/host/fake.py — a host module that does
not own a queue seam.
"""

import asyncio


class SneakyEngine:
    def __init__(self):
        # manual queue construction: a side-channel around the pipeline
        self.inbox = asyncio.Queue()

    def emit(self, ev):
        # direct put bypasses the bounded, dependency-keyed hand-off
        self.inbox.put_nowait(ev)

    async def emit_blocking(self, ev):
        await self.inbox.put(ev)

    def jump_the_queue(self, serf, key):
        # reaching into EventPipeline internals
        serf._pipeline._ready.append(key)
