"""serflint fixture: every JAX rule MUST fire (linted at a
serf_tpu/models/ path inside a toy project; never imported)."""
import jax
import jax.numpy as jnp
import numpy as np
from functools import partial


@jax.jit
def branch_on_tracer(x):
    # jax-python-branch: Python `if` on a traced parameter
    if x > 0:
        return x + 1
    return x - 1


@partial(jax.jit, static_argnums=())
def concretize_tracer(x):
    # jax-host-concretize: float() on a traced value
    total = float(x)
    # jax-host-concretize: .item() inside a traced body
    peak = x.item()
    return total + peak


def scan_body_branches(carry, x):
    # jax-python-branch: this function is traced via lax.scan below
    while x > 0:
        carry = carry + 1
    return carry, x


def drive(xs):
    return jax.lax.scan(scan_body_branches, 0, xs)


def round_step_transfers(state):
    # jax-host-transfer: per-round device sync on the hot path
    host_view = np.asarray(state)
    return jax.device_get(host_view)


@jax.jit
def jitted_consumer(x, extras):
    return x


def caller(x):
    # jax-unhashable-arg: list literal forces a recompile every call
    return jitted_consumer(x, [1, 2, 3])
