"""serflint fixture: the clean twin of bad_async.py — NO async rule may
fire here."""
import asyncio


def _log_exc(t):
    if not t.cancelled() and t.exception() is not None:
        pass


async def spawn_retained(registry: set):
    # handle retained + exception sink: the fire-forget contract
    t = asyncio.create_task(asyncio.sleep(1))
    registry.add(t)
    t.add_done_callback(registry.discard)
    t.add_done_callback(_log_exc)
    return t


async def sleeps_asynchronously():
    # the asyncio equivalent never blocks the loop
    await asyncio.sleep(0.5)


class Holder:
    def __init__(self):
        self._lock = asyncio.Lock()

    async def parks_outside_lock(self, event):
        async with self._lock:
            state = dict()
        # parks AFTER releasing — contenders are not serialized
        await asyncio.sleep(1.0)
        await event.wait()
        return state


class SharedState:
    def __init__(self):
        self._lock = asyncio.Lock()
        self._peers = {}

    async def writer_a(self, k, v):
        async with self._lock:
            self._peers[k] = v

    async def writer_b(self, k):
        async with self._lock:
            self._peers.pop(k, None)
