"""serflint fixture: SLO definitions that MUST fire the SLO family.

Linted pure-AST inside a toy project whose registry declares
``metrics={"serf.toy.counter"}`` and ``slos={"toy-slo"}``:

- ``toy-slo`` watches an undeclared metric → ``slo-metric-unknown``;
- ``rogue-slo`` is defined but not declared → ``slo-decl-drift``
  (and the registry's second declared SLO having no definition is the
  drift in the other direction, exercised by the test directly).
"""

SLO_TABLE = (
    SLODef(name="toy-slo",                              # noqa: F821
           metrics=("serf.not.declared",),
           planes=("host",), better="lower", objective=1.0,
           unit="ratio", description="watches a metric nobody declared"),
    SLODef(name="rogue-slo",                            # noqa: F821
           metrics=("serf.toy.counter",),
           planes=("device",), better="lower", objective=0.5,
           unit="ratio", description="defined but never declared"),
)
