"""serflint fixture: the clean twin of bad_slo.py — every SLO watches
a declared metric and matches the registry's SLOS declaration exactly,
so NO SLO rule may fire."""

SLO_TABLE = (
    SLODef(name="toy-slo",                              # noqa: F821
           metrics=("serf.toy.counter",),
           planes=("host", "device"), better="lower", objective=1.0,
           unit="ratio", description="a well-governed objective"),
)
