"""serflint fixture: telemetry-row declarations that MUST fire
``telemetry-field-drift``.

Linted pure-AST as a toy project's ``serf_tpu/models/swim.py``:

- ``orphan_field`` is a TELEMETRY_FIELDS entry with no TELEMETRY_MERGE
  entry (``unreduced:orphan_field`` — a row field the in-collective
  legs would silently drop);
- TELEMETRY_MERGE reduces ``ghost_field`` which is not a row field
  (``undeclared:ghost_field`` — a dead merge leg);
- ``alive`` declares merge op ``"mean"`` which no collective leg
  implements (``bad-op:alive`` — means are not associative without a
  count partial; declare the counts as "sum" fields instead);
- the toy README documents ``stale_field`` which the row does not carry
  (``stale-row:stale_field``) and has no row for ``orphan_field``
  (``undocumented:orphan_field``).
"""

TELEMETRY_FIELDS = ("alive", "orphan_field")

TELEMETRY_MERGE = {
    "alive": "mean",
    "ghost_field": "sum",
}
