"""serflint fixture: propagation-row declarations that MUST fire
``propagation-field-drift``.

Linted pure-AST as a toy project's ``serf_tpu/obs/propagation.py``
(the ``bad_telemetry.py`` shape, over the propagation observatory's
row contract):

- ``orphan_field`` is a PROPAGATION_FIELDS entry with no
  PROPAGATION_MERGE entry (``unreduced:orphan_field``);
- PROPAGATION_MERGE reduces ``ghost_field`` which is not a row field
  (``undeclared:ghost_field``);
- ``slots_sent`` declares merge op ``"mean"`` which no leg implements
  (``bad-op:slots_sent`` — means are not associative without a count
  partial);
- the toy README documents ``stale_field`` which the row does not
  carry (``stale-row:stale_field``) and has no row for
  ``orphan_field`` (``undocumented:orphan_field``).
"""

PROPAGATION_FIELDS = ("slots_sent", "orphan_field")

PROPAGATION_MERGE = {
    "slots_sent": "mean",
    "ghost_field": "sum",
}
