"""serflint fixture: the clean twin of bad_control.py — every knob
declared, every knob lawful, every law on a declared knob (registry
``control_knobs = {"fanout"}``) — must produce zero
``control-knob-drift`` findings."""

KNOB_FIELDS = ("fanout",)

DEVICE_LAWS = (
    ("some-signal", "fanout", "up"),
    ("other-signal", "fanout", "down"),
)
