"""serflint fixture: invariant-row declarations that MUST fire
``invariant-field-drift``.

Linted pure-AST as a toy project's ``serf_tpu/obs/watchdog.py``
(the ``bad_propagation.py`` shape, over the always-on watchdog's
in-scan invariant row contract):

- ``orphan_ok`` is an INVARIANT_FIELDS entry with no INVARIANT_MERGE
  entry (``unreduced:orphan_ok``);
- INVARIANT_MERGE reduces ``ghost_ok`` which is not a row field
  (``undeclared:ghost_ok``);
- ``overflow_ok`` declares merge op ``"sum"``, which the invariant row
  does not implement (``bad-op:overflow_ok`` — invariant flags are
  judged from replicated operands only; summing booleans across shards
  would change the predicate's meaning);
- the toy README documents ``stale_ok`` which the row does not carry
  (``stale-row:stale_ok``) and has no row for ``orphan_ok``
  (``undocumented:orphan_ok``).
"""

INVARIANT_FIELDS = ("overflow_ok", "orphan_ok")

INVARIANT_MERGE = {
    "overflow_ok": "sum",
    "ghost_ok": "replicated",
}
