"""serflint fixture: every async rule MUST fire on this file.

Linted as a toy-project file (never imported, never executed); the clean
twin is ok_async.py.
"""
import asyncio
import time


async def spawn_and_forget(loop):
    # async-fire-forget: bare statement, handle discarded
    asyncio.create_task(asyncio.sleep(1))
    # async-fire-forget: ensure_future variant
    asyncio.ensure_future(asyncio.sleep(1))
    # async-fire-forget: loop.create_task variant
    loop.create_task(asyncio.sleep(1))


async def blocks_the_loop():
    # async-blocking-call: sync sleep stalls every coroutine
    time.sleep(0.5)


class Holder:
    def __init__(self):
        self._lock = asyncio.Lock()

    async def parks_under_lock(self, event):
        async with self._lock:
            # async-lock-await: timer park inside the critical section
            await asyncio.sleep(1.0)
            # async-lock-await: event park inside the critical section
            await event.wait()


class SharedState:
    def __init__(self):
        self._peers = {}

    async def writer_a(self, k, v):
        self._peers[k] = v

    async def writer_b(self, k):
        self._peers.pop(k, None)
