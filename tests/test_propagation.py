"""Gossip propagation observatory (ISSUE 16 tentpole, acceptance-
pinned): the sentinel tracer obeys the house invariant — OFF (default)
the scan is jaxpr-identical to the untraced path (the ledger popcounts
simply don't exist), ON it changes no ``GossipState`` leaf and adds
ZERO per-round host transfers (device_get-count pinned); the
redundancy ledger closes row-by-row and lands near the analytic
``1/(window·fanout)`` model; the host ledger's fold is
order/partition-invariant (fold-of-union); and the CLI self-check
stays green.

Budget discipline: one tiny config (n=64, K=32), 10-round scans for
the bit-exactness pins; the heavy stamp-flavor × mesh cross is
``@slow`` (each axis is covered unsharded / single-flavor in tier-1).
"""

import importlib.util
import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serf_tpu.control.device import ControlConfig
from serf_tpu.models.dissemination import (
    GossipConfig,
    K_USER_EVENT,
    inject_fact,
)
from serf_tpu.models.failure import FailureConfig
from serf_tpu.models.swim import (
    ClusterConfig,
    make_cluster,
    run_cluster_sustained,
)
from serf_tpu.obs.propagation import (
    PROPAGATION_FIELDS,
    PROPAGATION_SERIES,
    PropagationLedger,
    analytic_redundancy,
    fold_propagation,
    propagation_to_store,
    summarize_propagation,
)
from serf_tpu.parallel.mesh import shard_state

REPO = Path(__file__).resolve().parent.parent
N, K, ROUNDS = 64, 32, 10
IDX = {f: i for i, f in enumerate(PROPAGATION_FIELDS)}


def _cfg(pack=True, schedule="ring"):
    return ClusterConfig(
        gossip=GossipConfig(n=N, k_facts=K, peer_sampling="rotation",
                            pack_stamp=pack),
        failure=FailureConfig(suspicion_rounds=8, max_new_facts=8,
                              probe_schedule="round_robin"),
        control=ControlConfig(enabled=False),
        push_pull_every=8, probe_every=2, exchange_schedule=schedule)


def _seeded(cfg):
    st = make_cluster(cfg, jax.random.key(0))
    g = inject_fact(st.gossip, cfg.gossip, subject=3, kind=K_USER_EVENT,
                    incarnation=0, ltime=5, origin=0)
    g = g._replace(alive=g.alive.at[jnp.asarray([7, N // 2])].set(False))
    return st._replace(gossip=g)


def _run(cfg, traced, mesh=None):
    run = jax.jit(lambda s, k: run_cluster_sustained(
        s, cfg, k, ROUNDS, 2, mesh=mesh, collect_propagation=traced))
    st = _seeded(cfg)
    if mesh is not None:
        st = shard_state(st, mesh)
    out = run(st, jax.random.key(3))
    if traced:
        final, pair = out
        return final, jax.device_get(pair)
    return out, None


def _assert_leaves_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        assert (np.asarray(jax.device_get(x))
                == np.asarray(jax.device_get(y))).all()


# ---------------------------------------------------------------------------
# house invariant: tracer off = untraced (jaxpr), tracer on = same state
# ---------------------------------------------------------------------------


def test_off_path_jaxpr_is_popcount_free():
    """THE off-is-free pin: with the flag off (default) the sustained
    scan's jaxpr carries no population_count — the redundancy ledger is
    Python-gated out of existence, not masked to zero at runtime."""
    cfg = _cfg()
    st = _seeded(cfg)
    off = str(jax.make_jaxpr(lambda s, k: run_cluster_sustained(
        s, cfg, k, ROUNDS, 2))(st, jax.random.key(3)))
    on = str(jax.make_jaxpr(lambda s, k: run_cluster_sustained(
        s, cfg, k, ROUNDS, 2, collect_propagation=True))(
            st, jax.random.key(3)))
    assert "population_count" not in off
    assert "population_count" in on


@pytest.mark.parametrize("pack", [True, False])
def test_tracer_on_is_state_bit_exact(pack):
    """Tracer on changes no GossipState leaf: the propagation rows are
    extra scan OUTPUTS, never a state perturbation — pinned for both
    stamp flavors on the unsharded path."""
    cfg = _cfg(pack=pack)
    f_off, _ = _run(cfg, traced=False)
    f_on, pair = _run(cfg, traced=True)
    _assert_leaves_equal(f_off, f_on)
    rows, cov = pair
    assert rows.shape == (ROUNDS, len(PROPAGATION_FIELDS))
    assert cov.shape == (ROUNDS, 2)          # events_per_round sentinels


def test_tracer_on_is_state_bit_exact_vmesh8(vmesh8):
    """Same pin on the sharded flagship round (one flavor in tier-1;
    the full cross rides the @slow soak)."""
    cfg = _cfg()
    f_off, _ = _run(cfg, traced=False, mesh=vmesh8)
    f_on, pair = _run(cfg, traced=True, mesh=vmesh8)
    _assert_leaves_equal(f_off, f_on)
    # and the sharded trace equals the unsharded one bit-for-bit (the
    # ledger reductions are GSPMD integer sums — exact in any order)
    _, ref_pair = _run(cfg, traced=True)
    assert (pair[0] == ref_pair[0]).all()
    assert (pair[1] == ref_pair[1]).all()


@pytest.mark.slow
@pytest.mark.parametrize("pack", [True, False])
@pytest.mark.parametrize("schedule", ["ring", "allgather"])
def test_tracer_bit_exact_heavy_cross(vmesh8, pack, schedule):
    """Redundant heavy parametrization: both stamp flavors × both ICI
    schedules on the virtual mesh (each axis already covered above)."""
    cfg = _cfg(pack=pack, schedule=schedule)
    f_off, _ = _run(cfg, traced=False, mesh=vmesh8)
    f_on, _ = _run(cfg, traced=True, mesh=vmesh8)
    _assert_leaves_equal(f_off, f_on)


# ---------------------------------------------------------------------------
# zero extra transfers: tracing adds no per-round (or per-run) device_get
# ---------------------------------------------------------------------------


def _count_device_gets(monkeypatch, **kwargs):
    from serf_tpu.faults.device import run_device_plan
    from serf_tpu.faults.plan import named_plan

    real = jax.device_get
    calls = []
    monkeypatch.setattr(jax, "device_get",
                        lambda *a, **k: calls.append(1) or real(*a, **k))
    result = run_device_plan(named_plan("partition-heal-loss"), _cfg(),
                             **kwargs)
    monkeypatch.setattr(jax, "device_get", real)
    return len(calls), result


def test_tracing_adds_zero_transfers(monkeypatch):
    """THE acceptance pin: a chaos run with the tracer on performs
    exactly as many jax.device_get calls as the telemetry-only run —
    the propagation rows ride the existing end-of-run transfer."""
    n_tele, _ = _count_device_gets(monkeypatch, collect_telemetry=True)
    n_both, r = _count_device_gets(monkeypatch, collect_telemetry=True,
                                   collect_propagation=True)
    assert n_both == n_tele, (
        f"tracer-on run did {n_both} device_gets vs {n_tele} without")
    assert r.propagation is not None and r.report.ok


# ---------------------------------------------------------------------------
# the redundancy ledger closes — row-by-row and against the model
# ---------------------------------------------------------------------------


def test_redundancy_ledger_closes():
    cfg = _cfg()
    _, (rows, cov) = _run(cfg, traced=True)
    sent = rows[:, IDX["slots_sent"]]
    learned = rows[:, IDX["slots_learned"]]
    redundant = rows[:, IDX["slots_redundant"]]
    ratio = rows[:, IDX["redundancy"]]
    assert (redundant == sent - learned).all()
    assert (ratio == redundant / np.maximum(sent, 1.0)).all()
    assert (learned <= sent).all()
    # coverage columns are true fractions
    assert (cov >= 0).all() and (cov <= 1).all()
    s = summarize_propagation(rows, cov)
    assert s.slots_sent == float(sent.sum())
    # the cumulative ratio lands near the analytic transmit-window model
    # (exact only in steady state at scale; 0.1 absorbs the small-N,
    # short-window bias — 0.92 measured vs 0.958 analytic at n=64)
    model = analytic_redundancy(cfg.gossip.transmit_window_rounds,
                                cfg.gossip.fanout)
    assert abs(s.redundancy - model) < 0.1


def test_summary_and_series_contract():
    """to_dict stringifies the time_to keys (JSON stability) and the
    ring series carry exactly the declared serf.propagation.* names."""
    cfg = _cfg()
    _, (rows, cov) = _run(cfg, traced=True)
    s = summarize_propagation(rows, cov)
    d = json.loads(json.dumps(s.to_dict()))
    assert set(d["time_to"]) == {"50", "90", "99"}
    assert d["rounds"] == ROUNDS and d["sentinels"] == 2
    store = propagation_to_store(rows, base_round=7)
    assert sorted(store.names()) == sorted(n for _, n in PROPAGATION_SERIES)
    # absolute round timestamps: base_round + i + 1
    t0 = store.get("serf.propagation.redundancy").points()[0][0]
    assert t0 == 8.0


# ---------------------------------------------------------------------------
# host plane: ledger payload round-trip + fold-of-union
# ---------------------------------------------------------------------------


class _Tctx:
    def __init__(self, hex_id, hops=0):
        self.hex_id, self.hops = hex_id, hops


def _ledger(traces, dup=0, rebroadcast=0):
    led = PropagationLedger()
    for h in traces:
        led.accept(_Tctx(h))
    for _ in range(dup):
        led.duplicate()
    for _ in range(rebroadcast):
        led.rebroadcast()
    return led


def test_ledger_payload_roundtrip_and_fold():
    """summary() survives the _serf_stats JSON wire and folds to the
    exact per-counter sums + per-trace node counts."""
    a = _ledger(["aa" * 16, "bb" * 16], dup=3, rebroadcast=2)
    b = _ledger(["aa" * 16], dup=1)
    nodes = {"n1": json.loads(json.dumps(a.summary())),
             "n2": json.loads(json.dumps(b.summary()))}
    fold = fold_propagation(nodes)
    assert fold["seen"] == 3 and fold["duplicates"] == 4
    assert fold["rebroadcasts"] == 2
    assert fold["dup_ratio"] == pytest.approx(4 / 7)
    assert fold["traces"]["aa" * 16]["nodes"] == 2
    assert fold["traces"]["bb" * 16]["nodes"] == 1
    assert a.first_seen("aa" * 16) is not None
    assert a.first_seen("cc" * 16) is None


def test_fold_is_partition_invariant():
    """fold(union) == merge of fold(parts): the counters are plain sums
    and the per-trace aggregates are min/max-assembled, so ANY grouping
    of the node payloads folds to the same cluster aggregate (the
    _serf_stats partial-merge contract)."""
    payloads = {f"n{i}": _ledger([f"{i:02x}" * 16, "ff" * 16],
                                 dup=i, rebroadcast=1).summary()
                for i in range(4)}
    whole = fold_propagation(payloads)
    for split_at in (1, 2, 3):
        items = sorted(payloads.items())
        left = fold_propagation(dict(items[:split_at]))
        right = fold_propagation(dict(items[split_at:]))
        assert left["seen"] + right["seen"] == whole["seen"]
        assert left["duplicates"] + right["duplicates"] \
            == whole["duplicates"]
        assert left["rebroadcasts"] + right["rebroadcasts"] \
            == whole["rebroadcasts"]
        ltr, rtr = left["traces"], right["traces"]
        for h, t in whole["traces"].items():
            assert t["nodes"] == (ltr.get(h, {}).get("nodes", 0)
                                  + rtr.get(h, {}).get("nodes", 0))


def test_ledger_recent_map_is_bounded():
    led = PropagationLedger(recent=4)
    for i in range(10):
        led.accept(_Tctx(f"{i:02x}" * 16))
    assert led.seen == 10
    assert len(led._recent) == 4
    assert led.first_seen("00" * 16) is None      # evicted, oldest first
    assert led.first_seen("09" * 16) is not None


# ---------------------------------------------------------------------------
# the CLI self-check (tier-1 hook)
# ---------------------------------------------------------------------------


def test_gossipscope_self_check():
    """tools/gossipscope.py --self-check: the traced device run must be
    sane (full coverage, finite t99, redundancy in (0,1)) and exit 0 —
    run in-process so the jit caches warm across the suite."""
    spec = importlib.util.spec_from_file_location(
        "gossipscope", REPO / "tools" / "gossipscope.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main(["--self-check"]) == 0
