"""The sendable-bitset cache must be a pure accelerator: every protocol
output (known/stamp/round/last_learn/facts) bit-identical with the cache
on or off, under the compositions the flagship actually runs — sustained
injection, failure detection, push/pull anti-entropy, external
alive-flips, out-of-band injections, and the stale-cache fallback after
a non-maintaining kernel ran (GossipState.sendable_round invariant,
serf_tpu/models/dissemination.py)."""

import dataclasses
import functools

import jax
import jax.numpy as jnp
import pytest

from serf_tpu.models.dissemination import (
    GossipConfig,
    K_USER_EVENT,
    inject_facts_batch,
    push_round_step,
    sending_mask,
    pack_bits,
)
from serf_tpu.models.failure import FailureConfig, run_swim
from serf_tpu.models.swim import (
    ClusterConfig,
    make_cluster,
    run_cluster_sustained,
)


def _gossip_equal(a, b):
    for name in ("known", "stamp", "round", "last_learn", "next_slot",
                 "alive", "incarnation", "tombstone"):
        assert bool(jnp.all(getattr(a, name) == getattr(b, name))), name
    for name in ("subject", "kind", "incarnation", "ltime", "valid"):
        assert bool(jnp.all(getattr(a.facts, name)
                            == getattr(b.facts, name))), f"facts.{name}"


def _cluster_cfg(cache: bool, n: int = 2048) -> ClusterConfig:
    # k_facts=64: at n=2048 the transmit limit is 16 rounds, and
    # sustained_round's fact-lifetime headroom check (ADVICE r5) requires
    # k_facts/events_per_round > transmit_limit
    return ClusterConfig(
        gossip=GossipConfig(n=n, k_facts=64, peer_sampling="rotation",
                            use_sendable_cache=cache),
        failure=FailureConfig(suspicion_rounds=8, max_new_facts=8,
                              probe_schedule="round_robin"),
        push_pull_every=8, probe_every=5)


def _drive_cache_on_off(n: int, segments: int, rounds: int) -> None:
    """Sustained scan segments with external churn + injections between
    them: the full gossip state must match bit-for-bit, cache on vs off."""
    cfgs = {c: _cluster_cfg(c, n=n) for c in (True, False)}
    runs = {c: jax.jit(functools.partial(run_cluster_sustained, cfg=cfg,
                                         events_per_round=2),
                       static_argnames=("num_rounds",))
            for c, cfg in cfgs.items()}
    states = {c: make_cluster(cfg, jax.random.key(0))
              for c, cfg in cfgs.items()}

    for seg in range(segments):
        for c in (True, False):
            states[c] = runs[c](states[c], key=jax.random.key(10 + seg),
                                num_rounds=rounds)
        _gossip_equal(states[True].gossip, states[False].gossip)
        # external churn: kill a few nodes, revive one — alive is not
        # folded into the cache, so this must not desync anything
        for c in (True, False):
            g = states[c].gossip
            g = g._replace(alive=g.alive.at[
                jnp.asarray([7 + seg, (n // 7) + seg])].set(False))
            g = g._replace(alive=g.alive.at[5].set(True))
            # out-of-band injection (the host plane can inject between
            # scan segments): preserves cache validity by construction
            g = inject_facts_batch(
                g, cfgs[c].gossip,
                subjects=jnp.asarray([(n // 2) + seg], jnp.int32),
                kind=K_USER_EVENT,
                incarnations=jnp.zeros((1,), jnp.uint32),
                ltimes=jnp.asarray([900 + seg], jnp.uint32),
                origins=jnp.asarray([11], jnp.int32),
                active=jnp.ones((1,), bool))
            states[c] = states[c]._replace(gossip=g)

    _gossip_equal(states[True].gossip, states[False].gossip)


def test_sustained_bit_exact_cache_on_off_fast():
    """Tier-1 pin at small N (same drive, compile-bound cost shrunk);
    the flagship-scale 2048x3x30 soak runs under -m slow."""
    _drive_cache_on_off(n=256, segments=2, rounds=12)


@pytest.mark.slow
def test_sustained_flagship_bit_exact_cache_on_off():
    _drive_cache_on_off(n=2048, segments=3, rounds=30)


def test_swim_only_bit_exact_cache_on_off():
    """Probe/refute/declare injections ride the cache-maintaining inject
    path; detection outcomes must be identical either way."""
    outs = {}
    for cache in (True, False):
        gcfg = GossipConfig(n=1024, k_facts=32, peer_sampling="rotation",
                            use_sendable_cache=cache)
        fcfg = FailureConfig(suspicion_rounds=8,
                             probe_schedule="round_robin")
        from serf_tpu.models.dissemination import inject_fact, make_state

        g = make_state(gcfg)
        g = inject_fact(g, gcfg, subject=3, kind=K_USER_EVENT,
                        incarnation=0, ltime=1, origin=0)
        g = g._replace(alive=g.alive.at[jnp.asarray([17, 400])].set(False))
        run = jax.jit(functools.partial(run_swim, cfg=gcfg, fcfg=fcfg),
                      static_argnames=("num_rounds",))
        outs[cache] = run(g, key=jax.random.key(1), num_rounds=60)
    _gossip_equal(outs[True], outs[False])


def test_checkpoint_backcompat_without_cache_fields(tmp_path):
    """A checkpoint written before the cache fields existed must restore
    with the always-safe defaults (stale plane, never read) instead of
    failing closed — long-running bench continuity."""
    import numpy as np

    from serf_tpu.models import checkpoint
    from serf_tpu.models.dissemination import inject_fact, make_state

    cfg = GossipConfig(n=128, k_facts=32)
    g = inject_fact(make_state(cfg), cfg, 3, K_USER_EVENT, 0, 1, 0)
    flat = {jax.tree_util.keystr(p): np.asarray(leaf)
            for p, leaf in jax.tree_util.tree_flatten_with_path(g)[0]
            if not jax.tree_util.keystr(p).endswith(
                (".sendable", ".sendable_round"))}
    path = str(tmp_path / "pre_r5.npz")
    with open(path, "wb") as f:
        np.savez(f, **flat)
    back = checkpoint.restore(path, make_state(cfg))
    assert int(back.sendable_round) == -1
    assert bool(jnp.all(back.sendable == 0))
    assert bool(jnp.all(back.known == g.known))
    # any OTHER missing array still fails closed
    flat2 = {k: v for k, v in flat.items() if not k.endswith(".known")}
    path2 = str(tmp_path / "broken.npz")
    with open(path2, "wb") as f:
        np.savez(f, **flat2)
    try:
        checkpoint.restore(path2, make_state(cfg))
        raise AssertionError("restore accepted a checkpoint missing known")
    except ValueError:
        pass


def test_stale_cache_falls_back_after_nonmaintaining_kernel():
    """push_round_step learns without maintaining the cache and must
    invalidate it; the next cached-config round falls back to the stamp
    recompute and stays bit-exact vs the cache-off config."""
    outs = {}
    for cache in (True, False):
        cfg = GossipConfig(n=256, k_facts=32, use_sendable_cache=cache)
        from serf_tpu.models.dissemination import (
            inject_fact,
            make_state,
            round_step,
        )
        g = make_state(cfg)
        g = inject_fact(g, cfg, subject=3, kind=K_USER_EVENT,
                        incarnation=0, ltime=1, origin=0)
        step = jax.jit(functools.partial(round_step, cfg=cfg))
        push = jax.jit(functools.partial(push_round_step, cfg=cfg))
        key = jax.random.key(2)
        for i in range(4):
            key, k2 = jax.random.split(key)
            g = step(g, key=k2)
        assert (int(g.sendable_round) == int(g.round)) == cache
        key, k2 = jax.random.split(key)
        g = push(g, key=k2)          # learns + invalidates
        assert int(g.sendable_round) == -1
        for i in range(4):
            key, k2 = jax.random.split(key)
            g = step(g, key=k2)      # first step falls back, then re-arms
        outs[cache] = g
    _gossip_equal(outs[True], outs[False])
    # and wherever the cache re-armed, it matches the semantic predicate
    # through the `& known` stale-bit mask selection applies
    # (GossipState.sendable_round invariant)
    g = outs[True]
    cfg = GossipConfig(n=256, k_facts=32)
    if int(g.sendable_round) == int(g.round):
        have = jnp.where(g.alive[:, None], g.sendable & g.known,
                         jnp.uint32(0))
        assert bool(jnp.all(pack_bits(sending_mask(g, cfg)) == have))
