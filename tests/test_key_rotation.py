"""Key-rotation chaos plane (ISSUE 20).

Acceptance pins:

- ``KeyResponse`` quorum math: ``quorum_ok`` is a strict majority of the
  membership observed AFTER the response drain, ``ok`` is full success,
  and retries surface in ``attempts``;
- the host-plane ``rotate-crash-restart`` plan runs green end-to-end:
  keyring-divergence + no-message-loss-mid-rotation judged, reconcile
  converges on the derived next key;
- SIGKILL mid-rotation on the PROC plane: a real OS process killed at
  the "use" switch restarts from its snapshotted keyring and reconverges
  to the new primary with no manual step (tier-1, smallest size);
- acceptance-size rotate-under-partition on both planes (@slow).
"""

import glob

import pytest

from serf_tpu.faults.host import rotation_keys, run_host_plan
from serf_tpu.faults.plan import named_plan
from serf_tpu.faults.proc import run_proc_plan
from serf_tpu.host.key_manager import KeyResponse
from serf_tpu.host.keyring import key_digest

pytestmark = pytest.mark.asyncio

ROTATION_INVARIANTS = {"keyring-divergence", "no-message-loss-mid-rotation"}


# ---------------------------------------------------------------------------
# KeyResponse quorum math (satellite 1)
# ---------------------------------------------------------------------------


def test_key_response_quorum_is_strict_majority():
    # 4 clean acks of 6 members: majority, but not full success
    r = KeyResponse(num_nodes=6, num_resp=5, num_err=1)
    assert r.quorum_ok and not r.ok
    # exactly half is NOT a quorum (3 clean of 6)
    r = KeyResponse(num_nodes=6, num_resp=4, num_err=1)
    assert not r.quorum_ok
    # full success implies quorum
    r = KeyResponse(num_nodes=3, num_resp=3, num_err=0)
    assert r.ok and r.quorum_ok


def test_key_response_empty_cluster_fails_closed():
    r = KeyResponse()
    assert not r.ok and not r.quorum_ok
    # a drain that saw zero members must not report success even with
    # zero errors (the num_nodes-after-drain bug this PR fixed)
    r = KeyResponse(num_nodes=0, num_resp=0, num_err=0)
    assert not r.ok


def test_key_response_attempts_defaults_to_one():
    assert KeyResponse().attempts == 1


# ---------------------------------------------------------------------------
# host plane: crash at the "use" switch, restart from the keyring file
# ---------------------------------------------------------------------------


async def test_rotate_crash_restart_host_plan_small(tmp_path):
    plan = named_plan("rotate-crash-restart", n=3)
    result = await run_host_plan(plan, str(tmp_path))
    assert result.report.ok, result.report.to_dict()
    names = {r.name for r in result.report.results}
    assert ROTATION_INVARIANTS <= names
    rot = result.rotation
    assert rot is not None and rot["converged"], rot
    assert rot["expected_primary"] == key_digest(rotation_keys(plan.seed)[1])
    # every surviving ring landed on the rotated primary
    for node, digest in rot["keyrings"].items():
        assert digest["primary"] == rot["expected_primary"], (node, digest)
    assert rot["decrypt_fail"] == 0, rot


# ---------------------------------------------------------------------------
# proc plane: REAL SIGKILL mid-rotation, restart from snapshotted keyring
# ---------------------------------------------------------------------------


def _agent_pids_under(tmp_dir: str):
    out = []
    for cmdline in glob.glob("/proc/[0-9]*/cmdline"):
        try:
            with open(cmdline, "rb") as f:
                if tmp_dir.encode() in f.read():
                    out.append(int(cmdline.split("/")[2]))
        except OSError:
            continue
    return out


async def test_rotate_crash_restart_proc_plan_small(tmp_path):
    # tier-1 keeps the SIGKILL-mid-rotation acceptance proven at the
    # smallest meaningful size: the killed agent restarts from its
    # persisted keyring (which predates the "use" switch) and must catch
    # up via the re-issued use before retire-old removes the base key
    plan = named_plan("rotate-crash-restart", n=3)
    result = await run_proc_plan(plan, str(tmp_path))
    assert result.report.ok, result.report.to_dict()
    names = {r.name for r in result.report.results}
    assert ROTATION_INVARIANTS <= names
    rot = result.rotation
    assert rot is not None and rot["converged"], rot
    assert rot["expected_primary"] == key_digest(rotation_keys(plan.seed)[1])
    for node, digest in rot["keyrings"].items():
        assert digest["primary"] == rot["expected_primary"], (node, digest)
    # post-heal probes actually delivered mid-rotation traffic
    assert rot["probes"]["delivered"] == rot["probes"]["nodes"], rot
    assert _agent_pids_under(str(tmp_path)) == []


# ---------------------------------------------------------------------------
# acceptance size (@slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow
async def test_rotate_under_partition_host_acceptance(tmp_path):
    result = await run_host_plan(named_plan("rotate-under-partition"),
                                 str(tmp_path))
    assert result.report.ok, result.report.to_dict()
    assert result.rotation["converged"], result.rotation


@pytest.mark.slow
async def test_rotate_under_partition_proc_acceptance(tmp_path):
    result = await run_proc_plan(named_plan("rotate-under-partition"),
                                 str(tmp_path))
    assert result.report.ok, result.report.to_dict()
    assert result.rotation["converged"], result.rotation


@pytest.mark.slow
async def test_rotate_under_churn_host_acceptance(tmp_path):
    result = await run_host_plan(named_plan("rotate-under-churn"),
                                 str(tmp_path))
    assert result.report.ok, result.report.to_dict()
    assert result.rotation["converged"], result.rotation
