"""Round-trip tests for the wire codec and every message type.

Analog of the reference's quickcheck `data_round_trip!` macro over every wire
type (serf-core/src/types/tests.rs:9-40) and the libfuzzer round-trip target
(fuzz/fuzz_targets/messages.rs:12-16): randomized structural round-trips.
"""

import random

import pytest

from serf_tpu import codec
from serf_tpu.types import (
    ConflictResponseMessage,
    IdFilter,
    JoinMessage,
    KeyRequestMessage,
    KeyResponseMessage,
    LeaveMessage,
    Member,
    MemberStatus,
    MessageType,
    Node,
    PushPullMessage,
    QueryFlag,
    QueryMessage,
    QueryResponseMessage,
    TagFilter,
    Tags,
    UserEventMessage,
    UserEvents,
    decode_message,
    encode_message,
    encode_relay_message,
)
from serf_tpu.types.messages import RelayMessage

rng = random.Random(0xC0FFEE)


def rand_str(n=12):
    return "".join(rng.choice("abcdefghijklmnop-_.0123456789") for _ in range(rng.randint(0, n)))


def rand_bytes(n=64):
    return bytes(rng.randrange(256) for _ in range(rng.randint(0, n)))


def test_varint_round_trip():
    for v in [0, 1, 127, 128, 300, 2**32 - 1, 2**63 - 1, 2**64 - 1]:
        buf = codec.encode_varint(v)
        out, pos = codec.decode_varint(buf)
        assert out == v and pos == len(buf)


def test_varint_fuzz():
    for _ in range(2000):
        v = rng.getrandbits(rng.randint(1, 64))
        out, _ = codec.decode_varint(codec.encode_varint(v))
        assert out == v


def test_varint_truncation_raises():
    with pytest.raises(codec.DecodeError):
        codec.decode_varint(b"\x80\x80")
    with pytest.raises(codec.DecodeError):
        codec.decode_varint(b"")


def test_zigzag():
    for v in [0, -1, 1, -(2**31), 2**31, -(2**62)]:
        assert codec.zigzag_decode(codec.zigzag_encode(v)) == v


def rand_node():
    return Node(rand_str() or "n", ("127.0.0.1", rng.randint(1, 65535)))


def rand_tags():
    return Tags({rand_str() or "k": rand_str() for _ in range(rng.randint(0, 4))})


def rand_member():
    return Member(
        node=rand_node(),
        tags=rand_tags(),
        status=MemberStatus(rng.randint(0, 4)),
        protocol_version=1,
        delegate_version=1,
    )


def make_messages():
    msgs = []
    for _ in range(50):
        msgs.append(JoinMessage(rng.getrandbits(48), rand_str() or "n"))
        msgs.append(LeaveMessage(rng.getrandbits(48), rand_str() or "n", rng.random() < 0.5))
        msgs.append(UserEventMessage(rng.getrandbits(32), rand_str() or "e", rand_bytes(), rng.random() < 0.5))
        msgs.append(
            PushPullMessage(
                ltime=rng.getrandbits(32),
                status_ltimes={rand_str() or f"m{i}": rng.getrandbits(32) for i in range(rng.randint(0, 5))},
                left_members=tuple(rand_str() or f"l{i}" for i in range(rng.randint(0, 3))),
                event_ltime=rng.getrandbits(32),
                events=tuple(
                    UserEvents(
                        rng.getrandbits(16),
                        tuple(UserEventMessage(rng.getrandbits(16), rand_str() or "e", rand_bytes(8))
                              for _ in range(rng.randint(0, 2))),
                    )
                    for _ in range(rng.randint(0, 3))
                ),
                query_ltime=rng.getrandbits(32),
            )
        )
        msgs.append(
            QueryMessage(
                ltime=rng.getrandbits(32),
                id=rng.getrandbits(32),
                from_node=rand_node(),
                filters=(IdFilter(tuple(rand_str() or "x" for _ in range(2))), TagFilter("role", "web.*")),
                flags=QueryFlag(rng.randint(0, 3)),
                relay_factor=rng.randint(0, 5),
                timeout_ns=rng.getrandbits(40),
                name=rand_str() or "q",
                payload=rand_bytes(),
            )
        )
        msgs.append(
            QueryResponseMessage(
                rng.getrandbits(32), rng.getrandbits(32), rand_node(), QueryFlag(rng.randint(0, 1)), rand_bytes()
            )
        )
        msgs.append(ConflictResponseMessage(rand_member()))
        msgs.append(KeyRequestMessage(rand_bytes(32)))
        msgs.append(
            KeyResponseMessage(
                rng.random() < 0.5, rand_str(), tuple(rand_bytes(16) for _ in range(rng.randint(0, 3))), rand_bytes(16)
            )
        )
    return msgs


@pytest.mark.parametrize("msg", make_messages(), ids=lambda m: type(m).__name__)
def test_message_round_trip(msg):
    assert decode_message(encode_message(msg)) == msg


def test_relay_round_trip():
    inner = encode_message(QueryResponseMessage(5, 42, rand_node(), QueryFlag.ACK, b"pong"))
    node = rand_node()
    buf = encode_relay_message(node, inner)
    assert buf[0] == int(MessageType.RELAY)
    out = decode_message(buf)
    assert isinstance(out, RelayMessage)
    assert out.node == node
    assert out.payload == inner
    # nested decode
    assert decode_message(out.payload).payload == b"pong"


def test_tags_round_trip():
    for _ in range(100):
        t = rand_tags()
        assert Tags.decode(t.encode()) == t


def test_member_round_trip():
    for _ in range(100):
        m = rand_member()
        assert Member.decode(m.encode()) == m


def test_unknown_type_raises():
    with pytest.raises(codec.DecodeError):
        decode_message(b"\xfe\x01\x02")
    with pytest.raises(codec.DecodeError):
        decode_message(b"")


def test_garbage_never_panics():
    """Fuzz analog: decoding random bytes either succeeds or raises DecodeError."""
    for _ in range(500):
        buf = rand_bytes(40)
        try:
            decode_message(buf)
        except codec.DecodeError:
            pass


def test_bitflip_fails_closed():
    """Single-bit corruptions of a valid message decode or raise DecodeError —
    wire-type confusion must never escape as AttributeError/TypeError."""
    wire = encode_message(QueryMessage(ltime=9, id=1, from_node=Node("a"), name="q"))
    for i in range(len(wire)):
        for bit in range(8):
            b = bytearray(wire)
            b[i] ^= 1 << bit
            try:
                decode_message(bytes(b))
            except codec.DecodeError:
                pass


def test_node_int_addr_round_trip():
    """Loopback-index (int) addresses must round-trip exactly (review finding)."""
    for addr in [3, 0, ("h", 1), "opaque", None]:
        n = Node("a", addr)
        assert Node.decode(n.encode()) == n


def test_tags_bad_klen_fails_closed():
    buf = codec.encode_length_delimited(1, codec.encode_varint(100) + b"ab")
    with pytest.raises(codec.DecodeError):
        Tags.decode(buf)


def test_bad_regex_filter_fails_closed():
    from serf_tpu.types.filters import decode_filter
    bad = codec.encode_varint_field(1, 1) + codec.encode_str_field(3, "t") + codec.encode_str_field(4, "(")
    with pytest.raises(codec.DecodeError):
        decode_filter(bad)


def test_varint_u64_bound():
    with pytest.raises(codec.DecodeError):
        codec.decode_varint(codec.encode_varint(2**64 - 1)[:-1] + b"\x7f")  # force >64 bits
    big = codec.encode_varint(2**64 - 1)
    assert codec.decode_varint(big)[0] == 2**64 - 1
