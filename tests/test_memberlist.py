"""SWIM layer tests: join/converge, failure detection, refutation, leave,
partition behavior, encryption.

Analog of the reference's multi-node-in-process strategy (SURVEY.md §4):
N real Memberlist instances on a loopback fabric with compressed protocol
timings (gossip 5 ms / probe 50 ms), convergence asserted by polling with a
7 s deadline (reference base/tests.rs:25-96).
"""

import asyncio
import time

import pytest

from serf_tpu.host.keyring import SecretKeyring
from serf_tpu.host.memberlist import Memberlist
from serf_tpu.host.messages import SwimState
from serf_tpu.host.transport import LoopbackNetwork
from serf_tpu.options import MemberlistOptions

pytestmark = pytest.mark.asyncio

DEADLINE = 7.0


async def wait_until(cond, deadline=DEADLINE, interval=0.01, msg="condition"):
    loop = asyncio.get_running_loop()
    end = loop.time() + deadline
    while loop.time() < end:
        if cond():
            return
        await asyncio.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


async def make_cluster(net, n, opts=None, keyring=None, start_port=0):
    nodes = []
    for i in range(start_port, start_port + n):
        t = net.bind(f"addr-{i}")
        ml = Memberlist(t, opts or MemberlistOptions.local(), f"node-{i}", keyring=keyring)
        await ml.start()
        nodes.append(ml)
    return nodes


async def join_all(nodes):
    for ml in nodes[1:]:
        await ml.join(nodes[0].transport.local_addr)


async def shutdown_all(nodes):
    for ml in nodes:
        await ml.shutdown()


async def test_join_two_nodes():
    net = LoopbackNetwork()
    nodes = await make_cluster(net, 2)
    try:
        await nodes[1].join(nodes[0].transport.local_addr)
        await wait_until(lambda: all(m.num_online_members() == 2 for m in nodes),
                         msg="2-node convergence")
        assert {n.id for n in nodes[0].members()} == {"node-0", "node-1"}
    finally:
        await shutdown_all(nodes)


async def test_join_converges_10_nodes():
    net = LoopbackNetwork()
    nodes = await make_cluster(net, 10)
    try:
        await join_all(nodes)
        await wait_until(lambda: all(m.num_online_members() == 10 for m in nodes),
                         msg="10-node convergence")
    finally:
        await shutdown_all(nodes)


async def test_failure_detection():
    net = LoopbackNetwork()
    nodes = await make_cluster(net, 4)
    try:
        await join_all(nodes)
        await wait_until(lambda: all(m.num_online_members() == 4 for m in nodes))
        victim = nodes[3]
        await victim.shutdown()
        await wait_until(
            lambda: all(m.num_online_members() == 3 for m in nodes[:3]),
            msg="failure detected on all survivors",
        )
        await wait_until(
            lambda: all(m._nodes["node-3"].state == SwimState.DEAD for m in nodes[:3]),
            msg="suspicion expires into DEAD",
        )
    finally:
        await shutdown_all(nodes[:3])


async def test_graceful_leave_is_left_not_dead():
    net = LoopbackNetwork()
    nodes = await make_cluster(net, 3)
    try:
        await join_all(nodes)
        await wait_until(lambda: all(m.num_online_members() == 3 for m in nodes))
        await nodes[2].leave(2.0)
        await wait_until(
            lambda: all(m._nodes["node-2"].state == SwimState.LEFT for m in nodes[:2]),
            msg="leave disseminated as LEFT",
        )
        await nodes[2].shutdown()
    finally:
        await shutdown_all(nodes[:2])


async def test_refute_suspicion():
    """A healthy node accused of being suspect must refute and stay alive."""
    net = LoopbackNetwork()
    nodes = await make_cluster(net, 3)
    try:
        await join_all(nodes)
        await wait_until(lambda: all(m.num_online_members() == 3 for m in nodes))
        # drop only packets TO node-2 briefly so node-0/1 suspect it
        net.drop_fn = lambda s, d, b: d == "addr-2"
        await wait_until(
            lambda: nodes[0]._nodes["node-2"].state != SwimState.ALIVE,
            msg="node-2 suspected/dead while unreachable",
        )
        net.drop_fn = None
        await wait_until(
            lambda: all(m._nodes["node-2"].state == SwimState.ALIVE for m in nodes[:2]),
            msg="node-2 refutes and is alive again",
        )
        inc = nodes[0]._nodes["node-2"].incarnation
        assert inc > 1  # refutation bumped the incarnation
    finally:
        await shutdown_all(nodes)


async def test_partition_and_heal():
    net = LoopbackNetwork()
    nodes = await make_cluster(net, 4)
    opts = nodes[0].opts
    try:
        await join_all(nodes)
        await wait_until(lambda: all(m.num_online_members() == 4 for m in nodes))
        net.partition({"addr-0", "addr-1"}, {"addr-2", "addr-3"})
        await wait_until(
            lambda: nodes[0].num_online_members() == 2 and nodes[2].num_online_members() == 2,
            msg="partition splits membership",
        )
        net.heal()
        # push/pull re-merges after heal (gossip to dead nodes also helps)
        for src, dst in [(1, 2), (3, 0)]:
            try:
                await nodes[src]._push_pull_with(nodes[dst].transport.local_addr, join=False)
            except ConnectionError:
                pass
        await wait_until(
            lambda: all(m.num_online_members() == 4 for m in nodes),
            msg="heal re-merges the cluster",
        )
    finally:
        await shutdown_all(nodes)


async def test_encrypted_cluster_converges():
    key = bytes(range(32))
    ring = SecretKeyring(key)
    net = LoopbackNetwork()
    nodes = await make_cluster(net, 3, keyring=ring)
    try:
        await join_all(nodes)
        await wait_until(lambda: all(m.num_online_members() == 3 for m in nodes))
        assert nodes[0].encryption_enabled()
    finally:
        await shutdown_all(nodes)


async def test_encrypted_rejects_plaintext_peer():
    ring = SecretKeyring(bytes(range(16)))
    net = LoopbackNetwork()
    enc = await make_cluster(net, 2, keyring=ring)
    plain = await make_cluster(net, 1, start_port=10)
    try:
        await enc[1].join(enc[0].transport.local_addr)
        with pytest.raises(Exception):
            await plain[0].join(enc[0].transport.local_addr)
        await wait_until(lambda: enc[0].num_online_members() == 2)
        assert enc[0].num_online_members() == 2  # plaintext node never got in
    finally:
        await shutdown_all(enc + plain)


async def test_user_message_delivery():
    net = LoopbackNetwork()
    nodes = await make_cluster(net, 2)
    got = []
    nodes[0].delegate.notify_message = got.append
    try:
        await nodes[1].join(nodes[0].transport.local_addr)
        await wait_until(lambda: all(m.num_online_members() == 2 for m in nodes))
        await nodes[1].send(nodes[0].transport.local_addr, b"hello-serf-plane")
        await wait_until(lambda: got == [b"hello-serf-plane"], msg="user message arrives")
    finally:
        await shutdown_all(nodes)


async def test_update_node_propagates_meta():
    net = LoopbackNetwork()
    nodes = await make_cluster(net, 3)
    try:
        await join_all(nodes)
        await wait_until(lambda: all(m.num_online_members() == 3 for m in nodes))
        nodes[0].delegate.node_meta = lambda limit: b"fresh-meta"
        await nodes[0].update_node(2.0)
        await wait_until(
            lambda: all(m._nodes["node-0"].meta == b"fresh-meta" for m in nodes[1:]),
            msg="meta update gossiped",
        )
    finally:
        await shutdown_all(nodes)


async def test_health_score_degrades_when_isolated():
    net = LoopbackNetwork()
    nodes = await make_cluster(net, 3)
    try:
        await join_all(nodes)
        await wait_until(lambda: all(m.num_online_members() == 3 for m in nodes))
        assert nodes[0].health_score() == 0
        # isolate node-0: its probes all fail -> Lifeguard degrades its health
        net.drop_fn = lambda s, d, b: s == "addr-0" or d == "addr-0"
        await wait_until(lambda: nodes[0].health_score() > 0,
                         msg="isolated node's health degrades")
    finally:
        await shutdown_all(nodes)


@pytest.mark.parametrize("compression,checksum", [
    ("zlib", "crc32"), ("brotli", "murmur3")])
async def test_compressed_checksummed_cluster_converges(compression,
                                                        checksum):
    """Wire pipeline parity: compression + checksum on packets and
    streams (reference compression/checksum transport features); brotli
    exercises the round-4 ctypes variant at cluster level."""
    import dataclasses

    from serf_tpu.host.wire import compression_available

    if not compression_available(compression):
        pytest.skip(f"{compression} unavailable in this image")
    net = LoopbackNetwork()
    opts = dataclasses.replace(MemberlistOptions.local(),
                               compression=compression, checksum=checksum)
    nodes = []
    for i in range(3):
        ml = Memberlist(net.bind(f"z{i}"), opts, f"z-{i}")
        await ml.start()
        nodes.append(ml)
    try:
        for ml in nodes[1:]:
            await ml.join("z0")
        await wait_until(lambda: all(m.num_online_members() == 3 for m in nodes),
                         msg=f"{compression} cluster convergence")
    finally:
        await shutdown_all(nodes)


async def test_checksum_drops_corrupted_packets():
    """A corrupted packet must be dropped by the checksum, not decoded."""
    import dataclasses
    from serf_tpu.utils import metrics as metrics_mod
    sink = metrics_mod.MetricsSink()
    metrics_mod.set_global_sink(sink)
    net = LoopbackNetwork()
    opts = dataclasses.replace(MemberlistOptions.local(), checksum="crc32")
    a = Memberlist(net.bind("ck0"), opts, "ck-0")
    b = Memberlist(net.bind("ck1"), opts, "ck-1")
    await a.start(); await b.start()

    # corrupt every 3rd packet in flight
    count = [0]
    orig_send = net.transports["ck0"].send_packet

    async def corrupting_send(addr, buf):
        count[0] += 1
        if count[0] % 3 == 0 and len(buf) > 6:
            buf = buf[:5] + bytes([buf[5] ^ 0xFF]) + buf[6:]
        await orig_send(addr, buf)

    net.transports["ck0"].send_packet = corrupting_send
    try:
        await b.join("ck0")
        await wait_until(lambda: a.num_online_members() == 2
                         and b.num_online_members() == 2)
        await wait_until(
            lambda: sink.counter("memberlist.packet.checksum_failed", {}) > 0,
            msg="corrupted packets detected and dropped")
    finally:
        metrics_mod.set_global_sink(metrics_mod.MetricsSink())
        await shutdown_all([a, b])


async def test_unsupported_wire_options_rejected():
    import dataclasses
    net = LoopbackNetwork()
    with pytest.raises(ValueError):
        Memberlist(net.bind("x0"), dataclasses.replace(
            MemberlistOptions.local(), compression="deflate64"), "x-0")
    with pytest.raises(ValueError):
        Memberlist(net.bind("x1"), dataclasses.replace(
            MemberlistOptions.local(), checksum="xxhash"), "x-1")


async def test_advertise_node_and_address():
    """Reference memberlist object-API surface (SURVEY.md §2.9): the
    advertised identity is the bound local node + transport address."""
    net = LoopbackNetwork()
    nodes = await make_cluster(net, 2)
    try:
        await nodes[1].join(nodes[0].transport.local_addr)
        ml = nodes[0]
        adv = ml.advertise_node()
        assert adv.id == ml.local_id() and adv.addr == ml.advertise_address()
        # what peers actually recorded matches what we advertise
        peer_view = {n.id: n.addr for n in nodes[1].members()}
        assert peer_view[adv.id] == adv.addr
    finally:
        await shutdown_all(nodes)


async def test_incompatible_version_peer_refused(caplog):
    """Version negotiation (reference serf-core/src/types/version.rs:9-43):
    a peer advertising a protocol range that does not intersect ours is
    never admitted — the gossip path drops its alives with a logged
    reason, and our member view stays clean."""
    import logging

    from serf_tpu.host.memberlist import VersionError

    net = LoopbackNetwork()
    nodes = await make_cluster(net, 2)
    try:
        alien = nodes[1]
        # simulate a build speaking only protocol v2-v3 (our range is v1)
        alien._vsn = (2, 3, 2, 1, 1, 1)
        alien._nodes[alien.local_id()].vsn = alien._vsn
        with caplog.at_level(logging.WARNING, logger="serf_tpu.memberlist"):
            # the seed sends an ErrorResp refusal frame before closing
            # (ADVICE r4), so the alien's join fails FAST with the version
            # conflict spelled out — not a generic 10 s recv timeout
            t0 = time.monotonic()
            with pytest.raises(VersionError, match="protocol"):
                await alien.join(nodes[0].transport.local_addr)
            assert time.monotonic() - t0 < 5.0, \
                "refusal did not reach the joiner (timed out instead)"
            await asyncio.sleep(0.3)
        assert nodes[0].num_online_members() == 1, \
            "incompatible peer was admitted"
        assert any("refusing" in r.message or "cannot join" in r.message
                   for r in caplog.records), "no logged refusal reason"
    finally:
        await shutdown_all(nodes)


async def test_incompatible_seed_fails_join_loudly():
    """Joining THROUGH an incompatible seed raises VersionError with the
    node id and the version conflict spelled out."""
    from serf_tpu.host.memberlist import VersionError

    net = LoopbackNetwork()
    nodes = await make_cluster(net, 2)
    try:
        seed = nodes[0]
        seed._vsn = (5, 6, 5, 1, 1, 1)
        seed._nodes[seed.local_id()].vsn = seed._vsn
        with pytest.raises(VersionError, match="node-0.*protocol"):
            await nodes[1].join(seed.transport.local_addr)
        assert nodes[1].num_online_members() == 1
    finally:
        await shutdown_all(nodes)


async def test_version_vector_rides_the_wire():
    """vsn is genuinely encoded + decoded on Alive and PushNodeState (not
    fabricated by the decoder default): a NON-default vector survives the
    round trip, and the vsn bytes field is present on the wire."""
    from serf_tpu.host import messages as sm
    from serf_tpu.types.member import Node

    odd = (2, 3, 2, 1, 2, 1)
    a = sm.Alive(7, Node("n", "a"), b"meta", odd)
    raw = sm.encode_swim(a)
    back = sm.decode_swim(raw)
    assert back.vsn == odd
    assert bytes(odd) in raw, "vsn bytes not on the Alive wire"

    ps = sm.PushNodeState(Node("n", "a"), 7, SwimState.ALIVE, b"m", odd)
    assert sm.PushNodeState.decode(ps.encode()).vsn == odd
    # default vector also genuinely travels (always-encoded)
    a1 = sm.Alive(1, Node("x", "y"))
    assert bytes(sm.DEFAULT_VSN) in sm.encode_swim(a1)


async def test_options_reject_unsupported_versions():
    import dataclasses

    net = LoopbackNetwork()
    with pytest.raises(ValueError, match="protocol_version"):
        Memberlist(net.bind("v1"), dataclasses.replace(
            MemberlistOptions.local(), protocol_version=9), "v-1")
    with pytest.raises(ValueError, match="delegate_version"):
        Memberlist(net.bind("v2"), dataclasses.replace(
            MemberlistOptions.local(), delegate_version=0), "v-2")
