"""The core serf scenario suite stamped over every shipped transport —
the analog of the reference's `test_mod!` macro, which expands each
scenario over {tokio,smol} x {tcp,tls,quic} (76 files under
serf/test/main/net/**, macro at serf/test/main.rs:1-23).

Scenarios: join/converge, graceful leave, user-event dissemination,
query request/response, snapshot crash-restart auto-rejoin.
Transports: loopback (in-process fabric), tcp, tls, udpstream (the
QUIC-slot datagram-stream transport).  IPv4/IPv6 family coverage for the
socket transports lives in test_serf.py::test_net_transport_stream_variants;
loss/partition storms in test_transport_storms.py.
"""

import asyncio

import pytest

from serf_tpu.host import Serf, SerfState
from serf_tpu.host.dstream import DatagramStreamTransport
from serf_tpu.host.events import EventSubscriber, QueryEvent, UserEvent
from serf_tpu.host.net import NetTransport, TlsNetTransport, make_tls_contexts
from serf_tpu.host.query import QueryParam
from serf_tpu.host.transport import LoopbackNetwork
from serf_tpu.options import Options
from serf_tpu.types.member import MemberStatus

from tests.test_serf import _self_signed_cert

pytestmark = pytest.mark.asyncio

TRANSPORTS = ("loopback", "tcp", "tls", "udpstream")


class _Fabric:
    """Uniform bind/addr-of surface over all four transport flavors, with
    stable per-node addresses so a restarted node can rebind its slot."""

    def __init__(self, kind, tmp_path):
        self.kind = kind
        self.net = LoopbackNetwork() if kind == "loopback" else None
        self.tls = None
        if kind == "tls":
            cert, key = _self_signed_cert(tmp_path)
            self.tls = make_tls_contexts(cert, key)
        self.addrs = {}          # node name -> bound address

    async def bind(self, name):
        if self.kind == "loopback":
            t = self.net.bind(name)
        else:
            addr = self.addrs.get(name, ("127.0.0.1", 0))
            if self.kind == "tcp":
                t = await NetTransport.bind(addr)
            elif self.kind == "udpstream":
                t = await DatagramStreamTransport.bind(addr)
            else:
                server_ctx, client_ctx = self.tls
                t = await TlsNetTransport.bind(addr, server_ctx=server_ctx,
                                               client_ctx=client_ctx)
        self.addrs[name] = t.local_addr
        return t

    def addr(self, name):
        return self.addrs[name]


async def wait_until(cond, deadline=10.0, msg="condition"):
    loop = asyncio.get_running_loop()
    end = loop.time() + deadline
    while loop.time() < end:
        if cond():
            return
        await asyncio.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


async def _cluster(fabric, n, opts=None, subscribers=False):
    nodes, subs = [], []
    for i in range(n):
        t = await fabric.bind(f"m{i}")
        sub = EventSubscriber() if subscribers else None
        s = await Serf.create(t, opts or Options.local(), f"mx-{i}",
                              subscriber=sub)
        nodes.append(s)
        subs.append(sub)
    for s in nodes[1:]:
        await s.join(fabric.addr("m0"))
    await wait_until(lambda: all(s.num_members() == n for s in nodes),
                     msg=f"{n}-node convergence over {fabric.kind}")
    return (nodes, subs) if subscribers else nodes


async def _shutdown(nodes):
    for s in nodes:
        if s.state != SerfState.SHUTDOWN:
            await s.shutdown()


@pytest.mark.parametrize("transport", TRANSPORTS)
async def test_join_and_graceful_leave(transport, tmp_path):
    fabric = _Fabric(transport, tmp_path)
    nodes = await _cluster(fabric, 3)
    try:
        await nodes[2].leave()
        await wait_until(
            lambda: all(s._members["mx-2"].member.status == MemberStatus.LEFT
                        for s in nodes[:2]),
            msg=f"graceful leave propagates over {transport}")
    finally:
        await _shutdown(nodes)


@pytest.mark.parametrize("transport", TRANSPORTS)
async def test_user_event_disseminates(transport, tmp_path):
    fabric = _Fabric(transport, tmp_path)
    nodes, subs = await _cluster(fabric, 3, subscribers=True)
    try:
        await nodes[0].user_event("deploy", b"v2-payload", coalesce=False)

        async def saw_event(sub):
            end = asyncio.get_running_loop().time() + 10.0
            while asyncio.get_running_loop().time() < end:
                ev = await sub.next(timeout=10.0)
                if isinstance(ev, UserEvent) and ev.name == "deploy":
                    return ev
            raise AssertionError("deploy event never arrived")

        for sub in subs[1:]:
            ev = await saw_event(sub)
            assert ev.payload == b"v2-payload"
    finally:
        await _shutdown(nodes)


@pytest.mark.parametrize("transport", TRANSPORTS)
async def test_query_request_response(transport, tmp_path):
    fabric = _Fabric(transport, tmp_path)
    nodes, subs = await _cluster(fabric, 3, subscribers=True)
    responders = []

    async def respond_loop(sub, node_id):
        async for ev in sub:
            if isinstance(ev, QueryEvent) and ev.name == "whoami":
                try:
                    await ev.respond(node_id.encode())
                except (TimeoutError, ValueError):
                    pass

    try:
        for s, sub in zip(nodes[1:], subs[1:]):
            responders.append(asyncio.create_task(
                respond_loop(sub, s.local_id)))
        resp = await nodes[0].query("whoami", b"",
                                    QueryParam(timeout=5.0))
        got = await resp.collect()
        names = sorted(r.payload.decode() for r in got)
        assert names == ["mx-1", "mx-2"], \
            f"query over {transport} answered by {names}"
    finally:
        for task in responders:
            task.cancel()
        await _shutdown(nodes)


@pytest.mark.parametrize("transport", TRANSPORTS)
async def test_snapshot_restart_auto_rejoins(transport, tmp_path):
    """Crash-restart: a node with a snapshot comes back on its old address
    and auto-rejoins from the recorded alive set — no explicit join()."""
    fabric = _Fabric(transport, tmp_path)
    snap = str(tmp_path / "m2.snap")
    nodes = await _cluster(fabric, 2)
    extra = None
    try:
        t2 = await fabric.bind("m2")
        extra = await Serf.create(
            t2, Options.local(snapshot_path=snap), "mx-2")
        await extra.join(fabric.addr("m0"))
        await wait_until(lambda: all(s.num_members() == 3
                                     for s in (*nodes, extra)),
                         msg=f"3-node convergence over {transport}")
        # crash (no leave) ...
        await extra.shutdown()
        await wait_until(
            lambda: nodes[0]._members["mx-2"].member.status
            in (MemberStatus.FAILED, MemberStatus.LEFT),
            msg=f"crash detected over {transport}")
        # ... restart on the SAME address with the same snapshot
        t2b = await fabric.bind("m2")
        extra = await Serf.create(
            t2b, Options.local(snapshot_path=snap), "mx-2")
        await wait_until(
            lambda: extra.num_members() == 3
            and all(s._members["mx-2"].member.status == MemberStatus.ALIVE
                    for s in nodes),
            msg=f"snapshot auto-rejoin over {transport}")
    finally:
        await _shutdown(nodes + ([extra] if extra else []))


@pytest.mark.parametrize("transport", TRANSPORTS)
async def test_set_tags_propagates(transport, tmp_path):
    fabric = _Fabric(transport, tmp_path)
    from serf_tpu.types.tags import Tags

    nodes = await _cluster(fabric, 3)
    try:
        await nodes[1].set_tags(Tags({"role": "db", "dc": "east"}))
        await wait_until(
            lambda: all(dict(s._members["mx-1"].member.tags) ==
                        {"role": "db", "dc": "east"} for s in nodes),
            msg=f"tag update propagates over {transport}")
    finally:
        await _shutdown(nodes)
