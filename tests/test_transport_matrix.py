"""The core serf scenario suite stamped over every shipped transport —
the analog of the reference's `test_mod!` macro, which expands each
scenario over {tokio,smol} x {tcp,tls,quic} (76 files under
serf/test/main/net/**, macro at serf/test/main.rs:1-23).

Scenarios (round 5 widened the matrix from 5 to 10, VERDICT r4 next-5):
join/converge, graceful leave, user-event dissemination, query
request/response, snapshot crash-restart auto-rejoin, tag propagation,
conflict name-resolution, cluster key rotation, snapshot compaction +
restart-rejoin, remove_failed_node+prune, coalesced member events.
Transports: loopback (in-process fabric), tcp, tls, udpstream (the
QUIC-slot datagram-stream transport).  IPv4/IPv6 family coverage for the
socket transports lives in test_serf.py::test_net_transport_stream_variants;
loss/partition storms in test_transport_storms.py.
"""

import asyncio
import os

import pytest

from serf_tpu.host import Serf, SerfState
from serf_tpu.host.dstream import DatagramStreamTransport
from serf_tpu.host.events import (
    EventSubscriber,
    MemberEvent,
    MemberEventType,
    QueryEvent,
    UserEvent,
)
from serf_tpu.host.keyring import SecretKeyring
from serf_tpu.host.net import NetTransport, TlsNetTransport, make_tls_contexts
from serf_tpu.host.query import QueryParam
from serf_tpu.host.transport import LoopbackNetwork
from serf_tpu.options import Options
from serf_tpu.types.member import MemberStatus

from tests.test_serf import _self_signed_cert

pytestmark = pytest.mark.asyncio

TRANSPORTS = ("loopback", "tcp", "tls", "udpstream")


class _Fabric:
    """Uniform bind/addr-of surface over all four transport flavors, with
    stable per-node addresses so a restarted node can rebind its slot."""

    def __init__(self, kind, tmp_path):
        self.kind = kind
        self.net = LoopbackNetwork() if kind == "loopback" else None
        self.tls = None
        if kind == "tls":
            cert, key = _self_signed_cert(tmp_path)
            self.tls = make_tls_contexts(cert, key)
        self.addrs = {}          # node name -> bound address

    async def bind(self, name, keyring=None):
        if self.kind == "loopback":
            t = self.net.bind(name)
        else:
            addr = self.addrs.get(name, ("127.0.0.1", 0))
            if self.kind == "tcp":
                t = await NetTransport.bind(addr)
            elif self.kind == "udpstream":
                # the segment plane shares the cluster keyring (QUIC's
                # always-encrypted stance) — rotation tests must cover it
                t = await DatagramStreamTransport.bind(addr,
                                                       keyring=keyring)
            else:
                server_ctx, client_ctx = self.tls
                t = await TlsNetTransport.bind(addr, server_ctx=server_ctx,
                                               client_ctx=client_ctx)
        self.addrs[name] = t.local_addr
        return t

    def addr(self, name):
        return self.addrs[name]


async def wait_until(cond, deadline=10.0, msg="condition"):
    loop = asyncio.get_running_loop()
    end = loop.time() + deadline
    while loop.time() < end:
        if cond():
            return
        await asyncio.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


async def _cluster(fabric, n, opts=None, subscribers=False, keyring=None):
    """``keyring``: a zero-arg factory called once per node — each node
    owns a distinct ring object with the same material (the production
    wiring; a single shared object would make rotation propagation
    vacuous).  On udpstream the same ring also encrypts the segments."""
    nodes, subs = [], []
    for i in range(n):
        ring = keyring() if keyring else None
        t = await fabric.bind(f"m{i}", keyring=ring)
        sub = EventSubscriber() if subscribers else None
        s = await Serf.create(t, opts or Options.local(), f"mx-{i}",
                              subscriber=sub, keyring=ring)
        nodes.append(s)
        subs.append(sub)
    for s in nodes[1:]:
        await s.join(fabric.addr("m0"))
    await wait_until(lambda: all(s.num_members() == n for s in nodes),
                     msg=f"{n}-node convergence over {fabric.kind}")
    return (nodes, subs) if subscribers else nodes


async def _shutdown(nodes):
    for s in nodes:
        if s.state != SerfState.SHUTDOWN:
            await s.shutdown()


@pytest.mark.parametrize("transport", TRANSPORTS)
async def test_join_and_graceful_leave(transport, tmp_path):
    fabric = _Fabric(transport, tmp_path)
    nodes = await _cluster(fabric, 3)
    try:
        await nodes[2].leave()
        await wait_until(
            lambda: all(s._members["mx-2"].member.status == MemberStatus.LEFT
                        for s in nodes[:2]),
            msg=f"graceful leave propagates over {transport}")
    finally:
        await _shutdown(nodes)


@pytest.mark.parametrize("transport", TRANSPORTS)
async def test_user_event_disseminates(transport, tmp_path):
    fabric = _Fabric(transport, tmp_path)
    nodes, subs = await _cluster(fabric, 3, subscribers=True)
    try:
        await nodes[0].user_event("deploy", b"v2-payload", coalesce=False)

        async def saw_event(sub):
            end = asyncio.get_running_loop().time() + 10.0
            while asyncio.get_running_loop().time() < end:
                ev = await sub.next(timeout=10.0)
                if isinstance(ev, UserEvent) and ev.name == "deploy":
                    return ev
            raise AssertionError("deploy event never arrived")

        for sub in subs[1:]:
            ev = await saw_event(sub)
            assert ev.payload == b"v2-payload"
    finally:
        await _shutdown(nodes)


@pytest.mark.parametrize("transport", TRANSPORTS)
async def test_query_request_response(transport, tmp_path):
    fabric = _Fabric(transport, tmp_path)
    nodes, subs = await _cluster(fabric, 3, subscribers=True)
    responders = []

    async def respond_loop(sub, node_id):
        async for ev in sub:
            if isinstance(ev, QueryEvent) and ev.name == "whoami":
                try:
                    await ev.respond(node_id.encode())
                except (TimeoutError, ValueError):
                    pass

    try:
        for s, sub in zip(nodes[1:], subs[1:]):
            responders.append(asyncio.create_task(
                respond_loop(sub, s.local_id)))
        resp = await nodes[0].query("whoami", b"",
                                    QueryParam(timeout=5.0))
        got = await resp.collect()
        names = sorted(r.payload.decode() for r in got)
        assert names == ["mx-1", "mx-2"], \
            f"query over {transport} answered by {names}"
    finally:
        for task in responders:
            task.cancel()
        await _shutdown(nodes)


@pytest.mark.parametrize("transport", TRANSPORTS)
async def test_snapshot_restart_auto_rejoins(transport, tmp_path):
    """Crash-restart: a node with a snapshot comes back on its old address
    and auto-rejoins from the recorded alive set — no explicit join()."""
    fabric = _Fabric(transport, tmp_path)
    snap = str(tmp_path / "m2.snap")
    nodes = await _cluster(fabric, 2)
    extra = None
    try:
        t2 = await fabric.bind("m2")
        extra = await Serf.create(
            t2, Options.local(snapshot_path=snap), "mx-2")
        await extra.join(fabric.addr("m0"))
        await wait_until(lambda: all(s.num_members() == 3
                                     for s in (*nodes, extra)),
                         msg=f"3-node convergence over {transport}")
        # crash (no leave) ...
        await extra.shutdown()
        await wait_until(
            lambda: nodes[0]._members["mx-2"].member.status
            in (MemberStatus.FAILED, MemberStatus.LEFT),
            msg=f"crash detected over {transport}")
        # ... restart on the SAME address with the same snapshot
        t2b = await fabric.bind("m2")
        extra = await Serf.create(
            t2b, Options.local(snapshot_path=snap), "mx-2")
        await wait_until(
            lambda: extra.num_members() == 3
            and all(s._members["mx-2"].member.status == MemberStatus.ALIVE
                    for s in nodes),
            msg=f"snapshot auto-rejoin over {transport}")
    finally:
        await _shutdown(nodes + ([extra] if extra else []))


@pytest.mark.parametrize("transport", TRANSPORTS)
async def test_set_tags_propagates(transport, tmp_path):
    fabric = _Fabric(transport, tmp_path)
    from serf_tpu.types.tags import Tags

    nodes = await _cluster(fabric, 3)
    try:
        await nodes[1].set_tags(Tags({"role": "db", "dc": "east"}))
        await wait_until(
            lambda: all(dict(s._members["mx-1"].member.tags) ==
                        {"role": "db", "dc": "east"} for s in nodes),
            msg=f"tag update propagates over {transport}")
    finally:
        await _shutdown(nodes)


@pytest.mark.parametrize("transport", TRANSPORTS)
async def test_conflict_name_resolution(transport, tmp_path):
    """Duplicate-id conflict resolved by majority vote: the usurper shuts
    itself down, the incumbent survives (reference name_resolution.rs /
    base.rs:1658-1780) — over every transport."""
    fabric = _Fabric(transport, tmp_path)
    nodes = await _cluster(fabric, 3)
    usurper = None
    try:
        t_evil = await fabric.bind("evil")
        usurper = await Serf.create(t_evil, Options.local(), "mx-1")
        try:
            await usurper.join(fabric.addr("m0"))
        except Exception:  # noqa: BLE001 - the join itself may be refused
            pass
        await wait_until(
            lambda: usurper.state == SerfState.SHUTDOWN
            or nodes[1].state == SerfState.SHUTDOWN,
            msg=f"one duplicate-id claimant shuts down over {transport}")
        assert nodes[1].state != SerfState.SHUTDOWN, \
            "the majority incumbent lost the conflict vote"
        assert usurper.state == SerfState.SHUTDOWN
    finally:
        await _shutdown(nodes)
        if usurper is not None and usurper.state != SerfState.SHUTDOWN:
            await usurper.shutdown()


@pytest.mark.parametrize("transport", TRANSPORTS)
async def test_cluster_key_rotation(transport, tmp_path):
    """Keyring orchestration over encrypted wire traffic on every
    transport (reference key_manager.rs): install a second key, rotate
    the primary to it, remove the old key, and keep disseminating."""
    k1, k2 = bytes(range(16)), bytes(range(16, 32))
    fabric = _Fabric(transport, tmp_path)
    nodes = await _cluster(fabric, 3, keyring=lambda: SecretKeyring(k1))
    try:
        km = nodes[0].key_manager()
        out = await km.install_key(k2)
        assert out.num_resp == 3 and out.num_err == 0, out.messages
        out = await km.use_key(k2)
        assert out.num_resp == 3 and out.num_err == 0, out.messages
        await wait_until(
            lambda: all(s.memberlist.keyring().primary_key() == k2
                        for s in nodes),
            msg=f"k2 primary everywhere over {transport}")
        out = await km.remove_key(k1)
        assert out.num_resp == 3 and out.num_err == 0, out.messages
        # the cluster still disseminates over the rotated key
        await nodes[1].user_event("rotated", b"ok", coalesce=False)
        await wait_until(
            lambda: all(s.event_clock.time() >= 2 for s in nodes),
            msg=f"user event after rotation over {transport}")
    finally:
        await _shutdown(nodes)


@pytest.mark.parametrize("transport", TRANSPORTS)
async def test_snapshot_compaction_then_restart_rejoins(transport,
                                                        tmp_path):
    """Compaction under event volume, then a crash-restart that rejoins
    from the COMPACTED snapshot (reference snapshoter_force_compact.rs +
    the resume path, SURVEY.md §5 checkpoint/resume)."""
    from serf_tpu.utils import metrics as metrics_mod

    snap = str(tmp_path / "mx2.snap")
    fabric = _Fabric(transport, tmp_path)
    sink = metrics_mod.MetricsSink()
    metrics_mod.set_global_sink(sink)
    nodes, extra = [], None
    try:
        nodes = await _cluster(fabric, 2)
        t2 = await fabric.bind("m2")
        extra = await Serf.create(
            t2, Options.local(snapshot_path=snap,
                              snapshot_min_compact_size=512), "mx-2")
        await extra.join(fabric.addr("m0"))
        await wait_until(lambda: extra.num_members() == 3,
                         msg=f"3-node convergence over {transport}")
        for i in range(200):
            await extra.user_event(f"e{i}", b"payload", coalesce=False)
        # generous deadlines: 200 events + the 500 ms flush/compact
        # cadence stretch well past 10 s on a loaded CI box (liveness,
        # not latency, is what this pins — the soak-suite convention)
        await wait_until(
            lambda: len(sink.histogram("serf.snapshot.compact", {})) > 0,
            deadline=25.0, msg=f"snapshot compaction ran over {transport}")
        await wait_until(
            lambda: os.path.exists(snap)
            and os.path.getsize(snap) < 4096,
            deadline=25.0, msg="snapshot compacted below write volume")
        # crash (no leave), restart on the same address from the
        # compacted snapshot: the alive set survived compaction, so the
        # node auto-rejoins without an explicit join()
        await extra.shutdown()
        t2b = await fabric.bind("m2")
        extra = await Serf.create(
            t2b, Options.local(snapshot_path=snap,
                               snapshot_min_compact_size=512), "mx-2")
        await wait_until(
            lambda: extra.num_members() == 3
            and all(s._members["mx-2"].member.status == MemberStatus.ALIVE
                    for s in nodes),
            deadline=25.0,
            msg=f"auto-rejoin from compacted snapshot over {transport}")
    finally:
        metrics_mod.set_global_sink(metrics_mod.MetricsSink())
        await _shutdown(nodes + ([extra] if extra else []))


@pytest.mark.parametrize("transport", TRANSPORTS)
async def test_remove_failed_node_prune(transport, tmp_path):
    """Operator-driven removal of a failed member with prune: the member
    is erased from every surviving table (reference remove/ suite)."""
    fabric = _Fabric(transport, tmp_path)
    nodes = await _cluster(fabric, 3)
    try:
        await nodes[2].shutdown()
        await wait_until(
            lambda: any(m.status == MemberStatus.FAILED
                        for m in nodes[0].members()
                        if m.node.id == "mx-2"),
            msg=f"crash detected over {transport}")
        await nodes[0].remove_failed_node("mx-2", prune=True)
        await wait_until(
            lambda: all(all(m.node.id != "mx-2" for m in s.members())
                        for s in nodes[:2]),
            msg=f"prune erases the member everywhere over {transport}")
    finally:
        await _shutdown(nodes)


@pytest.mark.parametrize("transport", TRANSPORTS)
async def test_coalesced_member_events(transport, tmp_path):
    """With coalesce_period set, join events arrive merged through the
    member-event coalescer on every transport (reference coalesce/)."""
    fabric = _Fabric(transport, tmp_path)
    sub = EventSubscriber()
    t0 = await fabric.bind("m0")
    s0 = await Serf.create(
        t0, Options.local(coalesce_period=0.1, quiescent_period=0.05),
        "mx-0", subscriber=sub)
    others = []
    try:
        for i in range(1, 4):
            t = await fabric.bind(f"m{i}")
            others.append(await Serf.create(t, Options.local(), f"mx-{i}"))
        for s in others:
            await s.join(fabric.addr("m0"))
        joined = set()

        async def collect():
            while len(joined) < 4:
                ev = await sub.next(timeout=10.0)
                if isinstance(ev, MemberEvent) \
                        and ev.ty == MemberEventType.JOIN:
                    joined.update(m.node.id for m in ev.members)

        await asyncio.wait_for(collect(), 10.0)
        assert joined == {"mx-0", "mx-1", "mx-2", "mx-3"}, joined
    finally:
        await _shutdown([s0, *others])
