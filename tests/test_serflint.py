"""serflint tier-1 contract (ISSUE 8).

- golden fixtures: per rule, one intentionally-bad snippet that MUST
  fire and one clean twin that must NOT (tests/serflint_fixtures/);
- suppression comments (mandatory reason) and the baseline round-trip;
- schema drift: changing a pytree leaf or a wire field without bumping
  the pinned fingerprint fails lint (toy-project fixture), and the
  runtime guards (checkpoint stamp, codec export) agree with the pins;
- the repo gate: ``tools/serflint.py --json`` exits 0 with zero new
  findings, in well under the 30 s acceptance bound.

Everything here runs the analyzer in-process on toy projects under
tmp_path (fixture files are copied to the path the rule scopes expect);
only the repo gate shells out, mirroring the chaos/obstop tier-1 hooks.
"""

import json
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "serflint_fixtures"

sys.path.insert(0, str(REPO))

from serf_tpu import analysis                               # noqa: E402
from serf_tpu.analysis import schema as schema_mod          # noqa: E402
from serf_tpu.analysis.core import Project, Registry        # noqa: E402


def toy_project(tmp_path, files, readme=None, registry=None,
                baseline=False, pins=False) -> Project:
    """Materialize a toy project tree and return its Project config."""
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text)
    readme_path = None
    if readme is not None:
        readme_path = tmp_path / "README.md"
        readme_path.write_text(readme)
    return Project(
        root=tmp_path, scan=("serf_tpu",), metric_scan=("serf_tpu",),
        readme=readme_path,
        baseline_path=(tmp_path / "baseline.json") if baseline else None,
        pins_path=(tmp_path / "pins.json") if pins else None,
        registry=registry)


def rules_fired(report):
    return {f.rule for f in report.findings}


def count(report, rule):
    return sum(1 for f in report.findings if f.rule == rule)


# ---------------------------------------------------------------------------
# async family: fixtures fire / clean twins don't
# ---------------------------------------------------------------------------


def test_async_bad_fixture_fires_every_rule(tmp_path):
    project = toy_project(tmp_path, {
        "serf_tpu/host/fake.py": (FIXTURES / "bad_async.py").read_text()})
    report = analysis.run_rules(project)
    assert count(report, "async-fire-forget") == 3
    assert count(report, "async-blocking-call") == 1
    assert count(report, "async-lock-await") == 2
    assert count(report, "async-shared-mut") == 1


def test_async_clean_twin_is_silent(tmp_path):
    project = toy_project(tmp_path, {
        "serf_tpu/host/fake.py": (FIXTURES / "ok_async.py").read_text()})
    report = analysis.run_rules(project)
    assert report.findings == []


# ---------------------------------------------------------------------------
# pipeline-bypass (the MPMC hand-off seam)
# ---------------------------------------------------------------------------


def test_pipeline_bad_fixture_fires_every_pattern(tmp_path):
    project = toy_project(tmp_path, {
        "serf_tpu/host/fake.py": (FIXTURES / "bad_pipeline.py").read_text()})
    report = analysis.run_rules(project, rules=["pipeline-bypass"])
    # queue ctor + put_nowait + put + internals reach
    assert count(report, "pipeline-bypass") == 4


def test_pipeline_clean_twin_is_silent(tmp_path):
    project = toy_project(tmp_path, {
        "serf_tpu/host/fake.py": (FIXTURES / "ok_pipeline.py").read_text()})
    report = analysis.run_rules(project, rules=["pipeline-bypass"])
    assert count(report, "pipeline-bypass") == 0


def test_pipeline_rule_exempts_queue_owning_modules(tmp_path):
    """The SAME bad file inside a queue-owning module (the subscriber
    channel, the transports) fires only the internals-reach pattern —
    those modules legitimately construct/drive their own queues."""
    project = toy_project(tmp_path, {
        "serf_tpu/host/events.py": (FIXTURES / "bad_pipeline.py")
        .read_text()})
    report = analysis.run_rules(project, rules=["pipeline-bypass"])
    assert count(report, "pipeline-bypass") == 1      # _pipeline._ready


# ---------------------------------------------------------------------------
# JAX family (scoped to serf_tpu/models|ops|parallel paths)
# ---------------------------------------------------------------------------


def test_jax_bad_fixture_fires_every_rule(tmp_path):
    project = toy_project(tmp_path, {
        "serf_tpu/models/fake.py": (FIXTURES / "bad_jax.py").read_text()})
    report = analysis.run_rules(project)
    assert count(report, "jax-python-branch") == 2      # if + scan while
    assert count(report, "jax-host-concretize") == 2    # float() + .item()
    assert count(report, "jax-host-transfer") == 2      # asarray + device_get
    assert count(report, "jax-unhashable-arg") == 1


def test_jax_clean_twin_is_silent(tmp_path):
    project = toy_project(tmp_path, {
        "serf_tpu/models/fake.py": (FIXTURES / "ok_jax.py").read_text()})
    report = analysis.run_rules(project)
    assert report.findings == []


def test_jax_rules_scope_outside_device_plane(tmp_path):
    """The same bad file OUTSIDE models/ops/parallel trips only the
    path-agnostic families — the JAX passes are scoped."""
    project = toy_project(tmp_path, {
        "serf_tpu/host/fake.py": (FIXTURES / "bad_jax.py").read_text()})
    report = analysis.run_rules(project)
    assert not any(r.startswith("jax-") for r in rules_fired(report))


# ---------------------------------------------------------------------------
# registry family
# ---------------------------------------------------------------------------

_EMITTER = '''\
from wherever import flight, metrics


def emit():
    metrics.incr("serf.fixture.good")
    metrics.gauge("serf.fixture.rogue", 1)
    flight.record("good-kind", detail=1)
    flight.record("rogue-kind")
'''

_README_OBS = '''\
## Observability

| Metric | type | labels | doc |
|---|---|---|---|
| `serf.fixture.good` | counter | — | fine |
'''


def test_registry_cross_checks_fire(tmp_path):
    project = toy_project(
        tmp_path, {"serf_tpu/fake.py": _EMITTER}, readme=_README_OBS,
        registry=Registry(
            metrics=frozenset({"serf.fixture.good", "serf.fixture.unused"}),
            flight_kinds=frozenset({"good-kind", "unused-kind"})))
    report = analysis.run_rules(project)
    by_key = {(f.rule, f.key) for f in report.findings}
    assert ("reg-metric-unknown", "serf.fixture.rogue") in by_key
    assert ("reg-metric-unused", "serf.fixture.unused") in by_key
    assert ("reg-flight-unknown", "rogue-kind") in by_key
    assert ("reg-flight-unused", "unused-kind") in by_key
    # registry declares serf.fixture.unused but README has no row
    assert ("reg-doc-drift", "serf.fixture.unused") in by_key


def test_registry_in_sync_is_silent(tmp_path):
    readme = _README_OBS + "| `serf.fixture.rogue` | gauge | — | now ok |\n"
    project = toy_project(
        tmp_path, {"serf_tpu/fake.py": _EMITTER}, readme=readme,
        registry=Registry(
            metrics=frozenset({"serf.fixture.good", "serf.fixture.rogue"}),
            flight_kinds=frozenset({"good-kind", "rogue-kind"})))
    report = analysis.run_rules(
        project, rules=["reg-metric-unknown", "reg-metric-unused",
                        "reg-doc-drift", "reg-flight-unknown",
                        "reg-flight-unused"])
    assert report.findings == []


# ---------------------------------------------------------------------------
# SLO family (ISSUE 10): the SLO table is registry-governed
# ---------------------------------------------------------------------------

_TOY_SLO_REGISTRY = dict(
    metrics=frozenset({"serf.toy.counter"}),
    flight_kinds=frozenset(),
    slos=frozenset({"toy-slo", "declared-but-undefined"}))

_README_SLO = '''\
## Time series & SLOs

| SLO | Planes | Objective | Meaning |
|---|---|---|---|
| `toy-slo` | host | 1.0 | fine |
| `declared-but-undefined` | host | 1.0 | fine |
'''


def test_slo_bad_fixture_fires_the_family(tmp_path):
    project = toy_project(
        tmp_path,
        {"serf_tpu/obs/fake_slo.py": (FIXTURES / "bad_slo.py").read_text()},
        readme=_README_SLO, registry=Registry(**_TOY_SLO_REGISTRY))
    report = analysis.run_rules(project)
    by_key = {(f.rule, f.key) for f in report.findings}
    # toy-slo watches an undeclared metric
    assert ("slo-metric-unknown",
            "toy-slo:serf.not.declared") in by_key
    # rogue-slo is defined but not declared; the registry's second
    # declared SLO has no definition — drift both ways
    assert ("slo-decl-drift", "rogue-slo") in by_key
    assert ("slo-decl-drift", "declared-but-undefined") in by_key
    # rogue-slo is also undocumented... but slo-doc-drift judges
    # declared-vs-documented: the README documents only declared names
    # here, so no doc finding for rogue-slo (decl drift covers it)
    assert not any(r == "slo-doc-drift" and k == "rogue-slo"
                   for r, k in by_key)


def test_slo_clean_twin_is_silent(tmp_path):
    readme = '''\
## Time series & SLOs

| SLO | Planes | Objective | Meaning |
|---|---|---|---|
| `toy-slo` | host+device | 1.0 | fine |
'''
    project = toy_project(
        tmp_path,
        {"serf_tpu/obs/fake_slo.py": (FIXTURES / "ok_slo.py").read_text()},
        readme=readme,
        registry=Registry(metrics=frozenset({"serf.toy.counter"}),
                          flight_kinds=frozenset(),
                          slos=frozenset({"toy-slo"})))
    report = analysis.run_rules(
        project, rules=["slo-metric-unknown", "slo-decl-drift",
                        "slo-doc-drift"])
    assert report.findings == []


def test_slo_doc_drift_both_ways(tmp_path):
    readme = '''\
## Time series & SLOs

| SLO | Planes | Objective | Meaning |
|---|---|---|---|
| `stale-row` | host | 1.0 | no such SLO |
'''
    project = toy_project(
        tmp_path,
        {"serf_tpu/obs/fake_slo.py": (FIXTURES / "ok_slo.py").read_text()},
        readme=readme,
        registry=Registry(metrics=frozenset({"serf.toy.counter"}),
                          flight_kinds=frozenset(),
                          slos=frozenset({"toy-slo"})))
    report = analysis.run_rules(project, rules=["slo-doc-drift"])
    keys = {f.key for f in report.findings}
    assert keys == {"toy-slo", "stale-row"}   # missing row + stale row


# ---------------------------------------------------------------------------
# control-knob family (ISSUE 11): the adaptive control plane is
# registry-governed — a knob without a law, or a law on an undeclared
# knob, fails lint
# ---------------------------------------------------------------------------


def test_control_knob_bad_fixture_fires_every_direction(tmp_path):
    project = toy_project(
        tmp_path,
        {"serf_tpu/control/device.py":
         (FIXTURES / "bad_control.py").read_text()},
        registry=Registry(metrics=frozenset(), flight_kinds=frozenset(),
                         control_knobs=frozenset({"fanout",
                                                  "probe_mult"})))
    report = analysis.run_rules(project, rules=["control-knob-drift"])
    keys = {f.key for f in report.findings}
    assert "field:rogue_knob" in keys       # undeclared knob field
    assert "lawless:rogue_knob" in keys     # knob with no law
    assert "law:undeclared_law_knob" in keys  # law on undeclared knob
    assert "undefined:probe_mult" in keys   # declared, defined nowhere


def test_control_knob_clean_twin_is_silent(tmp_path):
    project = toy_project(
        tmp_path,
        {"serf_tpu/control/device.py":
         (FIXTURES / "ok_control.py").read_text()},
        registry=Registry(metrics=frozenset(), flight_kinds=frozenset(),
                         control_knobs=frozenset({"fanout"})))
    report = analysis.run_rules(project, rules=["control-knob-drift"])
    assert report.findings == []


# ---------------------------------------------------------------------------
# telemetry-field-drift (the in-collective merge contract, ISSUE 15)
# ---------------------------------------------------------------------------

_README_TELEMETRY = """\
## Zero-cost telemetry & timeline export

| Field | Merge | Notes |
|---|---|---|
| `alive` | sum | fine |
| `stale_field` | sum | row removed from the code |
"""


def test_telemetry_bad_fixture_fires_every_direction(tmp_path):
    project = toy_project(
        tmp_path,
        {"serf_tpu/models/swim.py":
         (FIXTURES / "bad_telemetry.py").read_text()},
        readme=_README_TELEMETRY)
    report = analysis.run_rules(project, rules=["telemetry-field-drift"])
    keys = {f.key for f in report.findings}
    assert "unreduced:orphan_field" in keys    # row field, no merge leg
    assert "undeclared:ghost_field" in keys    # merge leg, no row field
    assert "bad-op:alive" in keys              # op no leg implements
    assert "undocumented:orphan_field" in keys # row field, no README row
    assert "stale-row:stale_field" in keys     # README row, no field


def test_telemetry_clean_twin_is_silent(tmp_path):
    readme = ("## Zero-cost telemetry & timeline export\n\n"
              "| Field | Merge | Notes |\n|---|---|---|\n"
              "| `alive` | sum | — |\n| `agreement` | sum | — |\n")
    project = toy_project(
        tmp_path,
        {"serf_tpu/models/swim.py":
         (FIXTURES / "ok_telemetry.py").read_text()},
        readme=readme)
    report = analysis.run_rules(project, rules=["telemetry-field-drift"])
    assert report.findings == []


# ---------------------------------------------------------------------------
# propagation-field-drift (the propagation-row merge contract, ISSUE 16)
# ---------------------------------------------------------------------------

_README_PROPAGATION = """\
## Propagation observability

| Field | Merge | Notes |
|---|---|---|
| `slots_sent` | sum | fine |
| `stale_field` | sum | row removed from the code |
"""


def test_propagation_bad_fixture_fires_every_direction(tmp_path):
    project = toy_project(
        tmp_path,
        {"serf_tpu/obs/propagation.py":
         (FIXTURES / "bad_propagation.py").read_text()},
        readme=_README_PROPAGATION)
    report = analysis.run_rules(project,
                                rules=["propagation-field-drift"])
    keys = {f.key for f in report.findings}
    assert "unreduced:orphan_field" in keys    # row field, no merge leg
    assert "undeclared:ghost_field" in keys    # merge leg, no row field
    assert "bad-op:slots_sent" in keys         # op no leg implements
    assert "undocumented:orphan_field" in keys # row field, no README row
    assert "stale-row:stale_field" in keys     # README row, no field


def test_propagation_clean_twin_is_silent(tmp_path):
    readme = ("## Propagation observability\n\n"
              "| Field | Merge | Notes |\n|---|---|---|\n"
              "| `slots_sent` | sum | — |\n"
              "| `cov_min` | replicated | — |\n")
    project = toy_project(
        tmp_path,
        {"serf_tpu/obs/propagation.py":
         (FIXTURES / "ok_propagation.py").read_text()},
        readme=readme)
    report = analysis.run_rules(project,
                                rules=["propagation-field-drift"])
    assert report.findings == []


# ---------------------------------------------------------------------------
# invariant-field-drift (the watchdog invariant-row contract, ISSUE 17)
# ---------------------------------------------------------------------------

_README_INVARIANT = """\
## Continuous verification & black box

| Field | Merge | Notes |
|---|---|---|
| `overflow_ok` | replicated | fine |
| `stale_ok` | replicated | row removed from the code |
"""


def test_invariant_bad_fixture_fires_every_direction(tmp_path):
    project = toy_project(
        tmp_path,
        {"serf_tpu/obs/watchdog.py":
         (FIXTURES / "bad_invariant.py").read_text()},
        readme=_README_INVARIANT)
    report = analysis.run_rules(project,
                                rules=["invariant-field-drift"])
    keys = {f.key for f in report.findings}
    assert "unreduced:orphan_ok" in keys      # row field, no merge leg
    assert "undeclared:ghost_ok" in keys      # merge leg, no row field
    assert "bad-op:overflow_ok" in keys       # op no leg implements
    assert "undocumented:orphan_ok" in keys   # row field, no README row
    assert "stale-row:stale_ok" in keys       # README row, no field


def test_invariant_clean_twin_is_silent(tmp_path):
    readme = ("## Continuous verification & black box\n\n"
              "| Field | Merge | Notes |\n|---|---|---|\n"
              "| `overflow_ok` | replicated | — |\n"
              "| `viol_mask` | replicated | — |\n")
    project = toy_project(
        tmp_path,
        {"serf_tpu/obs/watchdog.py":
         (FIXTURES / "ok_invariant.py").read_text()},
        readme=readme)
    report = analysis.run_rules(project,
                                rules=["invariant-field-drift"])
    assert report.findings == []


# ---------------------------------------------------------------------------
# schema family: drift without a bump fails lint; bump clears it
# ---------------------------------------------------------------------------

_TOY_PYTREE = '''\
from typing import NamedTuple


class GossipState(NamedTuple):
    known: int
    stamp: int
'''

_TOY_WIRE = '''\
class JoinMessage:
    ltime: int
    id: str

    TYPE = 2

    def encode_body(self):
        return codec.encode_varint_field(1, self.ltime) \\
            + codec.encode_str_field(2, self.id)

    @classmethod
    def decode_body(cls, buf):
        for f, _wt, v, _p in codec.iter_fields(buf):
            if f == 1:
                lt = v
            elif f == 2:
                nid = v
        return cls(lt, nid)
'''


_TOY_RECORDING = '''\
RECORDING_SCHEMA = {
    "header": ("v", "plane"),
    "view": ("seq", "digest"),
}
'''


_TOY_BLACKBOX = '''\
BLACKBOX_SCHEMA = {
    "meta": ("schema", "version", "node"),
    "flight": ("events",),
}
'''


def _schema_project(tmp_path):
    project = toy_project(tmp_path, {
        "serf_tpu/models/dissemination.py": _TOY_PYTREE,
        "serf_tpu/types/messages.py": _TOY_WIRE,
        "serf_tpu/replay/recording.py": _TOY_RECORDING,
        "serf_tpu/obs/blackbox.py": _TOY_BLACKBOX,
    }, pins=True)
    schema_mod.bump_pins(root=tmp_path, path=project.pins_path)
    return project


SCHEMA_RULES = ["schema-pytree-drift", "schema-wire-drift",
                "schema-recording-drift", "schema-blackbox-drift"]


def test_schema_pinned_is_silent(tmp_path):
    project = _schema_project(tmp_path)
    report = analysis.run_rules(project, rules=SCHEMA_RULES)
    assert report.findings == []


def test_pytree_leaf_change_without_bump_fails(tmp_path):
    project = _schema_project(tmp_path)
    p = tmp_path / "serf_tpu/models/dissemination.py"
    p.write_text(p.read_text() + "    tombstone: int\n")
    report = analysis.run_rules(project, rules=SCHEMA_RULES)
    assert rules_fired(report) == {"schema-pytree-drift"}
    # the deliberate bump clears it and advances the version
    before = json.loads(project.pins_path.read_text())
    schema_mod.bump_pins(root=tmp_path, path=project.pins_path)
    after = json.loads(project.pins_path.read_text())
    assert after["pytree"]["version"] == before["pytree"]["version"] + 1
    assert after["wire"] == before["wire"]
    report = analysis.run_rules(project, rules=SCHEMA_RULES)
    assert report.findings == []


def test_wire_field_change_without_bump_fails(tmp_path):
    project = _schema_project(tmp_path)
    p = tmp_path / "serf_tpu/types/messages.py"
    p.write_text(p.read_text().replace(
        "codec.encode_str_field(2, self.id)",
        "codec.encode_str_field(3, self.id)"))
    report = analysis.run_rules(project, rules=SCHEMA_RULES)
    assert rules_fired(report) == {"schema-wire-drift"}


def test_recording_field_change_without_bump_fails(tmp_path):
    project = _schema_project(tmp_path)
    p = tmp_path / "serf_tpu/replay/recording.py"
    p.write_text(p.read_text().replace('"seq", "digest"',
                                       '"seq", "digest", "nodes"'))
    report = analysis.run_rules(project, rules=SCHEMA_RULES)
    assert rules_fired(report) == {"schema-recording-drift"}
    schema_mod.bump_pins(root=tmp_path, path=project.pins_path)
    report = analysis.run_rules(project, rules=SCHEMA_RULES)
    assert report.findings == []


def test_blackbox_field_change_without_bump_fails(tmp_path):
    project = _schema_project(tmp_path)
    p = tmp_path / "serf_tpu/obs/blackbox.py"
    p.write_text(p.read_text().replace('("events",)',
                                       '("events", "dropped")'))
    report = analysis.run_rules(project, rules=SCHEMA_RULES)
    assert rules_fired(report) == {"schema-blackbox-drift"}
    schema_mod.bump_pins(root=tmp_path, path=project.pins_path)
    report = analysis.run_rules(project, rules=SCHEMA_RULES)
    assert report.findings == []


def test_repo_pins_match_current_sources():
    """The committed pins match the committed schemas — a PR that edits
    GossipState or a wire message without --bump-schema fails HERE
    (and in the repo gate below)."""
    pins = schema_mod.load_pins()
    assert pins["pytree"]["fingerprint"] == schema_mod.pytree_fingerprint()
    assert pins["wire"]["fingerprint"] == schema_mod.wire_fingerprint()
    assert pins["recording"]["fingerprint"] \
        == schema_mod.recording_fingerprint()
    assert pins["blackbox"]["fingerprint"] \
        == schema_mod.blackbox_fingerprint()
    # the specs cover the real surface
    spec = schema_mod.pytree_spec(REPO)
    assert set(spec) == {"FactTable", "GossipState", "VivaldiState",
                         "ClusterState", "ControlState"}
    assert "tombstone" in spec["GossipState"]
    assert "knobs" in spec["ControlState"]
    wire = schema_mod.wire_spec(REPO)
    assert "JoinMessage" in wire and "MessageType" in wire
    assert wire["MessageType"]["members"]["QUERY"] == 5


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

_BLOCKING = '''\
import asyncio
import time


async def f():
    time.sleep(1){suffix}
'''


def test_suppression_with_reason_silences(tmp_path):
    src = _BLOCKING.format(
        suffix="  # serflint: ignore[async-blocking-call] -- fixture: "
               "proving the suppression path")
    project = toy_project(tmp_path, {"serf_tpu/fake.py": src})
    report = analysis.run_rules(project)
    assert report.findings == []
    assert len(report.suppressed) == 1


def test_suppression_without_reason_is_a_finding(tmp_path):
    src = _BLOCKING.format(
        suffix="  # serflint: ignore[async-blocking-call]")
    project = toy_project(tmp_path, {"serf_tpu/fake.py": src})
    report = analysis.run_rules(project)
    # the original finding is suppressed, but the bare ignore is flagged
    assert rules_fired(report) == {"suppress-no-reason"}


def test_suppression_on_preceding_comment_line(tmp_path):
    src = ('import asyncio\nimport time\n\n\nasync def f():\n'
           '    # serflint: ignore[async-blocking-call] -- fixture: the\n'
           '    # reason wraps onto a second comment line\n'
           '    time.sleep(1)\n')
    project = toy_project(tmp_path, {"serf_tpu/fake.py": src})
    report = analysis.run_rules(project)
    assert report.findings == []
    assert len(report.suppressed) == 1


def test_unused_suppression_is_a_finding(tmp_path):
    src = ('import asyncio\n\n\nasync def f():\n'
           '    await asyncio.sleep(1)  '
           '# serflint: ignore[async-blocking-call] -- stale\n')
    project = toy_project(tmp_path, {"serf_tpu/fake.py": src})
    report = analysis.run_rules(project)
    assert rules_fired(report) == {"suppress-unused"}


def test_suppression_grammar_in_strings_is_inert(tmp_path):
    src = ('DOC = "use # serflint: ignore[async-blocking-call] -- reason"\n')
    project = toy_project(tmp_path, {"serf_tpu/fake.py": src})
    report = analysis.run_rules(project)
    assert report.findings == []


# ---------------------------------------------------------------------------
# baseline round-trip
# ---------------------------------------------------------------------------


def test_baseline_round_trip(tmp_path):
    bad = (FIXTURES / "bad_async.py").read_text()
    project = toy_project(tmp_path, {"serf_tpu/fake.py": bad},
                          baseline=True)
    n = len(analysis.run_rules(project).findings)
    assert n > 0

    # --fix-baseline grandfathers everything, but with TODO reasons the
    # gate refuses until a human annotates them
    wrote = analysis.fix_baseline(project)
    assert wrote == n
    report = analysis.run_rules(project)
    assert rules_fired(report) == {"baseline-no-reason"}
    assert len(report.baselined) == n

    # annotating every reason makes the gate green
    data = json.loads(project.baseline_path.read_text())
    for e in data["entries"]:
        e["reason"] = "fixture: justified"
    project.baseline_path.write_text(json.dumps(data))
    report = analysis.run_rules(project)
    assert report.findings == []
    assert len(report.baselined) == n

    # fixing the code makes every entry stale — loudly
    (tmp_path / "serf_tpu/fake.py").write_text(
        (FIXTURES / "ok_async.py").read_text())
    report = analysis.run_rules(project)
    assert rules_fired(report) == {"baseline-stale"}
    assert len(report.findings) == n


# ---------------------------------------------------------------------------
# docs pass
# ---------------------------------------------------------------------------


def test_docs_rule_table_enforced_both_ways(tmp_path):
    readme = ("## Static analysis\n\n| Rule | Catches | Example |\n"
              "|---|---|---|\n| `no-such-rule` | x | y |\n")
    project = toy_project(tmp_path, {"serf_tpu/fake.py": "x = 1\n"},
                          readme=readme)
    report = analysis.run_rules(project, rules=["docs-rule-table"])
    keys = {f.key for f in report.findings}
    assert "no-such-rule" in keys                  # stale row
    assert "async-fire-forget" in keys             # missing row


# ---------------------------------------------------------------------------
# runtime guards agree with the pins
# ---------------------------------------------------------------------------


def test_checkpoint_stamps_and_checks_schema_version(tmp_path):
    import numpy as np
    from serf_tpu.models import checkpoint
    from serf_tpu.models.dissemination import GossipConfig, make_state

    cfg = GossipConfig(n=32, k_facts=32)
    path = str(tmp_path / "ck.npz")
    checkpoint.save(path, make_state(cfg))
    with np.load(path) as data:
        assert int(data["__pytree_schema_version__"]) \
            == schema_mod.pytree_schema_version()
    checkpoint.restore(path, make_state(cfg))      # same version: fine

    tampered = dict(np.load(path))
    tampered["__pytree_schema_version__"] = np.asarray(
        schema_mod.pytree_schema_version() + 1, np.int64)
    path2 = str(tmp_path / "ck2.npz")
    with open(path2, "wb") as f:
        np.savez(f, **tampered)
    with pytest.raises(ValueError, match="MIGRATION.md"):
        checkpoint.restore(path2, make_state(cfg))


def test_codec_exports_wire_schema_version():
    from serf_tpu import codec
    assert codec.WIRE_SCHEMA_VERSION \
        == schema_mod.load_pins()["wire"]["version"]


# ---------------------------------------------------------------------------
# the tier-1 repo gate (like chaos.py --self-check)
# ---------------------------------------------------------------------------


def test_serflint_repo_gate_zero_new_findings():
    """``tools/serflint.py --json`` exits 0 on the repo: zero new
    findings over the reason-annotated baseline, in <30 s (acceptance
    bound; pure AST keeps it in single digits)."""
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "serflint.py"), "--json"],
        capture_output=True, text=True, timeout=120,
        env={"PATH": "/usr/bin:/bin", "PYTHONPATH": str(REPO)})
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout)
    assert out["findings"] == []
    assert out["stale_baseline"] == []
    # every baseline entry carries a real reason (gate-enforced too)
    for e in json.loads((REPO / "serflint_baseline.json").read_text(
            ))["entries"]:
        assert e["reason"] and not e["reason"].upper().startswith(
            ("TODO", "FIXME"))
    assert elapsed < 30, f"serflint took {elapsed:.1f}s (budget 30s)"


def test_rule_registry_is_exactly_the_shipped_set():
    """Adding a rule without extending the fixtures/README fails here
    on purpose — every rule ships with its golden fixtures."""
    assert set(analysis.ALL_RULES) == {
        "async-fire-forget", "async-blocking-call", "async-lock-await",
        "async-shared-mut", "pipeline-bypass",
        "jax-python-branch", "jax-host-concretize", "jax-host-transfer",
        "jax-unhashable-arg",
        "reg-metric-unknown", "reg-metric-unused", "reg-doc-drift",
        "reg-flight-unknown", "reg-flight-unused",
        "slo-metric-unknown", "slo-decl-drift", "slo-doc-drift",
        "control-knob-drift", "telemetry-field-drift",
        "propagation-field-drift", "invariant-field-drift",
        "schema-pytree-drift", "schema-wire-drift",
        "schema-recording-drift", "schema-blackbox-drift",
        "docs-rule-table",
        "suppress-no-reason", "suppress-unused",
        "baseline-stale", "baseline-no-reason",
    }
