"""Cluster-plane observability (PR 2): cross-node trace propagation, node
health scoring, and gossip-native `_serf_stats` aggregation.

Acceptance pins:

- on a 3-node in-proc cluster, ``Serf.cluster_stats()`` returns a
  ``ClusterSnapshot`` covering all 3 nodes with per-node health scores;
- a query initiated on node A yields flight-recorder entries sharing one
  trace id on at least 2 nodes;
- the ``tools/obstop.py --json`` self-check (the tier-1 cluster-plane
  contract hook) exits 0 and reports a complete snapshot.
"""

import asyncio
import json
import subprocess
import sys
from pathlib import Path

import pytest

from serf_tpu import codec, obs
from serf_tpu.obs.cluster import (
    ClusterSnapshot,
    decode_node_stats,
    fold_snapshot,
    membership_digest,
    render_table,
)
from serf_tpu.obs.flight import FlightRecorder
from serf_tpu.obs.health import (
    DEFAULT_SPECS,
    HealthScorer,
    UNHEALTHY_THRESHOLD,
)
from serf_tpu.obs.trace import (
    TraceBuffer,
    TraceContext,
    current_trace,
    new_trace,
    span,
    trace_scope,
)
from serf_tpu.types.member import Node
from serf_tpu.types.messages import (
    QueryFlag,
    QueryMessage,
    QueryResponseMessage,
    UserEventMessage,
    decode_message,
    encode_message,
)
from serf_tpu.utils import metrics
from serf_tpu.utils.metrics import MetricsSink

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def fresh_obs():
    """Isolate every test: fresh sink, trace ring, flight ring; restore
    the previous globals afterwards."""
    old_sink = metrics.global_sink()
    old_tracer = obs.global_tracer()
    old_rec = obs.global_recorder()
    metrics.set_global_sink(MetricsSink())
    obs.set_global_tracer(TraceBuffer())
    obs.set_global_recorder(FlightRecorder())
    yield
    metrics.set_global_sink(old_sink)
    obs.set_global_tracer(old_tracer)
    obs.set_global_recorder(old_rec)


# -- TraceContext ------------------------------------------------------------


def test_trace_context_roundtrip_and_hop():
    tc = new_trace("node-a")
    assert len(tc.trace_id) == 16 and tc.hops == 0
    decoded = TraceContext.decode(tc.encode())
    assert decoded == tc
    hopped = tc.hop()
    assert hopped.trace_id == tc.trace_id
    assert hopped.hops == 1 and tc.hops == 0  # immutable
    assert TraceContext.decode(hopped.encode()) == hopped


def test_trace_context_rejects_bad_id_length():
    bad = TraceContext(b"short", "node-a", 0)
    with pytest.raises(codec.DecodeError):
        TraceContext.decode(bad.encode())


def test_trace_scope_stamps_spans_and_flight_events():
    tc = new_trace("node-a")
    assert current_trace() is None
    with trace_scope(tc):
        assert current_trace() is tc
        with span("traced-op"):
            obs.record("some-event", node="node-a")
    assert current_trace() is None
    (d,) = obs.trace_dump(name="traced-op")
    assert d["attrs"]["trace"] == tc.hex_id
    (e,) = obs.flight_dump(kind="some-event")
    assert e["trace"] == tc.hex_id
    # None scope is a no-op: nothing stamped
    with trace_scope(None):
        obs.record("other-event")
    (e2,) = obs.flight_dump(kind="other-event")
    assert "trace" not in e2


# -- wire carriage -----------------------------------------------------------


def test_messages_carry_trace_context():
    tc = new_trace("origin-node")
    q = QueryMessage(ltime=7, id=42, from_node=Node("origin-node"),
                     name="status", payload=b"ping", tctx=tc)
    assert decode_message(encode_message(q)).tctx == tc
    ue = UserEventMessage(3, "deploy", b"v2", True, tc)
    assert decode_message(encode_message(ue)).tctx == tc
    qr = QueryResponseMessage(7, 42, Node("responder"), QueryFlag.NONE,
                              b"pong", tc)
    assert decode_message(encode_message(qr)).tctx == tc


def test_messages_without_trace_context_decode_to_none():
    # pre-PR-2 bytes (no tctx field) must decode cleanly — and a message
    # encoded without a context round-trips to None, not a fabricated one
    q = QueryMessage(ltime=7, id=42, from_node=Node("a"), name="status")
    decoded = decode_message(encode_message(q))
    assert decoded.tctx is None
    assert decoded == q


# -- health scoring ----------------------------------------------------------


def test_health_scorer_perfect_and_saturated():
    signals = {"probe": 0.0, "queue": 0.0, "tee": 0.0, "loop-lag": 0.0,
               "flight-drop": 0.0, "transport": 0.0}
    scorer = HealthScorer({k: (lambda k=k: signals[k]) for k in signals})
    assert scorer.sample().score == 100
    # saturate everything: weights sum to 100, so the score bottoms at 0.
    # counter components need TWO samples (they score growth).
    signals.update({"probe": 5.0, "queue": 5.0, "tee": 5.0,
                    "loop-lag": 1e6, "flight-drop": 1e6, "transport": 1e6})
    scorer.sample()
    signals.update({"flight-drop": 2e6, "transport": 2e6})
    assert scorer.sample().score == 0


def test_health_scorer_single_component_and_delta_healing():
    vals = {"transport": 0.0}
    scorer = HealthScorer({"transport": lambda: vals["transport"]})
    assert scorer.sample().score == 100
    spec = DEFAULT_SPECS["transport"]
    vals["transport"] = spec.saturation  # full burst in one window
    r = scorer.sample()
    assert r.score == int(round(100 - spec.weight))
    assert r.components["transport"].load == 1.0
    # counter stops growing -> the penalty heals on the next sample
    assert scorer.sample().score == 100
    # non-consuming reads (stats(), _serf_stats) observe the growth since
    # the last monitor tick WITHOUT shrinking the window: polling cannot
    # flatten a burst
    vals["transport"] += spec.saturation
    r1 = scorer.sample(consume=False)
    r2 = scorer.sample(consume=False)
    assert r1.score == r2.score == int(round(100 - spec.weight))
    assert scorer.sample(consume=True).score == r1.score
    assert scorer.sample().score == 100  # window advanced, burst healed


def test_health_scorer_broken_source_contributes_zero():
    def boom():
        raise RuntimeError("sensor failed")
    scorer = HealthScorer({"probe": boom})
    assert scorer.sample().score == 100


def test_unhealthy_threshold_partitions_fold():
    nodes = {
        "good": {"v": 1, "id": "good", "health": 100, "hc": {},
                 "q": [0, 0, 0], "lag": 0.0, "digest": "aaa"},
        "bad": {"v": 1, "id": "bad", "health": UNHEALTHY_THRESHOLD - 1,
                "hc": {}, "q": [0, 0, 0], "lag": 0.0, "digest": "bbb"},
    }
    snap = fold_snapshot("good", 2, nodes)
    assert snap.unhealthy == ["bad"]
    assert snap.divergent  # two distinct digests
    assert snap.aggregates["health"]["min"] == UNHEALTHY_THRESHOLD - 1
    assert snap.aggregates["health"]["max"] == 100.0


# -- stats payload / fold ----------------------------------------------------


def _report(nid, health, lag=0.0, digest="aaa"):
    return {"v": 1, "id": nid, "health": health, "hc": {},
            "q": [0, 0, 0], "lag": lag, "digest": digest}


def test_stats_partial_merge_is_fold_of_union():
    """The partial-merge contract (ISSUE 15: the host twin of the
    device TELEMETRY_MERGE legs): any grouping AND order of merges over
    disjoint responder subsets finishes to exactly the direct fold of
    the union — min/p50/max, unhealthy list, digest divergence all."""
    from serf_tpu.obs.cluster import StatsPartial

    nodes = {f"n{i}": _report(f"n{i}", health=40 + 10 * i, lag=float(i),
                              digest="aaa" if i % 2 else "bbb")
             for i in range(6)}
    direct = fold_snapshot("n0", 6, nodes)
    a = StatsPartial.of({k: nodes[k] for k in ("n0", "n1")})
    b = StatsPartial.of({k: nodes[k] for k in ("n2", "n3")})
    c = StatsPartial.of({k: nodes[k] for k in ("n4", "n5")})
    groupings = (
        a.merge(b).merge(c),              # left fold
        a.merge(b.merge(c)),              # right fold (associativity)
        c.merge(a).merge(b),              # reordered (commutativity)
        b.merge(c.merge(a)),
    )
    for p in groupings:
        snap = p.finish("n0", 6)
        assert snap.to_dict() == direct.to_dict()
    # a node id reached through two paths is the same answer: merging
    # overlapping partials does not double-count it
    overlap = a.merge(StatsPartial.of({"n1": nodes["n1"],
                                       "n2": nodes["n2"]})).merge(c)
    merged = overlap.merge(b).finish("n0", 6)
    assert merged.to_dict() == direct.to_dict()


def test_membership_digest_is_order_insensitive_and_status_sensitive():
    a = membership_digest([("n1", "ALIVE"), ("n2", "ALIVE")])
    b = membership_digest([("n2", "ALIVE"), ("n1", "ALIVE")])
    c = membership_digest([("n1", "ALIVE"), ("n2", "FAILED")])
    assert a == b != c
    assert len(a) == 12


def test_decode_node_stats_rejects_garbage():
    with pytest.raises(ValueError):
        decode_node_stats(b"\xff\xfenot json")
    with pytest.raises(ValueError):
        decode_node_stats(b'{"v": 99, "id": "x", "health": 1}')
    with pytest.raises(ValueError):
        decode_node_stats(b'{"v": 1, "health": 1}')
    with pytest.raises(ValueError):
        decode_node_stats(b'{"v": 1, "id": "x"}')
    d = decode_node_stats(b'{"v": 1, "id": "x", "health": 88}')
    assert d["health"] == 88 and d["q"] == [0, 0, 0]


def test_render_table_mentions_every_node():
    nodes = {f"node-{i}": {"v": 1, "id": f"node-{i}", "health": 100,
                           "hc": {"probe": 0.0}, "members": 3, "failed": 0,
                           "q": [1, 2, 3], "lag": 0.5, "digest": "abc"}
             for i in range(3)}
    text = render_table(fold_snapshot("node-0", 3, nodes))
    for nid in nodes:
        assert nid in text
    assert "3/3 nodes" in text and "converged" in text


# -- in-proc cluster scenarios ----------------------------------------------


async def _make_cluster(net, n):
    from serf_tpu.host import Serf
    from serf_tpu.options import Options

    nodes = [await Serf.create(net.bind(f"addr-{i}"), Options.local(),
                               f"node-{i}") for i in range(n)]
    for s in nodes[1:]:
        await s.join("addr-0")
    deadline = asyncio.get_running_loop().time() + 10.0
    while asyncio.get_running_loop().time() < deadline:
        if all(len(s.members()) == n for s in nodes):
            return nodes
        await asyncio.sleep(0.02)
    raise AssertionError(
        f"cluster failed to converge: {[len(s.members()) for s in nodes]}")


@pytest.mark.asyncio
async def test_cluster_stats_covers_every_live_node():
    from serf_tpu.host import LoopbackNetwork
    from serf_tpu.host.query import QueryParam

    net = LoopbackNetwork()
    nodes = await _make_cluster(net, 3)
    try:
        snap = await nodes[0].cluster_stats(QueryParam(timeout=3.0))
        assert isinstance(snap, ClusterSnapshot)
        assert set(snap.nodes) == {"node-0", "node-1", "node-2"}
        assert snap.expected == 3 and snap.complete
        for nid, d in snap.nodes.items():
            assert 0 <= d["health"] <= 100, (nid, d)
            assert d["hc"], f"{nid} reported no health components"
            assert d["members"] == 3
        assert set(snap.aggregates) == {"health", "members", "queue", "lag"}
        for agg in snap.aggregates.values():
            assert agg["min"] <= agg["p50"] <= agg["max"]
        # the per-node health gauges landed with node labels
        sink = metrics.global_sink()
        for nid in snap.nodes:
            assert sink.gauge_value("serf.health.score",
                                    {"node": nid}) is not None
        # round-trips through JSON (the obstop --json contract)
        assert json.loads(json.dumps(snap.to_dict()))["responders"] == 3
    finally:
        for s in nodes:
            await s.shutdown()


@pytest.mark.asyncio
async def test_query_trace_id_spans_origin_and_responders():
    from serf_tpu.host import LoopbackNetwork, QueryEvent, EventSubscriber
    from serf_tpu.host.query import QueryParam
    from serf_tpu.host import Serf
    from serf_tpu.options import Options

    net = LoopbackNetwork()
    sub = EventSubscriber()
    a = await Serf.create(net.bind("a"), Options.local(), "node-a")
    b = await Serf.create(net.bind("b"), Options.local(), "node-b",
                          subscriber=sub)
    c = await Serf.create(net.bind("c"), Options.local(), "node-c")
    try:
        await b.join("a")
        await c.join("a")
        deadline = asyncio.get_running_loop().time() + 10.0
        while asyncio.get_running_loop().time() < deadline:
            if all(len(s.members()) == 3 for s in (a, b, c)):
                break
            await asyncio.sleep(0.02)

        async def responder():
            while True:
                ev = await sub.next()
                if isinstance(ev, QueryEvent) and ev.name == "status":
                    await ev.respond(b"pong")
                    return

        task = asyncio.create_task(responder())
        resp = await a.query("status", b"ping", QueryParam(timeout=1.5))
        got = [r async for r in resp.responses()]
        task.cancel()
        assert got and got[0].payload == b"pong"

        # ACCEPTANCE: one trace id on >= 2 nodes' flight entries
        received = obs.flight_dump(kind="query-received")
        ours = [e for e in received if e.get("query") == "status"]
        assert ours, "no query-received flight events recorded"
        trace_ids = {e["trace"] for e in ours}
        assert len(trace_ids) == 1, f"expected one trace id, got {trace_ids}"
        (tid,) = trace_ids
        nodes_seen = {e["node"] for e in ours}
        assert {"node-a", "node-b"} <= nodes_seen, nodes_seen
        # origin-side correlation: the response echoed the same trace id
        responses = obs.flight_dump(kind="query-response", node="node-a")
        assert any(e["trace"] == tid and e["responder"] == "node-b"
                   for e in responses), responses
        # origin is hop 0; a node that got it via rebroadcast records >= 0
        by_node = {e["node"]: e for e in ours}
        assert by_node["node-a"]["hops"] == 0
        assert by_node["node-a"]["origin"] == "node-a"
    finally:
        for s in (a, b, c):
            await s.shutdown()


@pytest.mark.asyncio
async def test_user_event_trace_propagates():
    from serf_tpu.host import LoopbackNetwork

    net = LoopbackNetwork()
    nodes = await _make_cluster(net, 2)
    try:
        await nodes[1].user_event("deploy", b"v2")
        deadline = asyncio.get_running_loop().time() + 5.0
        while asyncio.get_running_loop().time() < deadline:
            evs = [e for e in obs.flight_dump(kind="user-event")
                   if e.get("event") == "deploy"]
            if {e["node"] for e in evs} == {"node-0", "node-1"}:
                break
            await asyncio.sleep(0.02)
        evs = [e for e in obs.flight_dump(kind="user-event")
               if e.get("event") == "deploy"]
        assert {e["node"] for e in evs} == {"node-0", "node-1"}
        assert len({e["trace"] for e in evs}) == 1
        assert all(e["origin"] == "node-1" for e in evs)
    finally:
        for s in nodes:
            await s.shutdown()


# -- satellites --------------------------------------------------------------


@pytest.mark.asyncio
async def test_event_pipeline_is_bounded_and_gauged():
    """The delivery path between protocol and subscriber is the bounded
    MPMC pipeline (host/pipeline.py): its intake bound comes from
    ``event_inbox_max``, fill settles to 0 when idle, and the tee-depth
    gauge is refreshed from the monitor hook."""
    from serf_tpu.host import LoopbackNetwork, Serf, EventSubscriber
    from serf_tpu.options import Options

    net = LoopbackNetwork()
    sub = EventSubscriber()
    s = await Serf.create(net.bind("a"), Options.local(), "node-a",
                          subscriber=sub)
    try:
        assert s._pipeline is not None
        assert s.opts.event_inbox_max > 0     # the intake bound governs
        # own-join events may still be draining; fill settles to 0
        deadline = asyncio.get_running_loop().time() + 5.0
        while s.event_tee_fill() > 0.0 \
                and asyncio.get_running_loop().time() < deadline:
            await asyncio.sleep(0.01)
        assert s.event_tee_fill() == 0.0
        assert s.pipeline_depth() == 0
        # the depth gauge is emitted on the periodic monitor hook
        await s.user_event("ping", b"")
        s._gauge_queue_ages()
        labels = {"node": "node-a"}
        assert metrics.global_sink().gauge_value(
            "serf.events.tee_depth", labels) is not None
        assert metrics.global_sink().gauge_value(
            "serf.pipeline.depth", labels) is not None
    finally:
        await s.shutdown()


def test_lossless_subscriber_drop_is_loud(caplog):
    import logging

    from serf_tpu.host.events import EventSubscriber

    sub = EventSubscriber(maxsize=1, lossless=True)
    sub._push("first")
    with caplog.at_level(logging.WARNING, logger="serf_tpu.events"):
        sub._push("second")  # forces drop-oldest on a lossless subscriber
    assert sub.dropped == 1 and sub.lossless_violations == 1
    assert any("LOSSLESS" in r.message for r in caplog.records)
    (e,) = obs.flight_dump(kind="subscriber-drop")
    assert e["contract"] == "lossless"
    sink = metrics.global_sink()
    assert sink.counter("serf.subscriber.lossless_violation") == 1.0
    # the plain mode stays quiet about contracts
    plain = EventSubscriber(maxsize=1, lossless=False)
    plain.lossless_violations == 0
    plain._push("a")
    plain._push("b")
    assert plain.lossless_violations == 0


def test_dstream_ooo_drop_counter():
    from serf_tpu.host.dstream import K_DATA, MAX_OOO, _Conn

    class _StubTransport:
        def _encode_segment(self, cid, kind, seq, payload):
            return b""

        def _sendto(self, wire, peer):
            pass

    conn = _Conn(_StubTransport(), ("127.0.0.1", 1), b"x" * 8)
    # fill the out-of-order buffer (rcv_next=0 stays the hole)
    for seq in range(1, MAX_OOO + 1):
        conn.on_segment(K_DATA, seq, b"p")
    assert len(conn.ooo) == MAX_OOO
    assert metrics.global_sink().counter("serf.dstream.ooo_dropped") == 0.0
    conn.on_segment(K_DATA, MAX_OOO + 1, b"p")  # overflow -> counted drop
    assert metrics.global_sink().counter("serf.dstream.ooo_dropped") == 1.0
    assert len(conn.ooo) == MAX_OOO


def test_health_in_serf_stats_and_options_serde():
    from serf_tpu.options import Options

    # health_interval round-trips the serde layer as a duration
    opts = Options(health_interval=2.5)
    assert opts.to_dict()["health_interval"] == "2s500ms"
    assert Options.from_json(opts.to_json()).health_interval == 2.5
    try:
        import tomllib  # noqa: F401 - 3.11+ only (test_options_serde skips too)
    except ModuleNotFoundError:
        return
    assert Options.from_toml(opts.to_toml()).health_interval == 2.5


# -- tier-1 contract hooks ---------------------------------------------------


def test_metrics_lint_covers_cluster_plane_gauges():
    """The README table documents the new gauges (and nothing stale)."""
    sys.path.insert(0, str(REPO / "tools"))
    try:
        import metrics_lint
        emitted = metrics_lint.emitted_names(
            [p for entry in metrics_lint.SCAN
             for p in (sorted((REPO / entry).rglob("*.py"))
                       if (REPO / entry).is_dir() else [REPO / entry])])
        documented = metrics_lint.documented_names(metrics_lint.README)
        for name in ("serf.health.score", "serf.health.component.<>",
                     "serf.loop.lag-ms", "serf.events.tee_depth",
                     "serf.dstream.ooo_dropped", "serf.dstream.retransmits",
                     "serf.subscriber.lossless_violation"):
            assert name in emitted, f"{name} not emitted anywhere"
            assert name in documented, f"{name} missing from README"
        assert metrics_lint.run() == 0
    finally:
        sys.path.remove(str(REPO / "tools"))


def test_obstop_json_self_check():
    """tools/obstop.py --json: the cluster-plane contract can't drift —
    a complete snapshot with per-node health, as JSON, exit 0."""
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "obstop.py"), "--json",
         "--nodes", "3"],
        capture_output=True, text=True, timeout=120,
        env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin",
             "PYTHONPATH": str(REPO)},
    )
    assert proc.returncode == 0, proc.stderr
    snap = json.loads(proc.stdout)
    assert snap["responders"] == 3 and snap["complete"]
    assert len(snap["nodes"]) == 3
    for d in snap["nodes"].values():
        assert 0 <= d["health"] <= 100
        assert d["hc"]
