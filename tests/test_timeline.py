"""The unified cross-plane timeline (ISSUE 15 tentpole b,
acceptance-pinned): exporter output validates against the trace-event
schema (sorted ts, matched B/E pairs, stable pid/tid mapping), survives
a JSON round-trip, and a loopback query-storm run's exported bundle
carries every surface — spans, flight, lifecycle, device rounds,
control, SLO, propagation, watchdog — on one correlated timebase."""

import asyncio
import json
import time

import pytest

from serf_tpu.obs.timeline import (
    SURFACES,
    DeviceRunAnchors,
    TimelineBuilder,
    validate_timeline,
)

T0 = 1_700_000_000.0


def _synthetic_builder():
    b = TimelineBuilder(meta={"test": True})
    b.add_spans([
        {"name": "outer", "start": T0, "duration_ms": 5.0, "depth": 0,
         "attrs": {"node": "n1"}, "status": "ok"},
        {"name": "inner", "start": T0 + 0.001, "duration_ms": 1.0,
         "depth": 1, "attrs": {"node": "n1"}},
        # OVERLAPS outer without nesting: must land on its own sub-lane
        {"name": "overlap", "start": T0 + 0.003, "duration_ms": 5.0,
         "attrs": {"node": "n1"}},
        # zero-duration span on the cluster process
        {"name": "blip", "start": T0, "duration_ms": 0.0, "attrs": {}},
    ])
    b.add_flight([
        {"seq": 1, "time": T0 + 0.01, "kind": "probe-failed",
         "node": "n1", "peer": "n2"},
        {"seq": 2, "time": T0 + 0.02, "kind": "slo-breach",
         "slo": "false-dead"},
        {"seq": 3, "time": T0 + 0.03, "kind": "control-decision",
         "knobs": {"fanout": 3}},
        # routes to the dedicated propagation lane (ISSUE 16)
        {"seq": 5, "time": T0 + 0.04, "kind": "propagation-trace",
         "plane": "host", "coverage": 1.0, "time_to_all_ms": 12.5},
        {"seq": 4, "time": T0 + 0.5, "kind": "slow-message",
         "node": "n1", "message": "user-event", "e2e_ms": 300.0,
         "stages_ms": {"transport": 100.0, "apply": 150.0,
                       "tee": 50.0}},
    ])
    b.add_lifecycle(
        {"stages": [{"stage": "apply", "mean_ms": 1.0, "p99_ms": 2.0,
                     "share": 0.5}],
         "e2e": {"p50_ms": 1.0, "p99_ms": 2.0}},
        T0 + 0.6, node="n1")
    anchors = DeviceRunAnchors(wall_start=T0, wall_end=T0 + 1.0, rounds=2)
    b.add_device_telemetry([[1, 2, 3, 4, 5, 6, 7, 8],
                            [2, 3, 4, 5, 6, 7, 8, 9]], anchors)
    b.add_control_decisions(
        [{"round": 1, "knobs": {"fanout": 4}, "shed": 0}], anchors)
    b.add_slo_verdicts([{"slo": "false-dead", "ok": True}], T0 + 0.7)
    # the always-on watchdog lane (ISSUE 17): one ok tick + one breach
    b.add_watchdog(
        {"ticks": 2, "breaches": 1, "bundles": ["bb-0.json"],
         "history": [{"tick": 1, "ok": True, "wall_time": T0 + 0.75,
                      "breaches": []},
                     {"tick": 2, "ok": False, "wall_time": T0 + 0.8,
                      "breaches": ["shed-ratio"]}]},
        T0 + 0.85)
    b.add_device_invariants([[1, 1, 1, 1, 0], [1, 0, 1, 1, 2]], anchors)
    return b


def test_synthetic_bundle_validates_with_all_surfaces():
    doc = _synthetic_builder().build()
    assert validate_timeline(doc) == []
    assert set(doc["otherData"]["surfaces"]) == set(SURFACES)


def test_overlapping_spans_keep_be_pairs_matched():
    """The 'overlap' span partially overlaps 'outer' — naive single-lane
    B/E emission would interleave B-outer B-overlap E-outer and fail the
    stack check; the sub-lane packer must keep every lane nested."""
    doc = _synthetic_builder().build()
    assert validate_timeline(doc) == []
    # the overlapping span really did move to an overflow lane
    span_tids = {e["tid"] for e in doc["traceEvents"]
                 if e.get("cat") == "span"}
    assert len(span_tids) >= 2


def test_json_round_trip_and_stable_pid_tid_mapping():
    d1 = _synthetic_builder().build()
    d2 = json.loads(json.dumps(_synthetic_builder().build()))
    assert validate_timeline(d2) == []
    # deterministic: two independent builds of the same inputs produce
    # the identical bundle — pid/tid assignment cannot depend on dict
    # order or wall clock
    assert d1 == d2
    # every named process appears exactly once in metadata
    names = [e["args"]["name"] for e in d1["traceEvents"]
             if e.get("ph") == "M" and e.get("name") == "process_name"]
    assert sorted(names) == sorted(set(names))
    assert "node:n1" in names and "device-plane" in names


def test_validator_rejects_broken_bundles():
    doc = _synthetic_builder().build()
    events = [e for e in doc["traceEvents"] if e.get("ph") != "M"]
    # unsorted timestamps
    broken = dict(doc, traceEvents=list(reversed(doc["traceEvents"])))
    assert any("not sorted" in p for p in validate_timeline(broken))
    # unmatched B: drop the first E event
    no_e = dict(doc, traceEvents=[e for e in doc["traceEvents"]
                                  if e.get("ph") != "E"])
    assert any("unmatched B" in p for p in validate_timeline(no_e))
    # unnamed pid: strip process metadata
    no_meta = dict(doc, traceEvents=events)
    assert any("process_name" in p for p in validate_timeline(no_meta))


def test_device_anchor_round_mapping_is_clamped_linear():
    a = DeviceRunAnchors(wall_start=100.0, wall_end=200.0, rounds=50,
                         base_round=10)
    assert a.round_wall(10) == 100.0
    assert a.round_wall(60) == 200.0
    assert a.round_wall(35) == 150.0
    assert a.round_wall(9) == 100.0      # clamped below
    assert a.round_wall(1000) == 200.0   # clamped above


def test_query_storm_bundle_has_all_six_surfaces(tmp_path):
    """THE acceptance pin: a loopback query-storm run (host leg with the
    adaptive controller attached + a small device leg with telemetry)
    exports one Perfetto-loadable bundle containing spans, flight,
    lifecycle, device rounds, control, and SLO verdicts on one
    correlated timebase — validated by schema, not by hand."""
    from serf_tpu.faults.device import run_device_plan
    from serf_tpu.faults.host import run_host_plan
    from serf_tpu.faults.plan import named_plan
    from serf_tpu.models.swim import ClusterConfig
    from serf_tpu.models.dissemination import GossipConfig
    from serf_tpu.models.failure import FailureConfig
    from serf_tpu.obs import slo
    from serf_tpu.obs.timeline import export_run_timeline

    plan = named_plan("query-storm")
    host_result = asyncio.run(
        run_host_plan(plan, tmp_dir=str(tmp_path), controller=True))
    host_verdicts = slo.judge_host_run(host_result, plan)

    cfg = ClusterConfig(
        gossip=GossipConfig(n=64, k_facts=32, peer_sampling="rotation"),
        failure=FailureConfig(suspicion_rounds=8, max_new_facts=8,
                              probe_schedule="round_robin"),
        push_pull_every=8, probe_every=2)
    t0 = time.time()
    dev_result = run_device_plan(plan, cfg, collect_telemetry=True)
    anchors = DeviceRunAnchors(wall_start=t0, wall_end=time.time(),
                               rounds=dev_result.rounds_run)
    dev_verdicts = slo.judge_device_run(dev_result, plan)

    out = str(tmp_path / "storm.trace.json")
    export_run_timeline(out, host_result=host_result,
                        host_verdicts=host_verdicts,
                        device_result=dev_result, device_anchors=anchors,
                        device_verdicts=dev_verdicts,
                        meta={"plan": plan.name})
    with open(out) as f:
        doc = json.load(f)
    assert validate_timeline(doc) == []
    surfaces = set(doc["otherData"]["surfaces"])
    assert set(SURFACES) <= surfaces, (
        f"missing surfaces: {set(SURFACES) - surfaces}")
    # one correlated timebase: device counter events interleave with
    # host events inside one sorted stream (not appended at the end)
    events = [e for e in doc["traceEvents"] if e.get("ph") != "M"]
    assert events, "empty bundle"
    cats = {e["cat"] for e in events}
    assert {"span", "flight", "lifecycle", "device", "control",
            "slo"} <= cats
