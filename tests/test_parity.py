"""Host-vs-device state parity: the north-star correctness bar.

The host Serf engine is the oracle (it implements the reference's
serialized, lock-ordered handler semantics); the device plane applies the
same intents as batched gossip facts.  For any intent set with distinct
Lamport times, both must resolve every member to the same status
(SURVEY.md §7 stage 3 and "hard parts": round-batched application must
reach the serialized fixpoint).
"""

import functools
import random

import jax
import jax.numpy as jnp
import pytest

from serf_tpu.host import LoopbackNetwork, Serf
from serf_tpu.host.memberlist import NodeState
from serf_tpu.models.dissemination import (
    GossipConfig,
    K_JOIN,
    K_LEAVE,
    inject_fact,
    make_state,
    run_rounds,
)
from serf_tpu.models.membership import (
    V_ALIVE,
    V_LEAVING,
    converged,
    intent_views,
)
from serf_tpu.options import Options
from serf_tpu.types.member import MemberStatus, Node
from serf_tpu.types.messages import JoinMessage, LeaveMessage

pytestmark = pytest.mark.asyncio


async def host_oracle(intents, subjects):
    """Apply intents through the real host handlers, in the given order."""
    net = LoopbackNetwork()
    serf = Serf(net.bind("oracle"), Options.local(), "oracle-node")
    # make every subject a known member (as if memberlist reported it alive)
    for s in subjects:
        serf._handle_node_join(NodeState(Node(s, s)))
    for kind, subject, lt in intents:
        if kind == "join":
            serf._handle_node_join_intent(JoinMessage(lt, subject))
        else:
            serf._handle_node_leave_intent(LeaveMessage(lt, subject))
    out = {}
    for s in subjects:
        out[s] = serf._members[s].member.status
    await serf.memberlist.transport.shutdown()
    return out


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
async def test_intent_fixpoint_parity(seed):
    rng = random.Random(seed)
    n_subjects = 12
    subjects = [f"m{i}" for i in range(n_subjects)]
    # distinct ltimes (ties are arrival-order dependent in the reference and
    # deliberately excluded from the parity contract)
    ltimes = list(range(1, 1 + n_subjects * 4))
    rng.shuffle(ltimes)
    intents = []
    li = 0
    for i, s in enumerate(subjects):
        for _ in range(rng.randint(1, 4)):
            kind = rng.choice(["join", "leave"])
            intents.append((kind, s, ltimes[li]))
            li += 1

    # ORACLE: serialized application in three different shuffled orders
    # must agree with itself (order independence at distinct ltimes)...
    results = []
    for _ in range(3):
        shuffled = intents[:]
        rng.shuffle(shuffled)
        results.append(await host_oracle(shuffled, subjects))
    assert results[0] == results[1] == results[2]
    oracle = results[0]

    # DEVICE: same intents as facts, gossiped to full dissemination
    cfg = GossipConfig(n=128, k_facts=64)
    st = make_state(cfg)
    order = intents[:]
    rng.shuffle(order)
    for j, (kind, s, lt) in enumerate(order):
        st = inject_fact(
            st, cfg, subject=subjects.index(s),
            kind=K_JOIN if kind == "join" else K_LEAVE,
            incarnation=0, ltime=lt, origin=rng.randrange(cfg.n))
    run = jax.jit(functools.partial(run_rounds, cfg=cfg),
                  static_argnames=("num_rounds",))
    st = run(st, key=jax.random.key(seed), num_rounds=40)

    subj_idx = jnp.arange(n_subjects, dtype=jnp.int32)
    assert bool(converged(st, cfg, subj_idx)), "device views did not converge"
    views = intent_views(st, cfg, subj_idx)
    device = {subjects[i]: int(views[0, i]) for i in range(n_subjects)}

    mapping = {MemberStatus.ALIVE: V_ALIVE, MemberStatus.LEAVING: V_LEAVING}
    for s in subjects:
        assert device[s] == mapping[oracle[s]], (
            f"parity violation for {s}: host={oracle[s].name} "
            f"device={device[s]} (seed {seed})")


@pytest.mark.parametrize("n", [
    # the full baseline-config scale is ~140s of tier-1 wall clock
    # (128 in-process Serfs + their shutdowns) — promoted to @slow
    # (ISSUE 11 budget reclaim); the 32-node variant keeps the
    # host-cluster-vs-device bridge pinned in tier-1 every run
    pytest.param(128, marks=pytest.mark.slow),
    32,
])
async def test_node_convergence_parity_with_host_cluster(n):
    """Baseline config #1 bridged to the device plane: a real n-node host
    cluster converges on membership; the device sim with the same join set
    converges to the same member list."""
    import asyncio
    import time

    net = LoopbackNetwork()
    nodes = []
    for i in range(n):
        s = await Serf.create(net.bind(f"a{i}"), Options.cluster(n), f"n{i}")
        nodes.append(s)
    try:
        t0 = time.monotonic()
        await asyncio.gather(*(s.join("a0") for s in nodes[1:]))
        while not all(len([m for m in s.members()
                           if m.status == MemberStatus.ALIVE]) == n
                      for s in nodes):
            await asyncio.sleep(0.05)
            # the reference's de-facto perf bar is 7 s (base/tests.rs:25-65)
            # on a dedicated runner; scale it so a loaded CI machine (the
            # full suite saturates every core) doesn't flake the bar — the
            # 2x (15 s) bound still flaked ~1-in-2 full-suite runs on a
            # busy box while passing in ~2 s isolated, so it judged the
            # scheduler, not the protocol.  The bound still catches gross
            # pathology (a convergence stall is minutes/never, not 25 s).
            assert time.monotonic() - t0 < 25.0, \
                f"{n}-node convergence blew the (3.5x reference) 25s budget"
        host_members = {m.node.id for m in nodes[0].members()}

        # device: n nodes, join intents for each, full dissemination
        # (fact ring must hold all n join intents at once)
        cfg = GossipConfig(n=n, k_facts=n)
        st = make_state(cfg)
        for i in range(n):
            st = inject_fact(st, cfg, subject=i, kind=K_JOIN,
                             incarnation=0, ltime=i + 1, origin=i)
        st = run_rounds(st, cfg, jax.random.key(0), 30)
        subj = jnp.arange(n, dtype=jnp.int32)
        views = intent_views(st, cfg, subj)
        assert bool(jnp.all(views == V_ALIVE))
        assert host_members == {f"n{i}" for i in range(n)}
        assert all(m.status == MemberStatus.ALIVE
                   for m in nodes[0].members())
    finally:
        for s in nodes:
            await s.shutdown()
