"""Pins the HBM traffic accounting (VERDICT r4 next-1a): the analytic
per-plane model stays inside its byte budget, identifies the true
dominator, and tracks the compiled HLO within a fusion band."""

import functools

import jax
import pytest

from serf_tpu.models.accounting import (
    hlo_bytes_per_round,
    ici_round_traffic,
    kernel_path_summary,
    round_traffic,
)
from serf_tpu.models.swim import (
    flagship_config,
    make_cluster,
    run_cluster_sustained,
)


#: the tracked byte budget for one sustained flagship round @1M (bytes).
#: Computed 352.6 MB mid round 5; 313.6 MB after the sendable-bitset
#: cache landed; 324.6 MB after the tombstone fold; 233.4 MB after the
#: round-6 stamp work (nibble-packed quarter-round stamps halve the
#: merge's learn pass 128→64 MB; the wrap clamp rides the learn pass so
#: the standalone clamp never fires under load; selection ANDs `known`
#: so inject drops its second retirement plane pass; the tombstone fold
#: skip-gates on retiring DEAD facts, which user-event churn never
#: opens).  A kernel change that pushes past the budget must either be
#: paid for deliberately (raise this with a note) or fixed.  Floor
#: guards against the model silently dropping terms.
SUSTAINED_BUDGET_1M = 240e6
SUSTAINED_FLOOR_1M = 190e6
#: the pre-round-6 sustained total the ≥25% reduction is judged against
ROUND5_SUSTAINED_1M = 313.6e6


def test_sustained_budget_at_1m():
    r = round_traffic(flagship_config(1_000_000), regime="sustained")
    assert SUSTAINED_FLOOR_1M < r.total_bytes <= SUSTAINED_BUDGET_1M, (
        f"sustained round moved {r.total_bytes / 1e6:.1f} MB, budget "
        f"{SUSTAINED_BUDGET_1M / 1e6:.0f} MB\n{r.table()}")
    # round-6 acceptance: ≥25% below the round-5 sustained total
    assert r.total_bytes <= 0.75 * ROUND5_SUSTAINED_1M, (
        f"stamp-plane halving regressed: {r.total_bytes / 1e6:.1f} MB "
        f"vs required ≤ {0.75 * ROUND5_SUSTAINED_1M / 1e6:.1f} MB")
    # the (halved) stamp plane is still the dominator, now nearly tied
    # with the packet plane (selection+exchange passes); if the order
    # flips, the optimization target has moved — update STATUS.md
    by_plane = r.by_plane()
    assert r.dominator() == "stamp"
    assert list(by_plane)[1] == "packets"
    assert 0.22 < by_plane["stamp"] / r.total_bytes < 0.36
    assert 0.22 < by_plane["packets"] / r.total_bytes < 0.33


def test_regime_ordering_matches_gate_design():
    """quiescent << active < sustained: the skip-gates must show up in
    the byte model exactly as they do in the measured rps splits."""
    cfg = flagship_config(1_000_000)
    sus = round_traffic(cfg, regime="sustained").total_bytes
    act = round_traffic(cfg, regime="active").total_bytes
    qui = round_traffic(cfg, regime="quiescent").total_bytes
    # the bar tightened from 0.15 when the sustained denominator dropped
    # 28% in round 6 — quiescent itself is unchanged (vivaldi-bound)
    assert qui < 0.2 * sus, "quiescent regime must be >80% cheaper"
    assert act < sus, "no-learn active rounds skip the stamp learn pass"
    det = round_traffic(cfg, regime="detection").total_bytes
    assert det > sus, "detection bursts must cost more than sustained"
    # single-chip ceiling arithmetic (STATUS.md): the 10k target is out
    # of reach for the sustained regime on ONE chip but inside it for
    # the gated regime — the 8-chip shard is where the target lives
    assert round_traffic(cfg, regime="sustained").ceiling_rounds_per_sec() < 10_000
    assert round_traffic(cfg, regime="quiescent").ceiling_rounds_per_sec() > 10_000


def test_ici_per_phase_per_chip_attribution():
    """ISSUE 6 acceptance: ici_round_traffic reports per-phase per-chip
    bytes for BOTH explicit exchange schedules, the per-phase HBM sums
    to the sustained model split D ways, and the α-β schedule decision
    lands where the arithmetic says it must (ring at flagship scale —
    the all-gather's full-plane HBM round-trip dominates; allgather at
    small blocks — launch latency dominates)."""
    cfg = flagship_config(1_000_000)
    d = 8
    m = ici_round_traffic(cfg, d)
    phases = m["per_phase_per_chip"]
    for name in ("selection", "exchange", "merge", "inject", "probe",
                 "push_pull", "vivaldi"):
        assert name in phases, name
        assert phases[name]["hbm_bytes_per_chip"] > 0
    ex = phases["exchange"]
    # both schedules ship the same wire bytes: (D-1) x the local block
    block = cfg.gossip.n * cfg.gossip.words * 4 / d
    assert ex["ici_bytes_per_chip_ring"] == (d - 1) * block
    assert ex["ici_bytes_per_chip_allgather"] == (d - 1) * block
    # ...but peak HBM differs by ~D/2x: that asymmetry IS the decision
    assert ex["peak_hbm_bytes_allgather"] > 4 * ex["peak_hbm_bytes_ring"]
    # per-phase HBM attribution closes against the sustained model
    total = sum(p["hbm_bytes_per_chip"] for p in phases.values())
    model = round_traffic(cfg, regime="sustained").total_bytes / d
    assert abs(total - model) / model < 1e-6
    # the schedule decision: ring at 1M, allgather at small n
    assert m["schedule"]["recommended"] == "ring"
    assert ici_round_traffic(flagship_config(8192), d)[
        "schedule"]["recommended"] == "allgather"
    # the 8-chip implied ceiling clears the 10k target with margin —
    # the whole reason the sharded path is the flagship (ROADMAP 1)
    assert m["implied_sustained_ceiling_rps"] > 2 * 10_000


def test_telemetry_leg_is_o_fields_not_o_n():
    """ISSUE 15 acceptance: the in-collective telemetry legs cost
    O(fields) bytes per chip — INDEPENDENT of node count — and a
    vanishing fraction of both the exchange block and the N-plane
    gather they replace.  Pinned beside the per-phase closure test so
    the ~0-extra-bytes claim lives in the same attribution."""
    from serf_tpu.models.accounting import telemetry_leg_traffic

    d = 8
    small = telemetry_leg_traffic(flagship_config(8192), d)
    big = telemetry_leg_traffic(flagship_config(1_000_000), d)
    # O(fields): the leg bytes do not move when N grows 122x
    assert small["bytes_per_chip_per_round"] == \
        big["bytes_per_chip_per_round"]
    # ...while the gathered alternative grows linearly with N
    assert big["gathered_alternative_bytes_per_chip"] > \
        100 * small["gathered_alternative_bytes_per_chip"]
    # ~0 extra bytes: under 2 KiB/chip/round at the flagship config,
    # < 0.2% of one exchange block, < 1e-4 of the gather it replaces
    assert big["bytes_per_chip_per_round"] < 2048
    block = 1_000_000 * flagship_config(1_000_000).gossip.words * 4 / d
    assert big["bytes_per_chip_per_round"] < 2e-3 * block
    assert big["fraction_of_gather"] < 1e-4
    # payloads are exactly the documented legs (K = 64 at the flagship)
    k = flagship_config(1_000_000).gossip.k_facts
    assert big["payload_bytes"] == {
        "pmax_subject_incarnations": 4 * k,
        "psum_stage1_partials": 4 * (1 + 2 * k),
        "psum_false_dead": 4,
    }
    # and the leg rides ici_round_traffic's attribution
    m = ici_round_traffic(flagship_config(1_000_000), d)
    assert m["telemetry"]["bytes_per_chip_per_round"] == \
        big["bytes_per_chip_per_round"]


def test_kernel_path_model_fused_vs_phased():
    """ISSUE 7 acceptance arithmetic: the fused kernel family removes
    the selection's full stamp-plane pass from the kernel dispatch path
    (>= 1 full-plane pass and >= 15 MB/round @1M vs the standalone
    kernels) and lands at byte PARITY with the XLA model of record —
    the fusion turns the model's XLA-fusion assumptions into authored
    DMA guarantees rather than claiming bytes the phased XLA model
    never paid.  (The ISSUE's aspirational >= 2x vs the 233.4 pin is
    unreachable under the bit-exactness constraint — the floor
    arithmetic is recorded in STATUS.md round 8.)"""
    cfg = flagship_config(1_000_000)
    s = kernel_path_summary(cfg)
    xla = s["paths"]["xla"]
    kern = s["paths"]["kernels"]
    fused = s["paths"]["fused"]
    # strictly fewer full-plane stamp passes than the phased kernels
    assert s["fused_vs_kernels"]["stamp_passes_removed"] >= 1.0
    assert fused["passes_by_plane"]["stamp"] < kern["passes_by_plane"]["stamp"]
    # the removed pass is the 32 MB selection stamp read at 1M, minus
    # the word-plane cache reads the cached selection pays instead
    assert s["fused_vs_kernels"]["bytes_saved"] >= 15e6
    # parity with the XLA model of record (the +-alive-column slack is
    # the kernels' explicit alive read the XLA model folds away)
    assert abs(fused["total_bytes"] - xla["total_bytes"]) <= 2e6
    assert fused["passes_by_plane"]["stamp"] == xla["passes_by_plane"]["stamp"]
    # regime sanity on the kernel paths: the pallas kernels stream the
    # stamp plane whenever the gossip gate is open (no learned_any DMA
    # gate), so their no-learn "active" round costs more than XLA's
    act_x = round_traffic(cfg, regime="active", path="xla").total_bytes
    act_f = round_traffic(cfg, regime="active", path="fused").total_bytes
    assert act_f > act_x
    # quiescent rounds never reach the kernels: identical on every path
    for path in ("kernels", "fused"):
        assert round_traffic(cfg, regime="quiescent",
                             path=path).total_bytes == round_traffic(
            cfg, regime="quiescent").total_bytes


def test_hlo_cross_check_small_n():
    """XLA's compiled bytes-accessed stays within a fusion band of the
    analytic model — keeps the model's fusion assumptions honest."""
    n = 16_384
    cfg = flagship_config(n)
    state = make_cluster(cfg, jax.random.key(0))
    run = jax.jit(functools.partial(run_cluster_sustained, cfg=cfg,
                                    events_per_round=2),
                  static_argnames=("num_rounds",))
    hlo = hlo_bytes_per_round(run, state, key=jax.random.key(1),
                              num_rounds=10)
    if hlo is None:
        pytest.skip("backend exposes no cost analysis")
    model = round_traffic(cfg, regime="sustained").total_bytes
    ratio = hlo / model
    assert 0.3 < ratio < 3.0, (
        f"HLO {hlo / 1e6:.1f} MB/round vs model {model / 1e6:.1f} "
        f"MB/round (ratio {ratio:.2f}) — model assumptions drifted")
