"""Pins the HBM traffic accounting (VERDICT r4 next-1a): the analytic
per-plane model stays inside its byte budget, identifies the true
dominator, and tracks the compiled HLO within a fusion band."""

import functools

import jax
import pytest

from serf_tpu.models.accounting import (
    hlo_bytes_per_round,
    round_traffic,
)
from serf_tpu.models.swim import (
    flagship_config,
    make_cluster,
    run_cluster_sustained,
)


#: the tracked byte budget for one sustained flagship round @1M (bytes).
#: Computed 352.6 MB mid round 5; 313.6 MB after the sendable-bitset
#: cache landed (selection's stamp read → one packed word-plane read);
#: 324.6 MB after the tombstone fold (durable death records cost ~11 MB
#: of retirement-coverage reads — paid deliberately: without them the
#: cluster forgets deaths when the ring recycles AND wastes ring slots
#: re-declaring them forever).  A kernel change that pushes past the
#: budget must either be paid for deliberately (raise this with a note)
#: or fixed.  Floor guards against the model silently dropping terms.
SUSTAINED_BUDGET_1M = 330e6
SUSTAINED_FLOOR_1M = 250e6


def test_sustained_budget_at_1m():
    r = round_traffic(flagship_config(1_000_000), regime="sustained")
    assert SUSTAINED_FLOOR_1M < r.total_bytes <= SUSTAINED_BUDGET_1M, (
        f"sustained round moved {r.total_bytes / 1e6:.1f} MB, budget "
        f"{SUSTAINED_BUDGET_1M / 1e6:.0f} MB\n{r.table()}")
    # the stamp plane is still the dominator, but the sendable cache cut
    # its share from 56% to ~42% (selection no longer reads it); if the
    # dominator flips, the optimization target has moved — update
    # STATUS.md
    assert r.dominator() == "stamp"
    assert 0.35 < r.by_plane()["stamp"] / r.total_bytes < 0.5


def test_regime_ordering_matches_gate_design():
    """quiescent << active < sustained: the skip-gates must show up in
    the byte model exactly as they do in the measured rps splits."""
    cfg = flagship_config(1_000_000)
    sus = round_traffic(cfg, regime="sustained").total_bytes
    act = round_traffic(cfg, regime="active").total_bytes
    qui = round_traffic(cfg, regime="quiescent").total_bytes
    assert qui < 0.15 * sus, "quiescent regime must be >85% cheaper"
    assert act < sus, "no-learn active rounds skip the stamp learn pass"
    det = round_traffic(cfg, regime="detection").total_bytes
    assert det > sus, "detection bursts must cost more than sustained"
    # single-chip ceiling arithmetic (STATUS.md): the 10k target is out
    # of reach for the sustained regime on ONE chip but inside it for
    # the gated regime — the 8-chip shard is where the target lives
    assert round_traffic(cfg, regime="sustained").ceiling_rounds_per_sec() < 10_000
    assert round_traffic(cfg, regime="quiescent").ceiling_rounds_per_sec() > 10_000


def test_hlo_cross_check_small_n():
    """XLA's compiled bytes-accessed stays within a fusion band of the
    analytic model — keeps the model's fusion assumptions honest."""
    n = 16_384
    cfg = flagship_config(n)
    state = make_cluster(cfg, jax.random.key(0))
    run = jax.jit(functools.partial(run_cluster_sustained, cfg=cfg,
                                    events_per_round=2),
                  static_argnames=("num_rounds",))
    hlo = hlo_bytes_per_round(run, state, key=jax.random.key(1),
                              num_rounds=10)
    if hlo is None:
        pytest.skip("backend exposes no cost analysis")
    model = round_traffic(cfg, regime="sustained").total_bytes
    ratio = hlo / model
    assert 0.3 < ratio < 3.0, (
        f"HLO {hlo / 1e6:.1f} MB/round vs model {model / 1e6:.1f} "
        f"MB/round (ratio {ratio:.2f}) — model assumptions drifted")
