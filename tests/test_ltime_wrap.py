"""Lamport u32 wrap story (VERDICT weak-4): FactTable.ltime supersession
is windowed two's-complement — wrap-safe while live ltimes span < 2^31 —
with a fail-loud guard where windowing can't save us.  Pins
dedup/supersession behavior near 2^31 and 2^32.
"""

import jax.numpy as jnp
import pytest

from serf_tpu.models.dissemination import (
    GossipConfig,
    K_JOIN,
    K_LEAVE,
    LTIME_WINDOW,
    inject_fact,
    ltime_newer,
    ltime_window_violation,
    make_state,
)
from serf_tpu.models.membership import (
    V_ALIVE,
    V_LEAVING,
    V_NONE,
    intent_views,
)

U32 = 1 << 32


def _views(join_lt=None, leave_lt=None, subject=3, n=16):
    """State where node ``subject`` knows a join and/or leave intent
    about itself at the given ltimes; returns its own status view."""
    cfg = GossipConfig(n=n, k_facts=32)
    st = make_state(cfg)
    if join_lt is not None:
        st = inject_fact(st, cfg, subject=subject, kind=K_JOIN,
                         incarnation=0, ltime=join_lt, origin=subject)
    if leave_lt is not None:
        st = inject_fact(st, cfg, subject=subject, kind=K_LEAVE,
                         incarnation=0, ltime=leave_lt, origin=subject)
    views = intent_views(st, cfg, jnp.asarray([subject]))
    return int(views[subject, 0]), st, cfg


def test_ltime_newer_wraps():
    assert bool(ltime_newer(5, U32 - 5))          # post-wrap supersedes
    assert not bool(ltime_newer(U32 - 5, 5))
    assert bool(ltime_newer(7, 6))
    assert not bool(ltime_newer(6, 6))
    # near 2^31: strictly inside the window still orders correctly
    assert bool(ltime_newer(10 + LTIME_WINDOW - 1, 10))
    assert not bool(ltime_newer(10, 10 + LTIME_WINDOW - 1))


def test_supersession_across_the_2_32_wrap():
    """A leave whose ltime wrapped past 2^32 supersedes a join sitting
    just below the wrap (the plain-u32 max would invert this forever)."""
    status, _, _ = _views(join_lt=U32 - 3, leave_lt=2)
    assert status == V_LEAVING
    # and symmetrically: a post-wrap join supersedes a pre-wrap leave
    status, _, _ = _views(join_lt=2, leave_lt=U32 - 3)
    assert status == V_ALIVE


def test_supersession_near_2_31_window_edge():
    """Distances up to 2^31 - 1 order correctly; ties prefer LEAVE."""
    status, _, _ = _views(join_lt=10, leave_lt=10 + LTIME_WINDOW - 1)
    assert status == V_LEAVING
    status, _, _ = _views(join_lt=10 + LTIME_WINDOW - 1, leave_lt=10)
    assert status == V_ALIVE
    status, _, _ = _views(join_lt=1000, leave_lt=1000)
    assert status == V_LEAVING                      # tie -> LEAVE
    status, _, _ = _views()
    assert status == V_NONE


def test_window_guard_fails_loud_at_2_31_span():
    """Exactly 2^31 apart is unorderable in two's complement — the
    guard flags it; anything strictly inside the window does not."""
    _, st, _ = _views(join_lt=10, leave_lt=10 + LTIME_WINDOW)
    assert bool(ltime_window_violation(st.facts))
    _, st, _ = _views(join_lt=10, leave_lt=10 + LTIME_WINDOW - 1)
    assert not bool(ltime_window_violation(st.facts))
    # a tight cluster of ltimes STRADDLING the 2^32 wrap is fine: the
    # circular span is small even though plain u32 values are far apart
    _, st, _ = _views(join_lt=U32 - 5, leave_lt=3)
    assert not bool(ltime_window_violation(st.facts))
    # empty / all-equal tables never violate
    cfg = GossipConfig(n=8, k_facts=32)
    assert not bool(ltime_window_violation(make_state(cfg).facts))


def test_dedup_ring_overwrite_near_wrap():
    """Ring-slot supersession (inject over an old slot) is ltime-
    agnostic — the known-bit retirement, not an ltime compare — so a
    wrapped clock cannot resurrect a retired fact."""
    cfg = GossipConfig(n=8, k_facts=32)
    st = make_state(cfg)
    for i in range(4):
        st = inject_fact(st, cfg, subject=i, kind=K_JOIN, incarnation=0,
                         ltime=(U32 - 2 + i) % U32,    # wraps mid-batch
                         origin=i)
    # ring cursor wraps: the next injection recycles slot 0
    st = st._replace(next_slot=jnp.asarray(cfg.k_facts, jnp.int32))
    st = inject_fact(st, cfg, subject=7, kind=K_LEAVE, incarnation=0,
                     ltime=5, origin=7)               # recycles slot 0
    assert int(st.facts.subject[0]) == 7
    assert int(st.facts.ltime[0]) == 5
    # the retired fact's knowledge is gone everywhere (bit cleared)
    views = intent_views(st, cfg, jnp.asarray([0]))
    assert int(views[0, 0]) == V_NONE
    assert not bool(ltime_window_violation(st.facts))


def test_window_violation_detected_under_jit():
    import jax

    _, st, _ = _views(join_lt=0, leave_lt=LTIME_WINDOW)
    violation = jax.jit(ltime_window_violation)(st.facts)
    assert bool(violation)


def test_invariant_checker_surfaces_ltime_violation():
    """The device invariant report goes RED on a blown window."""
    from serf_tpu.faults.invariants import check_device
    from serf_tpu.faults.plan import named_plan
    from serf_tpu.models.failure import FailureConfig
    from serf_tpu.models.swim import ClusterConfig, make_cluster
    import jax

    cfg = ClusterConfig(
        gossip=GossipConfig(n=16, k_facts=32),
        failure=FailureConfig(suspicion_rounds=8))
    state = make_cluster(cfg, jax.random.key(0))
    g = inject_fact(state.gossip, cfg.gossip, subject=1, kind=K_JOIN,
                    incarnation=0, ltime=0, origin=1)
    g = inject_fact(g, cfg.gossip, subject=2, kind=K_JOIN,
                    incarnation=0, ltime=LTIME_WINDOW, origin=2)
    state = state._replace(gossip=g)
    report = check_device(named_plan("self-check"), state, cfg,
                          init_alive=g.alive, rounds_run=int(g.round))
    bad = {r.name: r.ok for r in report.results}
    assert bad["ltime-window"] is False
