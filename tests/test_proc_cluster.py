"""Multi-process real-socket clusters (ISSUE 19).

Acceptance pins:

- control-channel frame + chaos-rule serde round-trips exactly;
- a REAL 3-process cluster on loopback sockets converges, SIGTERM is a
  graceful leave (peers see Left) while SIGKILL is a crash (peers see
  Failed) and a restart from the same snapshot dir rejoins with clocks
  not regressed;
- an abort mid-phase leaks NOTHING: every spawned process is reaped on
  the cancellation path;
- the snapshot-dir flock guard fails a second incarnation closed, and
  atomic config/keyring writes leave the old file intact when killed
  between write and rename;
- ``run_proc_plan`` judges the cross-process invariants green on the
  stock crash-restart and partition-heal-loss plans (@slow: 5+ procs),
  and a rigged red run collects every process's black-box bundle.
"""

import asyncio
import glob
import json
import os
import signal

import pytest

from serf_tpu.faults.plan import FaultPhase, FaultPlan, named_plan
from serf_tpu.faults.proc import ProcCluster, run_proc_plan
from serf_tpu.host import ctl
from serf_tpu.host.transport import ChaosRule, EdgeRates

pytestmark = pytest.mark.asyncio


# ---------------------------------------------------------------------------
# control-channel serde units
# ---------------------------------------------------------------------------


def test_ctl_frame_roundtrip():
    msg = {"op": "stats", "id": 7, "blob_b64": ctl.b64(b"\x00\xff")}
    buf = ctl.encode_frame(msg)
    assert buf[:4] == len(buf[4:]).to_bytes(4, "big")
    assert ctl.decode_frame(buf[4:]) == msg
    assert ctl.unb64(msg["blob_b64"]) == b"\x00\xff"
    assert ctl.unb64(None) == b""


def test_ctl_frame_rejects_oversize_and_non_object():
    with pytest.raises(ValueError):
        ctl.encode_frame({"x": "y" * (ctl.MAX_CTL_FRAME + 1)})
    with pytest.raises(ValueError):
        ctl.decode_frame(b"[1, 2]")


def test_chaos_rule_serde_roundtrip():
    rule = ChaosRule(
        groups=[{"127.0.0.1:1", "127.0.0.1:2"}, {"127.0.0.1:3"}],
        paused=frozenset({"127.0.0.1:2"}),
        drop=0.05, delay=0.01, jitter=0.002, duplicate=0.01,
        reorder=0.02, reorder_window=0.05, corrupt=0.01,
        edges={("127.0.0.1:1", "127.0.0.1:3"):
               EdgeRates(drop=1.0, corrupt=0.5)},
    )
    back = ctl.chaos_rule_from_dict(ctl.chaos_rule_to_dict(rule))
    assert back.groups == rule.groups
    assert back.paused == rule.paused
    assert (back.drop, back.delay, back.jitter) == (0.05, 0.01, 0.002)
    assert (back.duplicate, back.reorder, back.corrupt) == (0.01, 0.02, 0.01)
    assert back.reorder_window == 0.05
    assert back.edges[("127.0.0.1:1", "127.0.0.1:3")].drop == 1.0
    assert back.edges[("127.0.0.1:1", "127.0.0.1:3")].corrupt == 0.5
    # the JSON form survives an actual JSON round-trip (ctl wire format)
    wire = json.loads(json.dumps(ctl.chaos_rule_to_dict(rule)))
    again = ctl.chaos_rule_from_dict(wire)
    assert again.groups == rule.groups
    assert ctl.chaos_rule_to_dict(None) is None
    assert ctl.chaos_rule_from_dict(None) is None


def test_addr_key_normalizes_tuples():
    assert ctl.addr_key(("127.0.0.1", 7946)) == "127.0.0.1:7946"
    assert ctl.addr_key(["10.0.0.1", 1]) == "10.0.0.1:1"
    assert ctl.addr_key("127.0.0.1:7946") == "127.0.0.1:7946"


def test_agent_config_rejects_unknown_keys(tmp_path):
    from serf_tpu.host.agent import AgentConfig

    cfg = AgentConfig.from_dict({"node_id": "x", "profile": "proc"})
    assert cfg.build_options().memberlist.probe_interval == pytest.approx(0.2)
    with pytest.raises(ValueError, match="unknown AgentConfig keys"):
        AgentConfig.from_dict({"node_id": "x", "bind_addr": "oops"})
    with pytest.raises(ValueError, match="unknown profile"):
        AgentConfig.from_dict({"node_id": "x",
                               "profile": "datacenter"}).build_options()


# ---------------------------------------------------------------------------
# exclusivity + atomic publication (satellite 1)
# ---------------------------------------------------------------------------


def test_snapshot_flock_excludes_second_incarnation(tmp_path):
    from serf_tpu.host.snapshot import (
        SnapshotLockError,
        Snapshotter,
        open_and_replay_snapshot,
    )

    path = str(tmp_path / "serf.snap")
    first = Snapshotter(path, open_and_replay_snapshot(path))
    # a second live incarnation on the SAME snapshot dir fails closed,
    # naming the holder
    with pytest.raises(SnapshotLockError, match=str(os.getpid())):
        Snapshotter(path, open_and_replay_snapshot(path))
    asyncio.run(first.shutdown())
    # the lock dies with the holder: a fresh open now succeeds
    second = Snapshotter(path, open_and_replay_snapshot(path))
    asyncio.run(second.shutdown())


def test_atomic_write_kill_between_write_and_rename(tmp_path, monkeypatch):
    from serf_tpu.utils import files

    target = tmp_path / "keyring.json"
    files.atomic_write_text(str(target), "old-keys")

    def killed(src, dst):
        raise KeyboardInterrupt("simulated SIGKILL before rename")

    monkeypatch.setattr(files.os, "replace", killed)
    with pytest.raises(KeyboardInterrupt):
        files.atomic_write_text(str(target), "new-keys")
    monkeypatch.undo()
    # the OLD file is intact and no torn temp survives
    assert target.read_text() == "old-keys"
    assert [p.name for p in tmp_path.iterdir()] == ["keyring.json"]


# ---------------------------------------------------------------------------
# live 3-process cluster: lifecycle semantics (satellite 3)
# ---------------------------------------------------------------------------


def _agent_pids_under(tmp_dir: str):
    """Pids of any live process whose cmdline references ``tmp_dir`` —
    the leak audit that does not trust the harness's own bookkeeping."""
    out = []
    for cmdline in glob.glob("/proc/[0-9]*/cmdline"):
        try:
            with open(cmdline, "rb") as f:
                if tmp_dir.encode() in f.read():
                    out.append(int(cmdline.split("/")[2]))
        except OSError:
            continue
    return out


async def test_sigterm_leaves_sigkill_fails_restart_rejoins(tmp_path):
    cluster = ProcCluster(3, str(tmp_path))
    try:
        await cluster.start()
        assert await cluster.wait_convergence(10.0)

        # SIGTERM -> graceful leave: peers converge on Left, never Failed
        cluster.terminate(2)
        assert await cluster.wait_exit(2, timeout=10.0) == 0
        async def _left_everywhere():
            views = await cluster.views()
            return views and all("p2" in v["left"] and "p2" not in v["failed"]
                                 for v in views.values())
        await _poll(_left_everywhere, 10.0)

        # SIGKILL -> crash: survivors converge on Failed (no leave ran)
        before = await cluster.agents[1].client.call("stats")
        cluster.kill(1)
        async def _failed_somewhere():
            views = await cluster.views()
            return views and all("p1" in v["failed"] for v in views.values())
        await _poll(_failed_somewhere, 10.0)

        # restart from the SAME snapshot dir: rejoin, generation bumped,
        # clocks not regressed (snapshot replay seeds them)
        await cluster.restart(1, seed_addr=cluster.agents[0].addr)
        assert await cluster.wait_convergence(10.0)
        after = await cluster.agents[1].client.call("stats")
        assert after["generation"] == 1
        assert after["member_time"] >= before["member_time"]
        assert after["event_time"] >= before["event_time"]
    finally:
        cluster.teardown()
    assert cluster.leaked_pids() == []
    assert _agent_pids_under(str(tmp_path)) == []


async def _poll(predicate, deadline_s: float, every_s: float = 0.1):
    import time
    end = time.monotonic() + deadline_s
    while True:
        if await predicate():
            return
        if time.monotonic() > end:
            raise AssertionError(f"{predicate.__name__} not true "
                                 f"within {deadline_s}s")
        await asyncio.sleep(every_s)


# ---------------------------------------------------------------------------
# abort mid-phase leaks nothing (satellite 2)
# ---------------------------------------------------------------------------


async def test_abort_mid_phase_reaps_every_process(tmp_path):
    plan = named_plan("crash-restart", n=3)
    task = asyncio.ensure_future(run_proc_plan(plan, str(tmp_path)))
    # let the cluster spawn and enter the plan proper, then abort hard
    # mid-phase — the executor's finally must killpg-reap EVERYTHING
    # synchronously even though the task is being cancelled
    for _ in range(200):
        await asyncio.sleep(0.05)
        if _agent_pids_under(str(tmp_path)):
            break
    assert _agent_pids_under(str(tmp_path)), "cluster never spawned"
    await asyncio.sleep(0.4)
    task.cancel()
    with pytest.raises(asyncio.CancelledError):
        await task
    assert _agent_pids_under(str(tmp_path)) == []


# ---------------------------------------------------------------------------
# run_proc_plan: invariants + forensic artifacts
# ---------------------------------------------------------------------------


async def test_rigged_red_run_collects_every_blackbox(tmp_path, monkeypatch):
    # timing-rigged red runs are flaky by design (Lifeguard refutation
    # re-converges a healed 3-proc cluster in milliseconds), so force
    # the red verdict at the checker seam and prove the FORENSIC path:
    # blackbox_on_fail must collect a bundle from every live process
    from serf_tpu.faults import invariants as inv

    real = inv.check_proc

    def rigged(*args, **kwargs):
        report = real(*args, **kwargs)
        report.add("rigged-red", False, "forced for the forensic-path test")
        return report

    monkeypatch.setattr(inv, "check_proc", rigged)
    plan = FaultPlan(
        name="rigged-red", n=3, seed=3,
        phases=(FaultPhase(name="warm", duration_s=0.3, rounds=4),),
        settle_s=5.0, settle_rounds=2)
    result = await run_proc_plan(plan, str(tmp_path), blackbox_on_fail=True)
    assert not result.report.ok
    assert len(result.blackbox_dirs) == 3
    for node_id, bdir in result.blackbox_dirs.items():
        bundles = os.listdir(bdir)
        assert bundles, f"{node_id} dumped no black-box bundle"
    assert _agent_pids_under(str(tmp_path)) == []


async def test_crash_restart_proc_plan_small(tmp_path):
    # tier-1 keeps the cross-process executor proven end-to-end at the
    # smallest meaningful size; the 5-proc acceptance runs @slow below
    plan = named_plan("crash-restart", n=3)
    result = await run_proc_plan(plan, str(tmp_path))
    assert result.report.ok, result.report.to_dict()
    names = {r.name for r in result.report.results}
    assert {"membership-convergence", "no-false-dead",
            "clock-monotonicity", "crash-restart-rejoin",
            "degradation-fired", "no-task-death"} <= names
    assert result.all_pids and len(result.all_pids) == 4  # 3 + 1 restart
    assert _agent_pids_under(str(tmp_path)) == []


@pytest.mark.slow
async def test_crash_restart_proc_plan_acceptance(tmp_path):
    result = await run_proc_plan(named_plan("crash-restart"), str(tmp_path))
    assert result.report.ok, result.report.to_dict()
    # SIGKILL mid-push-pull left degradation evidence on survivors
    assert any(k.startswith("serf.degraded.")
               or k == "memberlist.probe.failed"
               for k, v in result.survivor_counters.items() if v > 0)


@pytest.mark.slow
async def test_partition_heal_loss_proc_plan_acceptance(tmp_path):
    result = await run_proc_plan(named_plan("partition-heal-loss"),
                                 str(tmp_path))
    assert result.report.ok, result.report.to_dict()
    assert result.settle_converged


@pytest.mark.slow
async def test_flaky_edges_soak_seven_procs(tmp_path):
    # 7 processes under every packet effect at once (delay/duplicate/
    # reorder lower to notes on this plane; drop/corrupt/blocking are
    # enforced at the real sender seam)
    result = await run_proc_plan(named_plan("flaky-edges", n=7),
                                 str(tmp_path))
    assert result.report.ok, result.report.to_dict()
    assert _agent_pids_under(str(tmp_path)) == []
