"""Pallas round kernels: parity with the XLA oracle (interpret mode on CPU,
compiled on TPU), for BOTH stamp-plane flavors (nibble-packed and the
unpacked A/B), plus the pallas_ok flight-recorder breadcrumb."""

import dataclasses
import functools

import jax
import jax.numpy as jnp
import pytest

from serf_tpu.models.dissemination import (
    GossipConfig,
    K_USER_EVENT,
    inject_fact,
    make_state,
    mod_age,
    round_step,
    run_rounds,
    unpack_bits,
    AGE_PIN_Q,
)
from serf_tpu.ops import round_kernels


def _rand_state(cfg, key):
    k2, k3, k4 = jax.random.split(key, 3)
    s = make_state(cfg)
    known = jax.random.bits(k2, (cfg.n, cfg.words), jnp.uint32)
    # random stamp bytes spanning the full range, incl. nibble values
    # "newer" than the round (garbage under cleared known bits is legal)
    stamp = jax.random.randint(k3, (cfg.n, cfg.stamp_cols), 0, 256
                               ).astype(jnp.uint8)
    if not cfg.pack_stamp:
        stamp = stamp & 0xF           # unpacked flavor stores nibbles
    alive = jax.random.bernoulli(k4, 0.9, (cfg.n,))
    return s._replace(known=known, stamp=stamp, alive=alive,
                      round=jnp.asarray(7, jnp.int32))


@pytest.mark.parametrize("packed", [True, False])
def test_select_packets_matches_oracle(packed):
    cfg = GossipConfig(n=512, k_facts=64, use_pallas=True,
                       pack_stamp=packed)
    s = _rand_state(cfg, jax.random.key(0))
    from serf_tpu.models.dissemination import pack_bits, sending_mask
    want_packets = pack_bits(sending_mask(s, cfg))
    packets = round_kernels.select_packets(
        s.stamp, s.known, s.alive[:, None].astype(jnp.uint8),
        cfg.transmit_limit_q, s.round, packed=packed, k_facts=64)
    assert bool(jnp.all(packets == want_packets))


@pytest.mark.parametrize("packed", [True, False])
def test_full_round_parity_pallas_vs_xla(packed):
    """STANDALONE-kernel path (fused_kernels=False — the PR-3 family the
    bench A/Bs against; the default fused family's stronger all-leaf
    bit-exactness contract lives in tests/test_fused_round.py)."""
    base = GossipConfig(n=512, k_facts=64, use_pallas=False,
                        pack_stamp=packed)
    fast = dataclasses.replace(base, use_pallas=True,
                               fused_kernels=False)
    s0 = _rand_state(base, jax.random.key(1))
    key = jax.random.key(2)
    a = jax.jit(functools.partial(round_step, cfg=base))(s0, key=key)
    b = jax.jit(functools.partial(round_step, cfg=fast))(s0, key=key)
    # protocol state must be bit-identical EXCEPT two documented fields:
    # the sendable CACHE legitimately diverges (the XLA path maintains
    # it, the pallas path invalidates — GossipState.sendable_round), and
    # the stamp plane may differ ONLY in clamp timing — the pallas merge
    # clamps while it streams every active round, the XLA path only on
    # learn rounds, so wrap-stale cells can pin at different rounds.
    # Their semantic content is identical: every threshold lives at or
    # below AGE_PIN_Q, so q-ages saturated at the pin must agree wherever
    # a known bit could expose them.
    a_cmp = a._replace(sendable=b.sendable, sendable_round=b.sendable_round,
                       stamp=b.stamp, last_clamp=b.last_clamp)
    for la, lb in zip(jax.tree_util.tree_leaves(a_cmp),
                      jax.tree_util.tree_leaves(b)):
        assert bool(jnp.all(la == lb))
    kb = unpack_bits(a.known, 64)
    qa = jnp.minimum(mod_age(a, base), AGE_PIN_Q)
    qb = jnp.minimum(mod_age(b, base), AGE_PIN_Q)
    assert bool(jnp.all(jnp.where(kb, qa == qb, True))), \
        "pinned q-ages diverged under known bits"
    assert int(b.sendable_round) == -1, \
        "pallas path must invalidate the cache it does not maintain"


def test_multi_round_convergence_with_pallas():
    cfg = GossipConfig(n=512, k_facts=32, use_pallas=True)
    s = inject_fact(make_state(cfg), cfg, 0, K_USER_EVENT, 0, 1, 0)
    run = jax.jit(functools.partial(run_rounds, cfg=cfg),
                  static_argnames=("num_rounds",))
    s = run(s, key=jax.random.key(3), num_rounds=30)
    from serf_tpu.models.dissemination import coverage
    assert float(coverage(s, cfg)[0]) == 1.0


def test_pallas_ok_guard():
    assert round_kernels.pallas_ok(1_000_000, 64)
    assert not round_kernels.pallas_ok(1000, 64)   # no supported block divides 1000
    assert not round_kernels.pallas_ok(512, 48)    # K not a multiple of 32


def test_pallas_fallback_records_flight_event():
    """An unsupported shape with use_pallas=True must leave a flight
    breadcrumb (r5 TPU_PROOF lesson: silent fallbacks made MosaicErrors
    invisible) — and still produce a correct round via the XLA path."""
    from serf_tpu import obs

    rec = obs.FlightRecorder(capacity=64)
    old = obs.global_recorder()
    obs.set_global_recorder(rec)
    try:
        cfg = GossipConfig(n=100, k_facts=32, use_pallas=True)
        s = inject_fact(make_state(cfg), cfg, 0, K_USER_EVENT, 0, 1, 0)
        s = jax.jit(functools.partial(round_step, cfg=cfg))(
            s, key=jax.random.key(0))
        assert int(s.round) == 1
        events = rec.dump(kind="pallas-fallback")
        assert events, "pallas_ok rejection must record a flight event"
        assert events[0]["n"] == 100 and events[0]["op"] == "round_step"
    finally:
        obs.set_global_recorder(old)
