"""Pallas round kernels: parity with the XLA oracle (interpret mode on CPU,
compiled on TPU)."""

import dataclasses
import functools

import jax
import jax.numpy as jnp

from serf_tpu.models.dissemination import (
    GossipConfig,
    K_USER_EVENT,
    inject_fact,
    make_state,
    round_step,
    run_rounds,
)
from serf_tpu.ops import round_kernels


def _rand_state(cfg, key):
    k2, k3, k4 = jax.random.split(key, 3)
    s = make_state(cfg)
    known = jax.random.bits(k2, (cfg.n, cfg.words), jnp.uint32)
    # random stamps spanning the full wrap range, incl. values "newer"
    # than the round (garbage under cleared known bits is legal)
    stamp = jax.random.randint(k3, (cfg.n, cfg.k_facts), 0, 256
                               ).astype(jnp.uint8)
    alive = jax.random.bernoulli(k4, 0.9, (cfg.n,))
    return s._replace(known=known, stamp=stamp, alive=alive,
                      round=jnp.asarray(7, jnp.int32))


def test_select_packets_matches_oracle():
    cfg = GossipConfig(n=512, k_facts=64, use_pallas=True)
    s = _rand_state(cfg, jax.random.key(0))
    from serf_tpu.models.dissemination import pack_bits, sending_mask
    want_packets = pack_bits(sending_mask(s, cfg))
    packets = round_kernels.select_packets(
        s.stamp, s.known, s.alive[:, None].astype(jnp.uint8),
        cfg.transmit_limit, s.round)
    assert bool(jnp.all(packets == want_packets))


def test_full_round_parity_pallas_vs_xla():
    base = GossipConfig(n=512, k_facts=64, use_pallas=False)
    fast = dataclasses.replace(base, use_pallas=True)
    s0 = _rand_state(base, jax.random.key(1))
    key = jax.random.key(2)
    a = jax.jit(functools.partial(round_step, cfg=base))(s0, key=key)
    b = jax.jit(functools.partial(round_step, cfg=fast))(s0, key=key)
    # protocol state must be bit-identical; the sendable CACHE fields
    # legitimately diverge (the XLA path maintains the cache, the pallas
    # path invalidates it — dissemination.GossipState.sendable_round)
    a_cmp = a._replace(sendable=b.sendable, sendable_round=b.sendable_round)
    for la, lb in zip(jax.tree_util.tree_leaves(a_cmp),
                      jax.tree_util.tree_leaves(b)):
        assert bool(jnp.all(la == lb))
    assert int(b.sendable_round) == -1, \
        "pallas path must invalidate the cache it does not maintain"


def test_multi_round_convergence_with_pallas():
    cfg = GossipConfig(n=512, k_facts=32, use_pallas=True)
    s = inject_fact(make_state(cfg), cfg, 0, K_USER_EVENT, 0, 1, 0)
    run = jax.jit(functools.partial(run_rounds, cfg=cfg),
                  static_argnames=("num_rounds",))
    s = run(s, key=jax.random.key(3), num_rounds=30)
    from serf_tpu.models.dissemination import coverage
    assert float(coverage(s, cfg)[0]) == 1.0


def test_pallas_ok_guard():
    assert round_kernels.pallas_ok(1_000_000, 64)
    assert not round_kernels.pallas_ok(1000, 64)   # no supported block divides 1000
    assert not round_kernels.pallas_ok(512, 48)    # K not a multiple of 32
