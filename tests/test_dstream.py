"""Datagram-stream transport (the QUIC slot): ARQ correctness under loss,
encryption, and lifecycle semantics.

The cluster-level conformance run (2-node serf over udpstream, v4+v6)
lives in test_serf.py's stream-variant matrix; these tests drive the
transport directly.
"""

import asyncio
import os
import random

import pytest

from serf_tpu.host.dstream import (
    MSS,
    DatagramStreamTransport,
    T_SEGMENT,
)
from serf_tpu.host.keyring import SecretKeyring

pytestmark = pytest.mark.asyncio


async def _pair(**kw):
    a = await DatagramStreamTransport.bind(("127.0.0.1", 0), **kw)
    b = await DatagramStreamTransport.bind(("127.0.0.1", 0), **kw)
    return a, b


async def test_frame_round_trip_small_and_large():
    a, b = await _pair()
    try:
        dial_task = asyncio.ensure_future(a.dial(b.local_addr))
        peer, srv = await asyncio.wait_for(b.accept(), 5)
        cli = await dial_task

        await cli.send_frame(b"hello")
        assert await srv.recv_frame(timeout=5) == b"hello"

        # multi-segment frame (spans many MSS chunks) + empty frame
        big = os.urandom(37 * MSS + 123)
        await srv.send_frame(big)
        await srv.send_frame(b"")
        assert await cli.recv_frame(timeout=10) == big
        assert await cli.recv_frame(timeout=5) == b""
    finally:
        await a.shutdown()
        await b.shutdown()


async def test_arq_recovers_from_heavy_loss():
    """20% segment loss in both directions: the retransmit machinery must
    still deliver every frame intact and in order."""
    a, b = await _pair()
    rng = random.Random(7)

    def lossy(t):
        orig = t._sendto

        def send(wire, addr):
            # drop only stream segments (never the bind machinery)
            if wire and wire[0] == T_SEGMENT and rng.random() < 0.20:
                return
            orig(wire, addr)
        t._sendto = send

    lossy(a)
    lossy(b)
    try:
        dial_task = asyncio.ensure_future(a.dial(b.local_addr))
        peer, srv = await asyncio.wait_for(b.accept(), 10)
        cli = await dial_task

        frames = [os.urandom(rng.randrange(1, 4 * MSS)) for _ in range(12)]
        for f in frames:
            await cli.send_frame(f)
        got = [await srv.recv_frame(timeout=30) for _ in frames]
        assert got == frames
    finally:
        await a.shutdown()
        await b.shutdown()


async def test_encrypted_segments_and_foreign_injection_dropped():
    key = os.urandom(32)
    a, b = await _pair(keyring=SecretKeyring(key))
    # an attacker (or misconfigured node) without the cluster key
    intruder = await DatagramStreamTransport.bind(("127.0.0.1", 0),
                                                  keyring=SecretKeyring(os.urandom(32)))
    try:
        dial_task = asyncio.ensure_future(a.dial(b.local_addr))
        peer, srv = await asyncio.wait_for(b.accept(), 5)
        cli = await dial_task
        await cli.send_frame(b"secret payload")
        assert await srv.recv_frame(timeout=5) == b"secret payload"

        # wrong-key dial never completes a handshake (segments dropped)
        with pytest.raises((TimeoutError, ConnectionError)):
            await intruder.dial(b.local_addr, timeout=1.0)

        # the established stream is unaffected by the garbage
        await srv.send_frame(b"still fine")
        assert await cli.recv_frame(timeout=5) == b"still fine"
    finally:
        await a.shutdown()
        await b.shutdown()
        await intruder.shutdown()


async def test_close_signals_peer_eof():
    a, b = await _pair()
    try:
        dial_task = asyncio.ensure_future(a.dial(b.local_addr))
        peer, srv = await asyncio.wait_for(b.accept(), 5)
        cli = await dial_task
        await cli.send_frame(b"last words")
        await cli.close()
        assert await srv.recv_frame(timeout=5) == b"last words"
        with pytest.raises(ConnectionError):
            await srv.recv_frame(timeout=5)
    finally:
        await a.shutdown()
        await b.shutdown()


async def test_dial_unreachable_times_out():
    a = await DatagramStreamTransport.bind(("127.0.0.1", 0))
    # an address with nothing listening: SYN retransmits, then times out
    try:
        with pytest.raises((TimeoutError, ConnectionError)):
            await a.dial(("127.0.0.1", 1), timeout=1.0)
    finally:
        await a.shutdown()


async def test_packet_plane_coexists_with_streams():
    a, b = await _pair()
    try:
        await a.send_packet(b.local_addr, b"gossip!")
        src, payload = await asyncio.wait_for(b.recv_packet(), 5)
        assert payload == b"gossip!"
        assert src[1] == a.local_addr[1]
    finally:
        await a.shutdown()
        await b.shutdown()


async def test_close_flushes_inflight_under_heavy_loss():
    """ADVICE r2 (medium): close() must not tear down while DATA segments
    are unacked — the final frames of a stream survive sustained loss
    because retransmission keeps running until everything (incl. the FIN)
    is acked."""
    a, b = await _pair()
    rng = random.Random(31)

    def lossy(t):
        orig = t._sendto

        def send(wire, addr):
            if wire and wire[0] == T_SEGMENT and rng.random() < 0.4:
                return
            orig(wire, addr)
        t._sendto = send

    lossy(a)
    try:
        dial_task = asyncio.ensure_future(a.dial(b.local_addr))
        peer, srv = await asyncio.wait_for(b.accept(), 10)
        cli = await dial_task
        last = os.urandom(6 * MSS + 17)
        await cli.send_frame(last)
        await cli.close()          # returns only after all inflight acked
        assert await srv.recv_frame(timeout=30) == last
    finally:
        await a.shutdown()
        await b.shutdown()


async def test_recv_after_eof_always_raises():
    """ADVICE r2 (low): every recv_frame after EOF must raise (TcpStream
    contract), not consume the sentinel once and hang forever."""
    a, b = await _pair()
    try:
        dial_task = asyncio.ensure_future(a.dial(b.local_addr))
        peer, srv = await asyncio.wait_for(b.accept(), 5)
        cli = await dial_task
        await cli.close()
        for _ in range(3):
            with pytest.raises(ConnectionError):
                # timeout=None is the hang-prone path; bound it externally
                await asyncio.wait_for(srv.recv_frame(), 5)
    finally:
        await a.shutdown()
        await b.shutdown()


async def test_fin_receiver_frees_conn_without_app_close(monkeypatch):
    """ADVICE r2 (low): a stream abandoned by the application after EOF
    must not leak its _Conn in transport._conns forever."""
    from serf_tpu.host import dstream as ds
    monkeypatch.setattr(ds, "FIN_LINGER", 0.3)
    a, b = await _pair()
    try:
        dial_task = asyncio.ensure_future(a.dial(b.local_addr))
        peer, srv = await asyncio.wait_for(b.accept(), 5)
        cli = await dial_task
        await cli.send_frame(b"bye")
        await cli.close()
        assert await srv.recv_frame(timeout=5) == b"bye"
        # srv never calls close(); the FIN linger must still free the conn
        deadline = asyncio.get_running_loop().time() + 5
        while b._conns and asyncio.get_running_loop().time() < deadline:
            await asyncio.sleep(0.05)
        assert not b._conns
    finally:
        await a.shutdown()
        await b.shutdown()


async def test_syn_flood_is_bounded():
    """ADVICE r2 (low): unsolicited SYNs must not grow _conns / the accept
    queue without bound."""
    from serf_tpu.host.dstream import MAX_PEER_CONNS, K_SYN
    a, b = await _pair()
    try:
        for i in range(4 * MAX_PEER_CONNS):
            cid = i.to_bytes(8, "big")
            a._sendto(a._encode_segment(cid, K_SYN, 0), b.local_addr)
        await asyncio.sleep(0.2)
        assert len(b._conns) <= MAX_PEER_CONNS
        assert b._accepts.qsize() <= MAX_PEER_CONNS
    finally:
        await a.shutdown()
        await b.shutdown()


async def test_fast_retransmit_recovers_single_loss_below_rto():
    """SACK + dup-ack fast retransmit (VERDICT r4 next-7): one lost DATA
    segment must be recovered in ~1 RTT via the duplicate-ACK path —
    latency well under the 150 ms RTO floor — and the SACKed later
    segments must never be retransmitted."""
    import time as _time

    from serf_tpu.host.dstream import _HDR, K_DATA, RTO_MIN

    a, b = await _pair()
    sent_counts: dict = {}
    dropped = []
    orig = a._sendto

    def send(wire, addr):
        if wire and wire[0] == T_SEGMENT:
            _cid, kind, seq = _HDR.unpack_from(wire, 1)
            if kind == K_DATA:
                sent_counts[seq] = sent_counts.get(seq, 0) + 1
                if seq == 1 and not dropped:
                    dropped.append(seq)
                    return          # the single injected loss
        orig(wire, addr)

    a._sendto = send
    try:
        dial_task = asyncio.ensure_future(a.dial(b.local_addr))
        peer, srv = await asyncio.wait_for(b.accept(), 5)
        cli = await dial_task
        conn = cli._c

        frame = os.urandom(8 * MSS)     # 9 segments: plenty of dup-acks
        t0 = _time.monotonic()
        await cli.send_frame(frame)
        got = await srv.recv_frame(timeout=5)
        dt = _time.monotonic() - t0

        assert got == frame
        assert dropped, "loss never injected — test is vacuous"
        assert conn.fast_retx_count >= 1, \
            "recovery did not go through fast retransmit"
        assert dt < RTO_MIN, \
            f"recovery took {dt * 1000:.0f} ms — waited out the RTO"
        # the hole was resent exactly once; every SACKed segment exactly
        # never (no spurious retransmission of delivered data)
        assert sent_counts[1] == 2, sent_counts
        spurious = {s: c for s, c in sent_counts.items()
                    if s != 1 and c != 1}
        assert not spurious, f"SACKed segments retransmitted: {spurious}"
    finally:
        await a.shutdown()
        await b.shutdown()


async def test_aimd_backs_off_through_bottleneck():
    """AIMD congestion response (the QUIC-slot WAN story): a token-bucket
    bottleneck between the endpoints drops whatever exceeds its rate.  The
    sender must (a) halve its window on loss — observed cwnd dips below
    the initial window — (b) still deliver the whole transfer intact, and
    (c) grow the window back through clean ACK rounds afterwards."""
    import time as _time

    from serf_tpu.host.dstream import CWND_INIT, CWND_MIN

    a, b = await _pair()

    class Bucket:
        """~40 segments/s sustained, burst of 24 — far below what a fixed
        64-segment blast would need."""
        def __init__(self):
            self.level = 24.0
            self.rate = 40.0
            self.last = _time.monotonic()
            self.dropped = 0

        def admit(self) -> bool:
            now = _time.monotonic()
            self.level = min(24.0, self.level + (now - self.last) * self.rate)
            self.last = now
            if self.level >= 1.0:
                self.level -= 1.0
                return True
            self.dropped += 1
            return False

    bucket = Bucket()
    orig = a._sendto

    def throttled(wire, addr):
        if wire and wire[0] == T_SEGMENT and not bucket.admit():
            return
        orig(wire, addr)

    a._sendto = throttled
    try:
        dial_task = asyncio.ensure_future(a.dial(b.local_addr))
        peer, srv = await asyncio.wait_for(b.accept(), 10)
        cli = await dial_task
        conn = cli._c

        payload = os.urandom(120 * MSS)   # 120 segments >> burst capacity
        send = asyncio.ensure_future(cli.send_frame(payload))
        got = await srv.recv_frame(timeout=60)
        await send
        assert got == payload, "bottlenecked transfer corrupted"
        assert bucket.dropped > 0, "bottleneck never engaged — test is vacuous"
        assert conn.cwnd_min_seen < CWND_INIT, \
            f"no multiplicative decrease observed (min {conn.cwnd_min_seen})"
        assert conn.cwnd >= CWND_MIN

        # recovery: clean ACK rounds grow the window back additively
        a._sendto = orig
        low = conn.cwnd
        for _ in range(6):
            f2 = os.urandom(8 * MSS)
            await cli.send_frame(f2)
            assert await srv.recv_frame(timeout=10) == f2
        assert conn.cwnd > low, \
            f"window never re-grew after the bottleneck ({conn.cwnd} <= {low})"
    finally:
        await a.shutdown()
        await b.shutdown()
