"""Datagram-stream transport (the QUIC slot): ARQ correctness under loss,
encryption, and lifecycle semantics.

The cluster-level conformance run (2-node serf over udpstream, v4+v6)
lives in test_serf.py's stream-variant matrix; these tests drive the
transport directly.
"""

import asyncio
import os
import random

import pytest

from serf_tpu.host.dstream import (
    MSS,
    DatagramStreamTransport,
    T_SEGMENT,
)
from serf_tpu.host.keyring import SecretKeyring

pytestmark = pytest.mark.asyncio


async def _pair(**kw):
    a = await DatagramStreamTransport.bind(("127.0.0.1", 0), **kw)
    b = await DatagramStreamTransport.bind(("127.0.0.1", 0), **kw)
    return a, b


async def test_frame_round_trip_small_and_large():
    a, b = await _pair()
    try:
        dial_task = asyncio.ensure_future(a.dial(b.local_addr))
        peer, srv = await asyncio.wait_for(b.accept(), 5)
        cli = await dial_task

        await cli.send_frame(b"hello")
        assert await srv.recv_frame(timeout=5) == b"hello"

        # multi-segment frame (spans many MSS chunks) + empty frame
        big = os.urandom(37 * MSS + 123)
        await srv.send_frame(big)
        await srv.send_frame(b"")
        assert await cli.recv_frame(timeout=10) == big
        assert await cli.recv_frame(timeout=5) == b""
    finally:
        await a.shutdown()
        await b.shutdown()


async def test_arq_recovers_from_heavy_loss():
    """20% segment loss in both directions: the retransmit machinery must
    still deliver every frame intact and in order."""
    a, b = await _pair()
    rng = random.Random(7)

    def lossy(t):
        orig = t._sendto

        def send(wire, addr):
            # drop only stream segments (never the bind machinery)
            if wire and wire[0] == T_SEGMENT and rng.random() < 0.20:
                return
            orig(wire, addr)
        t._sendto = send

    lossy(a)
    lossy(b)
    try:
        dial_task = asyncio.ensure_future(a.dial(b.local_addr))
        peer, srv = await asyncio.wait_for(b.accept(), 10)
        cli = await dial_task

        frames = [os.urandom(rng.randrange(1, 4 * MSS)) for _ in range(12)]
        for f in frames:
            await cli.send_frame(f)
        got = [await srv.recv_frame(timeout=30) for _ in frames]
        assert got == frames
    finally:
        await a.shutdown()
        await b.shutdown()


async def test_encrypted_segments_and_foreign_injection_dropped():
    key = os.urandom(32)
    a, b = await _pair(keyring=SecretKeyring(key))
    # an attacker (or misconfigured node) without the cluster key
    intruder = await DatagramStreamTransport.bind(("127.0.0.1", 0),
                                                  keyring=SecretKeyring(os.urandom(32)))
    try:
        dial_task = asyncio.ensure_future(a.dial(b.local_addr))
        peer, srv = await asyncio.wait_for(b.accept(), 5)
        cli = await dial_task
        await cli.send_frame(b"secret payload")
        assert await srv.recv_frame(timeout=5) == b"secret payload"

        # wrong-key dial never completes a handshake (segments dropped)
        with pytest.raises((TimeoutError, ConnectionError)):
            await intruder.dial(b.local_addr, timeout=1.0)

        # the established stream is unaffected by the garbage
        await srv.send_frame(b"still fine")
        assert await cli.recv_frame(timeout=5) == b"still fine"
    finally:
        await a.shutdown()
        await b.shutdown()
        await intruder.shutdown()


async def test_close_signals_peer_eof():
    a, b = await _pair()
    try:
        dial_task = asyncio.ensure_future(a.dial(b.local_addr))
        peer, srv = await asyncio.wait_for(b.accept(), 5)
        cli = await dial_task
        await cli.send_frame(b"last words")
        await cli.close()
        assert await srv.recv_frame(timeout=5) == b"last words"
        with pytest.raises(ConnectionError):
            await srv.recv_frame(timeout=5)
    finally:
        await a.shutdown()
        await b.shutdown()


async def test_dial_unreachable_times_out():
    a = await DatagramStreamTransport.bind(("127.0.0.1", 0))
    # an address with nothing listening: SYN retransmits, then times out
    try:
        with pytest.raises((TimeoutError, ConnectionError)):
            await a.dial(("127.0.0.1", 1), timeout=1.0)
    finally:
        await a.shutdown()


async def test_packet_plane_coexists_with_streams():
    a, b = await _pair()
    try:
        await a.send_packet(b.local_addr, b"gossip!")
        src, payload = await asyncio.wait_for(b.recv_packet(), 5)
        assert payload == b"gossip!"
        assert src[1] == a.local_addr[1]
    finally:
        await a.shutdown()
        await b.shutdown()
