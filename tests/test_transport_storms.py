"""Cluster storms over REAL transports: {tcp, tls, udpstream} × {drop
storm, partition bisection} plus a mid-run key rotation over the
datagram-stream transport.

The loopback storm suite (tests/test_soak.py) pins the protocol under
churn; these runs pin the TRANSPORTS — every stream plane the framework
ships (the reference's NetTransport / TLS / QUIC feature split,
serf/Cargo.toml:24-56) must carry the same cluster through loss,
partition, and key rotation.  Loss/partition are injected at the sender
seam through the unified chaos surface
(``serf_tpu.faults.host.attach_transport_chaos`` + ``ChaosRule`` — the
same rules a ``FaultPlan`` phase compiles to): ``send_packet`` for the
UDP gossip plane of every transport; ``_sendto`` additionally for
dstream so stream SEGMENTS drop too — exercising the ARQ under cluster
load, not just unit frames.
"""

import asyncio
import dataclasses
import random

import pytest

from serf_tpu.faults.host import attach_transport_chaos
from serf_tpu.host import Serf, SerfState
from serf_tpu.host.dstream import DatagramStreamTransport
from serf_tpu.host.net import NetTransport, TlsNetTransport, make_tls_contexts
from serf_tpu.host.transport import ChaosRule, EdgeRates
from serf_tpu.options import Options
from serf_tpu.types.member import MemberStatus

from tests.test_serf import _self_signed_cert

pytestmark = pytest.mark.asyncio

STREAMS = ("tcp", "tls", "udpstream")


async def _bind(stream, tmp_path, keyring=None, addr=("127.0.0.1", 0),
                _cache={}):
    # rejoiners rebind their OLD address: a same-id node on a new address
    # is the name-conflict scenario (arbitrated away by majority vote),
    # not the restart scenario the reference pins (base/tests/serf.rs:163)
    if stream == "tcp":
        return await NetTransport.bind(addr)
    if stream == "udpstream":
        return await DatagramStreamTransport.bind(addr, keyring=keyring)
    if "tls" not in _cache:
        _cache["tls"] = _self_signed_cert(tmp_path)
    cert, key = _cache["tls"]
    server_ctx, client_ctx = make_tls_contexts(cert, key)
    return await TlsNetTransport.bind(addr, server_ctx=server_ctx,
                                      client_ctx=client_ctx)


def _inject_loss(t, rng, rate, blocked_ports=None):
    """Sender-side fault injection, now delegating to the unified chaos
    surface (old knob kept so the storm mix reads unchanged): drop UDP
    packets (every transport) and dstream segments; optionally blackhole
    a set of destination ports (the partition — blocks packets AND
    dials).  Idempotent per transport (wraps once; later calls swap the
    installed ``ChaosRule``)."""
    attach_transport_chaos(t, src="self", addr_key=lambda a: a[1], rng=rng)
    blocked = blocked_ports or set()
    edges = {("self", port): EdgeRates(drop=1.0) for port in blocked}
    if rate or edges:
        t._chaos_rule = ChaosRule(drop=rate, edges=edges)
    else:
        t._chaos_rule = None


async def _converged(nodes, live, deadline_s, label):
    want = {nodes[i].local_id for i in live}
    loop = asyncio.get_running_loop()
    end = loop.time() + deadline_s
    while loop.time() < end:
        views = [{m.node.id for m in nodes[i].members()
                  if m.status == MemberStatus.ALIVE} for i in live]
        if all(v >= want for v in views):
            return
        await asyncio.sleep(0.05)
    views = [{m.node.id for m in nodes[i].members()
              if m.status == MemberStatus.ALIVE} for i in live]
    for v in views:
        assert v >= want, f"{label}: survivor view {v} missing {want - v}"


@pytest.mark.parametrize("stream", STREAMS)
async def test_drop_storm_converges(stream, tmp_path):
    """10% sender-side loss on the gossip plane (and dstream segments)
    through a kill/rejoin/user-event churn: survivors still converge."""
    rng = random.Random(11)
    n = 5
    transports = [await _bind(stream, tmp_path) for _ in range(n)]
    for t in transports:
        _inject_loss(t, rng, 0.10)
    nodes = {i: await Serf.create(transports[i], Options.local(),
                                  f"{stream}-drop-{i}") for i in range(n)}
    killed = set()
    try:
        for i in range(1, n):
            await nodes[i].join(transports[0].local_addr)
        for op in range(20):
            live = [i for i in nodes if i not in killed]
            r = rng.random()
            if r < 0.2 and len(live) > 3:
                v = rng.choice([i for i in live if i != 0])
                if rng.random() < 0.5:
                    await nodes[v].leave()
                await nodes[v].shutdown()
                killed.add(v)
            elif r < 0.4 and killed:
                b = rng.choice(sorted(killed))
                killed.discard(b)
                t = await _bind(stream, tmp_path,
                                addr=transports[b].local_addr)
                _inject_loss(t, rng, 0.10)
                transports[b] = t
                nodes[b] = await Serf.create(t, Options.local(),
                                             f"{stream}-drop-{b}")
                tgt = rng.choice([i for i in nodes
                                  if i not in killed and i != b])
                await nodes[b].join(transports[tgt].local_addr)
            else:
                await nodes[rng.choice(live)].user_event(
                    f"ev-{op}", b"x" * rng.randint(0, 40), coalesce=False)
            if rng.random() < 0.4:
                await asyncio.sleep(0.02)
        live = [i for i in nodes if i not in killed
                and nodes[i].state == SerfState.ALIVE]
        # 40 s liveness deadline: 10% loss stretches RTO/backoff badly on
        # a CI box that is mid-suite; this pins convergence, not latency
        await _converged(nodes, live, 40.0, f"{stream} drop storm")
    finally:
        for s in nodes.values():
            if s.state != SerfState.SHUTDOWN:
                await s.shutdown()


@pytest.mark.parametrize("stream", STREAMS)
async def test_partition_bisection_heals(stream, tmp_path):
    """Blackhole a 3/3 bisection mid-run (both packet and stream planes),
    keep each side gossiping, heal, and require full re-convergence —
    push/pull anti-entropy over the stream plane must carry the merge."""
    rng = random.Random(12)
    n = 6
    transports = [await _bind(stream, tmp_path) for _ in range(n)]
    for t in transports:
        _inject_loss(t, rng, 0.0)
    nodes = {i: await Serf.create(transports[i], Options.local(),
                                  f"{stream}-part-{i}") for i in range(n)}
    ports = [t.local_addr[1] for t in transports]
    try:
        for i in range(1, n):
            await nodes[i].join(transports[0].local_addr)
        await _converged(nodes, list(range(n)), 10.0,
                         f"{stream} pre-partition")
        # bisect: 0-2 | 3-5
        for i in range(n):
            other = set(ports[3:]) if i < 3 else set(ports[:3])
            _inject_loss(transports[i], rng, 0.0, blocked_ports=other)
        for op in range(8):
            side = nodes[rng.choice(range(3))] if op % 2 else \
                nodes[rng.choice(range(3, n))]
            await side.user_event(f"part-{op}", b"y", coalesce=False)
            await asyncio.sleep(0.05)
        # heal
        for i in range(n):
            _inject_loss(transports[i], rng, 0.0, blocked_ports=set())
        live = [i for i in nodes if nodes[i].state == SerfState.ALIVE]
        await _converged(nodes, live, 30.0, f"{stream} post-heal")
        # both sides' partition-era events eventually reached everyone:
        # event clocks witnessed on both sides converge upward
        assert all(nodes[i].event_clock.time() >= 8 for i in live)
    finally:
        for s in nodes.values():
            if s.state != SerfState.SHUTDOWN:
                await s.shutdown()


@pytest.mark.parametrize("stream", ("tcp", "udpstream"))
@pytest.mark.parametrize(
    "seed", (71, pytest.param(72, marks=pytest.mark.slow)))
async def test_api_storm_over_real_sockets(stream, seed, tmp_path):
    """The loopback randomized API storm (test_soak.py) ported to real
    stream transports (VERDICT r4 next-6): leave/shutdown churn, rejoins
    on the old address, user events, scatter-gather queries, and tag
    flaps interleave over live sockets.  The udpstream variant runs
    FULLY ENCRYPTED (cluster keyring on both the gossip wire and the
    stream segments) with 5% segment loss, so AIMD + SACK recovery +
    keyring decrypt + churn all interleave — the combination round 4
    shipped untested."""
    from serf_tpu.host.keyring import SecretKeyring

    from tests.storm_ops import run_api_storm

    rng = random.Random(seed)
    n = 8
    keyring = SecretKeyring(bytes(range(16))) if stream == "udpstream" \
        else None
    loss = 0.05 if stream == "udpstream" else 0.0
    addrs = {}
    nodes = {}

    async def spawn(i):
        t = await _bind(stream, tmp_path, keyring=keyring,
                        addr=addrs.get(i, ("127.0.0.1", 0)))
        _inject_loss(t, rng, loss)
        addrs[i] = t.local_addr
        return await Serf.create(t, Options.local(), f"st-{i}",
                                 keyring=keyring)

    for i in range(n):
        nodes[i] = await spawn(i)
    killed = set()
    try:
        for i in range(1, n):
            await nodes[i].join(addrs[0])
        await run_api_storm(rng, nodes, killed, 40, spawn,
                            lambda i: addrs[i])
        live = [i for i in nodes if i not in killed
                and nodes[i].state == SerfState.ALIVE]
        await _converged(nodes, live, 30.0,
                         f"{stream} api storm seed {seed}")
    finally:
        for s in nodes.values():
            if s.state != SerfState.SHUTDOWN:
                await s.shutdown()


async def test_key_rotation_storm_over_dstream(tmp_path):
    """Mid-run cluster key rotation while the dstream SEGMENT plane is
    encrypted with the same keyring: the rotation must propagate to both
    the gossip wire and the stream segments (shared mutable keyring), and
    a post-rotation rejoiner with the rotated ring must converge."""
    from serf_tpu.host.keyring import SecretKeyring
    from serf_tpu.options import MemberlistOptions

    rng = random.Random(13)
    k1, k2 = bytes(range(16)), bytes(range(16, 32))
    n = 4
    rings = [SecretKeyring(k1) for _ in range(n)]
    ml = dataclasses.replace(MemberlistOptions.local(), compression="zlib")
    opts = dataclasses.replace(Options.local(), memberlist=ml)
    transports = [await DatagramStreamTransport.bind(("127.0.0.1", 0),
                                                     keyring=rings[i])
                  for i in range(n)]
    for t in transports:
        _inject_loss(t, rng, 0.05)
    nodes = {i: await Serf.create(transports[i], opts, f"rot-{i}",
                                  keyring=rings[i]) for i in range(n)}
    try:
        for i in range(1, n):
            await nodes[i].join(transports[0].local_addr)
        await _converged(nodes, list(range(n)), 10.0, "pre-rotation")
        km = nodes[0].key_manager()
        out = await km.install_key(k2)
        assert out.num_err == 0, out
        out = await km.use_key(k2)
        assert out.num_err == 0, out
        # kill + rejoin one node with the ROTATED ring (operator handout)
        await nodes[3].shutdown()
        ring = SecretKeyring(k2, keys=[k1])
        t = await DatagramStreamTransport.bind(("127.0.0.1", 0),
                                               keyring=ring)
        _inject_loss(t, rng, 0.05)
        transports[3] = t
        nodes[3] = await Serf.create(t, opts, "rot-3", keyring=ring)
        await nodes[3].join(transports[0].local_addr)
        for op in range(6):
            await nodes[op % 3].user_event(f"rot-{op}", b"z", coalesce=False)
        live = [i for i in nodes if nodes[i].state == SerfState.ALIVE]
        await _converged(nodes, live, 25.0, "post-rotation")
        for i in live:
            assert nodes[i].memberlist.keyring().primary_key() == k2
    finally:
        for s in nodes.values():
            if s.state != SerfState.SHUTDOWN:
                await s.shutdown()
