"""Test harness: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on ``--xla_force_host_platform_device_count=8`` CPU devices, the
same way the driver's ``dryrun_multichip`` does.  This mirrors the
reference's runtime-generic test strategy (SURVEY.md §4): one test body,
parameterized by backend.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import asyncio  # noqa: E402
import inspect  # noqa: E402

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line("markers", "asyncio: run async test via asyncio.run")


@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
    """Minimal async-test support (pytest-asyncio is not in the image)."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(fn(**kwargs))
        return True
    return None
