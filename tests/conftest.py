"""Test harness: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on ``--xla_force_host_platform_device_count=8`` CPU devices, the
same way the driver's ``dryrun_multichip`` does.  This mirrors the
reference's runtime-generic test strategy (SURVEY.md §4): one test body,
parameterized by backend.
"""

import os

# Hard override: the environment's site hook registers the axon (real TPU
# tunnel) PJRT plugin at interpreter start; tests must run on the virtual
# 8-device CPU mesh.  Env alone is not enough — the config update after
# import is what reliably wins over the plugin registration.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
if "xla_backend_optimization_level" not in _flags:
    # tier-1 is XLA-COMPILE-bound on CPU (measured ~30% of suite wall
    # time in backend optimization); tests assert semantics, not CPU
    # codegen quality, so compile at -O0.  TPU runs and bench.py are
    # untouched (this is test-harness-only).
    _flags = (_flags + " --xla_backend_optimization_level=0").strip()
os.environ["XLA_FLAGS"] = _flags
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import asyncio  # noqa: E402
import inspect  # noqa: E402
import time  # noqa: E402

import pytest  # noqa: E402

#: tier-1 runtime budget guard (ISSUE 5 satellite): the slow-window
#: baseline the suite must stay under, vs. the driver's hard timeout.
#: pytest_terminal_summary prints a loud warning into the run log when
#: the wall clock exceeds the baseline — overload soaks must not
#: silently eat the tier-1 headroom.
TIER1_BASELINE_S = 790.0
TIER1_TIMEOUT_S = 870.0
_SESSION_T0 = time.monotonic()


def pytest_configure(config):
    config.addinivalue_line("markers", "asyncio: run async test via asyncio.run")
    # tier-1 runs `-m 'not slow'` (ROADMAP.md).  `slow` marks REDUNDANT
    # heavy parametrizations only (extra seeds of an already-covered code
    # path) — never the sole test of a distinct path — to keep tier-1
    # inside its runtime budget with >=10% headroom.
    config.addinivalue_line(
        "markers", "slow: heavy redundant parametrization; excluded from "
                   "tier-1 (-m 'not slow'), run explicitly with -m slow")
    # persist the slowest-test table into every run log (tier-1 tees its
    # terminal output): the budget guard below is only actionable when
    # the log also says WHERE the time went
    if config.option.durations is None:
        config.option.durations = 25


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    elapsed = time.monotonic() - _SESSION_T0
    if elapsed > TIER1_BASELINE_S:
        terminalreporter.write_line(
            f"TIER1-BUDGET WARNING: suite wall clock {elapsed:.0f}s exceeds "
            f"the ~{TIER1_BASELINE_S:.0f}s baseline (hard timeout "
            f"{TIER1_TIMEOUT_S:.0f}s) — check --durations table above for "
            "what grew", red=True, bold=True)
    else:
        terminalreporter.write_line(
            f"tier1-budget: {elapsed:.0f}s of ~{TIER1_BASELINE_S:.0f}s "
            f"baseline ({TIER1_TIMEOUT_S:.0f}s timeout)")


@pytest.fixture(scope="session")
def vmesh8():
    """The shard tests' 8-virtual-device CPU mesh (ISSUE 6 CI satellite).

    The device count is PROCESS-GLOBAL: ``xla_force_host_platform_
    device_count=8`` is set at the top of this conftest, before the
    first jax import, for the WHOLE tier-1 process — it cannot be
    toggled per test, and this fixture deliberately does not try (a
    mid-session flag flip would silently not take).  The fixture is the
    one sanctioned handle: it hands out the ``Mesh`` when the 8 devices
    actually materialized and skips (rather than mysteriously failing
    in shard_map) when some other harness launched the suite without
    the flag.  Unsharded tests are unaffected either way — a CPU
    "device" here is a thread-backed virtual device, and single-device
    jit never touches the other seven.
    """
    import jax

    from serf_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("virtual 8-device CPU mesh not provisioned "
                    "(xla_force_host_platform_device_count must be set "
                    "before the first jax import)")
    return make_mesh(8)


@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
    """Minimal async-test support (pytest-asyncio is not in the image)."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(fn(**kwargs))
        return True
    return None
