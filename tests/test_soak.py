"""Randomized protocol soak: a seeded storm of joins, leaves, kills,
events, and queries against a live host cluster must never wedge the
engine, and the survivors must converge afterwards.

The randomized analog of the reference's scenario suites — operations are
drawn from the full public API surface.
"""

import asyncio
import random

import pytest

from serf_tpu.host import LoopbackNetwork, QueryParam, Serf, SerfState
from serf_tpu.options import Options
from serf_tpu.types.member import MemberStatus

pytestmark = pytest.mark.asyncio


def _rebind(net, addr):
    """Bind the address anew for a restarted agent.  shutdown() always
    releases the loopback address, so a live registration here would mean
    two Serf instances racing on one packet queue — fail loudly."""
    assert addr not in net.transports, f"{addr} still owned by a live node"
    return net.bind(addr)


async def _assert_converges(nodes, live, want, deadline_s, label):
    """Every live node's ALIVE view must cover ``want`` within the deadline.
    Generous deadlines: these are liveness soaks, not latency bars (the 7 s
    convergence budget lives in the scenario suites), and a loaded CI
    machine must not flake them."""
    deadline = asyncio.get_running_loop().time() + deadline_s
    while asyncio.get_running_loop().time() < deadline:
        views = [{m.node.id for m in nodes[i].members()
                  if m.status == MemberStatus.ALIVE} for i in live]
        if all(v >= want for v in views):
            return
        await asyncio.sleep(0.05)
    views = [{m.node.id for m in nodes[i].members()
              if m.status == MemberStatus.ALIVE} for i in live]
    for v in views:
        assert v >= want, f"{label}: survivor view {v} missing {want - v}"


#: seed 1 stays tier-1 (the randomized API-storm loop is a distinct code
#: path); the extra seeds are redundancy and ride `-m slow`
@pytest.mark.parametrize(
    "seed", [1] + [pytest.param(s, marks=pytest.mark.slow)
                   for s in (2, 7, 8)])
async def test_randomized_soak(seed):
    from tests.storm_ops import run_api_storm

    rng = random.Random(seed)
    net = LoopbackNetwork()
    n = 10
    nodes = {}
    for i in range(n):
        nodes[i] = await Serf.create(net.bind(f"s{i}"), Options.local(),
                                     f"soak-{i}")
    for i in range(1, n):
        await nodes[i].join("s0")
    killed = set()
    try:
        async def respawn(i):
            return await Serf.create(_rebind(net, f"s{i}"),
                                     Options.local(), f"soak-{i}")

        await run_api_storm(rng, nodes, killed, 60, respawn,
                            lambda i: f"s{i}")
        live = [i for i in nodes if i not in killed
                and nodes[i].state == SerfState.ALIVE]
        await _assert_converges(nodes, live, {f"soak-{i}" for i in live},
                                25.0, f"seed {seed}")
    finally:
        for i, s in nodes.items():
            if s.state != SerfState.SHUTDOWN:
                await s.shutdown()


@pytest.mark.parametrize(
    "seed", [402, pytest.param(403, marks=pytest.mark.slow)])
async def test_partition_churn_storm(seed):
    """Churn storm with a mid-run bisection and heal.  Rejoins retry until
    they land (agent behavior — a node whose only join attempt failed
    during the partition is not a member and cannot be expected in views;
    the reference's reconnector likewise only re-dials FAILED members)."""
    rng = random.Random(seed)
    net = LoopbackNetwork()
    n = 8
    nodes = {i: await Serf.create(net.bind(f"s{i}"), Options.local(),
                                  f"storm-{i}") for i in range(n)}
    for i in range(1, n):
        await nodes[i].join("s0")
    killed = set()
    pending_join = {}
    try:
        for op in range(50):
            live = [i for i in nodes if i not in killed]
            r = rng.random()
            if op == 15:
                net.partition(set(f"s{i}" for i in range(4)),
                              set(f"s{i}" for i in range(4, n)))
            if op == 35:
                net.heal()
            # agent-like retry of any join that failed earlier
            for b in list(pending_join):
                try:
                    await nodes[b].join(pending_join[b])
                    del pending_join[b]
                except ConnectionError:
                    pass
            if r < 0.25 and len(live) > 4:
                v = rng.choice([i for i in live if i != 0])
                if rng.random() < 0.6:
                    await nodes[v].leave()
                await nodes[v].shutdown()
                killed.add(v)
                pending_join.pop(v, None)
            elif r < 0.5 and killed:
                b = rng.choice(sorted(killed))
                killed.discard(b)
                nodes[b] = await Serf.create(
                    _rebind(net, f"s{b}"), Options.local(), f"storm-{b}")
                tgt = f"s{rng.choice([i for i in nodes if i not in killed and i != b])}"
                try:
                    await nodes[b].join(tgt)
                except ConnectionError:
                    pending_join[b] = tgt   # partitioned: retry later
            if rng.random() < 0.3:
                await asyncio.sleep(0.02)
        net.heal()
        for b in list(pending_join):   # final retry round
            try:
                await nodes[b].join(pending_join[b])
                del pending_join[b]
            except ConnectionError:
                pass
        live = [i for i in nodes if i not in killed
                and nodes[i].state == SerfState.ALIVE
                and i not in pending_join]
        await _assert_converges(nodes, live, {f"storm-{i}" for i in live},
                                30.0, f"seed {seed}")
    finally:
        for s in nodes.values():
            if s.state != SerfState.SHUTDOWN:
                await s.shutdown()


async def test_encrypted_rotation_storm():
    """Churn storm on an encrypted+compressed+checksummed wire with a
    cluster-wide key rotation mid-run.  Rejoiners boot with the full
    persisted keyring (per serf rotation guidance, a node missing a key
    cannot decrypt replies encrypted with the new primary — verified
    separately as correct fail-loudly behavior)."""
    import dataclasses

    from serf_tpu.host.keyring import SecretKeyring
    from serf_tpu.options import MemberlistOptions

    rng = random.Random(22)
    net = LoopbackNetwork()
    k1, k2 = bytes(range(16)), bytes(range(16, 32))
    ml = dataclasses.replace(MemberlistOptions.local(), compression="zlib",
                             checksum="xxhash32")
    opts = dataclasses.replace(Options.local(), memberlist=ml)
    nodes = {i: await Serf.create(net.bind(f"e{i}"), opts, f"enc-{i}",
                                  keyring=SecretKeyring(k1))
             for i in range(6)}
    for i in range(1, 6):
        await nodes[i].join("e0")
    killed = set()
    rotated = False
    try:
        for op in range(40):
            live = [i for i in nodes if i not in killed]
            r = rng.random()
            if op == 20 and not rotated:
                km = nodes[live[0]].key_manager()
                out = await km.install_key(k2)
                # every live node must have answered, or a missed install
                # would surface 25 s later as an opaque convergence failure
                assert out.num_err == 0 and out.num_resp >= len(live), out
                out = await km.use_key(k2)
                assert out.num_err == 0 and out.num_resp >= len(live), out
                rotated = True
            if r < 0.2 and len(live) > 3:
                v = rng.choice([i for i in live if i != 0])
                if rng.random() < 0.5:
                    await nodes[v].leave()
                await nodes[v].shutdown()
                killed.add(v)
            elif r < 0.4 and killed:
                b = rng.choice(sorted(killed))
                killed.discard(b)
                # post-rotation rejoiners get the rotated keyring the way
                # a real operator redistributes it (a node killed BEFORE
                # the rotation never saved k2; booting it with only k1
                # fails loudly by design — covered separately)
                ring = (SecretKeyring(k2, keys=[k1]) if rotated
                        else SecretKeyring(k1))
                nodes[b] = await Serf.create(_rebind(net, f"e{b}"), opts,
                                             f"enc-{b}", keyring=ring)
                await nodes[b].join(
                    f"e{rng.choice([i for i in nodes if i not in killed and i != b])}")
            elif r < 0.7:
                await nodes[rng.choice(live)].user_event(
                    f"e{op}", b"x" * 40, coalesce=False)
            if rng.random() < 0.3:
                await asyncio.sleep(0.02)
        assert rotated
        live = [i for i in nodes if i not in killed
                and nodes[i].state == SerfState.ALIVE]
        await _assert_converges(nodes, live, {f"enc-{i}" for i in live},
                                25.0, "encrypted storm")
        # every survivor runs on the rotated primary
        for i in live:
            assert nodes[i].memberlist.keyring().primary_key() == k2
    finally:
        for s in nodes.values():
            if s.state != SerfState.SHUTDOWN:
                await s.shutdown()
