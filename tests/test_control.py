"""Adaptive control plane (ISSUE 11): controller stability + the A/B
acceptance — static configs breach, controlled twins re-converge.

Tier-1 contract (all small-N, module-scoped fixtures share the chaos
runs):

- hysteresis: a signal flickering around its threshold never actuates;
  a sustained signal actuates exactly once per hysteresis window;
- bounded step: no knob ever moves more than its per-round clamp, and
  every value stays inside its band (relaxes never cross the base);
- controller-off is BIT-EXACT with the static path: a disabled config
  never reads the control leaves (a mangled ControlState changes no
  gossip/vivaldi leaf);
- the two named control plans: static leg breaches an SLO
  (judge_device_run), controlled leg is all-green with the
  control-stability invariant;
- a recorded controlled run replays bit-exactly INCLUDING the control
  decisions, and a perturbed control step is named by the differ;
- the sharded controlled round (effective-fanout masking inside the
  shard_map exchange leg) is bit-exact with the unsharded one;
- the host ControllerTick: widens admission under shed burn with
  healthy nodes, tightens under degraded health, hysteresis + clamps
  pinned, and replay applies recorded decisions.
"""

import copy

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serf_tpu.control.device import (
    CONTROL_FIELDS,
    ControlConfig,
    ControlSignals,
    KNOB_FIELDS,
    control_step,
    gate_injections,
    knob_bounds,
    make_control,
)
from serf_tpu.models.dissemination import GossipConfig
from serf_tpu.models.failure import FailureConfig
from serf_tpu.models.swim import (
    ClusterConfig,
    make_cluster,
    run_cluster_sustained,
)

_FANOUT = KNOB_FIELDS.index("fanout")
_INJECT = KNOB_FIELDS.index("inject_limit")


def _cfg_tuple(n=64, k=32, fanout=4, fanout_base=1, **ctl):
    ccfg = ControlConfig(enabled=True, fanout_base=fanout_base, **ctl)
    gcfg = GossipConfig(n=n, k_facts=k, fanout=fanout,
                        peer_sampling="rotation")
    fcfg = FailureConfig(suspicion_rounds=8, max_new_facts=8,
                         probe_schedule="round_robin")
    return ccfg, gcfg, fcfg


def _sig(agreement=1.0, false_dead=0.0, overflow=0.0):
    return ControlSignals(agreement=jnp.float32(agreement),
                          false_dead=jnp.float32(false_dead),
                          overflow=jnp.float32(overflow))


def _drive(ctl, sigs, ccfg, gcfg, fcfg):
    rows = []
    for s in sigs:
        ctl = control_step(ctl, s, ccfg, gcfg, fcfg)
        rows.append(np.asarray(ctl.knobs))
    return ctl, np.stack(rows)


# ---------------------------------------------------------------------------
# control-law units
# ---------------------------------------------------------------------------


def test_hysteresis_flicker_never_actuates():
    """A telemetry signal oscillating around the threshold every round
    resets the streak each flip — the knob must never move."""
    ccfg, gcfg, fcfg = _cfg_tuple(hyst_up=3)
    ctl = make_control(ccfg, gcfg, fcfg)
    sigs = [_sig(agreement=0.5 if i % 2 == 0 else 0.95)
            for i in range(40)]  # low / neutral / low / neutral ...
    _, rows = _drive(ctl, sigs, ccfg, gcfg, fcfg)
    assert np.all(rows[:, _FANOUT] == rows[0, _FANOUT])


def test_hysteresis_sustained_signal_actuates_per_window():
    """A sustained low-agreement signal widens the fan-out exactly once
    per hyst_up rounds: monotone, evenly spaced — never a jump."""
    ccfg, gcfg, fcfg = _cfg_tuple(hyst_up=3)
    ctl = make_control(ccfg, gcfg, fcfg)
    _, rows = _drive(ctl, [_sig(agreement=0.5)] * 12, ccfg, gcfg, fcfg)
    fan = rows[:, _FANOUT]
    # +1 at rounds 3, 6, 9 (1-indexed); clamped at gossip.fanout = 4
    assert list(fan) == [1, 1, 2, 2, 2, 3, 3, 3, 4, 4, 4, 4]


def test_bounded_step_and_clamps_under_random_signals():
    ccfg, gcfg, fcfg = _cfg_tuple()
    base, lo, hi, step = knob_bounds(ccfg, gcfg, fcfg)
    rng = np.random.default_rng(7)
    sigs = [_sig(agreement=rng.uniform(0.3, 1.0),
                 false_dead=float(rng.integers(0, 3)),
                 overflow=float(i * rng.integers(0, 40)))
            for i in range(120)]
    ctl = make_control(ccfg, gcfg, fcfg)
    _, rows = _drive(ctl, sigs, ccfg, gcfg, fcfg)
    prev = np.asarray(base)
    for row in rows:
        assert np.all(np.abs(row - prev) <= step), (row, prev)
        assert np.all(row >= lo) and np.all(row <= hi), row
        prev = row


def test_relax_never_crosses_base():
    """After the protective excursion, sustained calm relaxes each knob
    back to its BASE — never past it."""
    ccfg, gcfg, fcfg = _cfg_tuple(hyst_up=1, hyst_down=1)
    base, _, _, _ = knob_bounds(ccfg, gcfg, fcfg)
    ctl = make_control(ccfg, gcfg, fcfg)
    # protective excursion: overflow ledger growing 8/round, agreement
    # low, false-deads present — every knob leaves its base
    ctl, _ = _drive(ctl, [_sig(agreement=0.2, false_dead=2.0,
                               overflow=8.0 * (i + 1))
                          for i in range(10)], ccfg, gcfg, fcfg)
    # calm: ledger frozen (delta 0 -> EWMA decays), agreement converged
    ctl, rows = _drive(ctl, [_sig(agreement=1.0, overflow=80.0)] * 60,
                       ccfg, gcfg, fcfg)
    assert np.array_equal(rows[-1], np.asarray(base))
    # monotone return: no overshoot below/above base on the way
    assert np.all(rows[:, _FANOUT] >= base[_FANOUT])
    assert np.all(rows[:, _INJECT] <= base[_INJECT])


def test_gate_injections_budget_depletes_across_batches():
    ccfg, gcfg, fcfg = _cfg_tuple(inject_limit_base=5)
    ctl = make_control(ccfg, gcfg, fcfg)
    a1, ctl = gate_injections(ctl, jnp.ones((4,), bool))
    assert int(jnp.sum(a1)) == 4 and int(ctl.inject_tokens) == 1
    a2, ctl = gate_injections(ctl, jnp.ones((4,), bool))
    # one token left: exactly the first active admitted (prefix kept)
    assert list(np.asarray(a2)) == [True, False, False, False]
    assert int(ctl.shed) == 3
    a3, ctl = gate_injections(ctl, jnp.ones((2,), bool))
    assert int(jnp.sum(a3)) == 0 and int(ctl.shed) == 5
    # refill on the next control tick
    ctl = control_step(ctl, _sig(), ccfg, gcfg, fcfg)
    assert int(ctl.inject_tokens) == 5


def test_controller_off_never_reads_the_control_leaf():
    """cfg.control.enabled=False is the static path: mangling every
    control value changes NO gossip/vivaldi leaf (bit-exact), pinned on
    the sustained flagship driver."""
    cfg = ClusterConfig(
        gossip=GossipConfig(n=48, k_facts=32, peer_sampling="rotation"),
        failure=FailureConfig(suspicion_rounds=8, max_new_facts=8,
                              probe_schedule="round_robin"),
        push_pull_every=4)
    key = jax.random.key(3)
    st = make_cluster(cfg, key)
    mangled = st._replace(control=st.control._replace(
        knobs=jnp.asarray([4, 7, 8, 1, 2], jnp.int32),
        inject_tokens=jnp.asarray(0, jnp.int32),
        shed=jnp.asarray(999, jnp.uint32)))
    fin_a = run_cluster_sustained(st, cfg, key, 8, events_per_round=2)
    fin_b = run_cluster_sustained(mangled, cfg, key, 8,
                                  events_per_round=2)
    for leaf_a, leaf_b in zip(jax.tree_util.tree_leaves(fin_a.gossip),
                              jax.tree_util.tree_leaves(fin_b.gossip)):
        assert bool(jnp.all(leaf_a == leaf_b))
    for leaf_a, leaf_b in zip(jax.tree_util.tree_leaves(fin_a.vivaldi),
                              jax.tree_util.tree_leaves(fin_b.vivaldi)):
        assert bool(jnp.all(leaf_a == leaf_b))
    # and the mangled leaf rides through untouched
    assert int(fin_b.control.shed) == 999


def test_control_registry_matches_knob_fields():
    from serf_tpu.analysis.registry import CONTROL_KNOBS
    from serf_tpu.control.host import HOST_KNOBS

    assert set(CONTROL_KNOBS) == set(KNOB_FIELDS) | set(HOST_KNOBS)


# ---------------------------------------------------------------------------
# the A/B acceptance plans (module-scoped runs, small N)
# ---------------------------------------------------------------------------


def _run_ab(plan_name: str, n: int):
    from serf_tpu.control.profiles import device_ab_config
    from serf_tpu.faults.device import run_device_plan
    from serf_tpu.faults.plan import named_plan
    from serf_tpu.obs import slo

    plan = named_plan(plan_name)
    out = {}
    for controlled in (False, True):
        cfg = device_ab_config(plan_name, n, 32, controlled)
        res = run_device_plan(plan, cfg, collect_telemetry=True)
        out["controlled" if controlled else "static"] = (
            res, slo.judge_device_run(res, plan, emit=False))
    return out


@pytest.fixture(scope="module")
def loss_ab():
    return _run_ab("control-loss-converge", 128)


@pytest.fixture(scope="module")
def shed_ab():
    return _run_ab("control-overload-shed", 96)


def test_loss_plan_static_breaches_convergence(loss_ab):
    res, verdicts = loss_ab["static"]
    assert not res.report.ok          # membership-convergence invariant
    breached = {v.slo for v in verdicts if not v.ok}
    assert "convergence-settle" in breached


def test_loss_plan_controlled_reconverges_all_green(loss_ab):
    res, verdicts = loss_ab["controlled"]
    assert res.report.ok, res.report.format()
    assert all(v.ok for v in verdicts), [v.slo for v in verdicts
                                         if not v.ok]
    # the controller actually adapted (widened fan-out past base)
    assert res.control_decisions
    assert max(d["knobs"]["fanout"] for d in res.control_decisions) > 1
    stab = [r for r in res.report.results
            if r.name == "control-stability"]
    assert stab and stab[0].ok, stab


def test_shed_plan_static_breaches_shed_ratio(shed_ab):
    res, verdicts = shed_ab["static"]
    breached = {v.slo for v in verdicts if not v.ok}
    assert "shed-ratio" in breached
    assert res.dropped / max(1, res.offered) > 0.95


def test_shed_plan_controlled_sheds_up_front_and_is_green(shed_ab):
    res, verdicts = shed_ab["controlled"]
    assert res.report.ok, res.report.format()
    assert all(v.ok for v in verdicts), [v.slo for v in verdicts
                                         if not v.ok]
    # admission control moved the loss up front: the controller's shed
    # ledger is large, the ring's mid-flight clobber ratio is small
    assert res.control_final["shed"] > 0
    assert res.dropped / max(1, res.offered) < 0.95
    # the tightening law actually fired
    assert min(d["knobs"]["inject_limit"]
               for d in res.control_decisions) \
        < res.control_rows[0][KNOB_FIELDS.index("inject_limit")]


def test_control_trajectory_row_shape(shed_ab):
    res, _ = shed_ab["controlled"]
    assert res.control_rows.shape == (res.rounds_run,
                                      len(CONTROL_FIELDS))
    assert res.control_final["steps"] == res.control_rows[-1][-1]


# ---------------------------------------------------------------------------
# record/replay of a controlled run (bit-exact incl. the control row)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def controlled_recording():
    from serf_tpu.control.profiles import device_ab_config
    from serf_tpu.faults.device import run_device_plan
    from serf_tpu.faults.plan import FaultPhase, FaultPlan
    from serf_tpu.replay.recording import RunRecorder
    from serf_tpu.replay.replayer import replay_device

    # a mini overload plan (tier-1 budget): one 400-event burst past the
    # ring + injection budget still produces tighten decisions, at a
    # third of the named plan's rounds/chunks
    plan = FaultPlan(
        name="mini-control-shed", n=4, seed=5,
        phases=(FaultPhase(name="warm", duration_s=0.2, rounds=8),
                FaultPhase(name="burst", duration_s=0.5, rounds=8,
                           event_rate=800.0)),
        settle_s=1.0, settle_rounds=16)
    cfg = device_ab_config("control-overload-shed", 64, 32, True)
    rec = RunRecorder()
    run_device_plan(plan, cfg, recorder=rec)
    recording = rec.to_recording()
    replay = replay_device(recording).to_recording()
    return recording, replay


def test_controlled_replay_bit_exact_including_control(
        controlled_recording):
    from serf_tpu.replay.differ import diff_recordings

    recording, replay = controlled_recording
    ctl_steps = [r for r in recording.records
                 if r.get("kind") == "step" and r["op"] == "control"]
    assert ctl_steps, "a controlled storm run must record decisions"
    rep = diff_recordings(recording, replay)
    assert rep.ok, rep.format()


def test_perturbed_control_decision_is_named_by_the_differ(
        controlled_recording):
    from serf_tpu.replay.differ import diff_recordings

    recording, replay = controlled_recording
    pert = copy.deepcopy(recording)
    seq = None
    for r in pert.records:
        if r.get("kind") == "step" and r["op"] == "control":
            r["args"]["knobs"]["inject_limit"] += 16
            r["chain"] = "0" * 16
            seq = r["seq"]
            break
    rep = diff_recordings(pert, replay)
    assert not rep.ok
    assert rep.first_divergent_step["seq"] == seq
    assert rep.first_divergent_step["a"]["op"] == "control"


# ---------------------------------------------------------------------------
# sharded controlled round: the effective-fanout mask composes with the
# explicit shard_map exchange leg bit-exactly
# ---------------------------------------------------------------------------


def test_sharded_controlled_round_bit_exact(vmesh8):
    from serf_tpu.parallel.mesh import shard_state

    cfg = ClusterConfig(
        gossip=GossipConfig(n=96, k_facts=32, fanout=4,
                            peer_sampling="rotation"),
        failure=FailureConfig(suspicion_rounds=8, max_new_facts=8,
                              probe_schedule="round_robin"),
        push_pull_every=8,
        control=ControlConfig(enabled=True, fanout_base=2, hyst_up=1,
                              hyst_down=2))
    key = jax.random.key(5)
    st = make_cluster(cfg, key)
    fin1 = run_cluster_sustained(st, cfg, key, 8, events_per_round=2)
    fin8 = run_cluster_sustained(shard_state(st, vmesh8), cfg, key, 8,
                                 events_per_round=2, mesh=vmesh8)
    for a, b in zip(jax.tree_util.tree_leaves(fin1.gossip),
                    jax.tree_util.tree_leaves(fin8.gossip)):
        assert bool(jnp.all(a == b))
    assert bool(jnp.all(fin1.control.knobs == fin8.control.knobs))
    assert int(fin1.control.steps) == int(fin8.control.steps)


# ---------------------------------------------------------------------------
# host controller
# ---------------------------------------------------------------------------


async def test_host_controller_widen_tighten_hysteresis_and_clamps():
    """Drive ControllerTick against two real loopback Serfs with a
    synthetic ring store: shed burn at green health widens the
    admission buckets once per hyst_up ticks up to the clamp; degraded
    health tightens them back (never below min_scale); the decision log
    satisfies the stability invariant."""
    from serf_tpu.control.host import ControllerTick, HostControlConfig
    from serf_tpu.faults.invariants import InvariantReport, \
        check_control_host
    from serf_tpu.host.serf import Serf
    from serf_tpu.host.transport import LoopbackNetwork
    from serf_tpu.obs.timeseries import SeriesStore
    from serf_tpu.options import Options

    net = LoopbackNetwork()
    opts = Options.local(user_event_rate=4.0, user_event_burst=4,
                         query_rate=4.0, query_burst=4)
    serfs = [await Serf.create(net.bind(f"c{i}"), opts, f"c{i}")
             for i in range(2)]
    try:
        store = SeriesStore()
        cfg = HostControlConfig(enabled=True, hyst_up=2, hyst_down=4,
                                step=2.0, max_scale=4.0)
        ctl = ControllerTick(lambda: serfs, store, cfg=cfg)
        base_rate = serfs[0]._admission._buckets["user_event"].rate

        def feed(shed, admitted, t):
            store.append("serf.overload.ingress_shed", t, shed,
                         kind="delta")
            store.append("serf.overload.ingress_admitted", t, admitted,
                         kind="delta")

        class _Score:
            def __init__(self, score):
                self.score = score

        def degrade(score):
            # the controller samples the nodes' own health scorers (the
            # admission gate's pattern), not a ring series
            for s in serfs:
                s._health.sample = lambda consume=False, _s=score: \
                    _Score(_s)

        # 8 ticks of heavy shed at green health: widen at ticks 2, 4, 6,
        # 8 — ×2 each, clamped at 4× base
        for t in range(8):
            feed(50, 1, float(t))
            ctl.tick()
        rate = serfs[0]._admission._buckets["user_event"].rate
        assert rate == pytest.approx(base_rate * cfg.max_scale)
        widen_decisions = [d for d in ctl.decisions
                           if d[1] == "user_event_rate"]
        assert len(widen_decisions) == 2          # 2x then clamp at 4x
        # degraded health tightens (hyst_up window again — protective)
        degrade(10)
        for t in range(8, 14):
            feed(0, 1, float(t))
            ctl.tick()
        rate2 = serfs[0]._admission._buckets["user_event"].rate
        assert rate2 < rate
        lo = base_rate * cfg.min_scale
        assert rate2 >= lo - 1e-9
        rep = InvariantReport(plane="host", plan="unit")
        check_control_host(rep, ctl)
        assert rep.ok, rep.format()
    finally:
        for s in serfs:
            await s.shutdown()


async def test_host_replay_applies_recorded_control_steps():
    from serf_tpu.control.host import apply_recorded
    from serf_tpu.host.serf import Serf
    from serf_tpu.host.transport import LoopbackNetwork
    from serf_tpu.options import Options

    net = LoopbackNetwork()
    s = await Serf.create(net.bind("r0"), Options.local(), "r0")
    try:
        apply_recorded({0: s}, "gossip_nodes", 5.0)
        assert s.memberlist.opts.gossip_nodes == 5
        apply_recorded({0: s}, "breaker_cooldown", 7.5)
        assert s.memberlist._breaker.cooldown == 7.5
        with pytest.raises(ValueError):
            apply_recorded({0: s}, "not_a_knob", 1.0)
    finally:
        await s.shutdown()
