"""In-collective telemetry (ISSUE 15 tentpole a, acceptance-pinned):
the sharded row — fused psum/pmax legs inside the exchange mesh,
``parallel.ring.round_telemetry_sharded`` — is BIT-IDENTICAL per round
to the gathered PR-10 row for both ICI schedules × both stamp flavors ×
controller on/off; the leg ships no N-plane collective (jaxpr-pinned);
and the same equality holds across a full chaos plan
(partition-heal-loss) on the sharded executor path.

Budget discipline: one tiny config (n=64, K=32), 10-round scans, the
unsharded reference memoized per (stamp flavor, controller) since the
ICI schedule cannot affect it.
"""

import jax
import jax.numpy as jnp
import pytest

from serf_tpu.control.device import ControlConfig
from serf_tpu.models.dissemination import (
    GossipConfig,
    K_USER_EVENT,
    inject_fact,
)
from serf_tpu.models.failure import FailureConfig
from serf_tpu.models.swim import (
    ClusterConfig,
    make_cluster,
    round_telemetry,
    run_cluster_sustained,
)
from serf_tpu.parallel.mesh import make_mesh, shard_state

N, K, ROUNDS = 64, 32, 10


def _cfg(pack=True, schedule="ring", control=False):
    return ClusterConfig(
        gossip=GossipConfig(n=N, k_facts=K, peer_sampling="rotation",
                            pack_stamp=pack),
        failure=FailureConfig(suspicion_rounds=8, max_new_facts=8,
                              probe_schedule="round_robin"),
        control=ControlConfig(enabled=control),
        push_pull_every=8, probe_every=2, exchange_schedule=schedule)


def _seeded(cfg):
    st = make_cluster(cfg, jax.random.key(0))
    g = inject_fact(st.gossip, cfg.gossip, subject=3, kind=K_USER_EVENT,
                    incarnation=0, ltime=5, origin=0)
    # two silent crashes: detection traffic (suspicions, declarations,
    # false-DEAD judgments) is part of the row being pinned
    g = g._replace(alive=g.alive.at[jnp.asarray([7, N // 2])].set(False))
    return st._replace(gossip=g)


def _ref_rows(pack, control):
    """Unsharded reference telemetry trajectory, memoized per (stamp
    flavor, controller) — the exchange schedule cannot affect it."""
    cache = _ref_rows.__dict__.setdefault("cache", {})
    key = (pack, control)
    if key not in cache:
        cfg = _cfg(pack=pack, control=control)
        run = jax.jit(lambda s, k: run_cluster_sustained(
            s, cfg, k, ROUNDS, 2, collect_telemetry=True))
        _, rows = run(_seeded(cfg), jax.random.key(3))
        cache[key] = jax.device_get(rows)
    return cache[key]


@pytest.mark.parametrize("pack", [True, False])
@pytest.mark.parametrize("schedule", ["ring", "allgather"])
@pytest.mark.parametrize("control", [False, True])
def test_in_collective_row_bit_identical(vmesh8, pack, schedule, control):
    cfg = _cfg(pack=pack, schedule=schedule, control=control)
    run = jax.jit(lambda s, k: run_cluster_sustained(
        s, cfg, k, ROUNDS, 2, mesh=vmesh8, collect_telemetry=True))
    _, rows = run(shard_state(_seeded(cfg), vmesh8), jax.random.key(3))
    sharded = jax.device_get(rows)
    ref = _ref_rows(pack, control)
    assert sharded.shape == ref.shape
    assert (sharded == ref).all(), (
        "sharded in-collective row diverged from the gathered row at "
        f"rounds {sorted(set(int(i) for i, _ in zip(*((sharded != ref).nonzero()))))}")


def test_telemetry_leg_ships_no_nplane_collective(vmesh8):
    """The acceptance 'zero additional per-round gathers': the traced
    in-collective telemetry computation contains psum + pmax legs and
    NO all_gather / gather-of-N anywhere — the O(fields) claim at the
    jaxpr level, beside the accounting model that prices it."""
    cfg = _cfg()
    st = shard_state(_seeded(cfg), vmesh8)
    jaxpr = str(jax.make_jaxpr(
        lambda s: round_telemetry(s, cfg, mesh=vmesh8))(st))
    assert "psum" in jaxpr
    assert "pmax" in jaxpr
    assert "all_gather" not in jaxpr
    assert "all_to_all" not in jaxpr


def test_chaos_plan_rows_match_sharded_vs_gathered(vmesh8):
    """Satellite pin: under a full chaos plan (partition-heal-loss —
    partitions, loss, heal, settle) the sharded executor's in-collective
    per-round rows equal the unsharded executor's gathered rows, ring
    series and final row both."""
    from serf_tpu.faults.device import run_device_plan
    from serf_tpu.faults.plan import named_plan

    plan = named_plan("partition-heal-loss")
    cfg = _cfg()
    r_ref = run_device_plan(plan, cfg, collect_telemetry=True)
    r_shard = run_device_plan(plan, cfg, mesh=vmesh8,
                              collect_telemetry=True)
    assert r_ref.telemetry_final == r_shard.telemetry_final
    names = r_ref.telemetry.names()
    assert names == r_shard.telemetry.names()
    for name in names:
        assert r_ref.telemetry.get(name).points() == \
            r_shard.telemetry.get(name).points(), name
    assert r_shard.report.ok, r_shard.report.format()
