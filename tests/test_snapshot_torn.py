"""Snapshot torn-tail tolerance: a crash during append leaves a
truncated record at the end of the log.  Replay must skip the torn tail
with a warning (never raise), report the valid prefix length, and the
writer must truncate the tail on reopen so post-restart appends never
interleave with garbage — pinned with a byte-level truncation sweep.
"""

from serf_tpu.host.snapshot import (
    R_ALIVE,
    R_CLOCK,
    R_EVENT_CLOCK,
    Snapshotter,
    _record,
    open_and_replay_snapshot,
)
from serf_tpu import codec
from serf_tpu.types.member import Node


def _make_log(path) -> bytes:
    recs = [
        _record(R_CLOCK, codec.encode_varint(17)),
        _record(R_ALIVE, Node("alpha", "addr-a").encode()),
        _record(R_ALIVE, Node("beta", "addr-b").encode()),
        _record(R_EVENT_CLOCK, codec.encode_varint(9)),
        _record(R_ALIVE, Node("gamma-with-a-longer-id", "addr-c").encode()),
    ]
    buf = b"".join(recs)
    path.write_bytes(buf)
    return buf


def _prefix_lengths(buf: bytes):
    """Byte offsets at complete-record boundaries."""
    out = [0]
    pos = 0
    while pos < len(buf):
        ln, p = codec.decode_varint(buf, pos + 1)
        pos = p + ln
        out.append(pos)
    return out


def test_truncation_sweep_never_raises_and_matches_prefix(tmp_path):
    """For EVERY truncation point, replay (a) does not raise, (b) equals
    the replay of the longest complete-record prefix, and (c) reports
    that prefix as valid_length."""
    path = tmp_path / "s.snap"
    buf = _make_log(path)
    boundaries = _prefix_lengths(buf)
    for cut in range(len(buf) + 1):
        path.write_bytes(buf[:cut])
        res = open_and_replay_snapshot(str(path))
        want_valid = max(b for b in boundaries if b <= cut)
        assert res.valid_length == want_valid, cut
        ref = open_and_replay_snapshot(str(path))  # idempotent
        assert {n.id for n in res.alive_nodes} == \
            {n.id for n in ref.alive_nodes}
        # the replayed state equals the clean prefix's
        path.write_bytes(buf[:want_valid])
        clean = open_and_replay_snapshot(str(path))
        assert {n.id for n in res.alive_nodes} == \
            {n.id for n in clean.alive_nodes}, cut
        assert (res.last_clock, res.last_event_clock) == \
            (clean.last_clock, clean.last_event_clock), cut


def test_torn_tail_truncated_on_reopen_and_appends_stay_clean(tmp_path):
    """Crash-mid-append then restart: the writer truncates the torn
    bytes before appending, so a LATER replay reads both the old prefix
    and the new records (without the repair, everything after the tear
    would be silently dropped)."""
    path = tmp_path / "s.snap"
    buf = _make_log(path)
    # tear mid-way through the last record
    torn = buf[: len(buf) - 7]
    path.write_bytes(torn)

    replay = open_and_replay_snapshot(str(path))
    assert replay.valid_length < len(torn)
    snap = Snapshotter(str(path), replay)
    try:
        # the reopen repaired the file down to the valid prefix
        assert path.stat().st_size == replay.valid_length
        snap._append(R_ALIVE, Node("delta", "addr-d").encode())
        snap._f.flush()
    finally:
        import asyncio
        asyncio.run(snap.shutdown())

    final = open_and_replay_snapshot(str(path))
    ids = {n.id for n in final.alive_nodes}
    assert "delta" in ids           # the post-restart append is readable
    assert "beta" in ids            # the old complete prefix survived
    assert "gamma-with-a-longer-id" not in ids  # the torn record is gone
    assert final.valid_length == path.stat().st_size


def test_torn_tail_metric_fires(tmp_path):
    from serf_tpu.utils import metrics

    sink = metrics.global_sink()
    base = sink.counter("serf.snapshot.torn_tail")
    path = tmp_path / "s.snap"
    buf = _make_log(path)
    path.write_bytes(buf[:-3])
    open_and_replay_snapshot(str(path))
    assert sink.counter("serf.snapshot.torn_tail") == base + 1


def test_fully_torn_file_boots_empty(tmp_path):
    """A file with no single complete record (e.g. crash on first-ever
    append) boots as empty and is truncated to zero on reopen."""
    path = tmp_path / "s.snap"
    path.write_bytes(b"\x01")      # type byte only, header torn
    res = open_and_replay_snapshot(str(path))
    assert res.valid_length == 0 and not res.alive_nodes
    snap = Snapshotter(str(path), res)
    try:
        assert path.stat().st_size == 0
    finally:
        import asyncio
        asyncio.run(snap.shutdown())


def test_unknown_record_types_skipped_not_fatal(tmp_path):
    """Unknown/legacy record types are SKIPPED with a counter — replay
    continues to the records after them (ISSUE 5 satellite; reference
    snapshot.rs:115-215 legacy Coordinate skip).  The length prefix
    makes the skip safe without understanding the payload."""
    from serf_tpu.utils import metrics

    sink = metrics.global_sink()
    base = sink.counter("serf.snapshot.unknown_record")
    path = tmp_path / "s.snap"
    recs = [
        _record(R_CLOCK, codec.encode_varint(5)),
        _record(R_ALIVE, Node("alpha", "addr-a").encode()),
        _record(42, b"future-or-legacy-payload"),   # unknown type
        _record(99),                                # unknown, empty
        _record(R_ALIVE, Node("beta", "addr-b").encode()),
        _record(R_EVENT_CLOCK, codec.encode_varint(7)),
    ]
    buf = b"".join(recs)
    path.write_bytes(buf)
    res = open_and_replay_snapshot(str(path))
    # everything AFTER the unknown records still replayed
    assert {n.id for n in res.alive_nodes} == {"alpha", "beta"}
    assert res.last_clock == 5 and res.last_event_clock == 7
    assert res.valid_length == len(buf)     # no torn tail: all complete
    assert res.unknown_records == 2
    assert sink.counter("serf.snapshot.unknown_record") == base + 2
    # the writer appends cleanly after them (no truncation of unknowns:
    # they are complete records, owned by some other build)
    snap = Snapshotter(str(path), res)
    try:
        assert path.stat().st_size == len(buf)
    finally:
        import asyncio
        asyncio.run(snap.shutdown())
