"""The shared randomized API-storm op loop.

One definition of the storm mix (leave/shutdown churn, rejoin, user
events, scatter-gather queries, tag flaps) drawn from the full public
API surface — used by the loopback soak (test_soak.py) and the
real-socket storms (test_transport_storms.py) so the two suites cannot
silently diverge.  Transport plumbing differs per caller and comes in
through the ``respawn`` / ``join_addr`` callbacks.
"""

import asyncio
import random
from typing import Callable, Dict, Set

from serf_tpu.host import QueryParam, Serf


async def run_api_storm(rng: random.Random, nodes: Dict[int, Serf],
                        killed: Set[int], ops: int,
                        respawn: Callable, join_addr: Callable) -> None:
    """Drive ``ops`` randomized API operations against the cluster.

    ``respawn(i) -> Serf``: restart node i on its OLD address (a same-id
    node on a new address is the name-conflict scenario, not a restart).
    ``join_addr(i)``: the address/name node i is joinable at.
    ``nodes``/``killed`` are mutated in place so the caller can assert on
    the final population.
    """
    from serf_tpu.types.tags import Tags

    for op in range(ops):
        live = [i for i in nodes if i not in killed]
        if not live:
            break
        actor = nodes[rng.choice(live)]
        r = rng.random()
        if r < 0.15 and len(live) > 4:
            victim = rng.choice([i for i in live if i != 0])
            if rng.random() < 0.5:
                await nodes[victim].leave()
            await nodes[victim].shutdown()
            killed.add(victim)
        elif r < 0.30 and killed:
            back = rng.choice(sorted(killed))
            killed.discard(back)
            nodes[back] = await respawn(back)
            tgt = rng.choice([i for i in nodes
                              if i not in killed and i != back])
            await nodes[back].join(join_addr(tgt))
        elif r < 0.6:
            await actor.user_event(
                f"ev-{op}", bytes([op % 256]) * rng.randint(0, 50),
                coalesce=False)
        elif r < 0.8:
            resp = await actor.query(f"q-{op}", b"",
                                     QueryParam(timeout=0.3))
            await resp.collect()
        else:
            await actor.set_tags(Tags(v=str(op)))
        if rng.random() < 0.3:
            await asyncio.sleep(0.02)
