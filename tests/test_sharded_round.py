"""The sharded flagship ``cluster_round`` (ISSUE 6 acceptance): bit-exact
vs the single-device round at small N for BOTH stamp flavors and BOTH
explicit ICI schedules; N-not-divisible-by-P and P=1 edge cases; the
sharded checkpoint round-trip; an existing named chaos plan green on the
sharded path; the roundprof ``--mesh`` smoke (≥90% byte attribution
preserved); and the sharding-spec coverage of the post-PR5 pytree.

Budget discipline: every variant is small and jitted once; the heavy
redundant parametrizations ride ``-m slow``.
"""

import functools
import json
import os
import tempfile

import jax
import jax.numpy as jnp
import pytest

from serf_tpu.models.dissemination import (
    GossipConfig,
    K_USER_EVENT,
    coverage,
    inject_fact,
)
from serf_tpu.models.failure import FailureConfig, believed_dead
from serf_tpu.models.swim import (
    ClusterConfig,
    make_cluster,
    run_cluster_sustained,
)
from serf_tpu.parallel.mesh import (
    best_device_count,
    make_mesh,
    shard_state,
    state_shardings,
)


def _cfg(n=256, pack=True, schedule="ring"):
    return ClusterConfig(
        gossip=GossipConfig(n=n, k_facts=32, peer_sampling="rotation",
                            pack_stamp=pack),
        failure=FailureConfig(suspicion_rounds=8, max_new_facts=8,
                              probe_schedule="round_robin"),
        push_pull_every=8, probe_every=2, exchange_schedule=schedule)


def _seeded(cfg):
    st = make_cluster(cfg, jax.random.key(0))
    g = inject_fact(st.gossip, cfg.gossip, subject=3, kind=K_USER_EVENT,
                    incarnation=0, ltime=5, origin=0)
    # two silent crashes so detection outcomes are part of the parity
    g = g._replace(alive=g.alive.at[jnp.asarray([7, cfg.n // 2])]
                   .set(False))
    return st._replace(gossip=g)


def _assert_cluster_equal(s8, s1, cfg):
    for name in ("known", "stamp", "alive", "tombstone", "round",
                 "incarnation", "next_slot", "overflow", "injected"):
        assert bool(jnp.all(getattr(s8.gossip, name)
                            == getattr(s1.gossip, name))), name
    # membership views / coverage trajectory / detection outcomes
    assert bool(jnp.all(coverage(s8.gossip, cfg.gossip)
                        == coverage(s1.gossip, cfg.gossip)))
    assert bool(jnp.all(
        believed_dead(s8.gossip, cfg.gossip, cfg.failure)
        == believed_dead(s1.gossip, cfg.gossip, cfg.failure)))
    assert bool(jnp.all(s8.vivaldi.vec == s1.vivaldi.vec))


def _ref_cluster(pack, n=128, rounds=16):
    """Single-device reference trajectory, memoized per stamp flavor —
    the ICI schedule cannot affect the unsharded round, so one compile
    serves both schedule variants."""
    cache = _ref_cluster.__dict__.setdefault("cache", {})
    if pack not in cache:
        cfg = _cfg(n=n, pack=pack)
        run_1 = jax.jit(functools.partial(run_cluster_sustained, cfg=cfg,
                                          events_per_round=2),
                        static_argnames=("num_rounds",))
        cache[pack] = run_1(_seeded(cfg), key=jax.random.key(2),
                            num_rounds=rounds)
    return cache[pack]


def _run_sharded(cfg, mesh, rounds=16):
    divisible = cfg.n % mesh.size == 0
    run_m = jax.jit(functools.partial(run_cluster_sustained, cfg=cfg,
                                      events_per_round=2, mesh=mesh),
                    static_argnames=("num_rounds",),
                    out_shardings=state_shardings(_seeded(cfg), mesh)
                    if divisible else None)
    st = _seeded(cfg)
    st_m = shard_state(st, mesh) if divisible else st
    return run_m(st_m, key=jax.random.key(2), num_rounds=rounds)


# tier-1 covers the flavor axis at CLUSTER level (both stamp flavors —
# the acceptance bar) on the flagship ring schedule; the allgather
# crosses are redundant at this level (both schedules are pinned
# bit-exact at round level in tests/test_ring.py, and the cluster path
# only threads the schedule string through) and ride -m slow.  The
# unsharded reference is compiled once per flavor (schedule-
# independent).
@pytest.mark.parametrize("pack,schedule", [
    (True, "ring"),
    (False, "ring"),
    pytest.param(True, "allgather", marks=pytest.mark.slow),
    pytest.param(False, "allgather", marks=pytest.mark.slow),
])
def test_sharded_cluster_round_bit_exact(vmesh8, pack, schedule):
    """Sharded (8 virtual devices) vs single-device cluster_round under
    sustained load: identical membership views, coverage trajectories,
    and detection outcomes — for both stamp flavors and both explicit
    ICI schedules."""
    cfg = _cfg(n=128, pack=pack, schedule=schedule)
    s8 = _run_sharded(cfg, vmesh8)
    _assert_cluster_equal(s8, _ref_cluster(pack), cfg)


@pytest.mark.slow
def test_sharded_cluster_round_indivisible_n(vmesh8):
    """n=100 on an 8-device mesh: the exchange falls back (GSPMD
    lowering) and the FULL round stays bit-exact — no crash, no drift.
    Redundant at cluster level (the fallback decision + parity + flight
    event are pinned at round level in tests/test_ring.py, which is the
    code that makes the choice), so it rides -m slow; the P=1 degenerate
    mesh is likewise pinned at round level."""
    cfg = _cfg(n=100)
    s8 = _run_sharded(cfg, vmesh8, rounds=10)
    run_1 = jax.jit(functools.partial(run_cluster_sustained, cfg=cfg,
                                      events_per_round=2),
                    static_argnames=("num_rounds",))
    s1 = run_1(_seeded(cfg), key=jax.random.key(2), num_rounds=10)
    _assert_cluster_equal(s8, s1, cfg)


def test_checkpoint_sharded_round_trip(vmesh8):
    """Gather on save, re-shard on load: a sharded state round-trips
    bit-exactly and comes back with the node sharding applied.  The
    state checkpointed is the (already advanced, already sharded)
    bit-exactness reference — no extra scan compile."""
    from serf_tpu.models import checkpoint

    cfg = _cfg(n=128)
    st = shard_state(_ref_cluster(True), vmesh8)
    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "shard.npz")
        checkpoint.save(p, st)
        back = checkpoint.restore(p, make_cluster(cfg, jax.random.key(0)),
                                  mesh=vmesh8)
        for a, b in zip(jax.tree_util.tree_leaves(back),
                        jax.tree_util.tree_leaves(st)):
            assert bool(jnp.all(a == b))
        # the restored state is actually node-sharded on the mesh
        assert back.gossip.known.sharding.spec[0] == "nodes"

        # device-count mismatch fails CLOSED with a clear error (128 is
        # not divisible by 6), never an XLA shape crash
        with pytest.raises(ValueError, match="device-count mismatch"):
            checkpoint.restore(p, make_cluster(cfg, jax.random.key(0)),
                               mesh=make_mesh(6))


def test_device_chaos_plan_green_on_sharded_path(vmesh8):
    """An existing named FaultPlan runs on the sharded flagship round
    with every invariant green (ISSUE 6 acceptance; tools/chaos.py
    --plane device reaches the same path via --devices)."""
    from serf_tpu.faults.device import run_device_plan
    from serf_tpu.faults.plan import named_plan

    cfg = ClusterConfig(
        gossip=GossipConfig(n=64, k_facts=32, peer_sampling="rotation"),
        failure=FailureConfig(suspicion_rounds=8, max_new_facts=8,
                              probe_schedule="round_robin"),
        push_pull_every=8)
    result = run_device_plan(named_plan("self-check"), cfg, mesh=vmesh8)
    assert result.report.ok, result.report.format()


def test_roundprof_mesh_smoke(vmesh8, capsys):
    """tools/roundprof.py --mesh: the sharded per-phase profile honors
    the JSON contract, labels the mesh, and keeps the ≥90% byte
    attribution self-check on the sharded path.  n=64/warm=1 keeps the
    nine shard_map phase compiles inside the tier-1 budget (ISSUE 15
    audit: the n=256/warm=2 build was a 19s test — promoted to -m slow
    below, same assertions)."""
    _roundprof_mesh_check(capsys, n="64", warm="1")


@pytest.mark.slow
def test_roundprof_mesh_smoke_full_n(vmesh8, capsys):
    """The original n=256/warm=2 sharded-profile build (redundant with
    the fast tier-1 variant above — same contract, same bar)."""
    _roundprof_mesh_check(capsys, n="256", warm="2")


def _roundprof_mesh_check(capsys, n: str, warm: str) -> None:
    import tools.roundprof as roundprof

    rc = roundprof.main(["--n", n, "--calls", "1", "--warm", warm,
                         "--mesh", "8", "--schedule", "ring", "--json"])
    assert rc == 0
    prof = json.loads(capsys.readouterr().out)
    assert prof["devices"] == 8 and prof["schedule"] == "ring"
    assert [r["phase"] for r in prof["phases"]] == [
        "inject", "selection", "exchange", "merge", "probe", "refute",
        "declare", "push_pull", "vivaldi"]
    frac = prof["attributed_bytes_frac"]
    assert frac is not None and frac >= 0.9, (
        f"sharded profile attributes only {frac} of the round's bytes")


def test_state_shardings_cover_post_pr5_pytree(vmesh8):
    """The sharding specs must cover the FULL GossipState: K-sized ring
    planes (slot_round) and scalars (overflow ledger) replicated,
    per-node planes node-sharded, and the chaos-mask schedule's [P, N]
    planes sharded on their second axis."""
    from serf_tpu.faults.device import lower_plan
    from serf_tpu.faults.plan import named_plan

    cfg = _cfg(n=128)
    st = _seeded(cfg)
    sh = state_shardings(st, vmesh8)
    assert sh.gossip.slot_round.spec == jax.sharding.PartitionSpec()
    assert sh.gossip.overflow.spec == jax.sharding.PartitionSpec()
    assert sh.gossip.known.spec[0] == "nodes"
    assert sh.gossip.stamp.spec[0] == "nodes"
    assert sh.positions.spec[0] == "nodes"
    assert sh.group.spec[0] == "nodes"

    sched = lower_plan(named_plan("self-check"), n=128)
    ssh = state_shardings(sched, vmesh8)
    assert ssh.group.spec == jax.sharding.PartitionSpec(None, "nodes")
    assert ssh.down.spec == jax.sharding.PartitionSpec(None, "nodes")
    assert ssh.drop.spec == jax.sharding.PartitionSpec()


def test_best_device_count():
    assert best_device_count(1_000_000, 8) == 8
    assert best_device_count(100, 8) == 5
    assert best_device_count(97, 8) == 1      # prime: unsharded
    assert best_device_count(8, 16) == 8
