"""Continuous-telemetry plane (ISSUE 10): ring time series + sampler.

- TimeSeries: the ring NEVER exceeds capacity whatever is thrown at it;
  power-of-two downsampling preserves delta sums / gauge levels;
  timestamps stay monotonic (a regressing clock is clamped, counted);
  JSON serde round-trips and rejects malformed payloads.
- SeriesStore: named rings, tails, serde.
- MetricsSampler: counter DELTAS per tick (not cumulative levels),
  gauge levels, flight-kind rates through the ``dump(since_seq=)``
  cursor — correct even after the flight ring evicted the overlap —
  and sink-reset safety.

Pure host-side python — no JAX in this file.
"""

import asyncio
import math

import pytest

from serf_tpu.obs.flight import FlightRecorder
from serf_tpu.obs.timeseries import (
    MetricsSampler,
    SeriesStore,
    TimeSeries,
    sparkline,
)
from serf_tpu.utils.metrics import MetricsSink


# ---------------------------------------------------------------------------
# TimeSeries ring
# ---------------------------------------------------------------------------


def test_ring_never_exceeds_capacity():
    ts = TimeSeries("x", kind="gauge", capacity=16)
    for i in range(10_000):
        ts.append(float(i), float(i))
        assert len(ts) < 16          # downsample fires AT capacity
    assert ts.appended == 10_000
    assert ts.downsamples >= 1
    # stride is a power of two and covers the history
    assert ts.stride & (ts.stride - 1) == 0
    assert ts.stride * 16 >= 10_000 / 2


def test_delta_downsample_preserves_sum():
    ts = TimeSeries("x", kind="delta", capacity=16)
    n = 1000
    for i in range(n):
        ts.append(float(i), 1.0)
    committed = (n // ts.stride) * ts.stride
    assert sum(ts.values()) == pytest.approx(committed)


def test_gauge_downsample_preserves_level():
    ts = TimeSeries("x", kind="gauge", capacity=16)
    for i in range(500):
        ts.append(float(i), 7.5)
    assert all(v == pytest.approx(7.5) for v in ts.values())


def test_timestamps_monotonic_with_clamping():
    ts = TimeSeries("x", capacity=16)
    ts.append(5.0, 1.0)
    ts.append(3.0, 2.0)               # clock regressed
    ts.append(6.0, 3.0)
    t = [p[0] for p in ts.points()]
    assert t == sorted(t)
    assert ts.clamped == 1


def test_window_aggregates_by_kind():
    g = TimeSeries("g", kind="gauge", capacity=16)
    d = TimeSeries("d", kind="delta", capacity=16)
    for i in range(4):
        g.append(float(i), float(i))
        d.append(float(i), 2.0)
    assert g.window(2) == pytest.approx(2.5)    # mean of 2, 3
    assert d.window(2) == pytest.approx(4.0)    # sum of 2 + 2


def test_serde_round_trip():
    ts = TimeSeries("serf.events", kind="delta", capacity=32)
    for i in range(100):
        ts.append(float(i), float(i % 5))
    back = TimeSeries.from_json(ts.to_json())
    assert back.to_dict() == ts.to_dict()
    assert back.name == "serf.events" and back.kind == "delta"


@pytest.mark.parametrize("mutation", [
    {"t": [1.0, 0.5], "v": [1.0, 2.0]},           # non-monotonic
    {"t": [1.0], "v": [1.0, 2.0]},                # length mismatch
    {"t": [float(i) for i in range(99)],
     "v": [0.0] * 99, "capacity": 8},             # over capacity
])
def test_serde_rejects_malformed(mutation):
    d = TimeSeries("x", capacity=8).to_dict()
    d.update(mutation)
    with pytest.raises(ValueError):
        TimeSeries.from_dict(d)


def test_constructor_validation():
    with pytest.raises(ValueError):
        TimeSeries("x", kind="nope")
    with pytest.raises(ValueError):
        TimeSeries("x", capacity=12)              # not a power of two
    with pytest.raises(ValueError):
        TimeSeries("x", capacity=4)               # too small


# ---------------------------------------------------------------------------
# SeriesStore
# ---------------------------------------------------------------------------


def test_store_get_or_create_and_tail():
    st = SeriesStore(capacity=16)
    st.append("a", 1.0, 10.0, kind="delta")
    st.append("a", 2.0, 20.0)
    st.append("b", 1.0, 5.0, kind="gauge")
    assert st.names() == ["a", "b"]
    assert st.get("a").kind == "delta"            # kind set at creation
    tail = st.tail(last=1)
    assert tail["a"] == [(2.0, 20.0)]
    back = SeriesStore.from_dict(st.to_dict())
    assert back.to_dict() == st.to_dict()


def test_sparkline_shapes():
    assert sparkline([]) == ""
    assert len(sparkline([1, 2, 3], width=16)) == 3
    assert sparkline([5.0] * 4) == "▁▁▁▁"         # flat = floor blocks
    s = sparkline(list(range(32)), width=8)
    assert len(s) == 8 and s[-1] == "█"
    assert sparkline([0.0, math.inf]) == "▁▁"     # non-finite safe


# ---------------------------------------------------------------------------
# MetricsSampler
# ---------------------------------------------------------------------------


def _sampler():
    sink = MetricsSink()
    rec = FlightRecorder(capacity=8)
    clock = iter(float(i) for i in range(1000))
    return sink, rec, MetricsSampler(sink=sink, recorder=rec,
                                     clock=lambda: next(clock))


def test_sampler_counter_deltas_and_gauge_levels():
    sink, _rec, s = _sampler()
    sink.incr("serf.events", 3)
    sink.gauge("serf.health.score", 90)
    s.sample()
    sink.incr("serf.events", 2)
    sink.gauge("serf.health.score", 70)
    s.sample()
    ev = s.store.get("serf.events")
    assert ev.kind == "delta" and ev.values() == [3.0, 2.0]
    hs = s.store.get("serf.health.score")
    assert hs.kind == "gauge" and hs.values() == [90.0, 70.0]


def test_sampler_label_sets_aggregate():
    sink, _rec, s = _sampler()
    sink.incr("serf.queries", 1, {"name": "a"})
    sink.incr("serf.queries", 4, {"name": "b"})
    sink.gauge("serf.queue.depth", 10, {"q": "a"})
    sink.gauge("serf.queue.depth", 20, {"q": "b"})
    s.sample()
    assert s.store.get("serf.queries").values() == [5.0]      # sum
    assert s.store.get("serf.queue.depth").values() == [15.0]  # mean


def test_sampler_flight_cursor_never_double_counts():
    _sink, rec, s = _sampler()
    for _ in range(3):
        rec.record("queue-overflow")
    s.sample()
    # overflow the tiny 8-slot ring: 20 more events arrive, eviction
    # discards 12 before the tick.  The since_seq cursor counts each
    # RETAINED event exactly once (a rate floor under eviction — the
    # evicted 12 are unattributable by design), and never re-reads the
    # 3 from the first tick.
    for _ in range(20):
        rec.record("queue-overflow")
    s.sample()
    vs = s.store.get("flight.queue-overflow").values()
    assert vs == [3.0, 8.0]
    # a third tick with nothing new records nothing for the kind
    s.sample()
    assert s.store.get("flight.queue-overflow").values() == [3.0, 8.0]


def test_sampler_baselines_preexisting_counter_totals():
    """Counters accumulated BEFORE the sampler existed (a shared
    process-global sink across runs) must not land as a bogus
    first-tick rate spike — deltas mean 'since this sampler started'
    (regression: run 2's rings opened with run 1's storm totals)."""
    sink = MetricsSink()
    rec = FlightRecorder(capacity=8)
    sink.incr("serf.overload.ingress_shed", 10_000)   # a previous run
    clock = iter(float(i) for i in range(100))
    s = MetricsSampler(sink=sink, recorder=rec,
                       clock=lambda: next(clock))
    sink.incr("serf.overload.ingress_shed", 3)
    s.sample()
    assert s.store.get("serf.overload.ingress_shed").values() == [3.0]


def test_sampler_sink_reset_records_absolute_not_negative():
    sink, _rec, s = _sampler()
    sink.incr("serf.events", 10)
    s.sample()
    sink.reset()
    sink.incr("serf.events", 4)
    s.sample()
    assert s.store.get("serf.events").values() == [10.0, 4.0]


def test_sampler_self_metrics_land_in_global_sink():
    from serf_tpu.utils import metrics as gm
    base = gm.global_sink().counter("serf.ts.samples")
    sink, _rec, s = _sampler()
    sink.incr("serf.events", 1)
    s.sample()
    assert gm.global_sink().counter("serf.ts.samples") == base + 1


async def test_sampler_asyncio_task_drives_ticks():
    sink = MetricsSink()
    rec = FlightRecorder(capacity=8)
    s = MetricsSampler(sink=sink, recorder=rec, interval_s=0.02)
    sink.incr("serf.events", 1)
    s.start()
    await asyncio.sleep(0.1)
    await s.stop()                    # takes one final sample
    assert s.ticks >= 2
    assert s.store.get("serf.events") is not None
