"""Hypothesis property tests — the analog of the reference's quickcheck
``data_round_trip!`` macro over every wire type (serf-core/src/types/
tests.rs:9-40) with real shrinking, complementing the seeded fuzz harness.
"""

import string

import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="hypothesis not installed in this image")
from hypothesis import given, settings, strategies as st  # noqa: E402

from serf_tpu import codec
from serf_tpu.host import messages as sm
from serf_tpu.host.wire import (
    CHECKSUMS,
    COMPRESSIONS,
    decode_wire,
    encode_wire,
)
from serf_tpu.types.member import Node
from serf_tpu.types.messages import (
    JoinMessage,
    LeaveMessage,
    PushPullMessage,
    QueryFlag,
    QueryMessage,
    UserEventMessage,
    UserEvents,
    decode_message,
    encode_message,
)

ids = st.text(alphabet=string.ascii_letters + string.digits + "-._",
              max_size=32)
ltimes = st.integers(min_value=0, max_value=2**63 - 1)
payloads = st.binary(max_size=256)
nodes = st.builds(Node, ids, st.one_of(
    st.none(), st.integers(min_value=0, max_value=2**16 - 1),
    st.tuples(st.text(alphabet=string.ascii_lowercase, min_size=1,
                      max_size=12),
              st.integers(min_value=0, max_value=65535))))

messages = st.one_of(
    st.builds(JoinMessage, ltimes, ids),
    st.builds(LeaveMessage, ltimes, ids, st.booleans()),
    st.builds(UserEventMessage, ltimes, ids, payloads, st.booleans()),
    st.builds(QueryMessage, ltimes,
              st.integers(min_value=0, max_value=2**32 - 1), nodes,
              st.just(()), st.sampled_from(list(QueryFlag)),
              st.integers(min_value=0, max_value=5),
              st.integers(min_value=0, max_value=2**40), ids, payloads),
    st.builds(PushPullMessage, ltimes,
              st.dictionaries(ids, ltimes, max_size=4),
              st.lists(ids, max_size=3).map(tuple), ltimes,
              st.lists(st.builds(
                  UserEvents, ltimes,
                  st.lists(st.builds(UserEventMessage, ltimes, ids, payloads,
                                     st.booleans()), max_size=2).map(tuple)),
                       max_size=2).map(tuple),
              ltimes),
)


@settings(max_examples=300, deadline=None)
@given(messages)
def test_message_round_trip(msg):
    assert decode_message(encode_message(msg)) == msg


vsns = st.tuples(*([st.integers(min_value=0, max_value=255)] * 6))
incs = st.integers(min_value=0, max_value=2**32 - 1)
swim_states = st.sampled_from(list(sm.SwimState))
push_states = st.builds(sm.PushNodeState, nodes, incs, swim_states,
                        payloads, vsns)
seqs = st.integers(min_value=0, max_value=2**32 - 1)
swim_messages = st.one_of(
    st.builds(sm.Alive, incs, nodes, payloads, vsns),
    st.builds(sm.Suspect, incs, ids, ids),
    st.builds(sm.Dead, incs, ids, ids),
    st.builds(sm.PushPull, st.booleans(),
              st.lists(push_states, max_size=3).map(tuple), payloads),
    st.builds(sm.Ping, seqs, nodes, ids),
    st.builds(sm.IndirectPing, seqs, nodes, nodes),
    st.builds(sm.Ack, seqs, payloads),
    st.builds(sm.Nack, seqs),
    st.builds(sm.UserMsg, payloads),
    st.builds(sm.ErrorResp, st.text(max_size=200)),
)


@settings(max_examples=300, deadline=None)
@given(swim_messages)
def test_swim_message_round_trip(msg):
    """The memberlist wire (incl. the round-4 vsn version vectors) must
    round-trip for arbitrary field values — the quickcheck analog for
    the §2.9 layer, covering every non-compound message type."""
    assert sm.decode_swim(sm.encode_swim(msg)) == msg


@settings(max_examples=100, deadline=None)
@given(st.lists(swim_messages, min_size=1, max_size=5))
def test_swim_compound_round_trip(msgs):
    """Compound packing: N messages in one datagram decode back to the
    same sequence."""
    wire = sm.encode_compound([sm.encode_swim(m) for m in msgs])
    out = sm.decode_swim(wire)
    if not isinstance(out, list):
        out = [out]
    assert out == msgs


@settings(max_examples=200, deadline=None)
@given(st.binary(max_size=200))
def test_decode_never_escapes_decode_error(buf):
    try:
        decode_message(buf)
    except codec.DecodeError:
        pass
    try:
        sm.decode_swim(buf)
    except codec.DecodeError:
        pass


def _lz4_available() -> bool:
    from serf_tpu.codec import _native
    return _native.lz4_fns() is not None


def _snappy_available() -> bool:
    from serf_tpu.codec import _native
    return _native.snappy_fns() is not None


# resolve availability once: a skip inside a @given body would skip the
# WHOLE test and silently drop the zlib/checksum coverage with it
_COMPRESSIONS = ([None, "zlib"]
                 + (["lz4"] if _lz4_available() else [])
                 + (["snappy"] if _snappy_available() else [])
                 + (["zstd"] if "zstd" in COMPRESSIONS else [])
                 + (["brotli"] if "brotli" in COMPRESSIONS else []))


@settings(max_examples=150, deadline=None)
@given(payloads, st.sampled_from(_COMPRESSIONS),
       st.sampled_from([None, *CHECKSUMS]))
def test_wire_pipeline_round_trip(payload, compression, checksum):
    enc = encode_wire(payload, compression, checksum)
    assert decode_wire(enc, compression, checksum) == payload


@pytest.mark.skipif(not _lz4_available(), reason="native lz4 unavailable")
@settings(max_examples=200, deadline=None)
@given(st.binary(max_size=300))
def test_lz4_round_trips_arbitrary_buffers(data):
    from serf_tpu.codec import _native

    comp, decomp = _native.lz4_fns()
    assert decomp(comp(data), len(data)) == data


@pytest.mark.skipif(not _snappy_available(),
                    reason="native snappy unavailable")
@settings(max_examples=200, deadline=None)
@given(st.binary(max_size=300))
def test_snappy_round_trips_arbitrary_buffers(data):
    from serf_tpu.codec import _native

    comp, decomp = _native.snappy_fns()
    assert decomp(comp(data), len(data)) == data


def _native_available() -> bool:
    from serf_tpu.codec import _native
    return _native.load() is not None


@pytest.mark.skipif(not _native_available(), reason="native lib unavailable")
@settings(max_examples=300, deadline=None)
@given(st.binary(max_size=120), st.integers(min_value=0, max_value=2**32 - 1))
def test_native_checksums_agree_with_spec(data, seed):
    """The one native-vs-spec checksum differential (tests/test_wire.py
    keeps only the registry-dispatch assertions)."""
    from serf_tpu.codec import _native
    from serf_tpu.host.wire import murmur3_32, xxhash32

    for name, py in (("xxhash32", xxhash32), ("murmur3", murmur3_32)):
        nat = _native.checksum_fn(name)
        assert nat(data, seed) == py(data, seed)
