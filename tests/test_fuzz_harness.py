"""Standing fuzz target wired into CI (the reference keeps a libfuzzer
target over the full message union, fuzz/fuzz_targets/messages.rs:12-16).

CI runs a short time-boxed slice each session; `python fuzz/fuzz_messages.py
--seconds 60` is the longer standalone artifact.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "fuzz"))

from fuzz_messages import arbitrary_message, encode_any, run  # noqa: E402


@pytest.mark.parametrize(
    "seed", [0, pytest.param(7, marks=pytest.mark.slow)])
def test_fuzz_slice_no_contract_violations(seed):
    stats = run(seed=seed, seconds=4.0, cases=None)
    assert stats["cases"] > 500, f"fuzzer too slow: {stats['cases']} cases"
    assert stats["violations"] == 0, stats["examples"]
    assert stats["native_diffs"] == 0, stats["examples"]
    # mutation/garbage probes actually exercised the fail-closed path
    assert stats["decode_errors"] > stats["cases"]


def test_arbitrary_messages_cover_every_envelope_type():
    import random

    from serf_tpu.types.messages import decode_message

    rng = random.Random(3)
    seen = set()
    for _ in range(2000):
        m = arbitrary_message(rng)
        raw = encode_any(m)
        seen.add(raw[0])
        assert decode_message(raw) is not None
    assert seen == set(range(1, 11)), f"envelope tags not all covered: {seen}"


def test_dstream_segment_fuzz_slice():
    """CI slice of the dstream segment fuzzer (untrusted-UDP parser)."""
    from fuzz_dstream import run as run_dstream

    # fixed case budget, not a wall-clock throughput floor (a loaded CI
    # machine made the old `cases > 2000 in 3s` assertion flake)
    stats = run_dstream(seed=1, seconds=60.0, cases=2000)
    assert stats["cases"] >= 2000, f"fuzzer stopped early: {stats['cases']}"
    assert stats["violations"] == 0, stats["examples"]
