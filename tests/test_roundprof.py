"""tools/roundprof.py tier-1 self-check: the per-phase profiler runs end
to end on the CPU backend, honors its --json contract, attributes >= 90%
of the whole compiled round's bytes to named phases (the acceptance bar —
an unattributed byte blob is the round-5 "no profile exists" failure mode
recurring), and its byte numbers stay tethered to the analytic model."""

import json

import jax
import pytest

from serf_tpu.obs.profile import PHASE_NAMES, profile_round, profile_table


def _small_profile():
    # module-level cache: one profile serves every assertion below.
    # Sized for the tier-1 budget (ISSUE 15 audit: the n=2048/K=64
    # build was a 15s test): n=512/K=32 compiles the same nine phase
    # executables and holds the same >=90% attribution bar; the
    # full-size build rides -m slow below.
    if not hasattr(_small_profile, "prof"):
        from serf_tpu.models.swim import flagship_config
        _small_profile.prof = profile_round(
            flagship_config(512, k_facts=32), events_per_round=2,
            timed_calls=1, warm_rounds=6)
    return _small_profile.prof


def test_roundprof_cli_json_contract(capsys):
    import tools.roundprof as roundprof

    rc = roundprof.main(["--n", "512", "--calls", "1", "--warm", "4",
                         "--json"])
    assert rc == 0
    out = capsys.readouterr()
    prof = json.loads(out.out)
    assert prof["n"] == 512 and prof["backend"] == jax.default_backend()
    assert [r["phase"] for r in prof["phases"]] == list(PHASE_NAMES)
    for r in prof["phases"]:
        for field in ("wall_ms", "xla_bytes", "model_bytes",
                      "achieved_gbps", "roofline_frac", "wall_share",
                      "byte_share", "excess"):
            assert field in r, f"{r['phase']} missing {field}"
    assert "whole_round" in prof and "anomalous_phase" in prof
    # the human table goes to stderr (stdout stays machine-clean)
    assert "per-phase round profile" in out.err


def test_roundprof_attributes_90_percent_of_round_bytes():
    prof = _small_profile()
    frac = prof["attributed_bytes_frac"]
    assert frac is not None, "backend exposed no cost analysis"
    assert frac >= 0.9, (
        f"named phases attribute only {frac:.1%} of the compiled round's "
        f"bytes — a phase is missing from the profile:\n"
        + profile_table(prof))


@pytest.mark.slow
def test_roundprof_attributes_90_percent_full_n():
    """The original n=2048/K=64 attribution build (redundant with the
    small-N tier-1 pin above — same phases, same bar — promoted to
    -m slow by the ISSUE 15 tier-1 budget audit)."""
    from serf_tpu.models.swim import flagship_config
    prof = profile_round(flagship_config(2048, k_facts=64),
                         events_per_round=2, timed_calls=1,
                         warm_rounds=10)
    frac = prof["attributed_bytes_frac"]
    assert frac is not None and frac >= 0.9, profile_table(prof)


def test_roundprof_phase_bytes_track_model():
    """Phases the analytic model prices must show compiled bytes within
    an order of magnitude of the per-occurrence model (fusion slack) —
    the cross-check that keeps entries citing real code paths."""
    prof = _small_profile()
    for r in prof["phases"]:
        if r["model_bytes"] <= 0 or r["xla_bytes"] <= 0:
            continue  # gated-off phases (refute/declare) price at 0
        ratio = r["xla_bytes"] / r["model_bytes"]
        assert 0.1 < ratio < 30.0, (
            f"phase {r['phase']}: compiled {r['xla_bytes'] / 1e6:.2f} MB "
            f"vs model {r['model_bytes'] / 1e6:.2f} MB (x{ratio:.1f})")


def test_roundprof_anomaly_flags_low_roofline_phase():
    """The anomaly is by construction the phase with the worst
    wall-share-to-byte-share excess; sanity-pin the arithmetic."""
    prof = _small_profile()
    an = prof["anomalous_phase"]
    worst = max(prof["phases"], key=lambda r: r["excess"])
    assert an["phase"] == worst["phase"]
    assert an["excess"] == worst["excess"]


def test_roundprof_fused_attribution_and_removed_pass():
    """ISSUE 7 tier-1 smoke: the FUSED-kernel round profiles with >=90%
    byte attribution (the fusion must REMOVE plane passes, not hide
    them inside one opaque call), the profile self-identifies its
    dispatch path, and the packed stamp plane is streamed strictly
    fewer times per round than on the phased standalone-kernel path."""
    import dataclasses

    from serf_tpu.models.accounting import round_traffic
    from serf_tpu.models.swim import flagship_config

    base = flagship_config(2048, k_facts=64)
    cfg = dataclasses.replace(
        base, gossip=dataclasses.replace(base.gossip, use_pallas=True))
    prof = profile_round(cfg, events_per_round=2, timed_calls=1,
                         warm_rounds=6)
    assert prof["kernel_path"] == "fused"
    frac = prof["attributed_bytes_frac"]
    assert frac is not None and frac >= 0.9, (
        f"fused round attributes only {frac} of compiled bytes:\n"
        + profile_table(prof))
    fused_stamp = prof["full_plane_passes"]["stamp"]
    phased_stamp = round_traffic(cfg, regime="sustained",
                                 path="kernels").passes_by_plane()["stamp"]
    assert fused_stamp < phased_stamp, (
        "the fused round must stream the packed stamp plane strictly "
        f"fewer times than the phased kernels ({fused_stamp} vs "
        f"{phased_stamp})")
    # the profiled byte columns agree: the fused selection phase reads
    # word planes only (no 1-byte-per-2-facts stamp column), so its
    # model bytes must be smaller than the phased kernel selection's
    sel = next(r for r in prof["phases"] if r["phase"] == "selection")
    phased_sel = sum(
        e.nbytes for e in round_traffic(cfg, regime="sustained",
                                        path="kernels").entries
        if e.phase == "selection")
    assert sel["model_bytes"] < phased_sel


def test_roundprof_stamp_unit_ab_removed_pass_and_attribution(capsys):
    """ISSUE 18 tier-1 smoke: the ``--stamp-unit`` A/B profiles both
    flavors with >=90% byte attribution (the deferral must REMOVE the
    per-round stamp pass, not hide bytes), the deferred leg streams the
    stamp plane strictly fewer times, prices overlay passes, and the
    modeled amortized bytes drop."""
    import tools.roundprof as roundprof

    rc = roundprof.main(["--n", "512", "--k", "32", "--calls", "1",
                         "--warm", "4", "--stamp-unit", "4", "--json"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    delta = out["delta"]
    assert delta["stamp_passes_removed"] > 0
    assert out["deferred"]["full_plane_passes"]["stamp"] \
        < out["per_round"]["full_plane_passes"]["stamp"]
    assert delta["overlay_passes_added"] > 0
    assert delta["model_bytes"]["deferred"] \
        < delta["model_bytes"]["per_round"]
    for leg in ("deferred", "per_round"):
        frac = delta["attributed_bytes_frac"][leg]
        assert frac is not None and frac >= 0.9, (leg, frac)


def test_roundprof_stamp_unit_rejects_kernel_and_mesh_crosses(capsys):
    import tools.roundprof as roundprof

    assert roundprof.main(["--stamp-unit", "4", "--fused"]) == 2
    assert roundprof.main(["--stamp-unit", "4", "--mesh", "2"]) == 2
