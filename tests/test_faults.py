"""Unified chaos plane: FaultPlan on both planes + degradation hardening.

Acceptance pins (ISSUE 4):

- ONE FaultPlan (partition -> heal + 5% loss) runs on BOTH the host
  loopback cluster and the device-plane sim from the same plan object,
  with the invariant checker green on both;
- killing a peer mid-push/pull degrades gracefully: backoff +
  circuit-breaker counters fire, no unhandled task death, and the
  cluster converges after the peer restarts;
- the legacy ``LoopbackNetwork`` knobs delegate onto the unified chaos
  rule (nothing breaks);
- ``tools/chaos.py --self-check`` exits 0 (tier-1 CLI hook).
"""

import asyncio
import random
import subprocess
import sys
from pathlib import Path

import pytest

from serf_tpu.faults.plan import (
    EdgeFault,
    FaultPhase,
    FaultPlan,
    named_plan,
)

REPO = Path(__file__).resolve().parent.parent

pytestmark = pytest.mark.asyncio


# ---------------------------------------------------------------------------
# plan validation
# ---------------------------------------------------------------------------


def test_plan_validation_rejects_bad_plans():
    with pytest.raises(ValueError):  # overlapping groups
        FaultPlan("x", n=4, phases=(
            FaultPhase(partitions=((0, 1), (1, 2))),)).validate()
    with pytest.raises(ValueError):  # rate outside [0, 1]
        FaultPlan("x", n=4, phases=(FaultPhase(drop=1.5),)).validate()
    with pytest.raises(ValueError):  # node out of range
        FaultPlan("x", n=4, phases=(FaultPhase(crash=(7,),
                                               restart=(7,)),)).validate()
    with pytest.raises(ValueError):  # ends with a node still down
        FaultPlan("x", n=4, phases=(FaultPhase(crash=(1,)),)).validate()
    with pytest.raises(ValueError):  # edge out of range
        FaultPlan("x", n=4, phases=(
            FaultPhase(edges=(EdgeFault(src=0, dst=9),)),)).validate()
    named_plan("partition-heal-loss").validate()  # built-ins are valid


def test_named_plan_registry():
    from serf_tpu.faults.plan import plan_names
    assert "partition-heal-loss" in plan_names()
    with pytest.raises(KeyError):
        named_plan("no-such-plan")


# ---------------------------------------------------------------------------
# device plane: the acceptance plan, lowered into the scan
# ---------------------------------------------------------------------------


def _device_cfg(n=128, k_facts=32):
    from serf_tpu.models.dissemination import GossipConfig
    from serf_tpu.models.failure import FailureConfig
    from serf_tpu.models.swim import ClusterConfig

    return ClusterConfig(
        gossip=GossipConfig(n=n, k_facts=k_facts,
                            peer_sampling="rotation"),
        failure=FailureConfig(suspicion_rounds=8, max_new_facts=8,
                              probe_schedule="round_robin"),
        push_pull_every=8)


def test_partition_heal_loss_device_plane():
    """The acceptance FaultPlan, device flavor: the plan lowers to
    per-round group/drop/liveness masks consumed inside the jitted scan,
    and every invariant is green after the settle window."""
    from serf_tpu.faults.device import lower_plan, run_device_plan

    plan = named_plan("partition-heal-loss")
    cfg = _device_cfg()
    sched = lower_plan(plan, cfg.n)
    # the bisection lowered to two real groups + loss only in its phase
    assert int(sched.group[1].max()) == 2 and int(sched.group[0].max()) == 0
    assert float(sched.drop[1]) == pytest.approx(0.05)
    assert float(sched.drop[0]) == 0.0
    result = run_device_plan(plan, cfg)
    assert result.report.ok, result.report.format()
    assert result.rounds_run == plan.total_rounds() + plan.settle_rounds
    names = [r.name for r in result.report.results]
    assert {"membership-convergence", "no-false-dead",
            "ltime-window"} <= set(names)


@pytest.mark.slow
def test_crash_restart_device_plane():
    """Crash + restart lowered to liveness masks, end to end (heavier
    sibling of the tier-1 host crash-restart run + the direct
    tombstone-refute unit below): the restarted node's death story is
    refuted and no alive node stays believed-dead."""
    from serf_tpu.faults.device import run_device_plan

    result = run_device_plan(named_plan("crash-restart"), _device_cfg())
    assert result.report.ok, result.report.format()


def test_tombstoned_alive_subject_refutes():
    """The device model gap the crash-restart plan exposed, pinned
    directly: a tombstoned subject that is actually alive (restart after
    its death record folded durable) refutes — incarnation bump +
    K_ALIVE fact + tombstone cleared — instead of staying believed-dead
    forever with no ring fact left to accuse it."""
    import jax
    import jax.numpy as jnp

    from serf_tpu.models.dissemination import (
        GossipConfig,
        K_ALIVE,
        make_state,
    )
    from serf_tpu.models.failure import (
        FailureConfig,
        believed_dead,
        refute_round,
    )

    cfg = GossipConfig(n=64, k_facts=32)
    fcfg = FailureConfig(suspicion_rounds=8)
    g = make_state(cfg)
    g = g._replace(tombstone=g.tombstone.at[5].set(True))
    assert bool(believed_dead(g, cfg, fcfg)[5])
    g2 = refute_round(g, cfg, fcfg, jax.random.key(0))
    assert int(g2.incarnation[5]) == int(g.incarnation[5]) + 1
    assert not bool(g2.tombstone[5])
    has_alive_fact = jnp.any((g2.facts.kind == K_ALIVE) & g2.facts.valid
                             & (g2.facts.subject == 5))
    assert bool(has_alive_fact)
    assert not bool(believed_dead(g2, cfg, fcfg)[5])
    # genuinely dead subjects stay tombstoned (the gate is alive-only)
    g3 = g._replace(alive=g.alive.at[5].set(False))
    g4 = refute_round(g3, cfg, fcfg, jax.random.key(1))
    assert bool(g4.tombstone[5])


async def test_crash_restart_host_plane(tmp_path):
    """Crash + restart on the host plane (wall-clock phases, snapshots
    on): the restarted node replays its snapshot, rejoins, and the
    crash-restart-rejoin invariant — clocks not regressed across the
    restart — is green."""
    from serf_tpu.faults.host import run_host_plan

    plan = named_plan("crash-restart")
    result = await run_host_plan(plan, tmp_dir=str(tmp_path))
    assert result.report.ok, result.report.format()
    rejoin = [r for r in result.report.results
              if r.name == "crash-restart-rejoin"][0]
    assert "1 restart(s)" in rejoin.detail and "snapshots=on" in rejoin.detail


# ---------------------------------------------------------------------------
# host plane: same plan object on a loopback cluster
# ---------------------------------------------------------------------------


async def test_partition_heal_loss_host_plane(tmp_path):
    """The SAME acceptance plan object on the host plane: loopback
    cluster, partition + loss phases from the executor, snapshots on,
    invariants green (the tier-1 both-planes pin with the device test
    above)."""
    from serf_tpu.faults.host import run_host_plan

    plan = named_plan("partition-heal-loss")
    result = await run_host_plan(plan, tmp_dir=str(tmp_path))
    assert result.report.ok, result.report.format()
    assert result.events_sent > 0
    # the checker saw real clock samples from every node
    assert all(result.clock_samples[f"n{i}"] for i in range(plan.n))


async def test_dial_pushpull_kill_mid_sync_degrades_gracefully(tmp_path):
    """Acceptance: kill a peer mid-sync; dial/push-pull paths must
    degrade measurably (backoff retries + circuit breaker opening), no
    task dies unhandled, and the cluster re-converges after restart."""
    from serf_tpu.faults import invariants as inv
    from serf_tpu.host.serf import Serf, SerfState
    from serf_tpu.host.transport import LoopbackNetwork
    from serf_tpu.options import Options
    from serf_tpu.utils import metrics

    def degraded(name):
        sink = metrics.global_sink()
        return sum(v for (n, _l), v in sink.counters.items() if n == name)

    base_retry = degraded("serf.degraded.dial_retry")
    base_opened = degraded("serf.degraded.breaker_opened")

    net = LoopbackNetwork()
    opts = Options.local()
    nodes = {i: await Serf.create(net.bind(f"k{i}"), opts, f"k{i}")
             for i in range(3)}
    died = []
    loop = asyncio.get_running_loop()
    prev_handler = None

    def exc_handler(lp, ctx):
        died.append(ctx.get("exception") or ctx.get("message"))

    prev_handler = loop.get_exception_handler()
    loop.set_exception_handler(exc_handler)
    try:
        for i in (1, 2):
            await nodes[i].join("k0")
        assert await inv.wait_host_convergence(list(nodes.values()), 5.0)

        # kill node 2 abruptly (no leave) and hammer its stream plane:
        # every push/pull from 0/1 now dials a dead address
        await nodes[2].shutdown()
        for _ in range(8):
            try:
                await nodes[0].memberlist._push_pull_with("k2", join=False)
            except (ConnectionError, TimeoutError):
                pass
        assert degraded("serf.degraded.dial_retry") > base_retry
        assert degraded("serf.degraded.breaker_opened") > base_opened
        # circuit now open: the next attempt fast-fails without retries
        with pytest.raises(ConnectionError):
            await nodes[0].memberlist._dial_stream("k2")

        # restart the peer on its old address; breaker half-open trial
        # must rediscover it and the cluster must re-converge
        nodes[2] = await Serf.create(net.bind("k2"), opts, "k2")
        await asyncio.sleep(opts.memberlist.breaker_cooldown + 0.05)
        await nodes[2].join("k0")
        live = [s for s in nodes.values() if s.state == SerfState.ALIVE]
        assert await inv.wait_host_convergence(live, 8.0)
        # no unhandled task death reached the event loop
        assert not died, died
    finally:
        loop.set_exception_handler(prev_handler)
        for s in nodes.values():
            if s.state != SerfState.SHUTDOWN:
                await s.shutdown()


async def test_corrupt_frame_quarantine():
    """A garbage stream frame is quarantined (counter + flight event),
    never a task death: the server keeps serving afterwards."""
    from serf_tpu import obs
    from serf_tpu.host.serf import Serf
    from serf_tpu.host.transport import LoopbackNetwork
    from serf_tpu.options import Options
    from serf_tpu.utils import metrics

    def counter():
        sink = metrics.global_sink()
        return sum(v for (n, _l), v in sink.counters.items()
                   if n == "serf.degraded.corrupt_frame")

    base = counter()
    net = LoopbackNetwork()
    a = await Serf.create(net.bind("c0"), Options.local(), "c0")
    b = await Serf.create(net.bind("c1"), Options.local(), "c1")
    try:
        await b.join("c0")
        # hand-dial and send garbage where a push/pull frame belongs
        stream = await b.memberlist.transport.dial("c0")
        await stream.send_frame(b"\xff\xfe not a frame \x00\x01")
        await asyncio.sleep(0.1)
        await stream.close()
        assert counter() > base
        assert any(e["kind"] == "corrupt-frame"
                   for e in obs.flight_dump(kind="corrupt-frame"))
        # the server still serves real syncs (no task death)
        await b.memberlist._push_pull_with("c0", join=False)
    finally:
        await a.shutdown()
        await b.shutdown()


# ---------------------------------------------------------------------------
# legacy knobs delegate onto the unified rule
# ---------------------------------------------------------------------------


def test_legacy_knobs_delegate_to_chaos_rule():
    from serf_tpu.host.transport import ChaosRule, LoopbackNetwork

    net = LoopbackNetwork()
    net.partition({"a", "b"}, {"c"})
    assert net._legacy.groups is not None
    assert not net._blocked("a", "b") and net._blocked("a", "c")
    net.heal()
    assert not net._blocked("a", "c")
    net.set_drop_rate(1.0)
    assert net._legacy.drop == 1.0
    assert net._should_drop("a", "c", b"x")
    net.set_drop_rate(0.0)
    assert not net._should_drop("a", "c", b"x")
    # executor rule composes with (not replaces) the legacy rule
    net.partition({"a"}, {"b", "c"})
    net.apply_faults(ChaosRule(drop=1.0))
    assert net._blocked("b", "a")          # legacy partition still holds
    assert net._should_drop("b", "c", b"x")  # executor drop applies
    net.apply_faults(None)
    assert not net._should_drop("b", "c", b"x")


async def test_chaos_effects_duplicate_and_corrupt():
    """Duplicate/corrupt/delay effects actually happen on the loopback
    fabric (counter-verified; receiver sees >= 2 copies, one possibly
    bit-flipped)."""
    from serf_tpu.host.transport import ChaosRule, LoopbackNetwork
    from serf_tpu.utils import metrics

    net = LoopbackNetwork()
    t0, t1 = net.bind("x0"), net.bind("x1")
    net.apply_faults(ChaosRule(duplicate=1.0, corrupt=1.0))
    sink = metrics.global_sink()
    base_dup = sink.counter("serf.faults.duplicated")
    base_cor = sink.counter("serf.faults.corrupted")
    await t0.send_packet("x1", b"\x00" * 8)
    got = []
    for _ in range(2):
        src, buf = await asyncio.wait_for(t1.recv_packet(), 1.0)
        got.append(buf)
    assert sink.counter("serf.faults.duplicated") == base_dup + 1
    assert sink.counter("serf.faults.corrupted") == base_cor + 1
    assert len(got) == 2
    assert any(b != b"\x00" * 8 for b in got)  # the bit flip landed
    await t0.shutdown()
    await t1.shutdown()


# ---------------------------------------------------------------------------
# overload plans (ISSUE 5 acceptance): query-storm on both planes
# ---------------------------------------------------------------------------


def test_load_phase_validation_and_lowering():
    with pytest.raises(ValueError):   # negative rate
        FaultPlan("x", n=4, phases=(FaultPhase(event_rate=-1.0),)).validate()
    with pytest.raises(ValueError):   # stall out of range
        FaultPlan("x", n=4, phases=(FaultPhase(stall=(9,)),)).validate()
    plan = named_plan("query-storm")
    assert plan.has_load() and plan.offered_rate() == 800.0

    from serf_tpu.faults.device import lower_plan
    sched = lower_plan(plan, 64)
    # the storm phase lowered its offered ops to fact injections
    assert sched.events[0] == 0
    assert sched.events[1] == 960       # ceil(800/s * 1.2s)
    assert any("query load lowered" in n for n in sched.notes)


async def test_query_storm_host_plane(tmp_path):
    """THE overload acceptance run (host flavor): admission sized under
    the storm, so the run is green only if every buffer held its bound,
    shed counters are NONZERO, accounting closes, and the lossless
    contract + post-storm convergence survive."""
    from serf_tpu.faults.host import run_host_plan

    plan = named_plan("query-storm")
    result = await run_host_plan(plan, tmp_dir=str(tmp_path))
    assert result.report.ok, result.report.format()
    names = {r.name for r in result.report.results}
    assert {"bounded-buffers", "shed-accounting", "lossless-intact",
            "storm-convergence"} <= names
    load = result.load
    assert load is not None
    assert load.ingress_shed > 0                  # the storm DID shed
    assert load.ingress_admitted > 0              # but service continued
    offered = load.events_offered + load.queries_offered
    assert load.ingress_admitted + load.ingress_shed == offered
    # the shed counters reached the degradation report too
    assert result.counters.get("serf.overload.ingress_shed", 0) > 0


def test_query_storm_device_plane():
    """The same plan object, device flavor: the storm's offered load
    lowers to fact injections past ring capacity, and the overflow
    accountant (serf.overload.device_dropped) must see the burst instead
    of letting it clobber silently."""
    from serf_tpu.faults.device import run_device_plan

    result = run_device_plan(named_plan("query-storm"), _device_cfg(n=96))
    assert result.report.ok, result.report.format()
    assert "overflow-accounted" in {r.name for r in result.report.results}
    assert result.offered > 0
    assert 0 < result.dropped <= result.offered
    # the pull-based emitter exports the same ledger
    from serf_tpu.models.dissemination import emit_gossip_metrics
    vals = emit_gossip_metrics(result.state.gossip,
                               _device_cfg(n=96).gossip)
    assert vals["serf.overload.device_dropped"] == result.dropped


@pytest.mark.slow
async def test_slow_consumer_host_plane(tmp_path):
    """The slow-consumer plan: a stalled event reader under sustained
    load — bounded memory, accounted sheds, and the stalled node catches
    up after the phase (heavier sibling of the direct slow-reader units
    in test_overload.py)."""
    from serf_tpu.faults.host import run_host_plan

    result = await run_host_plan(named_plan("slow-consumer"),
                                 tmp_dir=str(tmp_path))
    assert result.report.ok, result.report.format()
    assert result.load.ingress_shed > 0


# ---------------------------------------------------------------------------
# CLI self-check (tier-1 hook)
# ---------------------------------------------------------------------------


def test_chaos_cli_self_check():
    """tools/chaos.py --self-check: the chaos-plane contract cannot
    drift — both planes run the self-check plan green, exit 0."""
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "chaos.py"),
         "--self-check", "--json"],
        capture_output=True, text=True, timeout=300,
        env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin",
             "PYTHONPATH": str(REPO),
             "XLA_FLAGS": "--xla_backend_optimization_level=0"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    import json
    out = json.loads(proc.stdout)
    assert out["ok"] is True
    assert {r["plane"] for r in out["reports"]} == {"host", "device"}


# ---------------------------------------------------------------------------
# heavy chaos soak (redundant parametrization — slow, not tier-1)
# ---------------------------------------------------------------------------


@pytest.mark.slow
async def test_flaky_edges_host_soak(tmp_path):
    """The full flaky-edges gauntlet (drop+dup+reorder+corrupt+jitter+
    asymmetric edges) on the host plane — heavier sibling of the tier-1
    partition plan."""
    from serf_tpu.faults.host import run_host_plan

    result = await run_host_plan(named_plan("flaky-edges"),
                                 tmp_dir=str(tmp_path))
    assert result.report.ok, result.report.format()


@pytest.mark.slow
def test_partition_heal_loss_device_large():
    """Scale variant of the device acceptance run (1024 nodes)."""
    from serf_tpu.faults.device import run_device_plan

    result = run_device_plan(named_plan("partition-heal-loss"),
                             _device_cfg(n=1024))
    assert result.report.ok, result.report.format()
