"""Device-plane query engine: scatter/filter/gather/relay/timeout, and
host-vs-device parity (the SURVEY.md §7 stage-7 component).

The host Serf query engine is the oracle: for the same membership, filters,
and loss-free network, the device plane must deliver responses from exactly
the same responder set; the conflict majority vote must reproduce the host
engine's ``responses//2 + 1`` arithmetic.
"""

import functools

import jax
import jax.numpy as jnp
import pytest

from serf_tpu.models.dissemination import (
    GossipConfig,
    K_QUERY,
    K_USER_EVENT,
    inject_fact,
    make_state,
    round_step,
)
from serf_tpu.models.query import (
    QueryConfig,
    default_timeout_rounds,
    id_filter_mask,
    launch_query,
    majority_holds,
    majority_vote,
    make_queries,
    no_filter_mask,
    num_acks,
    num_responses,
    query_round,
    tag_filter_mask,
)


def _drive(gossip, qstate, cfg, qcfg, key, rounds, **kw):
    step = jax.jit(functools.partial(round_step, cfg=cfg))
    for _ in range(rounds):
        key, k1, k2 = jax.random.split(key, 3)
        gossip = step(gossip, key=k1)
        qstate = query_round(gossip, qstate, cfg, qcfg, k2, **kw)
    return gossip, qstate


def test_query_gathers_all_alive_responses():
    cfg = GossipConfig(n=256, k_facts=32)
    qcfg = QueryConfig(q_slots=4)
    g = make_state(cfg)
    qs = make_queries(cfg, qcfg)
    g, qs, qi = launch_query(g, qs, cfg, qcfg, origin=0,
                             eligible=no_filter_mask(cfg.n))
    g, qs = _drive(g, qs, cfg, qcfg, jax.random.key(0), 30)
    assert int(num_responses(qs)[int(qi)]) == cfg.n
    assert int(num_acks(qs)[int(qi)]) == cfg.n
    # responses carry the per-node payload (default: node index)
    assert bool(jnp.all(qs.resp_value[int(qi)] == jnp.arange(cfg.n)))


def test_id_filter_limits_responders():
    cfg = GossipConfig(n=128, k_facts=32)
    qcfg = QueryConfig(q_slots=4)
    g = make_state(cfg)
    qs = make_queries(cfg, qcfg)
    ids = [3, 17, 99]
    g, qs, qi = launch_query(g, qs, cfg, qcfg, origin=0,
                             eligible=id_filter_mask(cfg.n, ids))
    g, qs = _drive(g, qs, cfg, qcfg, jax.random.key(1), 30)
    got = set(int(i) for i in jnp.nonzero(qs.responded[int(qi)])[0])
    assert got == set(ids)


def test_tag_filter_limits_responders():
    cfg = GossipConfig(n=64, k_facts=32)
    qcfg = QueryConfig(q_slots=4)
    # tag plane: tag 0 = role (0=web, 1=db)
    tag_plane = jnp.zeros((cfg.n, 2), jnp.int32).at[10:20, 0].set(1)
    g = make_state(cfg)
    qs = make_queries(cfg, qcfg)
    g, qs, qi = launch_query(g, qs, cfg, qcfg, origin=0,
                             eligible=tag_filter_mask(tag_plane, 0, 1))
    g, qs = _drive(g, qs, cfg, qcfg, jax.random.key(2), 30)
    got = set(int(i) for i in jnp.nonzero(qs.responded[int(qi)])[0])
    assert got == set(range(10, 20))


def test_dead_nodes_do_not_respond_and_dead_origin_gets_nothing():
    cfg = GossipConfig(n=64, k_facts=32)
    qcfg = QueryConfig(q_slots=2)
    g = make_state(cfg)._replace(
        alive=jnp.ones((64,), bool).at[7].set(False))
    qs = make_queries(cfg, qcfg)
    g, qs, qi = launch_query(g, qs, cfg, qcfg, origin=0,
                             eligible=no_filter_mask(cfg.n))
    g, qs = _drive(g, qs, cfg, qcfg, jax.random.key(3), 30)
    assert not bool(qs.responded[int(qi), 7])
    assert int(num_responses(qs)[int(qi)]) == cfg.n - 1

    # dead origin: no deliveries at all
    g2 = make_state(cfg)._replace(
        alive=jnp.ones((64,), bool).at[0].set(False))
    qs2 = make_queries(cfg, qcfg)
    g2, qs2, qi2 = launch_query(g2, qs2, cfg, qcfg, origin=0,
                                eligible=no_filter_mask(cfg.n))
    g2, qs2 = _drive(g2, qs2, cfg, qcfg, jax.random.key(4), 20)
    assert int(num_responses(qs2)[int(qi2)]) == 0


def test_timeout_closes_query():
    cfg = GossipConfig(n=256, k_facts=32)
    qcfg = QueryConfig(q_slots=2)
    g = make_state(cfg)
    qs = make_queries(cfg, qcfg)
    # a 2-round deadline: dissemination cannot finish, late learners are
    # shut out (reference: responses after the deadline are dropped)
    g, qs, qi = launch_query(g, qs, cfg, qcfg, origin=0,
                             eligible=no_filter_mask(cfg.n),
                             timeout_rounds=2)
    g, qs = _drive(g, qs, cfg, qcfg, jax.random.key(5), 30)
    assert 0 < int(num_responses(qs)[int(qi)]) < cfg.n


def test_direct_drops_lose_responses_relay_recovers_them():
    cfg = GossipConfig(n=128, k_facts=32)
    g0 = make_state(cfg)

    # all direct sends dropped, no relay: origin only ever hears itself
    # (self-delivery is local, but the drop mask covers it too — so zero)
    qcfg = QueryConfig(q_slots=2, relay_factor=0)
    qs = make_queries(cfg, qcfg)
    g, qs, qi = launch_query(g0, qs, cfg, qcfg, origin=0,
                             eligible=no_filter_mask(cfg.n))
    drop = jnp.ones((qcfg.q_slots, cfg.n), bool)
    g, qs = _drive(g, qs, cfg, qcfg, jax.random.key(6), 25,
                   drop_direct=drop)
    assert int(num_responses(qs)[int(qi)]) == 0

    # same loss, relay_factor=3: relayed copies deliver everything
    qcfg_r = QueryConfig(q_slots=2, relay_factor=3)
    qs2 = make_queries(cfg, qcfg_r)
    g2, qs2, qi2 = launch_query(g0, qs2, cfg, qcfg_r, origin=0,
                                eligible=no_filter_mask(cfg.n))
    g2, qs2 = _drive(g2, qs2, cfg, qcfg_r, jax.random.key(7), 25,
                     drop_direct=drop)
    assert int(num_responses(qs2)[int(qi2)]) == cfg.n


def test_attempt_is_once_lost_stays_lost_without_relay():
    """A responder sends exactly once; if that send is dropped the response
    never arrives (reference: no retry), even when the drop mask later
    clears."""
    cfg = GossipConfig(n=64, k_facts=32)
    qcfg = QueryConfig(q_slots=2, relay_factor=0)
    g = make_state(cfg)
    qs = make_queries(cfg, qcfg)
    g, qs, qi = launch_query(g, qs, cfg, qcfg, origin=0,
                             eligible=no_filter_mask(cfg.n))
    drop = jnp.ones((qcfg.q_slots, cfg.n), bool)
    # first 30 rounds: everything drops (all nodes learn + attempt)
    g, qs = _drive(g, qs, cfg, qcfg, jax.random.key(8), 30, drop_direct=drop)
    lost = int(jnp.sum(qs.attempted[int(qi)]))
    assert lost == cfg.n
    # drops clear, but attempts are spent
    g, qs = _drive(g, qs, cfg, qcfg, jax.random.key(9), 10)
    assert int(num_responses(qs)[int(qi)]) == 0


def test_ring_overwrite_closes_query():
    cfg = GossipConfig(n=64, k_facts=32)
    qcfg = QueryConfig(q_slots=2)
    g = make_state(cfg)
    qs = make_queries(cfg, qcfg)
    g, qs, qi = launch_query(g, qs, cfg, qcfg, origin=0,
                             eligible=no_filter_mask(cfg.n))
    # overwrite the whole gossip ring with user events before any gather
    for i in range(cfg.k_facts):
        g = inject_fact(g, cfg, 100 + i, K_USER_EVENT, 0, 2 + i, 0)
    g, qs = _drive(g, qs, cfg, qcfg, jax.random.key(10), 20)
    assert int(num_responses(qs)[int(qi)]) == 0


def test_no_ack_when_not_requested():
    cfg = GossipConfig(n=64, k_facts=32)
    qcfg = QueryConfig(q_slots=2)
    g = make_state(cfg)
    qs = make_queries(cfg, qcfg)
    g, qs, qi = launch_query(g, qs, cfg, qcfg, origin=0,
                             eligible=no_filter_mask(cfg.n), want_ack=False)
    g, qs = _drive(g, qs, cfg, qcfg, jax.random.key(11), 30)
    assert int(num_acks(qs)[int(qi)]) == 0
    assert int(num_responses(qs)[int(qi)]) == cfg.n


def test_majority_vote_segment_sum():
    n = 101
    votes = jnp.asarray([0] * 60 + [1] * 41, jnp.int32)
    responded = jnp.ones((n,), bool)
    w, c, t = majority_vote(votes, responded, num_candidates=4)
    assert (int(w), int(c), int(t)) == (0, 60, 101)
    assert bool(majority_holds(c, t))
    # only the minority responds: no majority for 0
    responded = jnp.asarray([False] * 45 + [True] * 56)
    w, c, t = majority_vote(votes, responded, num_candidates=4)
    assert (int(w), int(c), int(t)) == (1, 41, 56)
    assert not bool(majority_holds(jnp.int32(15), jnp.int32(56)))
    # host arithmetic parity: majority = responses // 2 + 1
    for total, count in [(5, 3), (5, 2), (4, 2), (4, 3), (1, 1), (0, 0)]:
        host_ok = total > 0 and count >= total // 2 + 1
        assert bool(majority_holds(jnp.int32(count), jnp.int32(total))) == host_ok


@pytest.mark.asyncio
async def test_host_vs_device_query_parity():
    """Same membership + id filter, loss-free: the device responder set must
    equal the host engine's (style of tests/test_parity.py)."""
    from serf_tpu.host import LoopbackNetwork, QueryParam, Serf
    from serf_tpu.host.events import EventSubscriber, QueryEvent
    from serf_tpu.options import Options
    from serf_tpu.types.filters import IdFilter
    from serf_tpu.types.member import MemberStatus

    import asyncio

    n = 5
    filter_ids = [1, 3, 4]

    # -- host oracle
    net = LoopbackNetwork()
    subs = [EventSubscriber() for _ in range(n)]
    nodes = [await Serf.create(net.bind(f"a{i}"), Options.local(), f"n{i}",
                               subscriber=subs[i]) for i in range(n)]
    try:
        for s in nodes[1:]:
            await s.join("a0")
        for _ in range(400):
            if all(len([m for m in s.members()
                        if m.status == MemberStatus.ALIVE]) == n
                   for s in nodes):
                break
            await asyncio.sleep(0.02)

        async def responder(i):
            while True:
                ev = await subs[i].next()
                if isinstance(ev, QueryEvent) and ev.name == "who":
                    await ev.respond(f"n{i}".encode())
        tasks = [asyncio.create_task(responder(i)) for i in range(1, n)]
        resp = await nodes[0].query(
            "who", b"", QueryParam(
                timeout=1.5,
                filters=(IdFilter(tuple(f"n{i}" for i in filter_ids)),)))
        results = await resp.collect()
        host_responders = {r.from_id for r in results}
        for t in tasks:
            t.cancel()
    finally:
        for s in nodes:
            await s.shutdown()

    # -- device plane, same scenario (origin 0 not in the filter list)
    cfg = GossipConfig(n=n, k_facts=32, fanout=2)
    qcfg = QueryConfig(q_slots=2)
    g = make_state(cfg)
    qs = make_queries(cfg, qcfg)
    g, qs, qi = launch_query(g, qs, cfg, qcfg, origin=0,
                             eligible=id_filter_mask(n, filter_ids))
    g, qs = _drive(g, qs, cfg, qcfg, jax.random.key(12), 30)
    device_responders = {f"n{int(i)}"
                         for i in jnp.nonzero(qs.responded[int(qi)])[0]}

    assert device_responders == host_responders == \
        {f"n{i}" for i in filter_ids}
