"""Deterministic record/replay plane (ISSUE 9) — acceptance + units.

Acceptance pins:

- the seeded ``partition-heal-loss`` chaos plan, recorded and replayed
  on BOTH planes, yields identical membership-view digests every round
  (device: every protocol round, bit-exact; host: every convergence
  barrier, virtualized timing);
- a deliberately perturbed replay (one flipped recorded event) makes
  ``tools/replay.py diff`` exit nonzero and name the correct FIRST
  DIVERGENT ROUND plus the per-node view delta at that round;
- ``tools/chaos.py --record-on-fail`` writes the repro artifact exactly
  when an invariant fails (green runs keep nothing);
- the recording format is versioned and fails closed on mismatch /
  truncation, and its version is schema-pinned (serflint
  ``schema-recording-drift``).

Budget: the device record+replay pair is a module fixture (one compile,
small N); the heavy flavor/shard soak is ``@slow``.
"""

import copy
import importlib.util
import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

pytestmark = pytest.mark.asyncio


def _device_cfg(n=48, k_facts=32, **gossip_kw):
    from serf_tpu.replay.selfcheck import default_replay_cfg

    return default_replay_cfg(n, k_facts, **gossip_kw)


def _record_device(cfg, plan_name="partition-heal-loss", mesh=None):
    from serf_tpu.faults.device import run_device_plan
    from serf_tpu.faults.plan import named_plan
    from serf_tpu.replay.recording import RunRecorder

    recorder = RunRecorder()
    result = run_device_plan(named_plan(plan_name), cfg, mesh=mesh,
                             recorder=recorder)
    return result, recorder.to_recording()


@pytest.fixture(scope="module")
def device_artifacts():
    """One recorded + one replayed partition-heal-loss device run,
    shared by the acceptance/perturbation/CLI tests below."""
    from serf_tpu.replay.replayer import replay_device

    result, recording = _record_device(_device_cfg())
    replayed = replay_device(recording).to_recording()
    return {"result": result, "recording": recording,
            "replayed": replayed}


# ---------------------------------------------------------------------------
# acceptance: bit-exact record -> replay on both planes
# ---------------------------------------------------------------------------


def test_device_record_replay_bit_exact(device_artifacts):
    """THE device acceptance pin: every protocol round's membership-view
    digest from the replay matches the recording exactly."""
    from serf_tpu.faults.plan import named_plan
    from serf_tpu.replay.differ import diff_recordings

    result = device_artifacts["result"]
    rec = device_artifacts["recording"]
    assert result.report.ok, result.report.format()

    plan = named_plan("partition-heal-loss")
    views = rec.views()
    assert len(views) == plan.total_rounds() + plan.settle_rounds
    assert [v["round"] for v in views] == list(range(1, len(views) + 1))
    assert all(v["digest"] and len(v["nodes"]) == 48 for v in views)

    d = diff_recordings(rec, device_artifacts["replayed"])
    assert d.ok, d.format()
    assert d.compared_views == len(views)
    assert d.first_divergent_round is None


async def test_host_record_replay_bit_exact(tmp_path):
    """THE host acceptance pin: partition-heal-loss recorded on a live
    loopback cluster, then re-driven from the recording with virtualized
    timing — every barrier's membership-view digest matches."""
    from serf_tpu.faults.host import run_host_plan
    from serf_tpu.faults.plan import named_plan
    from serf_tpu.replay.differ import diff_recordings
    from serf_tpu.replay.recording import RunRecorder
    from serf_tpu.replay.replayer import replay_host

    plan = named_plan("partition-heal-loss", 4)
    (tmp_path / "rec").mkdir()
    (tmp_path / "rep").mkdir()
    recorder = RunRecorder()
    result = await run_host_plan(plan, tmp_dir=str(tmp_path / "rec"),
                                 recorder=recorder)
    assert result.report.ok, result.report.format()
    rec = recorder.to_recording()
    ops = {s["op"] for s in rec.steps()}
    # the recording captured the whole ingress surface: joins, phases,
    # tapped user events (background traffic), heal, both barriers
    assert {"join", "phase", "user-event", "heal", "barrier"} <= ops
    assert len(rec.views()) == 2          # quiet + settle barriers

    replayed = (await replay_host(
        rec, tmp_dir=str(tmp_path / "rep"))).to_recording()
    d = diff_recordings(rec, replayed)
    assert d.ok, d.format()
    assert d.compared_views == 2
    # per-barrier digests carry the per-node 12-hex view digests
    for v in rec.views():
        assert set(v["nodes"]) == {f"n{i}" for i in range(4)}


# ---------------------------------------------------------------------------
# acceptance: perturbed replay -> nonzero diff at the right round
# ---------------------------------------------------------------------------


def _perturb_phase1_inject(recording):
    """Flip one recorded event: the first inject feeding phase 1 (the
    second scan) gets its first origin shifted by one node."""
    pert = type(recording)(copy.deepcopy(recording.header),
                           copy.deepcopy(recording.records))
    scans_seen = 0
    for r in pert.records:
        if r["kind"] != "step":
            continue
        if r["op"] == "scan":
            scans_seen += 1
        if r["op"] == "inject" and scans_seen == 1:
            r["args"]["origins"][0] = (r["args"]["origins"][0] + 1) % 48
            return pert, r["seq"]
    raise AssertionError("no phase-1 inject step found")


def test_perturbed_replay_diverges_at_correct_round(device_artifacts,
                                                    tmp_path):
    """One flipped event -> the differ names the flipped STEP and the
    first divergent ROUND (phase 1 starts at round 13: phase 0 ran 12),
    with the per-node view delta; the CLI exits nonzero on it."""
    from serf_tpu.replay.differ import diff_recordings
    from serf_tpu.replay.replayer import replay_device

    rec = device_artifacts["recording"]
    pert, pert_seq = _perturb_phase1_inject(rec)
    replayed = replay_device(pert).to_recording()
    d = diff_recordings(rec, replayed)
    assert not d.ok
    assert d.first_divergent_step["seq"] == pert_seq
    assert d.first_divergent_round == 13, d.format()
    assert d.node_delta            # the differ shows WHICH views moved

    # CLI contract: diff exits nonzero and reports the same round
    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    rec.save(str(a))
    replayed.save(str(b))
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "replay.py"),
         "diff", str(a), str(b), "--json"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    out = json.loads(proc.stdout)
    assert out["first_divergent_round"] == 13
    assert out["node_delta"]

    # identical inputs exit 0
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "replay.py"),
         "diff", str(a), str(a)],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# chaos integration: --record-on-fail
# ---------------------------------------------------------------------------


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        f"_tool_{name}", REPO / "tools" / f"{name}.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_chaos_record_on_fail_writes_artifact_only_when_red(
        tmp_path, monkeypatch):
    """A red run writes the repro recording and names it; a green run
    keeps nothing (the recorder stays in-memory)."""
    from serf_tpu.faults.invariants import InvariantReport
    from serf_tpu.replay.recording import Recording, plan_to_dict

    chaos = _load_tool("chaos")

    def fake_run_host(plan, recorder=None, ok=False, controlled=False):
        rep = InvariantReport(plane="host", plan=plan.name)
        rep.add("membership-convergence", ok, "stubbed")
        if recorder is not None:
            recorder.header(plane="host", plan=plan_to_dict(plan),
                            seed=plan.seed, config={"options": "default",
                                                    "snapshots": True,
                                                    "n": plan.n})
            recorder.step("join", node=1, target="n0")

        class R:
            pass

        r = R()
        r.report = rep
        r.load = None
        return r

    argv = ["chaos.py", "--plan", "self-check", "--plane", "host",
            "--record-on-fail", "--record-dir", str(tmp_path)]
    monkeypatch.setattr(chaos, "run_host", fake_run_host)
    monkeypatch.setattr(sys, "argv", argv)
    assert chaos.main() == 1
    artifact = tmp_path / "chaos-self-check-host.replay.jsonl"
    assert artifact.exists()
    rec = Recording.load(artifact)
    assert rec.plane == "host" and rec.header["plan"]["name"] == "self-check"

    # green run: same wiring, ok report -> nothing written
    artifact.unlink()
    monkeypatch.setattr(chaos, "run_host",
                        lambda plan, recorder=None, controlled=False:
                        fake_run_host(plan, recorder, ok=True))
    assert chaos.main() == 0
    assert not artifact.exists()


# ---------------------------------------------------------------------------
# format / serde / differ units
# ---------------------------------------------------------------------------


def test_recording_format_versioned_and_truncation_fail_closed(tmp_path):
    from serf_tpu.replay.recording import (
        Recording,
        RecordingError,
        RunRecorder,
        recording_schema_version,
    )

    r = RunRecorder()
    r.header(plane="device", plan={"name": "x", "n": 2, "phases": []},
             seed=3, config={"n": 2})
    r.step("init", key="00")
    r.view(round_=1, digest="aabbccdd", nodes=["aa", "bb"])
    p = tmp_path / "r.jsonl"
    r.save(str(p))

    rec = Recording.load(p)
    assert rec.header["v"] == recording_schema_version() == 1
    assert len(rec.views()) == 1 and len(list(rec.steps())) == 1

    # version mismatch fails closed
    lines = p.read_text().splitlines()
    hdr = json.loads(lines[0])
    hdr["v"] = 999
    (tmp_path / "v.jsonl").write_text(
        "\n".join([json.dumps(hdr)] + lines[1:]) + "\n")
    with pytest.raises(RecordingError, match="v999"):
        Recording.load(tmp_path / "v.jsonl")

    # a truncated file (lost trailer) fails closed
    (tmp_path / "t.jsonl").write_text("\n".join(lines[:-1]) + "\n")
    with pytest.raises(RecordingError, match="truncated|no end"):
        Recording.load(tmp_path / "t.jsonl")

    # a dropped middle record breaks the step/view counts
    (tmp_path / "m.jsonl").write_text(
        "\n".join(lines[:1] + lines[2:]) + "\n")
    with pytest.raises(RecordingError, match="disagree"):
        Recording.load(tmp_path / "m.jsonl")


def test_plan_serde_roundtrip_every_named_plan():
    from serf_tpu.faults.plan import named_plan, plan_names
    from serf_tpu.replay.recording import plan_from_dict, plan_to_dict

    for name in plan_names():
        plan = named_plan(name)
        assert plan_from_dict(plan_to_dict(plan)) == plan


def test_device_config_serde_roundtrip():
    from serf_tpu.replay.recording import (
        device_config_from_dict,
        device_config_to_dict,
    )

    cfg = _device_cfg(n=64, k_facts=32, pack_stamp=False)
    assert device_config_from_dict(device_config_to_dict(cfg)) == cfg


def test_differ_detects_length_and_header_mismatch():
    from serf_tpu.replay.differ import diff_recordings
    from serf_tpu.replay.recording import Recording, RunRecorder

    def make(n_views, plane="device"):
        r = RunRecorder()
        r.header(plane=plane, plan={"name": "x"}, seed=1, config={})
        for i in range(n_views):
            r.view(round_=i + 1, digest=f"{i:08x}", nodes=None)
        return r.to_recording()

    same = diff_recordings(make(3), make(3))
    assert same.ok and same.compared_views == 3
    short = diff_recordings(make(3), make(2))
    assert not short.ok and "length" in short.length_note
    cross = diff_recordings(make(2), make(2, plane="host"))
    assert not cross.ok and cross.header_notes


async def test_host_replay_refuses_custom_options():
    from serf_tpu.faults.plan import named_plan
    from serf_tpu.replay.recording import (
        Recording,
        RecordingError,
        RunRecorder,
        plan_to_dict,
    )
    from serf_tpu.replay.replayer import replay_host

    r = RunRecorder()
    r.header(plane="host", plan=plan_to_dict(named_plan("self-check")),
             seed=3, config={"options": "custom", "n": 4})
    with pytest.raises(RecordingError, match="custom"):
        await replay_host(r.to_recording())


def test_recording_schema_is_pinned():
    """The recording format is the third pinned schema surface: the AST
    spec matches the live literal and the pin carries version 1."""
    from serf_tpu.analysis.schema import (
        load_pins,
        recording_fingerprint,
        recording_spec,
    )
    from serf_tpu.replay.recording import RECORDING_SCHEMA

    spec = recording_spec(REPO)
    assert spec == {k: list(v) for k, v in RECORDING_SCHEMA.items()}
    pins = load_pins()
    assert pins["recording"]["version"] == 1
    assert pins["recording"]["fingerprint"] == recording_fingerprint(REPO)


# ---------------------------------------------------------------------------
# heavy soak: both stamp flavors x sharded flagship (redundant cover of
# the tier-1 path above at other config points)
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("pack_stamp", [True, False])
def test_record_replay_flavors_sharded_soak(vmesh8, pack_stamp):
    from serf_tpu.replay.differ import diff_recordings
    from serf_tpu.replay.replayer import replay_device

    cfg = _device_cfg(n=64, k_facts=32, pack_stamp=pack_stamp)
    result, rec = _record_device(cfg, mesh=vmesh8)
    assert result.report.ok, result.report.format()
    replayed = replay_device(rec, mesh=vmesh8).to_recording()
    d = diff_recordings(rec, replayed)
    assert d.ok, d.format()


@pytest.mark.slow
def test_selfcheck_roundtrip_verdict():
    from serf_tpu.replay.selfcheck import device_roundtrip

    out = device_roundtrip(n=48)
    assert out["digest_equal"] and out["invariants_ok"]
    assert out["rounds"] == 60
