"""Rotation peer-sampling mode (GossipConfig.peer_sampling="rotation").

At 1M nodes every random-index gather/scatter lowers to a serial loop on
TPU (~10 ms per op — measured on v5e); rotation sampling replaces them
with contiguous rolls.  These tests pin (1) the roll addressing math,
(2) protocol behavior under rotation: dissemination converges, failure
detection detects, anti-entropy heals partitions, Vivaldi learns.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serf_tpu.models.dissemination import (
    GossipConfig,
    K_USER_EVENT,
    coverage,
    inject_fact,
    make_state,
    rolled_rows,
    run_rounds,
    sample_offsets,
)
from serf_tpu.models.failure import FailureConfig, run_swim, swim_round
from serf_tpu.models.swim import ClusterConfig, cluster_round, make_cluster


def test_rolled_rows_matches_modular_indexing():
    rng = np.random.default_rng(0)
    for shape, dtype in (((97,), np.uint32), ((64, 3), np.float32),
                         ((50, 2), np.bool_)):
        x = jnp.asarray(rng.integers(0, 2, size=shape).astype(dtype))
        n = shape[0]
        for shift in (0, 1, 7, n - 1):
            want = x[(jnp.arange(n) + shift) % n]
            got = rolled_rows(x, shift)
            assert jnp.array_equal(got, want), (shape, dtype, shift)


def test_rolled_rows_traced_shift():
    x = jnp.arange(40, dtype=jnp.int32)

    @jax.jit
    def f(s):
        return rolled_rows(x, s)

    assert jnp.array_equal(f(3), (jnp.arange(40) + 3) % 40)


def test_sample_offsets_nonzero():
    offs = sample_offsets(jax.random.key(0), 64, 100)
    assert bool(jnp.all((offs >= 1) & (offs < 100)))


def test_rotation_dissemination_converges():
    cfg = GossipConfig(n=4096, k_facts=32, peer_sampling="rotation")
    st = inject_fact(make_state(cfg), cfg, subject=7, kind=K_USER_EVENT,
                     incarnation=0, ltime=1, origin=7)
    st = run_rounds(st, cfg, jax.random.key(1), 40)
    assert float(coverage(st, cfg)[0]) == 1.0


def test_rotation_swim_detects_dead():
    cfg = GossipConfig(n=2048, k_facts=32, peer_sampling="rotation")
    fcfg = FailureConfig(suspicion_rounds=6, max_new_facts=8,
                         probe_schedule="round_robin")
    st = make_state(cfg)
    dead = jnp.asarray([100, 900, 1500])
    st = st._replace(alive=st.alive.at[dead].set(False))
    st = run_swim(st, cfg, fcfg, jax.random.key(2), 60)
    from serf_tpu.models.failure import detection_complete
    assert bool(detection_complete(st, cfg, fcfg))


def test_rotation_swim_no_false_deaths_lossless():
    cfg = GossipConfig(n=1024, k_facts=32, peer_sampling="rotation")
    fcfg = FailureConfig(suspicion_rounds=6, probe_schedule="round_robin")
    st = run_swim(make_state(cfg), cfg, fcfg, jax.random.key(3), 40)
    from serf_tpu.models.dissemination import K_DEAD, K_SUSPECT
    kinds = np.asarray(st.facts.kind)
    valid = np.asarray(st.facts.valid)
    assert not np.any(valid & np.isin(kinds, [K_SUSPECT, K_DEAD]))


def test_rotation_flagship_round_runs_and_vivaldi_learns():
    cfg = ClusterConfig(
        gossip=GossipConfig(n=2048, k_facts=32, peer_sampling="rotation"),
        failure=FailureConfig(probe_schedule="round_robin"),
        push_pull_every=8)
    st = make_cluster(cfg, jax.random.key(0))
    st = st._replace(gossip=inject_fact(
        st.gossip, cfg.gossip, subject=3, kind=K_USER_EVENT,
        incarnation=0, ltime=1, origin=3))

    from serf_tpu.models.vivaldi import mean_relative_error

    err0 = float(mean_relative_error(st.vivaldi, cfg.vivaldi, st.positions,
                                     jax.random.key(9)))

    def run(st, key, num_rounds):
        def body(carry, subkey):
            return cluster_round(carry, cfg, subkey), ()
        out, _ = jax.lax.scan(body, st, jax.random.split(key, num_rounds))
        return out

    st = jax.jit(run, static_argnames=("num_rounds",))(
        st, jax.random.key(4), 100)
    assert float(coverage(st.gossip, cfg.gossip)[0]) == 1.0
    err1 = float(mean_relative_error(st.vivaldi, cfg.vivaldi, st.positions,
                                     jax.random.key(9)))
    assert err1 < err0 * 0.7  # coordinates actually learned


def test_rotation_push_pull_heals_partition():
    """Partition setup comes from a FaultPlan lowered by the device
    executor (the unified chaos plane) — the same plan object a host
    cluster would run; ``make_partition`` remains as sugar and must
    agree with the lowering."""
    from serf_tpu.faults.device import lower_plan
    from serf_tpu.faults.plan import FaultPhase, FaultPlan
    from serf_tpu.models.antientropy import (
        knowledge_agreement,
        make_partition,
        push_pull_round,
    )

    cfg = GossipConfig(n=1024, k_facts=32, peer_sampling="rotation")
    st = inject_fact(make_state(cfg), cfg, subject=1, kind=K_USER_EVENT,
                     incarnation=0, ltime=1, origin=1)
    plan = FaultPlan(
        name="rotation-bisect", n=cfg.n,
        phases=(FaultPhase(name="bisect", rounds=30,
                           partitions=(range(0, cfg.n // 2),
                                       range(cfg.n // 2, cfg.n))),))
    group = lower_plan(plan).group[0]
    # the legacy helper builds the same equivalence classes (sampled
    # across the bisection boundary)
    legacy = make_partition(cfg.n)
    idx = jnp.asarray([0, 1, cfg.n // 2 - 1, cfg.n // 2, cfg.n - 1])
    assert bool(jnp.all(
        (group[idx][:, None] == group[idx][None, :])
        == (legacy[idx][:, None] == legacy[idx][None, :])))
    key = jax.random.key(5)
    from serf_tpu.models.dissemination import round_step
    step_part = jax.jit(lambda s, k: round_step(s, cfg, k, group=group))
    for _ in range(30):  # spread within the partition only
        key, k = jax.random.split(key)
        st = step_part(st, k)
    cov_partitioned = float(coverage(st, cfg)[0])
    assert cov_partitioned <= 0.55  # other half never saw it
    # heal: no group mask; a few push/pull syncs + rounds finish the job
    heal = jax.jit(lambda s, k1, k2: round_step(
        push_pull_round(s, cfg, k1), cfg, k2))
    for _ in range(20):
        key, k1, k2 = jax.random.split(key, 3)
        st = heal(st, k1, k2)
    assert float(coverage(st, cfg)[0]) == 1.0
    assert float(knowledge_agreement(st, cfg)) == 1.0


def test_peer_sampling_validation():
    with pytest.raises(ValueError):
        GossipConfig(n=64, peer_sampling="nope")


def test_rotation_probe_inverse_matches_scatter_formula():
    """The analytic inverse (rolls) must agree with the scatter-based
    subject/detector computation for the same rotation targets."""
    n = 257
    rng = np.random.default_rng(7)
    detected = jnp.asarray(rng.random(n) < 0.3)
    offset = 103
    targets = (jnp.arange(n, dtype=jnp.int32) + offset) % n
    # scatter formula (iid path)
    subject_scatter = jnp.zeros((n,), bool).at[targets].max(detected)
    det_writes = jnp.where(detected, jnp.arange(n, dtype=jnp.int32) + 1, 0)
    det_scatter = jnp.maximum(
        jnp.zeros((n,), jnp.int32).at[targets].max(det_writes) - 1, 0)
    # roll formula (rotation path)
    subject_roll = rolled_rows(detected, n - offset)
    det_roll_raw = (jnp.arange(n, dtype=jnp.int32) + (n - offset)) % n
    assert jnp.array_equal(subject_scatter, subject_roll)
    # scatter clamps non-detected subjects' detector to 0; compare only
    # where a detection exists (the injector masks the rest anyway)
    sel = np.asarray(subject_roll)
    assert np.array_equal(np.asarray(det_scatter)[sel],
                          np.asarray(det_roll_raw)[sel])


def test_rotation_query_gathers_all_responses():
    from serf_tpu.models.query import (
        QueryConfig,
        launch_query,
        make_queries,
        no_filter_mask,
        num_responses,
        query_round,
    )

    cfg = GossipConfig(n=512, k_facts=32, peer_sampling="rotation")
    qcfg = QueryConfig(q_slots=2, relay_factor=2)
    st = make_state(cfg)
    g, qstate, qi = launch_query(st, make_queries(cfg, qcfg), cfg, qcfg,
                                 origin=3, eligible=no_filter_mask(cfg.n))
    key = jax.random.key(6)
    from serf_tpu.models.dissemination import round_step

    @jax.jit
    def step(g, qstate, k1, k2):
        g = round_step(g, cfg, k1)
        return g, query_round(g, qstate, cfg, qcfg, k2)

    for _ in range(30):
        key, k1, k2 = jax.random.split(key, 3)
        g, qstate = step(g, qstate, k1, k2)
    assert int(num_responses(qstate)[qi]) == cfg.n  # everyone responded


def test_rotation_sharded_parity_8_devices():
    """Rotation mode must be bit-identical sharded vs unsharded: the
    rolls (concat + dynamic-slice across the sharded node axis) may not
    change results under GSPMD."""
    import functools

    from serf_tpu.models.swim import run_cluster
    from serf_tpu.parallel.mesh import make_mesh, shard_state, state_shardings

    cfg = ClusterConfig(
        gossip=GossipConfig(n=1024, k_facts=32, peer_sampling="rotation"),
        failure=FailureConfig(probe_schedule="round_robin"),
        push_pull_every=8)
    state = make_cluster(cfg, jax.random.key(0))
    state = state._replace(
        gossip=inject_fact(state.gossip, cfg.gossip, 3, K_USER_EVENT,
                           0, 5, 0))
    mesh = make_mesh(8)
    sharded = shard_state(state, mesh)
    out_sh = state_shardings(state, mesh)
    run8 = jax.jit(functools.partial(run_cluster, cfg=cfg),
                   static_argnames=("num_rounds",), out_shardings=out_sh)
    run1 = jax.jit(functools.partial(run_cluster, cfg=cfg),
                   static_argnames=("num_rounds",))
    s8 = run8(sharded, key=jax.random.key(2), num_rounds=30)
    s1 = run1(state, key=jax.random.key(2), num_rounds=30)
    assert bool(jnp.all(s1.gossip.known == s8.gossip.known))
    assert bool(jnp.all(s1.gossip.stamp == s8.gossip.stamp))
    assert bool(jnp.allclose(s1.vivaldi.vec, s8.vivaldi.vec, atol=1e-6))
