"""The nibble-packed stamp plane must be a pure representation change:
every protocol output (membership views, coverage trajectories, known/
facts/tombstones, detection outcomes, the sendable cache) bit-identical
with ``pack_stamp`` on or off, under the compositions the flagship
actually runs — sustained injection, churn + failure detection,
push/pull anti-entropy, and the quiescent gate.  This is the semantic
A/B that gates the traffic halving (ISSUE 3 tentpole)."""

import functools

import jax
import jax.numpy as jnp

from serf_tpu.models.dissemination import (
    AGE_PIN_Q,
    CLAMP_EVERY,
    GossipConfig,
    K_USER_EVENT,
    STAMP_UNIT,
    budgets_of,
    coverage,
    inject_fact,
    inject_facts_batch,
    make_state,
    mod_age,
    run_rounds,
    stamp_nibbles,
    unpack_bits,
)
from serf_tpu.models.failure import FailureConfig, run_swim
from serf_tpu.models.swim import (
    ClusterConfig,
    make_cluster,
    run_cluster_sustained,
)


def _flavors(n=512, k=64):
    return {pk: GossipConfig(n=n, k_facts=k, peer_sampling="rotation",
                             pack_stamp=pk) for pk in (True, False)}


def _semantically_equal(a, b, cfg_a, cfg_b):
    """Every protocol field bit-identical; the stamp planes identical
    through their nibble view (the only semantic content they have)."""
    for name in ("known", "round", "last_learn", "next_slot", "alive",
                 "incarnation", "tombstone", "sendable",
                 "sendable_round", "last_clamp"):
        assert bool(jnp.all(getattr(a, name) == getattr(b, name))), name
    for name in ("subject", "kind", "incarnation", "ltime", "valid"):
        assert bool(jnp.all(getattr(a.facts, name)
                            == getattr(b.facts, name))), f"facts.{name}"
    na = stamp_nibbles(a.stamp, cfg_a.k_facts, cfg_a.pack_stamp)
    nb = stamp_nibbles(b.stamp, cfg_b.k_facts, cfg_b.pack_stamp)
    assert bool(jnp.all(na == nb)), "stamp nibble values diverged"


def test_packed_shapes_and_layout():
    cfgs = _flavors(n=256, k=64)
    assert make_state(cfgs[True]).stamp.shape == (256, 32)
    assert make_state(cfgs[False]).stamp.shape == (256, 64)
    # layout: fact k lives in byte k//2, even k = low nibble
    s = make_state(cfgs[True])
    s = inject_fact(s, cfgs[True], 5, K_USER_EVENT, 0, 1, 0)
    s = inject_fact(s, cfgs[True], 6, K_USER_EVENT, 0, 2, 0)
    nib = stamp_nibbles(s.stamp, 64, True)
    assert nib.shape == (256, 64)
    # round 0 -> quarter 0 stamps; the known bits gate their validity
    assert bool(unpack_bits(s.known, 64)[0, 0])
    assert bool(unpack_bits(s.known, 64)[0, 1])


def test_gossip_trajectory_bit_exact_packed_vs_unpacked():
    """40 plain gossip rounds from one injected fact: coverage at every
    checkpoint and the final state must match bit-for-bit."""
    outs, covs = {}, {}
    for pk, cfg in _flavors(n=512, k=32).items():
        g = inject_fact(make_state(cfg), cfg, 3, K_USER_EVENT, 0, 1, 0)
        run = jax.jit(functools.partial(run_rounds, cfg=cfg),
                      static_argnames=("num_rounds",))
        traj = []
        for seg in range(4):
            g = run(g, key=jax.random.key(100 + seg), num_rounds=10)
            traj.append(coverage(g, cfg))
        outs[pk], covs[pk] = g, jnp.stack(traj)
    assert bool(jnp.all(covs[True] == covs[False])), \
        "coverage trajectories diverged"
    cfgs = _flavors(n=512, k=32)
    _semantically_equal(outs[True], outs[False], cfgs[True], cfgs[False])


def test_flagship_sustained_churn_bit_exact_packed_vs_unpacked():
    """The full flagship composition (sustained events + probes + refute
    + declare-at-probe-cadence + push/pull + vivaldi cadence) with
    external churn between scan segments: identical membership views and
    coverage trajectories — the ISSUE-3 acceptance A/B."""
    from serf_tpu.models.views import cluster_stats

    gcfgs = _flavors(n=512, k=64)
    cfgs = {pk: ClusterConfig(
        gossip=g,
        failure=FailureConfig(suspicion_rounds=8, max_new_facts=8,
                              probe_schedule="round_robin"),
        push_pull_every=8, probe_every=5) for pk, g in gcfgs.items()}
    runs = {pk: jax.jit(functools.partial(run_cluster_sustained, cfg=cfg,
                                          events_per_round=2),
                        static_argnames=("num_rounds",))
            for pk, cfg in cfgs.items()}
    states = {pk: make_cluster(cfg, jax.random.key(0))
              for pk, cfg in cfgs.items()}

    for seg in range(3):
        views = {}
        for pk in (True, False):
            states[pk] = runs[pk](states[pk], key=jax.random.key(10 + seg),
                                  num_rounds=25)
            g = states[pk].gossip
            # churn: kill two nodes, revive one, inject out-of-band
            g = g._replace(alive=g.alive.at[
                jnp.asarray([17 + seg, 400 + seg])].set(False))
            g = g._replace(alive=g.alive.at[9].set(True))
            g = inject_facts_batch(
                g, cfgs[pk].gossip,
                subjects=jnp.asarray([450 + seg], jnp.int32),
                kind=K_USER_EVENT,
                incarnations=jnp.zeros((1,), jnp.uint32),
                ltimes=jnp.asarray([900 + seg], jnp.uint32),
                origins=jnp.asarray([11], jnp.int32),
                active=jnp.ones((1,), bool))
            states[pk] = states[pk]._replace(gossip=g)
            views[pk] = jax.device_get(cluster_stats(g, cfgs[pk].gossip))
        for fa, fb in zip(views[True], views[False]):
            assert bool(jnp.all(fa == fb)), "membership views diverged"
    _semantically_equal(states[True].gossip, states[False].gossip,
                        gcfgs[True], gcfgs[False])


def test_swim_detection_bit_exact_packed_vs_unpacked():
    """Failure-detection outcomes (suspicion aging through declaration,
    refutation, tombstones) identical across flavors — 60 rounds crosses
    several clamp boundaries and a stamp wrap (16 quarters = 64 rounds
    at the margin the clamp protects)."""
    outs = {}
    for pk, gcfg in _flavors(n=512, k=32).items():
        fcfg = FailureConfig(suspicion_rounds=8,
                             probe_schedule="round_robin")
        g = make_state(gcfg)
        g = inject_fact(g, gcfg, subject=3, kind=K_USER_EVENT,
                        incarnation=0, ltime=1, origin=0)
        g = g._replace(alive=g.alive.at[jnp.asarray([17, 300])].set(False))
        run = jax.jit(functools.partial(run_swim, cfg=gcfg, fcfg=fcfg),
                      static_argnames=("num_rounds",))
        outs[pk] = run(g, key=jax.random.key(1), num_rounds=48)
    cfgs = _flavors(n=512, k=32)
    _semantically_equal(outs[True], outs[False], cfgs[True], cfgs[False])
    assert bool(jnp.any(~outs[True].alive)), "churn must have happened"


def test_quarter_age_derivation_and_budgets():
    """q-ages advance one tick per STAMP_UNIT rounds, budgets derive in
    q-units, and a fact stops sending within (limit-4, limit] rounds —
    the documented quantization."""
    cfg = GossipConfig(n=64, k_facts=32)           # transmit_limit = 8
    assert cfg.transmit_limit == 8 and cfg.transmit_limit_q == 2
    s = inject_fact(make_state(cfg), cfg, 1, K_USER_EVENT, 0, 1, 0)
    assert int(mod_age(s, cfg)[0, 0]) == 0
    assert int(budgets_of(s, cfg)[0, 0]) == cfg.transmit_limit_q
    # age advances only when the round crosses a quarter boundary
    for r in range(1, 12):
        ages = mod_age(s._replace(round=jnp.asarray(r, jnp.int32)), cfg)
        assert int(ages[0, 0]) == r // STAMP_UNIT
    # budget exhausts at q_age == limit_q, i.e. exactly round limit
    # (learn happened at a quarter boundary here)
    s8 = s._replace(round=jnp.asarray(cfg.transmit_limit, jnp.int32))
    assert int(budgets_of(s8, cfg)[0, 0]) == 0


def test_clamp_pins_and_never_wraps_under_thresholds():
    """A known fact left un-restamped for hundreds of rounds must always
    read as at-least-pin age (never wrap back under transmit/suspicion
    thresholds), in both flavors, with the clamp riding learn passes or
    the standalone pass (last_clamp)."""
    for pk, cfg in _flavors(n=256, k=32).items():
        g = inject_fact(make_state(cfg), cfg, 1, K_USER_EVENT, 0, 1, 0)
        run = jax.jit(functools.partial(run_rounds, cfg=cfg),
                      static_argnames=("num_rounds",))
        g = run(g, key=jax.random.key(2), num_rounds=260)
        known = unpack_bits(g.known, cfg.k_facts)
        ages = jnp.where(known, mod_age(g, cfg), jnp.uint8(255))
        covered_age = int(jnp.min(jnp.where(known, ages, jnp.uint8(255))))
        # after 260 quiet-ish rounds every stamp is pinned: q-age in
        # [AGE_PIN_Q, AGE_PIN_Q + CLAMP_EVERY/STAMP_UNIT], never < limit
        assert covered_age >= cfg.transmit_limit_q
        assert covered_age >= AGE_PIN_Q
        assert covered_age <= AGE_PIN_Q + CLAMP_EVERY // STAMP_UNIT
        # and the gossip gate is closed (nothing sendable anywhere)
        assert int(jnp.sum(budgets_of(g, cfg))) == 0
