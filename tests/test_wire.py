"""Wire pipeline registries: checksum known-answer vectors + pipeline
round-trips + corruption drops, over every registered algorithm.

The reference feature-gates crc32/xxhash/murmur3 checksums
(serf-core/src/types.rs:10-48); xxhash32 and murmur3_32 here are validated
against the published test vectors of their specs.
"""

import asyncio

import pytest

from serf_tpu.host import wire
from serf_tpu.host.wire import (
    CHECKSUMS,
    COMPRESSIONS,
    WireError,
    decode_wire,
    encode_wire,
    murmur3_32,
    xxhash32,
)


def test_xxhash32_known_vectors():
    # published XXH32 test vectors
    assert xxhash32(b"") == 0x02CC5D05
    assert xxhash32(b"", seed=0x9E3779B1) == 0x36B78AE7
    assert xxhash32(b"Hello World") == 0xB1FD16EE
    assert xxhash32(b"Nobody inspects the spammish repetition") == 0xE2293B2F
    # regression pin (self-computed; the 39-byte vector above already
    # validates the 4-lane stripe loop against the published value)
    assert xxhash32(b"xxhash is a fast non-cryptographic hash") == 0xBDED5229


def test_murmur3_known_vectors():
    # published MurmurHash3 x86_32 test vectors
    assert murmur3_32(b"") == 0
    assert murmur3_32(b"", seed=1) == 0x514E28B7
    assert murmur3_32(b"", seed=0xFFFFFFFF) == 0x81F16F39
    assert murmur3_32(b"test") == 0xBA6BD213
    assert murmur3_32(b"Hello, world!", seed=1234) == 0xFAF6CDB3
    assert murmur3_32(b"The quick brown fox jumps over the lazy dog") == 0x2E4FF723


@pytest.mark.parametrize("checksum", [None, *CHECKSUMS])
@pytest.mark.parametrize("compression", [None, *COMPRESSIONS])
def test_pipeline_round_trip(checksum, compression):
    payload = b"gossip!" * 40
    enc = encode_wire(payload, compression, checksum)
    assert decode_wire(enc, compression, checksum) == payload
    overhead = wire.wire_overhead(compression, checksum)
    assert len(enc) <= len(payload) + overhead


@pytest.mark.parametrize("checksum", list(CHECKSUMS))
def test_corruption_dropped(checksum):
    payload = b"x" * 100
    enc = bytearray(encode_wire(payload, "zlib", checksum))
    enc[len(enc) // 2] ^= 0x40
    with pytest.raises(WireError):
        decode_wire(bytes(enc), "zlib", checksum)
    with pytest.raises(WireError):
        decode_wire(b"\x00\x01", "zlib", checksum)  # truncated


@pytest.mark.asyncio
@pytest.mark.parametrize("checksum", ["xxhash32", "murmur3"])
async def test_cluster_converges_with_new_checksums(checksum):
    """End-to-end: a 3-node cluster over each new checksum variant."""
    from serf_tpu.host.memberlist import Memberlist
    from serf_tpu.host.transport import LoopbackNetwork
    from serf_tpu.options import MemberlistOptions

    import dataclasses

    net = LoopbackNetwork()
    opts = dataclasses.replace(MemberlistOptions.local(),
                               compression="zlib", checksum=checksum)
    nodes = []
    for i in range(3):
        ml = Memberlist(net.bind(f"w{i}"), opts, f"node-{i}")
        await ml.start()
        nodes.append(ml)
    try:
        for ml in nodes[1:]:
            await ml.join(nodes[0].transport.local_addr)
        deadline = asyncio.get_running_loop().time() + 7.0
        while asyncio.get_running_loop().time() < deadline:
            if all(m.num_online_members() == 3 for m in nodes):
                break
            await asyncio.sleep(0.01)
        assert all(m.num_online_members() == 3 for m in nodes)
    finally:
        for ml in nodes:
            await ml.shutdown()


def test_native_checksums_bound_and_dispatched():
    """The native implementations load and the registry dispatches to them
    (the value differential lives in tests/test_property.py)."""
    from serf_tpu.codec import _native

    if _native.load() is None:
        pytest.skip("native lib unavailable")
    for name, py in (("xxhash32", xxhash32), ("murmur3", murmur3_32)):
        nat = _native.checksum_fn(name)
        assert nat is not None, f"native {name} missing after rebuild"
        assert CHECKSUMS[name](b"probe") == py(b"probe")


def _lz4_available():
    from serf_tpu.codec import _native
    return _native.lz4_fns() is not None


@pytest.mark.skipif(not _lz4_available(), reason="native lz4 unavailable")
class TestLz4:
    def test_round_trip_identity(self):
        import random
        import zlib as z

        from serf_tpu.codec import _native

        comp, decomp = _native.lz4_fns()
        rng = random.Random(5)
        cases = [b"", b"a", b"abcd" * 1000, bytes(range(256)) * 8,
                 rng.randbytes(10_000)]
        # structured gossip-like payloads compress; random ones round-trip
        for data in cases:
            enc = comp(data)
            assert decomp(enc, len(data)) == data
        assert len(comp(b"abcd" * 1000)) < 200   # ratio sanity on repetitive
        # incompressible stays near-raw (token overhead only)
        rnd = rng.randbytes(5000)
        assert len(comp(rnd)) <= len(rnd) + len(rnd) // 255 + 16

    def test_decoder_rejects_malformed(self):
        import random

        from serf_tpu.codec import _native

        comp, decomp = _native.lz4_fns()
        good = comp(b"hello world, hello world, hello world")
        rng = random.Random(6)
        rejected = 0
        for _ in range(3000):
            b = bytearray(good)
            op = rng.random()
            if op < 0.4 and b:
                b = b[:rng.randrange(len(b))]
            elif op < 0.8 and b:
                b[rng.randrange(len(b))] ^= 1 << rng.randrange(8)
            else:
                b = bytearray(rng.randbytes(rng.randrange(60)))
            try:
                decomp(bytes(b), 37)  # raises unless exactly 37 decoded
            except ValueError:
                rejected += 1
        assert rejected > 1000  # the malformation probes actually rejected

    def test_wire_pipeline_with_lz4(self):
        payload = b"gossip state " * 50
        for checksum in (None, "crc32", "xxhash32"):
            enc = encode_wire(payload, "lz4", checksum)
            assert decode_wire(enc, "lz4", checksum) == payload
            assert len(enc) < len(payload) // 2  # it actually compressed

    @pytest.mark.asyncio
    async def test_cluster_converges_over_lz4(self):
        import asyncio
        import dataclasses

        from serf_tpu.host.memberlist import Memberlist
        from serf_tpu.host.transport import LoopbackNetwork
        from serf_tpu.options import MemberlistOptions

        net = LoopbackNetwork()
        opts = dataclasses.replace(MemberlistOptions.local(),
                                   compression="lz4", checksum="xxhash32")
        nodes = []
        for i in range(3):
            ml = Memberlist(net.bind(f"z{i}"), opts, f"node-{i}")
            await ml.start()
            nodes.append(ml)
        try:
            for ml in nodes[1:]:
                await ml.join(nodes[0].transport.local_addr)
            deadline = asyncio.get_running_loop().time() + 7.0
            while asyncio.get_running_loop().time() < deadline:
                if all(m.num_online_members() == 3 for m in nodes):
                    break
                await asyncio.sleep(0.01)
            assert all(m.num_online_members() == 3 for m in nodes)
        finally:
            for ml in nodes:
                await ml.shutdown()


@pytest.mark.skipif(not _lz4_available(), reason="native lz4 unavailable")
def test_lz4_rejects_implausible_declared_size():
    """A tiny packet declaring a huge output must be rejected BEFORE any
    allocation (memory-amplification guard)."""
    from serf_tpu import codec as c
    from serf_tpu.host.wire import _lz4_decompress

    tiny = c.encode_varint(64 * 1024 * 1024) + b"\x00"
    with pytest.raises(ValueError, match="implausible"):
        _lz4_decompress(tiny)
    # a plausible declaration still round-trips
    from serf_tpu.host.wire import _lz4_compress
    assert _lz4_decompress(_lz4_compress(b"x" * 300)) == b"x" * 300


def _snappy_available():
    from serf_tpu.codec import _native
    return _native.snappy_fns() is not None


@pytest.mark.skipif(not _snappy_available(), reason="native snappy unavailable")
class TestSnappy:
    def test_spec_vectors_decode(self):
        """Hand-assembled blocks per the public snappy format description:
        every element kind (literal short/extended, copy-1/2/4, overlapping
        RLE copy) decodes to its spec-defined expansion."""
        from serf_tpu.codec import _native

        _, decomp = _native.snappy_fns()
        # short literal: varint(5) + tag((5-1)<<2) + "hello"
        assert decomp(bytes([5, (5 - 1) << 2]) + b"hello", 5) == b"hello"
        # copy with 2-byte offset: "abcd" then len-4 off-4 copy
        blk = (bytes([8, (4 - 1) << 2]) + b"abcd"
               + bytes([2 | ((4 - 1) << 2), 4, 0]))
        assert decomp(blk, 8) == b"abcdabcd"
        # copy with 1-byte offset (tag carries len-4 and offset high bits)
        blk = (bytes([8, (4 - 1) << 2]) + b"abcd"
               + bytes([1 | ((4 - 4) << 2) | ((4 >> 8) << 5), 4]))
        assert decomp(blk, 8) == b"abcdabcd"
        # copy with 4-byte offset
        blk = (bytes([8, (4 - 1) << 2]) + b"abcd"
               + bytes([3 | ((4 - 1) << 2), 4, 0, 0, 0]))
        assert decomp(blk, 8) == b"abcdabcd"
        # overlapping copy = RLE: one "a" then off-1 len-7 copy
        blk = bytes([8, 0]) + b"a" + bytes([2 | ((7 - 1) << 2), 1, 0])
        assert decomp(blk, 8) == b"a" * 8
        # extended literal length (60 => one extra LE length byte)
        data = bytes(range(100))
        blk = bytes([100, 60 << 2, 99]) + data
        assert decomp(blk, 100) == data

    def test_round_trip_identity(self):
        import random

        from serf_tpu.codec import _native

        comp, decomp = _native.snappy_fns()
        rng = random.Random(7)
        cases = [b"", b"a", b"abcd" * 1000, bytes(range(256)) * 8,
                 rng.randbytes(10_000)]
        for data in cases:
            enc = comp(data)
            assert decomp(enc, len(data)) == data
        assert len(comp(b"abcd" * 1000)) < 200   # ratio sanity on repetitive
        rnd = rng.randbytes(5000)
        assert len(comp(rnd)) <= len(rnd) + len(rnd) // 60 + 16

    def test_decoder_rejects_malformed(self):
        import random

        from serf_tpu.codec import _native

        comp, decomp = _native.snappy_fns()
        good = comp(b"hello world, hello world, hello world")
        rng = random.Random(8)
        rejected = 0
        for _ in range(3000):
            b = bytearray(good)
            op = rng.random()
            if op < 0.4 and b:
                b = b[:rng.randrange(len(b))]
            elif op < 0.8 and b:
                b[rng.randrange(len(b))] ^= 1 << rng.randrange(8)
            else:
                b = bytearray(rng.randbytes(rng.randrange(60)))
            try:
                decomp(bytes(b), 37)  # raises unless exactly 37 decoded
            except ValueError:
                rejected += 1
        assert rejected > 1000

    def test_wire_pipeline_with_snappy(self):
        payload = b"gossip state " * 50
        for checksum in (None, "crc32", "murmur3"):
            enc = encode_wire(payload, "snappy", checksum)
            assert decode_wire(enc, "snappy", checksum) == payload
            assert len(enc) < len(payload) // 2  # it actually compressed

    @pytest.mark.asyncio
    async def test_cluster_converges_over_snappy(self):
        import dataclasses

        from serf_tpu.host.memberlist import Memberlist
        from serf_tpu.host.transport import LoopbackNetwork
        from serf_tpu.options import MemberlistOptions

        net = LoopbackNetwork()
        opts = dataclasses.replace(MemberlistOptions.local(),
                                   compression="snappy", checksum="murmur3")
        nodes = []
        for i in range(3):
            ml = Memberlist(net.bind(f"sn{i}"), opts, f"node-{i}")
            await ml.start()
            nodes.append(ml)
        try:
            for ml in nodes[1:]:
                await ml.join(nodes[0].transport.local_addr)
            deadline = asyncio.get_running_loop().time() + 7.0
            while asyncio.get_running_loop().time() < deadline:
                if all(m.num_online_members() == 3 for m in nodes):
                    break
                await asyncio.sleep(0.01)
            assert all(m.num_online_members() == 3 for m in nodes)
        finally:
            for ml in nodes:
                await ml.shutdown()


@pytest.mark.skipif(not _snappy_available(),
                    reason="native snappy unavailable")
def test_snappy_rejects_implausible_declared_size():
    """The preamble-declared size is bounded before allocation, same
    amplification guard as lz4."""
    from serf_tpu import codec as c
    from serf_tpu.host.wire import _snappy_compress, _snappy_decompress

    tiny = c.encode_varint(64 * 1024 * 1024) + b"\x00"
    with pytest.raises(ValueError, match="implausible"):
        _snappy_decompress(tiny)
    assert _snappy_decompress(_snappy_compress(b"x" * 300)) == b"x" * 300


@pytest.mark.skipif("zstd" not in COMPRESSIONS,
                    reason="zstandard module unavailable")
class TestZstd:
    def test_wire_pipeline_with_zstd(self):
        payload = b"gossip state " * 50
        for checksum in (None, "crc32", "xxhash32"):
            enc = encode_wire(payload, "zstd", checksum)
            assert decode_wire(enc, "zstd", checksum) == payload
            assert len(enc) < len(payload) // 2

    def test_corruption_dropped(self):
        enc = bytearray(encode_wire(b"y" * 200, "zstd", None))
        enc[-3] ^= 0x20
        with pytest.raises(WireError):
            decode_wire(bytes(enc), "zstd", None)

    def test_rejects_implausible_content_size(self):
        """A frame declaring > the 64 MiB cap is rejected before the
        decompressor allocates."""
        import zstandard

        from serf_tpu.host.wire import _zstd_decompress

        big = zstandard.ZstdCompressor(level=1).compress(
            b"\x00" * (65 * 1024 * 1024))
        assert len(big) < 1024 * 1024  # RLE frame: tiny payload, huge claim
        with pytest.raises(ValueError, match="implausible"):
            _zstd_decompress(big)
        # under the 64 MiB absolute cap but far past the entropy cap
        # (max(1 MiB floor, 255x payload)): must reject
        from serf_tpu.host.wire import _entropy_cap

        mid = zstandard.ZstdCompressor(level=1).compress(
            b"\x00" * (63 * 1024 * 1024))
        assert _entropy_cap(len(mid)) < 63 * 1024 * 1024
        with pytest.raises(ValueError, match="implausible"):
            _zstd_decompress(mid)
        # a LEGITIMATE >255x frame under the 1 MiB floor decodes fine
        # (the old strict proportional bound falsely rejected these)
        legit = zstandard.ZstdCompressor(level=1).compress(b"x" * 5000)
        assert len(legit) * 255 + 64 < 5000
        assert _zstd_decompress(legit) == b"x" * 5000

    @pytest.mark.asyncio
    async def test_cluster_converges_over_zstd(self):
        import dataclasses

        from serf_tpu.host.memberlist import Memberlist
        from serf_tpu.host.transport import LoopbackNetwork
        from serf_tpu.options import MemberlistOptions

        net = LoopbackNetwork()
        opts = dataclasses.replace(MemberlistOptions.local(),
                                   compression="zstd", checksum="crc32")
        nodes = []
        for i in range(3):
            ml = Memberlist(net.bind(f"zs{i}"), opts, f"node-{i}")
            await ml.start()
            nodes.append(ml)
        try:
            for ml in nodes[1:]:
                await ml.join(nodes[0].transport.local_addr)
            deadline = asyncio.get_running_loop().time() + 7.0
            while asyncio.get_running_loop().time() < deadline:
                if all(m.num_online_members() == 3 for m in nodes):
                    break
                await asyncio.sleep(0.01)
            assert all(m.num_online_members() == 3 for m in nodes)
        finally:
            for ml in nodes:
                await ml.shutdown()


@pytest.mark.skipif("brotli" not in COMPRESSIONS,
                    reason="system brotli libraries unavailable")
class TestBrotli:
    """The 4th reference compression variant, via ctypes to the system
    libbrotlienc/libbrotlidec (serf-core/Cargo.toml:30-37)."""

    def test_wire_pipeline_with_brotli(self):
        payload = b"gossip state " * 50
        for checksum in (None, "crc32", "murmur3"):
            enc = encode_wire(payload, "brotli", checksum)
            assert decode_wire(enc, "brotli", checksum) == payload
            assert len(enc) < len(payload) // 2

    def test_round_trip_sizes(self):
        import os

        from serf_tpu.host.wire import _brotli_compress, _brotli_decompress

        for size in (0, 1, 100, 1400, 65536):
            data = os.urandom(size)
            assert _brotli_decompress(_brotli_compress(data)) == data

    def test_corruption_dropped(self):
        enc = bytearray(encode_wire(b"y" * 200, "brotli", None))
        enc[-3] ^= 0x20
        with pytest.raises(WireError):
            decode_wire(bytes(enc), "brotli", None)

    def test_amplification_bounded(self):
        """A tiny stream claiming a huge output fails at the capped
        buffer instead of forcing the allocation."""
        from serf_tpu.host.wire import _brotli_compress, _brotli_decompress

        from serf_tpu.host.wire import _entropy_cap

        bomb = _brotli_compress(b"\x00" * (8 * 1024 * 1024))
        assert len(bomb) < 16 * 1024        # highly compressible
        assert _entropy_cap(len(bomb)) < 8 * 1024 * 1024
        with pytest.raises(ValueError, match="amplification"):
            _brotli_decompress(bomb)
        # a LEGITIMATE highly-compressible frame above 255x but under the
        # 1 MiB floor decodes fine (the zstd guard's old strict bound
        # falsely rejected these — found live)
        legit = _brotli_compress(b"x" * (512 * 1024))
        assert len(legit) * 255 + 64 < 512 * 1024
        assert _brotli_decompress(legit) == b"x" * (512 * 1024)

    def test_garbage_rejected(self):
        from serf_tpu.host.wire import _brotli_decompress

        with pytest.raises(ValueError):
            _brotli_decompress(b"\xff\xfe\xfd not brotli at all")
