"""Wire pipeline registries: checksum known-answer vectors + pipeline
round-trips + corruption drops, over every registered algorithm.

The reference feature-gates crc32/xxhash/murmur3 checksums
(serf-core/src/types.rs:10-48); xxhash32 and murmur3_32 here are validated
against the published test vectors of their specs.
"""

import asyncio

import pytest

from serf_tpu.host import wire
from serf_tpu.host.wire import (
    CHECKSUMS,
    COMPRESSIONS,
    WireError,
    decode_wire,
    encode_wire,
    murmur3_32,
    xxhash32,
)


def test_xxhash32_known_vectors():
    # published XXH32 test vectors
    assert xxhash32(b"") == 0x02CC5D05
    assert xxhash32(b"", seed=0x9E3779B1) == 0x36B78AE7
    assert xxhash32(b"Hello World") == 0xB1FD16EE
    assert xxhash32(b"Nobody inspects the spammish repetition") == 0xE2293B2F
    # regression pin (self-computed; the 39-byte vector above already
    # validates the 4-lane stripe loop against the published value)
    assert xxhash32(b"xxhash is a fast non-cryptographic hash") == 0xBDED5229


def test_murmur3_known_vectors():
    # published MurmurHash3 x86_32 test vectors
    assert murmur3_32(b"") == 0
    assert murmur3_32(b"", seed=1) == 0x514E28B7
    assert murmur3_32(b"", seed=0xFFFFFFFF) == 0x81F16F39
    assert murmur3_32(b"test") == 0xBA6BD213
    assert murmur3_32(b"Hello, world!", seed=1234) == 0xFAF6CDB3
    assert murmur3_32(b"The quick brown fox jumps over the lazy dog") == 0x2E4FF723


@pytest.mark.parametrize("checksum", [None, *CHECKSUMS])
@pytest.mark.parametrize("compression", [None, *COMPRESSIONS])
def test_pipeline_round_trip(checksum, compression):
    payload = b"gossip!" * 40
    enc = encode_wire(payload, compression, checksum)
    assert decode_wire(enc, compression, checksum) == payload
    overhead = wire.wire_overhead(compression, checksum)
    assert len(enc) <= len(payload) + overhead


@pytest.mark.parametrize("checksum", list(CHECKSUMS))
def test_corruption_dropped(checksum):
    payload = b"x" * 100
    enc = bytearray(encode_wire(payload, "zlib", checksum))
    enc[len(enc) // 2] ^= 0x40
    with pytest.raises(WireError):
        decode_wire(bytes(enc), "zlib", checksum)
    with pytest.raises(WireError):
        decode_wire(b"\x00\x01", "zlib", checksum)  # truncated


@pytest.mark.asyncio
@pytest.mark.parametrize("checksum", ["xxhash32", "murmur3"])
async def test_cluster_converges_with_new_checksums(checksum):
    """End-to-end: a 3-node cluster over each new checksum variant."""
    from serf_tpu.host.memberlist import Memberlist
    from serf_tpu.host.transport import LoopbackNetwork
    from serf_tpu.options import MemberlistOptions

    import dataclasses

    net = LoopbackNetwork()
    opts = dataclasses.replace(MemberlistOptions.local(),
                               compression="zlib", checksum=checksum)
    nodes = []
    for i in range(3):
        ml = Memberlist(net.bind(f"w{i}"), opts, f"node-{i}")
        await ml.start()
        nodes.append(ml)
    try:
        for ml in nodes[1:]:
            await ml.join(nodes[0].transport.local_addr)
        deadline = asyncio.get_running_loop().time() + 7.0
        while asyncio.get_running_loop().time() < deadline:
            if all(m.num_online_members() == 3 for m in nodes):
                break
            await asyncio.sleep(0.01)
        assert all(m.num_online_members() == 3 for m in nodes)
    finally:
        for ml in nodes:
            await ml.shutdown()


def test_native_checksums_match_python_oracle():
    """Differential: the C++ xxhash32/murmur3 must agree with the Python
    spec implementations on random inputs of every tail length."""
    import random

    from serf_tpu.codec import _native

    if _native.load() is None:
        pytest.skip("native lib unavailable")
    rng = random.Random(11)
    for name, py in (("xxhash32", xxhash32), ("murmur3", murmur3_32)):
        nat = _native.checksum_fn(name)
        assert nat is not None, f"native {name} missing after rebuild"
        for trial in range(500):
            data = rng.randbytes(rng.randrange(0, 100))
            seed = rng.choice([0, 1, 0xFFFFFFFF, rng.randrange(1 << 32)])
            assert nat(data, seed) == py(data, seed), \
                (name, seed, data.hex())
        # the registry picked the native path
        assert CHECKSUMS[name](b"probe") == py(b"probe")
