"""SLO plane (ISSUE 10): burn rates, anomaly flags, judges, the bench
gate, and the device per-round telemetry contract.

- burn-rate windows + EWMA/MAD anomaly math;
- judge(): breach fires the `slo-breach` flight event + breach counter,
  green lands `serf.slo.ok`;
- the SLO table is registry-governed (names + watched metrics) — the
  in-process mirror of serflint's `slo-*` rules;
- device telemetry: row stability at small N (same seed = identical
  rows, both stamp-packing flavors bit-identical), and the zero extra
  per-round `device_get` pin (transfer count is independent of round
  count);
- obswatch: the green path exits 0, the deliberately degraded plan
  (loss raised past heal) fires `slo-breach` and exits nonzero;
- bench regression gate: bands verdicts + the warn-only/re-baseline
  contract.
"""

import importlib.util
import json
import math
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from serf_tpu.obs import flight, slo  # noqa: E402
from serf_tpu.obs.timeseries import TimeSeries  # noqa: E402
from serf_tpu.utils import metrics  # noqa: E402


# ---------------------------------------------------------------------------
# burn rates + anomalies (pure math)
# ---------------------------------------------------------------------------


def _series(vals, kind="gauge"):
    ts = TimeSeries("x", kind=kind, capacity=64)
    for i, v in enumerate(vals):
        ts.append(float(i), float(v))
    return ts


def test_burn_rates_lower_better():
    ts = _series([0.5] * 40)
    b = slo.burn_rates(ts, objective=1.0, better="lower")
    assert b == {"8": 0.5, "32": 0.5}
    b = slo.burn_rates(_series([2.0] * 40), 1.0, "lower")
    assert b["8"] == 2.0 and b["32"] == 2.0


def test_burn_rates_higher_better_and_zero_objective():
    b = slo.burn_rates(_series([0.5] * 40), 1.0, "higher")
    assert b["8"] == 2.0                       # objective / mean
    # zero objective (false-dead): clean series burns 0, dirty caps
    assert slo.burn_rates(_series([0.0] * 40), 0.0, "lower")["8"] == 0.0
    assert slo.burn_rates(_series([1.0] * 40), 0.0, "lower")["8"] \
        == slo.BURN_CAP


def test_ewma_mad_flags_spike_only():
    assert slo.ewma_mad_flags([5.0] * 50) == []          # flat: never
    vals = [10.0 + 0.1 * (i % 3) for i in range(50)]
    vals[30] = 100.0                                     # the spike
    flagged = slo.ewma_mad_flags(vals)
    assert 30 in flagged
    # the EWMA takes a few ticks to decay back under the MAD threshold,
    # so flags trail the spike — but nothing BEFORE it may fire
    assert min(flagged) == 30
    assert slo.ewma_mad_flags([1.0, 2.0]) == []          # too short


# ---------------------------------------------------------------------------
# judge(): emission contract
# ---------------------------------------------------------------------------


@pytest.fixture
def fresh_obs():
    """Swap in a fresh global sink + flight recorder, restore after."""
    old_sink = metrics.global_sink()
    old_rec = flight.global_recorder()
    metrics.set_global_sink(metrics.MetricsSink())
    flight.set_global_recorder(flight.FlightRecorder())
    yield metrics.global_sink(), flight.global_recorder()
    metrics.set_global_sink(old_sink)
    flight.set_global_recorder(old_rec)


def test_judge_green_emits_ok_gauge(fresh_obs):
    sink, rec = fresh_obs
    d = slo.slo_def("shed-ratio")
    v = slo.judge(d, "host", 0.1)
    assert v.ok and not v.skipped
    assert sink.gauge_value("serf.slo.ok",
                            {"slo": "shed-ratio", "plane": "host"}) == 1.0
    assert rec.dump(kind="slo-breach") == []


def test_judge_breach_fires_flight_and_counter(fresh_obs):
    sink, rec = fresh_obs
    d = slo.slo_def("false-dead")
    v = slo.judge(d, "device", 3.0, detail="3 believed dead")
    assert not v.ok
    evs = rec.dump(kind="slo-breach")
    assert len(evs) == 1 and evs[0]["slo"] == "false-dead"
    assert sink.counter("serf.slo.breach",
                        {"slo": "false-dead", "plane": "device"}) == 1.0


def test_judge_unmeasured_is_skipped_green(fresh_obs):
    v = slo.judge(slo.slo_def("query-p99"), "host", None)
    assert v.ok and v.skipped and v.value is None


def test_verdict_dict_keeps_json_finite(fresh_obs):
    v = slo.judge(slo.slo_def("convergence-settle"), "device", math.inf)
    d = v.to_dict()
    assert d["value"] is None and d["ok"] is False
    json.dumps(d)                      # strictly serializable


# ---------------------------------------------------------------------------
# the table is registry-governed (in-process mirror of the lint rules)
# ---------------------------------------------------------------------------


def test_slo_table_matches_registry_declaration():
    from serf_tpu.analysis import registry as reg
    assert set(slo.slo_names()) == set(reg.SLOS)
    declared = {reg.normalize(m) for m in reg.METRICS}
    for d in slo.SLO_TABLE:
        assert d.better in ("lower", "higher")
        assert d.planes and set(d.planes) <= {"host", "device", "proc"}
        for m in d.metrics:
            assert reg.normalize(m) in declared, \
                f"SLO {d.name} watches undeclared metric {m}"


# ---------------------------------------------------------------------------
# device telemetry: stability + the one-device_get pin
# ---------------------------------------------------------------------------


def _small_cfg(n=32, with_vivaldi=True, **kw):
    from serf_tpu.models.dissemination import GossipConfig
    from serf_tpu.models.failure import FailureConfig
    from serf_tpu.models.swim import ClusterConfig
    return ClusterConfig(
        gossip=GossipConfig(n=n, k_facts=32, peer_sampling="rotation",
                            **kw),
        failure=FailureConfig(suspicion_rounds=6, max_new_facts=8,
                              probe_schedule="round_robin"),
        push_pull_every=8, with_vivaldi=with_vivaldi)


@pytest.fixture(scope="module")
def _telemetry_runner():
    """One jitted sustained-telemetry runner per cfg for the whole
    module — the determinism test's second run must reuse the compile
    (tier-1 budget: one compile per distinct shape)."""
    import functools

    import jax
    from serf_tpu.models.swim import run_cluster_sustained

    @functools.lru_cache(maxsize=4)
    def runner(cfg):
        return jax.jit(functools.partial(run_cluster_sustained, cfg=cfg,
                                         events_per_round=1,
                                         collect_telemetry=True),
                       static_argnames=("num_rounds",))
    return runner


def _telemetry_rows(cfg, runner, rounds=8):
    import jax
    from serf_tpu.models.swim import make_cluster
    st = make_cluster(cfg, jax.random.key(0))
    _, rows = runner(cfg)(st, key=jax.random.key(1), num_rounds=rounds)
    return np.asarray(jax.device_get(rows))


def test_device_telemetry_rows_stable_and_sane(_telemetry_runner):
    from serf_tpu.models.swim import TELEMETRY_FIELDS
    cfg = _small_cfg()
    a = _telemetry_rows(cfg, _telemetry_runner)
    b = _telemetry_rows(cfg, _telemetry_runner)
    assert a.shape == (8, len(TELEMETRY_FIELDS))
    np.testing.assert_array_equal(a, b)          # same seed = same rows
    f = dict(zip(TELEMETRY_FIELDS, a[-1]))
    assert f["alive"] == 32
    assert 0.0 <= f["agreement"] <= 1.0 and 0.0 <= f["coverage"] <= 1.0
    assert f["injected"] >= 8                    # 1 event/round landed
    assert np.isfinite(a).all()


@pytest.mark.slow
def test_device_telemetry_bit_exact_across_stamp_flavors(
        _telemetry_runner):
    """The packed/unpacked stamp planes are bit-exact in every protocol
    output — the telemetry rows derived from them must agree exactly."""
    a = _telemetry_rows(_small_cfg(pack_stamp=True), _telemetry_runner,
                        rounds=12)
    b = _telemetry_rows(_small_cfg(pack_stamp=False), _telemetry_runner,
                        rounds=12)
    np.testing.assert_array_equal(a, b)


def _device_get_count_for(settle_rounds, monkeypatch):
    """run_device_plan with telemetry on a 2-phase + settle plan; count
    jax.device_get calls.  Phase length is fixed at 4 and settle is a
    multiple of it, so EVERY plan length reuses the one compiled
    4-round scan (the chunking rule) — the count difference, if any,
    could only come from per-round/per-scan transfers."""
    import jax
    from serf_tpu.faults.device import run_device_plan
    from serf_tpu.faults.plan import FaultPhase, FaultPlan

    plan = FaultPlan(
        name=f"pin-{settle_rounds}", n=8, seed=3,
        phases=(FaultPhase(name="warm", rounds=4),
                FaultPhase(name="split", rounds=4,
                           partitions=((0, 1, 2, 3), (4, 5, 6, 7)))),
        settle_s=1.0, settle_rounds=settle_rounds)
    calls = {"n": 0}
    real = jax.device_get

    def counting(x):
        calls["n"] += 1
        return real(x)

    monkeypatch.setattr(jax, "device_get", counting)
    try:
        # vivaldi off: the pin is about TRANSFER counts, and the slim
        # round halves this test's one compile (tier-1 budget)
        res = run_device_plan(plan, _small_cfg(n=8, with_vivaldi=False),
                              collect_telemetry=True)
    finally:
        monkeypatch.setattr(jax, "device_get", real)
    assert res.telemetry is not None
    assert len(res.telemetry.get("serf.model.gossip.agreement")) \
        == 8 + settle_rounds
    return calls["n"]


def test_telemetry_adds_zero_per_round_device_gets(monkeypatch):
    """THE acceptance pin: the per-round telemetry plane transfers once
    per RUN — tripling the round (and scan) count must not change the
    number of device_get calls."""
    short = _device_get_count_for(8, monkeypatch)
    long = _device_get_count_for(40, monkeypatch)
    assert short == long


class _FakeDeviceResult:
    """Stub DeviceChaosResult for judge-layer unit tests."""

    def __init__(self, store, final, rounds_run, dropped=0, offered=0):
        self.telemetry = store
        self.telemetry_final = final
        self.rounds_run = rounds_run
        self.dropped = dropped
        self.offered = offered


def test_host_shed_burn_evidence_is_in_ratio_units(fresh_obs):
    """The burn numbers beside the host shed-ratio verdict must be in
    the SLO's own units (shed/(admitted+shed) per tick), never raw
    event counts judged against the 0.95 ratio objective (regression:
    a green verdict carried breach-scale burn values)."""
    from serf_tpu.faults.host import HostLoadReport
    from serf_tpu.faults.plan import named_plan
    from serf_tpu.obs.timeseries import SeriesStore

    store = SeriesStore(capacity=16)
    for t in range(10):
        store.append("serf.overload.ingress_shed", float(t), 10.0,
                     kind="delta")
        store.append("serf.overload.ingress_admitted", float(t), 30.0,
                     kind="delta")

    class R:
        series = store
        settle_convergence_s = 0.5
        settle_converged = True
        false_dead = 0
        load = HostLoadReport(events_offered=300, queries_offered=100,
                              ingress_admitted=300, ingress_shed=100)

    plan = named_plan("query-storm")
    verdicts = {v.slo: v for v in slo.judge_host_run(R(), plan)}
    shed = verdicts["shed-ratio"]
    assert shed.ok and shed.value == pytest.approx(0.25)
    # running ratio is 10/40 = 0.25 at every tick; burn = 0.25/0.95
    for b in shed.burn.values():
        assert b == pytest.approx(0.25 / 0.95, rel=1e-3)


def test_host_ratio_series_survives_mixed_downsampling():
    """The two counter rings start ticks apart and downsample on
    different schedules — the derived ratio must stay exact because
    delta downsampling preserves sums (regression: equal-stamp pairing
    dropped half the points and understated the ratio ~2x)."""
    from serf_tpu.obs.slo import _host_ratio_series
    from serf_tpu.obs.timeseries import SeriesStore

    store = SeriesStore(capacity=16)     # tiny: both rings WILL merge
    for t in range(400):
        store.append("serf.overload.ingress_admitted", float(t), 1.0,
                     kind="delta")
    for t in range(200, 400):
        store.append("serf.overload.ingress_shed", float(t), 1.0,
                     kind="delta")
    assert store.get("serf.overload.ingress_admitted").downsamples \
        > store.get("serf.overload.ingress_shed").downsamples

    class R:
        series = store

    ratio = _host_ratio_series(R())
    assert len(ratio) > 0
    # true running ratio at the end: 200 shed / (200 shed + 400 adm);
    # the stride buckets may hold a partial tail, so allow a few ticks
    assert ratio.last() == pytest.approx(200 / 600, rel=0.08)


def test_device_judge_survives_ring_downsampling(fresh_obs):
    """A converged run longer than the ring capacity: downsampling
    pair-merges the agreement series so its last STORED point reads
    < 1.0 — the verdict must come from the exact final row the executor
    stashed, not the merged ring (regression: long healthy runs were
    judged 'never re-converged')."""
    from serf_tpu.faults.plan import FaultPhase, FaultPlan
    from serf_tpu.obs.timeseries import SeriesStore

    store = SeriesStore(capacity=8)     # tiny ring: downsampling certain
    rounds = 64
    for r in range(rounds):
        ag = min(1.0, r / (rounds - 8))  # converges 8 rounds before end
        store.append("serf.model.gossip.agreement", float(r + 1), ag)
        store.append("serf.model.swim.false-dead", float(r + 1), 0.0)
    merged_last = store.get("serf.model.gossip.agreement").last()
    assert merged_last is None or merged_last < 1.0 - 1e-6 \
        or store.get("serf.model.gossip.agreement").stride > 1
    plan = FaultPlan(name="x", n=4,
                     phases=(FaultPhase(name="w", rounds=rounds - 16),),
                     settle_rounds=16)
    res = _FakeDeviceResult(
        store, final={"agreement": 1.0, "false_dead": 0.0},
        rounds_run=rounds)
    verdicts = {v.slo: v for v in slo.judge_device_run(res, plan)}
    assert verdicts["convergence-settle"].ok
    assert verdicts["false-dead"].ok
    # and the inverse: an honestly-unconverged final row still breaches
    res_bad = _FakeDeviceResult(
        store, final={"agreement": 0.7, "false_dead": 2.0},
        rounds_run=rounds)
    verdicts = {v.slo: v for v in slo.judge_device_run(res_bad, plan)}
    assert not verdicts["convergence-settle"].ok
    assert not verdicts["false-dead"].ok


# ---------------------------------------------------------------------------
# obswatch: green + deliberately degraded (in-process)
# ---------------------------------------------------------------------------


def _obswatch():
    spec = importlib.util.spec_from_file_location(
        "obswatch", REPO / "tools" / "obswatch.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_obswatch_self_check_hook(fresh_obs, capsys):
    """obswatch --self-check --json: the tier-1 SLO-plane hook — both
    planes judged from the shared table, exit 0, rings present.  Driven
    in-process (the test_replay chaos.main precedent) so this test and
    the degraded one below share ONE compiled phase scan instead of
    paying a subprocess jax startup + duplicate compile against the
    tier-1 budget."""
    mod = _obswatch()
    rc = mod.main(["--self-check", "--json", "--n", "32"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["ok"] is True
    planes = set(out["verdicts"])
    assert planes == {"device", "host"}
    for plane in planes:
        assert all(v["ok"] for v in out["verdicts"][plane])
    assert out["rings"]["device"]["serf.model.gossip.agreement"]
    assert out["slo_breach_events"] == []


def test_obswatch_degraded_breaches_and_exits_nonzero(fresh_obs):
    """Loss raised PAST heal (no settle budget, 90% drop to the end):
    convergence cannot complete — the run must fire `slo-breach` and
    exit nonzero.  Same cfg and phase length as the green hook above,
    so the scan compile is reused."""
    mod = _obswatch()
    rc = mod.main(["--device-only", "--degraded", "--n", "32"])
    assert rc != 0
    _sink, rec = fresh_obs
    evs = rec.dump(kind="slo-breach")
    assert evs and any(e["slo"] == "convergence-settle" for e in evs)


# ---------------------------------------------------------------------------
# bench regression gate
# ---------------------------------------------------------------------------


BANDS = {"cpu": {"cluster_round_sustained_rps": {"min": 2.0},
                 "sharded.sustained_rps": {"min": 1.0, "max": 1e6}}}


def test_score_bench_green_and_violation():
    detail = {"cluster_round_sustained_rps": 5.0,
              "sharded": {"sustained_rps": 10.0}}
    gate = slo.score_bench(detail, BANDS, "cpu")
    assert gate["ok"] and not gate["rebaseline"]
    assert len(gate["checked"]) == 2
    bad = dict(detail, cluster_round_sustained_rps=0.5)
    gate = slo.score_bench(bad, BANDS, "cpu")
    assert not gate["ok"]
    assert gate["violations"] == ["cluster_round_sustained_rps"]


def test_score_bench_missing_metric_is_reported_not_violated():
    gate = slo.score_bench({"cluster_round_sustained_rps": 5.0},
                           BANDS, "cpu")
    assert gate["ok"]
    assert gate["missing"] == ["sharded.sustained_rps"]


def test_score_bench_no_bands_is_rebaseline_round():
    gate = slo.score_bench({"x": 1.0}, BANDS, "tpu")
    assert gate["ok"] and gate["rebaseline"]
    gate = slo.score_bench({"x": 1.0}, None, "cpu")
    assert gate["ok"] and gate["rebaseline"]


def test_committed_baseline_bands_parse():
    """The committed BASELINE.json bands block is well-formed and only
    names dotted paths with min/max numbers."""
    bands = json.loads((REPO / "BASELINE.json").read_text())["bands"]
    for platform in ("cpu", "tpu"):
        for metric, band in bands.get(platform, {}).items():
            assert isinstance(metric, str)
            assert set(band) <= {"min", "max"}
            for v in band.values():
                float(v)
