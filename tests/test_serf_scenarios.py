"""Deeper serf scenario coverage mirroring the reference suites under
serf/test/main/net/** (SURVEY.md §4): coalescing, reaping, snapshot
compaction, conflict resolution, message-drop fault injection.
"""

import asyncio
import os

import pytest

from serf_tpu.host import (
    EventSubscriber,
    LoopbackNetwork,
    MemberEvent,
    MemberEventType,
    Serf,
    SerfState,
    UserEvent,
)
from serf_tpu.host.events import MemberEventCoalescer, UserEventCoalescer
from serf_tpu.options import Options
from serf_tpu.types.member import Member, MemberStatus, Node
from serf_tpu.types.messages import MessageType
from serf_tpu.types.tags import Tags

pytestmark = pytest.mark.asyncio
DEADLINE = 7.0


async def wait_until(cond, deadline=DEADLINE, interval=0.01, msg="condition"):
    loop = asyncio.get_running_loop()
    end = loop.time() + deadline
    while loop.time() < end:
        if cond():
            return
        await asyncio.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


# -- coalescer units (reference coalesce/member.rs, coalesce/user.rs) -------


def test_member_event_coalescer_keeps_latest_per_node():
    c = MemberEventCoalescer()
    m = Member(Node("a"), Tags(), MemberStatus.ALIVE)
    c.handle(MemberEvent(MemberEventType.JOIN, (m,)))
    c.handle(MemberEvent(MemberEventType.FAILED, (m,)))
    out = c.flush()
    assert len(out) == 1 and out[0].ty == MemberEventType.FAILED
    assert c.flush() == []  # drained


def test_member_event_coalescer_merges_by_type():
    c = MemberEventCoalescer()
    a = Member(Node("a"), Tags(), MemberStatus.ALIVE)
    b = Member(Node("b"), Tags(), MemberStatus.ALIVE)
    c.handle(MemberEvent(MemberEventType.JOIN, (a,)))
    c.handle(MemberEvent(MemberEventType.JOIN, (b,)))
    out = c.flush()
    assert len(out) == 1
    assert {m.node.id for m in out[0].members} == {"a", "b"}


def test_user_event_coalescer_dedups_by_ltime_name():
    c = UserEventCoalescer()
    e1 = UserEvent(5, "deploy", b"x", True)
    e2 = UserEvent(5, "deploy", b"x", True)
    e3 = UserEvent(6, "deploy", b"y", True)
    assert c.handle(e1) and c.handle(e2) and c.handle(e3)
    out = c.flush()
    assert [(e.ltime, e.name) for e in out] == [(5, "deploy"), (6, "deploy")]
    assert not c.handle(UserEvent(7, "x", b"", False))  # non-coalescable


async def _coalesced_join_ids(prefix: str, sub, opts) -> set:
    """Shared harness: a 4-node cluster whose seed delivers through
    ``sub`` with ``opts``; returns the node-id set collected from the
    coalesced JOIN member events."""
    net = LoopbackNetwork()
    s0 = await Serf.create(net.bind(f"{prefix}0"), opts, f"{prefix}-0",
                           subscriber=sub)
    others = []
    try:
        for i in range(1, 4):
            s = await Serf.create(net.bind(f"{prefix}{i}"), Options.local(),
                                  f"{prefix}-{i}")
            others.append(s)
            await s.join(f"{prefix}0")
        joined = set()

        async def collect():
            while len(joined) < 4:
                ev = await sub.next(timeout=DEADLINE)
                if isinstance(ev, MemberEvent) and ev.ty == MemberEventType.JOIN:
                    joined.update(m.node.id for m in ev.members)

        await asyncio.wait_for(collect(), DEADLINE)
        return joined
    finally:
        await s0.shutdown()
        for s in others:
            await s.shutdown()


async def test_coalesced_member_events_flow():
    """End-to-end: with coalesce_period set, join events arrive merged."""
    joined = await _coalesced_join_ids(
        "c", EventSubscriber(),
        Options.local(coalesce_period=0.1, quiescent_period=0.05))
    assert joined == {"c-0", "c-1", "c-2", "c-3"}


# -- reaper (reference base.rs:483-610) -------------------------------------


async def test_reaper_erases_failed_members_and_emits_reap():
    net = LoopbackNetwork()
    sub = EventSubscriber()
    opts = Options.local(reap_interval=0.1, reconnect_timeout=0.3,
                         reconnect_interval=3600.0)
    s0 = await Serf.create(net.bind("r0"), opts, "r-0", subscriber=sub)
    s1 = await Serf.create(net.bind("r1"), Options.local(), "r-1")
    try:
        await s1.join("r0")
        await wait_until(lambda: s0.num_members() == 2)
        await s1.shutdown()
        await wait_until(
            lambda: any(m.status == MemberStatus.FAILED for m in s0.members()
                        if m.node.id == "r-1"), msg="r-1 failed")
        # after reconnect_timeout the reaper erases it entirely
        await wait_until(lambda: s0.num_members() == 1, msg="r-1 reaped")

        async def got_reap():
            while True:
                ev = await sub.next(timeout=DEADLINE)
                if isinstance(ev, MemberEvent) and ev.ty == MemberEventType.REAP:
                    return ev

        ev = await asyncio.wait_for(got_reap(), DEADLINE)
        assert ev.members[0].node.id == "r-1"
    finally:
        await s0.shutdown()


# -- snapshot compaction (reference snapshot.rs:766-884) --------------------


async def test_snapshot_force_compaction(tmp_path):
    from serf_tpu.utils import metrics as metrics_mod

    snap = str(tmp_path / "s.snap")
    net = LoopbackNetwork()
    opts = Options.local(snapshot_path=snap, snapshot_min_compact_size=512)
    sink = metrics_mod.MetricsSink()
    metrics_mod.set_global_sink(sink)
    s0 = await Serf.create(net.bind("s0"), opts, "s-0")
    s1 = await Serf.create(net.bind("s1"), Options.local(), "s-1")
    try:
        # push enough user events to exceed the 512-byte compaction floor
        await s1.join("s0")
        await wait_until(lambda: s0.num_members() == 2)
        for i in range(400):
            await s0.user_event(f"e{i}", b"payload", coalesce=False)
        # compaction observably RAN (metric recorded), not just "file small"
        await wait_until(
            lambda: len(sink.histogram("serf.snapshot.compact", {})) > 0,
            deadline=10.0, msg="snapshot compaction ran")
        await wait_until(
            lambda: os.path.exists(snap) and os.path.getsize(snap) < 4096,
            deadline=10.0, msg="snapshot compacted below write volume")
        # the compacted snapshot still replays the member list
        await s0.shutdown()
        from serf_tpu.host.snapshot import open_and_replay_snapshot
        replay = open_and_replay_snapshot(snap)
        assert {n.id for n in replay.alive_nodes} == {"s-0", "s-1"}
        assert replay.last_event_clock > 100
    finally:
        metrics_mod.set_global_sink(metrics_mod.MetricsSink())
        await s1.shutdown()
        if s0.state != SerfState.SHUTDOWN:
            await s0.shutdown()


# -- conflict resolution (reference base.rs:1658-1780) ----------------------


async def test_name_conflict_minority_shuts_down():
    """Two nodes claim the same id; the majority keeps the incumbent and the
    usurper shuts itself down."""
    net = LoopbackNetwork()
    nodes = []
    for i in range(3):
        s = await Serf.create(net.bind(f"n{i}"), Options.local(), f"node-{i}")
        nodes.append(s)
    for s in nodes[1:]:
        await s.join("n0")
    await wait_until(lambda: all(s.num_members() == 3 for s in nodes))
    # an usurper claims node-1's id from a different address
    usurper = await Serf.create(net.bind("evil"), Options.local(), "node-1")
    try:
        try:
            await usurper.join("n0")
        except Exception:
            pass
        await wait_until(
            lambda: usurper.state == SerfState.SHUTDOWN
            or nodes[1].state == SerfState.SHUTDOWN,
            deadline=10.0, msg="one claimant shuts down")
        # the incumbent (majority view) survives
        assert nodes[1].state != SerfState.SHUTDOWN
        assert usurper.state == SerfState.SHUTDOWN
    finally:
        for s in nodes:
            await s.shutdown()
        if usurper.state != SerfState.SHUTDOWN:
            await usurper.shutdown()


# -- message-type fault injection (reference MessageDropper, SURVEY.md §4) --


async def test_drop_leave_messages_blocks_leave_dissemination():
    net = LoopbackNetwork()
    nodes = []
    for i in range(3):
        s = await Serf.create(net.bind(f"d{i}"), Options.local(), f"d-{i}")
        nodes.append(s)
    try:
        for s in nodes[1:]:
            await s.join("d0")
        await wait_until(lambda: all(s.num_members() == 3 for s in nodes))
        net.drop_message_types(serf_types=(MessageType.LEAVE,))
        # graceful leave can't disseminate its intent; peers see a LEFT via
        # the swim plane (memberlist leave) but never the serf leave intent —
        # the node must still complete its own leave locally
        await asyncio.wait_for(nodes[2].leave(), DEADLINE)
        assert nodes[2].state == SerfState.LEFT
        net.drop_message_types()  # heal
    finally:
        for s in nodes:
            await s.shutdown()


def test_dropper_classification_unit():
    """The classifier decodes the real wire format: swim types, compound
    parts, USER-wrapped serf envelopes, and RELAY nesting (review findings)."""
    from serf_tpu.host import messages as sm
    from serf_tpu.host.keyring import SecretKeyring
    from serf_tpu.types.member import Node
    from serf_tpu.types.messages import (QueryResponseMessage, encode_message,
                                         encode_relay_message)

    net = LoopbackNetwork()
    ping = sm.encode_swim(sm.Ping(1, Node("a", "x"), "b"))
    user_qr = sm.encode_swim(sm.UserMsg(
        encode_message(QueryResponseMessage(1, 2, Node("a")))))
    relayed = sm.encode_swim(sm.UserMsg(encode_relay_message(
        Node("b"), encode_message(QueryResponseMessage(1, 2, Node("a"))))))
    compound = sm.encode_compound([ping, user_qr])

    # swim USER type is droppable
    net.drop_message_types(swim_types=(sm.SwimMessageType.USER,))
    assert net.drop_fn(0, 1, user_qr) and not net.drop_fn(0, 1, ping)
    # serf type matches inside USER, including RELAY-nested
    net.drop_message_types(serf_types=(MessageType.QUERY_RESPONSE,))
    assert net.drop_fn(0, 1, user_qr)
    assert net.drop_fn(0, 1, relayed)
    assert not net.drop_fn(0, 1, ping)
    # compound drops when any part matches
    net.drop_message_types(swim_types=(sm.SwimMessageType.PING,))
    assert net.drop_fn(0, 1, compound)
    # encrypted: unclassifiable without keyring (pass through), classified with
    ring = SecretKeyring(bytes(range(16)))
    enc = ring.encrypt(ping)
    assert not net.drop_fn(0, 1, enc)
    net.drop_message_types(swim_types=(sm.SwimMessageType.PING,), keyring=ring)
    assert net.drop_fn(0, 1, enc)
    net.drop_message_types()
    assert net.drop_fn is None


def test_dropper_with_wire_options():
    """The classifier must see through compression/checksum framing when
    given the cluster options (review finding)."""
    import dataclasses
    from serf_tpu.host import messages as sm
    from serf_tpu.host.memberlist import Memberlist
    from serf_tpu.options import MemberlistOptions
    from serf_tpu.types.member import Node

    net = LoopbackNetwork()
    opts = dataclasses.replace(MemberlistOptions.local(),
                               compression="zlib", checksum="crc32")
    ml = Memberlist(net.bind("wire0"), opts, "wire-0")
    ping_plain = sm.encode_swim(sm.Ping(1, Node("a", "x"), "b"))
    on_wire = ml._encode_wire(ping_plain)
    assert on_wire != ping_plain
    # without opts: unclassifiable, passes through
    net.drop_message_types(swim_types=(sm.SwimMessageType.PING,))
    assert not net.drop_fn(0, 1, on_wire)
    # with opts: classified and dropped
    net.drop_message_types(swim_types=(sm.SwimMessageType.PING,), opts=opts)
    assert net.drop_fn(0, 1, on_wire)
    net.drop_message_types()


async def test_join_ignore_old_suppresses_event_replay():
    """join(ignore_old=True): user events that predate the join are not
    replayed to the newcomer (reference api.rs:318-417 event_join_ignore)."""
    net = LoopbackNetwork()
    created = []
    s0 = await Serf.create(net.bind("io0"), Options.local(), "io-0")
    created.append(s0)
    s1 = await Serf.create(net.bind("io1"), Options.local(), "io-1")
    created.append(s1)
    try:
        await s1.join("io0")
        await wait_until(lambda: s0.num_members() == 2)
        for i in range(3):
            await s0.user_event(f"old-{i}", b"x", coalesce=False)
        await wait_until(lambda: s1.event_clock.time() >= 4)

        sub = EventSubscriber()
        s2 = await Serf.create(net.bind("io2"), Options.local(), "io-2",
                               subscriber=sub)
        created.append(s2)
        await s2.join("io0", ignore_old=True)
        await wait_until(lambda: s2.num_members() == 3)
        await asyncio.sleep(0.5)  # let any (wrong) replay arrive
        replayed = []
        while True:
            ev = sub.try_next()
            if ev is None:
                break
            if isinstance(ev, UserEvent) and ev.name.startswith("old-"):
                replayed.append(ev.name)
        assert replayed == [], f"old events replayed: {replayed}"
        # but NEW events still flow
        await s0.user_event("fresh", b"y", coalesce=False)

        async def got_fresh():
            while True:
                ev = await sub.next(timeout=DEADLINE)
                if isinstance(ev, UserEvent) and ev.name == "fresh":
                    return True

        assert await asyncio.wait_for(got_fresh(), DEADLINE)
    finally:
        for s in created:
            try:
                await s.shutdown()
            except Exception:
                pass


async def test_join_without_ignore_old_replays_recent_events():
    """Default join: the push/pull event window IS replayed to newcomers."""
    net = LoopbackNetwork()
    created = []
    s0 = await Serf.create(net.bind("rp0"), Options.local(), "rp-0")
    created.append(s0)
    try:
        await s0.user_event("historic", b"x", coalesce=False)
        sub = EventSubscriber()
        s1 = await Serf.create(net.bind("rp1"), Options.local(), "rp-1",
                               subscriber=sub)
        created.append(s1)
        await s1.join("rp0")

        async def got_historic():
            while True:
                ev = await sub.next(timeout=DEADLINE)
                if isinstance(ev, UserEvent) and ev.name == "historic":
                    return True

        assert await asyncio.wait_for(got_historic(), DEADLINE)
    finally:
        for s in created:
            try:
                await s.shutdown()
            except Exception:
                pass


def test_subscriber_overflow_counted():
    """Drop-oldest overflow is a documented deviation from the reference's
    backpressuring channel; the loss must be observable (round-1 verdict)."""
    import asyncio

    from serf_tpu.host.events import EventSubscriber
    from serf_tpu.utils import metrics

    async def main():
        sub = EventSubscriber(maxsize=4)
        before = metrics.global_sink().counter("serf.subscriber.dropped")
        for i in range(10):
            sub._push(i)
        assert sub.dropped == 6
        assert metrics.global_sink().counter("serf.subscriber.dropped") - before == 6
        # newest events survive
        got = [sub.try_next() for _ in range(4)]
        assert got == [6, 7, 8, 9]

    asyncio.run(main())


async def test_lossless_subscriber_backpressures_never_drops():
    """Opt-in bounded BLOCKING subscriber (the reference's bounded
    channel semantics, event.rs:394-512): the producer awaits until the
    consumer makes room; every event arrives in order, none dropped."""
    from serf_tpu.host.events import EventSubscriber

    sub = EventSubscriber(maxsize=2, lossless=True)
    pushed = []

    async def producer():
        for i in range(10):
            await sub.push(i)
            pushed.append(i)

    task = asyncio.create_task(producer())
    await asyncio.sleep(0.05)
    assert len(pushed) < 10, "producer never backpressured"
    got = [await asyncio.wait_for(sub.next(), 2.0) for _ in range(10)]
    await task
    assert got == list(range(10))
    assert sub.dropped == 0


async def test_lossless_subscriber_composes_with_coalescers():
    """The coalesce pipeline delivers through ``await push``: with a
    tiny LOSSLESS subscriber the flush blocks instead of dropping, and
    every coalesced member event still arrives once drained."""
    sub = EventSubscriber(maxsize=1, lossless=True)
    joined = await _coalesced_join_ids(
        "lc", sub,
        Options.local(coalesce_period=0.05, quiescent_period=0.02))
    assert joined == {"lc-0", "lc-1", "lc-2", "lc-3"}
    assert sub.dropped == 0, "lossless subscriber dropped events"


async def test_leave_intent_avoids_infinite_rebroadcast():
    """The consul#8179 guard: a leave intent about an already-leaving/left
    member updates the time but must NOT be rebroadcast (the reference pins
    this with events_leave_avoid_infinite_rebroadcast)."""
    from serf_tpu.host import LoopbackNetwork, Serf
    from serf_tpu.host.memberlist import NodeState
    from serf_tpu.options import Options
    from serf_tpu.types.member import Node
    from serf_tpu.types.messages import LeaveMessage

    net = LoopbackNetwork()
    s = await Serf.create(net.bind("g"), Options.local(), "guard-node")
    try:
        s._handle_node_join(NodeState(Node("peer", "p")))
        # first leave intent: rebroadcast
        assert s._handle_node_leave_intent(LeaveMessage(10, "peer")) is True
        # re-delivery with a newer ltime while LEAVING: no rebroadcast
        assert s._handle_node_leave_intent(LeaveMessage(11, "peer")) is False
        assert s._members["peer"].status_time == 11  # time still advances
        # stale ltime: ignored outright
        assert s._handle_node_leave_intent(LeaveMessage(5, "peer")) is False
        # failed -> left transition rebroadcasts once, then suppresses
        s._handle_node_join(NodeState(Node("f", "f")))
        from serf_tpu.types.member import MemberStatus
        ms = s._members["f"]
        ms.member = ms.member.with_status(MemberStatus.FAILED)
        s._failed.append(ms)
        assert s._handle_node_leave_intent(LeaveMessage(20, "f")) is True
        assert s._members["f"].member.status == MemberStatus.LEFT
        assert s._handle_node_leave_intent(LeaveMessage(21, "f")) is False
    finally:
        await s.shutdown()


async def test_sweep_holds_while_leave_broadcast_pending():
    """The dangling-LEAVING sweep must not resurrect a member whose leave
    intent is still draining from OUR broadcast queue (congested queue /
    large cluster): the grace timer holds until the local dissemination
    finishes, then runs normally."""
    from serf_tpu.host.broadcast import Broadcast
    from serf_tpu.types.messages import LeaveMessage, encode_message

    net = LoopbackNetwork()
    opts = Options.local(broadcast_timeout=0.3, leave_propagate_delay=0.1)
    nodes = [await Serf.create(net.bind(f"pb{i}"), opts, f"pb-{i}")
             for i in range(2)]
    try:
        s0, s1 = nodes
        await s1.join("pb0")
        await wait_until(lambda: all(s.num_members() == 2 for s in nodes),
                         msg="2-node convergence")
        ms = s0._members["pb-1"]
        lt = ms.status_time + 1
        s0._handle_node_leave_intent(LeaveMessage(lt, "pb-1"),
                                     rebroadcast=False)
        assert ms.member.status == MemberStatus.LEAVING
        # pin a leave broadcast for pb-1 in the queue: sweep must hold.
        # grace = 2*(0.3+0.1) = 0.8s; the hold is capped at 5*grace = 4s.
        raw = encode_message(LeaveMessage(lt, "pb-1"))
        s0.intent_broadcasts.queue_broadcast(Broadcast(raw, name="pb-1"))
        since: dict = {}
        t0 = 1000.0
        s0._sweep_dangling_leaving(since, t0)
        s0._sweep_dangling_leaving(since, t0 + 2.0)    # >> grace, < cap
        assert ms.member.status == MemberStatus.LEAVING, \
            "sweep resurrected a member mid-leave-dissemination"
        # a STALE leave broadcast (ltime < status_time) must NOT hold:
        # replace the pinned broadcast with a superseded one and verify
        # the timer logic ignores it (status_time is lt, broadcast lt-1)
        s0.intent_broadcasts._items.clear()
        stale = encode_message(LeaveMessage(lt - 1, "pb-1"))
        s0.intent_broadcasts.queue_broadcast(Broadcast(stale, name="pb-1"))
        assert s0._pending_leave_ltimes().get("pb-1") == lt - 1
        # queue drained of CURRENT leaves -> grace restarts from the last
        # pending sweep (t0+2), then the normal repair applies
        s0._sweep_dangling_leaving(since, t0 + 2.5)
        assert ms.member.status == MemberStatus.LEAVING  # grace restarted
        s0._sweep_dangling_leaving(since, t0 + 10.0)
        assert ms.member.status == MemberStatus.ALIVE

        # cap: a leave broadcast that NEVER drains (transmit-starved in a
        # churning queue) cannot defer the repair past 5*grace
        s1_ms = None
        lt2 = s1._members["pb-0"].status_time + 1
        s1._handle_node_leave_intent(LeaveMessage(lt2, "pb-0"),
                                     rebroadcast=False)
        s1_ms = s1._members["pb-0"]
        assert s1_ms.member.status == MemberStatus.LEAVING
        raw2 = encode_message(LeaveMessage(lt2, "pb-0"))
        s1.intent_broadcasts.queue_broadcast(Broadcast(raw2, name="pb-0"))
        since2: dict = {}
        s1._sweep_dangling_leaving(since2, t0)
        s1._sweep_dangling_leaving(since2, t0 + 2.0)   # held (pending)
        assert s1_ms.member.status == MemberStatus.LEAVING
        s1._sweep_dangling_leaving(since2, t0 + 5.0)   # past 5*grace cap
        assert s1_ms.member.status == MemberStatus.ALIVE, \
            "a never-draining broadcast deferred the repair past the cap"
    finally:
        for s in nodes:
            await s.shutdown()


async def test_dangling_leaving_restored_by_reaper():
    """Equal-Lamport-time join/leave race (root cause of the soak seed-2
    flake): a rejoiner's fresh clock can collide with its old leave's
    ltime (push/pull witnesses pp.ltime - 1, reference-faithful), so at
    equal ltimes whichever intent a node applied FIRST wins at that node,
    permanently — some nodes hold ALIVE(t), a minority that saw the leave
    first holds LEAVING(t), and the <=-dedup means no message ever flips
    them.  The reaper's dangling-LEAVING sweep must restore such members
    to ALIVE while SWIM still probes them alive."""
    from serf_tpu.types.messages import JoinMessage, LeaveMessage

    net = LoopbackNetwork()
    opts = Options.local(broadcast_timeout=0.3, leave_propagate_delay=0.1)
    nodes = [await Serf.create(net.bind(f"dl{i}"), opts, f"dl-{i}")
             for i in range(3)]
    try:
        for s in nodes[1:]:
            await s.join("dl0")
        s0 = nodes[0]
        # wait for dl-2's REAL join intent (ltime >= 2) to land at s0,
        # not just SWIM-level membership: sampling status_time before it
        # arrives makes the synthetic ltimes below collide with the late
        # intent, which then flips the member ALIVE at a higher ltime
        # and invalidates the final newer-leave assertion (rare race)
        await wait_until(lambda: all(s.num_members() == 3 for s in nodes)
                         and s0._members["dl-2"].status_time > 0,
                         msg="3-node convergence incl. dl-2 join intent")
        ms = s0._members["dl-2"]
        lt = ms.status_time + 1
        # the losing arrival order: leave(t) first ...
        s0._handle_node_leave_intent(LeaveMessage(lt, "dl-2"),
                                     rebroadcast=False)
        assert s0._members["dl-2"].member.status == MemberStatus.LEAVING
        # ... then the equal-ltime join is a no-op (the non-confluence)
        s0._handle_node_join_intent(JoinMessage(lt, "dl-2"),
                                    rebroadcast=False)
        assert s0._members["dl-2"].member.status == MemberStatus.LEAVING
        # dl-2 is still alive and SWIM-probed; the sweep must repair
        await wait_until(
            lambda: s0._members["dl-2"].member.status == MemberStatus.ALIVE,
            deadline=10.0, msg="dangling LEAVING restored")
        # lamport state untouched: a genuinely newer leave still applies
        s0._handle_node_leave_intent(LeaveMessage(lt + 1, "dl-2"),
                                     rebroadcast=False)
        assert s0._members["dl-2"].member.status == MemberStatus.LEAVING
    finally:
        for s in nodes:
            await s.shutdown()


async def test_genuine_leaver_not_restored():
    """The dangling-LEAVING sweep must not resurrect a node that is
    actually leaving: its memberlist backing disappears within the leave
    window, so the sweep's SWIM-alive condition fails."""
    net = LoopbackNetwork()
    opts = Options.local(broadcast_timeout=0.3, leave_propagate_delay=0.1)
    nodes = [await Serf.create(net.bind(f"gl{i}"), opts, f"gl-{i}")
             for i in range(3)]
    try:
        for s in nodes[1:]:
            await s.join("gl0")
        await wait_until(lambda: all(s.num_members() == 3 for s in nodes),
                         msg="3-node convergence")
        await nodes[2].leave()
        await nodes[2].shutdown()
        # LEFT everywhere, and it STAYS left well past the sweep grace
        await wait_until(
            lambda: all(s._members["gl-2"].member.status == MemberStatus.LEFT
                        for s in nodes[:2]),
            msg="graceful leave propagates")
        await asyncio.sleep(1.5)   # > 2*(broadcast_timeout+propagate_delay)
        for s in nodes[:2]:
            assert s._members["gl-2"].member.status == MemberStatus.LEFT
    finally:
        for s in nodes:
            if s.state != SerfState.SHUTDOWN:
                await s.shutdown()
