"""Serf engine tests: the scenario suite the reference pins under
serf-core/src/serf/base/tests/ and serf/test/main/net/** (SURVEY.md §4) —
join intents, leave variants, events, queries, tags, conflict handling,
reaping, stats, coordinates.
"""

import asyncio

import pytest

from serf_tpu.host import (
    EventSubscriber,
    LoopbackNetwork,
    MemberEvent,
    MemberEventType,
    QueryEvent,
    QueryParam,
    Serf,
    SerfState,
    UserEvent,
)
from serf_tpu.options import Options
from serf_tpu.types.member import MemberStatus
from serf_tpu.types.filters import IdFilter, TagFilter
from serf_tpu.types.tags import Tags

pytestmark = pytest.mark.asyncio
DEADLINE = 7.0


async def wait_until(cond, deadline=DEADLINE, interval=0.01, msg="condition"):
    loop = asyncio.get_running_loop()
    end = loop.time() + deadline
    while loop.time() < end:
        if cond():
            return
        await asyncio.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


async def make_cluster(net, n, subscribe=(), opts_fn=None, start=0):
    nodes, subs = [], {}
    for i in range(start, start + n):
        opts = opts_fn(i) if opts_fn else Options.local()
        sub = EventSubscriber() if i in subscribe else None
        s = await Serf.create(net.bind(f"addr-{i}"), opts, f"node-{i}",
                              subscriber=sub)
        nodes.append(s)
        if sub:
            subs[i] = sub
    return nodes, subs


async def join_all(nodes):
    for s in nodes[1:]:
        await s.join("addr-" + nodes[0].local_id.split("-")[1])


def alive_members(s):
    return [m for m in s.members() if m.status == MemberStatus.ALIVE]


async def shutdown_all(nodes):
    for s in nodes:
        await s.shutdown()


async def test_create_single_node():
    net = LoopbackNetwork()
    s = await Serf.create(net.bind("a"), Options.local(), "solo")
    try:
        assert s.state == SerfState.ALIVE
        assert s.num_members() == 1
        assert s.members()[0].node.id == "solo"
        st = s.stats()
        assert st.members == 1 and not st.encrypted
    finally:
        await s.shutdown()


async def test_join_members_converge():
    net = LoopbackNetwork()
    nodes, _ = await make_cluster(net, 5)
    try:
        await join_all(nodes)
        await wait_until(lambda: all(len(alive_members(s)) == 5 for s in nodes),
                         msg="5 alive members everywhere")
        for s in nodes:
            assert {m.node.id for m in s.members()} == {f"node-{i}" for i in range(5)}
    finally:
        await shutdown_all(nodes)


async def test_join_events_emitted():
    net = LoopbackNetwork()
    nodes, subs = await make_cluster(net, 3, subscribe={0})
    try:
        await join_all(nodes)
        seen = set()

        async def collect():
            while len(seen) < 3:
                ev = await subs[0].next(timeout=DEADLINE)
                if isinstance(ev, MemberEvent) and ev.ty == MemberEventType.JOIN:
                    seen.update(m.node.id for m in ev.members)

        await asyncio.wait_for(collect(), DEADLINE)
        assert seen == {"node-0", "node-1", "node-2"}
    finally:
        await shutdown_all(nodes)


async def test_user_event_dissemination():
    net = LoopbackNetwork()
    nodes, subs = await make_cluster(net, 5, subscribe={0, 4})
    try:
        await join_all(nodes)
        await wait_until(lambda: all(len(alive_members(s)) == 5 for s in nodes))
        await nodes[2].user_event("deploy", b"v2", coalesce=False)

        async def got_event(sub):
            while True:
                ev = await sub.next(timeout=DEADLINE)
                if isinstance(ev, UserEvent) and ev.name == "deploy":
                    return ev

        ev0 = await asyncio.wait_for(got_event(subs[0]), DEADLINE)
        ev4 = await asyncio.wait_for(got_event(subs[4]), DEADLINE)
        assert ev0.payload == ev4.payload == b"v2"
        assert ev0.ltime == ev4.ltime
    finally:
        await shutdown_all(nodes)


async def test_user_event_dedup_no_redelivery():
    net = LoopbackNetwork()
    nodes, subs = await make_cluster(net, 3, subscribe={1})
    try:
        await join_all(nodes)
        await wait_until(lambda: all(len(alive_members(s)) == 3 for s in nodes))
        await nodes[0].user_event("once", b"x", coalesce=False)
        count = 0

        async def count_events():
            nonlocal count
            while True:
                ev = await subs[1].next(timeout=1.0)
                if isinstance(ev, UserEvent) and ev.name == "once":
                    count += 1

        try:
            await asyncio.wait_for(count_events(), 2.0)
        except (asyncio.TimeoutError, TimeoutError):
            pass
        assert count == 1  # gossip redundancy must not re-deliver
    finally:
        await shutdown_all(nodes)


async def test_user_event_size_limit():
    net = LoopbackNetwork()
    s = await Serf.create(net.bind("a"), Options.local(), "solo")
    big = await Serf.create(net.bind("b"),
                            Options.local(max_user_event_size=9 * 1024), "big")
    try:
        # configured limit (default 512)
        with pytest.raises(ValueError):
            await s.user_event("big", b"x" * 600)
        # raw size within the 9 KiB hard cap but ENCODED size above it
        with pytest.raises(ValueError):
            await big.user_event("abc", b"x" * (9 * 1024 - 6))
        # options exceeding the hard cap are rejected up front
        with pytest.raises(ValueError):
            Options(max_user_event_size=10 * 1024).validate()
    finally:
        await s.shutdown()
        await big.shutdown()


async def test_query_responses_and_acks():
    net = LoopbackNetwork()
    nodes, subs = await make_cluster(net, 4, subscribe={1, 2, 3})
    try:
        await join_all(nodes)
        await wait_until(lambda: all(len(alive_members(s)) == 4 for s in nodes))

        async def responder(i):
            while True:
                ev = await subs[i].next()
                if isinstance(ev, QueryEvent) and ev.name == "whoami":
                    await ev.respond(f"i-am-node-{i}".encode())
                    return

        tasks = [asyncio.create_task(responder(i)) for i in (1, 2, 3)]
        resp = await nodes[0].query("whoami", b"", QueryParam(request_ack=True, timeout=3.0))
        results = {r.from_id: r.payload async for r in resp.responses()}
        for t in tasks:
            t.cancel()
        assert results == {f"node-{i}": f"i-am-node-{i}".encode() for i in (1, 2, 3)}
    finally:
        await shutdown_all(nodes)


async def test_query_id_filter():
    net = LoopbackNetwork()
    nodes, subs = await make_cluster(net, 3, subscribe={1, 2})
    try:
        await join_all(nodes)
        await wait_until(lambda: all(len(alive_members(s)) == 3 for s in nodes))
        hits = []

        async def watcher(i):
            while True:
                ev = await subs[i].next()
                if isinstance(ev, QueryEvent) and ev.name == "targeted":
                    hits.append(i)
                    await ev.respond(b"yes")

        tasks = [asyncio.create_task(watcher(i)) for i in (1, 2)]
        resp = await nodes[0].query(
            "targeted", b"", QueryParam(filters=(IdFilter(("node-1",)),), timeout=2.0))
        results = [r.from_id async for r in resp.responses()]
        for t in tasks:
            t.cancel()
        assert results == ["node-1"]
        assert hits == [1]  # node-2 never saw it
    finally:
        await shutdown_all(nodes)


async def test_query_tag_filter():
    net = LoopbackNetwork()

    def opts_fn(i):
        role = "web" if i in (0, 1) else "db"
        return Options.local(tags=Tags(role=role))

    nodes, subs = await make_cluster(net, 3, subscribe={1, 2}, opts_fn=opts_fn)
    try:
        await join_all(nodes)
        await wait_until(lambda: all(len(alive_members(s)) == 3 for s in nodes))

        async def watcher(i):
            while True:
                ev = await subs[i].next()
                if isinstance(ev, QueryEvent) and ev.name == "webs":
                    await ev.respond(b"web-here")

        tasks = [asyncio.create_task(watcher(i)) for i in (1, 2)]
        resp = await nodes[0].query(
            "webs", b"", QueryParam(filters=(TagFilter("role", "^web$"),), timeout=2.0))
        results = sorted([r.from_id async for r in resp.responses()])
        for t in tasks:
            t.cancel()
        assert results == ["node-0", "node-1"] or results == ["node-1"]
        # node-0 also matches but never responds (it's the originator and has
        # no subscriber); node-2 (db) must not be in the results
        assert "node-2" not in results
    finally:
        await shutdown_all(nodes)


async def test_graceful_leave_events():
    net = LoopbackNetwork()
    nodes, subs = await make_cluster(net, 3, subscribe={0})
    try:
        await join_all(nodes)
        await wait_until(lambda: all(len(alive_members(s)) == 3 for s in nodes))
        await nodes[2].leave()
        assert nodes[2].state == SerfState.LEFT

        async def got_leave():
            while True:
                ev = await subs[0].next(timeout=DEADLINE)
                if isinstance(ev, MemberEvent) and ev.ty == MemberEventType.LEAVE:
                    return {m.node.id for m in ev.members}

        ids = await asyncio.wait_for(got_leave(), DEADLINE)
        assert ids == {"node-2"}
        ms = [m for m in nodes[0].members() if m.node.id == "node-2"][0]
        assert ms.status == MemberStatus.LEFT
    finally:
        await shutdown_all(nodes)


async def test_failed_member_and_force_leave():
    net = LoopbackNetwork()
    nodes, subs = await make_cluster(net, 3, subscribe={0})
    try:
        await join_all(nodes)
        await wait_until(lambda: all(len(alive_members(s)) == 3 for s in nodes))
        await nodes[2].shutdown()
        await wait_until(
            lambda: any(m.status == MemberStatus.FAILED
                        for m in nodes[0].members() if m.node.id == "node-2"),
            msg="node-2 marked failed")
        # force-leave flips failed -> left
        await nodes[0].remove_failed_node("node-2")
        await wait_until(
            lambda: all(
                any(m.node.id == "node-2" and m.status == MemberStatus.LEFT
                    for m in s.members())
                for s in nodes[:2]),
            msg="force-leave converts failed to left everywhere")
    finally:
        await shutdown_all(nodes[:2])


async def test_remove_failed_node_prune():
    net = LoopbackNetwork()
    nodes, _ = await make_cluster(net, 3)
    try:
        await join_all(nodes)
        await wait_until(lambda: all(len(alive_members(s)) == 3 for s in nodes))
        await nodes[2].shutdown()
        await wait_until(
            lambda: any(m.status == MemberStatus.FAILED
                        for m in nodes[0].members() if m.node.id == "node-2"))
        await nodes[0].remove_failed_node("node-2", prune=True)
        await wait_until(
            lambda: all(all(m.node.id != "node-2" for m in s.members())
                        for s in nodes[:2]),
            msg="prune erases the member everywhere")
    finally:
        await shutdown_all(nodes[:2])


async def test_set_tags_propagates_update_event():
    net = LoopbackNetwork()
    nodes, subs = await make_cluster(net, 3, subscribe={1})
    try:
        await join_all(nodes)
        await wait_until(lambda: all(len(alive_members(s)) == 3 for s in nodes))
        await nodes[0].set_tags(Tags(role="lead", dc="eu"))

        async def got_update():
            while True:
                ev = await subs[1].next(timeout=DEADLINE)
                if isinstance(ev, MemberEvent) and ev.ty == MemberEventType.UPDATE:
                    return ev.members[0]

        m = await asyncio.wait_for(got_update(), DEADLINE)
        assert m.node.id == "node-0"
        assert m.tags == Tags(role="lead", dc="eu")
        m0 = [m for m in nodes[2].members() if m.node.id == "node-0"][0]
        await wait_until(lambda: [m for m in nodes[2].members()
                                  if m.node.id == "node-0"][0].tags == Tags(role="lead", dc="eu"),
                         msg="tags visible on node-2")
    finally:
        await shutdown_all(nodes)


async def test_stats_and_queue_depths():
    net = LoopbackNetwork()
    nodes, _ = await make_cluster(net, 3)
    try:
        await join_all(nodes)
        await wait_until(lambda: all(len(alive_members(s)) == 3 for s in nodes))
        st = nodes[0].stats()
        assert st.members == 3
        assert st.member_time >= 1
        assert st.failed == 0
    finally:
        await shutdown_all(nodes)


async def test_coordinates_develop():
    net = LoopbackNetwork()
    net.latency_fn = lambda s, d: 0.01  # 10ms RTT one-way-ish
    nodes, _ = await make_cluster(net, 3)
    try:
        await join_all(nodes)
        await wait_until(lambda: all(len(alive_members(s)) == 3 for s in nodes))
        await wait_until(
            lambda: nodes[0].cached_coordinate("node-1") is not None,
            msg="coordinate learned from pings")
        c0 = nodes[0].coordinate()
        assert c0 is not None and c0.is_valid()
    finally:
        await shutdown_all(nodes)


async def test_rejoin_intent_refutes_leave():
    """A node that left can rejoin; join intent with newer ltime flips status
    back to alive everywhere (reference join-intent tests)."""
    net = LoopbackNetwork()
    nodes, _ = await make_cluster(net, 3)
    try:
        await join_all(nodes)
        await wait_until(lambda: all(len(alive_members(s)) == 3 for s in nodes))
        await nodes[2].leave()
        await nodes[2].shutdown()
        await wait_until(
            lambda: all(any(m.node.id == "node-2" and m.status == MemberStatus.LEFT
                            for m in s.members()) for s in nodes[:2]),
            msg="node-2 left everywhere")
        # restart node-2 on the same address and rejoin
        s2 = await Serf.create(net.bind("addr-2"), Options.local(), "node-2")
        nodes[2] = s2
        await s2.join("addr-0")
        await wait_until(
            lambda: all(len(alive_members(s)) == 3 for s in [nodes[0], nodes[1], s2]),
            msg="node-2 alive everywhere after rejoin")
    finally:
        await shutdown_all(nodes)


@pytest.mark.parametrize("host", ["127.0.0.1", "::1"])
async def test_net_transport_real_sockets(host):
    """Conformance: a serf cluster over real UDP/TCP, IPv4 and IPv6
    (the reference stamps its whole suite for both families)."""
    from serf_tpu.host.net import NetTransport
    try:
        t0 = await NetTransport.bind((host, 0))
    except OSError:
        pytest.skip(f"{host} unavailable")
    t1 = await NetTransport.bind((host, 0))
    s0 = await Serf.create(t0, Options.local(), "net-0")
    s1 = await Serf.create(t1, Options.local(), "net-1")
    try:
        await s1.join(t0.local_addr)
        await wait_until(lambda: s0.num_members() == 2 and s1.num_members() == 2,
                         msg="2-node convergence over real sockets")
        await s0.user_event("hello", b"udp", coalesce=False)
        await wait_until(lambda: s1.event_clock.time() >= 2,
                         msg="user event over real sockets")
    finally:
        await s0.shutdown()
        await s1.shutdown()


def _self_signed_cert(tmp_path, hostname="localhost"):
    """Generate a self-signed cert+key PEM pair (tests only)."""
    import datetime
    import ipaddress as ipa

    pytest.importorskip(
        "cryptography", reason="cryptography not installed in this image")
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, hostname)])
    san = x509.SubjectAlternativeName([
        x509.DNSName(hostname),
        x509.IPAddress(ipa.ip_address("127.0.0.1")),
        x509.IPAddress(ipa.ip_address("::1")),
    ])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=1))
            .add_extension(san, critical=False)
            .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                           critical=True)
            .sign(key, hashes.SHA256()))
    cert_pem = tmp_path / "cert.pem"
    key_pem = tmp_path / "key.pem"
    cert_pem.write_bytes(cert.public_bytes(serialization.Encoding.PEM))
    key_pem.write_bytes(key.private_bytes(
        serialization.Encoding.PEM, serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption()))
    return str(cert_pem), str(key_pem)


@pytest.mark.parametrize("host", ["127.0.0.1", "::1"])
@pytest.mark.parametrize("stream", ["tcp", "tls", "udpstream"])
async def test_net_transport_stream_variants(host, stream, tmp_path):
    """Conformance over real sockets for every stream plane: plain TCP,
    TLS-wrapped (the reference's NetTransport/TLS feature split), and the
    QUIC-slot datagram-stream transport (reliable streams over UDP),
    IPv4+IPv6."""
    from serf_tpu.host.dstream import DatagramStreamTransport
    from serf_tpu.host.net import NetTransport, TlsNetTransport, make_tls_contexts

    if stream == "tls":
        # one shared cluster cert (the single-cert self-signed deployment)
        cert, key = _self_signed_cert(tmp_path)

    async def bind(addr):
        if stream == "tcp":
            return await NetTransport.bind(addr)
        if stream == "udpstream":
            return await DatagramStreamTransport.bind(addr)
        server_ctx, client_ctx = make_tls_contexts(cert, key)
        return await TlsNetTransport.bind(addr, server_ctx=server_ctx,
                                          client_ctx=client_ctx)

    try:
        t0 = await bind((host, 0))
    except OSError:
        pytest.skip(f"{host} unavailable")
    t1 = await bind((host, 0))
    s0 = await Serf.create(t0, Options.local(), f"{stream}-0")
    s1 = await Serf.create(t1, Options.local(), f"{stream}-1")
    try:
        await s1.join(t0.local_addr)
        await wait_until(lambda: s0.num_members() == 2 and s1.num_members() == 2,
                         msg=f"2-node convergence over {stream}")
        await s0.user_event("hello", stream.encode(), coalesce=False)
        await wait_until(lambda: s1.event_clock.time() >= 2,
                         msg=f"user event over {stream}")
    finally:
        await s0.shutdown()
        await s1.shutdown()


async def test_join_resolves_dns_names():
    """The resolver seam: joins accept a hostname:port string and resolve it
    through the transport (reference Transport::Resolver)."""
    from serf_tpu.host.net import NetTransport

    t0 = await NetTransport.bind(("127.0.0.1", 0))
    t1 = await NetTransport.bind(("127.0.0.1", 0))
    s0 = await Serf.create(t0, Options.local(), "dns-0")
    s1 = await Serf.create(t1, Options.local(), "dns-1")
    try:
        port = t0.local_addr[1]
        await s1.join(f"localhost:{port}")
        await wait_until(lambda: s0.num_members() == 2 and s1.num_members() == 2,
                         msg="2-node convergence after DNS-resolved join")
        # unresolvable names fail loudly, not silently
        with pytest.raises(ConnectionError):
            await s1.memberlist.transport.resolve("no.such.host.invalid:1")
    finally:
        await s0.shutdown()
        await s1.shutdown()


async def test_resolver_address_forms():
    """resolve() handles bare IPv6 literals, bracketed IPv6:port, host:port,
    numeric pass-through, and malformed targets."""
    from serf_tpu.host.net import NetTransport

    t = await NetTransport.bind(("127.0.0.1", 0))
    try:
        assert await t.resolve(("127.0.0.1", 80)) == ("127.0.0.1", 80)
        assert await t.resolve("127.0.0.1:80") == ("127.0.0.1", 80)
        # bare IPv6 literal: NOT split at the last colon
        assert await t.resolve("::1") == "::1"
        assert await t.resolve("fe80::1") == "fe80::1"
        # bracketed IPv6 with port
        assert await t.resolve("[::1]:8080") == ("::1", 8080)
        with pytest.raises(ConnectionError):
            await t.resolve("host:notaport")
        # family constrained to the bound socket (IPv4 here)
        host, port = await t.resolve(f"localhost:9")
        assert host == "127.0.0.1" and port == 9
    finally:
        await t.shutdown()


async def test_key_manager_cluster_rotation():
    """Cluster-wide keyring orchestration (reference key_manager.rs):
    install a new key everywhere, rotate the primary, remove the old key,
    and keep gossiping through every stage."""
    from serf_tpu.host.keyring import SecretKeyring

    k1 = bytes(range(16))
    k2 = bytes(range(16, 32))
    net = LoopbackNetwork()
    nodes = []
    for i in range(3):
        s = await Serf.create(net.bind(f"k{i}"), Options.local(), f"node-{i}",
                              keyring=SecretKeyring(k1))
        nodes.append(s)
    try:
        for s in nodes[1:]:
            await s.join("k0")
        await wait_until(lambda: all(len(alive_members(s)) == 3 for s in nodes),
                         msg="3-node encrypted convergence")
        km = nodes[0].key_manager()
        assert km is not None

        out = await km.install_key(k2)
        assert out.num_resp == 3 and out.num_err == 0, out.messages
        await wait_until(
            lambda: all(k2 in s.memberlist.keyring().keys() for s in nodes),
            msg="k2 installed everywhere")

        out = await km.use_key(k2)
        assert out.num_resp == 3 and out.num_err == 0, out.messages
        await wait_until(
            lambda: all(s.memberlist.keyring().primary_key() == k2 for s in nodes),
            msg="k2 primary everywhere")

        out = await km.remove_key(k1)
        assert out.num_resp == 3 and out.num_err == 0, out.messages
        await wait_until(
            lambda: all(k1 not in s.memberlist.keyring().keys() for s in nodes),
            msg="k1 removed everywhere")

        # list aggregates per-node views: k2 is the unanimous primary
        out = await km.list_keys()
        assert out.num_resp == 3 and out.primary_keys == {k2: 3}
        assert out.keys == {k2: 3}

        # the cluster still works over the rotated key
        await nodes[1].user_event("rotated", b"ok", coalesce=False)
        await wait_until(lambda: all(s.event_clock.time() >= 2 for s in nodes),
                         msg="user event after rotation")
        # removing the active primary must fail loudly, not brick the cluster
        out = await km.remove_key(k2)
        assert out.num_err == 3
    finally:
        await shutdown_all(nodes)


async def test_corrupted_ping_payloads_rejected():
    """The reference's ping_versioning/ping_dimension corruption tests:
    bad ack payloads (wrong version, wrong dimensionality, garbage) must be
    rejected with the serf.coordinate.rejected metric — never crash the
    ping plane or poison the coordinate."""
    from serf_tpu.host.coordinate import Coordinate
    from serf_tpu.host.memberlist import NodeState
    from serf_tpu.host.serf import PING_VERSION
    from serf_tpu.types.member import Node
    from serf_tpu.utils import metrics

    net = LoopbackNetwork()
    s = await Serf.create(net.bind("ping"), Options.local(), "ping-node")
    try:
        dg = s.memberlist.delegate
        ns = NodeState(Node("peer", "x"))
        before = s.coord_client.get_coordinate()
        rejected0 = metrics.global_sink().counter("serf.coordinate.rejected", s._labels)

        good = Coordinate(portion=(0.01,) * 8, error=1.5,
                          adjustment=0.0, height=1e-5).encode()
        dg.notify_ping_complete(ns, 0.05, bytes([PING_VERSION + 1]) + good)
        dg.notify_ping_complete(ns, 0.05, bytes([PING_VERSION]) + b"\xff\x01garbage")
        # wrong dimensionality: a 2-d coordinate against the 8-d client
        bad_dim = Coordinate(portion=(1.0, 2.0))
        dg.notify_ping_complete(ns, 0.05, bytes([PING_VERSION]) + bad_dim.encode())
        dg.notify_ping_complete(ns, 0.0, bytes([PING_VERSION]) + good)  # zero rtt
        dg.notify_ping_complete(ns, 0.05, b"")                          # empty

        rejected = metrics.global_sink().counter("serf.coordinate.rejected", s._labels)
        assert rejected - rejected0 == 3   # version + garbage + dimension
        assert s.coord_client.get_coordinate() == before  # nothing applied
        assert "peer" not in s._coord_cache

        # a good payload still works after all the abuse
        dg.notify_ping_complete(ns, 0.05, bytes([PING_VERSION]) + good)
        assert "peer" in s._coord_cache
    finally:
        await s.shutdown()


async def test_pushpull_echo_of_self_does_not_broadcast():
    """Regression (round-4): a newer join intent about OURSELVES — the
    shape a push/pull ``status_ltimes`` echo takes — must be absorbed
    silently: adopt the ltime, stay ALIVE, queue NO broadcast, and leave
    the Lamport clock advanced only by the witness.  Rounds 2-3 turned
    every such echo into a "re-assert aliveness" join broadcast, which
    churned the clock during plain convergence and stomped equal-ltime
    leave races (the dangling-LEAVING sweep's domain)."""
    from serf_tpu.types.messages import JoinMessage

    net = LoopbackNetwork()
    s = await Serf.create(net.bind("echo"), Options.local(), "echo-node")
    try:
        me = s._members[s.local_id]
        echo_lt = me.status_time + 5
        depth_before = len(s.intent_broadcasts)
        tasks_before = len(asyncio.all_tasks())
        assert s._handle_node_join_intent(
            JoinMessage(echo_lt, s.local_id), rebroadcast=False) is True
        assert me.member.status == MemberStatus.ALIVE
        assert me.status_time == echo_lt
        # witness(echo_lt) makes time() == echo_lt + 1; anything larger
        # means an increment fired (i.e. a refutation/re-assert path ran)
        assert s.clock.time() == echo_lt + 1
        assert len(s.intent_broadcasts) == depth_before
        await asyncio.sleep(0.05)
        assert len(s.intent_broadcasts) == depth_before
        assert len(asyncio.all_tasks()) <= tasks_before + 1
    finally:
        await s.shutdown()


async def test_rejoin_via_stale_partner_converges():
    """The stale-partner rejoin corner (found by soak seeds 7/8): A leaves
    at ltime L; C restarts knowing A only as a left-members entry; A then
    rejoins THROUGH C, so A's clock never witnesses L and its join
    broadcast cannot beat stale LEAVING/LEFT states.  Convergence relies
    on memberlist notify_join revival plus left_members -> leave-intent
    self-refutation (base.rs:1468-1480); every view must reach ALIVE."""
    net = LoopbackNetwork()
    a = await Serf.create(net.bind("a"), Options.local(), "A")
    b = await Serf.create(net.bind("b"), Options.local(), "B")
    c = await Serf.create(net.bind("c"), Options.local(), "C")
    for s in (b, c):
        await s.join("a")
    await wait_until(lambda: all(len(alive_members(s)) == 3 for s in (a, b, c)),
                     msg="initial convergence")
    # C crashes, A leaves gracefully (only B knows the leave intent)
    await c.shutdown()
    await a.leave()
    await a.shutdown()
    await wait_until(lambda: b._members["A"].member.status == MemberStatus.LEFT,
                     msg="B sees A LEFT")

    # C restarts fresh, learns of A only via B's left_members
    c2 = await Serf.create(net.bind("c"), Options.local(), "C")
    await c2.join("b")
    await asyncio.sleep(0.3)

    # A restarts fresh and rejoins through the STALE partner C
    a2 = await Serf.create(net.bind("a"), Options.local(), "A")
    await a2.join("c")

    def all_alive():
        for s in (a2, b, c2):
            ms = s._members.get("A")
            if ms is None or ms.member.status != MemberStatus.ALIVE:
                return False
        return True

    await wait_until(all_alive, deadline=15.0,
                     msg="every view shows A ALIVE after stale-partner rejoin")


async def test_join_intent_revives_left_not_failed():
    """A join intent strictly newer than a graceful leave revives the LEFT
    member (it can only mean a rejoin — the leaver's own clock put the
    leave above all its earlier joins); a FAILED member is NOT revived by
    intents (the failure detector's judgment wins).  Found by soak seed 7:
    without the revival, the node keeps exporting the member in push/pull
    left_members stamped with the NEW ltime, poisoning freshly-joined
    peers with an unbeatable LEAVING state."""
    from serf_tpu.host.memberlist import NodeState
    from serf_tpu.types.member import Node
    from serf_tpu.types.messages import JoinMessage, LeaveMessage

    net = LoopbackNetwork()
    s = await Serf.create(net.bind("r"), Options.local(), "rev-node")
    try:
        # LEFT member at ltime 13
        s._handle_node_join(NodeState(Node("peer", "p")))
        s._handle_node_leave_intent(LeaveMessage(13, "peer"))
        from serf_tpu.host.memberlist import SwimState
        ns = NodeState(Node("peer", "p"))
        ns.state = SwimState.LEFT
        s._handle_node_leave(ns)
        assert s._members["peer"].member.status == MemberStatus.LEFT
        assert any(m.id == "peer" for m in s._left)
        # newer join intent: revive + drop from the left list
        assert s._handle_node_join_intent(JoinMessage(21, "peer")) is True
        assert s._members["peer"].member.status == MemberStatus.ALIVE
        assert s._members["peer"].status_time == 21
        assert not any(m.id == "peer" for m in s._left)

        # FAILED member: a newer join intent updates the ltime only
        s._handle_node_join(NodeState(Node("crashy", "c")))
        ns2 = NodeState(Node("crashy", "c"))
        ns2.state = SwimState.DEAD
        s._handle_node_leave(ns2)
        assert s._members["crashy"].member.status == MemberStatus.FAILED
        s._handle_node_join_intent(JoinMessage(30, "crashy"))
        assert s._members["crashy"].member.status == MemberStatus.FAILED
    finally:
        await s.shutdown()


async def test_zombie_revival_demoted_by_reaper():
    """If a LEFT member is revived by a newer join intent but the rejoiner
    died before its memberlist aliveness arrived, the reaper's zombie sweep
    demotes it back to FAILED (two unbacked sweeps of grace), restoring the
    reap/reconnect path."""
    import dataclasses

    from serf_tpu.host.memberlist import NodeState, SwimState
    from serf_tpu.options import MemberlistOptions
    from serf_tpu.types.member import Node
    from serf_tpu.types.messages import JoinMessage, LeaveMessage

    net = LoopbackNetwork()
    # compress reap + push/pull so the REAL reaper loop demotes within the
    # test budget (grace = max(2*reap, 10*push_pull) = 0.2 s)
    opts = dataclasses.replace(
        Options.local(), reap_interval=0.05,
        memberlist=dataclasses.replace(MemberlistOptions.local(),
                                       push_pull_interval=0.02))
    s = await Serf.create(net.bind("z"), opts, "z-node")
    try:
        s._handle_node_join(NodeState(Node("ghost", "g")))
        s._handle_node_leave_intent(LeaveMessage(13, "ghost"))
        ns = NodeState(Node("ghost", "g"))
        ns.state = SwimState.LEFT
        s._handle_node_leave(ns)
        # memberlist still records ghost as LEFT; the newer join intent
        # revives the serf entry with no live backing
        s.memberlist._nodes["ghost"] = ns
        s._handle_node_join_intent(JoinMessage(21, "ghost"))
        assert s._members["ghost"].member.status == MemberStatus.ALIVE

        # a backed member must never be demoted (control)
        s._handle_node_join(NodeState(Node("ok", "o")))
        s.memberlist._nodes["ok"] = NodeState(Node("ok", "o"),
                                              state=SwimState.ALIVE)

        # the REAL reaper loop demotes the unbacked ghost past the grace
        await wait_until(
            lambda: s._members["ghost"].member.status == MemberStatus.FAILED,
            deadline=5.0, msg="zombie demoted by the reaper loop")
        assert any(m.id == "ghost" for m in s._failed)
        assert s._members["ok"].member.status == MemberStatus.ALIVE

        # an unbacked LEAVING member (newer leave intent on a revived
        # ghost) is demoted too — LEAVING->LEFT needs a notify_leave that
        # can never fire without backing
        s._handle_node_join(NodeState(Node("ghost2", "g2")))
        s._handle_node_leave_intent(LeaveMessage(5, "ghost2"))
        ns3 = NodeState(Node("ghost2", "g2"))
        ns3.state = SwimState.LEFT
        s._handle_node_leave(ns3)
        s.memberlist._nodes["ghost2"] = ns3
        s._handle_node_join_intent(JoinMessage(9, "ghost2"))   # revive
        s._handle_node_leave_intent(LeaveMessage(11, "ghost2"))  # LEAVING
        assert s._members["ghost2"].member.status == MemberStatus.LEAVING
        await wait_until(
            lambda: s._members["ghost2"].member.status == MemberStatus.FAILED,
            deadline=5.0, msg="unbacked LEAVING demoted")
    finally:
        await s.shutdown()
