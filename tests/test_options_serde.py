"""Options serde: humantime durations + JSON/TOML round-trips (the
reference's serde feature, serf-core/src/options.rs:55, 567-590)."""

import dataclasses

import pytest

from serf_tpu.options import (
    MemberlistOptions,
    Options,
    format_duration,
    parse_duration,
)
from serf_tpu.types.tags import Tags


@pytest.mark.parametrize("text,want", [
    ("500ms", 0.5),
    ("24h", 86400.0),
    ("1h30m", 5400.0),
    ("2.5s", 2.5),
    ("1d", 86400.0),
    ("250us", 0.00025),
    ("0s", 0.0),
    ("5", 5.0),          # bare number = seconds
    ("0.25", 0.25),
    (3.0, 3.0),          # numbers pass through
    (0, 0.0),
])
def test_parse_duration_vectors(text, want):
    assert parse_duration(text) == pytest.approx(want)


@pytest.mark.parametrize("bad", ["", "5x", "h", "1h30", "-5s", -1, None])
def test_parse_duration_rejects_garbage(bad):
    with pytest.raises(ValueError):
        parse_duration(bad)


@pytest.mark.parametrize("seconds", [0.0, 0.5, 2.5, 60.0, 5400.0, 86400.0,
                                     0.025, 0.00025, 90061.5])
def test_format_parse_round_trip(seconds):
    assert parse_duration(format_duration(seconds)) == pytest.approx(seconds)


def test_format_duration_is_humantime_style():
    assert format_duration(86400.0) == "1d"
    assert format_duration(5400.0) == "1h30m"
    assert format_duration(0.5) == "500ms"
    assert format_duration(0.0) == "0s"


def _sample_options():
    return Options(
        reconnect_timeout=3600.0,
        tombstone_timeout=5400.0,
        max_user_event_size=777,
        rejoin_after_leave=True,
        snapshot_path="/tmp/snap.db",
        tags=Tags(role="web", dc="eu-1"),
        memberlist=dataclasses.replace(
            MemberlistOptions.lan(),
            gossip_interval=0.025,
            compression="zlib",
            checksum="crc32",
            metric_labels={"env": "test"},
        ),
    )


def test_json_round_trip():
    opts = _sample_options()
    back = Options.from_json(opts.to_json())
    assert back == opts
    # durations serialized as humantime strings, not floats
    assert '"tombstone_timeout": "1h30m"' in opts.to_json()


def test_toml_round_trip():
    pytest.importorskip(
        "tomllib", reason="tomllib requires Python 3.11+")
    opts = _sample_options()
    text = opts.to_toml()
    assert 'tombstone_timeout = "1h30m"' in text
    assert "[memberlist]" in text and "[tags]" in text
    back = Options.from_toml(text)
    assert back == opts


def test_default_options_round_trip_both_formats():
    pytest.importorskip(
        "tomllib", reason="tomllib requires Python 3.11+")
    opts = Options()
    assert Options.from_json(opts.to_json()) == opts
    assert Options.from_toml(opts.to_toml()) == opts


def test_durations_accept_plain_seconds():
    o = Options.from_dict({"broadcast_timeout": 2,
                           "memberlist": {"probe_timeout": 0.25}})
    assert o.broadcast_timeout == 2.0
    assert o.memberlist.probe_timeout == 0.25


def test_unknown_keys_fail_loudly():
    with pytest.raises(ValueError, match="unknown Options keys"):
        Options.from_dict({"broadcast_timeoutt": "5s"})
    with pytest.raises(ValueError, match="unknown MemberlistOptions keys"):
        Options.from_dict({"memberlist": {"gossip_intervall": "5ms"}})


def test_loaded_options_validate_and_run():
    pytest.importorskip(
        "tomllib", reason="tomllib requires Python 3.11+")
    """A config file's options must be usable end-to-end."""
    o = Options.from_toml(_sample_options().to_toml())
    o.validate()
