"""Durable death records (GossipState.tombstone): the cluster must not
FORGET a detected death when the fact ring recycles under sustained
load — the device analog of the reference's member table holding FAILED
after the broadcast queue drains (base.rs:1375-1440).  Found by the
round-5 200k sustained validation: detection_complete flipped back to
False once the rotating user events overwrote the death declarations."""

import functools

import jax
import jax.numpy as jnp

from serf_tpu.models.churn import ChurnConfig, churn_round
from serf_tpu.models.dissemination import (
    GossipConfig,
    K_ALIVE,
    K_DEAD,
    K_USER_EVENT,
    inject_fact,
    inject_facts_batch,
    make_state,
)
from serf_tpu.models.failure import believed_dead, detection_complete
from serf_tpu.models.swim import (
    flagship_config,
    make_cluster,
    run_cluster_sustained,
)


def test_detection_survives_ring_recycling_under_sustained_load():
    """Seeded deaths stay detected long after their declarations' ring
    slots were overwritten by the sustained event stream."""
    cfg = flagship_config(2048, k_facts=32)
    st = make_cluster(cfg, jax.random.key(0))
    g = st.gossip
    dead = [101, 700, 1500]
    g = g._replace(alive=g.alive.at[jnp.asarray(dead)].set(False))
    st = st._replace(gossip=g)
    # 1 event/round: slot lifetime 32 rounds stays above the 16-round
    # transmit limit (the ADVICE-r5 headroom check sustained_round
    # enforces) while the ring still recycles many times below
    run = jax.jit(functools.partial(run_cluster_sustained, cfg=cfg,
                                    events_per_round=1),
                  static_argnames=("num_rounds",))
    # 200 rounds at 1 event/round cycles the 32-slot ring ~6 times:
    # every detection-era fact has long been retired
    st = run(st, key=jax.random.key(1), num_rounds=200)
    g = st.gossip
    assert bool(jnp.all(g.tombstone[jnp.asarray(dead)])), \
        "retired death declarations did not fold into the tombstone"
    assert bool(detection_complete(g, cfg.gossip, cfg.failure)), \
        "cluster forgot detected deaths after ring recycling"
    # and the detector is NOT re-declaring them every cycle: no live
    # K_DEAD facts for tombstoned subjects should keep appearing (the
    # ring is all user events by now)
    live_dead_facts = int(jnp.sum((g.facts.kind == K_DEAD) & g.facts.valid))
    assert live_dead_facts == 0, \
        f"{live_dead_facts} dead facts still being re-declared"


def test_rejoin_clears_tombstone():
    """A rejoiner (K_ALIVE injection with bumped incarnation) clears its
    durable death record — the reference's refutation/rejoin path."""
    cfg = flagship_config(1024, k_facts=32)
    st = make_cluster(cfg, jax.random.key(0))
    g = st.gossip._replace(alive=st.gossip.alive.at[77].set(False))
    st = st._replace(gossip=g)
    # 1 event/round: lifetime headroom over the transmit limit (see above)
    run = jax.jit(functools.partial(run_cluster_sustained, cfg=cfg,
                                    events_per_round=1),
                  static_argnames=("num_rounds",))
    st = run(st, key=jax.random.key(1), num_rounds=120)
    g = st.gossip
    assert bool(g.tombstone[77])
    # revive through the churn path's exact mechanics (alive + bumped
    # incarnation + K_ALIVE fact)
    g = g._replace(alive=g.alive.at[77].set(True),
                   incarnation=g.incarnation.at[77].add(1))
    g = inject_fact(g, cfg.gossip, subject=77, kind=K_ALIVE,
                    incarnation=int(g.incarnation[77]), ltime=999,
                    origin=77)
    assert not bool(g.tombstone[77]), "K_ALIVE did not clear the tombstone"
    assert not bool(believed_dead(g, cfg.gossip, cfg.failure)[77])


def test_partial_dissemination_retirement_drops_record():
    """A K_DEAD fact retired before full dissemination does NOT set the
    tombstone (the documented compression: per-knower splits cannot be
    represented once the evidence is gone) — the detector re-suspects."""
    cfg = GossipConfig(n=256, k_facts=32)
    g = make_state(cfg)
    g = g._replace(alive=g.alive.at[9].set(False))
    # a declaration known ONLY by its declarer, then overwrite the whole
    # ring so it retires while partially disseminated
    g = inject_fact(g, cfg, subject=9, kind=K_DEAD, incarnation=1,
                    ltime=1, origin=0)
    for i in range(cfg.k_facts):
        g = inject_fact(g, cfg, subject=1000 + i, kind=K_USER_EVENT,
                        incarnation=0, ltime=10 + i, origin=0)
    assert not bool(g.tombstone[9]), \
        "partially-spread death must not fold into the tombstone"


def test_refuted_death_never_folds_into_tombstone():
    """A FALSE declaration the subject refuted (incarnation bumped above
    it) must not fold at retirement — otherwise a live node would be
    durably recorded dead with no clearing path (review finding)."""
    cfg = GossipConfig(n=256, k_facts=32)
    g = make_state(cfg)
    # false K_DEAD about ALIVE node 9 at its current incarnation (1)
    g = inject_fact(g, cfg, subject=9, kind=K_DEAD, incarnation=1,
                    ltime=1, origin=0)
    # ... which fully disseminates
    g = g._replace(known=g.known.at[:, 0].set(
        g.known[:, 0] | jnp.uint32(1)))
    # node 9 refutes: incarnation above the declaration + alive fact
    g = g._replace(incarnation=g.incarnation.at[9].set(2))
    g = inject_fact(g, cfg, subject=9, kind=K_ALIVE, incarnation=2,
                    ltime=2, origin=9)
    # recycle the ring so the stale covered declaration retires
    for i in range(cfg.k_facts):
        g = inject_fact(g, cfg, subject=500 + i, kind=K_USER_EVENT,
                        incarnation=0, ltime=10 + i, origin=0)
    assert not bool(g.tombstone[9]), \
        "refuted death folded into the tombstone"
    assert not bool(believed_dead(g, cfg, cfg_failure())[9])


def cfg_failure():
    from serf_tpu.models.failure import FailureConfig
    return FailureConfig()


def test_batch_retirement_folds_covered_deaths():
    """inject_facts_batch retirement path: a fully-known K_DEAD fact in
    the overwritten slots folds in; K_ALIVE batches clear subjects."""
    cfg = GossipConfig(n=128, k_facts=32)
    g = make_state(cfg)
    g = g._replace(alive=g.alive.at[5].set(False))
    g = inject_fact(g, cfg, subject=5, kind=K_DEAD, incarnation=1,
                    ltime=1, origin=0)
    # everyone learns it (set the known bit everywhere by brute force)
    word, bit = 0, 0
    g = g._replace(known=g.known.at[:, word].set(
        g.known[:, word] | jnp.uint32(1 << bit)))
    # overwrite the whole ring in ONE batch (wraps past slot 0)
    m = cfg.k_facts
    g = inject_facts_batch(
        g, cfg, subjects=jnp.arange(m, dtype=jnp.int32) + 500,
        kind=K_USER_EVENT, incarnations=jnp.zeros((m,), jnp.uint32),
        ltimes=jnp.arange(m, dtype=jnp.uint32) + 10,
        origins=jnp.zeros((m,), jnp.int32), active=jnp.ones((m,), bool))
    assert bool(g.tombstone[5])
    # an alive batch for subject 5 clears it
    g = inject_facts_batch(
        g, cfg, subjects=jnp.asarray([5], jnp.int32), kind=K_ALIVE,
        incarnations=jnp.asarray([2], jnp.uint32),
        ltimes=jnp.asarray([99], jnp.uint32),
        origins=jnp.asarray([5], jnp.int32),
        active=jnp.ones((1,), bool))
    assert not bool(g.tombstone[5])


def test_cluster_stats_counts_tombstoned_dead():
    """The operator Stats snapshot must not forget retired deaths either
    (reference Stats reads the member table, api.rs:586-602)."""
    from serf_tpu.models.views import cluster_stats

    cfg = GossipConfig(n=128, k_facts=32)
    g = make_state(cfg)
    g = g._replace(alive=g.alive.at[5].set(False),
                   tombstone=g.tombstone.at[5].set(True))
    st = cluster_stats(g, cfg)
    assert int(st.declared_dead) == 1
    assert int(st.failed) == 1


def test_churn_rejoin_clears_tombstone_in_composition():
    """End-to-end through churn_round: a tombstoned node rejoining via
    the churn process is no longer believed dead."""
    cfg = flagship_config(512, k_facts=32)
    st = make_cluster(cfg, jax.random.key(0))
    g = st.gossip._replace(alive=st.gossip.alive.at[33].set(False),
                           tombstone=st.gossip.tombstone.at[33].set(True))
    ccfg = ChurnConfig(rejoin_rate=1.0, max_events=4)
    # rejoin_rate=1: node 33 (the only dead one) rejoins this round
    g2, _ = churn_round(g, cfg.gossip, ccfg, jax.random.key(7))
    assert bool(g2.alive[33])
    assert not bool(g2.tombstone[33])
