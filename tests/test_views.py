"""Device-plane operator views: cluster_stats vs ground truth, and the
string-tags → tag-plane bridge driving the query engine (host TagFilter
parity)."""

import functools

import jax
import jax.numpy as jnp

from serf_tpu.models.dissemination import (
    GossipConfig,
    K_JOIN,
    K_LEAVE,
    K_SUSPECT,
    K_USER_EVENT,
    inject_fact,
    make_state,
    round_step,
)
from serf_tpu.models.query import (
    QueryConfig,
    launch_query,
    make_queries,
    query_round,
)
from serf_tpu.models.views import ClusterStats, TagInterner, cluster_stats


def test_cluster_stats_counts_match_ground_truth():
    cfg = GossipConfig(n=128, k_facts=32)
    s = make_state(cfg)._replace(
        alive=jnp.ones((128,), bool).at[5].set(False).at[9].set(False))
    s = inject_fact(s, cfg, 7, K_SUSPECT, 1, 3, 0)
    s = inject_fact(s, cfg, 8, K_SUSPECT, 1, 4, 0)
    s = inject_fact(s, cfg, 7, K_SUSPECT, 2, 5, 1)   # same subject twice
    s = inject_fact(s, cfg, 20, K_JOIN, 0, 6, 2)
    s = inject_fact(s, cfg, 21, K_LEAVE, 0, 7, 3)
    s = inject_fact(s, cfg, 1, K_USER_EVENT, 0, 8, 4)

    st = jax.jit(functools.partial(cluster_stats, cfg=cfg))(s)
    st = ClusterStats(*(int(x) for x in jax.device_get(st)))
    assert st.members == 126 and st.failed == 2
    assert st.suspected == 2           # subjects 7 and 8 (dedup by subject)
    assert st.leaving == 1
    assert st.intent_facts == 2
    assert st.event_facts == 1
    assert st.query_facts == 0
    assert st.queue_depth == 6         # every live fact still has budget
    assert st.max_ltime == 8
    assert st.round == 0


def test_cluster_stats_queue_drains():
    cfg = GossipConfig(n=64, k_facts=32)
    s = inject_fact(make_state(cfg), cfg, 0, K_USER_EVENT, 0, 1, 0)
    step = jax.jit(functools.partial(round_step, cfg=cfg))
    key = jax.random.key(0)
    for _ in range(200):
        key, k2 = jax.random.split(key)
        s = step(s, key=k2)
    st = cluster_stats(s, cfg)
    assert int(st.queue_depth) == 0    # budgets exhausted after convergence
    assert int(st.event_facts) == 1    # the fact itself is still resident


def test_tag_interner_plane_and_regex_filter():
    interner = TagInterner(["role", "dc"])
    tags = [{"role": "web", "dc": "us-1"},
            {"role": "db", "dc": "us-1"},
            {"role": "web-canary"},
            None,
            {"dc": "eu-2"}]
    plane = interner.plane(tags)
    assert plane.shape == (5, 2)
    assert int(plane[3, 0]) == TagInterner.ABSENT

    # reference-style regex filter: role ~ "^web"
    mask = interner.filter_mask(plane, "role", r"^web")
    assert [bool(x) for x in mask] == [True, False, True, False, False]
    # exact match
    mask = interner.filter_mask(plane, "role", r"^db$")
    assert [bool(x) for x in mask] == [False, True, False, False, False]
    # unknown key: nobody matches
    assert not bool(jnp.any(interner.filter_mask(plane, "zone", ".*")))


def test_tag_interner_drives_device_query_like_host_tagfilter():
    """End-to-end: regex tag filter -> interned mask -> device query; the
    responder set equals what the host TagFilter would accept."""
    from serf_tpu.types.filters import TagFilter
    from serf_tpu.types.tags import Tags

    n = 64
    interner = TagInterner(["role"])
    node_tags = [{"role": "web"} if i % 3 == 0 else
                 {"role": "db"} if i % 3 == 1 else None
                 for i in range(n)]
    plane = interner.plane(node_tags)

    cfg = GossipConfig(n=n, k_facts=32)
    qcfg = QueryConfig(q_slots=2)
    g, qs = make_state(cfg), make_queries(cfg, qcfg)
    g, qs, qi = launch_query(g, qs, cfg, qcfg, origin=0,
                             eligible=interner.filter_mask(plane, "role",
                                                           r"^(web|db)$"))
    step = jax.jit(functools.partial(round_step, cfg=cfg))
    key = jax.random.key(1)
    for _ in range(30):
        key, k1, k2 = jax.random.split(key, 3)
        g = step(g, key=k1)
        qs = query_round(g, qs, cfg, qcfg, k2)

    device_responders = {int(i) for i in jnp.nonzero(qs.responded[int(qi)])[0]}
    host_filter = TagFilter("role", r"^(web|db)$")
    host_responders = {
        i for i in range(n)
        if host_filter.matches(f"node-{i}",
                               Tags(node_tags[i]) if node_tags[i] else None)}
    assert device_responders == host_responders
