"""Native C++ codec scanner: build, parity with the Python oracle, fuzz.

The analog of the reference's per-type round-trip + fuzz strategy applied
across the two implementations: for any input, the native scanner and the
pure-Python loop must produce identical field tables or identical failures.
"""

import random

import pytest

from serf_tpu import codec
from serf_tpu.codec import _native


def _python_iter(buf):
    """The pure-Python field loop, bypassing the native dispatch."""
    out = []
    pos, end = 0, len(buf)
    while pos < end:
        key, pos = codec.decode_varint(buf, pos)
        field, wt = codec.split_tag(key)
        if wt == codec.WT_VARINT:
            value, pos = codec.decode_varint(buf, pos)
        elif wt == codec.WT_FIXED64:
            if pos + 8 > end:
                raise codec.DecodeError("truncated fixed64")
            value = buf[pos:pos + 8]
            pos += 8
        elif wt == codec.WT_LENGTH_DELIMITED:
            ln, pos = codec.decode_varint(buf, pos)
            if pos + ln > end:
                raise codec.DecodeError("truncated length-delimited field")
            value = buf[pos:pos + ln]
            pos += ln
        elif wt == codec.WT_FIXED32:
            if pos + 4 > end:
                raise codec.DecodeError("truncated fixed32")
            value = buf[pos:pos + 4]
            pos += 4
        else:
            raise codec.DecodeError(f"unknown wire type {wt}")
        out.append((field, wt, value))
    return out


needs_native = pytest.mark.skipif(_native.load() is None,
                                  reason="native codec unavailable (no g++?)")


@pytest.fixture(autouse=True)
def _always_dispatch_native(monkeypatch):
    """The size gate (NATIVE_SCAN_MIN_BYTES) routes small bodies to Python;
    these tests exist to exercise the native dispatch, so disable the gate."""
    monkeypatch.setattr(codec, "NATIVE_SCAN_MIN_BYTES", 0)


@needs_native
def test_native_builds_and_loads():
    assert _native.load() is not None


@needs_native
def test_native_varint_parity():
    lib = _native.load()
    import ctypes
    for v in [0, 1, 127, 128, 300, 2**32 - 1, 2**63 - 1, 2**64 - 1]:
        out = (ctypes.c_ubyte * 10)()
        n = lib.serf_varint_encode(v, out)
        assert bytes(out[:n]) == codec.encode_varint(v)
        val = ctypes.c_uint64()
        used = lib.serf_varint_decode(bytes(out[:n]), n, ctypes.byref(val))
        assert used == n and val.value == v


@needs_native
def test_native_scan_parity_on_valid_messages():
    from serf_tpu.types.messages import QueryMessage, QueryFlag, encode_message
    from serf_tpu.types.member import Node

    rng = random.Random(1)
    for _ in range(200):
        msg = QueryMessage(
            ltime=rng.getrandbits(48), id=rng.getrandbits(32),
            from_node=Node(f"n{rng.randrange(100)}", ("h", rng.randrange(1, 65536))),
            flags=QueryFlag(rng.randint(0, 3)), relay_factor=rng.randint(0, 9),
            timeout_ns=rng.getrandbits(40), name="q" * rng.randint(1, 9),
            payload=bytes(rng.randrange(256) for _ in range(rng.randint(0, 50))))
        body = encode_message(msg)[1:]
        native = _native.scan_fields(body, 0, len(body))
        py = _python_iter(body)
        assert native != -1
        assert [(f, w, v) for f, w, v, _ in native] == py


@needs_native
def test_native_scan_parity_fuzz():
    """Random bytes: both implementations accept with identical results or
    both reject."""
    rng = random.Random(7)
    for _ in range(3000):
        buf = bytes(rng.randrange(256) for _ in range(rng.randint(0, 60)))
        native = _native.scan_fields(buf, 0, len(buf))
        try:
            py = _python_iter(buf)
            assert native != -1, f"python accepted, native rejected: {buf.hex()}"
            assert [(f, w, v) for f, w, v, _ in native] == py
        except codec.DecodeError:
            assert native == -1, f"python rejected, native accepted: {buf.hex()}"


@needs_native
def test_decode_message_uses_native_and_agrees():
    """End-to-end: full message decoding with native on vs off must agree."""
    from serf_tpu.types.messages import (JoinMessage, PushPullMessage,
                                         UserEvents, UserEventMessage,
                                         encode_message, decode_message)

    msgs = [
        JoinMessage(5, "node-a"),
        PushPullMessage(7, {"a": 1, "b": 2}, ("x",), 3,
                        (UserEvents(2, (UserEventMessage(2, "e", b"p"),)),), 4),
    ]
    for m in msgs:
        wire = encode_message(m)
        with_native = decode_message(wire)
        saved = _native._lib, _native._tried
        _native._lib, _native._tried = None, True
        try:
            without_native = decode_message(wire)
        finally:
            _native._lib, _native._tried = saved
        assert with_native == without_native == m


@needs_native
def test_oversized_end_fails_closed():
    """end > len(buf) must never reach C with an oversized length
    (review finding: out-of-bounds read)."""
    buf = bytes([0x08, 0x01])
    assert list(codec.iter_fields(buf, 0, 10)) == [(1, 0, 1, 2)]
    with pytest.raises(codec.DecodeError):
        list(codec.iter_fields(bytes([0x08]), 0, 10))  # truncated varint


@needs_native
def test_bytearray_and_memoryview_inputs():
    """Mutable recv buffers must decode identically to bytes (review finding)."""
    from serf_tpu.types.messages import JoinMessage, encode_message
    wire = encode_message(JoinMessage(9, "n"))
    for cast in (bytes, bytearray, memoryview):
        out = list(codec.iter_fields(cast(wire[1:])))
        assert [(f, w, v) for f, w, v, _ in out] == \
            [(f, w, v) for f, w, v, _ in codec.iter_fields(wire[1:])]


@needs_native
def test_bounded_end_parity():
    """iter_fields with end < len(buf) must not read varints past end, and
    native/python must agree (review finding)."""
    buf = bytes([0x08, 0xFF, 0x01, 0x00])
    with pytest.raises(codec.DecodeError):
        list(codec.iter_fields(buf, 0, 2))
    import serf_tpu.codec._native as nat
    saved = nat._lib, nat._tried
    nat._lib, nat._tried = None, True
    try:
        with pytest.raises(codec.DecodeError):
            list(codec.iter_fields(buf, 0, 2))
    finally:
        nat._lib, nat._tried = saved


@needs_native
def test_new_pos_tracking():
    """The 4th tuple element is a real resume position on both paths."""
    body = (codec.encode_varint_field(1, 300)
            + codec.encode_bytes_field(2, b"xyz")
            + codec.encode_varint_field(3, 7))
    native = list(codec.iter_fields(body))
    import serf_tpu.codec._native as nat
    saved = nat._lib, nat._tried
    nat._lib, nat._tried = None, True
    try:
        py = list(codec.iter_fields(body))
    finally:
        nat._lib, nat._tried = saved
    assert native == py
