"""Quarter-deferred stamp flushes (ISSUE 18): the semantics contract.

- Derived views (known plane, coverage, detection outcomes, the
  selection predicate) are bit-exact vs the per-round flavor EVERY
  round; the packed stamp plane itself is bit-exact at flush
  boundaries (overlay drained) — for both stamp flavors and, sharded,
  both ICI schedules (heavy crosses ride ``-m slow``).
- ``stamp_flush_unit=1`` is the inert default: the overlay/last_flush
  leaves are never read (mangling them changes no other leaf).
- Wrap/clamp edges: a cohort crossing the mod-16 quarter wrap and a
  cohort whose flush carries the standalone clamp stay view-exact.
- A mid-cohort checkpoint (overlay pending) restores bit-exactly and
  the continued run matches the uninterrupted one.
- STAMP_UNIT as a live knob: the control law actuates both directions
  within its clamps, and a traced mid-run cadence change keeps the
  views bit-exact.
- The watchdog's ``stamp_staleness_ok`` invariant is green on a
  deferred sustained run.
- The ``fused_flush`` kernel is leaf-exact with ``flush_stamp_pass``
  (interpret mode); the standalone kernel family refuses deferred
  configs loudly at dispatch.
- The byte model: deferred @1M breaks the round-8 217 MB floor
  (flush + overlay decomposition pinned; per-round unchanged).

Budget discipline: everything is small-N; redundant flavor crosses
ride ``-m slow``.
"""

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from serf_tpu.models.dissemination import (
    GossipConfig,
    K_USER_EVENT,
    STAMP_UNIT,
    coverage,
    flush_stamp_pass,
    inject_fact,
    make_state,
    mod_age,
    pallas_dispatch_mode,
    round_q,
    round_step,
    select_words,
    stamp_nibbles,
    unpack_bits,
)
from serf_tpu.models.failure import FailureConfig, believed_dead
from serf_tpu.models.swim import (
    ClusterConfig,
    cluster_round,
    make_cluster,
    run_cluster_sustained,
)


def _cfg(n=96, pack=True, unit=4, cache=True, schedule="ring"):
    return ClusterConfig(
        gossip=GossipConfig(n=n, k_facts=32, peer_sampling="rotation",
                            pack_stamp=pack, stamp_flush_unit=unit,
                            use_sendable_cache=cache),
        failure=FailureConfig(suspicion_rounds=8, max_new_facts=8,
                              probe_schedule="round_robin"),
        push_pull_every=8, probe_every=2, exchange_schedule=schedule)


def _seeded(cfg):
    st = make_cluster(cfg, jax.random.key(0))
    g = inject_fact(st.gossip, cfg.gossip, subject=3, kind=K_USER_EVENT,
                    incarnation=0, ltime=5, origin=0)
    # two silent crashes so detection outcomes are part of the parity
    g = g._replace(alive=g.alive.at[jnp.asarray([7, cfg.gossip.n // 2])]
                   .set(False))
    return st._replace(gossip=g)


def _assert_views_equal(gd, gp, gcfg_d, gcfg_p, fcfg, ctx=""):
    """The derived-view oracle: everything a protocol consumer can
    observe must match between the deferred and per-round states."""
    for name in ("known", "alive", "tombstone", "round", "incarnation",
                 "next_slot", "overflow", "injected", "last_learn"):
        assert bool(jnp.all(getattr(gd, name) == getattr(gp, name))), \
            f"{name} diverged {ctx}"
    assert bool(jnp.all(select_words(gd, gcfg_d)
                        == select_words(gp, gcfg_p))), \
        f"selection predicate diverged {ctx}"
    assert bool(jnp.all(coverage(gd, gcfg_d) == coverage(gp, gcfg_p))), \
        f"coverage diverged {ctx}"
    assert bool(jnp.all(believed_dead(gd, gcfg_d, fcfg)
                        == believed_dead(gp, gcfg_p, fcfg))), \
        f"believed_dead diverged {ctx}"


def _assert_stamps_equal_where_known(gd, gp, gcfg):
    k = gcfg.k_facts
    kb = unpack_bits(gd.known, k)
    nd = stamp_nibbles(gd.stamp, k, gcfg.pack_stamp)
    np_ = stamp_nibbles(gp.stamp, k, gcfg.pack_stamp)
    assert bool(jnp.all(jnp.where(kb, nd == np_, True)))


# ---------------------------------------------------------------------------
# cluster-level lockstep: views exact every round, stamps at boundaries
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("pack,unit", [
    (True, 4),
    (False, 4),
    pytest.param(True, 2, marks=pytest.mark.slow),
    pytest.param(False, 2, marks=pytest.mark.slow),
])
def test_deferred_cluster_views_bit_exact(pack, unit):
    """Full protocol rounds (gossip + probes + declare + push/pull +
    Vivaldi) in lockstep, same keys, mid-run injections: every derived
    view matches the per-round flavor every round; at cohort boundaries
    the overlay is drained and the packed stamp plane agrees wherever a
    fact is known."""
    cfg_d = _cfg(pack=pack, unit=unit)
    cfg_p = _cfg(pack=pack, unit=1)
    step_d = jax.jit(functools.partial(cluster_round, cfg=cfg_d))
    step_p = jax.jit(functools.partial(cluster_round, cfg=cfg_p))
    sd, sp = _seeded(cfg_d), _seeded(cfg_p)
    for r in range(16):
        if r in (3, 9):       # mid-cohort injections (slot recycling)
            sd = sd._replace(gossip=inject_fact(
                sd.gossip, cfg_d.gossip, subject=5 + r,
                kind=K_USER_EVENT, incarnation=0, ltime=9 + r, origin=1))
            sp = sp._replace(gossip=inject_fact(
                sp.gossip, cfg_p.gossip, subject=5 + r,
                kind=K_USER_EVENT, incarnation=0, ltime=9 + r, origin=1))
        key = jax.random.key(100 + r)
        sd, sp = step_d(sd, key=key), step_p(sp, key=key)
        _assert_views_equal(sd.gossip, sp.gossip, cfg_d.gossip,
                            cfg_p.gossip, cfg_d.failure,
                            ctx=f"round {r + 1}")
        if int(sd.gossip.round) % unit == 0:  # flush boundary
            assert not bool(jnp.any(sd.gossip.overlay)), \
                f"overlay not drained at boundary round {r + 1}"
            _assert_stamps_equal_where_known(sd.gossip, sp.gossip,
                                             cfg_d.gossip)
        assert int(sd.gossip.round) - int(sd.gossip.last_flush) < unit \
            or not bool(jnp.any(sd.gossip.overlay))


@pytest.mark.parametrize("schedule", [
    "ring",
    pytest.param("allgather", marks=pytest.mark.slow),
])
def test_deferred_sharded_bit_exact(vmesh8, schedule):
    """The deferred flavor under the 8-virtual-device sharded flagship
    round: every GossipState leaf — overlay and last_flush included —
    matches the single-device deferred run."""
    from serf_tpu.parallel.mesh import shard_state

    cfg = _cfg(n=128, unit=4, schedule=schedule)
    st = _seeded(cfg)
    key = jax.random.key(2)
    fin1 = run_cluster_sustained(st, cfg, key, 12, events_per_round=2)
    fin8 = run_cluster_sustained(shard_state(st, vmesh8), cfg, key, 12,
                                 events_per_round=2, mesh=vmesh8)
    for (path, a), b in zip(
            jax.tree_util.tree_leaves_with_path(fin1.gossip),
            jax.tree_util.tree_leaves(fin8.gossip)):
        assert bool(jnp.all(a == b)), jax.tree_util.keystr(path)


def test_unit1_never_reads_the_deferred_leaves():
    """stamp_flush_unit=1 IS the per-round path: mangling the overlay
    and last_flush leaves changes no other GossipState leaf — the
    default config's round never reads them (the leaf-for-leaf identity
    with the pre-deferral behavior)."""
    cfg = _cfg(unit=1)
    key = jax.random.key(3)
    st = _seeded(cfg)
    mangled = st._replace(gossip=st.gossip._replace(
        overlay=jnp.full_like(st.gossip.overlay, 0xDEADBEEF),
        last_flush=jnp.asarray(-123, jnp.int32)))
    fin_a = run_cluster_sustained(st, cfg, key, 8, events_per_round=2)
    fin_b = run_cluster_sustained(mangled, cfg, key, 8,
                                  events_per_round=2)
    for (path, a), b in zip(
            jax.tree_util.tree_leaves_with_path(fin_a.gossip),
            jax.tree_util.tree_leaves(fin_b.gossip)):
        name = jax.tree_util.keystr(path)
        if "overlay" in name or "last_flush" in name:
            continue                      # the mangled leaves ride through
        assert bool(jnp.all(a == b)), name
    # and they DO ride through untouched (nothing wrote them either)
    assert int(fin_b.gossip.last_flush) == -123


# ---------------------------------------------------------------------------
# wrap/clamp edges (gossip-level lockstep across the mod-16 wrap)
# ---------------------------------------------------------------------------


def test_deferred_views_exact_across_quarter_wrap():
    """A cohort sequence crossing the 64-round stamp wrap (and riding
    the flush-pass clamp): views stay exact while old facts age past
    AGE_PIN_Q and get re-pinned by differently-timed clamp passes."""
    gcfg_d = GossipConfig(n=64, k_facts=32, peer_sampling="rotation",
                          stamp_flush_unit=4)
    gcfg_p = dataclasses.replace(gcfg_d, stamp_flush_unit=1)
    fcfg = FailureConfig(suspicion_rounds=8, max_new_facts=8,
                         probe_schedule="round_robin")
    base = make_state(gcfg_d)
    # a fact learned by everyone long ago (stamped in quarter 0), with
    # the round cursor about to cross the wrap: ages pin at AGE_PIN_Q
    g = inject_fact(base, gcfg_d, subject=3, kind=K_USER_EVENT,
                    incarnation=0, ltime=5, origin=0)
    start = 56
    g = g._replace(round=jnp.asarray(start, jnp.int32),
                   last_clamp=jnp.asarray(start, jnp.int32),
                   last_flush=jnp.asarray(start, jnp.int32),
                   last_learn=jnp.asarray(start, jnp.int32),
                   sendable_round=jnp.asarray(-1, jnp.int32))
    step_d = jax.jit(functools.partial(round_step, cfg=gcfg_d))
    step_p = jax.jit(functools.partial(round_step, cfg=gcfg_p))
    gd, gp = g, g
    for r in range(16):                   # 56 -> 72, across the wrap
        if r == 2:                        # fresh mid-cohort learn
            gd = inject_fact(gd, gcfg_d, subject=9, kind=K_USER_EVENT,
                             incarnation=0, ltime=7, origin=1)
            gp = inject_fact(gp, gcfg_p, subject=9, kind=K_USER_EVENT,
                             incarnation=0, ltime=7, origin=1)
        key = jax.random.key(200 + r)
        gd, gp = step_d(gd, key=key), step_p(gp, key=key)
        kb = unpack_bits(gd.known, 32)
        # the protocol-effective age: every threshold lives at or under
        # AGE_PIN_Q, so ages are equivalent once both sides saturate —
        # RAW nibbles legitimately differ mid-cohort for wrap-stale
        # cells (the per-round clamp rides every learn pass, the
        # deferred clamp rides the flush; the bound is what matters)
        aged = jnp.minimum(mod_age(gd, gcfg_d), 8)
        agep = jnp.minimum(mod_age(gp, gcfg_p), 8)
        assert bool(jnp.all(jnp.where(kb, aged == agep, True))), \
            f"effective mod_age diverged at round {56 + r + 1}"
        assert bool(jnp.all(gd.known == gp.known))
        assert bool(jnp.all(select_words(gd, gcfg_d)
                            == select_words(gp, gcfg_p)))
        assert bool(jnp.all(coverage(gd, gcfg_d)
                            == coverage(gp, gcfg_p)))


def test_flush_pass_overlay_new_and_clamp_edges():
    """flush_stamp_pass cell semantics, both stamp flavors: pending
    overlay cells get the COHORT quarter round_q(next-1), this merge's
    fresh learns get round_q(next) and WIN over a stale surviving
    overlay bit, wrap-stale cells are re-pinned by the riding clamp."""
    for pack in (True, False):
        gcfg = GossipConfig(n=8, k_facts=32, peer_sampling="rotation",
                            stamp_flush_unit=4, pack_stamp=pack)
        st = make_state(gcfg)
        nxt = 68                               # boundary; quarter 17&0xF=1
        rq, rq_prev = int(round_q(nxt)), int(round_q(nxt - 1))
        assert rq != rq_prev                   # cohort ends ON a quarter
        nib = jnp.zeros((8, 32), jnp.uint8)
        # fact 0: stamped 9 quarters ago (wrap-stale, must re-pin)
        nib = nib.at[:, 0].set((rq - 9) & 0xF)
        stamp = nib if not pack else (
            nib[:, 0::2] | (nib[:, 1::2] << 4))
        overlay = jnp.zeros_like(st.overlay)
        overlay = overlay.at[:, 0].set(jnp.uint32(0b0110))  # facts 1, 2
        new = jnp.zeros_like(st.overlay)
        new = new.at[:, 0].set(jnp.uint32(0b0100))          # fact 2 again
        known = jnp.full_like(st.known, jnp.uint32(0b0111))
        stamp2, _, sr2 = flush_stamp_pass(
            stamp, known, new, overlay, jnp.asarray(nxt, jnp.int32),
            gcfg, st.sendable)
        out = stamp_nibbles(stamp2, 32, pack)
        assert int(sr2) == nxt                 # cache valid for `nxt`
        # pending overlay cell -> the cohort quarter
        assert bool(jnp.all(out[:, 1] == rq_prev))
        # fresh learn wins over the overlay bit
        assert bool(jnp.all(out[:, 2] == rq))
        # wrap-stale cell re-pinned: derived q-age is AGE_PIN_Q, not 9
        age0 = (rq - out[:, 0].astype(jnp.int32)) & 0xF
        assert bool(jnp.all(age0 == 8))


# ---------------------------------------------------------------------------
# mid-cohort checkpoint
# ---------------------------------------------------------------------------


def test_mid_cohort_checkpoint_restart_bit_exact(tmp_path):
    """Save at a mid-cohort round with a NONEMPTY overlay, restore into
    a fresh template, continue — every leaf matches the uninterrupted
    run (the overlay and last_flush round-trip; the next boundary flush
    retires the restored pending learns exactly)."""
    from serf_tpu.models import checkpoint

    cfg = _cfg(n=64, unit=4)
    st = _seeded(cfg)
    key = jax.random.key(4)
    mid = run_cluster_sustained(st, cfg, key, 6, events_per_round=2)
    assert int(mid.gossip.round) % 4 != 0      # genuinely mid-cohort
    assert bool(jnp.any(mid.gossip.overlay)), \
        "sustained load must leave pending overlay learns mid-cohort"
    path = str(tmp_path / "mid_cohort.ckpt")
    checkpoint.save(path, mid)
    restored = checkpoint.restore(path, make_cluster(cfg,
                                                     jax.random.key(9)))
    for (p, a), b in zip(jax.tree_util.tree_leaves_with_path(mid),
                         jax.tree_util.tree_leaves(restored)):
        assert bool(jnp.all(a == b)), jax.tree_util.keystr(p)
    key2 = jax.random.key(5)
    fin_a = run_cluster_sustained(mid, cfg, key2, 6, events_per_round=2)
    fin_b = run_cluster_sustained(restored, cfg, key2, 6,
                                  events_per_round=2)
    for (p, a), b in zip(jax.tree_util.tree_leaves_with_path(fin_a),
                         jax.tree_util.tree_leaves(fin_b)):
        assert bool(jnp.all(a == b)), jax.tree_util.keystr(p)


# ---------------------------------------------------------------------------
# STAMP_UNIT as a live controller knob
# ---------------------------------------------------------------------------


def test_stamp_unit_law_actuates_both_directions():
    """The control law (control/device.py): sustained overflow pressure
    defers harder (log2 knob up to 2 = unit 4); sustained low agreement
    walks it back down, stopping at the configured base — never below."""
    from serf_tpu.control.device import (ControlConfig, ControlSignals,
                                         KNOB_FIELDS, control_step,
                                         knob_bounds, make_control)

    su = KNOB_FIELDS.index("stamp_unit")
    ccfg = ControlConfig(enabled=True, hyst_up=1, hyst_down=1)
    gcfg = GossipConfig(n=64, k_facts=32, peer_sampling="rotation",
                        stamp_flush_unit=2)
    fcfg = FailureConfig(suspicion_rounds=8, max_new_facts=8,
                         probe_schedule="round_robin")
    base, lo, hi, step = knob_bounds(ccfg, gcfg, fcfg)
    assert (base[su], lo[su], hi[su], step[su]) == (1, 0, 2, 1)

    def drive(ctl, sigs):
        rows = []
        for s in sigs:
            ctl = control_step(ctl, s, ccfg, gcfg, fcfg)
            rows.append(int(ctl.knobs[su]))
        return ctl, rows

    ctl = make_control(ccfg, gcfg, fcfg)
    # overflow burn (ledger growing 8/round): defer harder, clamp at 2
    ctl, up = drive(ctl, [ControlSignals(agreement=jnp.float32(1.0),
                                         false_dead=jnp.float32(0.0),
                                         overflow=jnp.float32(8.0 * (i + 1)))
                          for i in range(8)])
    assert max(up) == 2 and up[-1] == 2
    # convergence burning (ledger frozen — the overflow EWMA needs
    # ~16 rounds to decay under overflow_hi before the agreement leg
    # of the law can win): flush sooner, stop at base
    ctl, down = drive(ctl, [ControlSignals(agreement=jnp.float32(0.5),
                                           false_dead=jnp.float32(0.0),
                                           overflow=jnp.float32(64.0))
                            ] * 30)
    assert down[-1] == int(base[su])
    assert min(down) >= int(base[su])      # the relax never crosses base
    # a per-round base pins the knob: no headroom in either direction
    g1 = dataclasses.replace(gcfg, stamp_flush_unit=1)
    b1, l1, h1, _ = knob_bounds(ccfg, g1, fcfg)
    assert (b1[su], l1[su], h1[su]) == (0, 0, 0)


def test_traced_stamp_unit_change_mid_run_stays_view_exact():
    """round_step with a TRACED stamp_unit (the controller's live
    cadence): switching 4 -> 2 -> 4 mid-run — without retracing — keeps
    every derived view bit-exact vs the per-round reference."""
    gcfg_d = GossipConfig(n=64, k_facts=32, peer_sampling="rotation",
                          stamp_flush_unit=2)
    gcfg_p = dataclasses.replace(gcfg_d, stamp_flush_unit=1)
    g0 = inject_fact(make_state(gcfg_d), gcfg_d, subject=3,
                     kind=K_USER_EVENT, incarnation=0, ltime=5, origin=0)
    step_d = jax.jit(functools.partial(round_step, cfg=gcfg_d))
    step_p = jax.jit(functools.partial(round_step, cfg=gcfg_p))
    units = [4, 4, 4, 2, 2, 4, 2, 4, 4, 2, 2, 2]
    gd, gp = g0, g0
    n_traces = 0
    for r, u in enumerate(units):
        if r == 4:
            gd = inject_fact(gd, gcfg_d, subject=9, kind=K_USER_EVENT,
                             incarnation=0, ltime=8, origin=2)
            gp = inject_fact(gp, gcfg_p, subject=9, kind=K_USER_EVENT,
                             incarnation=0, ltime=8, origin=2)
        key = jax.random.key(300 + r)
        gd = step_d(gd, key=key, stamp_unit=jnp.asarray(u, jnp.int32))
        gp = step_p(gp, key=key)
        kb = unpack_bits(gd.known, 32)
        assert bool(jnp.all(gd.known == gp.known)), f"round {r}"
        assert bool(jnp.all(jnp.where(
            kb, mod_age(gd, gcfg_d) == mod_age(gp, gcfg_p), True)))
        assert bool(jnp.all(select_words(gd, gcfg_d)
                            == select_words(gp, gcfg_p)))
    n_traces = step_d._cache_size()
    assert n_traces == 1, "a traced unit must not retrace per value"


# ---------------------------------------------------------------------------
# watchdog: the staleness invariant rides the deferred run green
# ---------------------------------------------------------------------------


def test_watchdog_staleness_invariant_green_on_deferred_run():
    from serf_tpu.obs.watchdog import INVARIANT_FIELDS

    idx = INVARIANT_FIELDS.index("stamp_staleness_ok")
    cfg = _cfg(n=64, unit=4)
    st = _seeded(cfg)
    _, irows = run_cluster_sustained(st, cfg, jax.random.key(6), 12,
                                     events_per_round=2,
                                     collect_invariants=True)
    irows = np.asarray(irows)
    assert irows.shape == (12, len(INVARIANT_FIELDS))
    assert (irows[:, idx] == 1.0).all()
    assert (irows[:, INVARIANT_FIELDS.index("viol_mask")] == 0.0).all()


# ---------------------------------------------------------------------------
# kernel family: fused_flush parity; standalone kernels refuse deferred
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("pack", [
    True,
    pytest.param(False, marks=pytest.mark.slow),
])
def test_fused_flush_leaf_exact_with_xla_deferred(pack):
    """The fused family on a deferred config (interpret mode): every
    GossipState leaf matches the XLA deferred reference after every
    round — the flush kernel lands the same nibbles, cache, and
    overlay clear under the same do_flush cond."""
    gcfg = GossipConfig(n=128, k_facts=32, peer_sampling="rotation",
                        stamp_flush_unit=4, pack_stamp=pack)
    fast = dataclasses.replace(gcfg, use_pallas=True, fused_kernels=True)
    assert pallas_dispatch_mode(fast) == ("fused", "")
    g0 = inject_fact(make_state(gcfg), gcfg, subject=3,
                     kind=K_USER_EVENT, incarnation=0, ltime=5, origin=0)
    step_a = jax.jit(functools.partial(round_step, cfg=gcfg))
    step_b = jax.jit(functools.partial(round_step, cfg=fast))
    a, b = g0, g0
    for r in range(6):
        if r == 2:
            a = inject_fact(a, gcfg, subject=9, kind=K_USER_EVENT,
                            incarnation=0, ltime=8, origin=2)
            b = inject_fact(b, fast, subject=9, kind=K_USER_EVENT,
                            incarnation=0, ltime=8, origin=2)
        key = jax.random.key(400 + r)
        a, b = step_a(a, key=key), step_b(b, key=key)
        for (path, la), lb in zip(jax.tree_util.tree_leaves_with_path(a),
                                  jax.tree_util.tree_leaves(b)):
            assert bool(jnp.all(la == lb)), (
                f"leaf {jax.tree_util.keystr(path)} diverged round {r}")


def test_standalone_kernels_refuse_deferred_configs():
    deferred = GossipConfig(n=128, k_facts=32, peer_sampling="rotation",
                            stamp_flush_unit=4, use_pallas=True,
                            fused_kernels=False)
    mode, reason = pallas_dispatch_mode(deferred)
    assert mode == "" and "overlay" in reason
    # same shape, per-round: the standalone family still dispatches
    per_round = dataclasses.replace(deferred, stamp_flush_unit=1)
    assert pallas_dispatch_mode(per_round) == ("kernels", "")


def test_bad_flush_unit_rejected():
    for bad in (3, 8, 0):
        with pytest.raises(ValueError, match="stamp_flush_unit"):
            GossipConfig(n=64, k_facts=32, peer_sampling="rotation",
                         stamp_flush_unit=bad)


# ---------------------------------------------------------------------------
# the byte model: the 217 floor breaks, decomposition pinned
# ---------------------------------------------------------------------------


def test_deferred_byte_model_breaks_the_floor():
    """The STATUS round-9 re-pin: deferred @1M unit 4 under 180 MB/round
    (xla) vs the unchanged 233.4 per-round model — with the flush +
    overlay entries present and the overlay plane priced."""
    from serf_tpu.models.accounting import round_traffic
    from serf_tpu.models.swim import flagship_config

    cfg = flagship_config(1_000_000)
    per_round = round_traffic(cfg, sustained_rate=2)
    assert per_round.total_bytes == pytest.approx(233.3875e6, rel=1e-3)
    deferred = round_traffic(cfg, sustained_rate=2, stamp_deferred=True)
    assert deferred.total_bytes <= 180e6           # the floor is broken
    assert deferred.total_bytes >= 170e6           # and honestly priced
    dcfg = dataclasses.replace(
        cfg, gossip=dataclasses.replace(cfg.gossip, stamp_flush_unit=2))
    half = round_traffic(dcfg, sustained_rate=2)
    assert deferred.total_bytes < half.total_bytes < per_round.total_bytes
    # the decomposition: per-cohort flush (stamp RW at 1/unit) + the
    # overlay fold, and the overlay plane shows up in the plane sizes
    merge_planes = {(e.plane, e.rw): e for e in deferred.entries
                    if e.phase == "merge"}
    flush = merge_planes[("stamp", "RW")]
    assert flush.cadence == pytest.approx(1.0 / STAMP_UNIT)
    assert "flush" in flush.where
    fold = merge_planes[("overlay", "RW")]
    assert fold.cadence == pytest.approx(1.0 / STAMP_UNIT)
    assert deferred.plane_sizes["overlay"] \
        == deferred.plane_sizes["known"]
    assert "overlay" not in per_round.plane_sizes
    assert not any(e.plane == "overlay" for e in per_round.entries)
    # fused flush kernel stays within a pass of the XLA model; the
    # standalone family is priced (dispatch refuses it anyway)
    fused = round_traffic(cfg, sustained_rate=2, path="fused",
                          stamp_deferred=True)
    assert fused.total_bytes <= 181e6
    kernels = round_traffic(cfg, sustained_rate=2, path="kernels",
                            stamp_deferred=True)
    assert kernels.total_bytes > fused.total_bytes
