"""The toyregistry example (reference examples/toyconsul parity) must work
as documented."""

import asyncio
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))

pytestmark = pytest.mark.asyncio


async def agent_rpc(sock, req, timeout=5.0):
    """Line-delimited JSON RPC over the agent's unix control socket."""
    import json

    reader, writer = await asyncio.open_unix_connection(sock)
    writer.write((json.dumps(req) + "\n").encode())
    await writer.drain()
    out = json.loads(await asyncio.wait_for(reader.readline(), timeout))
    writer.close()
    return out


async def test_toyregistry_end_to_end():
    from toyregistry import ToyRegistry
    from serf_tpu.host import LoopbackNetwork
    from serf_tpu.options import Options

    net = LoopbackNetwork()
    agents = []
    for i in range(4):
        a = await ToyRegistry.start(net.bind(f"agent-{i}"), Options.local(),
                                    f"agent-{i}")
        agents.append(a)
    try:
        for a in agents[1:]:
            await a.serf.join("agent-0")
        await agents[0].register("api", "10.0.0.1:8080")
        await agents[2].register("db", "10.0.0.2:5432")
        deadline = asyncio.get_running_loop().time() + 7.0
        want = {"api": "10.0.0.1:8080", "db": "10.0.0.2:5432"}
        while asyncio.get_running_loop().time() < deadline:
            if all(a.list_local() == want for a in agents):
                break
            await asyncio.sleep(0.01)
        assert all(a.list_local() == want for a in agents)
        merged = await agents[3].list_consistent(timeout=1.0)
        assert merged == want
        await agents[1].deregister("db")
        deadline = asyncio.get_running_loop().time() + 7.0
        while asyncio.get_running_loop().time() < deadline:
            if all("db" not in a.list_local() for a in agents):
                break
            await asyncio.sleep(0.01)
        assert all(a.list_local() == {"api": "10.0.0.1:8080"} for a in agents)
    finally:
        for a in agents:
            await a.shutdown()


async def test_agent_unix_socket_rpc():
    """The toyconsul-parity socket RPC: two real-socket agents, driven
    through their unix sockets."""
    import json
    import tempfile

    from toyregistry import serve_agent

    import socket

    def free_port():
        with socket.socket() as sk:
            sk.bind(("127.0.0.1", 0))
            return sk.getsockname()[1]

    pa, pb = free_port(), free_port()
    d = tempfile.mkdtemp()
    sa, sb = f"{d}/a.sock", f"{d}/b.sock"
    t1 = asyncio.create_task(serve_agent(sa, f"127.0.0.1:{pa}", None))
    await asyncio.sleep(0.5)
    t2 = asyncio.create_task(
        serve_agent(sb, f"127.0.0.1:{pb}", f"127.0.0.1:{pa}"))
    await asyncio.sleep(0.5)

    rpc = agent_rpc

    try:
        assert (await rpc(sa, {"op": "register", "name": "api",
                               "addr": "10.0.0.1:80"}))["ok"]
        deadline = asyncio.get_running_loop().time() + 7.0
        while asyncio.get_running_loop().time() < deadline:
            out = await rpc(sb, {"op": "list"})
            if out["services"] == {"api": "10.0.0.1:80"}:
                break
            await asyncio.sleep(0.1)
        assert out["services"] == {"api": "10.0.0.1:80"}
        members = await rpc(sb, {"op": "members"})
        assert len(members["members"]) == 2
        bad = await rpc(sa, {"op": "nope"})
        assert not bad["ok"]
    finally:
        t1.cancel()
        t2.cancel()


async def test_agent_rpc_over_tls():
    """The agent CLI's --tls path: two TLS-stream agents sharing a cluster
    cert converge and replicate a registration."""
    import json
    import socket
    import tempfile

    from toyregistry import serve_agent

    from test_serf import _self_signed_cert

    def free_port():
        with socket.socket() as sk:
            sk.bind(("127.0.0.1", 0))
            return sk.getsockname()[1]

    d = tempfile.mkdtemp()
    import pathlib
    cert, key = _self_signed_cert(pathlib.Path(d))
    pa, pb = free_port(), free_port()
    sa, sb = f"{d}/a.sock", f"{d}/b.sock"
    t1 = asyncio.create_task(
        serve_agent(sa, f"127.0.0.1:{pa}", None, (cert, key)))
    await asyncio.sleep(0.5)
    t2 = asyncio.create_task(
        serve_agent(sb, f"127.0.0.1:{pb}", f"127.0.0.1:{pa}", (cert, key)))
    await asyncio.sleep(0.5)

    rpc = agent_rpc

    try:
        assert (await rpc(sa, {"op": "register", "name": "db",
                               "addr": "10.0.0.9:5432"}))["ok"]
        deadline = asyncio.get_running_loop().time() + 7.0
        out = {"services": None}
        while asyncio.get_running_loop().time() < deadline:
            out = await rpc(sb, {"op": "list"})
            if out["services"] == {"db": "10.0.0.9:5432"}:
                break
            await asyncio.sleep(0.1)
        assert out["services"] == {"db": "10.0.0.9:5432"}
    finally:
        t1.cancel()
        t2.cancel()


async def test_agent_over_udpstream():
    """The agent CLI's --udpstream path: a 2-agent cluster over the
    QUIC-slot transport, driven through the unix-socket control plane
    exactly as the documented CLI would."""
    import tempfile

    from toyregistry import serve_agent

    with tempfile.TemporaryDirectory() as d:
        s0 = os.path.join(d, "a0.sock")
        s1 = os.path.join(d, "a1.sock")
        t0 = asyncio.create_task(
            serve_agent(s0, "127.0.0.1:0", None, udpstream=True))
        t1 = None
        try:
            for _ in range(100):
                if os.path.exists(s0):
                    break
                await asyncio.sleep(0.05)
            # discover the first agent's real bound port via the members
            # op, then join the second agent to it
            members = await agent_rpc(s0, {"op": "members"})
            port = members["members"][0]["addr"][1]
            t1 = asyncio.create_task(
                serve_agent(s1, "127.0.0.1:0", f"127.0.0.1:{port}",
                            udpstream=True))
            for _ in range(100):
                if os.path.exists(s1):
                    break
                await asyncio.sleep(0.05)
            for _ in range(200):
                m = await agent_rpc(s0, {"op": "members"})
                if len(m["members"]) == 2:
                    break
                await asyncio.sleep(0.05)
            assert len(m["members"]) == 2, m
            await agent_rpc(s0, {"op": "register", "name": "api",
                                 "addr": "10.0.0.1:80"})
            for _ in range(200):
                listing = await agent_rpc(s1, {"op": "list"})
                if listing.get("services", {}).get("api") == "10.0.0.1:80":
                    break
                await asyncio.sleep(0.05)
            assert listing["services"]["api"] == "10.0.0.1:80"
        finally:
            t0.cancel()
            if t1 is not None:
                t1.cancel()
