"""Benchmark: 1M-node SWIM cluster simulation throughput on TPU.

Headline metric (BASELINE.md north star): gossip rounds/sec simulating a
1,000,000-node SWIM cluster — full protocol rounds (dissemination with
transmit-limited budgets + probe/suspect/refute/declare failure detection) —
target >= 10,000 rounds/sec on a v5e-8.  ``vs_baseline`` is measured against
that 10k target.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Robustness: the TPU here is reached through a tunnel that can wedge (a
killed client can leave the allocator grant stuck).  The orchestrator runs
the measurement in a subprocess with a hard timeout; if the TPU path hangs
it falls back to an honestly-labeled CPU measurement instead of hanging the
driver.  Run with ``--run`` to execute the measurement directly.
"""

from __future__ import annotations

import functools
import json
import os
import subprocess
import sys
import time

N_NODES = 1_000_000
K_FACTS = 64
ROUNDS_PER_CALL = 100
TIMED_CALLS = 3
TARGET_ROUNDS_PER_SEC = 10_000.0  # BASELINE.json north star (v5e-8)
TPU_TIMEOUT_S = int(os.environ.get("SERF_TPU_BENCH_TIMEOUT", "480"))
CPU_TIMEOUT_S = int(os.environ.get("SERF_TPU_BENCH_CPU_TIMEOUT", "900"))


def main() -> None:
    import jax

    if jax.default_backend() == "cpu":
        # CPU fallback keeps the same cluster size but fewer rounds
        global ROUNDS_PER_CALL, TIMED_CALLS
        ROUNDS_PER_CALL, TIMED_CALLS = 10, 2
    import jax.numpy as jnp

    from serf_tpu.models.dissemination import (
        GossipConfig,
        K_USER_EVENT,
        coverage,
        inject_fact,
        make_state,
    )
    from serf_tpu.models.failure import FailureConfig, run_swim

    cfg = GossipConfig(n=N_NODES, k_facts=K_FACTS)
    fcfg = FailureConfig(suspicion_rounds=12, max_new_facts=8)

    key = jax.random.key(0)
    state = make_state(cfg)
    # realistic work: live dissemination + a churn event to detect
    for i in range(8):
        state = inject_fact(state, cfg, subject=i * 1000, kind=K_USER_EVENT,
                            incarnation=0, ltime=i + 1, origin=i * 1000)
    dead = jnp.arange(0, N_NODES, N_NODES // 100)[:64]  # 64 dead nodes
    state = state._replace(alive=state.alive.at[dead].set(False))

    run = jax.jit(functools.partial(run_swim, cfg=cfg, fcfg=fcfg),
                  static_argnames=("num_rounds",), donate_argnums=(0,))

    # warmup / compile
    key, k = jax.random.split(key)
    state = jax.block_until_ready(run(state, key=k, num_rounds=ROUNDS_PER_CALL))

    t0 = time.perf_counter()
    for _ in range(TIMED_CALLS):
        key, k = jax.random.split(key)
        state = run(state, key=k, num_rounds=ROUNDS_PER_CALL)
    state = jax.block_until_ready(state)
    dt = time.perf_counter() - t0

    rounds = ROUNDS_PER_CALL * TIMED_CALLS
    rps = rounds / dt

    # sanity: the simulation made protocol progress (facts spread)
    cov = float(coverage(state, cfg)[0])
    if not (0.0 < cov <= 1.0):
        print(json.dumps({"metric": "ERROR: no protocol progress",
                          "value": 0, "unit": "rounds/sec",
                          "vs_baseline": 0.0}))
        sys.exit(1)

    platform = f"{len(jax.devices())}x {jax.devices()[0].device_kind}"
    if jax.default_backend() == "cpu":
        platform += " (CPU FALLBACK — TPU tunnel unavailable)"
    print(json.dumps({
        "metric": f"SWIM gossip rounds/sec @ {N_NODES} simulated nodes "
                  f"(full round: dissemination + failure detection), "
                  f"{platform}",
        "value": round(rps, 2),
        "unit": "rounds/sec",
        "vs_baseline": round(rps / TARGET_ROUNDS_PER_SEC, 4),
    }))


def orchestrate() -> None:
    """Run the measurement in a subprocess with a timeout; CPU fallback if
    the TPU tunnel is wedged."""
    me = os.path.abspath(__file__)
    try:
        proc = subprocess.run([sys.executable, me, "--run"],
                              capture_output=True, text=True,
                              timeout=TPU_TIMEOUT_S)
        out = _last_json_line(proc.stdout)
        if proc.returncode == 0 and out is not None:
            print(out)
            return
        sys.stderr.write(proc.stderr[-2000:] + "\n")
    except subprocess.TimeoutExpired:
        sys.stderr.write("TPU bench timed out (wedged tunnel?); "
                         "falling back to CPU\n")
    env = dict(os.environ, SERF_TPU_BENCH_CPU="1")
    try:
        proc = subprocess.run([sys.executable, me, "--run"],
                              capture_output=True, text=True,
                              timeout=CPU_TIMEOUT_S, env=env)
        out = _last_json_line(proc.stdout)
        if proc.returncode == 0 and out is not None:
            print(out)
            return
        sys.stderr.write(proc.stderr[-2000:] + "\n")
    except subprocess.TimeoutExpired:
        sys.stderr.write("CPU fallback bench also timed out\n")
    print(json.dumps({"metric": "ERROR: bench failed on TPU and CPU",
                      "value": 0, "unit": "rounds/sec",
                      "vs_baseline": 0.0}))
    sys.exit(1)


def _last_json_line(stdout: str):
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return line
    return None


if __name__ == "__main__":
    if "--run" in sys.argv:
        if os.environ.get("SERF_TPU_BENCH_CPU") == "1":
            import jax
            jax.config.update("jax_platforms", "cpu")
        main()
    else:
        orchestrate()
