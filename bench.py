"""Benchmark: 1M-node SWIM cluster simulation throughput on TPU.

Headline metric (BASELINE.md north star): gossip rounds/sec simulating a
1,000,000-node SWIM cluster — full protocol rounds (dissemination with
transmit-limited budgets + probe/suspect/refute/declare failure detection) —
target >= 10,000 rounds/sec on a v5e-8.  ``vs_baseline`` is measured against
that 10k target.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

from __future__ import annotations

import functools
import json
import sys
import time

N_NODES = 1_000_000
K_FACTS = 64
ROUNDS_PER_CALL = 100
TIMED_CALLS = 3
TARGET_ROUNDS_PER_SEC = 10_000.0  # BASELINE.json north star (v5e-8)


def main() -> None:
    import jax
    import jax.numpy as jnp

    from serf_tpu.models.dissemination import (
        GossipConfig,
        K_USER_EVENT,
        coverage,
        inject_fact,
        make_state,
    )
    from serf_tpu.models.failure import FailureConfig, run_swim

    cfg = GossipConfig(n=N_NODES, k_facts=K_FACTS)
    fcfg = FailureConfig(suspicion_rounds=12, max_new_facts=8)

    key = jax.random.key(0)
    state = make_state(cfg)
    # realistic work: live dissemination + a churn event to detect
    for i in range(8):
        state = inject_fact(state, cfg, subject=i * 1000, kind=K_USER_EVENT,
                            incarnation=0, ltime=i + 1, origin=i * 1000)
    dead = jnp.arange(0, N_NODES, N_NODES // 100)[:64]  # 64 dead nodes
    state = state._replace(alive=state.alive.at[dead].set(False))

    run = jax.jit(functools.partial(run_swim, cfg=cfg, fcfg=fcfg),
                  static_argnames=("num_rounds",), donate_argnums=(0,))

    # warmup / compile
    key, k = jax.random.split(key)
    state = jax.block_until_ready(run(state, key=k, num_rounds=ROUNDS_PER_CALL))

    t0 = time.perf_counter()
    for _ in range(TIMED_CALLS):
        key, k = jax.random.split(key)
        state = run(state, key=k, num_rounds=ROUNDS_PER_CALL)
    state = jax.block_until_ready(state)
    dt = time.perf_counter() - t0

    rounds = ROUNDS_PER_CALL * TIMED_CALLS
    rps = rounds / dt

    # sanity: the simulation made protocol progress (facts spread)
    cov = float(coverage(state, cfg)[0])
    if not (0.0 < cov <= 1.0):
        print(json.dumps({"metric": "ERROR: no protocol progress",
                          "value": 0, "unit": "rounds/sec",
                          "vs_baseline": 0.0}))
        sys.exit(1)

    print(json.dumps({
        "metric": f"SWIM gossip rounds/sec @ {N_NODES} simulated nodes "
                  f"(full round: dissemination + failure detection), "
                  f"{len(jax.devices())}x {jax.devices()[0].device_kind}",
        "value": round(rps, 2),
        "unit": "rounds/sec",
        "vs_baseline": round(rps / TARGET_ROUNDS_PER_SEC, 4),
    }))


if __name__ == "__main__":
    main()
