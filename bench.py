"""Benchmark: 1M-node serf/SWIM cluster simulation throughput on TPU.

Headline metric (BASELINE.md north star): FULL protocol rounds/sec
simulating a 1,000,000-node cluster with the flagship ``cluster_round``
under SUSTAINED LOAD — ``EVENTS_PER_ROUND`` fresh user events injected
every round (the reference's continuous-broadcast workload) on top of
gossip dissemination with transmit-limited budgets + probe/indirect-probe/
suspect/refute/declare failure detection + periodic push/pull anti-entropy
+ Vivaldi coordinate co-training — target >= 10,000 rounds/sec on a v5e-8.
``vs_baseline`` is measured against that 10k target.  The quiescent
steady state and the detection-hot active window are reported alongside
in ``BENCH_DETAIL.json``.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Secondary measurements (run_swim without anti-entropy/vivaldi, and the
Pallas-kernel A/B on TPU) go to stderr and ``BENCH_DETAIL.json``.

Robustness: the TPU here is reached through a tunnel that can wedge (a
killed client can leave the allocator grant stuck).  The orchestrator runs
the measurement in a subprocess with a hard timeout; if the TPU path hangs
it falls back to an honestly-labeled CPU measurement instead of hanging the
driver.  Run with ``--run`` to execute the measurement directly.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
import subprocess
import sys
import time

N_NODES = int(os.environ.get("SERF_TPU_BENCH_N", 1_000_000))
K_FACTS = 64
#: sustained-load headline: fresh user events injected per round.  2 at
#: K_FACTS=64 gives each fact a 32-round ring lifetime, above the 1M-node
#: transmit_limit of 28 — facts fully disseminate before retirement
#: (mirrors the reference's event-buffer headroom, event_buffer_size=512)
EVENTS_PER_ROUND = 2
ROUNDS_PER_CALL = 100
TIMED_CALLS = 3
#: rounds the warmup must cover so the seeded churn's detection cycle
#: (suspicion window + declaration + dissemination) finishes BEFORE the
#: steady-state timing starts, whatever rounds_per_call is
WARMUP_ROUNDS = 50
TARGET_ROUNDS_PER_SEC = 10_000.0  # BASELINE.json north star (v5e-8)
# Budget discipline (round-3 lesson: 1500+900 s exceeded the driver's own
# timeout, which killed the orchestrator mid-fallback and recorded NOTHING
# — rc=124 in BENCH_r03.json).  A cheap liveness probe decides TPU-vs-CPU
# up front.  The probe gets 3 SPACED attempts with backoff (VERDICT
# next-3: a transient tunnel wedge should not condemn a whole round to
# CPU), but retries only when the outcome is retryable — a clean "CPU
# only" verdict (rc 3) is deterministic and never retried, and retry
# attempts run under the shorter RETRY timeout.  Worst case INCLUDING the
# 20 s SIGINT-grace each timed-out child gets:
# (60+20) + 3 + (25+20) + 6 + (25+20) + (510+20) + (450+20) ≈ 1180 s on
# the pathological wedge-probe-then-TPU-headline-fails path — within the
# window the round-2/round-3 history shows the driver allows, and the
# realistic paths (probe ok first try, or deterministic CPU-only) are
# unchanged.
PROBE_TIMEOUT_S = int(os.environ.get("SERF_TPU_BENCH_PROBE_TIMEOUT", "60"))
PROBE_RETRY_TIMEOUT_S = int(os.environ.get(
    "SERF_TPU_BENCH_PROBE_RETRY_TIMEOUT", "25"))
PROBE_ATTEMPTS = int(os.environ.get("SERF_TPU_BENCH_PROBE_ATTEMPTS", "3"))
PROBE_BACKOFF_S = (3, 6)
TPU_TIMEOUT_S = int(os.environ.get("SERF_TPU_BENCH_TIMEOUT", "510"))
CPU_TIMEOUT_S = int(os.environ.get("SERF_TPU_BENCH_CPU_TIMEOUT", "450"))
#: rolling record of the last successful TPU measurement (timestamp +
#: headline numbers).  Written after every TPU-backed headline; embedded
#: as a ``tpu_last_good`` block in any CPU-fallback headline so a
#: BENCH_r*.json produced during a tunnel outage still carries the last
#: real accelerator numbers alongside the honestly-labeled CPU ones.
TPU_LAST_GOOD_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "TPU_LAST_GOOD.json")


def _round_scalar(state):
    """The i32 round counter, whatever the state flavor."""
    return (state.gossip if hasattr(state, "gossip") else state).round


def _time_rounds(jitted, state_factory, key, rounds_per_call, timed_calls,
                 measure_active=True, op=None):
    """Time with a per-call HOST TRANSFER of the round counter.

    Returns ``(state, steady_rps, active_rps)``.  The warmup call on the
    first seeded state compiles AND plays out the seeded churn's
    detection cycle; the timed calls after it measure the post-detection
    STEADY STATE — the regime a healthy production cluster spends almost
    all rounds in (gossip, probes, anti-entropy, and vivaldi still run
    every round; only the nothing-pending refute/declare/inject phases
    skip).  A freshly re-seeded state then reuses the compiled
    executable, and its first call times the ACTIVE window (detection
    hot) as the companion number.

    ``block_until_ready`` is NOT a trustworthy completion barrier on the
    axon tunnel: with donated buffers it can report ready while execution
    is still in flight (observed: 100-round 1M-node scans "completing" in
    0.0 ms, a physical impossibility against HBM bandwidth — the round-1
    179k-rounds/s claim was this artifact).  A device→host transfer of an
    output scalar cannot complete before the program that produces it, so
    every timed call ends with one."""
    import jax
    import numpy as np

    from serf_tpu.obs.device import dispatch_timer

    def call(state, k):
        """One jitted call ending in the host-transfer barrier, timed
        into the obs dispatch registry (first call for the op/signature
        = compile phase, the rest steady) when ``op`` is named."""
        if op is None:
            state = jitted(state, key=k, num_rounds=rounds_per_call)
            int(np.asarray(_round_scalar(state)))
            return state
        with dispatch_timer(op, signature=rounds_per_call):
            state = jitted(state, key=k, num_rounds=rounds_per_call)
            int(np.asarray(_round_scalar(state)))
        return state

    state = state_factory()
    # warm up PAST the detection cycle (suspicion_rounds=12 + declaration
    # + dissemination) so the timed calls genuinely measure steady state
    # even on the CPU fallback's short rounds_per_call=10 — repeat the
    # compiled call rather than recompiling a longer scan
    warm_calls = max(1, -(-WARMUP_ROUNDS // rounds_per_call))
    for _ in range(warm_calls):
        key, k = jax.random.split(key)
        state = call(state, k)
    t0 = time.perf_counter()
    for _ in range(timed_calls):
        key, k = jax.random.split(key)
        state = call(state, k)
    steady_rps = (rounds_per_call * timed_calls) / (time.perf_counter() - t0)
    active_rps = None
    if measure_active:
        fresh = state_factory()
        key, k = jax.random.split(key)
        t0 = time.perf_counter()
        fresh = call(fresh, k)
        active_rps = rounds_per_call / (time.perf_counter() - t0)
    return state, steady_rps, active_rps


def _control_ab(n: int) -> dict:
    """Static-vs-controlled device A/B of the control-overload-shed
    plan (serf_tpu/control) at bench-friendly N: the static leg must
    breach the shed-ratio SLO, the controlled leg must be all-green
    with a stable knob trajectory — the adaptive control plane's
    regression surface (bands in BASELINE.json)."""
    from serf_tpu.control.profiles import device_ab_config
    from serf_tpu.faults.device import run_device_plan
    from serf_tpu.faults.plan import named_plan
    from serf_tpu.obs import slo

    plan = named_plan("control-overload-shed")
    out = {"plan": plan.name, "n": n}
    for leg, controlled in (("static", False), ("controlled", True)):
        cfg = device_ab_config(plan.name, n, 32, controlled)
        res = run_device_plan(plan, cfg, collect_telemetry=True)
        verdicts = slo.judge_device_run(res, plan)
        breaches = [v.slo for v in verdicts if not v.ok]
        out[leg] = {
            "invariants_ok": res.report.ok,
            "slo_breaches": breaches,
            "dropped": res.dropped,
            "offered": res.offered,
        }
        if controlled:
            out[leg]["control_final"] = res.control_final
            out[leg]["decisions"] = len(res.control_decisions)
            out[leg]["stability_ok"] = all(
                r.ok for r in res.report.results
                if r.name == "control-stability")
    out["static_breaches"] = len(out["static"]["slo_breaches"])
    out["controlled_breaches"] = (
        len(out["controlled"]["slo_breaches"])
        + (0 if out["controlled"]["invariants_ok"] else 1))
    out["controlled_breach_names"] = out["controlled"]["slo_breaches"]
    return out


def main() -> None:
    import jax

    on_cpu = jax.default_backend() == "cpu"
    rounds_per_call = 10 if on_cpu else ROUNDS_PER_CALL
    timed_calls = 2 if on_cpu else TIMED_CALLS

    import jax.numpy as jnp

    from serf_tpu.models.dissemination import (
        GossipConfig,
        K_USER_EVENT,
        coverage,
        inject_fact,
    )
    from serf_tpu.models.failure import run_swim
    from serf_tpu.models.swim import (
        emit_cluster_metrics,
        flagship_config,
        make_cluster,
        run_cluster,
        run_cluster_sustained,
    )
    from serf_tpu.obs.device import (
        dispatch_summary,
        dispatch_timer,
        reset_dispatch_registry,
    )

    reset_dispatch_registry()

    # the node count disambiguates this artifact from smaller-N smoke
    # runs (a 100k validation and a 1M record look like a 100x collapse
    # without it)
    detail = {"n": N_NODES}
    # --export-timeline capture slots (filled by the telemetry-scan and
    # host-plane sections below, exported as one bundle at the end)
    _tl_rows = _tl_anchors = _tl_host_result = _tl_host_verdicts = None
    _tl_spans = _tl_flight = None
    # THE flagship workload definition (swim.flagship_config): rotation
    # sampling + round-robin probes (the at-scale mode — no 1M-row random
    # gathers), reference LAN gossip:probe cadence, push/pull every 16.
    # The accounting model and tests/test_accounting.py budget the same
    # definition, so bench and budget cannot drift apart.
    cfg = flagship_config(N_NODES, k_facts=K_FACTS)
    gcfg, fcfg = cfg.gossip, cfg.failure

    def seeded_state(c):
        n = c.n
        key = jax.random.key(0)
        st = make_cluster(c, key)
        g = st.gossip
        # realistic work: live dissemination + churn events to detect
        spacing = max(1, n // 8)
        origins = {(i * spacing) % n for i in range(8)}
        for i in range(8):
            g = inject_fact(g, c.gossip, subject=(i * spacing) % n,
                            kind=K_USER_EVENT, incarnation=0, ltime=i + 1,
                            origin=(i * spacing) % n)
        # 16 deaths: real churn for the detector, with ring HEADROOM —
        # 16 suspicions + 16 declarations + 8 events + refutations fit
        # K_FACTS=64, so detection COMPLETES and the cluster reaches its
        # steady state.  (64 deaths filled the 64-slot ring exactly,
        # locking the simulation in a permanent evict/re-inject cycle no
        # provisioned deployment runs in — the reference sizes its event
        # buffers at 512 for the same reason.)
        n_dead = min(16, n // 100)        # keep tiny smoke-test Ns sane
        if n_dead:
            # never kill a fact origin: a dead origin can't gossip, so its
            # fact would legitimately sit at coverage 0 and trip the
            # protocol-progress sanity check
            ids, step = [], n // n_dead
            for i in range(n_dead):
                d = (i * step + 1) % n
                while d in origins:
                    d = (d + 1) % n
                ids.append(d)
            g = g._replace(alive=g.alive.at[jnp.asarray(ids)].set(False))
        return st._replace(gossip=g)

    # --- HEADLINE: the flagship cluster round under SUSTAINED LOAD --------
    # EVENTS_PER_ROUND fresh user events injected every round (the
    # reference's continuous-broadcast workload, BASELINE.json config #2)
    # keep the quiescent gate open: every round pays the full select/
    # exchange/merge cost, so this number rewards doing the dissemination
    # work faster — a cluster idling at speed cannot inflate it (VERDICT
    # r4: the steady-state headline mostly measured the gated path).
    run_sus = jax.jit(functools.partial(run_cluster_sustained, cfg=cfg,
                                        events_per_round=EVENTS_PER_ROUND),
                      static_argnames=("num_rounds",), donate_argnums=(0,))
    sus_state, sustained_rps, _ = _time_rounds(
        run_sus, lambda: seeded_state(cfg), jax.random.key(3),
        rounds_per_call, timed_calls, measure_active=False,
        op="bench.run_cluster_sustained")
    detail["cluster_round_sustained_rps"] = round(sustained_rps, 2)
    detail["sustained_events_per_round"] = EVENTS_PER_ROUND

    # --- SHARDED flagship: the path the 10k target actually lives on ------
    # (ISSUE 6).  The single-chip HBM arithmetic caps the sustained round
    # at ~3.5k rps; the N/8-per-chip shard with packets-only ICI traffic
    # is the headline path on a v5e-8.  Measured on whatever mesh is
    # visible (the CPU fallback provisions 8 virtual host devices — that
    # measures collective-schedule overhead, not ICI, so the analytic
    # 8-chip ceiling is embedded right next to the measured number); on
    # CPU the mesh leg runs at a bounded N so it never eats the driver
    # window (override with SERF_TPU_BENCH_SHARD_N).
    try:
        from serf_tpu.models.accounting import ici_round_traffic
        from serf_tpu.parallel.mesh import (
            best_device_count,
            emit_shard_metrics,
            make_mesh,
            shard_state,
        )
        model8 = ici_round_traffic(cfg, 8)
        shard_n = int(os.environ.get(
            "SERF_TPU_BENCH_SHARD_N",
            min(N_NODES, 131072) if on_cpu else N_NODES))
        d_use = best_device_count(shard_n, len(jax.devices()))
        schedule = model8["schedule"]["recommended"]
        sharded = {
            "n": shard_n,
            "devices": d_use,
            "schedule": schedule,
            "virtual_mesh": on_cpu,
            # the analytic 8-chip numbers the virtual-mesh rps must be
            # judged against (the trajectory the BASELINE target tracks)
            "model_8chip": {
                "exchange_ici_bytes_per_chip": model8["per_phase_per_chip"]
                ["exchange"][f"ici_bytes_per_chip_{schedule}"],
                "hbm_bytes_per_chip_sustained":
                    model8["hbm_bytes_per_chip_sustained"],
                "implied_sustained_ceiling_rps":
                    round(model8["implied_sustained_ceiling_rps"], 1),
            },
        }
        if d_use >= 2:
            # measure the schedule the model recommends — thread it into
            # the config so the recorded schedule is the one that RAN
            cfg_s = dataclasses.replace(
                flagship_config(shard_n, k_facts=K_FACTS),
                exchange_schedule=schedule)
            mesh = make_mesh(d_use)
            run_shard = jax.jit(
                functools.partial(run_cluster_sustained, cfg=cfg_s,
                                  events_per_round=EVENTS_PER_ROUND,
                                  mesh=mesh),
                static_argnames=("num_rounds",), donate_argnums=(0,))
            _, shard_rps, _ = _time_rounds(
                run_shard, lambda: shard_state(seeded_state(cfg_s), mesh),
                jax.random.key(3), rounds_per_call, timed_calls,
                measure_active=False, op="bench.run_cluster_sharded")
            sharded["sustained_rps"] = round(shard_rps, 2)
            # gauges describe the MEASURED run (shard_n nodes, d_use
            # devices), not the 1M/8-chip target model beside them
            model_run = ici_round_traffic(cfg_s, d_use)
            sharded["model_measured_run"] = {
                "exchange_ici_bytes_per_chip":
                    model_run["per_phase_per_chip"]["exchange"]
                    [f"ici_bytes_per_chip_{schedule}"],
                "hbm_bytes_per_chip_sustained":
                    model_run["hbm_bytes_per_chip_sustained"],
            }
            emit_shard_metrics(
                d_use, schedule,
                sharded["model_measured_run"]
                ["exchange_ici_bytes_per_chip"],
                rps=shard_rps)
        else:
            sharded["skipped"] = "mesh needs >= 2 devices dividing n"
        detail["sharded"] = sharded
    except Exception as e:  # noqa: BLE001 - never lose the headline to it
        sharded = {"error": repr(e)[:300]}
        detail["sharded"] = sharded

    # --- fused-vs-phased pallas A/B (ISSUE 7): the fused cache-
    # maintaining kernel family vs the standalone (phased) kernels, same
    # seeds, same sustained-load config.  On the CPU fallback the
    # kernels run in interpret mode at a bounded N (override with
    # SERF_TPU_BENCH_FUSED_N) — that measures kernel-DISPATCH shape, not
    # HBM; the analytic kernel-path model embedded beside it carries the
    # TPU claim (same convention as the sharded section's ICI model).
    try:
        from serf_tpu.models.accounting import kernel_path_summary
        fused_n = int(os.environ.get(
            "SERF_TPU_BENCH_FUSED_N",
            min(N_NODES, 4096) if on_cpu else N_NODES))
        summary = kernel_path_summary(cfg, sustained_rate=EVENTS_PER_ROUND)
        fused_ab = {
            "n": fused_n,
            "interpret_mode": on_cpu,
            # the analytic kernel-path comparison @ headline N (the
            # number STATUS.md re-pins): fused removes the selection's
            # full stamp-plane pass vs the phased kernels
            "model_n": N_NODES,
            "model": {
                "bytes_per_round": {
                    p: round(v["total_bytes"], 1)
                    for p, v in summary["paths"].items()},
                "stamp_passes": {
                    p: v["passes_by_plane"].get("stamp")
                    for p, v in summary["paths"].items()},
                "fused_vs_kernels": summary["fused_vs_kernels"],
            },
        }
        ab_rounds = 5 if on_cpu else 50
        base_ab = flagship_config(fused_n, k_facts=K_FACTS)
        from serf_tpu.models.dissemination import pallas_dispatch_mode
        for name, fused in (("phased", False), ("fused", True)):
            cfg_ab = dataclasses.replace(
                base_ab, gossip=dataclasses.replace(
                    base_ab.gossip, use_pallas=True, fused_kernels=fused))
            # breadcrumb: what each flavor ACTUALLY dispatched — a shape
            # rejection (e.g. a SERF_TPU_BENCH_FUSED_N override) falls
            # back to XLA and would otherwise masquerade as a kernel A/B
            mode, _ = pallas_dispatch_mode(cfg_ab.gossip)
            fused_ab[f"{name}_kernel_path"] = mode or "xla"
            run_ab = jax.jit(
                functools.partial(run_cluster_sustained, cfg=cfg_ab,
                                  events_per_round=EVENTS_PER_ROUND),
                static_argnames=("num_rounds",))
            st = seeded_state(cfg_ab)
            with dispatch_timer(f"bench.fused_ab.{name}",
                                signature=ab_rounds):
                st = run_ab(st, key=jax.random.key(3),
                            num_rounds=ab_rounds)
                int(jnp.asarray(st.gossip.round))  # barrier (compile)
            t0 = time.time()
            st = run_ab(st, key=jax.random.key(4), num_rounds=ab_rounds)
            int(jnp.asarray(st.gossip.round))      # barrier (steady)
            fused_ab[f"{name}_rps"] = round(ab_rounds / (time.time() - t0),
                                            2)
        fused_ab["fused_over_phased"] = round(
            fused_ab["fused_rps"] / max(fused_ab["phased_rps"], 1e-9), 3)
        detail["fused_ab"] = fused_ab
    except Exception as e:  # noqa: BLE001 - never lose the headline to it
        fused_ab = {"error": repr(e)[:300]}
        detail["fused_ab"] = fused_ab

    # --- quarter-deferred stamp flushes A/B (ISSUE 18): deferred
    # (stamp_flush_unit=4) vs per-round stamps, same seeds, same
    # sustained-load config — the measured side of
    # accounting.round_traffic(stamp_deferred=): the per-learn-round
    # stamp R+W becomes a once-per-cohort flush + the overlay ride,
    # breaking the 217 MB/round bit-exact floor at 1M.  On the CPU
    # fallback the rps ratio measures dispatch shape, not HBM; the
    # embedded byte model carries the TPU claim (fused_ab convention).
    try:
        from serf_tpu.models.accounting import round_traffic
        from serf_tpu.models.dissemination import pallas_dispatch_mode
        stamp_n = int(os.environ.get(
            "SERF_TPU_BENCH_STAMP_N",
            min(N_NODES, 4096) if on_cpu else N_NODES))
        model_cfg = flagship_config(N_NODES, k_facts=K_FACTS)
        stamp_ab = {
            "n": stamp_n,
            "unit": 4,
            "model_n": N_NODES,
            # modeled MB/round @ headline N (what STATUS.md re-pins):
            # per-round vs deferred, with the flush+overlay decomposition
            "model_per_round_mb": round(round_traffic(
                model_cfg, sustained_rate=EVENTS_PER_ROUND,
                stamp_deferred=False).total_bytes / 1e6, 1),
            "model_deferred_mb": round(round_traffic(
                model_cfg, sustained_rate=EVENTS_PER_ROUND,
                stamp_deferred=True).total_bytes / 1e6, 1),
        }
        ab_rounds = 5 if on_cpu else 50
        base_ab = flagship_config(stamp_n, k_facts=K_FACTS)
        for name, unit in (("per_round", 1), ("deferred", 4)):
            cfg_ab = dataclasses.replace(
                base_ab, gossip=dataclasses.replace(
                    base_ab.gossip, stamp_flush_unit=unit))
            # breadcrumb: which kernel path each flavor dispatches (the
            # deferred path refuses the standalone kernels; both flavors
            # here run plain XLA unless the config says otherwise)
            mode, _ = pallas_dispatch_mode(cfg_ab.gossip)
            stamp_ab[f"{name}_kernel_path"] = mode or "xla"
            run_ab = jax.jit(
                functools.partial(run_cluster_sustained, cfg=cfg_ab,
                                  events_per_round=EVENTS_PER_ROUND),
                static_argnames=("num_rounds",))
            st = seeded_state(cfg_ab)
            with dispatch_timer(f"bench.stamp_flush_ab.{name}",
                                signature=ab_rounds):
                st = run_ab(st, key=jax.random.key(3),
                            num_rounds=ab_rounds)
                int(jnp.asarray(st.gossip.round))  # barrier (compile)
            t0 = time.time()
            st = run_ab(st, key=jax.random.key(4), num_rounds=ab_rounds)
            int(jnp.asarray(st.gossip.round))      # barrier (steady)
            stamp_ab[f"{name}_rps"] = round(
                ab_rounds / (time.time() - t0), 2)
        stamp_ab["deferred_over_per_round"] = round(
            stamp_ab["deferred_rps"]
            / max(stamp_ab["per_round_rps"], 1e-9), 3)
        detail["stamp_flush_ab"] = stamp_ab
    except Exception as e:  # noqa: BLE001 - never lose the headline to it
        detail["stamp_flush_ab"] = {"error": repr(e)[:300]}

    # sanity: injection genuinely ran every round (the gate never closed)
    # and dissemination made real progress (facts spreading, ring live)
    g = sus_state.gossip
    gate_open = (int(g.round) - int(g.last_learn)
                 < cfg.gossip.transmit_limit)
    mean_cov = float(jnp.where(g.facts.valid,
                               coverage(g, cfg.gossip), 0.0).mean())
    if not gate_open or not (0.0 < mean_cov <= 1.0):
        print(json.dumps({"metric": "ERROR: no protocol progress under "
                                    "sustained load",
                          "value": 0, "unit": "rounds/sec",
                          "vs_baseline": 0.0}))
        sys.exit(1)

    # print + flush the headline BEFORE the secondary benches: if a
    # secondary hangs/crashes, the orchestrator can still salvage the
    # already-valid headline from the subprocess's captured stdout
    platform = f"{len(jax.devices())}x {jax.devices()[0].device_kind}"
    if on_cpu:
        platform += " (CPU FALLBACK — TPU tunnel unavailable)"
    print(json.dumps({
        "metric": f"full serf cluster rounds/sec under sustained load "
                  f"({EVENTS_PER_ROUND} fresh user events injected/round) "
                  f"@ {N_NODES} simulated nodes (gossip + failure "
                  f"detection + anti-entropy + vivaldi), {platform}",
        "value": round(sustained_rps, 2),
        "unit": "rounds/sec",
        "vs_baseline": round(sustained_rps / TARGET_ROUNDS_PER_SEC, 4),
        # the flagship sharded path (N/P per chip, packets-only ICI) —
        # where the 10k target lives; full numbers in BENCH_DETAIL.json
        "sharded": sharded,
        # fused-vs-phased pallas kernel A/B (same seeds/config) + the
        # analytic kernel-path model; full numbers in BENCH_DETAIL.json
        "fused_ab": fused_ab,
    }), flush=True)

    # --- secondary: quiescent steady state + detection-hot active window --
    run_flag = jax.jit(functools.partial(run_cluster, cfg=cfg),
                       static_argnames=("num_rounds",), donate_argnums=(0,))
    state, flagship_rps, flagship_active = _time_rounds(
        run_flag, lambda: seeded_state(cfg), jax.random.key(1),
        rounds_per_call, timed_calls, op="bench.run_cluster")
    detail["cluster_round_rps"] = round(flagship_rps, 2)
    detail["cluster_round_active_rps"] = round(flagship_active, 2)

    # sanity: the steady-state simulation made protocol progress; a run
    # that didn't discredits BOTH its numbers
    cov = float(coverage(state.gossip, cfg.gossip)[0])
    if not (0.0 < cov <= 1.0):
        sys.stderr.write("WARNING: steady-state run made no protocol "
                         "progress\n")
        detail["cluster_round_rps"] = 0.0
        detail["cluster_round_active_rps"] = 0.0

    # --- secondary: swim-only (dissemination + failure detection) ---------
    run_sw = jax.jit(functools.partial(run_swim, cfg=gcfg, fcfg=fcfg),
                     static_argnames=("num_rounds",), donate_argnums=(0,))
    _, swim_rps, swim_active = _time_rounds(
        run_sw, lambda: seeded_state(cfg).gossip, jax.random.key(2),
        rounds_per_call, timed_calls, op="bench.run_swim")
    detail["run_swim_rps"] = round(swim_rps, 2)
    detail["run_swim_active_rps"] = round(swim_active, 2)

    # --- secondary: iid-sampling A/B (the random-gather/scatter mode) ------
    gcfg_iid = dataclasses.replace(gcfg, peer_sampling="iid")
    fcfg_iid = dataclasses.replace(fcfg, probe_schedule="random")
    run_iid = jax.jit(functools.partial(run_swim, cfg=gcfg_iid,
                                        fcfg=fcfg_iid),
                      static_argnames=("num_rounds",), donate_argnums=(0,))
    _, iid_rps, _ = _time_rounds(
        run_iid, lambda: seeded_state(cfg).gossip, jax.random.key(2),
        rounds_per_call, timed_calls, measure_active=False,
        op="bench.run_swim_iid")
    detail["run_swim_iid_rps"] = round(iid_rps, 2)

    # --- secondary: Pallas fused-kernel A/B (TPU only; compiled, not
    #     interpret mode) ---------------------------------------------------
    if not on_cpu:
        try:
            gcfg_p = dataclasses.replace(gcfg, use_pallas=True)
            cfg_p = dataclasses.replace(cfg, gossip=gcfg_p)
            run_pal = jax.jit(
                functools.partial(run_swim, cfg=gcfg_p, fcfg=fcfg),
                static_argnames=("num_rounds",), donate_argnums=(0,))
            _, pal_rps, _ = _time_rounds(
                run_pal, lambda: seeded_state(cfg_p).gossip,
                jax.random.key(2), rounds_per_call, timed_calls,
                measure_active=False, op="bench.run_swim_pallas")
            detail["run_swim_pallas_rps"] = round(pal_rps, 2)
        except Exception as e:  # noqa: BLE001 - A/B is best-effort detail
            detail["run_swim_pallas_error"] = repr(e)[:300]

    # device-plane gauges off the final sustained state (the same
    # emitters operators get through the metrics sink) plus the per-op
    # compile-vs-steady dispatch split — the TPU-time attribution the
    # headline number alone cannot give
    try:
        detail["device_metrics"] = {
            k: round(v, 6) for k, v in
            emit_cluster_metrics(sus_state, cfg).items()}
    except Exception as e:  # noqa: BLE001 - attribution is best-effort
        detail["device_metrics_error"] = repr(e)[:300]
    detail["dispatch"] = dispatch_summary()

    # --- per-phase round profile (tools/roundprof.py method): every bench
    # artifact doubles as a profile (VERDICT r5: "no profile exists that
    # explains where the time goes").  Profiled at a bounded N by default
    # so the profile never eats the driver window (override with
    # SERF_TPU_BENCH_PROFILE_N); the anomalous-phase flag is what the
    # measured-vs-roofline hunt needs.
    try:
        from serf_tpu.models.swim import flagship_config as _fc
        from serf_tpu.obs.profile import profile_round
        prof_n = int(os.environ.get("SERF_TPU_BENCH_PROFILE_N",
                                    min(N_NODES, 65536)))
        prof = profile_round(_fc(prof_n, k_facts=K_FACTS),
                             events_per_round=EVENTS_PER_ROUND,
                             timed_calls=1, warm_rounds=10)
        detail["profile"] = prof
        slowest = sorted(prof["phases"], key=lambda r: -r["wall_ms"])[:2]
        sys.stderr.write(
            "profile top-2 slowest phases @n=%d: %s; attributed %s of "
            "compiled round bytes\n" % (
                prof_n,
                ", ".join(f"{r['phase']} {r['wall_ms']:.2f} ms "
                          f"(roofline {r['roofline_frac']:.4f})"
                          for r in slowest),
                prof.get("attributed_bytes_frac")))
    except Exception as e:  # noqa: BLE001 - the profile is best-effort
        detail["profile_error"] = repr(e)[:300]

    # static-analysis finding trajectory (serflint, pure AST — ~3s):
    # the tier-1 gate holds NEW findings at zero and the baseline
    # should only shrink; BENCH_DETAIL tracks both per round
    try:
        from serf_tpu import analysis
        from serf_tpu.utils import metrics
        rep = analysis.analyze_repo()
        by_rule: dict = {}
        for f in rep.findings:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        metrics.gauge("serf.analysis.findings", len(rep.findings))
        metrics.gauge("serf.analysis.baselined", len(rep.baselined))
        detail["analysis"] = {
            "serf.analysis.findings": len(rep.findings),
            "serf.analysis.baselined": len(rep.baselined),
            "suppressed": len(rep.suppressed),
            "by_rule": by_rule,
        }
    except Exception as e:  # noqa: BLE001 - the lint embed is best-effort
        detail["analysis_error"] = repr(e)[:300]

    # continuous-telemetry rings (ISSUE 10): a short sustained scan with
    # the per-round telemetry rows collected INSIDE the scan (one
    # device_get for the whole run) — BENCH_DETAIL carries the ring
    # summaries, so every bench artifact shows the per-round trajectory
    # (alive/agreement/coverage/overflow), not just endpoint means.  The
    # telemetry leg runs at a bounded N so it never eats the driver
    # window (override with SERF_TPU_BENCH_TS_N).
    try:
        from serf_tpu.obs.timeseries import telemetry_to_store
        ts_n = int(os.environ.get("SERF_TPU_BENCH_TS_N",
                                  min(N_NODES, 4096)))
        ts_rounds = 48
        cfg_ts = flagship_config(ts_n, k_facts=K_FACTS)
        run_ts = jax.jit(functools.partial(
            run_cluster_sustained, cfg=cfg_ts,
            events_per_round=EVENTS_PER_ROUND, collect_telemetry=True),
            static_argnames=("num_rounds",))
        # compile outside the anchored window: the timeline maps rounds
        # linearly across [t0, t1], so a first-call XLA compile inside
        # it would shift every device sample seconds away from the host
        # events it must correlate with
        _warm = run_ts(seeded_state(cfg_ts), key=jax.random.key(4),
                       num_rounds=ts_rounds)
        jax.device_get(_warm[0].gossip.round)
        _t_ts0 = time.time()
        with dispatch_timer("bench.telemetry_scan", signature=ts_rounds):
            _, rows = run_ts(seeded_state(cfg_ts), key=jax.random.key(5),
                             num_rounds=ts_rounds)
            rows = jax.device_get(rows)      # THE one transfer (barrier)
        _tl_rows, _tl_anchors = rows, (_t_ts0, time.time(), ts_rounds)
        ts_store = telemetry_to_store(rows)
        detail["timeseries"] = {"n": ts_n, "rounds": ts_rounds,
                                "summaries": ts_store.summaries()}
    except Exception as e:  # noqa: BLE001 - the rings are best-effort
        detail["timeseries_error"] = repr(e)[:300]

    # propagation observatory (ISSUE 16): trace the first injected batch
    # as sentinel facts through a short sustained scan at the same
    # bounded N as the telemetry leg, and price the useful-vs-redundant
    # byte split of the round floor — measured redundancy + coverage
    # marks at small N, analytic redundancy/t99 at the 1M flagship (the
    # numbers the BASELINE.json propagation bands pin)
    try:
        from serf_tpu.models.accounting import propagation_split
        from serf_tpu.obs.propagation import (
            analytic_redundancy,
            analytic_rounds_to_coverage,
            emit_propagation_metrics,
            summarize_propagation,
        )
        pr_n = int(os.environ.get("SERF_TPU_BENCH_TS_N",
                                  min(N_NODES, 4096)))
        pr_rounds = 48
        cfg_pr = flagship_config(pr_n, k_facts=K_FACTS)
        run_pr = jax.jit(functools.partial(
            run_cluster_sustained, cfg=cfg_pr,
            events_per_round=EVENTS_PER_ROUND,
            collect_propagation=True),
            static_argnames=("num_rounds",))
        with dispatch_timer("bench.propagation_scan", signature=pr_rounds):
            _, prop_pair = run_pr(
                seeded_state(cfg_pr), key=jax.random.key(6),
                num_rounds=pr_rounds)
            prop_rows, prop_cov = jax.device_get(prop_pair)
        psum = summarize_propagation(prop_rows, prop_cov)
        emit_propagation_metrics(psum, {"plane": "device"})
        g1m = flagship_config(1_000_000).gossip
        split_1m = propagation_split(flagship_config(1_000_000))
        detail["propagation"] = {
            "n": pr_n, "rounds": pr_rounds,
            "sentinels": psum.sentinels,
            "time_to": psum.to_dict()["time_to"],
            "final_coverage": round(psum.final_coverage, 4),
            "redundancy": round(psum.redundancy, 4),
            "slots_sent": psum.slots_sent,
            "slots_learned": psum.slots_learned,
            "model_redundancy_1m": round(analytic_redundancy(
                g1m.transmit_window_rounds, g1m.fanout), 4),
            "model_t99_rounds_1m": analytic_rounds_to_coverage(
                g1m.n, g1m.fanout),
            "split_1m": {
                "total_bytes": split_1m["total_bytes"],
                "dissemination_bytes": split_1m["dissemination_bytes"],
                "useful_bytes": round(split_1m["useful_bytes"], 1),
                "redundant_bytes": round(split_1m["redundant_bytes"], 1),
            },
        }
    except Exception as e:  # noqa: BLE001 - the tracer leg is best-effort
        detail["propagation_error"] = repr(e)[:300]

    # SLO verdict on the headline itself (obs/slo.py, the SAME table the
    # chaos/obswatch CLIs judge): the measured sustained rps must not
    # exceed the analytic bandwidth ceiling — a number past physics is a
    # measurement artifact (the round-1 179k-rps class), and this is
    # where it gets caught permanently
    try:
        from serf_tpu.models.accounting import round_traffic
        from serf_tpu.obs import slo as slo_mod
        ceiling = round_traffic(cfg).ceiling_rounds_per_sec()
        v = slo_mod.judge(slo_mod.slo_def("sustained-rps-ceiling"),
                          "device", sustained_rps / max(ceiling, 1e-9),
                          detail=f"measured {sustained_rps:.1f} rps vs "
                                 f"analytic ceiling {ceiling:.1f} rps")
        detail["slo"] = [v.to_dict()]
        if not v.ok:
            sys.stderr.write(
                "SLO BREACH: measured rps exceeds the analytic ceiling "
                "— distrust this measurement\n")
    except Exception as e:  # noqa: BLE001 - the verdict is best-effort
        detail["slo_error"] = repr(e)[:300]

    # record/replay determinism self-check (ISSUE 9): record a short
    # seeded device run, replay it from the recording, and require the
    # per-round membership-view digest streams to be identical — a
    # nondeterminism regression (or a replay-plane bug) shows up in the
    # per-round trajectory instead of a user's chaos report
    try:
        from serf_tpu.replay.selfcheck import device_roundtrip
        detail["replay"] = device_roundtrip()
        if not detail["replay"]["digest_equal"]:
            where = detail["replay"]["first_divergent_round"]
            sys.stderr.write(
                "replay self-check DIVERGED at round %s\n"
                % ("<none: stream length/step mismatch>"
                   if where is None else where))
    except Exception as e:  # noqa: BLE001 - the self-check is best-effort
        detail["replay_error"] = repr(e)[:300]

    # adaptive-control A/B (ISSUE 11): run the control-overload-shed
    # device plan static vs controlled at small N and embed the verdict
    # pair — the static leg must BREACH an SLO (that is the scenario's
    # contract) and the controlled leg must be all-green, and the
    # regression gate's bands guard both directions forever (a controller
    # regression reads as controlled_breaches > 0; a scenario gone soft
    # reads as static_breaches == 0)
    try:
        detail["control_ab"] = _control_ab(
            int(os.environ.get("SERF_TPU_BENCH_CONTROL_N", "96")))
        if detail["control_ab"]["controlled_breaches"]:
            sys.stderr.write(
                "CONTROL A/B: controlled run still breaches "
                f"{detail['control_ab']['controlled_breach_names']}\n")
    except Exception as e:  # noqa: BLE001 - the A/B is best-effort
        detail["control_ab_error"] = repr(e)[:300]

    # host-plane headline (ISSUE 12): events/sec + queries/sec through a
    # loopback cluster under the query-storm FaultPlan, with the message
    # lifecycle ledger's per-stage latency decomposition — the hard
    # before-numbers ROADMAP item 1's throughput rebuild must beat, and
    # the BASELINE.json host bands guard them forever.  Rates are engine
    # counter deltas (every node's accepted handlings) over the whole
    # run wall clock, so they measure the full asyncio + codec pipeline
    # under storm, not the offered-load constants.
    try:
        import asyncio

        from serf_tpu.faults.host import (
            _counter_total as _ctr,
            run_host_plan,
        )
        from serf_tpu.faults.plan import named_plan
        from serf_tpu.obs import slo as slo_mod

        host_plan = named_plan("query-storm")
        base_ev, base_q = _ctr("serf.events"), _ctr("serf.queries")
        t0 = time.perf_counter()
        host_result = asyncio.run(run_host_plan(host_plan))
        host_elapsed = time.perf_counter() - t0
        host_verdicts = slo_mod.judge_host_run(host_result, host_plan)
        _tl_host_result, _tl_host_verdicts = host_result, host_verdicts
        # snapshot the drop-oldest span/flight rings NOW: the
        # obs_overhead section below runs two more query-storm legs
        # whose events would otherwise pollute (or wholly evict) this
        # run's lanes from the --export-timeline bundle
        from serf_tpu.obs import flight as _tl_flight_mod
        from serf_tpu.obs import trace as _tl_trace_mod
        _tl_spans = _tl_trace_mod.trace_dump()
        _tl_flight = _tl_flight_mod.flight_dump()
        host_load = host_result.load
        detail["host_plane"] = {
            "plan": host_plan.name,
            "n": host_plan.n,
            "elapsed_s": round(host_elapsed, 2),
            "events_per_sec": round(
                (_ctr("serf.events") - base_ev) / host_elapsed, 1),
            "queries_per_sec": round(
                (_ctr("serf.queries") - base_q) / host_elapsed, 1),
            "events_offered": host_load.events_offered,
            "queries_offered": host_load.queries_offered,
            "ingress_admitted": host_load.ingress_admitted,
            "ingress_shed": host_load.ingress_shed,
            "invariants_ok": host_result.report.ok,
            "slo_ok": slo_mod.all_ok(host_verdicts),
            "slo": slo_mod.verdicts_to_dict(host_verdicts),
            "lifecycle": host_result.lifecycle,
            # HISTORICAL captures, not measured by this run: the PR-12
            # box's pre-rebuild numbers and the rebuild box's own
            # same-box before (2026-08-04) — kept beside every fresh
            # decomposition so the before/after story travels with the
            # artifact, explicitly labeled so a future box's run can't
            # be misread as having re-measured them
            "before_captures": {
                "_doc": "historical pre-rebuild captures; NOT measured "
                        "by this bench run",
                "pr12_capture": {
                    "events_per_sec": [182, 249],
                    "queries_per_sec": [89, 125],
                    "queue_wait_share": 0.42,
                    "queue_wait_p99_ms": 100.0,
                    "owner_p99": "queue-wait",
                },
                "rebuild_box_2026-08-04": {
                    "events_per_sec": 71.3,
                    "queries_per_sec": 23.8,
                    "events_offered": 36,    # the load gen itself was
                    "queries_offered": 12,   # starved by the old seam
                    "tee_p99_ms": 1243.0,
                },
            },
        }
        lcs = host_result.lifecycle or {}
        sys.stderr.write(
            "host plane @%d nodes (query-storm): %.0f events/s + %.0f "
            "queries/s handled; e2e p50 %.2f ms p99 %.2f ms, p99 owner "
            "%s, attributed %.0f%%\n" % (
                host_plan.n,
                detail["host_plane"]["events_per_sec"],
                detail["host_plane"]["queries_per_sec"],
                lcs.get("e2e", {}).get("p50_ms", 0.0),
                lcs.get("e2e", {}).get("p99_ms", 0.0),
                lcs.get("owner_p99"),
                100 * (lcs.get("attributed_frac") or 0.0)))
    except Exception as e:  # noqa: BLE001 - never lose the headline to it
        detail["host_plane_error"] = repr(e)[:300]

    # --- proc_cluster (ISSUE 19): the SAME query-storm offered load,
    # but through a REAL 5-process cluster — one OS process per node
    # (serf_tpu.host.agent, jax-free) on real loopback sockets, driven
    # over the control channel.  Rates are the folded per-process
    # engine counters (every agent's accepted handlings) over the run
    # wall clock, so they price the full process + socket + ctl-channel
    # stack; the per-node lifecycle ledgers run hot (sample_n=4) and
    # the message-weighted attribution band keeps the decomposition
    # complete across process boundaries.
    try:
        import asyncio
        import tempfile as _tf

        from serf_tpu.faults.plan import named_plan
        from serf_tpu.faults.proc import run_proc_plan

        proc_plan = named_plan("query-storm")    # n=5, storm load phases
        t0 = time.perf_counter()
        with _tf.TemporaryDirectory(prefix="serf-bench-proc-") as _td:
            proc_result = asyncio.run(run_proc_plan(
                proc_plan, tmp_dir=_td, lifecycle_sample_n=4))
        proc_elapsed = time.perf_counter() - t0
        pc = proc_result.counters
        plcs = proc_result.lifecycle or {}
        weighted = [(lc["attributed_frac"], lc.get("sampled", 0))
                    for lc in plcs.values()
                    if lc.get("attributed_frac") is not None]
        tot_sampled = sum(s for _, s in weighted)
        proc_attr = (sum(a * s for a, s in weighted) / tot_sampled
                     if tot_sampled else None)
        proc_p99 = max((lc.get("e2e", {}).get("p99_ms", 0.0)
                        for lc in plcs.values()), default=0.0)
        proc_load = proc_result.load
        detail["proc_cluster"] = {
            "plan": proc_plan.name,
            "processes": proc_plan.n,
            "elapsed_s": round(proc_elapsed, 2),
            "events_per_sec": round(
                pc.get("serf.events", 0.0) / proc_elapsed, 1),
            "queries_per_sec": round(
                pc.get("serf.queries", 0.0) / proc_elapsed, 1),
            "events_offered": proc_load.events_offered,
            "queries_offered": proc_load.queries_offered,
            "events_admitted": proc_load.events_admitted,
            "events_shed": proc_load.events_shed,
            "queries_admitted": proc_load.queries_admitted,
            "queries_shed": proc_load.queries_shed,
            "invariants_ok": proc_result.report.ok,
            "settle_convergence_s": round(
                proc_result.settle_convergence_s, 3),
            "lifecycle": {
                "attributed_frac": (round(proc_attr, 4)
                                    if proc_attr is not None else None),
                "e2e_p99_ms": round(proc_p99, 2),
                "sampled": tot_sampled,
                "per_node": plcs,
            },
        }
        sys.stderr.write(
            "proc cluster @%d processes (query-storm): %.0f events/s + "
            "%.0f queries/s handled in %.1fs; invariants %s, "
            "attribution %s\n" % (
                proc_plan.n,
                detail["proc_cluster"]["events_per_sec"],
                detail["proc_cluster"]["queries_per_sec"],
                proc_elapsed,
                "ok" if proc_result.report.ok else "RED",
                ("%.0f%%" % (100 * proc_attr)
                 if proc_attr is not None else "n/a")))
    except Exception as e:  # noqa: BLE001 - never lose the headline to it
        detail["proc_cluster_error"] = repr(e)[:300]

    # --- encryption_ab (ISSUE 20): the crypto tax, priced three ways.
    # (a) AEAD microbench: seal+open round-trip of a gossip-sized frame
    # on the ACTIVE backend (CRYPTO_BACKEND names it — AES-GCM with the
    # wheel, stdlib HMAC-SHA256-CTR without; the band is set for the
    # slower stdlib path).  (b) macro A/B: the SAME query-storm plan
    # plaintext (the host_plane leg above) vs encrypted, run twice —
    # gossip fan-out amortized (seal once per BATCH frame, default) vs
    # per-packet (amortize off) — crypto_tax is plaintext/encrypted
    # handled-throughput, amortize_gain is the deterministic
    # would-have-sealed/actually-sealed counter ratio (>= 1 by
    # construction whenever fan-out > 1), and batched >= per-packet is
    # pinned on seals-per-opportunity, not wall clock.  (c) rotation
    # headline: the rotate-under-partition chaos plan end-to-end, its
    # measured post-heal reconvergence latency against the 5 s SLO.
    try:
        import asyncio
        import dataclasses as _dc
        import tempfile as _tf

        from serf_tpu.faults.host import (
            _counter_total as _ctr,
            _load_opts,
            run_host_plan,
        )
        from serf_tpu.faults.plan import named_plan
        from serf_tpu.host import keyring as _kr

        _ring = _kr.SecretKeyring(b"\x07" * 32)
        _frame = b"\xa5" * 512
        for _ in range(20):                      # warm the hash paths
            _ring.decrypt(_ring.encrypt(_frame))
        _iters = 300
        t0 = time.perf_counter()
        for _ in range(_iters):
            _ring.decrypt(_ring.encrypt(_frame))
        seal_open_us = (time.perf_counter() - t0) / _iters * 1e6

        storm = named_plan("query-storm")
        enc_plan = _dc.replace(storm, name="query-storm-encrypted",
                               encrypted=True)
        lopts = _load_opts(enc_plan)
        enc_legs = {}
        for leg, amortize in (("amortized", True), ("per_packet", False)):
            o = lopts.replace(memberlist=_dc.replace(
                lopts.memberlist, gossip_encrypt_amortize=amortize))
            b_ev, b_q = _ctr("serf.events"), _ctr("serf.queries")
            b_enc = _ctr("serf.keyring.encrypt")
            b_sav = _ctr("serf.keyring.encrypt_amortized")
            b_fail = _ctr("serf.keyring.decrypt_fail")
            # no tmp_dir: the plaintext host_plane leg above runs
            # without snapshots, so the encrypted legs must too — the
            # tax measured is crypto, not snapshot I/O (rings stay
            # in-memory; persistence is the rotation plan's job)
            t0 = time.perf_counter()
            enc_res = asyncio.run(run_host_plan(enc_plan, opts=o))
            el = time.perf_counter() - t0
            seals = _ctr("serf.keyring.encrypt") - b_enc
            saved = _ctr("serf.keyring.encrypt_amortized") - b_sav
            enc_legs[leg] = {
                "elapsed_s": round(el, 2),
                "events_per_sec": round(
                    (_ctr("serf.events") - b_ev) / el, 1),
                "queries_per_sec": round(
                    (_ctr("serf.queries") - b_q) / el, 1),
                "seals": seals,
                "seals_saved": saved,
                # seals per seal-opportunity: 1.0 on the per-packet
                # path, < 1.0 whenever amortization collapsed a fan-out
                "seals_per_opportunity": round(
                    seals / max(1, seals + saved), 4),
                "decrypt_fail": _ctr("serf.keyring.decrypt_fail") - b_fail,
                "invariants_ok": enc_res.report.ok,
            }
        amort = enc_legs["amortized"]
        per_pkt = enc_legs["per_packet"]
        plain = detail.get("host_plane")
        if not plain or not plain.get("events_per_sec"):
            # host_plane leg errored: run our own plaintext reference
            b_ev = _ctr("serf.events")
            t0 = time.perf_counter()
            asyncio.run(run_host_plan(storm))
            el = time.perf_counter() - t0
            plain = {"events_per_sec": round(
                (_ctr("serf.events") - b_ev) / el, 1)}
        crypto_tax = round(
            plain["events_per_sec"] / max(1e-9, amort["events_per_sec"]),
            4)
        amortize_gain = round(
            (amort["seals"] + amort["seals_saved"])
            / max(1, amort["seals"]), 4)

        rot_plan = named_plan("rotate-under-partition")
        with _tf.TemporaryDirectory(prefix="serf-bench-rot-") as _td:
            rot_res = asyncio.run(run_host_plan(rot_plan, tmp_dir=_td))
        rot = rot_res.rotation or {}
        rot_latency = (float(rot.get("latency_s", float("inf")))
                       if rot.get("converged") else float("inf"))
        detail["encryption_ab"] = {
            "backend": _kr.CRYPTO_BACKEND,
            "seal_open_us": round(seal_open_us, 1),
            "plaintext_events_per_sec": plain["events_per_sec"],
            "encrypted": enc_legs,
            "crypto_tax": crypto_tax,
            "amortize_gain": amortize_gain,
            # the batched-codec claim, deterministically: the amortized
            # path never seals MORE per opportunity than per-packet
            "batched_le_per_packet": (
                amort["seals_per_opportunity"]
                <= per_pkt["seals_per_opportunity"] + 1e-9),
            "rotation_latency_s": (round(rot_latency, 3)
                                   if rot_latency != float("inf")
                                   else None),
            "rotation_converged": bool(rot.get("converged")),
            "rotation_invariants_ok": rot_res.report.ok,
        }
        sys.stderr.write(
            "encryption A/B (%s): seal+open %.0f us/op @%dB; "
            "query-storm %.0f ev/s plain vs %.0f ev/s encrypted "
            "(tax %.2fx), amortize gain %.2fx (%d seals saved); "
            "rotation reconverged in %.3fs (SLO 5s)\n" % (
                _kr.CRYPTO_BACKEND, seal_open_us, len(_frame),
                plain["events_per_sec"], amort["events_per_sec"],
                crypto_tax, amortize_gain, amort["seals_saved"],
                rot_latency))
    except Exception as e:  # noqa: BLE001 - never lose the headline to it
        detail["encryption_ab_error"] = repr(e)[:300]

    # --- obs_overhead (ISSUE 15): the observability plane must never
    # silently become the load.  Device: the same bounded-N sustained
    # scan with per-round telemetry collection ON vs OFF; host: the
    # query-storm loopback run with lifecycle stage clocks at the chaos
    # sampling rate (sample_n=4) vs disabled (0), events/sec compared
    # against the host_plane section's sample_n=4 run above.  The
    # BASELINE.json bands cap both overhead fractions at <= 10% — a
    # telemetry-plane regression trips the same gate as a throughput one.
    try:
        ov_n = int(os.environ.get("SERF_TPU_BENCH_TS_N",
                                  min(N_NODES, 4096)))
        ov_rounds = 48
        cfg_ov = flagship_config(ov_n, k_facts=K_FACTS)
        ov = {"n": ov_n, "rounds": ov_rounds}
        rps = {}
        for flag in (True, False):
            run_ov = jax.jit(functools.partial(
                run_cluster_sustained, cfg=cfg_ov,
                events_per_round=EVENTS_PER_ROUND,
                collect_telemetry=flag),
                static_argnames=("num_rounds",))
            # warm through the seeded detection transient so the timed
            # window measures the steady state on BOTH legs (same
            # discipline as _time_rounds: state advances across calls —
            # re-running the detection-hot window from the same initial
            # state every rep would charge the telemetry leg for the
            # chaos transient, not for telemetry)
            st = seeded_state(cfg_ov)
            out = run_ov(st, key=jax.random.key(6),
                         num_rounds=ov_rounds)   # compile + warm
            st = out[0] if flag else out
            int(jnp.asarray(st.gossip.round))    # barrier (host transfer
            # — NOT block_until_ready, which the tunnel has reported
            # ready on in-flight work; see _time_rounds)
            best = 0.0
            for rep in range(2):                 # best-of-2 vs jitter
                t0 = time.perf_counter()
                out = run_ov(st, key=jax.random.key(7 + rep),
                             num_rounds=ov_rounds)
                st = out[0] if flag else out
                int(jnp.asarray(st.gossip.round))   # barrier
                best = max(best, ov_rounds / (time.perf_counter() - t0))
            rps["on" if flag else "off"] = best
        ov["device_rps_telemetry_on"] = round(rps["on"], 2)
        ov["device_rps_telemetry_off"] = round(rps["off"], 2)
        ov["device_overhead_frac"] = round(
            max(0.0, 1.0 - rps["on"] / max(rps["off"], 1e-9)), 4)

        if "host_plane" in detail:
            # SYMMETRIC legs: both runs happen back-to-back here in the
            # already-warm process (the host_plane section above was
            # the process's FIRST loopback run — reusing its number as
            # the ON leg would charge one-time warmup to the ledger)
            import asyncio

            from serf_tpu.faults.host import (
                _counter_total as _ctr_ov,
                run_host_plan as _rhp_ov,
            )
            from serf_tpu.faults.plan import named_plan as _np_ov
            plan_ov = _np_ov("query-storm")
            eps = {}
            for sample_n in (4, 0):
                base = _ctr_ov("serf.events")
                t0 = time.perf_counter()
                asyncio.run(_rhp_ov(plan_ov, lifecycle_sample_n=sample_n))
                el = time.perf_counter() - t0
                eps[sample_n] = (_ctr_ov("serf.events") - base) / el
            ov["host_events_per_sec_sample4"] = round(eps[4], 1)
            ov["host_events_per_sec_sample0"] = round(eps[0], 1)
            ov["host_overhead_frac"] = round(
                max(0.0, 1.0 - eps[4] / max(eps[0], 1e-9)), 4)
        detail["obs_overhead"] = ov
        sys.stderr.write(
            "obs overhead: device %.1f%% (telemetry scan on/off %.2f/"
            "%.2f rps), host %s\n" % (
                100 * ov["device_overhead_frac"], rps["on"], rps["off"],
                ("%.1f%%" % (100 * ov["host_overhead_frac"])
                 if "host_overhead_frac" in ov else "n/a")))
    except Exception as e:  # noqa: BLE001 - never lose the headline to it
        detail["obs_overhead_error"] = repr(e)[:300]

    # --- watchdog_overhead (ISSUE 17): the always-on watchdog must stay
    # near-zero cost.  Device: the bounded-N sustained scan with the
    # in-scan invariant row ON vs OFF; host: the query-storm loopback
    # run with the watchdog task ticking vs disabled.  Both run their
    # legs ABBA (on, off, off, on; best per leg) so clock drift cancels
    # instead of biasing whichever leg ran second.  The
    # blackbox_roundtrip self-check (synthetic breach -> dump ->
    # validate/render/diff/timeline, tools/blackbox.py) rides along;
    # BASELINE.json bands cap both overhead fractions and pin the
    # roundtrip green.
    try:
        wd_n = int(os.environ.get("SERF_TPU_BENCH_TS_N",
                                  min(N_NODES, 4096)))
        wd_rounds = 48
        cfg_wd = flagship_config(wd_n, k_facts=K_FACTS)
        wdov = {"n": wd_n, "rounds": wd_rounds}
        run_wd = {}
        for flag in (True, False):
            run_wd[flag] = jax.jit(functools.partial(
                run_cluster_sustained, cfg=cfg_wd,
                events_per_round=EVENTS_PER_ROUND,
                collect_invariants=flag),
                static_argnames=("num_rounds",))
        # compile + warm both legs through the detection transient
        # before any timing (same discipline as obs_overhead)
        st = seeded_state(cfg_wd)
        for flag in (True, False):
            out = run_wd[flag](st, key=jax.random.key(16),
                               num_rounds=wd_rounds)
            st = out[0] if flag else out
            int(jnp.asarray(st.gossip.round))     # barrier
        best_wd = {True: 0.0, False: 0.0}
        for i, flag in enumerate((True, False, False, True)):   # ABBA
            t0 = time.perf_counter()
            out = run_wd[flag](st, key=jax.random.key(17 + i),
                               num_rounds=wd_rounds)
            st = out[0] if flag else out
            int(jnp.asarray(st.gossip.round))     # barrier
            best_wd[flag] = max(best_wd[flag],
                                wd_rounds / (time.perf_counter() - t0))
        wdov["device_rps_invariants_on"] = round(best_wd[True], 2)
        wdov["device_rps_invariants_off"] = round(best_wd[False], 2)
        wdov["device_overhead_frac"] = round(
            max(0.0, 1.0 - best_wd[True] / max(best_wd[False], 1e-9)), 4)

        if "host_plane" in detail:
            import asyncio

            from serf_tpu.faults.host import (
                _counter_total as _ctr_wd,
                run_host_plan as _rhp_wd,
            )
            from serf_tpu.faults.plan import named_plan as _np_wd
            plan_wd = _np_wd("query-storm")
            eps_wd = {True: 0.0, False: 0.0}
            for flag in (True, False, False, True):             # ABBA
                base = _ctr_wd("serf.events")
                t0 = time.perf_counter()
                asyncio.run(_rhp_wd(plan_wd, watchdog=flag))
                el = time.perf_counter() - t0
                eps_wd[flag] = max(
                    eps_wd[flag], (_ctr_wd("serf.events") - base) / el)
            wdov["host_events_per_sec_watchdog_on"] = round(
                eps_wd[True], 1)
            wdov["host_events_per_sec_watchdog_off"] = round(
                eps_wd[False], 1)
            wdov["host_overhead_frac"] = round(
                max(0.0, 1.0 - eps_wd[True] / max(eps_wd[False], 1e-9)),
                4)

        # forensic-path self-check: the breach -> bundle -> render/
        # diff/timeline loop must round-trip (stdout redirected — the
        # orchestrator parses this process's LAST stdout JSON line as
        # the headline)
        import contextlib
        import importlib.util as _ilu
        spec = _ilu.spec_from_file_location(
            "_bb_tool", os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "tools", "blackbox.py"))
        bb_tool = _ilu.module_from_spec(spec)
        spec.loader.exec_module(bb_tool)
        with contextlib.redirect_stdout(sys.stderr):
            wdov["blackbox_roundtrip_ok"] = int(
                bb_tool.main(["self-check"]) == 0)
        detail["watchdog_overhead"] = wdov
        sys.stderr.write(
            "watchdog overhead: device %.1f%% (invariant row on/off "
            "%.2f/%.2f rps), host %s, blackbox roundtrip %s\n" % (
                100 * wdov["device_overhead_frac"], best_wd[True],
                best_wd[False],
                ("%.1f%%" % (100 * wdov["host_overhead_frac"])
                 if "host_overhead_frac" in wdov else "n/a"),
                "ok" if wdov["blackbox_roundtrip_ok"] else "FAIL"))
    except Exception as e:  # noqa: BLE001 - never lose the headline to it
        detail["watchdog_overhead_error"] = repr(e)[:300]

    # --- unified timeline bundle (--export-timeline / ISSUE 15): one
    # Perfetto-loadable artifact beside the numbers — the telemetry
    # scan's device rounds on the wall clock plus the host-plane run's
    # spans/flight/lifecycle/SLO lanes
    tl_path = os.environ.get("SERF_TPU_BENCH_TIMELINE")
    if tl_path:
        try:
            from serf_tpu.obs.timeline import (
                DeviceRunAnchors,
                TimelineBuilder,
                export_run_timeline,
            )
            builder = TimelineBuilder(
                meta={"source": "bench", "n": N_NODES,
                      "platform": f"{len(jax.devices())}x "
                                  f"{jax.devices()[0].device_kind}"})
            if _tl_rows is not None:
                t0, t1, rr = _tl_anchors
                builder.add_device_telemetry(
                    _tl_rows, DeviceRunAnchors(wall_start=t0, wall_end=t1,
                                               rounds=rr))
            export_run_timeline(
                tl_path, host_result=_tl_host_result,
                host_verdicts=_tl_host_verdicts, builder=builder,
                spans=_tl_spans, flight=_tl_flight)
            detail["timeline"] = tl_path
            sys.stderr.write(f"timeline bundle: {tl_path} "
                             "(open at https://ui.perfetto.dev)\n")
        except Exception as e:  # noqa: BLE001 - artifact is best-effort
            detail["timeline_error"] = repr(e)[:300]

    # --- regression gate (ISSUE 10): score the headline numbers against
    # the committed BASELINE.json bands (per-platform dotted-path min/max
    # — format documented in README "Time series & SLOs").  WARN-ONLY by
    # default so the first round re-baselines instead of failing; set
    # --strict (env SERF_TPU_BENCH_STRICT=1) for a nonzero exit on a
    # band violation.
    gate = None
    try:
        from serf_tpu.obs.slo import score_bench
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BASELINE.json")) as f:
            bands = json.load(f).get("bands")
        gate = score_bench(detail, bands, "cpu" if on_cpu else "tpu")
        detail["regression_gate"] = gate
        if gate["rebaseline"]:
            sys.stderr.write(
                "regression gate: no bands for this platform — "
                "re-baseline round (add them to BASELINE.json)\n")
        for v in gate["violations"]:
            row = next(c for c in gate["checked"] if c["metric"] == v)
            sys.stderr.write(
                f"REGRESSION-GATE VIOLATION: {v} = {row['value']:g} "
                f"outside [{row['min']}, {row['max']}]\n")
    except Exception as e:  # noqa: BLE001 - the gate must never eat the
        detail["regression_gate_error"] = repr(e)[:300]   # headline

    detail["platform"] = platform
    sys.stderr.write(json.dumps(detail) + "\n")
    strict_rc = strict_gate_rc(gate)
    # Only ORCHESTRATED runs write the committed artifact: ad-hoc
    # `--run` smoke tests at small N kept clobbering the 1M
    # measured-of-record (twice in round 5) — the orchestrator sets the
    # env marker for its children
    if os.environ.get("SERF_TPU_BENCH_RECORD") == "1":
        try:
            with open(os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "BENCH_DETAIL.json"), "w") as f:
                json.dump(detail, f, indent=1)
        except OSError:
            pass
    # strict mode exits nonzero on a band violation — AFTER the headline
    # was printed and the artifact written, so nothing is ever lost
    if strict_rc:
        sys.exit(strict_rc)


def strict_gate_rc(gate) -> int:
    """The ``--strict`` exit decision for a scored regression gate
    (``obs.slo.score_bench`` output): 4 on a band violation when
    SERF_TPU_BENCH_STRICT=1, else 0.  Factored out so the strict
    contract is test-pinned (tests/test_lifecycle.py) without running
    the full bench."""
    if (os.environ.get("SERF_TPU_BENCH_STRICT") == "1"
            and gate is not None and not gate["ok"]):
        return 4
    return 0


def probe() -> None:
    """Tunnel-liveness probe: tiny jit + a device->host transfer.

    Exit 0 = a real (non-CPU) accelerator executed a program end-to-end;
    exit 3 = only CPU visible; anything else / a hang = wedged tunnel.
    Kept deliberately tiny so it finishes in seconds when healthy."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    if jax.default_backend() == "cpu":
        sys.exit(3)
    # accumulate in f32: a backend summing the reduce in bf16 saturates
    # far below 2^24 and an exact-equality check would misclassify a
    # healthy accelerator as a wedged tunnel (ADVICE r4)
    x = jax.jit(lambda a: (a @ a.T).astype(jnp.float32).sum())(
        jnp.ones((256, 256), jnp.bfloat16))
    got = float(np.asarray(x))        # host transfer = completion barrier
    assert got == 256.0 * 256 * 256, got
    sys.stderr.write(f"probe ok: {jax.devices()[0].device_kind}\n")
    sys.exit(0)


def _run_child(args, timeout_s: int, env=None):
    """subprocess.run with SIGINT-first termination.

    A SIGKILLed TPU client can leave the tunnel's allocator grant stuck
    (observed round 2 — the wedge persisted across sessions).  On timeout
    we SIGINT so Python unwinds and destroys the client, then escalate
    only if the child ignores it.  Returns (returncode|None, stdout,
    stderr); returncode None = timed out."""
    import signal

    proc = subprocess.Popen(args, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True, env=env)
    try:
        out, err = proc.communicate(timeout=timeout_s)
        return proc.returncode, out, err
    except subprocess.TimeoutExpired:
        proc.send_signal(signal.SIGINT)
        try:
            out, err = proc.communicate(timeout=20)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, err = proc.communicate()
        return None, out or "", err or ""


def _save_tpu_last_good(headline_json: str) -> None:
    try:
        headline = json.loads(headline_json)
    except ValueError:
        return
    try:
        with open(TPU_LAST_GOOD_PATH, "w") as f:
            json.dump({"ts": time.time(),
                       "iso": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                            time.gmtime()),
                       "headline": headline}, f, indent=1)
    except OSError:
        pass


def _load_tpu_last_good():
    try:
        with open(TPU_LAST_GOOD_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _probe_tunnel(me: str) -> bool:
    """Tunnel-liveness with bounded retries: up to PROBE_ATTEMPTS spaced
    attempts.  rc 0 = accelerator proven; rc 3 = CPU-only, deterministic
    (no retry); anything else (wedge/timeout/crash) retries after a
    backoff — a transiently stuck allocator grant often clears in
    seconds once the dead client's grip is released."""
    for attempt in range(PROBE_ATTEMPTS):
        timeout = PROBE_TIMEOUT_S if attempt == 0 else PROBE_RETRY_TIMEOUT_S
        rc, _, perr = _run_child([sys.executable, me, "--probe"], timeout)
        sys.stderr.write(perr[-500:] + "\n")
        if rc == 0:
            return True
        if rc == 3:
            sys.stderr.write("probe: CPU-only backend (deterministic); "
                             "not retrying\n")
            return False
        if attempt < PROBE_ATTEMPTS - 1:
            delay = PROBE_BACKOFF_S[min(attempt, len(PROBE_BACKOFF_S) - 1)]
            sys.stderr.write("probe attempt %d/%d failed (rc=%s); "
                             "retrying in %ds\n"
                             % (attempt + 1, PROBE_ATTEMPTS, rc, delay))
            time.sleep(delay)
    sys.stderr.write("tunnel probe failed after %d attempts\n"
                     % PROBE_ATTEMPTS)
    return False


def orchestrate() -> None:
    """Probe the tunnel (retried with backoff), then run the measurement
    on whichever backend the probe proved; never exceed the driver
    window."""
    me = os.path.abspath(__file__)
    tpu_alive = _probe_tunnel(me)

    record_env = dict(os.environ, SERF_TPU_BENCH_RECORD="1")
    if tpu_alive:
        rc, out_s, err_s = _run_child([sys.executable, me, "--run"],
                                      TPU_TIMEOUT_S, env=record_env)
        sys.stderr.write(err_s[-2000:] + "\n")
        out = _last_json_line(out_s)
        # the headline is printed+flushed before the secondary benches, so
        # even a timeout (rc None) in a secondary leaves a salvageable line
        if out is not None and "ERROR" not in out:
            if rc is None:
                sys.stderr.write("TPU bench timed out after the headline; "
                                 "keeping the measured headline\n")
            _save_tpu_last_good(out)
            print(out)
            if rc == 4:          # --strict regression-gate violation
                sys.exit(4)
            return
        sys.stderr.write("TPU bench produced no headline (probe had "
                         "passed); falling back to CPU\n")

    env = dict(record_env, SERF_TPU_BENCH_CPU="1")
    rc, out_s, err_s = _run_child([sys.executable, me, "--run"],
                                  CPU_TIMEOUT_S, env=env)
    sys.stderr.write(err_s[-2000:] + "\n")
    out = _last_json_line(out_s)
    if out is not None and "ERROR" not in out:
        # embed the last KNOWN-GOOD TPU numbers beside the CPU fallback:
        # the artifact stays honest (platform says CPU) but the round
        # record keeps the accelerator's last measured reality
        last_good = _load_tpu_last_good()
        if last_good is not None:
            try:
                merged = json.loads(out)
                merged["tpu_last_good"] = last_good
                out = json.dumps(merged)
            except ValueError:
                pass
        print(out)
        if rc == 4:              # --strict regression-gate violation
            sys.exit(4)
        return
    if rc is None:
        sys.stderr.write("CPU fallback bench also timed out\n")
    print(json.dumps({"metric": "ERROR: bench failed on TPU and CPU",
                      "value": 0, "unit": "rounds/sec",
                      "vs_baseline": 0.0}))
    sys.exit(1)


def _last_json_line(stdout: str):
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return line
    return None


if __name__ == "__main__":
    if "--strict" in sys.argv:
        # regression-gate strictness rides the env so the orchestrator's
        # measurement children inherit it
        os.environ["SERF_TPU_BENCH_STRICT"] = "1"
    if "--export-timeline" in sys.argv:
        # the bundle path rides the env so the orchestrator's
        # measurement children inherit it (same pattern as --strict)
        i = sys.argv.index("--export-timeline")
        path = sys.argv[i + 1] if i + 1 < len(sys.argv) \
            and not sys.argv[i + 1].startswith("--") else "bench.trace.json"
        os.environ["SERF_TPU_BENCH_TIMELINE"] = path
    if "--probe" in sys.argv:
        probe()
    elif "--run" in sys.argv:
        if os.environ.get("SERF_TPU_BENCH_CPU") == "1":
            # provision the virtual 8-device mesh BEFORE the first jax
            # import so the CPU fallback can still measure the sharded
            # flagship section (same recipe as tests/conftest.py); the
            # TPU path sees its real chips instead
            _flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
                      if "xla_force_host_platform_device_count" not in f]
            _flags.append("--xla_force_host_platform_device_count=8")
            os.environ["XLA_FLAGS"] = " ".join(_flags)
            import jax
            jax.config.update("jax_platforms", "cpu")
        main()
    else:
        orchestrate()
