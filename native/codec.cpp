// Native wire-codec hot path for serf-tpu.
//
// The host plane's inner decode loop (protobuf-style tag|wiretype field
// scanning with LEB128 varints) is the per-packet cost on every gossip
// message; this scanner does one pass in C++ and hands Python a packed
// field table.  Capability parity target: the reference's zero-copy
// `*Ref<'a>` decode views (serf-core/src/types/, SURVEY.md §2.4) — same
// fail-closed semantics as the Python implementation in
// serf_tpu/codec/__init__.py, which remains the semantic oracle.
//
// Build: g++ -O2 -shared -fPIC -o libserfcodec.so codec.cpp
// ABI: plain C, consumed via ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstddef>

namespace {

constexpr uint64_t U64_MAX = ~0ULL;

// Decode one LEB128 varint.  Returns bytes consumed, 0 on truncation/overflow.
inline long varint(const unsigned char* buf, long len, uint64_t* value) {
    uint64_t result = 0;
    int shift = 0;
    for (long i = 0; i < len; ++i) {
        if (shift > 63) return 0;  // >64-bit varint
        uint64_t b = buf[i];
        uint64_t chunk = (b & 0x7F);
        // overflow check: chunk must fit in the remaining bits
        if (shift == 63 && chunk > 1) return 0;
        result |= chunk << shift;
        if (!(b & 0x80)) {
            *value = result;
            return i + 1;
        }
        shift += 7;
    }
    return 0;  // truncated
}

}  // namespace

extern "C" {

// Scan a message body into a packed field table.
//
// Each scanned field writes 4 entries into `out`:
//   [field_no, wire_type, value_or_offset, length]
// - WT_VARINT (0):          value_or_offset = the value,
//                           length slot = post-field byte offset (the
//                           Python binding derives new_pos from it)
// - WT_FIXED64 (1):         value_or_offset = byte offset, length = 8
// - WT_LENGTH_DELIMITED(2): value_or_offset = payload offset, length = n
// - WT_FIXED32 (5):         value_or_offset = byte offset, length = 4
//
// Returns the number of fields scanned, or -1 on malformed input
// (truncation, overlong varint, unknown wire type, field table overflow).
long serf_scan_fields(const unsigned char* buf, long len,
                      uint64_t* out, long max_fields) {
    long pos = 0;
    long count = 0;
    while (pos < len) {
        uint64_t key;
        long used = varint(buf + pos, len - pos, &key);
        if (used == 0) return -1;
        pos += used;
        uint64_t field = key >> 3;
        uint64_t wt = key & 0x7;
        if (count >= max_fields) return -1;
        uint64_t* slot = out + count * 4;
        slot[0] = field;
        slot[1] = wt;
        switch (wt) {
            case 0: {  // varint
                uint64_t v;
                used = varint(buf + pos, len - pos, &v);
                if (used == 0) return -1;
                pos += used;
                slot[2] = v;
                slot[3] = (uint64_t)pos;  // post-field offset (for new_pos)
                break;
            }
            case 1: {  // fixed64
                if (pos + 8 > len) return -1;
                slot[2] = (uint64_t)pos;
                slot[3] = 8;
                pos += 8;
                break;
            }
            case 2: {  // length-delimited
                uint64_t n;
                used = varint(buf + pos, len - pos, &n);
                if (used == 0) return -1;
                pos += used;
                if (n > (uint64_t)(len - pos)) return -1;
                slot[2] = (uint64_t)pos;
                slot[3] = n;
                pos += (long)n;
                break;
            }
            case 5: {  // fixed32
                if (pos + 4 > len) return -1;
                slot[2] = (uint64_t)pos;
                slot[3] = 4;
                pos += 4;
                break;
            }
            default:
                return -1;
        }
        ++count;
    }
    return count;
}

// Encode a varint into out (must have >= 10 bytes); returns length written.
long serf_varint_encode(uint64_t value, unsigned char* out) {
    long i = 0;
    while (true) {
        unsigned char b = value & 0x7F;
        value >>= 7;
        if (value) {
            out[i++] = b | 0x80;
        } else {
            out[i++] = b;
            return i;
        }
    }
}

// Decode a varint; returns bytes consumed or 0 on error.
long serf_varint_decode(const unsigned char* buf, long len, uint64_t* value) {
    return varint(buf, len, value);
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Wire checksums (host/wire.py registry hot path).
//
// xxhash32 and murmur3_x86_32 per their public specs — the Python
// implementations in serf_tpu/host/wire.py are the semantic oracles
// (validated against published vectors); these native versions are the
// per-packet fast path.
// ---------------------------------------------------------------------------

namespace {

inline uint32_t rotl32(uint32_t x, int r) {
    return (x << r) | (x >> (32 - r));
}

inline uint32_t read_le32(const unsigned char* p) {
    return static_cast<uint32_t>(p[0]) |
           (static_cast<uint32_t>(p[1]) << 8) |
           (static_cast<uint32_t>(p[2]) << 16) |
           (static_cast<uint32_t>(p[3]) << 24);
}

}  // namespace

extern "C" {

uint32_t serf_xxhash32(const unsigned char* data, long n, uint32_t seed) {
    const uint32_t P1 = 2654435761U, P2 = 2246822519U, P3 = 3266489917U,
                   P4 = 668265263U, P5 = 374761393U;
    long idx = 0;
    uint32_t h;
    if (n >= 16) {
        uint32_t v1 = seed + P1 + P2, v2 = seed + P2, v3 = seed,
                 v4 = seed - P1;
        while (idx <= n - 16) {
            v1 = rotl32(v1 + read_le32(data + idx) * P2, 13) * P1; idx += 4;
            v2 = rotl32(v2 + read_le32(data + idx) * P2, 13) * P1; idx += 4;
            v3 = rotl32(v3 + read_le32(data + idx) * P2, 13) * P1; idx += 4;
            v4 = rotl32(v4 + read_le32(data + idx) * P2, 13) * P1; idx += 4;
        }
        h = rotl32(v1, 1) + rotl32(v2, 7) + rotl32(v3, 12) + rotl32(v4, 18);
    } else {
        h = seed + P5;
    }
    h += static_cast<uint32_t>(n);
    while (idx <= n - 4) {
        h = rotl32(h + read_le32(data + idx) * P3, 17) * P4;
        idx += 4;
    }
    while (idx < n) {
        h = rotl32(h + data[idx] * P5, 11) * P1;
        ++idx;
    }
    h ^= h >> 15; h *= P2;
    h ^= h >> 13; h *= P3;
    h ^= h >> 16;
    return h;
}

uint32_t serf_murmur3_32(const unsigned char* data, long n, uint32_t seed) {
    const uint32_t C1 = 0xCC9E2D51U, C2 = 0x1B873593U;
    uint32_t h = seed;
    const long rounds = n / 4;
    for (long i = 0; i < rounds; ++i) {
        uint32_t k = read_le32(data + i * 4);
        k *= C1; k = rotl32(k, 15); k *= C2;
        h ^= k; h = rotl32(h, 13); h = h * 5 + 0xE6546B64U;
    }
    const unsigned char* tail = data + rounds * 4;
    uint32_t k = 0;
    switch (n & 3) {
        case 3: k ^= static_cast<uint32_t>(tail[2]) << 16; [[fallthrough]];
        case 2: k ^= static_cast<uint32_t>(tail[1]) << 8;  [[fallthrough]];
        case 1: k ^= tail[0];
                k *= C1; k = rotl32(k, 15); k *= C2; h ^= k;
    }
    h ^= static_cast<uint32_t>(n);
    h ^= h >> 16; h *= 0x85EBCA6BU;
    h ^= h >> 13; h *= 0xC2B2AE35U;
    h ^= h >> 16;
    return h;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// LZ4 block format codec (host/wire.py "lz4" compression variant).
//
// Implemented from the public LZ4 block format description: sequences of
// [token][literal-len ext][literals][2B LE offset][match-len ext], last
// sequence literals-only.  The decoder is fully bounds-checked (every read
// and write validated) — it parses untrusted packets.  The encoder is a
// greedy hash-table matcher; correctness is what matters here, ratio is
// secondary to zlib (tests pin round-trip identity and decoder robustness).
// ---------------------------------------------------------------------------

namespace {

constexpr long LZ4_MIN_MATCH = 4;
constexpr long LZ4_LAST_LITERALS = 5;   // spec: last 5 bytes are literals
constexpr long LZ4_MFLIMIT = 12;        // spec: no match closer than 12B to end
constexpr int LZ4_HASH_LOG = 13;

inline uint32_t lz4_hash(uint32_t v) {
    return (v * 2654435761U) >> (32 - LZ4_HASH_LOG);
}

inline uint32_t read32(const unsigned char* p) {
    return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
           (static_cast<uint32_t>(p[2]) << 16) |
           (static_cast<uint32_t>(p[3]) << 24);
}

}  // namespace

extern "C" {

// Compress src[0..n) into dst (capacity cap).  Returns compressed size,
// or -1 if dst is too small.  Worst case needs n + n/255 + 16 bytes.
long serf_lz4_compress(const unsigned char* src, long n,
                       unsigned char* dst, long cap) {
    long table[1 << LZ4_HASH_LOG];
    for (long i = 0; i < (1 << LZ4_HASH_LOG); ++i) table[i] = -1;

    long ip = 0, op = 0, anchor = 0;
    const long mflimit = n - LZ4_MFLIMIT;

    auto emit = [&](long lit_len, long match_off, long match_len) -> bool {
        long need = 1 + lit_len / 255 + 1 + lit_len +
                    (match_len ? 2 + (match_len - LZ4_MIN_MATCH) / 255 + 1 : 0);
        if (op + need > cap) return false;
        long ml_code = match_len ? match_len - LZ4_MIN_MATCH : 0;
        unsigned char token =
            static_cast<unsigned char>((lit_len >= 15 ? 15 : lit_len) << 4);
        if (match_len) token |= (ml_code >= 15 ? 15 : ml_code);
        dst[op++] = token;
        if (lit_len >= 15) {
            long rest = lit_len - 15;
            while (rest >= 255) { dst[op++] = 255; rest -= 255; }
            dst[op++] = static_cast<unsigned char>(rest);
        }
        for (long i = 0; i < lit_len; ++i) dst[op++] = src[anchor + i];
        if (match_len) {
            dst[op++] = static_cast<unsigned char>(match_off & 0xFF);
            dst[op++] = static_cast<unsigned char>((match_off >> 8) & 0xFF);
            if (ml_code >= 15) {
                long rest = ml_code - 15;
                while (rest >= 255) { dst[op++] = 255; rest -= 255; }
                dst[op++] = static_cast<unsigned char>(rest);
            }
        }
        return true;
    };

    if (n >= LZ4_MFLIMIT) {
        while (ip < mflimit) {
            uint32_t h = lz4_hash(read32(src + ip));
            long cand = table[h];
            table[h] = ip;
            if (cand >= 0 && ip - cand <= 0xFFFF &&
                read32(src + cand) == read32(src + ip)) {
                // extend the match (stop LZ4_LAST_LITERALS from the end)
                long ml = LZ4_MIN_MATCH;
                long limit = n - LZ4_LAST_LITERALS;
                while (ip + ml < limit && src[cand + ml] == src[ip + ml]) ++ml;
                if (!emit(ip - anchor, ip - cand, ml)) return -1;
                ip += ml;
                anchor = ip;
            } else {
                ++ip;
            }
        }
    }
    // final literals
    if (!emit(n - anchor, 0, 0)) return -1;
    return op;
}

// Decompress src[0..n) into dst (capacity cap).  Returns decompressed
// size, or -1 on ANY malformation (truncated sequence, offset beyond
// output start, output overflow).
long serf_lz4_decompress(const unsigned char* src, long n,
                         unsigned char* dst, long cap) {
    long ip = 0, op = 0;
    while (ip < n) {
        unsigned char token = src[ip++];
        // literal length
        long lit = token >> 4;
        if (lit == 15) {
            unsigned char b;
            do {
                if (ip >= n) return -1;
                b = src[ip++];
                lit += b;
            } while (b == 255);
        }
        if (ip + lit > n || op + lit > cap) return -1;
        for (long i = 0; i < lit; ++i) dst[op++] = src[ip++];
        if (ip == n) break;  // last sequence: literals only
        // match
        if (ip + 2 > n) return -1;
        long off = src[ip] | (static_cast<long>(src[ip + 1]) << 8);
        ip += 2;
        if (off == 0 || off > op) return -1;
        long ml = (token & 0x0F);
        if (ml == 15) {
            unsigned char b;
            do {
                if (ip >= n) return -1;
                b = src[ip++];
                ml += b;
            } while (b == 255);
        }
        ml += LZ4_MIN_MATCH;
        if (op + ml > cap) return -1;
        for (long i = 0; i < ml; ++i) {  // byte-wise: overlapping matches
            dst[op] = dst[op - off];
            ++op;
        }
    }
    return op;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Snappy block format codec (host/wire.py "snappy" compression variant).
//
// Implemented from the public snappy format description: a varint preamble
// with the uncompressed length, then elements tagged by the low 2 bits —
// 00 literal (6-bit length, or 60..63 = 1..4 extra LE length bytes),
// 01 copy with 1-byte offset (len 4..11, 11-bit offset),
// 10 copy with 2-byte LE offset (len 1..64),
// 11 copy with 4-byte LE offset (len 1..64).
// Same stance as the LZ4 codec above: the decoder is fully bounds-checked
// (it parses untrusted packets); the encoder is a greedy hash matcher.
// ---------------------------------------------------------------------------

namespace {

constexpr int SNAPPY_HASH_LOG = 13;

inline uint32_t snappy_hash(uint32_t v) {
    return (v * 2654435761U) >> (32 - SNAPPY_HASH_LOG);
}

}  // namespace

extern "C" {

// Compress src[0..n) into dst (capacity cap), preamble included.  Returns
// compressed size, or -1 if dst is too small.  Worst case needs
// n + n/60 + 8 bytes (literal tags + preamble).
long serf_snappy_compress(const unsigned char* src, long n,
                          unsigned char* dst, long cap) {
    long op = 0;
    // preamble: varint uncompressed length
    {
        uint64_t v = (uint64_t)n;
        do {
            if (op >= cap) return -1;
            unsigned char b = v & 0x7F;
            v >>= 7;
            dst[op++] = v ? (b | 0x80) : b;
        } while (v);
    }

    auto emit_literal = [&](long from, long len) -> bool {
        if (len == 0) return true;  // one element: literals go to 2^32
        long l = len - 1;
        long need = len + (l < 60 ? 1 : (l < 256 ? 2 : (l < 65536 ? 3 : 5)));
        if (op + need > cap) return false;
        if (l < 60) {
            dst[op++] = (unsigned char)(l << 2);
        } else if (l < 256) {
            dst[op++] = 60 << 2;
            dst[op++] = (unsigned char)l;
        } else if (l < 65536) {
            dst[op++] = 61 << 2;
            dst[op++] = (unsigned char)(l & 0xFF);
            dst[op++] = (unsigned char)(l >> 8);
        } else {
            dst[op++] = 63 << 2;
            dst[op++] = (unsigned char)(l & 0xFF);
            dst[op++] = (unsigned char)((l >> 8) & 0xFF);
            dst[op++] = (unsigned char)((l >> 16) & 0xFF);
            dst[op++] = (unsigned char)((l >> 24) & 0xFF);
        }
        for (long i = 0; i < len; ++i) dst[op++] = src[from + i];
        return true;
    };

    auto emit_copy = [&](long off, long len) -> bool {
        while (len > 0) {
            long chunk = len > 64 ? (len - 4 >= 64 ? 64 : 60) : len;
            len -= chunk;
            if (off < 2048 && chunk >= 4 && chunk <= 11) {
                if (op + 2 > cap) return false;
                dst[op++] = (unsigned char)(1 | ((chunk - 4) << 2) |
                                            ((off >> 8) << 5));
                dst[op++] = (unsigned char)(off & 0xFF);
            } else if (off < 65536) {
                if (op + 3 > cap) return false;
                dst[op++] = (unsigned char)(2 | ((chunk - 1) << 2));
                dst[op++] = (unsigned char)(off & 0xFF);
                dst[op++] = (unsigned char)(off >> 8);
            } else {
                if (op + 5 > cap) return false;
                dst[op++] = (unsigned char)(3 | ((chunk - 1) << 2));
                dst[op++] = (unsigned char)(off & 0xFF);
                dst[op++] = (unsigned char)((off >> 8) & 0xFF);
                dst[op++] = (unsigned char)((off >> 16) & 0xFF);
                dst[op++] = (unsigned char)((off >> 24) & 0xFF);
            }
        }
        return true;
    };

    long table[1 << SNAPPY_HASH_LOG];
    for (long i = 0; i < (1 << SNAPPY_HASH_LOG); ++i) table[i] = -1;

    long ip = 0, anchor = 0;
    while (ip + 4 <= n) {
        uint32_t h = snappy_hash(read32(src + ip));
        long cand = table[h];
        table[h] = ip;
        if (cand >= 0 && read32(src + cand) == read32(src + ip)) {
            long ml = 4;
            while (ip + ml < n && src[cand + ml] == src[ip + ml]) ++ml;
            if (!emit_literal(anchor, ip - anchor)) return -1;
            if (!emit_copy(ip - cand, ml)) return -1;
            ip += ml;
            anchor = ip;
        } else {
            ++ip;
        }
    }
    if (!emit_literal(anchor, n - anchor)) return -1;
    return op;
}

// Decompress src[0..n) into dst (capacity cap).  Parses the preamble and
// requires the declared length to equal the actual output exactly.
// Returns decompressed size, or -1 on ANY malformation (bad preamble,
// declared > cap, truncated element, offset beyond output start, output
// overflow, trailing garbage, length mismatch).
long serf_snappy_decompress(const unsigned char* src, long n,
                            unsigned char* dst, long cap) {
    uint64_t declared;
    long ip = varint(src, n, &declared);
    if (ip == 0 || declared > (uint64_t)cap) return -1;
    long op = 0;
    while (ip < n) {
        unsigned char tag = src[ip++];
        switch (tag & 3) {
            case 0: {  // literal
                long len = (tag >> 2) + 1;
                if (len > 60) {
                    long extra = len - 60;  // 1..4 length bytes
                    if (ip + extra > n) return -1;
                    len = 0;
                    for (long i = 0; i < extra; ++i)
                        len |= (long)src[ip + i] << (8 * i);
                    len += 1;
                    ip += extra;
                    if (len < 0) return -1;  // 4-byte length overflowed long?
                }
                if (ip + len > n || op + len > (long)declared) return -1;
                for (long i = 0; i < len; ++i) dst[op++] = src[ip++];
                break;
            }
            case 1: {  // copy, 1-byte offset
                if (ip + 1 > n) return -1;
                long len = ((tag >> 2) & 7) + 4;
                long off = ((long)(tag >> 5) << 8) | src[ip++];
                if (off == 0 || off > op || op + len > (long)declared)
                    return -1;
                for (long i = 0; i < len; ++i) { dst[op] = dst[op - off]; ++op; }
                break;
            }
            case 2: {  // copy, 2-byte offset
                if (ip + 2 > n) return -1;
                long len = (tag >> 2) + 1;
                long off = (long)src[ip] | ((long)src[ip + 1] << 8);
                ip += 2;
                if (off == 0 || off > op || op + len > (long)declared)
                    return -1;
                for (long i = 0; i < len; ++i) { dst[op] = dst[op - off]; ++op; }
                break;
            }
            default: {  // copy, 4-byte offset
                if (ip + 4 > n) return -1;
                long len = (tag >> 2) + 1;
                long off = (long)src[ip] | ((long)src[ip + 1] << 8) |
                           ((long)src[ip + 2] << 16) |
                           ((long)src[ip + 3] << 24);
                ip += 4;
                if (off == 0 || off > op || op + len > (long)declared)
                    return -1;
                for (long i = 0; i < len; ++i) { dst[op] = dst[op - off]; ++op; }
                break;
            }
        }
    }
    if (op != (long)declared) return -1;
    return op;
}

}  // extern "C"
