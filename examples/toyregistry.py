"""toyregistry: an eventually-consistent service registry over serf-tpu.

Capability parity with the reference's ``examples/toyconsul`` (584 LoC of
Rust; SURVEY.md §2.10): each agent runs a Serf node; ``register`` publishes
a service as a user event, every agent folds events into a local registry,
and ``list`` answers from local state — eventually consistent by gossip.
Queries give a consistent-read path (scatter ``list`` to all agents).

Run a demo cluster in-process:

    python examples/toyregistry.py demo

or drive agents programmatically (see ``ToyRegistry``).
"""

from __future__ import annotations

import asyncio
import json
import sys
from typing import Dict, Optional

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # repo root when run directly

from serf_tpu.host import (  # noqa: E402
    EventSubscriber,
    LoopbackNetwork,
    QueryEvent,
    QueryParam,
    Serf,
    UserEvent,
)
from serf_tpu.options import Options  # noqa: E402


class ToyRegistry:
    """One agent: a Serf node + a registry folded from user events."""

    def __init__(self, serf: Serf, subscriber: EventSubscriber):
        self.serf = serf
        self.registry: Dict[str, str] = {}
        self._sub = subscriber
        self._task: Optional[asyncio.Task] = None

    @classmethod
    async def start(cls, transport, opts: Options, node_id: str) -> "ToyRegistry":
        sub = EventSubscriber()
        serf = await Serf.create(transport, opts, node_id, subscriber=sub)
        agent = cls(serf, sub)
        agent._task = asyncio.create_task(agent._run(), name=f"toyreg-{node_id}")
        return agent

    async def _run(self) -> None:
        async for ev in self._sub:
            try:
                if isinstance(ev, UserEvent) and ev.name == "register":
                    entry = json.loads(ev.payload.decode())
                    self.registry[entry["name"]] = entry["addr"]
                elif isinstance(ev, UserEvent) and ev.name == "deregister":
                    self.registry.pop(ev.payload.decode(), None)
                elif isinstance(ev, QueryEvent) and ev.name == "list":
                    try:
                        await ev.respond(json.dumps(self.registry).encode())
                    except (TimeoutError, ValueError):
                        pass
            except (json.JSONDecodeError, KeyError, UnicodeDecodeError) as e:
                # a malformed event from a peer must not kill the fold loop
                print(f"{self.serf.local_id}: ignoring malformed event "
                      f"{getattr(ev, 'name', '?')!r}: {e}", file=sys.stderr)

    # -- the three verbs of the reference example --------------------------

    async def register(self, name: str, addr: str) -> None:
        payload = json.dumps({"name": name, "addr": addr}).encode()
        await self.serf.user_event("register", payload, coalesce=False)

    async def deregister(self, name: str) -> None:
        await self.serf.user_event("deregister", name.encode(), coalesce=False)

    def list_local(self) -> Dict[str, str]:
        return dict(self.registry)

    async def list_consistent(self, timeout: float = 2.0) -> Dict[str, str]:
        """Scatter a list query; merge every agent's view."""
        resp = await self.serf.query("list", b"", QueryParam(timeout=timeout))
        merged: Dict[str, str] = dict(self.registry)
        async for r in resp.responses():
            merged.update(json.loads(r.payload.decode()))
        return merged

    async def shutdown(self) -> None:
        if self._task:
            self._task.cancel()
        await self.serf.shutdown()


async def demo() -> None:
    net = LoopbackNetwork()
    agents = []
    for i in range(5):
        a = await ToyRegistry.start(net.bind(f"agent-{i}"), Options.local(),
                                    f"agent-{i}")
        agents.append(a)
    for a in agents[1:]:
        await a.serf.join("agent-0")
    print("5-agent cluster up")

    await agents[0].register("api", "10.0.0.1:8080")
    await agents[2].register("db", "10.0.0.2:5432")
    await asyncio.sleep(0.3)
    for a in agents:
        print(f"{a.serf.local_id}: {a.list_local()}")
    print("consistent view:", await agents[4].list_consistent())
    await agents[1].deregister("db")
    await asyncio.sleep(0.3)
    print("after deregister:", agents[3].list_local())
    for a in agents:
        await a.shutdown()


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "demo":
        asyncio.run(demo())
    else:
        print(__doc__)
