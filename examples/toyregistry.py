"""toyregistry: an eventually-consistent service registry over serf-tpu.

Capability parity with the reference's ``examples/toyconsul`` (584 LoC of
Rust; SURVEY.md §2.10): each agent runs a Serf node; ``register`` publishes
a service as a user event, every agent folds events into a local registry,
and ``list`` answers from local state — eventually consistent by gossip.
Queries give a consistent-read path (scatter ``list`` to all agents).

Like the reference, agents also expose a **unix-socket RPC**: run an agent
with real UDP/TCP networking and drive it from a client:

    python examples/toyregistry.py agent /tmp/a.sock 127.0.0.1:7946 &
    python examples/toyregistry.py agent /tmp/b.sock 127.0.0.1:7947 \
        --join 127.0.0.1:7946 &
    python examples/toyregistry.py client /tmp/a.sock register api 10.0.0.1:80
    python examples/toyregistry.py client /tmp/b.sock list
    python examples/toyregistry.py client /tmp/b.sock members

``--join`` accepts hostnames (``node1.example:7946`` — resolved through the
transport's DNS seam).  ``--tls CERT KEY`` runs the stream plane (push/pull
state sync) over TLS; all agents of a cluster share one cert in the
self-signed deployment:

    python examples/toyregistry.py agent /tmp/a.sock 127.0.0.1:7946 \
        --tls cluster.pem cluster.key &

``--udpstream`` runs gossip AND streams over one UDP socket (the
QUIC-slot datagram-stream transport, AIMD congestion control); mutually
exclusive with ``--tls``:

    python examples/toyregistry.py agent /tmp/a.sock 127.0.0.1:7946 \
        --udpstream &

Or run an in-process demo cluster:

    python examples/toyregistry.py demo
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
from typing import Dict, Optional

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # repo root when run directly

from serf_tpu.host import (  # noqa: E402
    EventSubscriber,
    LoopbackNetwork,
    QueryEvent,
    QueryParam,
    Serf,
    UserEvent,
)
from serf_tpu.options import Options  # noqa: E402


class ToyRegistry:
    """One agent: a Serf node + a registry folded from user events."""

    def __init__(self, serf: Serf, subscriber: EventSubscriber):
        self.serf = serf
        self.registry: Dict[str, str] = {}
        self._sub = subscriber
        self._task: Optional[asyncio.Task] = None

    @classmethod
    async def start(cls, transport, opts: Options, node_id: str) -> "ToyRegistry":
        sub = EventSubscriber()
        serf = await Serf.create(transport, opts, node_id, subscriber=sub)
        agent = cls(serf, sub)
        agent._task = asyncio.create_task(agent._run(), name=f"toyreg-{node_id}")
        return agent

    async def _run(self) -> None:
        async for ev in self._sub:
            try:
                if isinstance(ev, UserEvent) and ev.name == "register":
                    entry = json.loads(ev.payload.decode())
                    self.registry[entry["name"]] = entry["addr"]
                elif isinstance(ev, UserEvent) and ev.name == "deregister":
                    self.registry.pop(ev.payload.decode(), None)
                elif isinstance(ev, QueryEvent) and ev.name == "list":
                    try:
                        await ev.respond(json.dumps(self.registry).encode())
                    except (TimeoutError, ValueError):
                        pass
            except (json.JSONDecodeError, KeyError, UnicodeDecodeError) as e:
                # a malformed event from a peer must not kill the fold loop
                print(f"{self.serf.local_id}: ignoring malformed event "
                      f"{getattr(ev, 'name', '?')!r}: {e}", file=sys.stderr)

    # -- the three verbs of the reference example --------------------------

    async def register(self, name: str, addr: str) -> None:
        payload = json.dumps({"name": name, "addr": addr}).encode()
        await self.serf.user_event("register", payload, coalesce=False)

    async def deregister(self, name: str) -> None:
        await self.serf.user_event("deregister", name.encode(), coalesce=False)

    def list_local(self) -> Dict[str, str]:
        return dict(self.registry)

    async def list_consistent(self, timeout: float = 2.0) -> Dict[str, str]:
        """Scatter a list query; merge every agent's view."""
        resp = await self.serf.query("list", b"", QueryParam(timeout=timeout))
        merged: Dict[str, str] = dict(self.registry)
        async for r in resp.responses():
            merged.update(json.loads(r.payload.decode()))
        return merged

    async def shutdown(self) -> None:
        if self._task:
            self._task.cancel()
        await self.serf.shutdown()


async def demo() -> None:
    net = LoopbackNetwork()
    agents = []
    for i in range(5):
        a = await ToyRegistry.start(net.bind(f"agent-{i}"), Options.local(),
                                    f"agent-{i}")
        agents.append(a)
    for a in agents[1:]:
        await a.serf.join("agent-0")
    print("5-agent cluster up")

    await agents[0].register("api", "10.0.0.1:8080")
    await agents[2].register("db", "10.0.0.2:5432")
    await asyncio.sleep(0.3)
    for a in agents:
        print(f"{a.serf.local_id}: {a.list_local()}")
    print("consistent view:", await agents[4].list_consistent())
    await agents[1].deregister("db")
    await asyncio.sleep(0.3)
    print("after deregister:", agents[3].list_local())
    for a in agents:
        await a.shutdown()


# -- unix-socket RPC plane (the reference's clap CLI + socket, rebuilt) ------


async def serve_agent(sock_path: str, bind: str, join: Optional[str],
                      tls: Optional[tuple] = None,
                      udpstream: bool = False) -> None:
    """Run one agent on real UDP/TCP (or TLS streams with ``--tls CERT
    KEY``, or the QUIC-slot single-UDP-socket transport with
    ``--udpstream``), controllable over a unix socket with line-delimited
    JSON: {"op": "register"|"deregister"|"list"|"list-consistent"|
    "members"|"leave", ...}.  ``--join`` accepts hostnames (resolved
    through the transport's DNS seam)."""
    from serf_tpu.host.net import NetTransport, TlsNetTransport, make_tls_contexts

    host, port = bind.rsplit(":", 1)
    if udpstream:
        from serf_tpu.host.dstream import DatagramStreamTransport
        transport = await DatagramStreamTransport.bind((host, int(port)))
    elif tls:
        server_ctx, client_ctx = make_tls_contexts(*tls)
        transport = await TlsNetTransport.bind(
            (host, int(port)), server_ctx=server_ctx, client_ctx=client_ctx)
    else:
        transport = await NetTransport.bind((host, int(port)))
    # identity from the ACTUAL bound address: naming from the bind string
    # makes every ":0"-bound agent the same node (instant name conflict)
    real_host, real_port = transport.local_addr[:2]
    agent = await ToyRegistry.start(transport, Options(),
                                    f"agent@{real_host}:{real_port}")
    if join:
        # raw string: the transport resolver handles host:port / DNS / IPv6
        await agent.serf.join(join)

    async def handle(reader, writer):
        try:
            while True:
                line = await reader.readline()
                if not line:
                    return
                try:
                    req = json.loads(line)
                    op = req.get("op")
                    if op == "register":
                        await agent.register(req["name"], req["addr"])
                        out = {"ok": True}
                    elif op == "deregister":
                        await agent.deregister(req["name"])
                        out = {"ok": True}
                    elif op == "list":
                        out = {"ok": True, "services": agent.list_local()}
                    elif op == "list-consistent":
                        out = {"ok": True,
                               "services": await agent.list_consistent()}
                    elif op == "members":
                        out = {"ok": True, "members": [
                            {"id": m.node.id, "status": m.status.name,
                             "addr": m.node.addr}
                            for m in agent.serf.members()]}
                    elif op == "leave":
                        await agent.serf.leave()
                        out = {"ok": True}
                    else:
                        out = {"ok": False, "error": f"unknown op {op!r}"}
                except Exception as e:  # noqa: BLE001 - RPC surface
                    out = {"ok": False, "error": str(e)}
                writer.write((json.dumps(out) + "\n").encode())
                await writer.drain()
        finally:
            writer.close()

    try:
        os.unlink(sock_path)  # stale socket from a killed agent
    except FileNotFoundError:
        pass
    server = await asyncio.start_unix_server(handle, path=sock_path)
    print(f"agent {agent.serf.local_id} up; rpc={sock_path}", flush=True)
    async with server:
        await server.serve_forever()


async def client_cmd(sock_path: str, argv) -> None:
    op = argv[0]
    req = {"op": op}
    if op == "register":
        req["name"], req["addr"] = argv[1], argv[2]
    elif op == "deregister":
        req["name"] = argv[1]
    reader, writer = await asyncio.open_unix_connection(sock_path)
    writer.write((json.dumps(req) + "\n").encode())
    await writer.drain()
    print((await reader.readline()).decode().strip())
    writer.close()


if __name__ == "__main__":
    try:
        if len(sys.argv) > 1 and sys.argv[1] == "demo":
            asyncio.run(demo())
        elif len(sys.argv) > 3 and sys.argv[1] == "agent":
            join_addr = None
            if "--join" in sys.argv:
                idx = sys.argv.index("--join") + 1
                if idx >= len(sys.argv):
                    sys.exit("error: --join requires an address")
                join_addr = sys.argv[idx]
            tls = None
            if "--tls" in sys.argv:
                idx = sys.argv.index("--tls")
                if idx + 2 >= len(sys.argv):
                    sys.exit("error: --tls requires CERT and KEY paths")
                tls = (sys.argv[idx + 1], sys.argv[idx + 2])
            udpstream = "--udpstream" in sys.argv
            if udpstream and tls:
                sys.exit("error: --udpstream and --tls are mutually "
                         "exclusive (for an encrypted UDP-stream cluster, "
                         "use a keyring — see serf_tpu.host.dstream)")
            asyncio.run(serve_agent(sys.argv[2], sys.argv[3], join_addr, tls,
                                    udpstream=udpstream))
        elif len(sys.argv) > 3 and sys.argv[1] == "client":
            asyncio.run(client_cmd(sys.argv[2], sys.argv[3:]))
        else:
            print(__doc__)
    except IndexError:
        sys.exit(f"error: missing operands\n{__doc__}")
