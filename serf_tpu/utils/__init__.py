"""Shared utilities: the metrics facade (``serf_tpu.utils.metrics``)."""
