"""Shared utilities: the metrics facade (``serf_tpu.utils.metrics``) and
the SERF_TPU_LOG logging bootstrap (``serf_tpu.utils.logging``)."""

from serf_tpu.utils.logging import get_logger, setup_logging

__all__ = ["get_logger", "setup_logging"]
