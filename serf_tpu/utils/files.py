"""Crash-safe file writes: write-tmp-fsync-rename, never a torn file.

A process killed mid-``write()`` must never leave a half-written file a
restart then trusts (ISSUE 19 satellite): every durable single-file
artifact — keyring saves, agent config files, ready files the proc
harness polls — goes through :func:`atomic_write_bytes`, which stages
the content in a same-directory temp file, fsyncs it, and publishes it
with ``os.replace`` (atomic on POSIX).  A crash before the rename leaves
the OLD file intact; a crash after leaves the NEW one complete.
"""

from __future__ import annotations

import os
import tempfile


def atomic_write_bytes(path: str, data: bytes, mode: int = 0o644) -> None:
    """Atomically publish ``data`` at ``path`` (tmp + fsync + rename).
    The temp file lives in the target's directory so the rename never
    crosses a filesystem boundary (which would silently degrade to a
    non-atomic copy)."""
    directory = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=directory,
                               prefix=os.path.basename(path) + ".",
                               suffix=".tmp")
    try:
        os.fchmod(fd, mode)
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        # the staged temp must not survive a failed publish — but the
        # target itself is untouched either way (that is the contract)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_text(path: str, text: str, mode: int = 0o644) -> None:
    atomic_write_bytes(path, text.encode("utf-8"), mode=mode)
