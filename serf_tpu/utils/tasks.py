"""Background-task hygiene: spawn with a retained handle + exception sink.

The serflint ``async-fire-forget`` pass (serf_tpu.analysis) enforces the
negative half of the contract — a ``create_task`` whose handle is
discarded can be GC'd mid-flight and its exception is swallowed until
interpreter exit.  This module is the positive half, the ONE spawn shape
the host plane uses: the handle is retained by the caller (list, set,
dict — ownership stays explicit) and a done-callback logs any exception
the task died with the moment it dies, instead of burying it until
``shutdown()`` awaits-and-ignores.

CancelledError is not an error: every loop in the tree is shut down by
cancellation.
"""

from __future__ import annotations

import asyncio
from typing import Callable, List, Optional, Set

from serf_tpu.utils.logging import get_logger

log = get_logger("tasks")

#: process-fatal-exception observers (``fn(task_name, exc)``): the
#: watchdog (``obs/watchdog.Watchdog.install_task_hook``) registers here
#: so a background task dying with a real exception counts as a breach
#: and triggers the black-box dump — the "crash forensics" half of the
#: spawn contract.  Hooks must never raise; a raising hook is logged and
#: dropped for the event (never unregistered behind the owner's back).
_failure_hooks: List[Callable[[str, BaseException], None]] = []


def add_failure_hook(fn: Callable[[str, BaseException], None]) -> None:
    if fn not in _failure_hooks:
        _failure_hooks.append(fn)


def remove_failure_hook(fn: Callable[[str, BaseException], None]) -> None:
    try:
        _failure_hooks.remove(fn)
    except ValueError:
        pass


def log_task_exception(task: "asyncio.Task") -> None:
    """Done-callback: surface a background task's death loudly (once,
    when it happens).  Reading ``.exception()`` also marks it retrieved,
    so asyncio's own exit-time "exception was never retrieved" noise is
    replaced by a structured log line."""
    if task.cancelled():
        return
    exc = task.exception()
    if exc is not None:
        log.error("background task %r died: %r", task.get_name(), exc)
        for fn in list(_failure_hooks):
            try:
                fn(task.get_name(), exc)
            except Exception:  # noqa: BLE001 — the sink must not raise
                log.exception("task failure hook %r raised", fn)


def spawn_logged(coro, name: str,
                 registry: Optional[Set["asyncio.Task"]] = None
                 ) -> "asyncio.Task":
    """``create_task`` + exception sink.  ``registry`` (a set) retains
    the handle and self-cleans on completion — the dynamic-task pattern
    ``Serf._bg``/``Memberlist._bg`` already use; without it the CALLER
    must retain the returned handle."""
    t = asyncio.create_task(coro, name=name)
    if registry is not None:
        registry.add(t)
        t.add_done_callback(registry.discard)
    t.add_done_callback(log_task_exception)
    return t
