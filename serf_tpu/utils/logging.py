"""Idempotent, env-filtered logging for the ``serf_tpu`` logger tree.

Analog of the reference's ``SERF_TESTING_LOG`` subscriber
(serf-core/src/lib.rs:96-114): set ``SERF_TPU_LOG=DEBUG`` (any logging
level name) to see structured protocol decision logs.  Unknown level
names fail loudly (logging raises ValueError) instead of silently
downgrading.

Unlike the old ``logging.basicConfig`` bootstrap — a no-op whenever the
root logger is already configured (pytest, an embedding application) —
``setup_logging`` attaches its own tagged handler to the ``serf_tpu``
PARENT logger: calling it again replaces nothing and re-applies the
level, and host/model modules get their loggers from
``get_logger(subsystem)`` so every subsystem hangs off the same tree
(one knob filters them all).
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Optional

#: the parent of every logger this package emits through
ROOT_LOGGER = "serf_tpu"

_FORMAT = "%(asctime)s %(name)s %(levelname)s %(message)s"
#: marker attribute identifying the handler setup_logging owns
_HANDLER_TAG = "_serf_tpu_handler"


def get_logger(subsystem: str) -> logging.Logger:
    """The canonical logger for a subsystem: ``serf_tpu.<subsystem>``.

    Every host/model module routes through this instead of ad-hoc
    ``logging.getLogger`` names, so the whole tree shares the parent's
    handler/level from :func:`setup_logging`."""
    if subsystem == ROOT_LOGGER:
        return logging.getLogger(ROOT_LOGGER)
    if subsystem.startswith(ROOT_LOGGER + "."):
        return logging.getLogger(subsystem)
    return logging.getLogger(f"{ROOT_LOGGER}.{subsystem}")


def setup_logging(env_var: str = "SERF_TPU_LOG",
                  level: Optional[str] = None,
                  stream=None) -> Optional[logging.Logger]:
    """Enable protocol logs on the ``serf_tpu`` logger tree.

    ``level`` overrides the environment; with neither set this is a
    no-op (returns None).  Idempotent: repeated calls reuse the one
    tagged handler and only re-apply level/format — safe under pytest or
    inside applications that configured the root logger themselves
    (events still propagate to root handlers as usual)."""
    level = level or os.environ.get(env_var)
    if not level:
        return None
    parent = logging.getLogger(ROOT_LOGGER)
    parent.setLevel(level.upper())
    handler = next((h for h in parent.handlers
                    if getattr(h, _HANDLER_TAG, False)), None)
    if handler is None:
        handler = logging.StreamHandler(stream or sys.stderr)
        setattr(handler, _HANDLER_TAG, True)
        handler.setFormatter(logging.Formatter(_FORMAT))
        parent.addHandler(handler)
    elif stream is not None:
        handler.setStream(stream)
    return parent
