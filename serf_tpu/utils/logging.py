"""Env-filtered logging bootstrap.

Analog of the reference's ``SERF_TESTING_LOG`` subscriber
(serf-core/src/lib.rs:96-114): set ``SERF_TPU_LOG=DEBUG`` (any logging
level name) to see structured protocol decision logs.  Unknown level names
fail loudly (logging raises ValueError) instead of silently downgrading.
"""

from __future__ import annotations

import logging
import os


def setup_logging(env_var: str = "SERF_TPU_LOG") -> None:
    level = os.environ.get(env_var)
    if not level:
        return
    logging.basicConfig(
        level=level.upper(),
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
