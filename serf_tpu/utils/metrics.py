"""In-process metrics facade: counters, gauges, histograms with labels.

Analog of the reference's ``metrics`` crate facade (SURVEY.md §5): the engine
emits at the same points with the same metric names (``serf.events``,
``serf.member.join``, ``serf.queue.*`` depth gauges, message-size histograms,
...).  A process-global ``MetricsSink`` collects; swap it out to export.
"""

from __future__ import annotations

import math
import threading
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

LabelSet = Tuple[Tuple[str, str], ...]

# Histograms keep summary stats plus a fixed-size ring of recent samples;
# observe() is called per packet sent/received, so raw samples must never
# accumulate unboundedly in a long-running agent.
HISTOGRAM_RING_SIZE = 256


def _labels(labels: Optional[Dict[str, str]]) -> LabelSet:
    if not labels:
        return ()
    return tuple(sorted(labels.items()))


def percentile_of(sorted_samples: List[float], p: float) -> float:
    """Nearest-rank p-th percentile (0..100) of a pre-sorted sample list;
    0.0 when empty.  Shared by HistogramSummary and the exporters so the
    JSON snapshot and the Prometheus quantile series always agree."""
    if not 0.0 <= p <= 100.0:
        raise ValueError(f"percentile must be in [0, 100], got {p}")
    if not sorted_samples:
        return 0.0
    rank = max(1, math.ceil(p / 100.0 * len(sorted_samples)))
    return sorted_samples[min(rank, len(sorted_samples)) - 1]


class HistogramSummary:
    __slots__ = ("count", "total", "_min", "_max", "_ring", "_pos")

    def __init__(self, ring_size: int = HISTOGRAM_RING_SIZE):
        self.count = 0
        self.total = 0.0
        self._min = float("inf")
        self._max = float("-inf")
        self._ring: List[float] = [0.0] * ring_size
        self._pos = 0

    def observe(self, value: float) -> None:
        self.count += 1
        self.total += value
        self._min = min(self._min, value)
        self._max = max(self._max, value)
        self._ring[self._pos] = value
        self._pos = (self._pos + 1) % len(self._ring)

    @property
    def min(self) -> float:
        """Smallest observed sample; 0.0 before any observation (an empty
        histogram must not leak ±inf into exports/JSON)."""
        return self._min if self.count else 0.0

    @property
    def max(self) -> float:
        """Largest observed sample; 0.0 before any observation."""
        return self._max if self.count else 0.0

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def recent(self) -> List[float]:
        """Last ≤ring_size samples, oldest first."""
        if self.count >= len(self._ring):
            return self._ring[self._pos:] + self._ring[:self._pos]
        return self._ring[:self._pos]

    def percentile(self, p: float) -> float:
        """p-th percentile (0..100) over the retained sample ring (the
        last ≤ring_size observations — an approximation of the lifetime
        distribution, exact while count <= ring_size).  0.0 when empty."""
        return percentile_of(sorted(self.recent()), p)


class MetricsSink:
    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[Tuple[str, LabelSet], float] = defaultdict(float)
        self.gauges: Dict[Tuple[str, LabelSet], float] = {}
        self.histograms: Dict[Tuple[str, LabelSet], HistogramSummary] = (
            defaultdict(HistogramSummary))

    def incr(self, name: str, value: float = 1.0, labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self.counters[(name, _labels(labels))] += value

    def gauge(self, name: str, value: float, labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self.gauges[(name, _labels(labels))] = value

    def observe(self, name: str, value: float, labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self.histograms[(name, _labels(labels))].observe(value)

    # inspection helpers (tests, stats)
    def counter(self, name: str, labels: Optional[Dict[str, str]] = None) -> float:
        return self.counters.get((name, _labels(labels)), 0.0)

    def gauge_value(self, name: str, labels: Optional[Dict[str, str]] = None) -> Optional[float]:
        return self.gauges.get((name, _labels(labels)))

    def histogram(self, name: str, labels: Optional[Dict[str, str]] = None) -> List[float]:
        """Recent samples (bounded ring) for the named histogram."""
        h = self.histograms.get((name, _labels(labels)))
        return h.recent() if h is not None else []

    def histogram_summary(self, name: str, labels: Optional[Dict[str, str]] = None) -> Optional[HistogramSummary]:
        return self.histograms.get((name, _labels(labels)))

    def reset(self) -> None:
        with self._lock:
            self.counters.clear()
            self.gauges.clear()
            self.histograms.clear()


_global = MetricsSink()


def global_sink() -> MetricsSink:
    return _global


def set_global_sink(sink: MetricsSink) -> None:
    global _global
    _global = sink


def incr(name: str, value: float = 1.0, labels: Optional[Dict[str, str]] = None) -> None:
    _global.incr(name, value, labels)


def gauge(name: str, value: float, labels: Optional[Dict[str, str]] = None) -> None:
    _global.gauge(name, value, labels)


def observe(name: str, value: float, labels: Optional[Dict[str, str]] = None) -> None:
    _global.observe(name, value, labels)

