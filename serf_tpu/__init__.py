"""serf-tpu: a TPU-native cluster-membership / gossip framework.

A ground-up rebuild of the capabilities of al8n/serf (SWIM + Lifeguard gossip,
Lamport-clocked event/query dissemination, push/pull anti-entropy, Vivaldi
network coordinates, snapshot/resume, key management) as a two-plane system:

- **host plane** (``serf_tpu.host``): an asyncio Serf engine with the same
  public API surface as the reference (`new/join/leave/user_event/query/
  members/stats/...`), pluggable transports (in-memory loopback, UDP/TCP),
  and full protocol semantics.  This is both a usable small/medium-cluster
  implementation and the parity oracle for the device plane.
- **device plane** (``serf_tpu.models``, ``serf_tpu.ops``,
  ``serf_tpu.parallel``): the whole cluster's state as struct-of-arrays in
  HBM; a gossip round is a sparse neighbor-gather plus a ``vmap``-ed local
  Lamport-merge transition under ``jit``, sharded over a device mesh with
  ``shard_map`` + ``ppermute`` for cross-chip edges.  Simulates million-node
  SWIM clusters to convergence.

Reference layer map: /root/reference README.md:110-144 (see SURVEY.md §1).
"""

__version__ = "0.1.0"

from serf_tpu.types.clock import LamportClock, LamportTime
from serf_tpu.options import Options

__all__ = ["LamportClock", "LamportTime", "Options", "__version__"]
