"""ctypes loader for the native codec scanner (native/codec.cpp).

Builds the shared library on first use with g++ (the image has the native
toolchain but no pybind11; plain C ABI + ctypes keeps the binding thin).
Set ``SERF_TPU_NO_NATIVE=1`` to force the pure-Python path.  The Python
implementation in ``serf_tpu.codec`` is always the semantic oracle; parity
is pinned by tests/test_native_codec.py.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

from serf_tpu.utils.logging import get_logger

log = get_logger("codec.native")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "codec.cpp")
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "build")
_SO = os.path.join(_BUILD_DIR, "libserfcodec.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", _SO + ".tmp", _SRC],
            check=True, capture_output=True, timeout=120)
        os.replace(_SO + ".tmp", _SO)
        return True
    except (subprocess.SubprocessError, OSError) as e:
        log.debug("native codec build failed: %s", e)
        return False


def load() -> Optional[ctypes.CDLL]:
    """The shared library, building it if needed; None if unavailable."""
    global _lib, _tried
    if _lib is not None:
        return _lib
    if _tried:
        return None  # build/load already failed; stay lock-free on the hot path
    if os.environ.get("SERF_TPU_NO_NATIVE") == "1":
        return None
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SRC) > os.path.getmtime(_SO)):
            if not os.path.exists(_SRC) or not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            log.debug("native codec load failed: %s", e)
            return None
        lib.serf_scan_fields.restype = ctypes.c_long
        lib.serf_scan_fields.argtypes = [
            ctypes.c_char_p, ctypes.c_long,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_long]
        lib.serf_varint_encode.restype = ctypes.c_long
        lib.serf_varint_encode.argtypes = [
            ctypes.c_uint64, ctypes.POINTER(ctypes.c_ubyte)]
        lib.serf_varint_decode.restype = ctypes.c_long
        lib.serf_varint_decode.argtypes = [
            ctypes.c_char_p, ctypes.c_long, ctypes.POINTER(ctypes.c_uint64)]
        for name in ("serf_xxhash32", "serf_murmur3_32"):
            fn = getattr(lib, name, None)
            if fn is not None:
                fn.restype = ctypes.c_uint32
                fn.argtypes = [ctypes.c_char_p, ctypes.c_long,
                               ctypes.c_uint32]
        _lib = lib
        return _lib


_tls = threading.local()


def _scratch(n_fields: int):
    """Reusable per-thread output buffer (ctypes allocation dominates the
    cost of scanning small packets otherwise)."""
    buf = getattr(_tls, "buf", None)
    if buf is None or len(buf) < n_fields * 4:
        cap = max(n_fields * 4, 1024)
        buf = (ctypes.c_uint64 * cap)()
        _tls.buf = buf
    return buf


def scan_fields(buf: bytes, pos: int, end: int):
    """Native one-pass field scan of ``buf[pos:end]``.

    Returns a list of (field, wire_type, value, new_pos) tuples with the
    same semantics as the pure-Python ``iter_fields``, or None if the
    native library is unavailable.  Raises nothing itself — malformed input
    returns the sentinel -1 count which the caller converts to DecodeError.
    """
    lib = load()
    if lib is None:
        return None
    if not isinstance(buf, bytes):
        buf = bytes(buf)  # ctypes c_char_p needs immutable bytes
    end = min(end, len(buf))  # never hand C a length beyond the buffer
    body = buf if (pos == 0 and end == len(buf)) else buf[pos:end]
    n = len(body)
    max_fields = n // 2 + 1
    out = _scratch(max_fields)
    count = lib.serf_scan_fields(body, n, out, max_fields)
    if count < 0:
        return -1
    result = []
    for i in range(count):
        base = i * 4
        field = out[base]
        wt = out[base + 1]
        voff = out[base + 2]
        length = out[base + 3]
        if wt == 0:
            value = int(voff)
            new_pos = pos + int(length)  # C stores the post-field offset here
        else:
            value = body[voff : voff + length]
            new_pos = pos + int(voff) + int(length)
        result.append((int(field), int(wt), value, new_pos))
    return result


def checksum_fn(name: str):
    """Native checksum implementation (``xxhash32`` / ``murmur3``) or None.

    A freshly-rebuilt library always has these; ``getattr`` guards a stale
    prebuilt .so from before they existed."""
    lib = load()
    if lib is None:
        return None
    sym = {"xxhash32": "serf_xxhash32", "murmur3": "serf_murmur3_32"}.get(name)
    fn = getattr(lib, sym, None) if sym else None
    if fn is None:
        return None
    return lambda data, seed=0: fn(bytes(data), len(data), seed)


def lz4_fns():
    """Native LZ4 block (compress, decompress) or None.

    compress(data) -> bytes; decompress(data, out_size) -> bytes (exact
    declared size required; raises ValueError on malformed input)."""
    lib = load()
    if lib is None or not hasattr(lib, "serf_lz4_compress"):
        return None
    lib.serf_lz4_compress.restype = ctypes.c_long
    lib.serf_lz4_compress.argtypes = [
        ctypes.c_char_p, ctypes.c_long,
        ctypes.POINTER(ctypes.c_ubyte), ctypes.c_long]
    lib.serf_lz4_decompress.restype = ctypes.c_long
    lib.serf_lz4_decompress.argtypes = [
        ctypes.c_char_p, ctypes.c_long,
        ctypes.POINTER(ctypes.c_ubyte), ctypes.c_long]

    def compress(data: bytes) -> bytes:
        data = bytes(data)
        cap = len(data) + len(data) // 255 + 16
        out = (ctypes.c_ubyte * cap)()
        got = lib.serf_lz4_compress(data, len(data), out, cap)
        if got < 0:
            raise ValueError("lz4 compression buffer overflow")
        return bytes(out[:got])

    def decompress(data: bytes, out_size: int) -> bytes:
        data = bytes(data)
        out = (ctypes.c_ubyte * max(out_size, 1))()
        got = lib.serf_lz4_decompress(data, len(data), out, out_size)
        if got != out_size:
            raise ValueError("malformed lz4 block")
        return bytes(out[:got])

    return compress, decompress


def snappy_fns():
    """Native snappy block (compress, decompress) or None.

    compress(data) -> bytes (varint preamble included, per the snappy
    format); decompress(data, max_size) -> bytes (the block's declared
    length must match the decoded output and fit max_size; raises
    ValueError on malformed input)."""
    lib = load()
    if lib is None or not hasattr(lib, "serf_snappy_compress"):
        return None
    lib.serf_snappy_compress.restype = ctypes.c_long
    lib.serf_snappy_compress.argtypes = [
        ctypes.c_char_p, ctypes.c_long,
        ctypes.POINTER(ctypes.c_ubyte), ctypes.c_long]
    lib.serf_snappy_decompress.restype = ctypes.c_long
    lib.serf_snappy_decompress.argtypes = [
        ctypes.c_char_p, ctypes.c_long,
        ctypes.POINTER(ctypes.c_ubyte), ctypes.c_long]

    def compress(data: bytes) -> bytes:
        data = bytes(data)
        cap = len(data) + len(data) // 60 + 16
        out = (ctypes.c_ubyte * cap)()
        got = lib.serf_snappy_compress(data, len(data), out, cap)
        if got < 0:
            raise ValueError("snappy compression buffer overflow")
        return bytes(out[:got])

    def decompress(data: bytes, max_size: int) -> bytes:
        data = bytes(data)
        out = (ctypes.c_ubyte * max(max_size, 1))()
        got = lib.serf_snappy_decompress(data, len(data), out, max_size)
        if got < 0:
            raise ValueError("malformed snappy block")
        return bytes(out[:got])

    return compress, decompress
