"""Wire codec: protobuf-style varint encoding framework.

The reference hand-rolls a protobuf-style encoding (tag|wiretype lead bytes,
LEB128 varints, length-delimited nesting) for every message — deliberately not
msgpack-compatible with Go serf (reference serf-core/src/types/message.rs,
README.md:100-103).  This module provides the same primitives as a small,
dependency-free framework; message classes in ``serf_tpu.types`` declare field
specs and get symmetric encode/decode.

A C++ fast path (``native/codec.cpp``) is loaded via ctypes when built; the
pure-Python path is always available and is the semantic definition.

``WIRE_SCHEMA_VERSION`` (module attribute, lazily loaded) is the pinned
version of the whole wire surface — message field lists, wire field
numbers, the ``MessageType``/``QueryFlag`` registries — from serflint's
``serf_tpu/analysis/pins/schema_pins.json``.  Changing any of those
without bumping the pin is a lint failure (``schema-wire-drift``); the
deliberate bump is ``python tools/serflint.py --bump-schema`` (see
MIGRATION.md).  Persisted or cross-version consumers should record this
number next to encoded payloads.
"""

from __future__ import annotations

import struct
from typing import Iterator, Tuple


def __getattr__(name: str):
    # lazy so codec (imported everywhere, early) never depends on the
    # analysis package's import order
    if name == "WIRE_SCHEMA_VERSION":
        from serf_tpu.analysis.schema import wire_schema_version
        return wire_schema_version()
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

# Wire types (protobuf-compatible numbering).
WT_VARINT = 0
WT_FIXED64 = 1
WT_LENGTH_DELIMITED = 2
WT_FIXED32 = 5


class DecodeError(Exception):
    """Raised on malformed wire data (truncation, bad tag, overlong varint)."""


# Below this body size the ctypes call overhead exceeds the C scan win
# (measured: ~9us/call of ctypes setup vs ~1us/field Python loop).
NATIVE_SCAN_MIN_BYTES = 512


def _native_scan(buf: bytes, pos: int, end: int):
    """Lazy import to avoid a cycle; returns None when native is absent or
    the body is too small to amortize the ctypes round-trip."""
    if end - pos < NATIVE_SCAN_MIN_BYTES:
        return None
    from serf_tpu.codec import _native
    return _native.scan_fields(buf, pos, end)


def encode_varint(value: int) -> bytes:
    """LEB128 unsigned varint."""
    if value < 0:
        raise ValueError("varint must be non-negative")
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decode_varint(buf: bytes, pos: int = 0) -> Tuple[int, int]:
    """Decode a varint at ``pos``; returns (value, new_pos).

    Values are bounded to u64; anything that would exceed 2**64-1 raises
    ``DecodeError``.  Non-canonical (padded) encodings of in-range values are
    accepted, as in protobuf.
    """
    result = 0
    shift = 0
    n = len(buf)
    while True:
        if pos >= n:
            raise DecodeError("truncated varint")
        if shift > 63:
            raise DecodeError("varint overflow (>64 bits)")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if result > 0xFFFFFFFFFFFFFFFF:
            raise DecodeError("varint overflow (>64 bits)")
        if not (b & 0x80):
            return result, pos
        shift += 7


#: sanity bound on frames decoded from one batch payload — a crafted
#: tiny packet must not cost a million-object allocation
BATCH_MAX_FRAMES = 65536


def encode_frames(payloads) -> bytes:
    """Concatenate N opaque payloads as varint-length-prefixed frames —
    the framing primitive under the batched-codec entry point
    (``types.messages.encode_message_batch`` / ``BatchMessage``)."""
    out = bytearray()
    for p in payloads:
        out += encode_varint(len(p))
        out += p
    return bytes(out)


def decode_frames(buf: bytes, pos: int = 0) -> list:
    """Inverse of :func:`encode_frames`; fails closed with
    ``DecodeError`` on truncation or an implausible frame count."""
    parts = []
    n = len(buf)
    while pos < n:
        if len(parts) >= BATCH_MAX_FRAMES:
            raise DecodeError("batch frame count exceeds bound")
        ln, pos = decode_varint(buf, pos)
        if pos + ln > n:
            raise DecodeError("truncated batch frame")
        parts.append(buf[pos:pos + ln])
        pos += ln
    return parts


def zigzag_encode(value: int) -> int:
    return (value << 1) ^ (value >> 63) if value < 0 else value << 1


def zigzag_decode(value: int) -> int:
    return (value >> 1) ^ -(value & 1)


def tag_byte(field: int, wire_type: int) -> bytes:
    return encode_varint((field << 3) | wire_type)


def split_tag(key: int) -> Tuple[int, int]:
    return key >> 3, key & 0x7


def encode_length_delimited(field: int, payload: bytes) -> bytes:
    return tag_byte(field, WT_LENGTH_DELIMITED) + encode_varint(len(payload)) + payload


def encode_varint_field(field: int, value: int) -> bytes:
    return tag_byte(field, WT_VARINT) + encode_varint(value)


def encode_fixed64_field(field: int, value: int) -> bytes:
    return tag_byte(field, WT_FIXED64) + struct.pack("<Q", value & 0xFFFFFFFFFFFFFFFF)


def encode_double_field(field: int, value: float) -> bytes:
    return tag_byte(field, WT_FIXED64) + struct.pack("<d", value)


def encode_str_field(field: int, value: str) -> bytes:
    return encode_length_delimited(field, value.encode("utf-8"))


def encode_bytes_field(field: int, value: bytes) -> bytes:
    return encode_length_delimited(field, value)


def iter_fields(buf: bytes, pos: int = 0, end: int | None = None) -> Iterator[Tuple[int, int, object, int]]:
    """Iterate (field, wire_type, value, new_pos) over a message body.

    - WT_VARINT          -> int
    - WT_FIXED64         -> 8 raw bytes (caller interprets as u64 or f64)
    - WT_LENGTH_DELIMITED-> bytes view
    - WT_FIXED32         -> 4 raw bytes

    Uses the native C++ scanner (native/codec.cpp) when built; the Python
    loop below is the semantic oracle and the fallback.
    """
    if end is None:
        end = len(buf)
    else:
        end = min(end, len(buf))
        if end < len(buf):
            # bound the scan: a varint must not be read past `end`
            buf = buf[:end]
    scanned = _native_scan(buf, pos, end)
    if scanned is not None:
        if scanned == -1:
            raise DecodeError("malformed message body (native scanner)")
        yield from scanned
        return
    while pos < end:
        key, pos = decode_varint(buf, pos)
        field, wt = split_tag(key)
        if wt == WT_VARINT:
            value, pos = decode_varint(buf, pos)
        elif wt == WT_FIXED64:
            if pos + 8 > end:
                raise DecodeError("truncated fixed64")
            value = buf[pos : pos + 8]
            pos += 8
        elif wt == WT_LENGTH_DELIMITED:
            ln, pos = decode_varint(buf, pos)
            if pos + ln > end:
                raise DecodeError("truncated length-delimited field")
            value = buf[pos : pos + ln]
            pos += ln
        elif wt == WT_FIXED32:
            if pos + 4 > end:
                raise DecodeError("truncated fixed32")
            value = buf[pos : pos + 4]
            pos += 4
        else:
            raise DecodeError(f"unknown wire type {wt}")
        yield field, wt, value, pos


def read_double(raw) -> float:
    if not isinstance(raw, (bytes, bytearray)) or len(raw) != 8:
        raise DecodeError("expected fixed64 field")
    return struct.unpack("<d", raw)[0]


def read_u64(raw) -> int:
    if not isinstance(raw, (bytes, bytearray)) or len(raw) != 8:
        raise DecodeError("expected fixed64 field")
    return struct.unpack("<Q", raw)[0]


# Wire-type guards: decoders use these so a field encoded with the wrong wire
# type raises DecodeError at decode time instead of producing a type-confused
# message that explodes later inside a protocol handler.

def as_uint(v) -> int:
    if not isinstance(v, int):
        raise DecodeError("wire type mismatch: expected varint field")
    return v


def as_bytes(v) -> bytes:
    if not isinstance(v, (bytes, bytearray, memoryview)):
        raise DecodeError("wire type mismatch: expected length-delimited field")
    return bytes(v)


def as_str(v) -> str:
    try:
        return as_bytes(v).decode("utf-8")
    except UnicodeDecodeError as e:
        raise DecodeError(f"invalid utf-8 in string field: {e}") from e
