"""Membership-view digests — the bit-exactness ledger of record/replay.

Device plane: :func:`state_digest` folds each node's *knowledge view* —
its known-fact set (fact identity: subject/kind/incarnation/ltime/valid,
weighted by ring slot), its ground-truth liveness, incarnation and
tombstone record — into one u32 per node plus one u32 for the whole
cluster, computed INSIDE the jitted scan (an FNV-style mix; pure
elementwise + reductions, so it shards and scans for free).  The
membership view (``models.membership.intent_views`` /
``failure.believed_dead``) is a pure function of exactly these inputs,
so digest equality every round implies view equality every round; the
digest additionally covers user-event facts, which a flipped replay
event must perturb.  Deliberately NOT covered: the stamp (age) plane and
the send caches — retransmit budgets, not view state (two runs that
agree on every digest agree on what every node believes, which is the
contract the differ judges; record and replay of the same recording are
bit-exact on the full state anyway).

Host plane: :func:`host_view_digest` reuses the cluster-plane
``membership_digest`` (sorted ``(node_id, status)`` pairs per node) and
folds the per-node digests into one run digest.  Host digests are taken
at convergence *barriers* only — wall-clock gossip interleaving is not
deterministic, converged membership is (see README "Record & replay").
"""

from __future__ import annotations

import hashlib
from typing import Dict, Tuple

import jax.numpy as jnp

from serf_tpu.models.dissemination import (
    GossipConfig,
    GossipState,
    unpack_bits,
)

_FNV_PRIME = 16777619
_FNV_BASIS = 2166136261
#: odd slot/node weights (Knuth + golden-ratio constants) make the
#: commutative sum position-sensitive: the same fact hash in a different
#: ring slot, or the same per-node digest on a different node, changes
#: the fold
_SLOT_MULT = 2654435761
_NODE_MULT = 2654435769


def _mix(h: jnp.ndarray, x) -> jnp.ndarray:
    return (h ^ jnp.asarray(x).astype(jnp.uint32)) * jnp.uint32(_FNV_PRIME)


def fact_hashes(state: GossipState) -> jnp.ndarray:
    """u32[K]: one hash per ring slot over the fact's full identity."""
    f = state.facts
    h = jnp.full(f.subject.shape, _FNV_BASIS, jnp.uint32)
    h = _mix(h, f.subject)
    h = _mix(h, f.kind)
    h = _mix(h, f.incarnation)
    h = _mix(h, f.ltime)
    h = _mix(h, f.valid)
    return h


def state_digest(state: GossipState, cfg: GossipConfig
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(overall u32, per-node u32[N]) knowledge-view digest; jit-safe."""
    k = cfg.k_facts
    fh = fact_hashes(state)
    slot_w = (jnp.uint32(2) * jnp.arange(k, dtype=jnp.uint32)
              + jnp.uint32(1)) * jnp.uint32(_SLOT_MULT)
    weighted = fh * slot_w                                   # u32[K]
    known = unpack_bits(state.known, k)                      # bool[N, K]
    node = jnp.sum(jnp.where(known, weighted[None, :], jnp.uint32(0)),
                   axis=1, dtype=jnp.uint32)
    node = _mix(node, state.alive)
    node = _mix(node, state.tombstone)
    node = _mix(node, state.incarnation)
    n = node.shape[0]
    node_w = (jnp.uint32(2) * jnp.arange(n, dtype=jnp.uint32)
              + jnp.uint32(1)) * jnp.uint32(_NODE_MULT)
    overall = jnp.sum(node * node_w, dtype=jnp.uint32)
    overall = _mix(overall, state.round)
    return overall, node


def host_view_digest(serfs) -> Tuple[str, Dict[str, str]]:
    """(overall 16-hex, {node_id: 12-hex}) membership-view digest over
    the given live Serf nodes (host plane, barrier points only)."""
    from serf_tpu.obs.cluster import membership_digest

    nodes = {
        s.local_id: membership_digest(
            [(m.node.id, m.status.name) for m in s.members()])
        for s in serfs
    }
    h = hashlib.sha256()
    for nid, d in sorted(nodes.items()):
        h.update(nid.encode("utf-8", errors="replace"))
        h.update(b"\x00")
        h.update(d.encode("ascii"))
        h.update(b"\x01")
    return h.hexdigest()[:16], nodes
