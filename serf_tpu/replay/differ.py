"""Digest-stream differ: where did two runs first disagree, and how.

Compares two recordings' ordered step-chain + membership-view digest
streams and reports the **first divergent round** (first view record
whose digest differs) plus the per-node view delta at that round, and
the first divergent *step* (first ingress action whose chain hash
differs — pinpoints a perturbed/injected event even when the view
consequence lands rounds later).  ``tools/replay.py diff`` renders the
report and exits nonzero on any divergence — a red chaos run's artifact
plus this differ is a bisectable repro, not an anecdote.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from serf_tpu.obs import flight
from serf_tpu.utils import metrics

from serf_tpu.replay.recording import Recording


@dataclass
class DiffReport:
    ok: bool = True
    compared_steps: int = 0
    compared_views: int = 0
    #: first view record whose digest differs (protocol round on device,
    #: barrier index on host); None = all compared views agree
    first_divergent_round: Optional[int] = None
    #: per-node digest delta at that round: {node: [a_digest, b_digest]}
    node_delta: Dict[str, List[Optional[str]]] = field(default_factory=dict)
    #: first step whose chain differs: {"seq", "a", "b"} with both sides'
    #: op + args; None = all compared steps agree
    first_divergent_step: Optional[Dict[str, Any]] = None
    #: header-level mismatches (plane/plan/config fingerprint)
    header_notes: List[str] = field(default_factory=list)
    #: one stream ended before the other
    length_note: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {
            "ok": self.ok,
            "compared_steps": self.compared_steps,
            "compared_views": self.compared_views,
            "first_divergent_round": self.first_divergent_round,
            "node_delta": self.node_delta,
            "first_divergent_step": self.first_divergent_step,
            "header_notes": self.header_notes,
            "length_note": self.length_note,
        }

    def format(self) -> str:
        lines = [f"replay diff: {'IDENTICAL' if self.ok else 'DIVERGED'} "
                 f"({self.compared_steps} steps, {self.compared_views} "
                 "view rounds compared)"]
        for note in self.header_notes:
            lines.append(f"  header: {note}")
        if self.first_divergent_step is not None:
            s = self.first_divergent_step
            lines.append(f"  first divergent step: seq {s['seq']} — "
                         f"a={s['a']} vs b={s['b']}")
        if self.first_divergent_round is not None:
            lines.append(
                f"  first divergent round: {self.first_divergent_round}")
            shown = sorted(self.node_delta)[:8]
            for node in shown:
                a, b = self.node_delta[node]
                lines.append(f"    node {node}: {a} vs {b}")
            more = len(self.node_delta) - len(shown)
            if more > 0:
                lines.append(f"    ... {more} more node(s) differ")
        if self.length_note:
            lines.append(f"  {self.length_note}")
        return "\n".join(lines)


def _node_delta(a_nodes, b_nodes) -> Dict[str, List[Optional[str]]]:
    """Per-node digests may be dicts (host: id -> hex) or lists (device:
    index -> hex) or None (past NODE_DIGEST_CAP)."""
    if a_nodes is None or b_nodes is None:
        return {}
    if isinstance(a_nodes, list):
        a_nodes = {str(i): v for i, v in enumerate(a_nodes)}
    if isinstance(b_nodes, list):
        b_nodes = {str(i): v for i, v in enumerate(b_nodes)}
    out: Dict[str, List[Optional[str]]] = {}
    for node in sorted(set(a_nodes) | set(b_nodes)):
        av, bv = a_nodes.get(node), b_nodes.get(node)
        if av != bv:
            out[node] = [av, bv]
    return out


def diff_recordings(a: Recording, b: Recording) -> DiffReport:
    """Compare two recordings' digest streams entry by entry."""
    rep = DiffReport()
    for key in ("plane", "fingerprint"):
        if a.header.get(key) != b.header.get(key):
            rep.header_notes.append(
                f"{key}: {a.header.get(key)!r} != {b.header.get(key)!r}")
            rep.ok = False
    sa, sb = a.digest_stream(), b.digest_stream()
    for ra, rb in zip(sa, sb):
        if ra["kind"] != rb["kind"]:
            rep.ok = False
            if rep.first_divergent_step is None:
                rep.first_divergent_step = {
                    "seq": ra["seq"],
                    "a": {"kind": ra["kind"]}, "b": {"kind": rb["kind"]}}
            break
        if ra["kind"] == "step":
            rep.compared_steps += 1
            if ra["chain"] != rb["chain"] \
                    and rep.first_divergent_step is None:
                rep.ok = False
                rep.first_divergent_step = {
                    "seq": ra["seq"],
                    "a": {"op": ra["op"], "args": ra["args"]},
                    "b": {"op": rb["op"], "args": rb["args"]},
                }
        else:
            rep.compared_views += 1
            if ra["digest"] != rb["digest"] \
                    and rep.first_divergent_round is None:
                rep.ok = False
                rep.first_divergent_round = ra["round"]
                rep.node_delta = _node_delta(ra.get("nodes"),
                                             rb.get("nodes"))
    if len(sa) != len(sb):
        rep.ok = False
        rep.length_note = (f"streams differ in length: {len(sa)} vs "
                           f"{len(sb)} records")
    if not rep.ok:
        metrics.incr("serf.replay.divergence")
        flight.record("replay-divergence",
                      round=rep.first_divergent_round,
                      step=(rep.first_divergent_step or {}).get("seq"))
    return rep
