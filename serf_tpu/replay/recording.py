"""Recording format for the deterministic record/replay plane (ISSUE 9).

A recording is versioned JSONL — one JSON object per line — capturing
everything non-deterministic about a chaos run so it can be re-executed
bit-exactly (device plane) or re-driven with virtualized timing (host
plane) and judged round by round:

- ``header`` (first line): recording-format version (``v`` — pinned in
  ``serf_tpu/analysis/pins/schema_pins.json`` like the checkpoint pytree
  and wire schemas; see MIGRATION.md "Schema versioning"), plane, the
  full serialized :class:`~serf_tpu.faults.plan.FaultPlan`, its seed,
  the executor config (device: the whole ``ClusterConfig``; host: the
  Options mode) and a fingerprint over both;
- ``step``: one ingress/driver action in applied order — device:
  ``init`` (cluster construction key) / ``inject`` (explicit fact
  batches: eids, origins, ltimes — the replayer consumes THESE, not a
  re-derivation, so a perturbed recording replays perturbed) / ``scan``
  (phase index, round count, raw PRNG key material); host: ``join`` /
  ``user-event`` / ``query`` (via the ``Serf.set_ingress_tap`` seam) /
  ``phase`` / ``restart`` / ``heal`` / ``barrier``.  Every step carries
  a ``chain`` hash folding the step content into the previous chain, so
  the differ can name the exact first divergent step;
- ``view``: a membership-view digest snapshot (device: one per protocol
  round from inside the jitted scan; host: one per convergence barrier)
  — the bit-exactness ledger the differ compares;
- ``end`` (last line): step/view counts — truncated-file detection.

The record kinds and their field lists are declared in
``RECORDING_SCHEMA`` below, which serflint AST-fingerprints and pins
(rule ``schema-recording-drift``): changing the format without
``python tools/serflint.py --bump-schema`` is a lint failure.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from pathlib import Path
from typing import Any, Callable, Dict, Iterator, List, Optional

from serf_tpu.faults.plan import EdgeFault, FaultPhase, FaultPlan
from serf_tpu.obs import flight
from serf_tpu.utils import metrics

#: the declared record surface: kind -> ordered field names.  serflint's
#: ``schema-recording-drift`` rule fingerprints THIS literal — adding,
#: removing or renaming a field is a deliberate, version-bumped act.
RECORDING_SCHEMA = {
    "header": ("v", "plane", "plan", "seed", "config", "fingerprint"),
    "step": ("seq", "op", "args", "chain"),
    "view": ("seq", "round", "digest", "nodes"),
    "end": ("seq", "steps", "views"),
}

#: per-node digests are embedded in ``view`` records only up to this
#: node count; past it only the overall digest is stored (the differ
#: then reports the divergent round without a per-node delta)
NODE_DIGEST_CAP = 4096


def recording_schema_version() -> int:
    """The pinned recording-format version (lazy import so the replay
    plane never rides the analysis package into runtime processes that
    do not record)."""
    from serf_tpu.analysis.schema import recording_schema_version as v

    return v()


def _canon(obj: Any) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def _fingerprint(obj: Any) -> str:
    return hashlib.sha256(_canon(obj).encode()).hexdigest()[:16]


# ---------------------------------------------------------------------------
# plan / config serde
# ---------------------------------------------------------------------------


def plan_to_dict(plan: FaultPlan) -> Dict[str, Any]:
    return dataclasses.asdict(plan)


def plan_from_dict(d: Dict[str, Any]) -> FaultPlan:
    phases = []
    for ph in d["phases"]:
        ph = dict(ph)
        ph["partitions"] = tuple(tuple(g) for g in ph.get("partitions", ()))
        ph["edges"] = tuple(EdgeFault(**e) for e in ph.get("edges", ()))
        for key in ("crash", "pause", "restart", "stall", "rotate"):
            ph[key] = tuple(ph.get(key, ()))
        phases.append(FaultPhase(**ph))
    plan = FaultPlan(name=d["name"], n=int(d["n"]), phases=tuple(phases),
                     seed=int(d.get("seed", 0)),
                     # pre-PR-20 recordings carry no encrypted flag
                     encrypted=bool(d.get("encrypted", False)),
                     settle_s=float(d.get("settle_s", 8.0)),
                     settle_rounds=int(d.get("settle_rounds", 40)))
    plan.validate()
    return plan


def device_config_to_dict(cfg) -> Dict[str, Any]:
    """Full ``ClusterConfig`` serialization (nested frozen dataclasses)."""
    return dataclasses.asdict(cfg)


def device_config_from_dict(d: Dict[str, Any]):
    from serf_tpu.control.device import ControlConfig
    from serf_tpu.models.failure import FailureConfig
    from serf_tpu.models.swim import ClusterConfig
    from serf_tpu.models.dissemination import GossipConfig
    from serf_tpu.models.vivaldi import VivaldiConfig

    top = {k: v for k, v in d.items()
           if k not in ("gossip", "failure", "vivaldi", "control")}
    return ClusterConfig(
        gossip=GossipConfig(**d["gossip"]),
        failure=FailureConfig(**d["failure"]),
        vivaldi=VivaldiConfig(**d["vivaldi"]),
        # pre-PR-11 recordings carry no control block: static default
        control=ControlConfig(**d.get("control", {})),
        **top)


# ---------------------------------------------------------------------------
# recordings
# ---------------------------------------------------------------------------


class RecordingError(ValueError):
    """A recording could not be parsed / replayed (bad version, truncated
    file, unsupported config)."""


class Recording:
    """A loaded (or just-produced) recording: header + ordered records."""

    def __init__(self, header: Dict[str, Any], records: List[Dict[str, Any]]):
        self.header = header
        self.records = records

    @property
    def plane(self) -> str:
        return self.header["plane"]

    def steps(self) -> Iterator[Dict[str, Any]]:
        return (r for r in self.records if r["kind"] == "step")

    def views(self) -> List[Dict[str, Any]]:
        return [r for r in self.records if r["kind"] == "view"]

    def digest_stream(self) -> List[Dict[str, Any]]:
        """The ordered comparison surface: step + view records."""
        return [r for r in self.records if r["kind"] in ("step", "view")]

    def plan(self) -> FaultPlan:
        return plan_from_dict(self.header["plan"])

    @classmethod
    def load(cls, path) -> "Recording":
        lines = Path(path).read_text().splitlines()
        if not lines:
            raise RecordingError(f"{path}: empty recording")
        try:
            rows = [json.loads(ln) for ln in lines if ln.strip()]
        except json.JSONDecodeError as e:
            raise RecordingError(f"{path}: undecodable line: {e}") from e
        header = rows[0]
        if header.get("kind") != "header":
            raise RecordingError(f"{path}: first record is not a header")
        v = header.get("v")
        if v != recording_schema_version():
            raise RecordingError(
                f"{path}: recording format v{v} != pinned "
                f"v{recording_schema_version()} (see MIGRATION.md "
                "'Schema versioning')")
        records = rows[1:]
        end = [r for r in records if r.get("kind") == "end"]
        if not end:
            raise RecordingError(f"{path}: no end record (truncated file?)")
        n_steps = sum(1 for r in records if r.get("kind") == "step")
        n_views = sum(1 for r in records if r.get("kind") == "view")
        if end[-1].get("steps") != n_steps or end[-1].get("views") != n_views:
            raise RecordingError(
                f"{path}: end record counts ({end[-1].get('steps')} steps/"
                f"{end[-1].get('views')} views) disagree with the file "
                f"({n_steps}/{n_views}) — truncated or edited recording")
        return cls(header, [r for r in records if r.get("kind") != "end"]
                   + end[-1:])

    def save(self, path) -> str:
        p = Path(path)
        with p.open("w") as f:
            f.write(_canon(self.header) + "\n")
            for r in self.records:
                f.write(_canon(r) + "\n")
        metrics.incr("serf.replay.records", 1 + len(self.records))
        flight.record("replay-recorded", path=str(p),
                      plane=self.header.get("plane"),
                      plan=self.header.get("plan", {}).get("name"))
        return str(p)


class RunRecorder:
    """Builds a recording as a run executes.  The executors
    (``faults.host.run_host_plan`` / ``faults.device.run_device_plan``)
    call :meth:`header` once, then :meth:`step` / :meth:`view` in applied
    order; :meth:`finish` seals the trailer (idempotent)."""

    def __init__(self):
        self.records: List[Dict[str, Any]] = []
        self._header: Optional[Dict[str, Any]] = None
        self._seq = 0
        self._chain = "0" * 16
        self._finished = False

    def header(self, plane: str, plan: Dict[str, Any], seed: int,
               config: Dict[str, Any]) -> None:
        if self._header is not None:
            raise RecordingError("recorder header written twice")
        self._header = {
            "kind": "header",
            "v": recording_schema_version(),
            "plane": plane,
            "plan": plan,
            "seed": int(seed),
            "config": config,
            "fingerprint": _fingerprint({"plan": plan, "config": config}),
        }
        # the chain starts from the run identity, so two recordings of
        # DIFFERENT runs never share step chains even for equal prefixes
        self._chain = self._header["fingerprint"]

    def step(self, op: str, **args: Any) -> Dict[str, Any]:
        self._seq += 1
        self._chain = hashlib.sha256(
            (self._chain + _canon({"op": op, "args": args})).encode()
        ).hexdigest()[:16]
        rec = {"kind": "step", "seq": self._seq, "op": op, "args": args,
               "chain": self._chain}
        self.records.append(rec)
        return rec

    def view(self, round_: int, digest: str,
             nodes: Optional[Any] = None) -> Dict[str, Any]:
        self._seq += 1
        rec = {"kind": "view", "seq": self._seq, "round": int(round_),
               "digest": digest, "nodes": nodes}
        self.records.append(rec)
        return rec

    def ingress_tap(self) -> Callable:
        """The callable ``Serf.set_ingress_tap`` expects: records every
        offered ``user_event``/``query`` as a step (payload hex-encoded)."""
        def tap(op: str, node: str, **args: Any) -> None:
            payload = args.pop("payload", b"")
            self.step(op, node=node, payload=payload.hex(), **args)
        return tap

    def finish(self) -> None:
        if self._finished:
            return
        self._finished = True
        n_views = sum(1 for r in self.records if r["kind"] == "view")
        self._seq += 1
        self.records.append({
            "kind": "end", "seq": self._seq,
            "steps": sum(1 for r in self.records if r["kind"] == "step"),
            "views": n_views,
        })
        metrics.gauge("serf.replay.rounds", n_views)

    def to_recording(self) -> Recording:
        if self._header is None:
            raise RecordingError("recorder has no header")
        self.finish()
        return Recording(dict(self._header), list(self.records))

    def save(self, path) -> str:
        return self.to_recording().save(path)


def load_recording(path) -> Recording:
    return Recording.load(path)


def record_scan_controls(recorder: RunRecorder, base_round: int,
                         rows, prev_row):
    """Append one ``control`` step per controller DECISION (round where
    the knob vector changed) from a host-side stacked control-row block
    — THE one formatting path shared by the recorder
    (``faults.device.run_device_plan``) and ``replay.replayer
    .replay_device``, like :func:`record_scan_views`: the replayer
    re-DERIVES its control rows from the scan and emits through this
    same function, so a recorded and a replayed controlled run can only
    produce identical step chains if the control plane is bit-exact —
    and a perturbed recording's diff names the first divergent control
    decision.  Returns the block's last row (the caller threads it into
    the next scan's extraction)."""
    from serf_tpu.control.device import decisions_of

    decisions, prev = decisions_of(prev_row, rows, base_round)
    for d in decisions:
        recorder.step("control", **d)
    return prev


def record_scan_views(recorder: RunRecorder, base_round: int, dg, dn,
                      include_nodes: bool) -> None:
    """Transfer one phase scan's digest stream (``run_phase(...,
    collect_digests=True)`` output) and append one ``view`` record per
    round.  This is the ONE formatting path shared by the recorder
    (``faults.device.run_device_plan``) and ``replay.replayer
    .replay_device`` — record and replay streams can only compare equal
    if they are emitted in lockstep, so neither side formats on its
    own."""
    import jax

    digests = jax.device_get(dg)
    node_digests = jax.device_get(dn) if include_nodes else None
    for j, d in enumerate(digests):
        recorder.view(
            round_=base_round + j + 1,
            digest=f"{int(d):08x}",
            nodes=([f"{int(x):08x}" for x in node_digests[j]]
                   if node_digests is not None else None))


# ---------------------------------------------------------------------------
# PRNG key serde (device plane; jax imported lazily so the recording
# format itself stays importable in host-only / tooling processes)
# ---------------------------------------------------------------------------


def key_to_hex(key) -> str:
    import jax
    import numpy as np

    return np.asarray(jax.random.key_data(key)).tobytes().hex()


def key_from_hex(h: str):
    import jax
    import jax.numpy as jnp
    import numpy as np

    data = np.frombuffer(bytes.fromhex(h), np.uint32)
    return jax.random.wrap_key_data(jnp.asarray(data))
