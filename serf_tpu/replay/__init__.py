"""Deterministic record/replay plane (ISSUE 9).

Closes the observability loop ROADMAP item 5 calls for: a chaos run's
non-deterministic ingress — seed, config, joins, user events, queries,
the FaultPlan phase schedule — is captured as a compact versioned JSONL
**recording** (``replay.recording``), re-executed bit-exactly on the
device plane / re-driven with virtualized timing on the host plane
(``replay.replayer``), and judged round by round with membership-view
**digests** (``replay.digest``) by the **differ** (``replay.differ``),
which names the first divergent round and the per-node view delta.
``tools/replay.py`` is the operator CLI (record / replay / diff);
``tools/chaos.py --record-on-fail`` turns every red chaos run into a
shippable repro artifact.  The record/replay-as-debugging discipline
follows "Rethinking State-Machine Replication for Parallelism"
(PAPERS.md).

The heavy submodules (replayer, selfcheck) load lazily so importing the
package for the format/differ never pulls the executors or jax.
"""

from serf_tpu.replay.differ import DiffReport, diff_recordings  # noqa: F401
from serf_tpu.replay.recording import (  # noqa: F401
    RECORDING_SCHEMA,
    Recording,
    RecordingError,
    RunRecorder,
    load_recording,
    plan_from_dict,
    plan_to_dict,
    recording_schema_version,
)


def __getattr__(name: str):
    if name in ("replay_device", "replay_host", "replay_recording"):
        from serf_tpu.replay import replayer
        return getattr(replayer, name)
    if name in ("state_digest", "host_view_digest"):
        from serf_tpu.replay import digest
        return getattr(digest, name)
    if name == "device_roundtrip":
        from serf_tpu.replay.selfcheck import device_roundtrip
        return device_roundtrip
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
