"""Determinism self-check: record a short seeded device run, replay it,
assert digest equality.  ``bench.py`` embeds the result in
``BENCH_DETAIL.json`` every round, so a determinism regression (a
nondeterministic op sneaking into the round, a digest drift, a replay
bug) shows up in the per-round trajectory, not in a user's bug report.
"""

from __future__ import annotations

from typing import Any, Dict


def default_replay_cfg(n: int = 48, k_facts: int = 32, **gossip_kw):
    """The reference small-N device config every replay surface shares —
    the bench self-check, ``tools/replay.py record`` and the acceptance
    tests must exercise the SAME configuration or their verdicts stop
    being comparable."""
    from serf_tpu.models.dissemination import GossipConfig
    from serf_tpu.models.failure import FailureConfig
    from serf_tpu.models.swim import ClusterConfig

    return ClusterConfig(
        gossip=GossipConfig(n=n, k_facts=k_facts,
                            peer_sampling="rotation", **gossip_kw),
        failure=FailureConfig(suspicion_rounds=8, max_new_facts=8,
                              probe_schedule="round_robin"),
        push_pull_every=8)


def device_roundtrip(n: int = 48, k_facts: int = 32) -> Dict[str, Any]:
    """Record the tiny ``self-check`` plan on the device plane, replay
    it, and diff the digest streams.  Returns a compact verdict dict."""
    from serf_tpu.faults.device import run_device_plan
    from serf_tpu.faults.plan import named_plan
    from serf_tpu.replay.differ import diff_recordings
    from serf_tpu.replay.recording import RunRecorder
    from serf_tpu.replay.replayer import replay_device

    plan = named_plan("self-check")
    cfg = default_replay_cfg(n, k_facts)
    recorder = RunRecorder()
    result = run_device_plan(plan, cfg, recorder=recorder)
    recording = recorder.to_recording()
    replayed = replay_device(recording).to_recording()
    d = diff_recordings(recording, replayed)
    return {
        "plan": plan.name,
        "n": n,
        "rounds": d.compared_views,
        "digest_equal": d.ok,
        "first_divergent_round": d.first_divergent_round,
        "invariants_ok": bool(result.report.ok),
    }
