"""Re-execute a recording on its plane and re-derive the digest stream.

Device plane (:func:`replay_device`): reconstructs the mask schedule
from the recorded plan (``faults.device.lower_plan`` is pure), consumes
the recorded injection batches VERBATIM (not a re-derivation — so a
perturbed recording replays perturbed) and re-runs the jitted phase
scans with the recorded PRNG key material, emitting the same per-round
membership-view digests.  Replay of an unmodified recording is
bit-exact: every round's digest matches.

Host plane (:func:`replay_host`): stands up a fresh loopback cluster and
re-drives the recorded ingress — joins, every offered user_event/query,
phase/restart/heal transitions — with VIRTUALIZED timing: phase wall
durations are preserved, but intra-phase event spacing is not (a
phase's events are applied back-to-back at phase entry).  Re-drive is
PARALLEL by the same dependency analysis that makes the host pipeline's
MPMC consumption safe (``host.pipeline.dependency_key``): consecutive
ingress steps with the same key (one tenant's events/queries) re-drive
serially in recorded order, while cross-key steps are gathered
concurrently — commutative ingress reorders freely, exactly as it did
live.  Membership-view digests are re-taken at the recorded convergence
barriers, where converged membership is deterministic even though
gossip interleaving is not (README "Record & replay" states the full
determinism contract); pre-rebuild recordings replay to identical
barrier digests through the parallel path.
"""

from __future__ import annotations

import asyncio
from typing import Dict, Optional

from serf_tpu.replay.recording import (
    NODE_DIGEST_CAP,
    Recording,
    RecordingError,
    RunRecorder,
    device_config_from_dict,
    key_from_hex,
    plan_from_dict,
    record_scan_views,
)


def replay_device(rec: Recording, mesh=None) -> RunRecorder:
    """Re-execute a device recording; returns the replay's recorder
    (diff its ``to_recording()`` against the source with
    ``differ.diff_recordings``)."""
    import jax
    import jax.numpy as jnp

    from serf_tpu.faults.device import (
        _inject_runner,
        lower_plan,
        phase_runner,
    )
    from serf_tpu.models.swim import make_cluster

    if rec.plane != "device":
        raise RecordingError(
            f"replay_device on a {rec.plane!r}-plane recording")
    plan = plan_from_dict(rec.header["plan"])
    cfg = device_config_from_dict(rec.header["config"])
    sched = lower_plan(plan, cfg.n)
    out = RunRecorder()
    out.header(plane="device", plan=rec.header["plan"],
               seed=rec.header["seed"], config=rec.header["config"])

    run = None
    state = None
    init_alive = None
    no_group = jnp.zeros((cfg.n,), jnp.int32)
    no_down = jnp.zeros((cfg.n,), bool)
    total = 0
    want_ctl = cfg.control.enabled
    ctl_prev = None
    if want_ctl:
        import numpy as np

        from serf_tpu.control.device import knob_bounds
        base, _, _, _ = knob_bounds(cfg.control, cfg.gossip, cfg.failure)
        ctl_prev = np.concatenate(
            [np.asarray(base, np.float32), np.zeros(2, np.float32)])
    for s in rec.steps():
        op, a = s["op"], s["args"]
        if op == "init":
            if mesh is None and int(a.get("mesh_devices", 1)) > 1:
                from serf_tpu.parallel.mesh import make_mesh
                mesh = make_mesh(int(a["mesh_devices"]))
            state = make_cluster(cfg, key_from_hex(a["key"]))
            if mesh is not None:
                from serf_tpu.parallel.mesh import shard_state
                state = shard_state(state, mesh)
            init_alive = state.gossip.alive
            run = phase_runner(cfg, mesh)
            out.step("init", **a)
        elif op == "inject":
            if state is None:
                raise RecordingError("inject step before init")
            chunk = len(a["eids"])
            # same jitted chunk executable (and, under control, the same
            # admission gate) as the recording run: the control state is
            # deterministic, so the admitted subset is too; eids/ltimes/
            # origins are consumed VERBATIM (a perturbed recording
            # replays perturbed)
            run_inject = _inject_runner(cfg, want_ctl, int(a["kind"]))
            g, ctrl = run_inject(
                state.gossip, state.control,
                jnp.asarray(a["eids"], jnp.int32),
                jnp.asarray(a["ltimes"], jnp.uint32),
                jnp.asarray(a["origins"], jnp.int32),
                jnp.ones((chunk,), bool))
            state = state._replace(gossip=g, control=ctrl)
            out.step("inject", **a)
        elif op == "scan":
            if state is None:
                raise RecordingError("scan step before init")
            pi = int(a["phase"])
            num_rounds = int(a["rounds"])
            group = sched.group[pi] if pi >= 0 else no_group
            drop = sched.drop[pi] if pi >= 0 else jnp.float32(0.0)
            down = sched.down[pi] if pi >= 0 else no_down
            out.step("scan", **a)
            include_nodes = cfg.n <= NODE_DIGEST_CAP
            state, aux = run(
                state, key=key_from_hex(a["key"]), num_rounds=num_rounds,
                group=group, drop=drop, init_alive=init_alive, down=down,
                collect_digests=True, include_nodes=include_nodes,
                collect_control=want_ctl)
            if want_ctl:
                (dg, dn), crows = aux
            else:
                dg, dn = aux
            record_scan_views(out, total, dg, dn, include_nodes)
            if want_ctl:
                from serf_tpu.replay.recording import record_scan_controls
                ctl_prev = record_scan_controls(
                    out, total, jax.device_get(crows), ctl_prev)
            total += num_rounds
        elif op == "control":
            # recorded controller decisions are DERIVED state, not
            # ingress: the replay re-computes its own from the scan (and
            # emitted them above) — the recorded ones are the comparison
            # surface, never an input
            continue
        else:
            raise RecordingError(f"unknown device step op {op!r}")
    out.finish()
    return out


def _host_node(nodes: Dict[int, object], nid) -> Optional[object]:
    """Map a recorded node reference (``"n3"`` or ``3``) to the current
    Serf instance (restart replaces entries)."""
    if isinstance(nid, str) and nid.startswith("n"):
        nid = nid[1:]
    try:
        return nodes.get(int(nid))
    except (TypeError, ValueError):
        return None


async def replay_host(rec: Recording,
                      tmp_dir: Optional[str] = None) -> RunRecorder:
    """Re-drive a host recording against a fresh loopback cluster."""
    import os

    from serf_tpu.faults import invariants as inv
    from serf_tpu.faults.host import HostFaultExecutor, _load_opts
    from serf_tpu.host.query import QueryParam
    from serf_tpu.host.serf import Serf, SerfState
    from serf_tpu.host.transport import LoopbackNetwork
    from serf_tpu.options import Options
    from serf_tpu.replay.digest import host_view_digest

    if rec.plane != "host":
        raise RecordingError(
            f"replay_host on a {rec.plane!r}-plane recording")
    if rec.header["config"].get("options") != "default":
        raise RecordingError(
            "host replay supports executor-default Options only (the "
            "recording was made with custom opts)")
    # snapshots change restart semantics (a crashed node comes back warm
    # from its snapshot), so replay must match the recorded flag exactly:
    # a snapshot-less recording replays snapshot-less even when the
    # caller offers a tmp_dir, and a snapshotted one fails closed
    # without somewhere to put them
    snapshots = bool(rec.header["config"].get("snapshots"))
    if snapshots and tmp_dir is None:
        raise RecordingError(
            "recording was made with per-node snapshots; replay_host "
            "needs a tmp_dir to reproduce restart-from-snapshot")
    plan = plan_from_dict(rec.header["plan"])
    n = plan.n
    base_opts = _load_opts(plan) if plan.has_load() else Options.local()
    out = RunRecorder()
    out.header(plane="host", plan=rec.header["plan"],
               seed=rec.header["seed"], config=rec.header["config"])
    net = LoopbackNetwork()
    ex = HostFaultExecutor(plan, net)
    nodes: Dict[int, Serf] = {}

    def node_opts(i: int):
        if not snapshots:
            return base_opts
        return base_opts.replace(
            snapshot_path=os.path.join(tmp_dir, f"replay-n{i}.snap"))

    async def make_node(i: int) -> Serf:
        return await Serf.create(net.bind(f"n{i}"), node_opts(i), f"n{i}")

    barrier_index = 0
    pending_sleep = 0.0

    async def serve_phase_window() -> None:
        # virtualized timing: the open phase's wall duration is served
        # when the stream reaches the step that ends it — its events
        # were applied back-to-back at phase entry
        nonlocal pending_sleep
        if pending_sleep > 0:
            await asyncio.sleep(pending_sleep)
            pending_sleep = 0.0

    # -- dependency-aware parallel re-drive ---------------------------------
    # consecutive ingress steps accumulate into per-dependency-key
    # chains (host.pipeline.dependency_key semantics: tenant name class);
    # a flush re-drives every chain concurrently, each chain serially in
    # recorded order.  Any non-ingress step is a barrier for the window.
    from serf_tpu.host.pipeline import name_class

    ingress_window: Dict[tuple, list] = {}

    async def _drive_one(a: dict, is_query: bool) -> None:
        node = _host_node(nodes, a["node"])
        if node is None or node.state != SerfState.ALIVE:
            return
        try:
            if is_query:
                # recorded verbatim: 0.0 is QueryParam's "use the
                # node's default_query_timeout" sentinel, not a
                # missing value
                await node.query(
                    a["name"], bytes.fromhex(a["payload"]),
                    QueryParam(timeout=float(a.get("timeout", 0.0))))
            else:
                await node.user_event(
                    a["name"], bytes.fromhex(a["payload"]),
                    coalesce=bool(a.get("coalesce", False)))
        except Exception:  # noqa: BLE001 - replay is best-effort (sheds
            # replay as sheds: an OverloadError here IS fidelity)
            pass

    async def _drive_chain(steps: list) -> None:
        for a, is_query in steps:          # per-key: recorded order
            await _drive_one(a, is_query)
            await asyncio.sleep(0)

    async def flush_ingress() -> None:
        if not ingress_window:
            return
        chains = list(ingress_window.values())
        ingress_window.clear()
        await asyncio.gather(*(_drive_chain(c) for c in chains))

    try:
        for i in range(n):
            nodes[i] = await make_node(i)
        for s in rec.steps():
            op, a = s["op"], s["args"]
            out.step(op, **a)
            if op in ("user-event", "query"):
                is_query = op == "query"
                key = ("query" if is_query else "user",
                       name_class(a["name"]))
                ingress_window.setdefault(key, []).append((a, is_query))
                continue
            # every other step is an ordering barrier for the window
            await flush_ingress()
            if op == "join":
                try:
                    await nodes[int(a["node"])].join(a["target"])
                except Exception:  # noqa: BLE001 - replay is best-effort
                    pass
            elif op == "phase":
                await serve_phase_window()
                pi = int(a["index"])
                phase = plan.phases[pi]
                for i in phase.crash:
                    if nodes[i].state != SerfState.SHUTDOWN:
                        await nodes[i].shutdown()
                ex.apply_phase(pi)
                pending_sleep = phase.duration_s
            elif op == "restart":
                i = int(a["node"])
                if nodes[i].state == SerfState.SHUTDOWN:
                    nodes[i] = await make_node(i)
                if a.get("seed"):
                    try:
                        await nodes[i].join(a["seed"])
                    except Exception:  # noqa: BLE001
                        pass
            elif op == "control":
                # re-apply the recorded controller decision at its
                # stream position: host replay reproduces the recorded
                # adaptations instead of re-running a controller against
                # nondeterministic timing
                from serf_tpu.control.host import apply_recorded
                apply_recorded(nodes, a["knob"], float(a["value"]))
            elif op == "heal":
                await serve_phase_window()
                ex.clear()
            elif op == "barrier":
                await serve_phase_window()
                live = [nodes[i] for i in nodes
                        if nodes[i].state == SerfState.ALIVE]
                await inv.wait_host_convergence(
                    live, deadline_s=float(a.get("deadline_s",
                                                 plan.settle_s)))
                digest, node_digests = host_view_digest(live)
                out.view(round_=barrier_index, digest=digest,
                         nodes=node_digests)
                barrier_index += 1
            else:
                raise RecordingError(f"unknown host step op {op!r}")
        await flush_ingress()
        out.finish()
        return out
    finally:
        for s in nodes.values():
            if s.state != SerfState.SHUTDOWN:
                await s.shutdown()


def replay_recording(rec: Recording, tmp_dir: Optional[str] = None,
                     mesh=None) -> RunRecorder:
    """Plane-dispatching convenience: replays on whichever plane the
    recording was made (host replays inside a private event loop)."""
    if rec.plane == "device":
        return replay_device(rec, mesh=mesh)
    return asyncio.run(replay_host(rec, tmp_dir=tmp_dir))
