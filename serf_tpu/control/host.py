"""Host-plane adaptive control: a controller tick on the MetricsSampler.

:class:`ControllerTick` closes the loop the PR-10 host sampler opened:
every tick it reads the live burn-rate evidence (the sampler's delta
rings + the nodes' own membership views) and actuates the
formerly-static host knobs — the PR-5 admission buckets, the PR-4
breaker cooldown, and the memberlist probe/gossip cadence + suspicion
multiplier (Lifeguard's local-health stretch made cluster-wide).

Same discipline as the device law (``control/device.py``): a
declarative law table (:data:`HOST_LAWS`, lint-checked against
:data:`HOST_KNOBS` and the declared registry), per-knob hysteresis
streaks (fast protective moves, slow relaxation), bounded multiplicative
steps inside clamp bands, and every decision observable — a
``control-decision`` flight event, ``serf.control.knob.<>`` gauges, a
``serf.control.steps`` counter, and (when a PR-9 recorder is attached)
a ``control`` step in the recording so a bad adaptation is a bisectable
artifact (``replay.replayer.replay_host`` re-applies recorded decisions
at their stream positions via :func:`apply_recorded`).

Actuation is idempotent: the controller re-applies the current absolute
target values to every live node each tick, so a node the chaos plan
restarted (fresh Serf, base knobs) is re-converged onto the adapted
operating point at the next tick without special-casing.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

from serf_tpu.obs import flight
from serf_tpu.utils import metrics
from serf_tpu.utils.logging import get_logger

log = get_logger("control.host")

#: the controller-writable host knob set.  serflint's
#: ``control-knob-drift`` holds this literal to the declared registry
#: (analysis/registry.py CONTROL_KNOBS) and to HOST_LAWS, both ways.
HOST_KNOBS = ("user_event_rate", "query_rate", "breaker_cooldown",
              "suspicion_mult", "probe_interval", "gossip_nodes",
              "gossip_interval")

#: declarative law table: (signal, knob, direction).  README "Adaptive
#: control" documents each row with its step and clamp.
HOST_LAWS = (
    # shed burning while the node is HEALTHY = the bucket is tighter
    # than measured capacity -> admit more; degraded health -> tighten
    ("shed-burn-healthy", "user_event_rate", "up"),
    ("health-degraded", "user_event_rate", "down"),
    ("shed-burn-healthy", "query_rate", "up"),
    ("health-degraded", "query_rate", "down"),
    # breaker churn = peers flapping under degradation -> longer
    # cooldowns (fewer wasted trials); calm -> restore
    ("breaker-churn", "breaker_cooldown", "up"),
    ("breaker-calm", "breaker_cooldown", "down"),
    # responsive-node false-DEAD = the detector is outrunning the
    # network -> stretch suspicion + slow probing (Lifeguard, made
    # cluster-wide); clear -> restore
    ("false-dead", "suspicion_mult", "up"),
    ("false-dead-clear", "suspicion_mult", "down"),
    ("false-dead", "probe_interval", "up"),
    ("false-dead-clear", "probe_interval", "down"),
    # membership views diverging = convergence burning -> widen gossip
    # fan-out and tighten the gossip interval; converged -> restore
    ("view-divergence", "gossip_nodes", "up"),
    ("view-converged", "gossip_nodes", "down"),
    ("view-divergence", "gossip_interval", "down"),
    ("view-converged", "gossip_interval", "up"),
)


@dataclasses.dataclass(frozen=True)
class HostControlConfig:
    enabled: bool = False
    #: consecutive ticks of a protective signal before an actuation
    hyst_up: int = 2
    #: consecutive ticks of a relaxing signal before an actuation
    hyst_down: int = 6
    #: multiplicative step per actuation for float knobs
    step: float = 1.5
    #: clamp band for float knobs, as multiples of the baseline value
    max_scale: float = 8.0
    min_scale: float = 0.25
    #: additive step bound for the integer knobs (suspicion_mult,
    #: gossip_nodes)
    int_step: int = 1
    int_headroom: int = 3
    #: windowed shed/(shed+admitted) above this = shed burning
    shed_burn_hi: float = 0.5
    #: health score floor: above = healthy enough to widen admission,
    #: below = degraded (tighten)
    health_floor: int = 60
    #: ring window (points) the shed/breaker burn signals read
    window: int = 8


#: float knobs move multiplicatively (×step / ÷step); int knobs move by
#: ±int_step.  "up"/"down" in HOST_LAWS refer to the VALUE.
_INT_KNOBS = frozenset({"suspicion_mult", "gossip_nodes"})
#: the protective direction per knob — gets hyst_up; the opposite
#: (relaxing, back toward base) gets hyst_down
_PROTECT: Dict[str, str] = {
    "user_event_rate": "up", "query_rate": "up",
    "breaker_cooldown": "up", "suspicion_mult": "up",
    "probe_interval": "up", "gossip_nodes": "up",
    "gossip_interval": "down",
}


def _window_sum(series, window: int) -> float:
    if series is None:
        return 0.0
    return float(sum(series.values(last=window)))


class ControllerTick:
    """The host control loop.  Construct with a callable returning the
    CURRENT live Serf list (restarts swap instances) and the sampler's
    :class:`~serf_tpu.obs.timeseries.SeriesStore`; call :meth:`tick`
    once per sampler tick."""

    def __init__(self, live: Callable[[], List[object]], store,
                 cfg: Optional[HostControlConfig] = None,
                 recorder=None):
        self.live = live
        self.store = store
        self.cfg = cfg or HostControlConfig(enabled=True)
        self.recorder = recorder
        self.ticks = 0
        #: per-knob signed hysteresis streaks (+ toward "up")
        self._streak: Dict[str, int] = {k: 0 for k in HOST_KNOBS}
        #: decision log: (tick, knob, old, new) — the stability
        #: invariant's trajectory
        self.decisions: List[Tuple[int, str, float, float]] = []
        self._base: Optional[Dict[str, float]] = None
        self.values: Dict[str, float] = {}

    # -- knob access ---------------------------------------------------------

    def _snapshot_base(self, serf) -> Dict[str, float]:
        ml = serf.memberlist
        buckets = getattr(serf._admission, "_buckets", {})
        return {
            "user_event_rate": getattr(buckets.get("user_event"), "rate",
                                       0.0),
            "query_rate": getattr(buckets.get("query"), "rate", 0.0),
            "breaker_cooldown": ml.opts.breaker_cooldown,
            "suspicion_mult": float(ml.opts.suspicion_mult),
            "probe_interval": ml.opts.probe_interval,
            "gossip_nodes": float(ml.opts.gossip_nodes),
            "gossip_interval": ml.opts.gossip_interval,
        }

    def bounds(self) -> Dict[str, Tuple[float, float, float]]:
        """{knob: (lo, hi, max_step_ratio_or_delta)} — the clamp/step
        spec the stability invariant checks the decision log against."""
        assert self._base is not None
        out: Dict[str, Tuple[float, float, float]] = {}
        for k in HOST_KNOBS:
            b = self._base[k]
            if k in _INT_KNOBS:
                out[k] = (b, b + self.cfg.int_headroom,
                          float(self.cfg.int_step))
            else:
                out[k] = (b * self.cfg.min_scale, b * self.cfg.max_scale,
                          self.cfg.step)
        return out

    def _apply(self, serfs) -> None:
        """Idempotently push the current target values onto every live
        node (restarted nodes re-converge onto the adapted point)."""
        for s in serfs:
            ml = s.memberlist
            ml.opts = dataclasses.replace(
                ml.opts,
                breaker_cooldown=self.values["breaker_cooldown"],
                suspicion_mult=int(round(self.values["suspicion_mult"])),
                probe_interval=self.values["probe_interval"],
                gossip_nodes=int(round(self.values["gossip_nodes"])),
                gossip_interval=self.values["gossip_interval"])
            ml._breaker.cooldown = self.values["breaker_cooldown"]
            buckets = getattr(s._admission, "_buckets", {})
            for op, knob in (("user_event", "user_event_rate"),
                             ("query", "query_rate")):
                bucket = buckets.get(op)
                if bucket is not None and self.values[knob] > 0:
                    bucket.rate = self.values[knob]

    # -- signals -------------------------------------------------------------

    def _signals(self, serfs) -> Dict[str, int]:
        """Per-knob desired direction (+1 up / -1 down / 0 hold)."""
        cfg = self.cfg
        shed = _window_sum(self.store.get("serf.overload.ingress_shed"),
                           cfg.window)
        admitted = _window_sum(
            self.store.get("serf.overload.ingress_admitted"), cfg.window)
        shed_ratio = shed / (shed + admitted) if (shed + admitted) > 0 \
            else 0.0
        # health comes straight off the nodes' scorers (the admission
        # gate's consume=False pattern), NOT the serf.health.score ring:
        # the periodic health monitor's cadence is much coarser than a
        # short chaos run, and a safety law that only fires when a gauge
        # happens to have been exported is dead code.  Worst (minimum)
        # node score gates the cluster-wide widening.
        health = 100.0
        for s in serfs:
            try:
                health = min(health,
                             s._health.sample(consume=False).score)
            except Exception:  # noqa: BLE001 - a broken signal never gates
                pass
        breaker_churn = _window_sum(
            self.store.get("serf.degraded.breaker_opened"), cfg.window)

        live_ids = {s.local_id for s in serfs}
        false_dead = 0
        diverged = 0
        from serf_tpu.types.member import MemberStatus
        for s in serfs:
            alive_view = set()
            for m in s.members():
                if m.status == MemberStatus.ALIVE:
                    alive_view.add(m.node.id)
                elif m.status == MemberStatus.FAILED \
                        and m.node.id in live_ids:
                    false_dead += 1
            if not live_ids <= alive_view:
                diverged += 1

        if health < cfg.health_floor:
            admission = -1
        elif shed_ratio > cfg.shed_burn_hi:
            admission = 1
        else:
            admission = 0
        fd = 1 if false_dead > 0 else -1
        view = 1 if diverged > 0 else -1
        return {
            "user_event_rate": admission,
            "query_rate": admission,
            "breaker_cooldown": 1 if breaker_churn > 0 else -1,
            "suspicion_mult": fd,
            "probe_interval": fd,
            "gossip_nodes": view,
            "gossip_interval": -view,   # diverging -> gossip FASTER (down)
        }

    # -- the tick ------------------------------------------------------------

    def tick(self) -> List[Tuple[str, float, float]]:
        """One control evaluation; returns this tick's actuations as
        ``(knob, old, new)``."""
        if not self.cfg.enabled:
            # same contract as the device plane's ControlConfig.enabled:
            # a disabled controller never touches a knob
            return []
        serfs = [s for s in self.live()]
        if not serfs:
            self.ticks += 1
            return []
        if self._base is None:
            self._base = self._snapshot_base(serfs[0])
            self.values = dict(self._base)
        cfg = self.cfg
        sig = self._signals(serfs)
        bounds = self.bounds()
        applied: List[Tuple[str, float, float]] = []
        for knob in HOST_KNOBS:
            s = sig[knob]
            streak = self._streak[knob]
            if s == 0:
                self._streak[knob] = 0
                continue
            streak = streak + s if (streak > 0) == (s > 0) and streak != 0 \
                else s
            protect_up = _PROTECT[knob] == "up"
            window = cfg.hyst_up if (s > 0) == protect_up else cfg.hyst_down
            if abs(streak) < window:
                self._streak[knob] = streak
                continue
            self._streak[knob] = 0
            lo, hi, _step = bounds[knob]
            old = self.values[knob]
            base = self._base[knob]
            if knob in _INT_KNOBS:
                new = old + s * cfg.int_step
            else:
                new = old * cfg.step if s > 0 else old / cfg.step
            # relaxing moves never cross the baseline operating point
            relaxing = (s > 0) != protect_up
            if relaxing:
                new = max(new, min(base, old)) if s < 0 \
                    else min(new, max(base, old))
            new = min(max(new, lo), hi)
            if abs(new - old) < 1e-12:
                continue
            self.values[knob] = new
            applied.append((knob, old, new))
            self.decisions.append((self.ticks, knob, old, new))
            metrics.gauge(f"serf.control.knob.{knob}", new,
                          {"plane": "host"})
            metrics.incr("serf.control.steps", 1, {"plane": "host"})
            flight.record("control-decision", plane="host", knob=knob,
                          old=round(old, 6), value=round(new, 6),
                          tick=self.ticks)
            if self.recorder is not None:
                self.recorder.step("control", knob=knob,
                                   value=round(new, 6), tick=self.ticks)
        if applied:
            log.info("control tick %d: %s", self.ticks,
                     ", ".join(f"{k} {o:g}->{n:g}" for k, o, n in applied))
        self._apply(serfs)
        self.ticks += 1
        return applied

    def trajectories(self) -> Dict[str, List[Tuple[float, float]]]:
        """Per-knob (tick, value) decision trajectories, starting at the
        baseline — the stability invariant's input."""
        assert self._base is not None or not self.decisions
        out: Dict[str, List[Tuple[float, float]]] = {
            k: [(0.0, (self._base or {}).get(k, 0.0))] for k in HOST_KNOBS}
        for tick, knob, _old, new in self.decisions:
            out[knob].append((float(tick), new))
        return out

    def to_dict(self) -> Dict[str, object]:
        return {
            "ticks": self.ticks,
            "decisions": [
                {"tick": t, "knob": k, "old": round(o, 6),
                 "value": round(n, 6)}
                for t, k, o, n in self.decisions],
            "values": {k: round(v, 6) for k, v in self.values.items()},
            "base": {k: round(v, 6)
                     for k, v in (self._base or {}).items()},
        }


def apply_recorded(nodes: Dict[int, object], knob: str,
                   value: float) -> None:
    """Apply one recorded controller decision to every live node — the
    host replayer's ``control``-step handler (replay re-applies the
    recorded adaptation at its stream position instead of re-running a
    controller against nondeterministic timing)."""
    from serf_tpu.host.serf import SerfState

    if knob not in HOST_KNOBS:
        raise ValueError(f"recorded control step names unknown knob "
                         f"{knob!r} (have {HOST_KNOBS})")
    for s in nodes.values():
        if s.state != SerfState.ALIVE:
            continue
        ml = s.memberlist
        if knob == "breaker_cooldown":
            ml.opts = dataclasses.replace(ml.opts, breaker_cooldown=value)
            ml._breaker.cooldown = value
        elif knob == "suspicion_mult":
            ml.opts = dataclasses.replace(ml.opts,
                                          suspicion_mult=int(round(value)))
        elif knob == "probe_interval":
            ml.opts = dataclasses.replace(ml.opts, probe_interval=value)
        elif knob == "gossip_nodes":
            ml.opts = dataclasses.replace(ml.opts,
                                          gossip_nodes=int(round(value)))
        elif knob == "gossip_interval":
            ml.opts = dataclasses.replace(ml.opts, gossip_interval=value)
        else:
            bucket = getattr(s._admission, "_buckets", {}).get(
                "user_event" if knob == "user_event_rate" else "query")
            if bucket is not None:
                bucket.rate = value
