"""Chaos A/B configuration profiles for the adaptive control plane.

``tools/chaos.py --controller ab`` runs every plan twice per plane —
the STATIC config (controller off) and its CONTROLLED twin — and prints
the SLO verdicts side by side.  The two named control plans
(``control-loss-converge`` / ``control-overload-shed``) carry profiles
engineered so the static run measurably breaches an SLO while the
controlled run must come back all-green; every other plan A/Bs the
default chaos config against itself-plus-controller.

The static and controlled configs deliberately share every protocol
constant except the controller's headroom: the loss plan's static
fan-out IS the controlled run's ``fanout_base`` (the controller starts
at the static operating point and may only adapt within its clamps), so
the A/B isolates the control law, not a config delta.
"""

from __future__ import annotations

from typing import Optional, Tuple

from serf_tpu.control.device import ControlConfig
from serf_tpu.control.host import HostControlConfig


def device_ab_config(plan_name: str, n: int, k_facts: int,
                     controlled: bool):
    """The device-plane ClusterConfig for one leg of a chaos A/B."""
    from serf_tpu.models.dissemination import GossipConfig
    from serf_tpu.models.failure import FailureConfig
    from serf_tpu.models.swim import ClusterConfig

    if plan_name == "control-loss-converge":
        # convergence-isolation profile: anti-entropy off (push/pull
        # would paper over stranded facts) and detection off (heavy loss
        # would otherwise churn the small ring with suspicion facts —
        # this plan judges the dissemination law).  Static fan-out 1 is
        # the breach; the controlled twin starts AT 1 with headroom to 4.
        return ClusterConfig(
            gossip=GossipConfig(n=n, k_facts=k_facts,
                                fanout=4 if controlled else 1,
                                peer_sampling="rotation"),
            failure=FailureConfig(suspicion_rounds=8, max_new_facts=8,
                                  probe_schedule="round_robin"),
            push_pull_every=0, with_failure=False, with_vivaldi=False,
            control=ControlConfig(enabled=controlled, fanout_base=1))
    if plan_name == "control-overload-shed":
        # overload profile: the storm bursts far past ring capacity;
        # static admits everything (and clobbers it), the controlled
        # twin's injection budget adapts down under overflow pressure.
        # Both legs run quarter-deferred stamp flushes at base unit 2
        # (shared protocol constant, same as fanout_base): the overflow
        # burn that tightens admission also drives STAMP_UNIT up
        # (defer harder), and the relax law walks it back to base —
        # the knob actuates both directions on this plan, and the
        # recorded controlled run replays the DEFERRED path bit-exactly
        return ClusterConfig(
            gossip=GossipConfig(n=n, k_facts=k_facts,
                                peer_sampling="rotation",
                                stamp_flush_unit=2),
            failure=FailureConfig(suspicion_rounds=8, max_new_facts=8,
                                  probe_schedule="round_robin"),
            push_pull_every=8,
            control=ControlConfig(enabled=controlled))
    # any other plan: the default chaos config, plus the controller with
    # stock clamps on the controlled leg (fan-out headroom 3 -> 4)
    return ClusterConfig(
        gossip=GossipConfig(n=n, k_facts=k_facts,
                            fanout=4 if controlled else 3,
                            peer_sampling="rotation"),
        failure=FailureConfig(suspicion_rounds=8, max_new_facts=8,
                              probe_schedule="round_robin"),
        push_pull_every=8,
        control=ControlConfig(enabled=controlled, fanout_base=3))


def host_ab_profile(plan_name: str, controlled: bool
                    ) -> Tuple[Optional[object],
                               Optional[HostControlConfig]]:
    """(opts, control_cfg) for one host-plane A/B leg.  ``opts=None``
    keeps the executor defaults (``faults.host._load_opts`` for load
    plans)."""
    if plan_name == "control-overload-shed":
        from serf_tpu.options import Options

        # deliberately conservative static buckets: rate-2 trickle +
        # burst 2 per node against a 900 ops/s storm -> the static leg
        # sheds >95% of offered load (shed-ratio breach); the controller
        # may widen up to 8x while health holds
        opts = Options.local(
            user_event_rate=2.0, user_event_burst=2,
            query_rate=2.0, query_burst=2,
            max_query_responses=64,
            event_queue_bytes=256 * 1024,
            query_queue_bytes=128 * 1024,
            event_inbox_max=2048,
        )
        return opts, (HostControlConfig(enabled=True, hyst_up=2,
                                        hyst_down=8, step=1.6,
                                        max_scale=8.0)
                      if controlled else None)
    return None, (HostControlConfig(enabled=True) if controlled else None)
