"""Device-plane adaptive control: the SLO signals actuate the knobs.

The telemetry plane (PR 10) made the cluster watch itself; this module
makes it *act*.  A small :class:`ControlState` row rides the cluster
pytree and is updated INSIDE the jitted scan (:func:`control_step`) from
the same per-round telemetry row the SLO plane judges — the
cluster-wide generalization of Lifeguard's local-health loop (PAPER.md
§"Lifeguard": a node stretches its own timeouts when its local health
degrades; here the whole simulated cluster stretches/widens/sheds from
the live convergence, false-DEAD and overflow signals).

Design rules (the anti-oscillation invariant in
``faults/invariants.py`` pins them):

- **bounded step** — each knob moves at most ``KNOB_STEP`` units per
  round, clamped to its ``[min, max]`` band;
- **hysteresis** — a knob only moves after its signal has pointed the
  same direction for ``hyst_up``/``hyst_down`` consecutive rounds
  (protective moves use the shorter window, relaxing moves the longer
  one, so the controller reacts fast and backs off slowly);
- **declarative law table** — :data:`DEVICE_LAWS` names every
  signal → knob → direction edge; serflint's ``control-knob-drift``
  rule cross-checks it (both ways) against :data:`KNOB_FIELDS` and the
  declared registry (``analysis/registry.py CONTROL_KNOBS``), so a knob
  without a law — or a law actuating an undeclared knob — fails lint.

The knobs themselves are the controller-writable subset of the
formerly-static config, now traced leaves (``KNOB_FIELDS`` order):

- ``fanout`` — effective gossip fan-out in ``[fanout_min,
  gossip.fanout]``; the static ``gossip.fanout`` is the shape bound
  (offsets are always sampled for it — same RNG stream either way) and
  the exchange masks contributions ``f >= fanout`` out;
- ``probe_mult`` — probe-cadence multiplier: probes (and the declare
  scan + Vivaldi samples that ride them) run every
  ``probe_every * probe_mult`` rounds — Lifeguard's "probe slower when
  the signal is unreliable", cluster-wide;
- ``stretch_q`` — suspicion stretch in quarter-round stamp ticks,
  added to ``failure.suspicion_q`` in the declare expiry scan and the
  ``believed_dead`` judgment (clamped at the AGE_PIN_Q representability
  bound) — Lifeguard's suspicion-timeout stretch;
- ``inject_limit`` — per-round fact-injection admission budget
  (``inject_tokens`` refills to it every round): the device analog of
  the PR-5 ingress buckets.  :func:`gate_injections` spends the tokens
  on every injection batch; refusals land in the ``shed`` ledger and
  ``serf.control.shed``;
- ``stamp_unit`` — the deferred-stamp cohort size as ``log2(unit)``
  (0/1/2 = flush every 1/2/4 rounds; readers compute ``1 << knob``):
  byte-budget burn (overflow pressure) defers harder, convergence-
  settle burn (agreement low) flushes sooner.  Pinned at 0 when the
  config is per-round (``gossip.stamp_flush_unit == 1``) — the knob
  only actuates on configs that built the overlay machinery.  Every
  unit divides STAMP_UNIT, so every multiple-of-STAMP_UNIT round is a
  flush boundary under ANY unit value: a mid-run unit switch can never
  strand a pending cohort past its quarter (the
  ``stamp_staleness_ok`` watchdog field pins this live).

With ``ControlConfig.enabled=False`` (the default) none of this is
read: the control leaves ride the pytree untouched and every round is
bit-exact with the pre-control static path (pinned by
tests/test_control.py).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

#: the controller-writable knob set, in ControlState.knobs order.
#: serflint's ``control-knob-drift`` holds this literal to the declared
#: registry (analysis/registry.py CONTROL_KNOBS) and to DEVICE_LAWS.
KNOB_FIELDS = ("fanout", "probe_mult", "stretch_q", "inject_limit",
               "stamp_unit")

#: the declarative control-law table: (signal, knob, direction).  Every
#: KNOB_FIELDS entry must appear as a law's knob (a knob nobody actuates
#: is dead config) and every law's knob must be a declared KNOB_FIELDS
#: entry — both directions lint-enforced.  The README "Adaptive
#: control" table documents each row with its clamp.
DEVICE_LAWS = (
    ("agreement-low", "fanout", "up"),
    ("agreement-converged", "fanout", "down"),
    ("false-dead", "probe_mult", "up"),
    ("false-dead-clear", "probe_mult", "down"),
    ("false-dead", "stretch_q", "up"),
    ("false-dead-clear", "stretch_q", "down"),
    ("overflow-pressure", "inject_limit", "down"),
    ("overflow-calm", "inject_limit", "up"),
    ("overflow-pressure", "stamp_unit", "up"),
    ("agreement-low", "stamp_unit", "down"),
)

#: per-round control-row field order (``control_row``): the knob vector
#: plus the shed/actuation ledgers — the trajectory the stability
#: invariant judges and the PR-9 recording's ``control`` steps carry.
CONTROL_FIELDS = KNOB_FIELDS + ("shed", "steps")

#: KNOB_FIELDS index constants — every knob READER (cluster_round,
#: round_telemetry, the executors) must use these, never bare ints, so
#: a KNOB_FIELDS reorder cannot silently actuate the wrong knob
KNOB_FANOUT = KNOB_FIELDS.index("fanout")
KNOB_PROBE_MULT = KNOB_FIELDS.index("probe_mult")
KNOB_STRETCH_Q = KNOB_FIELDS.index("stretch_q")
KNOB_INJECT_LIMIT = KNOB_FIELDS.index("inject_limit")
KNOB_STAMP_UNIT = KNOB_FIELDS.index("stamp_unit")
_FANOUT, _PROBE_MULT, _STRETCH_Q, _INJECT_LIMIT, _STAMP_UNIT = (
    KNOB_FANOUT, KNOB_PROBE_MULT, KNOB_STRETCH_Q, KNOB_INJECT_LIMIT,
    KNOB_STAMP_UNIT)


@dataclasses.dataclass(frozen=True)
class ControlConfig:
    """Static controller configuration (clamps, thresholds, hysteresis).

    Zeros mean "derive from the protocol config" (resolved by
    :func:`knob_bounds`): ``fanout_base=0`` starts at ``gossip.fanout``
    (no headroom — the controller can only relax), ``stretch_max_q=0``
    uses the full representable headroom ``AGE_PIN_Q - suspicion_q``,
    ``inject_limit_*=0`` derive from ``k_facts``.
    """

    enabled: bool = False
    #: starting effective fanout (0 = gossip.fanout); gossip.fanout is
    #: the max — give the controller headroom by setting the static
    #: fanout high and the base low
    fanout_base: int = 0
    fanout_min: int = 1
    probe_mult_max: int = 4
    stretch_max_q: int = 0
    inject_limit_base: int = 0      # 0 = 4 * k_facts
    inject_limit_floor: int = 0     # 0 = max(1, k_facts // 2)
    inject_limit_step: int = 0      # 0 = max(1, k_facts // 2)
    #: consecutive signal rounds before a protective move (widen fanout,
    #: slow probes, stretch suspicion, tighten injection)
    hyst_up: int = 3
    #: consecutive signal rounds before a relaxing move back toward the
    #: base — longer than hyst_up so recovery is deliberate, not jumpy
    hyst_down: int = 6
    #: knowledge agreement below this (sustained) = convergence burning
    agreement_low: float = 0.9
    #: EWMA of per-round in-window clobbers above this = overflow
    #: pressure; below ``overflow_hi / 4`` = calm
    overflow_hi: float = 1.0
    overflow_alpha: float = 0.125

    def __post_init__(self):
        if self.hyst_up < 1 or self.hyst_down < 1:
            raise ValueError("hysteresis windows must be >= 1 round")
        if not (0.0 < self.agreement_low <= 1.0):
            raise ValueError(
                f"agreement_low must be in (0, 1], got {self.agreement_low}")
        if not (0.0 < self.overflow_alpha <= 1.0):
            raise ValueError("overflow_alpha must be in (0, 1]")


class ControlState(NamedTuple):
    """The traced control plane: O(knobs) scalars riding the cluster
    pytree (checkpoint schema surface — growing this bumps the pinned
    pytree version, see MIGRATION.md)."""

    knobs: jnp.ndarray           # i32[len(KNOB_FIELDS)]
    streak: jnp.ndarray          # i32[len(KNOB_FIELDS)] signed hysteresis
                                 # streak per knob (+ = toward "up")
    inject_tokens: jnp.ndarray   # i32 scalar: remaining per-round
                                 # injection admission budget
    shed: jnp.ndarray            # u32 scalar: injections refused by the
                                 # controller (cumulative)
    last_overflow: jnp.ndarray   # f32 scalar: overflow ledger at the
                                 # previous control tick
    overflow_ewma: jnp.ndarray   # f32 scalar: EWMA of per-round
                                 # in-window clobbers
    steps: jnp.ndarray           # u32 scalar: knob actuations (decisions)


class ControlSignals(NamedTuple):
    """The telemetry scalars the law table reads, extracted by the
    caller (``models/swim.control_tick``) so this module never imports
    the model layer."""

    agreement: jnp.ndarray       # f32: knowledge agreement after the round
    false_dead: jnp.ndarray      # f32: alive nodes believed dead
    overflow: jnp.ndarray        # f32: cumulative in-window clobber ledger


def knob_bounds(ccfg: ControlConfig, gcfg, fcfg):
    """Resolve the per-knob (base, min, max, step) vectors against the
    protocol config — trace-time numpy (static shapes/clamps).
    ``gcfg``/``fcfg`` are the GossipConfig/FailureConfig the knobs
    override."""
    # lazy: models/swim imports this module at load time (the config
    # lives on ClusterConfig) — importing the models package here at
    # module scope would be a cycle
    from serf_tpu.models.dissemination import AGE_PIN_Q

    k = gcfg.k_facts
    fan_base = ccfg.fanout_base or gcfg.fanout
    if not (1 <= ccfg.fanout_min <= fan_base <= gcfg.fanout):
        raise ValueError(
            f"control fanout band [{ccfg.fanout_min}, base {fan_base}, "
            f"max {gcfg.fanout}] is not ordered (gossip.fanout is the "
            "static max — raise it for controller headroom)")
    stretch_max = ccfg.stretch_max_q or max(0, AGE_PIN_Q - fcfg.suspicion_q)
    if fcfg.suspicion_q + stretch_max > AGE_PIN_Q:
        raise ValueError(
            f"stretch_max_q {stretch_max} would push the suspicion "
            f"window past the AGE_PIN_Q={AGE_PIN_Q} stamp representability "
            "bound")
    inj_base = ccfg.inject_limit_base or 4 * k
    inj_floor = ccfg.inject_limit_floor or max(1, k // 2)
    inj_step = ccfg.inject_limit_step or max(1, k // 2)
    # stamp_unit carries log2(stamp_flush_unit) — units are {1, 2, 4}
    # by GossipConfig validation, so the band is [0, 2].  A per-round
    # config pins the knob at 0: actuating deferral requires the
    # overlay machinery the config opted out of.
    su_base = gcfg.stamp_flush_unit.bit_length() - 1
    su_hi = 2 if gcfg.stamp_flush_unit > 1 else 0
    base = np.array([fan_base, 1, 0, inj_base, su_base], np.int32)
    lo = np.array([ccfg.fanout_min, 1, 0, inj_floor, 0], np.int32)
    hi = np.array([gcfg.fanout, ccfg.probe_mult_max, stretch_max,
                   inj_base, su_hi], np.int32)
    step = np.array([1, 1, 1, inj_step, 1], np.int32)
    return base, lo, hi, step


def make_control(ccfg: ControlConfig, gcfg, fcfg) -> ControlState:
    """Neutral initial control state (knobs at their bases)."""
    base, _lo, _hi, _step = knob_bounds(ccfg, gcfg, fcfg)
    return ControlState(
        knobs=jnp.asarray(base),
        streak=jnp.zeros((len(KNOB_FIELDS),), jnp.int32),
        inject_tokens=jnp.asarray(int(base[_INJECT_LIMIT]), jnp.int32),
        shed=jnp.asarray(0, jnp.uint32),
        last_overflow=jnp.asarray(0.0, jnp.float32),
        overflow_ewma=jnp.asarray(0.0, jnp.float32),
        steps=jnp.asarray(0, jnp.uint32),
    )


#: which direction is the PROTECTIVE move per knob (gets hyst_up; the
#: opposite, relaxing direction gets hyst_down): widen fanout, slow
#: probes, stretch suspicion, TIGHTEN injection admission, DEFER stamp
#: flushes harder (amortize bytes under pressure)
_PROTECT_DIR = np.array([1, 1, 1, -1, 1], np.int32)


def control_step(control: ControlState, sig: ControlSignals,
                 ccfg: ControlConfig, gcfg, fcfg) -> ControlState:
    """One control tick (inside the jitted scan, after a protocol
    round): evaluate the law table on the telemetry signals, advance the
    hysteresis streaks, and move any knob whose streak crossed its
    window — by at most one bounded step, inside its clamp band.

    The decision taken after round R is the dynamic config of round
    R+1 (``cluster_round`` reads ``state.control`` at entry).
    """
    base, lo, hi, step = (jnp.asarray(a) for a in
                          knob_bounds(ccfg, gcfg, fcfg))

    # -- signals -> per-knob desired direction (i32 in {-1, 0, +1}) ---------
    # agreement-low / agreement-converged -> fanout
    fan_sig = jnp.where(sig.agreement < ccfg.agreement_low, 1,
                        jnp.where(sig.agreement >= 1.0 - 1e-6, -1, 0))
    # false-dead / false-dead-clear -> probe_mult + stretch_q (the two
    # Lifeguard moves share one signal)
    fd_sig = jnp.where(sig.false_dead > 0.5, 1, -1)
    # overflow-pressure / overflow-calm -> inject_limit (direction is
    # DOWN under pressure: tighten admission)
    delta = jnp.maximum(sig.overflow - control.last_overflow, 0.0)
    ewma = ((1.0 - ccfg.overflow_alpha) * control.overflow_ewma
            + ccfg.overflow_alpha * delta)
    inj_sig = jnp.where(ewma > ccfg.overflow_hi, -1,
                        jnp.where(ewma < ccfg.overflow_hi / 4.0, 1, 0))
    # overflow-pressure / agreement-low -> stamp_unit (byte-budget burn
    # defers flushes harder; convergence-settle burn flushes sooner —
    # same EWMA operand as inj_sig, same agreement operand as fan_sig)
    su_sig = jnp.where(ewma > ccfg.overflow_hi, 1,
                       jnp.where(sig.agreement < ccfg.agreement_low, -1, 0))
    sig_v = jnp.stack([fan_sig, fd_sig, fd_sig, inj_sig,
                       su_sig]).astype(jnp.int32)

    # -- hysteresis streaks --------------------------------------------------
    cont = jnp.sign(control.streak) == sig_v
    streak = jnp.where(sig_v == 0, 0,
                       jnp.where(cont, control.streak + sig_v, sig_v))
    protect = sig_v == jnp.asarray(_PROTECT_DIR)
    window = jnp.where(protect, ccfg.hyst_up, ccfg.hyst_down)
    fire = (sig_v != 0) & (jnp.abs(streak) >= window)

    # -- bounded actuation ---------------------------------------------------
    # relaxing moves (opposite of the protective direction) never cross
    # the BASE: the controller returns to the configured operating
    # point, it does not overshoot past it
    relaxing = sig_v == -jnp.asarray(_PROTECT_DIR)
    lo_eff = jnp.where(relaxing & (sig_v < 0),
                       jnp.maximum(lo, jnp.minimum(base, control.knobs)), lo)
    hi_eff = jnp.where(relaxing & (sig_v > 0),
                       jnp.minimum(hi, jnp.maximum(base, control.knobs)), hi)
    knobs = jnp.clip(control.knobs + sig_v * step * fire, lo_eff, hi_eff)
    changed = knobs != control.knobs
    streak = jnp.where(fire, 0, streak)
    return control._replace(
        knobs=knobs,
        streak=streak,
        # the per-round injection admission budget refills to the (new)
        # limit — tokens spent by this round's batches do not carry debt
        inject_tokens=knobs[_INJECT_LIMIT],
        last_overflow=jnp.asarray(sig.overflow, jnp.float32),
        overflow_ewma=ewma.astype(jnp.float32),
        steps=control.steps + jnp.sum(changed).astype(jnp.uint32),
    )


def gate_injections(control: ControlState, active: jnp.ndarray):
    """Device-plane injection admission: spend ``inject_tokens`` on an
    injection batch's ``active`` prefix mask.  Returns ``(admitted,
    control')`` — ``admitted`` is still a prefix mask (the first
    ``tokens`` active entries), refusals land in the ``shed`` ledger.
    Chunked storm bursts all land in one round, so the budget depletes
    ACROSS batches until the next round's refill — exactly the host
    plane's token-bucket semantics, vectorized."""
    pos = jnp.cumsum(active.astype(jnp.int32))          # 1-based among actives
    admitted = active & (pos <= control.inject_tokens)
    n_active = jnp.sum(active).astype(jnp.int32)
    n_admit = jnp.sum(admitted).astype(jnp.int32)
    return admitted, control._replace(
        inject_tokens=control.inject_tokens - n_admit,
        shed=control.shed + (n_active - n_admit).astype(jnp.uint32))


def control_row(control: ControlState) -> jnp.ndarray:
    """f32[len(CONTROL_FIELDS)]: the per-round control trajectory row
    (knobs + shed + actuation count) — a scan output, transferred with
    the telemetry rows in the run's single ``device_get``."""
    return jnp.concatenate([
        control.knobs.astype(jnp.float32),
        jnp.stack([control.shed.astype(jnp.float32),
                   control.steps.astype(jnp.float32)]),
    ])


def decisions_of(prev_row, rows, base_round: int):
    """Extract the controller DECISIONS (rounds where the knob vector
    changed) from a host-side stacked row block ``rows[R, C]``.

    Returns ``(decisions, last_row)`` where each decision is a
    JSON-ready dict — THE one formatting path shared by the recorder
    (``faults.device.run_device_plan``) and ``replay.replayer
    .replay_device``, so recorded and replayed ``control`` steps can
    only compare equal if the derivation is bit-exact (the PR-9
    ``record_scan_views`` discipline)."""
    nk = len(KNOB_FIELDS)
    out = []
    prev = prev_row
    for j, row in enumerate(np.asarray(rows)):
        if prev is not None and np.array_equal(np.asarray(prev)[:nk],
                                               row[:nk]):
            prev = row
            continue
        out.append({
            "round": int(base_round + j + 1),
            "knobs": {name: int(row[i])
                      for i, name in enumerate(KNOB_FIELDS)},
            "shed": int(row[nk]),
        })
        prev = row
    return out, prev


def emit_control_metrics(final_row, labels=None) -> dict:
    """Land the final control row on the process sink (pull-based, like
    the other device emitters): one ``serf.control.knob.<>`` gauge per
    knob plus the shed ledger.  ``final_row`` is host-side (the run's
    single transfer already happened)."""
    from serf_tpu.utils import metrics

    row = np.asarray(final_row)
    vals = {}
    for i, name in enumerate(KNOB_FIELDS):
        vals[f"serf.control.knob.{name}"] = float(row[i])
        metrics.gauge(f"serf.control.knob.{name}", float(row[i]), labels)
    shed = float(row[len(KNOB_FIELDS)])
    vals["serf.control.shed"] = shed
    metrics.gauge("serf.control.shed", shed, labels)
    steps = float(row[len(KNOB_FIELDS) + 1])
    if steps:
        metrics.incr("serf.control.steps", steps, labels)
    return vals
