"""serf_tpu.control: the adaptive control plane (ISSUE 11).

One declarative control law, actuated on both planes:

- **device** (``control.device``): a traced :class:`ControlState` row
  on the cluster pytree, updated inside the jitted scan from the PR-10
  telemetry row — effective fanout, probe-cadence multiplier,
  Lifeguard-style suspicion stretch, and a per-round injection
  admission budget, all bounded-step + hysteresis-gated;
- **host** (``control.host``): a :class:`ControllerTick` on the PR-10
  ``MetricsSampler`` actuating the PR-5 admission buckets, the PR-4
  breaker cooldown, and the memberlist probe/gossip/suspicion knobs.

``control.profiles`` holds the chaos A/B configurations
(``tools/chaos.py --controller``): per named plan, the static config
that measurably breaches an SLO and the controlled twin that must
re-converge to all-green.
"""

from serf_tpu.control.device import (   # noqa: F401
    CONTROL_FIELDS,
    ControlConfig,
    ControlSignals,
    ControlState,
    DEVICE_LAWS,
    KNOB_FANOUT,
    KNOB_FIELDS,
    KNOB_INJECT_LIMIT,
    KNOB_PROBE_MULT,
    KNOB_STRETCH_Q,
    control_row,
    control_step,
    decisions_of,
    emit_control_metrics,
    gate_injections,
    knob_bounds,
    make_control,
)
from serf_tpu.control.host import (     # noqa: F401
    HOST_KNOBS,
    HOST_LAWS,
    ControllerTick,
    HostControlConfig,
    apply_recorded,
)
