"""Delegate callback surfaces.

Two layers of hooks, mirroring the reference:

1. ``SwimDelegate`` — what the SWIM loop invokes upward into serf
   (reference memberlist delegate traits, consumed at
   serf-core/src/serf/delegate.rs:117-805; surface enumerated in
   SURVEY.md §2.9).
2. ``MergeDelegate`` / ``ReconnectDelegate`` — the user-facing hooks serf
   itself exposes (reference serf-core/src/delegate.rs:15-23), composable
   via ``CompositeDelegate`` (delegate/composite.rs:14).
"""

from __future__ import annotations

from typing import List, Optional, Sequence


class SwimDelegate:
    """Upward callbacks from the SWIM/gossip layer.  All optional."""

    def node_meta(self, limit: int) -> bytes:
        """Metadata blob advertised in alive messages (serf: encoded tags)."""
        return b""

    def notify_message(self, raw: bytes) -> None:
        """A user-plane (serf) message arrived via packet or gossip."""

    def broadcast_messages(self, overhead: int, limit: int) -> List[bytes]:
        """Piggy-back: extra user-plane broadcasts to stuff into a gossip
        packet within ``limit`` bytes (``overhead`` charged per message)."""
        return []

    def local_state(self, join: bool) -> bytes:
        """Anti-entropy blob for push/pull exchange."""
        return b""

    def merge_remote_state(self, buf: bytes, is_join: bool) -> None:
        """Apply a peer's anti-entropy blob."""

    # membership notifications
    def notify_join(self, node_state) -> None: ...
    def notify_leave(self, node_state) -> None: ...
    def notify_update(self, node_state) -> None: ...

    def notify_alive(self, node_state) -> Optional[str]:
        """Veto-able alive notification; return an error string to reject."""
        return None

    def notify_merge(self, peers: Sequence) -> Optional[str]:
        """Veto-able push/pull merge; return an error string to abort."""
        return None

    def notify_conflict(self, existing, other) -> None:
        """Two distinct addresses claim the same node id."""

    # ping plane (Vivaldi piggyback)
    def ack_payload(self) -> bytes:
        return b""

    def notify_ping_complete(self, node_state, rtt: float, payload: bytes) -> None: ...


class MergeDelegate:
    """User veto over cluster merges (reference delegate/merge.rs)."""

    def notify_merge(self, members) -> Optional[str]:
        return None


class ReconnectDelegate:
    """Per-member reconnect-timeout override (reference delegate/reconnect.rs)."""

    def reconnect_timeout(self, member, timeout: float) -> float:
        return timeout


class CompositeDelegate(MergeDelegate, ReconnectDelegate):
    """Combine independently supplied user hooks
    (reference delegate/composite.rs:14)."""

    def __init__(self, merge: Optional[MergeDelegate] = None,
                 reconnect: Optional[ReconnectDelegate] = None):
        self._merge = merge
        self._reconnect = reconnect

    def notify_merge(self, members) -> Optional[str]:
        if self._merge is not None:
            return self._merge.notify_merge(members)
        return None

    def reconnect_timeout(self, member, timeout: float) -> float:
        if self._reconnect is not None:
            return self._reconnect.reconnect_timeout(member, timeout)
        return timeout
