"""Cluster-wide keyring orchestration over internal queries.

Reference: serf-core/src/key_manager.rs:24-120 — each op broadcasts a
``_serf_*_key`` query and aggregates per-node ``KeyResponseMessage``s into a
``KeyResponse`` summary.

Hardened for rotation-under-chaos (ISSUE 20): every op runs up to
``KEY_OP_ATTEMPTS`` bounded attempts (a partition or a mid-query member
change must not turn one lost response into a failed rotation), the quorum
denominator is the membership AFTER the response drain (not a pre-drain
snapshot that a join/leave mid-query skews), per-node failures survive into
``KeyResponse.messages``, and the op's wall latency + retries + residual
partial failures are emitted on the ``serf.rotation.*`` metrics the
rotation-latency SLO watches.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from serf_tpu import codec
from serf_tpu.host.query import QueryParam
from serf_tpu.obs import flight
from serf_tpu.types.messages import (
    KeyRequestMessage,
    KeyResponseMessage,
    decode_message,
    encode_message,
)
from serf_tpu.utils import metrics

#: bounded retry: a key op re-broadcasts until every reachable member
#: acked or the attempts run out — rotation under churn must tolerate a
#: response lost to a probe-window partition without failing the op
KEY_OP_ATTEMPTS = 3


@dataclass
class KeyResponse:
    """Aggregated result of a cluster key operation."""

    messages: Dict[str, str] = field(default_factory=dict)  # node -> error/info
    num_nodes: int = 0
    num_resp: int = 0
    num_err: int = 0
    keys: Dict[bytes, int] = field(default_factory=dict)          # key -> count
    primary_keys: Dict[bytes, int] = field(default_factory=dict)  # key -> count
    attempts: int = 1

    @property
    def quorum_ok(self) -> bool:
        """Did a strict majority of the membership ack without error?
        (The denominator is the membership observed after the response
        drain — callers stop re-deriving this from raw counts.)"""
        return (self.num_resp - self.num_err) > self.num_nodes // 2

    @property
    def ok(self) -> bool:
        """Full success: every member responded and none errored."""
        return (self.num_err == 0 and self.num_nodes > 0
                and self.num_resp >= self.num_nodes)


class KeyManager:
    def __init__(self, serf):
        self.serf = serf

    async def install_key(self, key: bytes) -> KeyResponse:
        return await self._key_op("_serf_install_key", key)

    async def use_key(self, key: bytes) -> KeyResponse:
        return await self._key_op("_serf_use_key", key)

    async def remove_key(self, key: bytes) -> KeyResponse:
        return await self._key_op("_serf_remove_key", key)

    async def list_keys(self) -> KeyResponse:
        return await self._key_op("_serf_list_keys", None)

    async def _key_op(self, name: str, key: Optional[bytes]) -> KeyResponse:
        t0 = time.perf_counter()
        out = KeyResponse()
        for attempt in range(1, KEY_OP_ATTEMPTS + 1):
            out = await self._key_op_once(name, key)
            out.attempts = attempt
            if out.ok:
                break
            if attempt < KEY_OP_ATTEMPTS:
                metrics.incr("serf.rotation.retry")
        latency_ms = (time.perf_counter() - t0) * 1e3
        # gauge, not observe: the sampler folds counters+gauges into the
        # watchdog's store, so the SLO watch sees the latest op latency
        metrics.gauge("serf.rotation.latency-ms", latency_ms)
        if out.num_err:
            # residual per-node failures on the FINAL attempt — the
            # partial-failure half of the rotation report
            metrics.incr("serf.rotation.partial", out.num_err)
        flight.record(
            "key-rotation",
            op=name, attempts=out.attempts, num_nodes=out.num_nodes,
            num_resp=out.num_resp, num_err=out.num_err,
            quorum_ok=out.quorum_ok, latency_ms=round(latency_ms, 3))
        return out

    async def _key_op_once(self, name: str,
                           key: Optional[bytes]) -> KeyResponse:
        payload = encode_message(KeyRequestMessage(key or b""))
        resp = await self.serf.query(name, payload, QueryParam())
        out = KeyResponse()
        async for r in resp.responses():
            out.num_resp += 1
            try:
                msg = decode_message(r.payload)
            except codec.DecodeError as e:
                out.num_err += 1
                out.messages[r.from_id] = f"undecodable response: {e}"
                continue
            if not isinstance(msg, KeyResponseMessage):
                out.num_err += 1
                out.messages[r.from_id] = "unexpected response type"
                continue
            if not msg.result:
                out.num_err += 1
            if msg.message:
                out.messages[r.from_id] = msg.message
            for k in msg.keys:
                out.keys[k] = out.keys.get(k, 0) + 1
            if msg.primary_key:
                out.primary_keys[msg.primary_key] = \
                    out.primary_keys.get(msg.primary_key, 0) + 1
        # the quorum denominator: membership AFTER the drain (a member
        # joining/leaving mid-query otherwise skews quorum_ok)
        out.num_nodes = self.serf.num_members()
        return out
