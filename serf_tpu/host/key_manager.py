"""Cluster-wide keyring orchestration over internal queries.

Reference: serf-core/src/key_manager.rs:24-120 — each op broadcasts a
``_serf_*_key`` query and aggregates per-node ``KeyResponseMessage``s into a
``KeyResponse`` summary.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

from serf_tpu import codec
from serf_tpu.host.query import QueryParam
from serf_tpu.types.messages import (
    KeyRequestMessage,
    KeyResponseMessage,
    decode_message,
    encode_message,
)


@dataclass
class KeyResponse:
    """Aggregated result of a cluster key operation."""

    messages: Dict[str, str] = field(default_factory=dict)  # node -> error/info
    num_nodes: int = 0
    num_resp: int = 0
    num_err: int = 0
    keys: Dict[bytes, int] = field(default_factory=dict)          # key -> count
    primary_keys: Dict[bytes, int] = field(default_factory=dict)  # key -> count


class KeyManager:
    def __init__(self, serf):
        self.serf = serf

    async def install_key(self, key: bytes) -> KeyResponse:
        return await self._key_op("_serf_install_key", key)

    async def use_key(self, key: bytes) -> KeyResponse:
        return await self._key_op("_serf_use_key", key)

    async def remove_key(self, key: bytes) -> KeyResponse:
        return await self._key_op("_serf_remove_key", key)

    async def list_keys(self) -> KeyResponse:
        return await self._key_op("_serf_list_keys", None)

    async def _key_op(self, name: str, key: Optional[bytes]) -> KeyResponse:
        payload = encode_message(KeyRequestMessage(key or b""))
        resp = await self.serf.query(name, payload, QueryParam())
        out = KeyResponse(num_nodes=self.serf.num_members())
        async for r in resp.responses():
            out.num_resp += 1
            try:
                msg = decode_message(r.payload)
            except codec.DecodeError as e:
                out.num_err += 1
                out.messages[r.from_id] = f"undecodable response: {e}"
                continue
            if not isinstance(msg, KeyResponseMessage):
                out.num_err += 1
                out.messages[r.from_id] = "unexpected response type"
                continue
            if not msg.result:
                out.num_err += 1
            if msg.message:
                out.messages[r.from_id] = msg.message
            for k in msg.keys:
                out.keys[k] = out.keys.get(k, 0) + 1
            if msg.primary_key:
                out.primary_keys[msg.primary_key] = \
                    out.primary_keys.get(msg.primary_key, 0) + 1
        return out
